"""paddle.regularizer (reference: python/paddle/regularizer.py): weight
decay specs consumed by optimizers and ParamAttr."""
from __future__ import annotations

__all__ = ["WeightDecayRegularizer", "L1Decay", "L2Decay"]


class WeightDecayRegularizer:
    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)

    @property
    def coeff(self):
        return self._coeff


class L1Decay(WeightDecayRegularizer):
    """L1 weight decay: grad += coeff * sign(param)."""


class L2Decay(WeightDecayRegularizer):
    """L2 weight decay: grad += coeff * param."""
