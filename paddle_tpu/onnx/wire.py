"""Minimal protobuf wire-format encoder/decoder for ONNX serialization.

The ONNX file format is protobuf (onnx/onnx.proto — a stable, public
schema).  This module hand-rolls the two wire primitives protobuf needs
(varint + length-delimited) so `paddle.onnx.export` produces real .onnx
files without the `onnx` package (not installed in this image; the
reference links protobuf in C++, paddle2onnx side).  The decoder exists
so tests can round-trip and *evaluate* what was written.
"""
from __future__ import annotations

import struct

__all__ = ["varint", "field_varint", "field_bytes", "field_string",
           "field_float", "parse_message", "parse_string", "parse_floats"]


def varint(n: int) -> bytes:
    if n < 0:
        n += 1 << 64            # protobuf encodes negatives as 10-byte
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field: int, wire_type: int) -> bytes:
    return varint((field << 3) | wire_type)


def field_varint(field: int, value: int) -> bytes:
    return _tag(field, 0) + varint(value)


def field_bytes(field: int, data: bytes) -> bytes:
    return _tag(field, 2) + varint(len(data)) + data


def field_string(field: int, s: str) -> bytes:
    return field_bytes(field, s.encode("utf-8"))


def field_float(field: int, value: float) -> bytes:
    return _tag(field, 5) + struct.pack("<f", value)


# ------------------------------------------------------------- decoding
def _read_varint(buf: bytes, i: int):
    shift = 0
    val = 0
    while True:
        b = buf[i]
        i += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, i
        shift += 7


def parse_message(buf: bytes):
    """Parse one protobuf message into {field: [raw values]} — varints as
    int, length-delimited as bytes, fixed32 as 4 bytes."""
    out: dict[int, list] = {}
    i = 0
    while i < len(buf):
        key, i = _read_varint(buf, i)
        field, wt = key >> 3, key & 7
        if wt == 0:
            v, i = _read_varint(buf, i)
        elif wt == 2:
            ln, i = _read_varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        elif wt == 5:
            v = buf[i:i + 4]
            i += 4
        elif wt == 1:
            v = buf[i:i + 8]
            i += 8
        else:
            raise ValueError(f"unsupported wire type {wt}")
        out.setdefault(field, []).append(v)
    return out


def parse_string(raw: bytes) -> str:
    return raw.decode("utf-8")


def parse_floats(raw: bytes):
    return struct.unpack(f"<{len(raw) // 4}f", raw)
