"""paddle.onnx.export — static Program / Layer → ONNX file.

Reference: the reference exports through the external paddle2onnx
converter (python/paddle/onnx/export.py calls p2o over a serialized
inference program).  Here the converter is native: the recorded static
Program DAG (static/graph.py) maps op-by-op onto ONNX operators and the
file is serialized with the in-tree protobuf wire writer (wire.py /
proto.py) — no external packages.

Supported ops cover the deploy-side surface (linear/conv/pool/norm/
activation/shape ops).  Anything else raises a loud
``OnnxUnsupportedError`` naming the op — never a silently wrong graph.
"""
from __future__ import annotations

import numpy as np

from . import proto
from .proto import NP2ONNX

__all__ = ["export_program", "OnnxUnsupportedError"]


class OnnxUnsupportedError(NotImplementedError):
    pass


def _opname_of(var):
    # build_node names outputs f"{opname}_{counter}"
    return var.name.rsplit("_", 1)[0]


def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return [int(x) for x in v]
    return [int(v)] * n


def _pads(padding, nd=2):
    """paddle padding (int | [p1, p2] | [(lo, hi), ...]) -> onnx pads
    [x1_begin, x2_begin, ..., x1_end, x2_end, ...]."""
    if isinstance(padding, int):
        return [padding] * (2 * nd)
    padding = list(padding)
    if all(isinstance(p, int) for p in padding) and len(padding) == nd:
        return padding + padding
    lohi = [tuple(p) for p in padding]
    return [p[0] for p in lohi] + [p[1] for p in lohi]


class _Exporter:
    def __init__(self):
        self.nodes: list[bytes] = []
        self.initializers: list[bytes] = []
        self._init_names: dict[int, str] = {}
        self._var_names: dict[str, str] = {}
        self._tmp = 0

    # ------------------------------------------------------------ names
    def tmp(self, base):
        self._tmp += 1
        return f"{base}__{self._tmp}"

    def ref(self, x):
        """ONNX name for a Variable / Parameter / python constant."""
        from ..framework.tensor import Tensor
        from ..static.graph import Variable

        if isinstance(x, Variable):
            return self._var_names[x.name]
        if isinstance(x, Tensor):
            key = id(x)
            if key not in self._init_names:
                name = x.name or f"param_{len(self._init_names)}"
                self._init_names[key] = name
                self.initializers.append(
                    proto.tensor(name, np.asarray(x._data)))
            return self._init_names[key]
        # literal -> constant initializer
        arr = np.asarray(x)
        name = self.tmp("const")
        self.initializers.append(proto.tensor(name, arr))
        return name

    def const(self, arr, base="c"):
        name = self.tmp(base)
        self.initializers.append(proto.tensor(name, np.asarray(arr)))
        return name

    def emit(self, op_type, inputs, outputs, **attrs):
        self.nodes.append(proto.node(op_type, inputs, outputs,
                                     name=self.tmp(op_type), **attrs))

    # ------------------------------------------------------------- walk
    def export(self, feed_vars, fetch_vars, name="paddle_tpu"):
        from ..static.graph import Variable

        for v in feed_vars:
            self._var_names[v.name] = v.name

        done = set()

        def visit(v: Variable):
            if v.name in self._var_names:
                return
            if v.source is None:
                raise OnnxUnsupportedError(
                    f"variable {v.name} has no source and is not a feed")
            src_id = id(v.source)
            if src_id in done:
                return
            # visit producers first
            from jax.tree_util import tree_flatten
            from ..framework.tensor import Tensor
            body, args, kwargs, n_outs = v.source
            flat, _ = tree_flatten(
                (args, kwargs),
                is_leaf=lambda x: isinstance(x, (Variable, Tensor)))
            for x in flat:
                if isinstance(x, Variable):
                    visit(x)
            done.add(src_id)
            self._emit_op(v, body, args, kwargs, n_outs)

        for v in fetch_vars:
            visit(v)

        inputs = [proto.value_info(v.name, v.shape,
                                   NP2ONNX[np.dtype(v.dtype)])
                  for v in feed_vars]
        outputs = [proto.value_info(self._var_names[v.name], v.shape,
                                    NP2ONNX[np.dtype(v.dtype)])
                   for v in fetch_vars]
        g = proto.graph(self.nodes, name, inputs, outputs,
                        self.initializers)
        return proto.model(g)

    # ------------------------------------------------------ op emitters
    def _emit_op(self, out_var, body, args, kwargs, n_outs):
        from ..static.graph import Variable

        opname = _opname_of(out_var)
        prog = out_var.program
        outs = [w for w in prog.vars.values() if w.source is out_var.source]
        outs.sort(key=lambda w: w.out_index)
        out_names = []
        for w in outs:
            nm = w.name
            self._var_names[w.name] = nm
            out_names.append(nm)
        self._cur_outs = outs   # static shapes for shape-op emitters

        fn = getattr(self, f"_op_{opname}", None)
        if fn is None:
            raise OnnxUnsupportedError(
                f"op '{opname}' has no ONNX mapping (paddle_tpu.onnx "
                f"supports: "
                f"{sorted(m[4:] for m in dir(self) if m.startswith('_op_'))})")
        fn(args, kwargs, out_names)

    # elementwise / activations ------------------------------------------
    def _binop(self, onnx_op, args, out_names):
        self.emit(onnx_op, [self.ref(args[0]), self.ref(args[1])],
                  out_names)

    def _op_add(self, a, k, o):
        self._binop("Add", a, o)

    def _op_subtract(self, a, k, o):
        self._binop("Sub", a, o)

    def _op_multiply(self, a, k, o):
        self._binop("Mul", a, o)

    def _op_divide(self, a, k, o):
        self._binop("Div", a, o)

    def _op_relu(self, a, k, o):
        self.emit("Relu", [self.ref(a[0])], o)

    def _op_sigmoid(self, a, k, o):
        self.emit("Sigmoid", [self.ref(a[0])], o)

    def _op_tanh(self, a, k, o):
        self.emit("Tanh", [self.ref(a[0])], o)

    def _op_softmax(self, a, k, o):
        axis = k.get("axis", a[1] if len(a) > 1 else -1)
        self.emit("Softmax", [self.ref(a[0])], o, axis=int(axis))

    def _op_cast(self, a, k, o):
        dt = k.get("dtype", a[1] if len(a) > 1 else "float32")
        from ..framework.dtype import to_np_dtype
        self.emit("Cast", [self.ref(a[0])], o,
                  to=NP2ONNX[np.dtype(to_np_dtype(dt))])

    # linear algebra ------------------------------------------------------
    def _op_matmul(self, a, k, o):
        if k.get("transpose_x") or k.get("transpose_y"):
            raise OnnxUnsupportedError("matmul transpose_x/y")
        self._binop("MatMul", a, o)

    def _op_linear(self, a, k, o):
        x, w = a[0], a[1]
        bias = k.get("bias", a[2] if len(a) > 2 else None)
        if bias is None:
            self.emit("MatMul", [self.ref(x), self.ref(w)], o)
        else:
            mm = self.tmp("linear_mm")
            self.emit("MatMul", [self.ref(x), self.ref(w)], [mm])
            self.emit("Add", [mm, self.ref(bias)], o)

    # conv / pool ---------------------------------------------------------
    def _op_conv2d(self, a, k, o):
        x, w = a[0], a[1]
        bias = k.get("bias", a[2] if len(a) > 2 else None)
        if k.get("data_format", "NCHW") != "NCHW":
            raise OnnxUnsupportedError("conv2d NHWC export")
        ins = [self.ref(x), self.ref(w)]
        if bias is not None:
            ins.append(self.ref(bias))
        self.emit("Conv", ins, o,
                  strides=_pair(k.get("stride", 1)),
                  pads=_pads(k.get("padding", 0)),
                  dilations=_pair(k.get("dilation", 1)),
                  group=int(k.get("groups", 1)))

    def _pool(self, onnx_op, a, k, o, extra=None):
        ksize = _pair(k.get("kernel_size", a[1] if len(a) > 1 else 2))
        stride = k.get("stride")
        stride = ksize if stride is None else _pair(stride)
        attrs = dict(kernel_shape=ksize, strides=stride,
                     pads=_pads(k.get("padding", 0)),
                     ceil_mode=int(bool(k.get("ceil_mode", False))))
        if extra:
            attrs.update(extra)
        self.emit(onnx_op, [self.ref(a[0])], o, **attrs)

    def _op_max_pool2d(self, a, k, o):
        self._pool("MaxPool", a, k, o)

    def _op_avg_pool2d(self, a, k, o):
        self._pool("AveragePool", a, k, o,
                   extra={"count_include_pad":
                          int(not k.get("exclusive", True))})

    def _op_adaptive_avg_pool2d(self, a, k, o):
        osz = k.get("output_size", a[1] if len(a) > 1 else 1)
        if _pair(osz) != [1, 1]:
            raise OnnxUnsupportedError("adaptive_avg_pool2d output != 1")
        self.emit("GlobalAveragePool", [self.ref(a[0])], o)

    # shape ops -----------------------------------------------------------
    def _op_flatten(self, a, k, o):
        # ONNX Flatten is strictly 2-D-out; paddle's (start, stop) form
        # is a Reshape to the statically known output shape
        shp = self.const(
            np.asarray(self._cur_outs[0].shape, np.int64), "flat_shape")
        self.emit("Reshape", [self.ref(a[0]), shp], o)

    def _op_reshape(self, a, k, o):
        shape = k.get("shape", a[1])
        shp = self.const(np.asarray(list(shape), np.int64), "shape")
        self.emit("Reshape", [self.ref(a[0]), shp], o)

    def _op_transpose(self, a, k, o):
        perm = k.get("perm", a[1])
        self.emit("Transpose", [self.ref(a[0])], o,
                  perm=[int(p) for p in perm])

    def _op_concat(self, a, k, o):
        xs = a[0]
        axis = int(k.get("axis", a[1] if len(a) > 1 else 0))
        self.emit("Concat", [self.ref(x) for x in xs], o, axis=axis)

    def _op_mean(self, a, k, o):
        # opset <= 17: axes is an ATTRIBUTE (moved to an input in 18)
        axis = k.get("axis", a[1] if len(a) > 1 else None)
        keep = bool(k.get("keepdim", False))
        if axis is None:
            self.emit("ReduceMean", [self.ref(a[0])], o,
                      keepdims=int(keep))
        else:
            axes = [axis] if isinstance(axis, int) else list(axis)
            self.emit("ReduceMean", [self.ref(a[0])], o,
                      axes=[int(x) for x in axes], keepdims=int(keep))

    def _op_embedding(self, a, k, o):
        x, w = a[0], a[1]
        if k.get("padding_idx") is not None:
            raise OnnxUnsupportedError("embedding padding_idx export")
        self.emit("Gather", [self.ref(w), self.ref(x)], o, axis=0)

    # norm / dropout ------------------------------------------------------
    def _op_batch_norm(self, a, k, o):
        # inference form only (Program.clone(for_test=True) bakes
        # training=False); outputs: (y, new_rm, new_rv) — rm/rv pass
        # through untouched at inference
        training = k.get("training", a[5] if len(a) > 5 else False)
        if training:
            raise OnnxUnsupportedError(
                "batch_norm training=True (export in eval mode)")
        x, rm, rv = a[0], a[1], a[2]
        w = k.get("weight", a[3] if len(a) > 3 else None)
        b = k.get("bias", a[4] if len(a) > 4 else None)
        eps = float(k.get("epsilon", a[7] if len(a) > 7 else 1e-5))
        c = np.asarray(rm._data if hasattr(rm, "_data") else rm).shape[0]
        wn = self.ref(w) if w is not None else \
            self.const(np.ones(c, np.float32), "bn_w")
        bn = self.ref(b) if b is not None else \
            self.const(np.zeros(c, np.float32), "bn_b")
        self.emit("BatchNormalization",
                  [self.ref(x), wn, bn, self.ref(rm), self.ref(rv)],
                  [o[0]], epsilon=eps)
        # rm/rv outputs: identity passthrough keeps the graph closed
        for i, src in ((1, rm), (2, rv)):
            if i < len(o):
                self.emit("Identity", [self.ref(src)], [o[i]])

    def _op_layer_norm(self, a, k, o):
        x = a[0]
        w = k.get("weight", a[2] if len(a) > 2 else None)
        b = k.get("bias", a[3] if len(a) > 3 else None)
        eps = float(k.get("epsilon", a[4] if len(a) > 4 else 1e-5))
        norm_shape = k.get("normalized_shape", a[1] if len(a) > 1 else None)
        if isinstance(norm_shape, int):
            norm_dims, nd = [norm_shape], 1
        else:
            norm_dims = [int(d) for d in norm_shape]
            nd = len(norm_dims)
        # ONNX Scale (input 2) is REQUIRED: synthesize ones when paddle
        # had no weight, so a provided bias is never silently dropped
        scale = self.ref(w) if w is not None else self.const(
            np.ones(norm_dims, np.float32), "ln_scale")
        ins = [self.ref(x), scale]
        if b is not None:
            ins.append(self.ref(b))
        self.emit("LayerNormalization", ins, o[:1], axis=-nd, epsilon=eps)

    def _op_dropout(self, a, k, o):
        training = k.get("training", True)
        if training:
            raise OnnxUnsupportedError(
                "dropout training=True (clone the program for_test)")
        self.emit("Identity", [self.ref(a[0])], o[:1])


def export_program(feed_vars, fetch_vars, path, name="paddle_tpu"):
    """Serialize the program slice producing ``fetch_vars`` to
    ``path`` (binary ONNX ModelProto).  Returns the path."""
    if not path.endswith(".onnx"):
        path = path + ".onnx"
    blob = _Exporter().export(feed_vars, fetch_vars, name=name)
    with open(path, "wb") as f:
        f.write(blob)
    return path
