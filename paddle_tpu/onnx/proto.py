"""ONNX proto message builders over the wire encoder.

Field numbers follow the public onnx/onnx.proto schema (stable across
IR versions).  Only the message subset `export` emits is implemented.
"""
from __future__ import annotations

import numpy as np

from .wire import (field_bytes, field_float, field_string, field_varint,
                   varint)

# TensorProto.DataType
FLOAT, INT64, INT32, BOOL, DOUBLE = 1, 7, 6, 9, 11
UINT8, INT8, FLOAT16, BFLOAT16 = 2, 3, 10, 16

NP2ONNX = {
    np.dtype(np.float32): FLOAT, np.dtype(np.int64): INT64,
    np.dtype(np.int32): INT32, np.dtype(np.bool_): BOOL,
    np.dtype(np.float64): DOUBLE, np.dtype(np.uint8): UINT8,
    np.dtype(np.int8): INT8, np.dtype(np.float16): FLOAT16,
}

# AttributeProto.AttributeType
A_FLOAT, A_INT, A_STRING, A_TENSOR, A_FLOATS, A_INTS, A_STRINGS = \
    1, 2, 3, 4, 6, 7, 8


def tensor(name: str, arr: np.ndarray) -> bytes:
    """TensorProto: dims=1, data_type=2, name=8, raw_data=9."""
    arr = np.ascontiguousarray(arr)
    out = b"".join(field_varint(1, int(d)) for d in arr.shape)
    out += field_varint(2, NP2ONNX[arr.dtype])
    out += field_string(8, name)
    out += field_bytes(9, arr.tobytes())
    return out


def attribute(name: str, value) -> bytes:
    """AttributeProto: name=1, f=2, i=3, s=4, t=5, floats=7, ints=8,
    type=20."""
    out = field_string(1, name)
    if isinstance(value, bool):
        out += field_varint(3, int(value)) + field_varint(20, A_INT)
    elif isinstance(value, int):
        out += field_varint(3, value) + field_varint(20, A_INT)
    elif isinstance(value, float):
        out += field_float(2, value) + field_varint(20, A_FLOAT)
    elif isinstance(value, str):
        out += field_bytes(4, value.encode()) + field_varint(20, A_STRING)
    elif isinstance(value, np.ndarray):
        out += field_bytes(5, tensor(name + "_t", value))
        out += field_varint(20, A_TENSOR)
    elif isinstance(value, (list, tuple)):
        if value and isinstance(value[0], float):
            for v in value:
                out += field_float(7, v)
            out += field_varint(20, A_FLOATS)
        else:
            for v in value:
                out += field_varint(8, int(v))
            out += field_varint(20, A_INTS)
    else:
        raise TypeError(f"attribute {name}: {type(value)}")
    return out


def node(op_type: str, inputs, outputs, name="", **attrs) -> bytes:
    """NodeProto: input=1, output=2, name=3, op_type=4, attribute=5."""
    out = b"".join(field_string(1, i) for i in inputs)
    out += b"".join(field_string(2, o) for o in outputs)
    if name:
        out += field_string(3, name)
    out += field_string(4, op_type)
    for k, v in attrs.items():
        out += field_bytes(5, attribute(k, v))
    return out


def value_info(name: str, shape, elem_type=FLOAT) -> bytes:
    """ValueInfoProto{name=1, type=2{tensor_type=1{elem_type=1,
    shape=2{dim=1{dim_value=1|dim_param=2}}}}}."""
    dims = b""
    for d in shape:
        if d is None or (isinstance(d, int) and d < 0):
            dim = field_string(2, "batch")
        else:
            dim = field_varint(1, int(d))
        dims += field_bytes(1, dim)
    shape_p = field_bytes(2, dims)
    ttype = field_varint(1, elem_type) + shape_p
    tp = field_bytes(1, ttype)
    return field_string(1, name) + field_bytes(2, tp)


def graph(nodes, name, inputs, outputs, initializers) -> bytes:
    """GraphProto: node=1, name=2, initializer=5, input=11, output=12."""
    out = b"".join(field_bytes(1, n) for n in nodes)
    out += field_string(2, name)
    out += b"".join(field_bytes(5, t) for t in initializers)
    out += b"".join(field_bytes(11, i) for i in inputs)
    out += b"".join(field_bytes(12, o) for o in outputs)
    return out


def model(graph_bytes: bytes, opset: int = 17,
          producer: str = "paddle_tpu") -> bytes:
    """ModelProto: ir_version=1, producer_name=2, producer_version=3,
    graph=7, opset_import=8{domain=1, version=2}."""
    opset_p = field_string(1, "") + field_varint(2, opset)
    out = field_varint(1, 8)               # IR version 8
    out += field_string(2, producer)
    out += field_string(3, "0.0")
    out += field_bytes(7, graph_bytes)
    out += field_bytes(8, opset_p)
    return out
