"""paddle.onnx — native ONNX export.

Reference: python/paddle/onnx/export.py (delegates to the external
paddle2onnx converter over a serialized inference program).  Here the
conversion is in-tree: the layer is traced into a static Program
(static/graph.py records the op DAG), each framework op maps onto ONNX
operators (export.py), and the ModelProto is serialized with a
hand-rolled protobuf wire writer (wire.py) — the `onnx` package is not
bundled in this image and is not required.  Unsupported ops raise
``OnnxUnsupportedError`` naming the op; a silently wrong graph is never
emitted.  (The TPU-native deployment path remains ``paddle.jit.save``'s
StableHLO artifact; ONNX export serves non-XLA runtimes.)
"""
from __future__ import annotations

from .export import OnnxUnsupportedError, export_program

__all__ = ["export", "export_program", "OnnxUnsupportedError"]


def export(layer, path, input_spec=None, opset_version=17, **configs):
    """Trace ``layer`` with ``input_spec`` and write ``path``+'.onnx'.

    Reference signature: python/paddle/onnx/export.py.  The layer is
    captured in eval mode (dropout off, batch-norm on global stats),
    matching the reference's export of the inference program.
    """
    from .. import enable_static, disable_static
    from ..static import Program, program_guard, data

    if input_spec is None:
        raise ValueError("paddle.onnx.export requires input_spec "
                         "(list of paddle.static.InputSpec)")
    if opset_version != 17:
        raise ValueError(
            f"paddle.onnx.export emits opset-17 operator semantics "
            f"(LayerNormalization >= 17, attribute-form ReduceMean <= 17); "
            f"got opset_version={opset_version}")
    for spec in input_spec:
        if any(d is None or d < 0 for d in spec.shape):
            raise ValueError(
                f"dynamic dims in input_spec {spec.shape}: this exporter "
                "is static-shape (shapes are baked at trace time, like "
                "jax.export) — pass concrete dims and re-export per "
                "shape bucket")
    was_training = getattr(layer, "training", False)
    if hasattr(layer, "eval"):
        layer.eval()
    enable_static()
    try:
        prog = Program()
        with program_guard(prog):
            feeds = []
            for i, spec in enumerate(input_spec):
                feeds.append(data(spec.name or f"x{i}", list(spec.shape),
                                  str(spec.dtype)))
            out = layer(*feeds)
        fetches = list(out) if isinstance(out, (list, tuple)) else [out]
        return export_program(feeds, fetches, path,
                              name=type(layer).__name__)
    finally:
        disable_static()
        if was_training and hasattr(layer, "train"):
            layer.train()
