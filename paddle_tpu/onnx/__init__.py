"""paddle.onnx — ONNX export entry.

Reference: python/paddle/onnx/export.py (delegates to paddle2onnx).
Gated here: the onnx/paddle2onnx toolchain is not bundled (zero-egress
image), and the TPU-native deployment path is `paddle.jit.save`'s
StableHLO export (jit/serialization.py), which XLA-based runtimes load
directly.  If `onnx` is importable we still refuse rather than emit a
half-correct graph.
"""
from __future__ import annotations

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    raise NotImplementedError(
        "ONNX graph conversion is not implemented (the paddle2onnx "
        "toolchain is not bundled); use paddle_tpu.jit.save(layer, path, "
        "input_spec=...) — its .stablehlo artifact is the TPU-native "
        "deployment format, loadable via jax.export")
