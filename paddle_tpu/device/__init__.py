"""Device management (reference: python/paddle/device, paddle/phi/backends).

The TPU runtime has one device class; CPUPlace/CUDAPlace etc. are accepted
for API compatibility and map onto jax devices.  `set_device` selects the
default jax device used for new tensors.
"""
from __future__ import annotations

import jax

__all__ = ["set_device", "get_device", "get_all_custom_device_type",
           "CPUPlace", "CUDAPlace", "XPUPlace", "TPUPlace", "CustomPlace",
           "cuda", "device_count", "is_available"]

_current = None


class _Place:
    def __init__(self, device_id=0):
        self.device_id = int(device_id)

    def __repr__(self):
        return f"{type(self).__name__}({self.device_id})"

    def __eq__(self, other):
        return type(self) is type(other) and self.device_id == other.device_id

    def __hash__(self):
        return hash((type(self).__name__, self.device_id))


class CPUPlace(_Place):
    def __init__(self):
        super().__init__(0)

    def __repr__(self):
        return "Place(cpu)"


class CUDAPlace(_Place):
    pass


class CUDAPinnedPlace(_Place):
    def __init__(self):
        super().__init__(0)


class XPUPlace(_Place):
    pass


class TPUPlace(_Place):
    pass


class CustomPlace(_Place):
    def __init__(self, dev_type, device_id=0):
        super().__init__(device_id)
        self.dev_type = dev_type


def set_device(device: str):
    """Accepts 'cpu', 'tpu', 'tpu:0', also 'gpu:0' (mapped to the default
    accelerator for source compatibility)."""
    global _current
    name = device.split(":")[0]
    idx = int(device.split(":")[1]) if ":" in device else 0
    platform = {"cpu": "cpu", "tpu": None, "gpu": None, "xpu": None}.get(name)
    try:
        devs = jax.devices(platform) if platform else jax.devices()
    except RuntimeError:
        devs = jax.devices()
    _current = devs[idx % len(devs)]
    jax.config.update("jax_default_device", _current)
    return _current


def get_device() -> str:
    d = _current or jax.devices()[0]
    plat = d.platform
    name = "gpu" if plat in ("tpu", "axon") else plat  # paddle-style string
    return f"{name}:{d.id}" if plat != "cpu" else "cpu"


def get_all_custom_device_type():
    return ["tpu"]


def device_count():
    return len(jax.devices())


def is_available():
    return True


class cuda:
    """paddle.device.cuda compat shims (map to the accelerator)."""

    @staticmethod
    def device_count():
        return len(jax.devices())

    @staticmethod
    def max_memory_allocated(device=None):
        d = jax.devices()[0]
        stats = getattr(d, "memory_stats", lambda: {})() or {}
        return stats.get("peak_bytes_in_use", 0)

    @staticmethod
    def memory_allocated(device=None):
        d = jax.devices()[0]
        stats = getattr(d, "memory_stats", lambda: {})() or {}
        return stats.get("bytes_in_use", 0)

    @staticmethod
    def empty_cache():
        pass

    @staticmethod
    def synchronize(device=None):
        import jax
        (jax.device_put(0) + 0).block_until_ready()
