"""Device management (reference: python/paddle/device, paddle/phi/backends).

The TPU runtime has one device class; CPUPlace/CUDAPlace etc. are accepted
for API compatibility and map onto jax devices.  `set_device` selects the
default jax device used for new tensors.
"""
from __future__ import annotations

import jax

__all__ = ["set_device", "get_device", "get_all_custom_device_type",
           "CPUPlace", "CUDAPlace", "XPUPlace", "TPUPlace", "CustomPlace",
           "cuda", "device_count", "is_available"]

_current = None


class _Place:
    def __init__(self, device_id=0):
        self.device_id = int(device_id)

    def __repr__(self):
        return f"{type(self).__name__}({self.device_id})"

    def __eq__(self, other):
        return type(self) is type(other) and self.device_id == other.device_id

    def __hash__(self):
        return hash((type(self).__name__, self.device_id))


class CPUPlace(_Place):
    def __init__(self):
        super().__init__(0)

    def __repr__(self):
        return "Place(cpu)"


class CUDAPlace(_Place):
    pass


class CUDAPinnedPlace(_Place):
    def __init__(self):
        super().__init__(0)


class XPUPlace(_Place):
    pass


class TPUPlace(_Place):
    pass


class CustomPlace(_Place):
    def __init__(self, dev_type, device_id=0):
        super().__init__(device_id)
        self.dev_type = dev_type


def set_device(device: str):
    """Accepts 'cpu', 'tpu', 'tpu:0', also 'gpu:0' (mapped to the default
    accelerator for source compatibility)."""
    global _current
    name = device.split(":")[0]
    idx = int(device.split(":")[1]) if ":" in device else 0
    platform = {"cpu": "cpu", "tpu": None, "gpu": None, "xpu": None}.get(name)
    try:
        devs = jax.devices(platform) if platform else jax.devices()
    except RuntimeError:
        devs = jax.devices()
    _current = devs[idx % len(devs)]
    jax.config.update("jax_default_device", _current)
    return _current


def get_device() -> str:
    d = _current or jax.devices()[0]
    plat = d.platform
    name = "gpu" if plat in ("tpu", "axon") else plat  # paddle-style string
    return f"{name}:{d.id}" if plat != "cpu" else "cpu"


def get_all_custom_device_type():
    return ["tpu"]


def device_count():
    return len(jax.devices())


def is_available():
    return True


class cuda:
    """paddle.device.cuda compat shims (map to the accelerator)."""

    @staticmethod
    def device_count():
        return len(jax.devices())

    @staticmethod
    def max_memory_allocated(device=None):
        d = jax.devices()[0]
        stats = getattr(d, "memory_stats", lambda: {})() or {}
        return stats.get("peak_bytes_in_use", 0)

    @staticmethod
    def memory_allocated(device=None):
        d = jax.devices()[0]
        stats = getattr(d, "memory_stats", lambda: {})() or {}
        return stats.get("bytes_in_use", 0)

    @staticmethod
    def empty_cache():
        pass

    @staticmethod
    def synchronize(device=None):
        import jax
        (jax.device_put(0) + 0).block_until_ready()


class IPUPlace(_Place):
    def __init__(self):
        super().__init__(0)


class Stream:
    """Stream surface (reference device/__init__.py Stream over C++
    streams).  XLA owns real streams; this is an ordering token whose
    synchronize() drains the device queue."""

    def __init__(self, device=None, priority=2):
        self.device = device
        self.priority = priority

    def synchronize(self):
        synchronize()

    def wait_event(self, event):
        event.synchronize()

    def wait_stream(self, stream):
        stream.synchronize()

    def record_event(self, event=None):
        event = event or Event()
        event.record(self)
        return event


class Event:
    """Event surface (reference device/__init__.py Event)."""

    def __init__(self, device=None, enable_timing=False, blocking=False,
                 interprocess=False):
        self.device = device
        self._recorded = False

    def record(self, stream=None):
        self._recorded = True

    def query(self):
        return True

    def synchronize(self):
        synchronize()


_current_stream = Stream()


def current_stream(device=None):
    return _current_stream


def set_stream(stream):
    global _current_stream
    prev = _current_stream
    _current_stream = stream
    return prev


class stream_guard:
    def __init__(self, stream):
        self._stream = stream
        self._prev = None

    def __enter__(self):
        self._prev = set_stream(self._stream)
        return self._stream

    def __exit__(self, *exc):
        set_stream(self._prev)
        return False


def synchronize(device=None):
    """Block until all queued device work completes (reference
    device/cuda synchronize); jax effectively syncs via a trivial fetch.
    Accepts None, a jax Device, or a paddle-style string ('gpu:0')."""
    import jax
    if device is None:
        dev = jax.devices()[0]
    elif isinstance(device, str):
        plat, _, idx = device.partition(":")
        idx = int(idx) if idx else 0
        try:
            dev = jax.devices(plat)[idx]
        except RuntimeError:
            dev = jax.devices()[0]  # platform not present: sync default
    else:
        dev = device
    jax.block_until_ready(jax.device_put(0, dev))


def get_all_device_type():
    import jax
    return sorted({d.platform for d in jax.devices()})


def get_available_device():
    import jax
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return []


def get_cudnn_version():
    return None  # no cuDNN on TPU


def is_compiled_with_cinn():
    return False


def is_compiled_with_cuda():
    return False


def is_compiled_with_rocm():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_ipu():
    return False


def is_compiled_with_custom_device(device_type=None):
    import jax
    return any(d.platform not in ("cpu", "gpu", "tpu")
               for d in jax.devices())


def is_compiled_with_distribute():
    return True  # XLA collectives are always in
