"""Metrics (reference: python/paddle/metric/metrics.py — Metric base with
compute/update/reset/accumulate, Accuracy, Precision, Recall, Auc)."""
from __future__ import annotations

import abc

import numpy as np

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


class Metric(metaclass=abc.ABCMeta):
    def __init__(self):
        pass

    @abc.abstractmethod
    def reset(self): ...

    @abc.abstractmethod
    def update(self, *args): ...

    @abc.abstractmethod
    def accumulate(self): ...

    @abc.abstractmethod
    def name(self): ...

    def compute(self, *args):
        return args


def _np(x):
    if hasattr(x, "numpy"):
        return x.numpy()
    return np.asarray(x)


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        pred = _np(pred)
        label = _np(label)
        idx = np.argsort(-pred, axis=-1)[..., :self.maxk]
        if label.ndim == pred.ndim:          # one-hot / soft labels
            label = np.argmax(label, axis=-1)
        correct = (idx == label[..., None])
        return correct.astype(np.float32)

    def update(self, correct, *args):
        correct = _np(correct)
        accs = []
        for k in self.topk:
            num = correct[..., :k].sum()
            accs.append(num / max(1, correct.shape[0]))
            self.total[self.topk.index(k)] += num
        self.count += correct.shape[0]
        return accs[0] if len(accs) == 1 else accs

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = 0

    def accumulate(self):
        res = [t / max(1, self.count) for t in self.total]
        return res[0] if len(res) == 1 else res

    def name(self):
        return self._name


class Precision(Metric):
    def __init__(self, name="precision"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = (_np(preds) > 0.5).astype(np.int64).reshape(-1)
        labels = _np(labels).astype(np.int64).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        d = self.tp + self.fp
        return self.tp / d if d else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = (_np(preds) > 0.5).astype(np.int64).reshape(-1)
        labels = _np(labels).astype(np.int64).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        d = self.tp + self.fn
        return self.tp / d if d else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """ROC AUC via threshold bucketing (reference Auc: num_thresholds)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        super().__init__()
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def update(self, preds, labels):
        preds = _np(preds)
        if preds.ndim == 2 and preds.shape[1] == 2:
            preds = preds[:, 1]
        preds = preds.reshape(-1)
        labels = _np(labels).astype(np.int64).reshape(-1)
        idx = np.clip((preds * self.num_thresholds).astype(np.int64),
                      0, self.num_thresholds)
        np.add.at(self._stat_pos, idx, labels == 1)
        np.add.at(self._stat_neg, idx, labels == 0)

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1, np.int64)
        self._stat_neg = np.zeros(self.num_thresholds + 1, np.int64)

    def accumulate(self):
        tot_pos = tot_neg = 0.0
        auc = 0.0
        for i in range(self.num_thresholds, -1, -1):
            new_pos = tot_pos + self._stat_pos[i]
            new_neg = tot_neg + self._stat_neg[i]
            auc += (new_pos + tot_pos) * (new_neg - tot_neg) / 2.0
            tot_pos, tot_neg = new_pos, new_neg
        d = tot_pos * tot_neg
        return float(auc / d) if d else 0.0

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Functional top-k accuracy (reference paddle.metric.accuracy)."""
    from ..ops.registry import op
    import jax.numpy as jnp

    @op(name="accuracy")
    def _acc(pred, lab):
        idx = jnp.argsort(-pred, axis=-1)[..., :k]
        l = lab
        if l.ndim == pred.ndim:
            l = jnp.argmax(l, axis=-1)
        if l.ndim == pred.ndim - 1:
            l = l[..., None]
        hit = jnp.any(idx == l, axis=-1)
        return jnp.mean(hit.astype(jnp.float32))
    return _acc(input, label)
