"""paddle.metric (reference: python/paddle/metric/metrics.py)."""
from .metrics import Metric, Accuracy, Precision, Recall, Auc, accuracy  # noqa: F401
