"""Request lifecycle for the continuous-batching engine.

A request moves QUEUED -> PREFILL -> DECODE -> DONE (or CANCELLED from
any live state).  Tokens stream out as they are sampled: consumers can
poll :attr:`output_tokens`, register an ``on_token`` callback, or pull
from :meth:`stream` (which drives the attached engine when it runs dry,
so a plain ``for tok in req.stream():`` serves the request end to end).
With ``sync_interval > 1`` tokens surface in bursts of up to
``sync_interval`` — the host only observes the device token ring at
sync points, trading streaming latency for fewer device round-trips.
"""
from __future__ import annotations

import enum
import itertools
import time

import numpy as np

from ..models.generation import GenerationConfig
from ..sanitizer import make_lock

__all__ = ["Request", "RequestState", "GenerationConfig"]

_ids = itertools.count()
_ids_lock = make_lock("request._ids_lock")


class RequestState(enum.Enum):
    QUEUED = "queued"
    PREFILL = "prefill"
    DECODE = "decode"
    DONE = "done"
    CANCELLED = "cancelled"


class Request:
    """One generation request.

    ``gen`` is a per-request :class:`GenerationConfig` — each request
    chooses its own ``max_new_tokens`` / ``eos_token_id`` / sampling
    knobs; the engine batches them anyway (iteration-level scheduling:
    the batch composition is a per-step decision, not a compile-time
    one)."""

    def __init__(self, prompt, gen: GenerationConfig | None = None, *,
                 deadline: float | None = None, on_token=None,
                 arrival_time: float | None = None, priority: int = 0,
                 tenant: str | None = None, adapter: str | None = None):
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("empty prompt")
        gen = gen or GenerationConfig()
        if gen.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        with _ids_lock:
            self.id = next(_ids)
        self.prompt = prompt
        self.gen = gen
        self.deadline = deadline          # absolute, on the engine clock
        # scheduling class: higher admits first and may preempt lower
        # residents (server maps low/normal/high -> -1/0/1; any int works)
        self.priority = int(priority)
        # times this request was preempted (evicted for a higher class
        # and re-queued for resume)
        self.preemptions = 0
        self.on_token = on_token
        self.state = RequestState.QUEUED
        self.cancel_requested = False
        # length|eos|cancelled|deadline|error
        self.finish_reason: str | None = None
        # human-readable failure detail when finish_reason == "error"
        # (quarantined by the engine: non-finite logits, replay failure,
        # recovery budget exhausted, ...)
        self.error: str | None = None
        self.output_tokens: list[int] = []
        # prompt tokens served from the engine's prefix cache at
        # admission (0 with caching off); set by Engine._prefill
        self.num_cached_tokens = 0

        # ------------------------------------------------ cost ledger
        # Per-request cost attribution (observability.usage): plain
        # counters the engine bumps unconditionally at the seams that
        # already update the global mirrors, so summed ledgers equal
        # the global counters exactly on deterministic workloads.
        # Billing tenant (HTTP X-Tenant header / body field / submit
        # kwarg; "" and None canonicalize to "anon").
        self.tenant = str(tenant).strip() if tenant else "anon"
        # LoRA adapter id (HTTP X-Adapter header / body field / submit
        # kwarg; None = dense base model).  Resolved to a bank row by
        # the engine's AdapterStore at submit; the row is re-acquired on
        # preemption resume so the id, not the row, is durable state.
        self.adapter = str(adapter).strip() if adapter else None
        self.queue_seconds = 0.0          # admission + resume re-queues
        self.prefill_computed_tokens = 0  # prompt tokens run on device
        self.prefill_cached_tokens = 0    # skipped via prefix cache/CoW
        self.prefill_chunks = 0           # chunked-prefill chunks run
        self.spec_proposed_tokens = 0     # draft tokens proposed
        self.spec_accepted_tokens = 0     # draft tokens accepted
        self.pages_allocated = 0          # fresh pool acquisitions
        self.spilled_pages = 0            # pages copied to host on
        self.spill_bytes = 0              # ... preemption, and back on
        self.restored_pages = 0           # ... resume
        self.restore_bytes = 0
        self.replays = 0                  # recovery replays
        # KV residency, folded in by the UsageMeter (0.0 when off)
        self.page_seconds = 0.0
        self.host_page_seconds = 0.0

        # tracing (observability.tracing): the engine opens a root
        # "request" span per request — parented under the caller's
        # traceparent when one arrived over HTTP — plus child spans for
        # the queue wait and the decode phase.  All None when tracing
        # is not in play (engine-only tests, bare Request objects).
        self.trace_parent = None          # SpanContext from the caller
        self.root_span = None
        self.queue_span = None
        self.decode_span = None
        # tail-latency forensics (observability.requestlog): the
        # engine's RequestLog attaches a RequestTimeline at submit;
        # None when forensics is off — every engine seam guards on it
        self.timeline = None

        # timing (engine clock): TTFT = first_token_at - arrival_time
        self.arrival_time = time.monotonic() if arrival_time is None \
            else arrival_time
        # queue-wait anchor for the cost ledger: reset to "now" on a
        # preemption re-queue so queue_seconds sums every wait
        self._queued_since = self.arrival_time
        self.admitted_at: float | None = None
        # FIFO stamp assigned by the scheduler at FIRST submit; a
        # preempted victim keeps it, so it re-queues ahead of later
        # arrivals of its class (Request ids are construction order,
        # which is not necessarily submission order)
        self.arrival_seq: int | None = None
        self.first_token_at: float | None = None
        self.last_token_at: float | None = None
        self.finished_at: float | None = None

        self._engine = None               # set by Engine.submit

    # ------------------------------------------------------------- status
    @property
    def num_generated(self) -> int:
        return len(self.output_tokens)

    def is_finished(self) -> bool:
        return self.state in (RequestState.DONE, RequestState.CANCELLED)

    def resume_tokens(self) -> np.ndarray:
        """Prompt + tokens generated so far — the effective prompt a
        preempted request re-prefills from on re-admission."""
        if not self.output_tokens:
            return self.prompt
        return np.concatenate(
            [self.prompt, np.asarray(self.output_tokens, np.int32)])

    @property
    def remaining_new_tokens(self) -> int:
        """Generation budget left after any already-emitted tokens."""
        return max(self.gen.max_new_tokens - self.num_generated, 1)

    def cancel(self):
        """Request cancellation.  Queued requests drop at the next
        scheduling pass; running requests are evicted at the next
        iteration boundary (their pages return to the pool)."""
        if not self.is_finished():
            self.cancel_requested = True

    # ---------------------------------------------------------- streaming
    def stream(self):
        """Yield output tokens in order.  When no token is pending and
        the request is attached to an engine, drives ``engine.step()``
        until the next token lands (or the request finishes)."""
        i = 0
        while True:
            while i < len(self.output_tokens):
                yield self.output_tokens[i]
                i += 1
            if self.is_finished():
                return
            if self._engine is None:
                return
            if not self._engine.step() and not self.is_finished() \
                    and i >= len(self.output_tokens):
                raise RuntimeError(
                    f"engine made no progress while request {self.id} is "
                    f"{self.state.value} (drained engine?)")

    def result(self) -> list[int]:
        """Block (by driving the attached engine) until finished; returns
        the generated tokens."""
        for _ in self.stream():
            pass
        return list(self.output_tokens)

    # ------------------------------------------------- engine-side hooks
    def _emit(self, token: int, now: float):
        self.output_tokens.append(int(token))
        if self.first_token_at is None:
            self.first_token_at = now
        self.last_token_at = now
        if self.on_token is not None:
            self.on_token(self, int(token))

    def __repr__(self):
        return (f"Request(id={self.id}, state={self.state.value}, "
                f"prompt_len={self.prompt.size}, "
                f"generated={self.num_generated}/{self.gen.max_new_tokens})")
