"""KV-page allocator for the serving engine.

Reference analog: the block tables fed to
block_multi_head_attention_kernel.cu — each sequence owns a list of
fixed-size pages in one shared pool, so HBM scales with the tokens
actually resident, not batch * max_len.

Unlike :class:`~paddle_tpu.ops.pallas.paged_attention.PagedPool` (which
reserves pages for ONE static batch up front), this manager serves a
changing request population: pages cycle through a free list as
requests are admitted and evicted, and an allocation that does not fit
returns ``None`` — backpressure the scheduler turns into queueing,
never an exception out of the engine.

The dump-page convention matches the paged kernel's contract: page id
``num_pages`` is a shared scratch page that absorbs writes through
table padding; it is never handed to a sequence.
"""
from __future__ import annotations

import numpy as np

from .. import observability as _obs

__all__ = ["BlockManager"]

_M_PAGES_IN_USE = _obs.gauge(
    "serving_pages_in_use", "KV pages currently owned by live sequences")
_M_PAGES_TOTAL = _obs.gauge(
    "serving_pages_total", "allocatable KV pages in the engine pool")


class BlockManager:
    """Free-list page allocator + per-sequence block tables.

    ``num_pages`` is the number of *allocatable* pages; the pool arrays
    the engine builds must hold ``num_pages + 1`` rows (the extra row is
    the dump page, :attr:`dump_page`).
    """

    def __init__(self, num_pages: int, page_size: int):
        if num_pages < 1:
            raise ValueError(f"num_pages must be >= 1, got {num_pages}")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.dump_page = self.num_pages       # pool row past the real pages
        # FIFO reuse keeps page churn spread across the pool
        self._free: list[int] = list(range(self.num_pages))
        self._tables: dict[int, list[int]] = {}   # seq id -> owned pages
        _M_PAGES_TOTAL.set(self.num_pages)
        _M_PAGES_IN_USE.set(0)

    # ------------------------------------------------------------- sizing
    def pages_needed(self, prompt_len: int, max_new_tokens: int) -> int:
        """Pages a request reserves for its whole lifetime (prompt +
        every token it may generate) — admission is all-or-nothing, so
        an admitted request can never hit pool exhaustion mid-decode."""
        return -(-(int(prompt_len) + int(max_new_tokens)) // self.page_size)

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def pages_in_use(self) -> int:
        return self.num_pages - len(self._free)

    def can_allocate(self, n: int) -> bool:
        return n <= len(self._free)

    # --------------------------------------------------------- alloc/free
    def allocate(self, seq_id: int, n: int):
        """Reserve ``n`` pages for ``seq_id``.  Returns the page-id list,
        or ``None`` when the pool cannot satisfy the request
        (backpressure — the caller keeps the request queued)."""
        if seq_id in self._tables:
            raise ValueError(f"sequence {seq_id} already owns pages")
        if n > len(self._free):
            return None
        pages, self._free = self._free[:n], self._free[n:]
        self._tables[seq_id] = pages
        _M_PAGES_IN_USE.set(self.pages_in_use)
        return list(pages)

    def free_seq(self, seq_id: int):
        """Return ``seq_id``'s pages to the free list (idempotent)."""
        pages = self._tables.pop(seq_id, None)
        if pages:
            self._free.extend(pages)
        _M_PAGES_IN_USE.set(self.pages_in_use)

    def pages_of(self, seq_id: int):
        return list(self._tables.get(seq_id, ()))

    # ------------------------------------------------------------- tables
    def table_row(self, seq_id: int, width: int) -> np.ndarray:
        """The sequence's block-table row, dump-padded to ``width``
        (the engine's static table shape)."""
        pages = self._tables.get(seq_id, ())
        if len(pages) > width:
            raise ValueError(
                f"sequence {seq_id} owns {len(pages)} pages, table width "
                f"is only {width}")
        row = np.full((width,), self.dump_page, np.int32)
        row[:len(pages)] = pages
        return row

    def empty_row(self, width: int) -> np.ndarray:
        """An all-dump row (idle slots write/read only the dump page)."""
        return np.full((width,), self.dump_page, np.int32)
