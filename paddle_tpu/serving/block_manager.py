"""KV-page allocator + automatic prefix cache for the serving engine.

Reference analog: the block tables fed to
block_multi_head_attention_kernel.cu — each sequence owns a list of
fixed-size pages in one shared pool, so HBM scales with the tokens
actually resident, not batch * max_len.

Unlike :class:`~paddle_tpu.ops.pallas.paged_attention.PagedPool` (which
reserves pages for ONE static batch up front), this manager serves a
changing request population: pages cycle through a free list as
requests are admitted and evicted, and an allocation that does not fit
returns ``None`` — backpressure the scheduler turns into queueing,
never an exception out of the engine.

With ``enable_prefix_cache=True`` the manager additionally runs
automatic prefix caching (vLLM's hash-based PagedAttention reuse /
SGLang's RadixAttention, restructured as a chain index over pages):

  * every page holding a **page_size-aligned full chunk** of a prompt
    is registered in a chain index keyed ``(parent page, token chunk)``
    — exact-match keys, so a recycled parent id can never alias a stale
    chain (children are detached before a parent is ever reused);
  * a later request walks its prompt chunk-by-chunk down the chain and
    **shares** every page it matches (refcount++), paying pages only
    for the unmatched suffix — admission is charged for *new* pages
    only, which is what raises effective pool capacity;
  * the **partially-filled tail page** of a prompt is indexed with its
    token content; a new request whose suffix extends a matching tail
    gets a **copy-on-write** source: the engine copies the page's KV
    rows into the request's own tail page and recomputes only from the
    divergence point (the shared copy is never written);
  * when a sequence releases its pages, registered pages with refcount
    0 park in an **LRU** side pool instead of the free list; under
    pressure the allocator evicts LRU pages leaf-first (a page is only
    evicted once no cached chain or tail hangs off it), so the free
    list is a floor, not a ceiling, on allocatable pages.

The dump-page convention matches the paged kernel's contract: page id
``num_pages`` is a shared scratch page that absorbs writes through
table padding; it is never handed to a sequence.
"""
from __future__ import annotations

import hashlib
from collections import OrderedDict, deque

import numpy as np

from .. import observability as _obs

__all__ = ["BlockManager"]

_M_PAGES_IN_USE = _obs.gauge(
    "serving_pages_in_use", "KV pages currently owned by live sequences")
_M_PAGES_TOTAL = _obs.gauge(
    "serving_pages_total", "allocatable KV pages in the engine pool")
_M_PREFIX_PAGES = _obs.counter(
    "serving_prefix_cache_pages_total",
    "full-chunk prefix-cache lookups by result", ("result",))
_M_PREFIX_TOKENS = _obs.counter(
    "serving_prefix_cached_tokens_total",
    "prompt tokens whose prefill was skipped via the prefix cache")
_M_PREFIX_EVICT = _obs.counter(
    "serving_prefix_cache_evictions_total",
    "cached refcount-0 pages evicted (LRU, leaf-first) under pressure")
_M_PREFIX_COW = _obs.counter(
    "serving_prefix_cache_cow_total",
    "copy-on-write page copies for partially-filled tail pages")
_M_CACHED_PAGES = _obs.gauge(
    "serving_prefix_cached_pages",
    "pages currently registered in the prefix index (incl. shared)")
_M_PAGES_FREE = _obs.gauge(
    "serving_pages_free", "KV pages on the free list (parked cached "
    "pages are reusable but counted separately)")
_M_FRAG = _obs.gauge(
    "serving_page_fragmentation_ratio",
    "fraction of idle pages (free + parked cached) the largest waiting "
    "request cannot use (0: nothing waiting or all idle pages usable; "
    "1: the queue head cannot be placed at all)")
_M_PAGES_ALLOC = _obs.counter(
    "serving_pages_allocated_total",
    "fresh page acquisitions (free-list pops + LRU evictions; shared "
    "prefix-cache pages are not re-acquired)")
_M_SPILLED = _obs.counter(
    "serving_spilled_pages_total",
    "KV pages copied device -> host RAM when a resident was preempted "
    "for a higher-priority request")
_M_RESTORED = _obs.counter(
    "serving_restored_pages_total",
    "host-parked KV pages copied back to device on preempted-request "
    "resume (prefill skipped for those positions)")
_M_SPILL_BYTES = _obs.counter(
    "serving_spill_bytes_total",
    "bytes of KV copied device -> host by preemption spills")
_M_HOST_PARKED = _obs.gauge(
    "serving_host_spill_pages",
    "KV pages currently parked in the host-RAM spill tier "
    "(content-addressed, LRU-bounded by FLAGS_serving_host_pages)")

_ROOT = -1          # chain parent of the first chunk of every prompt


class BlockManager:
    """Free-list page allocator + per-sequence block tables (+ optional
    prefix cache).

    ``num_pages`` is the number of *allocatable* pages; the pool arrays
    the engine builds must hold ``num_pages + 1`` rows (the extra row is
    the dump page, :attr:`dump_page`).
    """

    def __init__(self, num_pages: int, page_size: int,
                 enable_prefix_cache: bool = False, faults=None,
                 host_pages: int | None = None):
        if num_pages < 1:
            raise ValueError(f"num_pages must be >= 1, got {num_pages}")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        if host_pages is None:
            from ..flags import FLAGS
            host_pages = int(FLAGS.get("FLAGS_serving_host_pages") or 0)
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.host_pages = max(int(host_pages), 0)
        self.dump_page = self.num_pages       # pool row past the real pages
        self.prefix_cache = bool(enable_prefix_cache)
        self.faults = faults                  # chaos harness (None = off)
        # FIFO reuse keeps page churn spread across the pool; a deque
        # makes both ends O(1) (popping the head of a plain list shifts
        # the whole tail on every acquisition)
        self._free: deque[int] = deque(range(self.num_pages))
        self._tables: dict[int, list[int]] = {}   # seq id -> owned pages
        self._ref: dict[int, int] = {}            # page -> live-seq refs
        self._meta: dict[int, dict] = {}          # seq id -> prefill plan
        # committed-token ledger (speculative append/rollback): seq id ->
        # {"committed", "floor", "capacity"} token counts
        self._commit: dict[int, dict] = {}
        # prefix-cache state.  Chain index: (parent page, chunk) -> page;
        # tail index: parent page -> {page: partial-chunk tokens}.
        self._index: dict[tuple, int] = {}
        self._key_of: dict[int, tuple] = {}       # page -> its chain key
        self._tails: dict[int, dict[int, tuple]] = {}
        self._tail_parent: dict[int, int] = {}    # tail page -> parent
        self._children: dict[int, set] = {}       # page -> cached children
        self._lru: OrderedDict[int, None] = OrderedDict()
        # host spill tier (preempt-and-swap): content-addressed KV page
        # copies keyed by the sha1 of the absolute token prefix they
        # cover — under greedy causal attention a page's KV depends only
        # on that prefix, so any sequence sharing it can unpark the copy
        self._host: OrderedDict[str, tuple] = OrderedDict()
        # chunked-prefill publish deferral: when the engine will prefill
        # an admission in chunks of this many tokens, allocate_seq skips
        # chain registration (the pages hold no KV yet) and the engine
        # calls publish_seq once the last chunk has landed (0 = off)
        self.defer_publish = 0
        # usage meter (observability.usage.UsageMeter) fed page
        # hold/release and host-tier eviction events for the
        # page-seconds ledger; None (the default) costs one attribute
        # test per allocation — the engine wires it when metering is on
        self.usage = None
        # python-side mirrors of the serving_prefix_* metrics (stats())
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.prefix_evictions = 0
        self.cow_copies = 0
        self.cached_tokens = 0
        self.pages_allocated = 0    # mirror of serving_pages_allocated_total
        self.spilled_pages = 0      # mirror of serving_spilled_pages_total
        self.restored_pages = 0     # mirror of serving_restored_pages_total
        self.spill_bytes = 0        # mirror of serving_spill_bytes
        _M_PAGES_TOTAL.set(self.num_pages)
        self._update_pool_gauges()

    # ------------------------------------------------------------- sizing
    def pages_needed(self, prompt_len: int, max_new_tokens: int) -> int:
        """Pages a request reserves for its whole lifetime (prompt +
        every token it may generate) — admission is all-or-nothing, so
        an admitted request can never hit pool exhaustion mid-decode."""
        return -(-(int(prompt_len) + int(max_new_tokens)) // self.page_size)

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def cached_pages(self) -> int:
        """Pages registered in the prefix index (shared or parked)."""
        return len(self._key_of) + len(self._tail_parent)

    @property
    def pages_in_use(self) -> int:
        """Pages owned by live sequences.  Cached refcount-0 pages in
        the LRU side pool are reusable, so they do not count."""
        return self.num_pages - len(self._free) - len(self._lru)

    def can_allocate(self, n: int) -> bool:
        return n <= len(self._free) + len(self._lru)

    # --------------------------------------------------------- alloc/free
    def allocate(self, seq_id: int, n: int):
        """Reserve ``n`` pages for ``seq_id``.  Returns the page-id list,
        or ``None`` when the pool cannot satisfy the request
        (backpressure — the caller keeps the request queued)."""
        if seq_id in self._tables:
            raise ValueError(f"sequence {seq_id} already owns pages")
        pages = self._acquire(n)
        if pages is None:
            return None
        for p in pages:
            self._ref[p] = 1
        self._tables[seq_id] = pages
        self._meta[seq_id] = {"cached_len": 0, "cow_src": None}
        self._commit[seq_id] = {"committed": 0, "floor": 0,
                                "capacity": n * self.page_size}
        _obs.flight("blocks", "alloc_seq", seq=seq_id, pages=len(pages),
                    shared=0, cached_tokens=0, cow=False)
        if self.usage is not None:
            self.usage.on_hold(seq_id, pages, fresh=len(pages))
        self._update_pool_gauges()
        return list(pages)

    def allocate_seq(self, seq_id: int, prompt, max_new_tokens: int):
        """Admission entry point: match ``prompt`` against the prefix
        cache, share matched pages, and reserve fresh pages for the
        suffix only.  Returns the sequence's full page list (shared
        prefix first) or ``None`` on backpressure.  The prefill plan
        (``cached_len``, ``cow_src``) is retrievable via
        :meth:`seq_meta` until :meth:`free_seq`."""
        if not self.prefix_cache:
            pages = self.allocate(seq_id,
                                  self.pages_needed(len(prompt),
                                                    max_new_tokens))
            if pages is not None:
                c = self._commit[seq_id]
                c["committed"] = c["floor"] = len(prompt)
            return pages
        if seq_id in self._tables:
            raise ValueError(f"sequence {seq_id} already owns pages")
        prompt = tuple(int(t) for t in np.asarray(prompt).reshape(-1))
        plen = len(prompt)
        ps = self.page_size
        total = self.pages_needed(plen, max_new_tokens)
        full = plen // ps

        # walk the chain index chunk by chunk
        matched: list[int] = []
        parent = _ROOT
        for c in range(full):
            page = self._index.get((parent, prompt[c * ps:(c + 1) * ps]))
            if page is None:
                break
            matched.append(page)
            parent = page
        if matched and len(matched) * ps >= plen:
            # full-prompt hit: drop the last match so at least one token
            # still runs through the model (its logits seed decoding)
            matched.pop()
            parent = matched[-1] if matched else _ROOT
        m = len(matched)
        self.prefix_hits += m
        self.prefix_misses += full - m
        if m:
            _M_PREFIX_PAGES.labels("hit").inc(m)
        if full - m:
            _M_PREFIX_PAGES.labels("miss").inc(full - m)

        # protect the matched chain, then acquire the suffix pages (the
        # acquire may LRU-evict; refcounted pages are never candidates)
        for p in matched:
            self._incref(p)
        fresh = self._acquire(total - m)
        if fresh is None:
            for p in matched:
                self._decref(p)
            self._update_pool_gauges()
            return None
        for p in fresh:
            self._ref[p] = 1

        # copy-on-write probe AFTER acquiring (the acquire could have
        # evicted a tail candidate): longest common prefix between the
        # prompt's remainder and a cached partial tail under `parent`
        cached_len = m * ps
        cow_src = None
        rem = prompt[m * ps:]
        best_cp = 0
        for page, toks in self._tails.get(parent, {}).items():
            cp = 0
            for a, b in zip(rem, toks):
                if a != b:
                    break
                cp += 1
            # cap so at least one prompt token is left to recompute
            cp = min(cp, plen - m * ps - 1)
            if cp > best_cp:
                best_cp, cow_src = cp, page
        if cow_src is not None:
            cached_len += best_cp
            self.cow_copies += 1
            _M_PREFIX_COW.inc()

        self.cached_tokens += cached_len
        if cached_len:
            _M_PREFIX_TOKENS.inc(cached_len)

        # chunked admissions defer registration: the fresh pages hold no
        # KV until their chunk runs, and a concurrent admission matching
        # them in the meantime would attend over unwritten pages —
        # publish_seq re-runs the registration after the last chunk
        deferred = bool(self.defer_publish
                        and plen - cached_len > self.defer_publish)

        pages = matched + fresh
        self._tables[seq_id] = pages
        self._meta[seq_id] = {"cached_len": cached_len, "cow_src": cow_src,
                              "deferred": deferred}
        self._commit[seq_id] = {"committed": plen, "floor": plen,
                                "capacity": total * ps}
        _obs.flight("blocks", "alloc_seq", seq=seq_id, pages=len(pages),
                    shared=m, cached_tokens=cached_len,
                    cow=cow_src is not None)
        if self.usage is not None:
            self.usage.on_hold(seq_id, pages, fresh=len(fresh))

        # register this prompt's fresh full chunks (chain through any
        # page an identical chunk already cached)
        for c in range(m, full):
            if deferred:
                break
            key = (parent, prompt[c * ps:(c + 1) * ps])
            existing = self._index.get(key)
            if existing is not None:
                parent = existing
                continue
            page = pages[c]
            self._index[key] = page
            self._key_of[page] = key
            self._children.setdefault(parent, set()).add(page)
            parent = page
        # register the partial tail (its prompt-token content is final:
        # decode writes only to later slots of the page)
        off = plen - full * ps
        if off > 0 and not deferred:
            tail_toks = prompt[full * ps:]
            tails = self._tails.setdefault(parent, {})
            if tail_toks not in tails.values():
                page = pages[full]
                tails[page] = tail_toks
                self._tail_parent[page] = parent
                self._children.setdefault(parent, set()).add(page)
        _M_CACHED_PAGES.set(self.cached_pages)
        self._update_pool_gauges()
        return list(pages)

    def publish_seq(self, seq_id: int, tokens):
        """Deferred chain registration for a chunk-prefilled admission.

        :meth:`allocate_seq` skips chain/tail registration when the
        engine will prefill in chunks (``meta["deferred"]``); the
        engine calls this once the last chunk has landed, passing
        exactly the token prefix whose KV is now device-resident.
        Idempotent and a no-op for non-deferred sequences."""
        meta = self._meta.get(seq_id)
        pages = self._tables.get(seq_id)
        if (not self.prefix_cache or not meta or not pages
                or not meta.pop("deferred", False)):
            return
        toks = tuple(int(t) for t in np.asarray(tokens).reshape(-1))
        ps = self.page_size
        full = min(len(toks) // ps, len(pages))
        parent = _ROOT
        for c in range(full):
            key = (parent, toks[c * ps:(c + 1) * ps])
            existing = self._index.get(key)
            if existing is not None:
                parent = existing
                continue
            page = pages[c]
            if page in self._key_of or page in self._tail_parent:
                parent = page         # already carries another key
                continue
            self._index[key] = page
            self._key_of[page] = key
            self._children.setdefault(parent, set()).add(page)
            parent = page
        off = len(toks) - full * ps
        if off > 0 and full < len(pages):
            page = pages[full]
            tail_toks = toks[full * ps:]
            tails = self._tails.setdefault(parent, {})
            if (tail_toks not in tails.values()
                    and page not in self._key_of
                    and page not in self._tail_parent):
                tails[page] = tail_toks
                self._tail_parent[page] = parent
                self._children.setdefault(parent, set()).add(page)
        _obs.flight("blocks", "publish_seq", seq=seq_id,
                    chunks=full, tail=off)
        _M_CACHED_PAGES.set(self.cached_pages)

    def seq_meta(self, seq_id: int) -> dict:
        """The prefill plan recorded at admission: ``cached_len`` tokens
        already resident (prefill runs only the suffix) and ``cow_src``,
        the tail page to copy-on-write from (or None)."""
        meta = self._meta.get(seq_id)
        if meta is None:
            return {"cached_len": 0, "cow_src": None}
        # "deferred" is internal publish bookkeeping, not plan state
        return {"cached_len": meta["cached_len"],
                "cow_src": meta["cow_src"]}

    def free_seq(self, seq_id: int):
        """Release ``seq_id``'s pages (idempotent).  Registered pages
        whose refcount hits 0 park in the LRU pool (still matchable);
        unregistered pages return to the free list."""
        pages = self._tables.pop(seq_id, None)
        self._meta.pop(seq_id, None)
        self._commit.pop(seq_id, None)
        if pages:
            if self.usage is not None:
                self.usage.on_release(seq_id, pages)
            for p in pages:
                self._decref(p)
        self._update_pool_gauges()

    def pages_of(self, seq_id: int):
        return list(self._tables.get(seq_id, ()))

    # ---------------------------------------------------------- recovery
    def flush_prefix_cache(self) -> int:
        """Invalidate every prefix-cache registration and free the
        parked LRU pages.  Called when the device KV pool is rebuilt
        (engine recovery): the chain index describes KV *content* that
        no longer exists, so any future match would share garbage.
        Live sequences keep their tables/refcounts — their content is
        regenerated by replay — but their pages are unregistered, so a
        later free sends them to the free list, not the LRU.  Returns
        the number of registrations dropped."""
        dropped = len(self._key_of) + len(self._tail_parent)
        for page in self._lru:
            self._free.append(page)
        self._lru.clear()
        self._index.clear()
        self._key_of.clear()
        self._tails.clear()
        self._tail_parent.clear()
        self._children.clear()
        _M_CACHED_PAGES.set(self.cached_pages)
        self._update_pool_gauges()
        if dropped:
            _obs.flight("blocks", "prefix_flush", dropped=dropped)
        return dropped

    def replay_plan(self, seq_id: int, tokens) -> dict:
        """Prefill plan for re-running ``seq_id``'s committed ``tokens``
        through the model after a runner rebuild (the sequence still
        owns its pages; only device KV content was lost).

        Walks the chain index like admission, but a chunk only counts
        as cached when the index maps it to **this sequence's own
        page** — sharers hold identical page ids, so once one of them
        has replayed, the others' leading chunks match and their
        replay prefills only the unshared suffix.  The replayed full
        chunks are (re-)registered on the sequence's own pages; partial
        tails are not re-registered (past the prompt they contain
        generated tokens, which admission-time tail matching must never
        see).  Returns ``{"cached_len", "hits", "misses"}``; at least
        one token is always left to recompute."""
        pages = self._tables.get(seq_id)
        if pages is None:
            raise ValueError(f"sequence {seq_id} owns no pages")
        if not self.prefix_cache:
            return {"cached_len": 0, "hits": 0, "misses": 0}
        tokens = tuple(int(t) for t in np.asarray(tokens).reshape(-1))
        ps = self.page_size
        full = len(tokens) // ps
        matched = 0
        parent = _ROOT
        for c in range(full):
            page = self._index.get((parent, tokens[c * ps:(c + 1) * ps]))
            if page is None or page != pages[c]:
                break
            matched += 1
            parent = page
        cached_len = min(matched * ps, len(tokens) - 1)
        self.prefix_hits += matched
        self.prefix_misses += full - matched
        if matched:
            _M_PREFIX_PAGES.labels("hit").inc(matched)
        if full - matched:
            _M_PREFIX_PAGES.labels("miss").inc(full - matched)
        if cached_len:
            self.cached_tokens += cached_len
            _M_PREFIX_TOKENS.inc(cached_len)
        # re-register the chunks this replay regenerates, chaining
        # through any page an identical chunk already re-cached
        for c in range(matched, full):
            key = (parent, tokens[c * ps:(c + 1) * ps])
            existing = self._index.get(key)
            if existing is not None:
                parent = existing
                continue
            page = pages[c]
            if page in self._key_of:      # already carries another key
                parent = page
                continue
            self._index[key] = page
            self._key_of[page] = key
            self._children.setdefault(parent, set()).add(page)
            parent = page
        _M_CACHED_PAGES.set(self.cached_pages)
        return {"cached_len": cached_len, "hits": matched,
                "misses": full - matched}

    # ------------------------------------ host spill tier (preemption)
    def spill_digest(self, tokens, chunk: int) -> str:
        """Content address of page ``chunk``'s KV: sha1 over the int32
        bytes of the absolute token prefix the page covers.  Greedy
        causal attention makes KV a pure function of that prefix, so
        the digest is valid across sequences and across preempt/resume
        cycles of the same request."""
        ps = self.page_size
        data = np.asarray(tokens, np.int32).reshape(-1)[:(chunk + 1) * ps]
        return hashlib.sha1(data.tobytes()).hexdigest()

    def spill_plan(self, seq_id: int, tokens) -> list:
        """``(page, digest)`` pairs worth copying to host before
        ``seq_id`` is preempted: exclusive (refcount-1) pages holding a
        *complete* chunk of committed KV.  After a sync the device KV
        covers positions ``0..len(tokens)-2`` (the last emitted token's
        KV is written by the next decode step), so chunk ``c`` is
        complete iff ``(c+1)*page_size <= len(tokens)-1``.  Shared
        pages are skipped — they stay matchable through the chain
        index; pages whose digest is already parked are skipped too
        (content-addressed: the copy exists)."""
        if self.host_pages <= 0:
            return []
        pages = self._tables.get(seq_id)
        if not pages:
            return []
        toks = np.asarray(tokens, np.int32).reshape(-1)
        full = max(0, (toks.size - 1) // self.page_size)
        plan = []
        for c in range(min(full, len(pages))):
            page = pages[c]
            if self._ref.get(page, 0) != 1:
                continue
            digest = self.spill_digest(toks, c)
            if digest in self._host:
                self._host.move_to_end(digest)
                continue
            plan.append((page, digest))
        return plan

    def host_put(self, digest: str, *arrays):
        """Park one page's KV in the host tier (LRU-bounded).

        Variadic: dense pages park ``(k, v)``; int8 KV pages park
        ``(k, v, kscale, vscale)`` — the quantized bytes plus their f32
        scales, never a dequantized copy, which is what makes
        ``spill_bytes`` genuinely shrink under ``kv_quant``.  The byte
        ledger sums the actual itemsize of whatever was parked."""
        arrays = tuple(np.asarray(a) for a in arrays)
        self._host[digest] = arrays
        self._host.move_to_end(digest)
        while len(self._host) > self.host_pages:
            dropped, _ = self._host.popitem(last=False)
            if self.usage is not None:
                self.usage.on_host_evict(dropped)
        nbytes = sum(a.nbytes for a in arrays)
        self.spilled_pages += 1
        self.spill_bytes += nbytes
        _M_SPILLED.inc()
        _M_SPILL_BYTES.inc(nbytes)
        _M_HOST_PARKED.set(len(self._host))

    def host_probe(self, digest: str) -> bool:
        return digest in self._host

    @property
    def host_parked(self) -> int:
        """Pages currently parked in the host spill tier."""
        return len(self._host)

    def host_get(self, digest: str):
        """The parked array tuple for ``digest`` (LRU-touched), or
        None — ``(k, v)`` dense, ``(k, v, kscale, vscale)`` int8."""
        entry = self._host.get(digest)
        if entry is not None:
            self._host.move_to_end(digest)
        return entry

    def host_discard(self, digests):
        """Drop parked entries (failed-spill abort path)."""
        for d in digests:
            if self._host.pop(d, None) is not None \
                    and self.usage is not None:
                self.usage.on_host_evict(d)
        _M_HOST_PARKED.set(len(self._host))

    def note_restored(self, n: int = 1):
        """Account ``n`` host-parked pages copied back to device."""
        self.restored_pages += n
        _M_RESTORED.inc(n)

    def release_preempted(self, seq_id: int, tokens):
        """Release a preempted sequence's pages after its exclusive KV
        was spilled to host.  With the prefix cache on, the complete
        committed chunks are first (re-)registered in the chain index —
        replay_plan-style, on the sequence's own pages — so they park
        in the LRU instead of the free list and the resume admission
        matches them without recomputing.  Partial tails are never
        registered (past the prompt they hold generated tokens, which
        admission-time tail matching must not see)."""
        if self.prefix_cache and seq_id in self._tables:
            pages = self._tables[seq_id]
            toks = tuple(int(t)
                         for t in np.asarray(tokens).reshape(-1))
            ps = self.page_size
            full = min(max(0, (len(toks) - 1) // ps), len(pages))
            parent = _ROOT
            for c in range(full):
                key = (parent, toks[c * ps:(c + 1) * ps])
                existing = self._index.get(key)
                if existing is not None:
                    parent = existing
                    continue
                page = pages[c]
                if page in self._key_of:  # already carries another key
                    parent = page
                    continue
                self._index[key] = page
                self._key_of[page] = key
                self._children.setdefault(parent, set()).add(page)
                parent = page
            _M_CACHED_PAGES.set(self.cached_pages)
        self.free_seq(seq_id)

    # ------------------------------------- committed tokens (speculative)
    # Pages are reserved all-or-nothing at admission, so speculative
    # decoding never allocates mid-flight; what moves is the
    # committed-token ledger.  A verify step appends all k+1 proposed
    # positions, then rolls the rejected suffix back, so the ledger
    # charges pages (ceil(committed / page_size)) for ACCEPTED tokens
    # only.  Page ids never move and refcounts are untouched, which is
    # what keeps CoW/prefix-cache sharing safe under rollback: a
    # rejected position's stale KV sits past the sequence's visible
    # length (`lens = pos + 1` masks it) until a later append overwrites
    # it in place.

    def committed_tokens(self, seq_id: int) -> int:
        """Tokens durably owned by ``seq_id`` (prompt + accepted)."""
        return int(self._commit.get(seq_id, {}).get("committed", 0))

    def committed_pages(self, seq_id: int) -> int:
        """Pages charged for the committed tokens — the accepted-only
        page charge the speculative path reports against the all-or-
        nothing reservation."""
        c = self.committed_tokens(seq_id)
        return -(-c // self.page_size)

    def append(self, seq_id: int, n: int) -> int:
        """Advance ``seq_id``'s committed-token count by ``n`` (the
        multi-token path: a verify step appends all k+1 proposed
        positions at once).  Raises if the sequence is unknown or the
        append would overrun its admission reservation — admitted
        requests can never legally hit this.  Returns the new count."""
        c = self._commit.get(seq_id)
        if c is None:
            raise ValueError(f"sequence {seq_id} owns no pages")
        if n < 0:
            raise ValueError(f"append of {n} tokens (use rollback)")
        if c["committed"] + n > c["capacity"]:
            raise ValueError(
                f"sequence {seq_id}: appending {n} tokens overruns the "
                f"reservation ({c['committed']} committed, capacity "
                f"{c['capacity']})")
        c["committed"] += n
        return c["committed"]

    def rollback(self, seq_id: int, n: int) -> int:
        """Retreat ``seq_id``'s committed-token count by ``n`` rejected
        speculative positions.  Raises if that would drop below the
        admission content (the prompt) — rollback can only undo
        speculation, never durable tokens, so prefix-cache chunks
        registered at admission stay valid.  Returns the new count."""
        c = self._commit.get(seq_id)
        if c is None:
            raise ValueError(f"sequence {seq_id} owns no pages")
        if n < 0:
            raise ValueError(f"rollback of {n} tokens (use append)")
        if c["committed"] - n < c["floor"]:
            raise ValueError(
                f"sequence {seq_id}: rolling back {n} tokens drops below "
                f"the admission content ({c['committed']} committed, "
                f"floor {c['floor']})")
        c["committed"] -= n
        return c["committed"]

    # --------------------------------------------------- pool accounting
    def _update_pool_gauges(self):
        _M_PAGES_IN_USE.set(self.pages_in_use)
        _M_PAGES_FREE.set(len(self._free))

    def pool_accounting(self) -> dict:
        """Exact pool census from three independent structures.  Every
        allocatable page is in exactly one of: referenced by a live
        sequence (``live``), parked refcount-0 in the prefix LRU
        (``cached``), or on the free list (``free``) — ``leak`` is the
        shortfall and must be 0 (asserted by tests, surfaced here so a
        future accounting bug shows up in /debug/resources, not as a
        slow pool shrink)."""
        live = len(self._ref)
        cached = len(self._lru)
        free = len(self._free)
        return {"live": live, "cached": cached, "free": free,
                "total": self.num_pages,
                "allocated_total": self.pages_allocated,
                "host_parked": len(self._host),
                "leak": self.num_pages - (live + cached + free)}

    def prefix_digest(self, max_entries: int = 64) -> dict:
        """Compact cached-chain summary for the fleet plane: the sha1
        digest (first 16 hex chars) of every *root-level* cached chunk,
        hashed over the same int32 token bytes as the router's
        affinity key — so the router can match an incoming prompt's
        first page-aligned chunk against a replica's published digests
        and estimate its expected prefix-hit rate without shipping
        token ids over the wire."""
        roots = sorted(
            hashlib.sha1(np.asarray(chunk, np.int32).tobytes())
            .hexdigest()[:16]
            for (parent, chunk) in self._index if parent == _ROOT)
        return {"page_size": self.page_size,
                "roots": roots[:max_entries],
                "dropped": max(0, len(roots) - max_entries),
                "cached_pages": self.cached_pages,
                "cached_tokens": self.cached_tokens}

    def pool_bytes(self, *, num_layers: int, num_kv_heads: int,
                   head_dim: int, dtype_itemsize: int, tp: int = 1,
                   kv_quant: bool = False) -> dict:
        """KV pool sizing for the engine's pool arrays, head-sharded
        over a tp-way mesh.  The pool the runner builds is
        ``2 * [L, num_pages+1, kvh, page_size, hd]`` (k + v, one extra
        dump row); sharding along the head axis divides exactly that by
        ``tp`` per device, while the page table (and this manager's
        whole accounting) stays host-side and mesh-agnostic — the same
        page ids address every shard.  ``kv_quant`` sizes the int8 page
        mode: 1-byte KV elements plus the two f32 scale pools
        (``2 * [L, rows, kvh, page_size]``)."""
        if tp < 1 or num_kv_heads % tp:
            raise ValueError(
                f"tp={tp} must be >= 1 and divide num_kv_heads="
                f"{num_kv_heads} (the pool shards along the head axis)")
        rows = self.num_pages + 1           # + dump page
        elems = (2 * num_layers * rows * num_kv_heads * self.page_size
                 * head_dim)
        if kv_quant:
            total = elems + (2 * num_layers * rows * num_kv_heads
                             * self.page_size * 4)
        else:
            total = elems * dtype_itemsize
        return {"total_bytes": total,
                "per_device_bytes": total // tp,
                "rows": rows, "tp": tp, "kv_quant": bool(kv_quant)}

    def _reclaimable(self) -> int:
        """Parked LRU pages an allocator under pressure could actually
        recycle: leaf-first eviction frees a parked page only once every
        cached child is gone, so a parked parent whose children include
        a *live* page is pinned.  Computed as a leaf-peeling fixpoint
        (peel parked pages whose cached children are all already
        peeled)."""
        parked = set(self._lru)
        reclaimed: set[int] = set()
        changed = True
        while changed:
            changed = False
            for page in parked - reclaimed:
                kids = self._children.get(page, set())
                # children outside `parked` are live (refcounted) and pin
                # this page; parked children must peel first
                if all(k in reclaimed for k in kids):
                    reclaimed.add(page)
                    changed = True
        return len(reclaimed)

    def fragmentation(self, need: int | None = None) -> float:
        """Fraction of *idle* pages (free + parked cached) that cannot
        serve a waiting request of ``need`` pages.  0.0 when nothing is
        waiting or every idle page is usable; 1.0 when the request
        cannot be placed at all even after evicting every reclaimable
        parked page."""
        idle = len(self._free) + len(self._lru)
        if not need or idle == 0:
            return 0.0
        usable = len(self._free) + self._reclaimable()
        if need <= usable:
            unusable = idle - usable      # pinned parked pages only
        else:
            unusable = idle               # request can't be placed
        return unusable / idle

    def record_fragmentation(self, need: int | None) -> float:
        """Compute :meth:`fragmentation` for the queue head's demand and
        publish it on the ``serving_page_fragmentation_ratio`` gauge."""
        ratio = self.fragmentation(need)
        _M_FRAG.set(ratio)
        return ratio

    def seq_footprint(self, seq_id: int) -> dict:
        """Per-request page footprint: total pages in the block table,
        split into ``shared`` (refcount > 1, also held by another live
        sequence or chain) and ``exclusive``, plus the admission plan's
        ``cached_len`` tokens."""
        pages = self._tables.get(seq_id, ())
        shared = sum(1 for p in pages if self._ref.get(p, 0) > 1)
        meta = self._meta.get(seq_id, {})
        return {"pages": len(pages), "shared": shared,
                "exclusive": len(pages) - shared,
                "cached_len": int(meta.get("cached_len", 0)),
                "committed_tokens": self.committed_tokens(seq_id),
                "committed_pages": self.committed_pages(seq_id)}

    # ------------------------------------------------- refcount internals
    def _incref(self, page: int):
        self._ref[page] = self._ref.get(page, 0) + 1
        self._lru.pop(page, None)

    def _decref(self, page: int):
        n = self._ref.get(page, 0) - 1
        if n > 0:
            self._ref[page] = n
            return
        self._ref.pop(page, None)
        if page in self._key_of or page in self._tail_parent:
            self._lru[page] = None       # parked, still matchable
        else:
            self._free.append(page)

    def _acquire(self, n: int):
        """Take ``n`` pages: free list first, then LRU eviction of
        cached refcount-0 pages (leaf-first, so a chain parent is never
        recycled while children could still match through it)."""
        if (n > 0 and self.faults is not None
                and self.faults.check("page_alloc", need=n) is not None):
            return None        # synthetic device-OOM -> backpressure
        got: list[int] = []
        while len(got) < n:
            if self._free:
                got.append(self._free.popleft())
            elif self._lru and self._evict_one():
                continue
            else:
                # rollback: nothing partially held on failure (restore
                # FIFO order at the head of the deque)
                self._free.extendleft(reversed(got))
                return None
        if got:
            self.pages_allocated += len(got)
            _M_PAGES_ALLOC.inc(len(got))
        return got

    def _evict_one(self) -> bool:
        for page in self._lru:            # oldest first
            if self._children.get(page):
                continue                  # not a leaf yet
            self._lru.pop(page)
            self._unregister(page)
            self._free.append(page)
            self.prefix_evictions += 1
            _obs.flight("blocks", "page_evict", page=page)
            _M_PREFIX_EVICT.inc()
            _M_CACHED_PAGES.set(self.cached_pages)
            return True
        return False

    def _unregister(self, page: int):
        key = self._key_of.pop(page, None)
        if key is not None:
            self._index.pop(key, None)
            self._children.get(key[0], set()).discard(page)
        parent = self._tail_parent.pop(page, None)
        if parent is not None:
            self._tails.get(parent, {}).pop(page, None)
            self._children.get(parent, set()).discard(page)

    # ------------------------------------------------------------- tables
    def table_row(self, seq_id: int, width: int) -> np.ndarray:
        """The sequence's block-table row, dump-padded to ``width``
        (the engine's static table shape)."""
        pages = self._tables.get(seq_id, ())
        if len(pages) > width:
            raise ValueError(
                f"sequence {seq_id} owns {len(pages)} pages, table width "
                f"is only {width}")
        row = np.full((width,), self.dump_page, np.int32)
        row[:len(pages)] = pages
        return row

    def empty_row(self, width: int) -> np.ndarray:
        """An all-dump row (idle slots write/read only the dump page)."""
        return np.full((width,), self.dump_page, np.int32)
