"""Multi-LoRA adapter serving + the offline batch lane.

| Module  | Role |
|---------|------|
| store   | AdapterStore: host parking, loud validation, LRU device residency over the runner's packed bank |
| batch   | BatchJob: lowest-priority JSONL drip-feed for `/v1/batches` |

The device half lives elsewhere: the packed ``[rows, r, dim]`` bank and
per-slot adapter-index vector ride the runner's decode state
(``serving.parallel.runner``), and the batched gather-LoRA matmul is
``ops.pallas.lora_matmul``.
"""
from .batch import BATCH_PRIORITY, BatchJob
from .store import (AdapterStore, LORA_KEYS, lora_key_dims,
                    merge_adapter, random_adapter)

__all__ = ["AdapterStore", "BatchJob", "BATCH_PRIORITY", "LORA_KEYS",
           "lora_key_dims", "merge_adapter", "random_adapter"]
