"""Offline batch lane: drip-feed a JSONL job at the lowest priority.

Reference analog: the offline scoring lanes of the reference's
recommendation stack — bulk work shares the serving fleet but must
never displace interactive traffic.  The serving-era mechanism is
already built: the priority scheduler admits the highest class first
and preempts-and-swaps lower-class residents (PR 14), so a batch job
is just a feeder that (a) submits at a class BELOW every interactive
name and (b) keeps only a small window in flight, letting interactive
arrivals win every admission race and evict batch slots on demand.

A :class:`BatchJob` owns one input file's lifecycle: records validate
up front, ``pump()`` (called from the engine loop between steps) reaps
finished requests into the output JSONL and tops the in-flight window
back up, ``progress()`` is the JSON the ``/v1/batches/<id>`` endpoint
serves.  No threads: the job advances exactly when the engine does.
"""
from __future__ import annotations

import itertools
import json
import os
import time

from ..request import GenerationConfig
from ...sanitizer import make_lock

__all__ = ["BatchJob", "BATCH_PRIORITY"]

# below every interactive class (server names low/normal/high ->
# -1/0/1): interactive arrivals admit first and preempt batch residents
BATCH_PRIORITY = -2

_job_ids = itertools.count()
_job_ids_lock = make_lock("lora.batch._job_ids")


def _validate_records(records) -> list[dict]:
    out = []
    for i, rec in enumerate(records):
        if not isinstance(rec, dict):
            raise ValueError(f"batch record {i}: expected an object, "
                             f"got {type(rec).__name__}")
        prompt = rec.get("prompt")
        if not isinstance(prompt, (list, tuple)) or not prompt \
                or not all(isinstance(t, int) for t in prompt):
            raise ValueError(
                f"batch record {i}: 'prompt' must be a non-empty list "
                "of token ids")
        mnt = rec.get("max_tokens", None)
        if mnt is not None and (not isinstance(mnt, int) or mnt < 1):
            raise ValueError(
                f"batch record {i}: 'max_tokens' must be a positive "
                f"int, got {mnt!r}")
        out.append(rec)
    if not out:
        raise ValueError("batch job has no records")
    return out


class BatchJob:
    """One offline job: validated records in, JSONL results out.

    ``pump(submit)`` is the whole engine contract — ``submit`` has the
    ``engine.submit`` shape (``submit(prompt, gen, priority=, tenant=,
    adapter=)``) and the job never holds more than ``window`` requests
    in flight, so a saturating job occupies at most ``window`` decode
    slots for interactive traffic to preempt."""

    def __init__(self, records, *, window: int = 2,
                 max_tokens: int = 16, output_path: str | None = None,
                 tenant: str | None = None, adapter: str | None = None,
                 job_id: str | None = None):
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self.records = _validate_records(records)
        self.window = int(window)
        self.max_tokens = int(max_tokens)
        self.output_path = output_path
        self.tenant = tenant
        self.adapter = adapter
        with _job_ids_lock:
            self.id = job_id or f"batch-{next(_job_ids)}"
        self.created_at = time.monotonic()
        self.finished_at: float | None = None
        self._next = 0                    # next record index to submit
        self._inflight: dict[int, object] = {}     # index -> Request
        self.completed = 0
        self.failed = 0
        self.preemptions = 0              # summed over reaped requests
        self.output_tokens = 0
        self.error: str | None = None
        self._out = None

    @classmethod
    def from_jsonl(cls, path: str, **kw):
        """Load records from a JSONL file of ``{"prompt": [ids], ...}``
        objects; the default output lands beside it as
        ``<path>.out.jsonl`` unless ``output_path`` is given."""
        records = []
        with open(path) as f:
            for ln, line in enumerate(f):
                line = line.strip()
                if not line:
                    continue
                try:
                    records.append(json.loads(line))
                except ValueError as e:
                    raise ValueError(
                        f"{path}:{ln + 1}: invalid JSON: {e}") from None
        kw.setdefault("output_path", path + ".out.jsonl")
        return cls(records, **kw)

    # ------------------------------------------------------------- pumping
    @property
    def done(self) -> bool:
        return self._next >= len(self.records) and not self._inflight

    def pump(self, submit) -> bool:
        """Reap finished in-flight requests, then top the window back
        up.  Returns True while the job still has work (so engine
        loops can ``while job.pump(...) or engine.step(): ...``)."""
        for idx in list(self._inflight):
            req = self._inflight[idx]
            if not req.is_finished():
                continue
            del self._inflight[idx]
            self.preemptions += req.preemptions
            self.output_tokens += req.num_generated
            if req.finish_reason == "error":
                self.failed += 1
            else:
                self.completed += 1
            self._write_result(idx, req)
        while (self._next < len(self.records)
               and len(self._inflight) < self.window):
            idx = self._next
            self._next += 1
            rec = self.records[idx]
            gen = GenerationConfig(
                max_new_tokens=rec.get("max_tokens", self.max_tokens))
            try:
                req = submit(rec["prompt"], gen,
                             priority=BATCH_PRIORITY,
                             tenant=rec.get("tenant", self.tenant),
                             adapter=rec.get("adapter", self.adapter))
            except Exception as e:            # bad record (e.g. unknown
                self.failed += 1              # adapter): fail the row,
                self.error = str(e)           # keep the job moving
                self._write_result(idx, None, error=str(e))
                continue
            self._inflight[idx] = req
        if self.done and self.finished_at is None:
            self.finished_at = time.monotonic()
            if self._out is not None:
                self._out.close()
                self._out = None
        return not self.done

    def _write_result(self, idx: int, req, *, error: str | None = None):
        rec = self.records[idx]
        row = {"index": idx, "prompt": list(rec["prompt"])}
        if rec.get("id") is not None:
            row["id"] = rec["id"]
        if req is not None:
            row["tokens"] = list(req.output_tokens)
            row["finish_reason"] = req.finish_reason
            if req.adapter:
                row["adapter"] = req.adapter
            if req.error:
                row["error"] = req.error
        else:
            row["finish_reason"] = "error"
            row["error"] = error
        if self.output_path is None:
            return
        if self._out is None:
            self._out = open(self.output_path, "a")
        self._out.write(json.dumps(row) + "\n")
        self._out.flush()
        os.fsync(self._out.fileno())

    # ------------------------------------------------------------ progress
    def progress(self) -> dict:
        """The ``GET /v1/batches/<id>`` payload."""
        total = len(self.records)
        return {
            "id": self.id,
            "status": "completed" if self.done else "running",
            "total": total,
            "submitted": self._next,
            "inflight": len(self._inflight),
            "completed": self.completed,
            "failed": self.failed,
            "preemptions": self.preemptions,
            "output_tokens": self.output_tokens,
            "output_path": self.output_path,
            "adapter": self.adapter,
            "error": self.error,
        }
