"""Multi-LoRA adapter store: host parking + LRU device residency.

Reference analog: the per-tenant parameter-server tables of the
reference's recommendation stack — one base model, thousands of small
per-tenant deltas, only the hot set resident on the accelerator.  The
serving-era mirrors are Punica / vLLM multi-LoRA: rank-r adapter pairs
``(A [r, in], B [r, out])`` per projection, applied as
``h W + (alpha/r) * (h A^T) B``.

The :class:`AdapterStore` owns the host half:

  * ``register`` validates an adapter LOUDLY (all seven projection
    keys, per-key shapes against the model config, uniform rank,
    floating dtype — the ``_validate_quantized_state`` posture: a
    malformed adapter fails at registration, not as an opaque shape
    error inside the first traced step) and parks a float32 copy on
    host.
  * ``acquire``/``release`` manage the LRU-bounded device residency:
    the runner's packed bank has ``capacity`` usable rows (row 0 is the
    zeroed no-adapter row); an acquire on a parked adapter loads it
    into a free row, evicting the least-recently-used *idle* resident
    when full.  Rows with live requests are pinned — refcounts are
    taken at submit and dropped at finalize, surviving preemption, so
    an in-flight request's adapter can never be evicted under it.
  * ``attach`` binds a runner and (re)loads every resident adapter —
    the engine-recovery path rebuilds the device bank from host truth.

Bank rows hold float32 regardless of the base dtype: the delta matmuls
accumulate in f32 anyway (``ops.pallas.lora_matmul``), and the bank is
tiny next to the weights (2 * L * r * (in + out) floats per adapter).
"""
from __future__ import annotations

from collections import OrderedDict

import numpy as np

from ... import observability as _obs
from ...sanitizer import make_lock

__all__ = ["AdapterStore", "LORA_KEYS", "lora_key_dims",
           "random_adapter", "merge_adapter"]

# the seven projection outputs an adapter touches, named like
# models.generation._layer_weights
LORA_KEYS = ("q", "k", "v", "o", "gate", "up", "down")

# short key -> generation-state weight path (for merged-weight refs)
_STATE_PATHS = {
    "q": "self_attn.q_proj.weight", "k": "self_attn.k_proj.weight",
    "v": "self_attn.v_proj.weight", "o": "self_attn.o_proj.weight",
    "gate": "mlp.gate_proj.weight", "up": "mlp.up_proj.weight",
    "down": "mlp.down_proj.weight",
}

_M_LOADS = _obs.counter(
    "serving_lora_loads_total",
    "adapter loads into the device bank (cold acquires)")
_M_EVICTIONS = _obs.counter(
    "serving_lora_evictions_total",
    "idle adapters evicted from the device bank to make room")
_M_RESIDENT = _obs.gauge(
    "serving_lora_resident",
    "adapters currently resident in the device bank")


def lora_key_dims(config) -> dict:
    """``{key: (in_dim, out_dim)}`` of each adapted projection — the
    single source of truth the store validates against and the runner
    sizes its bank from."""
    h = config.hidden_size
    hd = config.head_dim
    qd = config.num_attention_heads * hd
    kvd = config.num_key_value_heads * hd
    inter = config.intermediate_size
    return {"q": (h, qd), "k": (h, kvd), "v": (h, kvd), "o": (qd, h),
            "gate": (h, inter), "up": (h, inter), "down": (inter, h)}


def _validate_adapter(config, name, weights) -> int:
    """Loud shape/dtype/rank validation; returns the adapter's rank."""
    if not isinstance(weights, dict):
        raise ValueError(
            f"adapter {name!r}: weights must be a dict "
            f"{{key: (A, B)}}, got {type(weights).__name__}")
    missing = [k for k in LORA_KEYS if k not in weights]
    extra = [k for k in weights if k not in LORA_KEYS]
    if missing or extra:
        raise ValueError(
            f"adapter {name!r}: expected exactly keys {LORA_KEYS}, "
            f"missing {missing}, unexpected {extra}")
    L = config.num_hidden_layers
    dims = lora_key_dims(config)
    rank = None
    for key in LORA_KEYS:
        pair = weights[key]
        if not (isinstance(pair, (tuple, list)) and len(pair) == 2):
            raise ValueError(
                f"adapter {name!r}[{key!r}]: expected an (A, B) pair, "
                f"got {type(pair).__name__}")
        a, b = (np.asarray(pair[0]), np.asarray(pair[1]))
        if not (np.issubdtype(a.dtype, np.floating)
                and np.issubdtype(b.dtype, np.floating)):
            raise ValueError(
                f"adapter {name!r}[{key!r}]: A/B must be floating, "
                f"got {a.dtype}/{b.dtype}")
        if a.ndim != 3 or b.ndim != 3:
            raise ValueError(
                f"adapter {name!r}[{key!r}]: A/B must be "
                f"[layers, r, dim], got {a.shape}/{b.shape}")
        r = a.shape[1]
        if rank is None:
            rank = r
        ind, outd = dims[key]
        if a.shape != (L, rank, ind):
            raise ValueError(
                f"adapter {name!r}[{key!r}]: A shape {a.shape} != "
                f"expected {(L, rank, ind)} (layers, r, in_dim)")
        if b.shape != (L, rank, outd):
            raise ValueError(
                f"adapter {name!r}[{key!r}]: B shape {b.shape} != "
                f"expected {(L, rank, outd)} (layers, r, out_dim)")
    return rank


class AdapterStore:
    """Host registry + LRU device residency for LoRA adapters.

    ``capacity`` is the number of usable bank rows the runner
    allocates (+1 internally for the zeroed no-adapter row 0).
    ``rank`` may be given up front or inferred from the first
    registration; every adapter must match it exactly (the packed
    bank has one static rank axis)."""

    def __init__(self, config, *, capacity: int = 4,
                 rank: int | None = None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if rank is not None and rank < 1:
            raise ValueError(f"rank must be >= 1, got {rank}")
        self.config = config
        self.capacity = int(capacity)
        self.rank = None if rank is None else int(rank)
        self._lock = make_lock("lora.AdapterStore")
        self._host: dict[str, dict] = {}      # name -> parked weights
        self._alpha: dict[str, float] = {}
        self._resident: OrderedDict[str, int] = OrderedDict()  # -> row
        self._refs: dict[str, int] = {}       # live-request pins
        self._requests: dict[str, int] = {}   # per-adapter acquire census
        self._runner = None
        self.loads = 0
        self.evictions = 0

    # ------------------------------------------------------------ registry
    def register(self, name: str, weights: dict, *, alpha: float = 1.0):
        """Validate and park an adapter on host.  ``weights`` is
        ``{key: (A [L, r, in], B [L, r, out])}`` over :data:`LORA_KEYS`;
        the applied delta is ``(alpha / r) * (h A^T) B``."""
        name = str(name).strip()
        if not name:
            raise ValueError("adapter name must be non-empty")
        if float(alpha) <= 0.0:
            raise ValueError(f"adapter {name!r}: alpha must be > 0, "
                             f"got {alpha}")
        r = _validate_adapter(self.config, name, weights)
        with self._lock:
            if self.rank is None:
                self.rank = r
            elif r != self.rank:
                raise ValueError(
                    f"adapter {name!r}: rank {r} != store rank "
                    f"{self.rank} (the packed bank has one static "
                    "rank axis — pad or re-train)")
            if name in self._resident:
                raise ValueError(
                    f"adapter {name!r} is device-resident; release it "
                    "before re-registering")
            self._host[name] = {
                key: (np.asarray(a, np.float32).copy(),
                      np.asarray(b, np.float32).copy())
                for key, (a, b) in weights.items()}
            self._alpha[name] = float(alpha)
            self._requests.setdefault(name, 0)

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._host)

    def resident(self) -> list[str]:
        """Resident adapter names in LRU order (oldest first)."""
        with self._lock:
            return list(self._resident)

    def parked(self) -> list[str]:
        with self._lock:
            return sorted(set(self._host) - set(self._resident))

    def row_of(self, name: str) -> int | None:
        with self._lock:
            return self._resident.get(name)

    # ----------------------------------------------------------- residency
    def attach(self, runner):
        """Bind the device runner and (re)load every resident adapter
        into its bank — host parking is the source of truth, so engine
        recovery rebuilds the bank by re-attaching."""
        if getattr(runner, "lora_slots", 0) != self.capacity:
            raise ValueError(
                f"runner bank has {getattr(runner, 'lora_slots', 0)} "
                f"rows, store capacity is {self.capacity}")
        if self.rank is not None and runner.lora_rank != self.rank:
            raise ValueError(
                f"runner bank rank {runner.lora_rank} != store rank "
                f"{self.rank}")
        with self._lock:
            self._runner = runner
            for name, row in self._resident.items():
                self._load(name, row)

    def _load(self, name: str, row: int):
        if self._runner is not None:
            host = self._host[name]
            self._runner.load_adapter(
                row, {k: ab[0] for k, ab in host.items()},
                {k: ab[1] for k, ab in host.items()},
                self._alpha[name] / self.rank)
        self.loads += 1
        _M_LOADS.inc()

    def acquire(self, name: str | None) -> int:
        """Pin ``name`` for one request and return its bank row
        (0 for ``None`` — the zeroed no-adapter row).  Loads parked
        adapters on demand, evicting the LRU *idle* resident when the
        bank is full; raises when every row is pinned by live
        requests."""
        if name is None:
            return 0
        with self._lock:
            if name not in self._host:
                raise KeyError(
                    f"unknown adapter {name!r}; registered: "
                    f"{sorted(self._host)}")
            self._requests[name] = self._requests.get(name, 0) + 1
            if name in self._resident:
                self._resident.move_to_end(name)
                self._refs[name] = self._refs.get(name, 0) + 1
                return self._resident[name]
            row = self._free_row()
            self._resident[name] = row
            self._refs[name] = 1
            self._load(name, row)
            _M_RESIDENT.set(len(self._resident))
            return row

    def _free_row(self) -> int:
        used = set(self._resident.values())
        for row in range(1, self.capacity + 1):
            if row not in used:
                return row
        for victim in list(self._resident):       # LRU order
            if self._refs.get(victim, 0) == 0:
                row = self._resident.pop(victim)
                self._refs.pop(victim, None)
                self.evictions += 1
                _M_EVICTIONS.inc()
                return row
        raise RuntimeError(
            f"all {self.capacity} adapter bank rows are pinned by live "
            "requests — raise the store capacity or drain first")

    def release(self, name: str | None):
        """Drop one request's pin (keeps the adapter resident — it
        becomes evictable once idle)."""
        if name is None:
            return
        with self._lock:
            if self._refs.get(name, 0) <= 0:
                raise RuntimeError(
                    f"release of adapter {name!r} without a matching "
                    "acquire")
            self._refs[name] -= 1

    # ---------------------------------------------------------------- info
    def bank_bytes(self) -> int:
        """Device bytes of the packed bank (all rows, f32)."""
        if self.rank is None:
            return 0
        per_row = sum(ind + outd
                      for ind, outd in lora_key_dims(self.config)
                      .values())
        rows = self.capacity + 1
        layers = self.config.num_hidden_layers
        return layers * rows * self.rank * per_row * 4 + rows * 4

    def snapshot(self) -> dict:
        """JSON-able census for ``/debug/resources``, the fleet
        summary, and the ``lora.json`` observability side-file."""
        with self._lock:
            return {
                "capacity": self.capacity,
                "rank": self.rank,
                "registered": sorted(self._host),
                "resident": list(self._resident),
                "parked": sorted(set(self._host) - set(self._resident)),
                "pinned": {n: c for n, c in self._refs.items() if c > 0},
                "bank_bytes": self.bank_bytes(),
                "loads": self.loads,
                "evictions": self.evictions,
                "requests": dict(self._requests),
            }


# ---------------------------------------------------------------- helpers
def random_adapter(config, rank: int, *, seed: int = 0,
                   scale: float = 0.5) -> dict:
    """Deterministic random adapter weights for tests and benches —
    both A and B non-zero (real LoRA zero-inits B; a zero delta would
    make every parity check vacuous)."""
    rng = np.random.default_rng(seed)
    L = config.num_hidden_layers
    out = {}
    for key, (ind, outd) in lora_key_dims(config).items():
        out[key] = (
            rng.normal(0.0, scale / np.sqrt(ind),
                       (L, rank, ind)).astype(np.float32),
            rng.normal(0.0, scale / np.sqrt(rank),
                       (L, rank, outd)).astype(np.float32))
    return out


def merge_adapter(state: dict, config, weights: dict,
                  *, alpha: float = 1.0) -> dict:
    """Dense merged-weights reference: ``W + (alpha/r) A^T B`` folded
    into a copy of a float generation-state dict — the ground truth the
    bank-applied path must match token-for-token under greedy."""
    rank = _validate_adapter(config, "<merge>", weights)
    s = float(alpha) / rank
    out = dict(state)
    for key, (a, b) in weights.items():
        for i in range(config.num_hidden_layers):
            name = f"llama.layers.{i}.{_STATE_PATHS[key]}"
            w = np.asarray(out[name])
            delta = s * (np.asarray(a[i], np.float32).T
                         @ np.asarray(b[i], np.float32))
            out[name] = (w.astype(np.float32) + delta).astype(w.dtype)
    return out
