"""Continuous-batching inference engine over the paged KV pool.

The design that turns the paged kernels into a serving system (Orca's
iteration-level scheduling over vLLM-style PagedAttention, mapped onto
the reference block_multi_head_attention serving path):

  * ONE jitted single-token decode step over a fixed number of decode
    slots and one shared page pool.  Slot occupancy, positions, and
    block tables are *data* (int32 arrays), never shapes — admitting or
    evicting a request between steps re-traces nothing.  The step
    reuses ``_decode_layer_paged`` from ``models/generation.py``
    verbatim, so engine numerics match the one-shot
    ``build_generate_fn_paged`` token for token under greedy decoding.
  * prefill-on-admit: an admitted request's prompt runs through
    ``_prefill_layer`` (padded to a page-multiple bucket; one trace per
    bucket) and pages its KV straight into the shared pool; the token
    sampled from the prompt's last logits is the request's first output
    (its TTFT mark).  With ``enable_prefix_cache=True`` the admission
    only reserves pages for (and prefills) the prompt's UNCACHED
    suffix: shared prefix pages come straight from the
    :class:`BlockManager` chain index, a matching partial tail page is
    copied (copy-on-write) on device, and the suffix runs through a
    cached-prefill jit that attends over the resident prefix KV.
  * device-resident decode state: ``table``/``pos``/``tok``, the active
    mask, and a ``[sync_interval, slots]`` sampled-token ring live on
    device and are donated through the step — a steady-state decode
    iteration uploads nothing and downloads nothing.  The host fetches
    the ring once every ``sync_interval`` steps (greedy path) and the
    ``[slots, V]`` logits only when an active request actually samples;
    admissions and evictions patch single slot rows in place.
  * idle slots park on the dump page (table row all-dump, pos 0): their
    lockstep writes land in scratch, their outputs are discarded
    host-side — no masking inside the program.

The device half of all of this — weight placement, the KV pools, the
decode state, and the jitted programs themselves — lives in a
:class:`~paddle_tpu.serving.parallel.ModelRunner` (the engine never
owns a jit directly).  The runner optionally spans a tensor-parallel
mesh (``mesh=`` / ``FLAGS_serving_mesh_tp``): heads and the FFN hidden
dim shard across the ``tp`` axis, the pool shards along the head axis,
and the engine's host-side page table and scheduling stay mesh-
agnostic.  ``tp=1`` is exactly the single-chip programs.

Sampling is host-side per request (greedy = argmax of the step's f32
logits, matching ``_sample``'s greedy branch exactly; stochastic
requests draw from a per-request numpy RNG so results do not depend on
batch composition).  Set ``emit_logits=True`` at engine construction to
serve ``do_sample`` requests — any active sampling request forces a
per-step sync (the host must feed the sampled token back before the
next step), so ``sync_interval`` only pays off on greedy traffic.
"""
from __future__ import annotations

import time

import jax
import numpy as np

from .. import observability as _obs
from ..flags import FLAGS
from ..observability.resources import resource_tracker
from ..models.generation import GenerationConfig
from ..models.llama import LlamaConfig
from .block_manager import BlockManager
from .faults import InjectedFault, fault_plan_from_flags
from .parallel import ModelRunner, parse_mesh
from .request import Request, RequestState
from .scheduler import Scheduler

__all__ = ["Engine", "NonFiniteLogitsError", "create_engine"]

_M_STEPS = _obs.counter(
    "serving_decode_steps_total", "engine decode iterations")
_M_TOKENS = _obs.counter(
    "serving_tokens_total", "tokens emitted to requests")
_M_REQUESTS = _obs.counter(
    "serving_requests_total", "finished requests", ("outcome",))
_M_FINISH = _obs.counter(
    "serving_finish_total",
    "finished requests by finish_reason "
    "(length|eos|cancelled|deadline|error)", ("reason",))
_M_RECOVERY = _obs.counter(
    "serving_recovery_total",
    "self-healing events: 'quarantine' = one request failed in place "
    "(finish_reason='error', batch kept running), 'rebuild' = runner "
    "rebuilt + in-flight requests replayed, 'stall' = rebuild declared "
    "by the watchdog, 'drain' = restart budget exhausted, escalated",
    ("kind",))
_M_HOST_SYNCS = _obs.counter(
    "serving_host_syncs_total",
    "device->host transfers on the serving hot path: 'ring' = sampled-"
    "token ring fetch (one per sync_interval decode steps on the greedy "
    "path), 'logits' = [slots, V] logits fetch (only when an active "
    "request samples), 'prefill' = first-token logits at admission",
    ("kind",))
_M_PHASE_SECONDS = _obs.counter(
    "serving_step_phase_seconds_total",
    "engine wall seconds by phase: 'prefill' jit calls (incl. CoW "
    "copies), 'decode' step dispatch, 'host_sync' blocking ring "
    "fetches — the resource tracker's tokens/s and MFU denominator",
    ("phase",))
_M_CHUNKS = _obs.counter(
    "serving_prefill_chunks_total",
    "chunked-prefill jit calls: admission prefill split into "
    "FLAGS_serving_prefill_chunk-token pieces interleaved with decode "
    "steps (chunk K attends chunks 1..K-1 via the cached-prefill jit)")


class NonFiniteLogitsError(ValueError):
    """A request's logits hold no usable probability mass (NaN/Inf from
    the model, or top_k/top_p masked every candidate).  A per-request
    failure: the engine quarantines the offending request
    (finish_reason='error') and keeps the rest of the batch running."""


def _serving_hists():
    buckets = _obs.registry.SERVING_LATENCY_BUCKETS
    ttft = _obs.histogram(
        "serving_ttft_seconds", "request arrival -> first token",
        buckets=buckets)
    tpot = _obs.histogram(
        "serving_tpot_seconds", "inter-token latency during decode",
        buckets=buckets)
    e2e = _obs.histogram(
        "serving_e2e_seconds", "request arrival -> completion",
        buckets=buckets)
    return ttft, tpot, e2e


class Engine:
    """Drives admission, prefill, and the shared decode step.

    Static shapes (fixed at construction — the no-retrace contract):
    ``max_slots`` decode slots, ``table_width`` pages per sequence,
    ``num_pages (+ dump)`` pool rows, ``sync_interval`` ring rows, and
    the per-bucket prefill widths.  Everything per-request is data.
    """

    def __init__(self, model=None, *, config: LlamaConfig = None,
                 state: dict | None = None, max_slots: int = 4,
                 page_size: int = 64, num_pages: int | None = None,
                 max_model_len: int | None = None,
                 emit_logits: bool = False,
                 enable_prefix_cache: bool = False,
                 sync_interval: int = 1, clock=time.monotonic,
                 slo=None, mesh=None, spec_k: int | None = None,
                 prefill_chunk: int | None = None,
                 preempt: bool | None = None, faults=None, usage=None,
                 quant: str | None = None,
                 kv_quant: bool | None = None, lora=None,
                 requestlog=None):
        if model is not None:
            from ..framework.tensor import Tensor
            config = model.config
            state = {k: (v._data if isinstance(v, Tensor) else v)
                     for k, v in model.functional_state().items()}
        if config is None or state is None:
            raise ValueError("pass a model, or both config= and state=")
        # quantized serving: convert the dense checkpoint at
        # construction (embeddings/norms/lm_head stay dense, so the
        # dtype read below still sees the checkpoint dtype).  quant off
        # (the default) leaves the state untouched — zero behavior
        # change, same guard style as faults/sanitizer.
        if quant is None:
            quant = str(FLAGS.get("FLAGS_serving_quant") or "")
        if kv_quant is None:
            kv_quant = bool(FLAGS.get("FLAGS_serving_kv_quant"))
        if quant not in ("", "int8", "int4"):
            raise ValueError(
                f"quant must be '', 'int8', or 'int4', got {quant!r}")
        self.quant = quant
        self.kv_quant = bool(kv_quant)
        if self.quant:
            from .quantize import quantize_state
            state = quantize_state(state, kind=self.quant)
        # multi-LoRA serving: an AdapterStore sizes the runner's packed
        # adapter bank (rows x rank fixed at construction — the
        # no-retrace contract extends to the bank shape).  lora=None
        # (the default) passes empty tuples through every jitted
        # program: the dense jaxprs are byte-identical to a build
        # without the knob, same guard style as quant/kv_quant.
        self.lora = lora
        if lora is not None and lora.rank is None:
            raise ValueError(
                "the AdapterStore has no adapters and no explicit "
                "rank= — the runner cannot size the bank (register "
                "one adapter first, or pass AdapterStore(rank=...))")
        self.config = config
        self.state = state
        self.max_slots = int(max_slots)
        self.page_size = int(page_size)
        self.max_model_len = int(max_model_len
                                 or config.max_position_embeddings)
        if self.max_model_len > config.max_position_embeddings:
            raise ValueError(
                f"max_model_len {self.max_model_len} exceeds the model's "
                f"max_position_embeddings {config.max_position_embeddings}")
        self.table_width = -(-self.max_model_len // self.page_size)
        if num_pages is None:       # full residency: every slot can run
            num_pages = self.max_slots * self.table_width  # at max length
        self.emit_logits = bool(emit_logits)
        self.enable_prefix_cache = bool(enable_prefix_cache)
        self.sync_interval = int(sync_interval)
        if self.sync_interval < 1:
            raise ValueError(
                f"sync_interval must be >= 1, got {sync_interval}")
        self._clock = clock
        if mesh is None:
            mesh = int(FLAGS.get("FLAGS_serving_mesh_tp") or 1)
        self.tp = parse_mesh(mesh)
        if spec_k is None:
            spec_k = int(FLAGS.get("FLAGS_serving_spec_k") or 0)
        self.spec_k = int(spec_k)
        if self.spec_k < 0:
            raise ValueError(f"spec_k must be >= 0, got {spec_k}")
        if self.spec_k:
            from .spec import NgramProposer, SpecStats
            self._proposer = NgramProposer(self.spec_k)
            self._spec = SpecStats()
        else:
            self._proposer = None
            self._spec = None
        if prefill_chunk is None:
            prefill_chunk = int(
                FLAGS.get("FLAGS_serving_prefill_chunk") or 0)
        self.prefill_chunk = max(int(prefill_chunk), 0)
        if preempt is None:
            preempt = bool(FLAGS.get("FLAGS_serving_preempt"))
        self.preempt = bool(preempt)
        # chaos harness: None (the default when FLAGS_serving_fault_plan
        # is empty) keeps every injection site to a single None test
        self.faults = fault_plan_from_flags() if faults is None else faults

        self.blocks = BlockManager(
            num_pages, self.page_size,
            enable_prefix_cache=self.enable_prefix_cache,
            faults=self.faults)
        # chunked admissions must not be cache-matchable until their KV
        # has actually been written: the scheduler admits every queue
        # head before the engine runs any prefill, so eager registration
        # would let a same-pass admission attend over unwritten pages.
        # allocate_seq defers registration past this many fresh tokens
        # and the engine publishes after the last chunk lands.
        self.blocks.defer_publish = self.prefill_chunk
        self.scheduler = Scheduler(self.blocks, self.max_slots,
                                   clock=self._clock,
                                   preempt_enabled=self.preempt)
        self.scheduler._finalize = self._finalize
        # preempt-and-swap: the scheduler picks the victim, the engine
        # owns the device side (spill exclusive KV pages to the host
        # tier, release the pages, park the slot)
        self.scheduler._preempt = self._preempt
        # every eviction parks its slot — not just the length/eos path in
        # _emit.  A cancel/deadline eviction inside scheduler.schedule()
        # would otherwise leave the slot's table/pos pointing at freed
        # pages, and the lockstep decode step (which writes KV for every
        # slot) would corrupt them once reallocated to a new request.
        self.scheduler._on_evict = self._park
        # per-request cost attribution (observability.usage): every
        # call site below is a single ``is not None`` test, so the
        # default (no meter) adds zero work to the serving path
        self.usage = usage
        if usage is not None:
            if usage._clock is None:
                usage._clock = self._clock   # page-seconds on engine clock
            self.blocks.usage = usage        # page hold/release + host tier
            self.scheduler.usage = usage     # fair-share victim selection
            if slo is not None:
                slo.verdict_hook = usage.slo_verdict
            # the process-active meter: obs.dump() writes usage.json
            # from it (last engine built wins, like the profiler)
            _obs.set_active_usage(usage)
        # tail-latency forensics (observability.requestlog): per-
        # request lifecycle timelines + critical-path attribution +
        # SLO-violation exemplars.  Same zero-overhead-off contract:
        # every seam below is a single ``is not None`` test when no
        # log is attached (pinned by the tail_forensics gate scenario)
        self.requestlog = requestlog
        if requestlog is not None:
            if slo is not None:
                # violation exemplars ride the tracker's verdicts; the
                # usage meter's verdict_hook is untouched — the two
                # subsystems compose through separate hooks
                slo.exemplar_hook = requestlog.slo_verdict
            # the process-active log: obs.dump() writes exemplars.json
            _obs.set_active_requestlog(requestlog)

        L = config.num_hidden_layers
        kvh, hd = config.num_key_value_heads, config.head_dim
        dtype = state["llama.embed_tokens.weight"].dtype
        self._embed_itemsize = int(np.dtype(dtype).itemsize)
        # head-sharded pool sizing: the BlockManager knows how many
        # bytes each mesh position holds, the runner reports it
        sizing = self.blocks.pool_bytes(
            num_layers=L, num_kv_heads=kvh, head_dim=hd,
            dtype_itemsize=int(np.dtype(dtype).itemsize), tp=self.tp,
            kv_quant=self.kv_quant)
        # the device half: mesh, weight placement, pools, decode state,
        # and every jitted program live behind the runner seam.  The
        # kwargs are kept so recover() can rebuild an identical runner
        # after a poisoned step (fresh pools, same static shapes).
        self._runner_kw = dict(
            tp=self.tp, max_slots=self.max_slots,
            page_size=self.page_size, table_width=self.table_width,
            num_pages=self.blocks.num_pages,
            dump_page=self.blocks.dump_page,
            sync_interval=self.sync_interval,
            emit_logits=self.emit_logits, spec_k=self.spec_k,
            kv_quant=self.kv_quant,
            lora_slots=(self.lora.capacity if self.lora is not None
                        else 0),
            lora_rank=(self.lora.rank if self.lora is not None else 0),
            per_device_pool_bytes=sizing["per_device_bytes"])
        self.runner = ModelRunner(config, state, **self._runner_kw)
        if self.lora is not None:
            # bind the store to the bank: resident adapters (if any)
            # upload now; later acquires patch single rows in place
            self.lora.attach(self.runner)

        # host-side mirrors of the slot state (bookkeeping + targeted
        # device patches on admit/evict; NEVER re-uploaded per step)
        self.table = np.tile(self.blocks.empty_row(self.table_width),
                             (self.max_slots, 1))
        self._pos = np.zeros((self.max_slots,), np.int32)
        self._tok = np.zeros((self.max_slots,), np.int32)
        self._active = np.zeros((self.max_slots,), np.int32)
        # per-slot adapter bank row (0 = the permanently-zero no-adapter
        # row); patched on admit/evict alongside the other mirrors
        self._aidx = np.zeros((self.max_slots,), np.int32)
        self._ring_cursor = 0           # host mirror of the ring index
        # ring rows the host has not consumed yet, in decode order:
        # [(ring row, [(slot, request), ...], drafts-or-None), ...] —
        # the third element is the verify step's {slot: draft tokens}
        # (a verify row syncs immediately, so it is always solitary)
        self._pending: list[tuple[int, list, dict | None]] = []
        self._last_logits = None        # device handle, fetched lazily

        self.decode_steps = 0       # mirror of serving_decode_steps_total
        self.host_syncs = 0         # ring fetches (1 per sync_interval)
        self.logit_fetches = 0      # [slots, V] transfers (sampling only)
        # chunked prefill: in-flight admission prefills advanced one
        # chunk per engine step — {slot: state dict} (see _begin_chunks)
        self._chunking: dict[int, dict] = {}
        self.prefill_chunks = 0     # mirror of serving_prefill_chunks_total
        self.preemptions = 0        # successful preempt-and-swap spills
        self.spill_aborts = 0       # preemptions aborted by a failed spill
        # overload-degradation witness: the most prompt tokens prefilled
        # between two decode steps — bounded by prefill_chunk when
        # chunking is on, by the longest prompt when it is off
        self._prefill_since_decode = 0
        self.max_prefill_gap = 0
        # self-healing mirrors of serving_recovery_total
        self.recoveries = 0         # runner rebuilds (recover() calls)
        self.quarantines = 0        # requests failed in place
        self.replayed_requests = 0  # in-flight requests re-prefilled
        # per-phase wall seconds (mirror of serving_step_phase_seconds_
        # total; resource_snapshot() reports them per engine)
        self.timings = {"prefill_s": 0.0, "decode_s": 0.0,
                        "host_sync_s": 0.0}
        # monotonically increasing iteration counter.  The serving
        # watchdog reads it lock-free (comparing against active_count)
        # to detect a wedged decode loop — never reset.
        self.progress = 0
        # what the engine is doing RIGHT NOW, published for the
        # sampling profiler (observability/profiling.py): prefill /
        # prefill_chunk / decode / verify / host_sync / idle.  A plain
        # attribute store at each section entry — read lock-free from
        # the sampler thread, same contract as the watchdog's
        # ``progress`` reads; costs nothing when no profiler runs.
        self.current_phase = "idle"
        self.slo = slo              # optional slo.SLOTracker
        # open "engine.decode_segment" span covering the device steps
        # since the last host sync (None between segments)
        self._seg_span = None
        self._seg_steps = 0
        self._rngs: dict[int, np.random.Generator] = {}
        self._ttft, self._tpot, self._e2e = _serving_hists()
        self._pages_hist = _obs.histogram(
            "serving_pages_in_use_hist",
            "pages-in-use sampled at each decode step",
            buckets=_pages_buckets(self.blocks.num_pages))

        # resource tracker: model size + device kind feed the MFU
        # estimate (tokens/s * 2 * n_params / peak_flops)
        n_params = sum(int(np.prod(v.shape))
                       for v in state.values() if hasattr(v, "shape"))
        try:
            device_kind = jax.devices()[0].device_kind
        except Exception:
            device_kind = None
        resource_tracker().set_model(n_params=n_params,
                                     device_kind=device_kind)

        # quantized-serving metric surface: registered only when quant
        # is on, so a dense engine exports exactly the pre-quant set
        if self.quant or self.kv_quant:
            _obs.gauge(
                "serving_quant_weight_bits",
                "weight-only quantization width of the serving state "
                "(8 = int8, 4 = nibble-packed int4, 0 = dense weights)"
            ).set({"int8": 8, "int4": 4}.get(self.quant, 0))
            _obs.gauge(
                "serving_quant_kv_page_bits",
                "KV pool element width: 8 under the int8 page mode "
                "(per-(page-row, head) f32 scales ride separately), "
                "else the checkpoint dtype width"
            ).set(8 if self.kv_quant
                  else int(np.dtype(dtype).itemsize) * 8)
            _obs.gauge(
                "serving_quant_kv_page_bytes",
                "bytes one KV page pair (k + v + scales) occupies — "
                "what each spill/restore moves and what pool sizing "
                "charges per page"
            ).set(self._page_bytes())
            # quant.json provider for obs.dump() (last engine wins,
            # like the profiler/usage holders)
            _obs.set_active_quant(self)

        # multi-LoRA metric surface: registered only when a store is
        # attached, so a dense engine exports exactly the pre-LoRA set
        if self.lora is not None:
            _obs.gauge(
                "serving_lora_bank_bytes",
                "device bytes the packed adapter bank occupies "
                "(all rows, every projection, + the scale vector)"
            ).set(self.runner.lora_bank_bytes())
            # lora.json provider for obs.dump() (last engine wins)
            _obs.set_active_lora(self)

    # ------------------------------------------------ runner delegation
    # python-side mirror of serving_decode_step_traces_total: counted at
    # trace time inside the runner's step body (the no-retrace contract)
    @property
    def decode_traces(self) -> int:
        return self.runner.decode_traces

    @property
    def kpool(self):
        return self.runner.kpool

    @property
    def vpool(self):
        return self.runner.vpool

    @property
    def _prefill_fns(self):
        return self.runner._prefill_fns

    @property
    def _prefill_cached_fns(self):
        return self.runner._prefill_cached_fns

    # ----------------------------------------------------------- intake
    def submit(self, prompt, gen: GenerationConfig | None = None, *,
               deadline: float | None = None, on_token=None,
               arrival_time: float | None = None, trace=None,
               priority: int = 0, tenant: str | None = None,
               adapter: str | None = None) -> Request:
        """``trace`` is an optional tracing.SpanContext (or Span) the
        request's root span is parented under — the server passes the
        extracted ``traceparent`` here so the engine-side spans join the
        caller's distributed trace.  Without it the root span inherits
        the submitting thread's current span, if any.  ``priority``
        sets the scheduling class: higher admits first and (with
        preemption enabled) may preempt lower-priority residents.
        ``tenant`` is the billing dimension for the usage meter
        (HTTP ``X-Tenant`` / body field; default ``"anon"``).
        ``adapter`` names a LoRA adapter registered with the engine's
        :class:`~paddle_tpu.serving.lora.AdapterStore` (HTTP
        ``X-Adapter`` / body field); unknown names are rejected here,
        before any page or span is held."""
        req = Request(prompt, gen, deadline=deadline, on_token=on_token,
                      priority=priority, tenant=tenant, adapter=adapter,
                      arrival_time=(self._clock() if arrival_time is None
                                    else arrival_time))
        if req.adapter is not None and self.lora is None:
            raise ValueError(
                f"request names adapter {req.adapter!r} but the engine "
                "was built without lora= (pass an AdapterStore)")
        total = req.prompt.size + req.gen.max_new_tokens
        if total > self.max_model_len:
            raise ValueError(
                f"prompt ({req.prompt.size}) + max_new_tokens "
                f"({req.gen.max_new_tokens}) = {total} exceeds "
                f"max_model_len {self.max_model_len}")
        need = self.blocks.pages_needed(req.prompt.size,
                                        req.gen.max_new_tokens)
        if need > self.blocks.num_pages:
            raise ValueError(
                f"request needs {need} KV pages but the pool only has "
                f"{self.blocks.num_pages}; it could never be admitted "
                "(raise num_pages or lower max_new_tokens)")
        if req.gen.do_sample and not self.emit_logits:
            raise ValueError(
                "do_sample requests need an engine built with "
                "emit_logits=True (host-side sampling reads the logits)")
        # pin the adapter's bank row for the request's whole lifetime
        # (submit -> _finalize): preemption parks KV, never the adapter,
        # so a resume re-enters decode on the same row.  Unknown names
        # KeyError here; a full bank (every row pinned) RuntimeErrors.
        req._adapter_row = (self.lora.acquire(req.adapter)
                            if self.lora is not None else 0)
        req._engine = self
        # spans only after every validation — a rejected submit must not
        # leave dangling open spans
        tr = _obs.tracer()
        attrs = {"req": req.id, "prompt_len": int(req.prompt.size),
                 "max_new_tokens": int(req.gen.max_new_tokens)}
        req.trace_parent = trace
        if trace is not None:
            req.root_span = tr.start_span("request", parent=trace,
                                          attributes=attrs)
        else:
            req.root_span = tr.start_span("request", attributes=attrs)
        req.queue_span = tr.start_span("scheduler.queue_wait",
                                       parent=req.root_span)
        if self.requestlog is not None:
            # after the root span exists so the timeline carries the
            # trace id (the /debug/trace <-> /debug/exemplars join)
            self.requestlog.attach(req)
        try:
            _obs.flight("engine", "submit", req=req.id,
                        prompt_len=int(req.prompt.size),
                        trace=req.root_span.trace_id)
            if self.usage is not None:
                # register BEFORE the scheduler sees the request so any
                # admission-time page holds already attribute to it
                self.usage.on_submit(req)
            self.scheduler.submit(req)
        except BaseException:
            # a rejected submit (queue full, shutdown race) must not
            # leave the request's spans open in the tracer ring — nor
            # its adapter row pinned
            if self.lora is not None and req.adapter is not None:
                self.lora.release(req.adapter)
            if self.requestlog is not None:
                self.requestlog.discard(req.id)
            req.queue_span.end()
            req.root_span.end()
            raise
        return req

    # -------------------------------------------------------- main loop
    def step(self) -> bool:
        """One engine iteration: evict/admit (scheduler pass), prefill
        admissions, then one lockstep decode step over the active slots.
        Returns whether any work happened."""
        now = self._clock()
        admitted = self.scheduler.schedule(now)
        # chunk states registered by THIS step's admissions already ran
        # their first chunk inside _prefill — snapshot the in-flight set
        # first so each prefill advances exactly one chunk per step
        inflight = list(self._chunking)
        for slot, req in admitted:
            self._prefill(slot, req)
        advanced = 0
        for slot in inflight:
            if slot in self._chunking:      # evicted states drop out
                self._advance_chunk(slot)
                advanced += 1
        active = [i for i, r in enumerate(self.scheduler.slots)
                  if r is not None and r.state == RequestState.DECODE]
        if active:
            self._decode(active)
        else:
            # gap witness: nothing was decoding, so this step's prefill
            # work starved no resident — the stall meter restarts
            self._prefill_since_decode = 0
        self.current_phase = "idle"
        self.progress += 1          # watchdog heartbeat
        return bool(admitted) or bool(active) or bool(advanced)

    def run_until_complete(self, max_steps: int | None = None):
        """Drive step() until no live or queued work remains."""
        steps = 0
        while self.scheduler.has_work():
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                raise RuntimeError(
                    f"engine did not quiesce within {max_steps} steps")

    def drain(self):
        """Graceful drain: stop admitting; finish what is running.
        Queued requests stay queued until :meth:`resume`."""
        self.scheduler.drain()
        while self.scheduler.active_count:
            self.step()

    def resume(self):
        self.scheduler.resume()

    # ----------------------------------------------------------- prefill
    def _prefill(self, slot: int, req: Request):
        if req.queue_span is not None:      # queue wait ends at admission
            req.queue_span.end()
            req.queue_span = None
        if req.admitted_at is not None:
            # ledger: queue-wait seconds — every wait (first admission
            # and each preemption re-queue) sums into the same field
            req.queue_seconds += max(
                0.0, req.admitted_at - req._queued_since)
            req._queued_since = req.admitted_at
            if req.timeline is not None:
                # a re-queue wait after preemption charges to the
                # preempted bucket — the request would not have waited
                # had it not been preempted
                req.timeline.note(
                    "preempted" if req.num_generated else "queue",
                    req.admitted_at, event="admit", slot=slot,
                    then="prefill_compute")
        if req.num_generated:
            # re-admission of a preempted request: rebuild device KV
            # from the prefix cache + host spill tier + a re-prefill of
            # the remainder; no token is emitted
            self._resume(slot, req)
            return
        self.current_phase = "prefill"
        t0 = time.perf_counter()
        ps = self.page_size
        plen = req.prompt.size
        meta = self.blocks.seq_meta(req.id)
        cached = int(meta["cached_len"])
        row = self.blocks.table_row(req.id, self.table_width)
        if self.prefill_chunk and plen - cached > self.prefill_chunk:
            # chunked admission: CoW once up front, then one chunk per
            # engine step so decoding slots keep stepping in between
            try:
                if meta["cow_src"] is not None:
                    self.runner.copy_page(int(meta["cow_src"]),
                                          int(row[cached // ps]))
            except Exception as e:
                self._note_phase("prefill", time.perf_counter() - t0)
                self._quarantine(slot, req, e, self._clock())
                return
            req.num_cached_tokens = cached
            req.prefill_cached_tokens += cached
            req.prefill_computed_tokens += plen - cached
            self._note_phase("prefill", time.perf_counter() - t0)
            self._begin_chunks(slot, req, req.prompt, cached, row)
            return
        try:
            if meta["cow_src"] is not None:
                # copy-on-write: duplicate the matching tail page into
                # this request's own tail before any writes land there
                self.runner.copy_page(int(meta["cow_src"]),
                                      int(row[cached // ps]))
            if cached == 0:
                bucket = -(-plen // ps) * ps
                ids = np.zeros((1, bucket), np.int32)
                ids[0, :plen] = req.prompt
                logits = self.runner.prefill(
                    ids, plen, row, adapter_row=req._adapter_row)
            else:
                suffix = plen - cached
                bucket = -(-suffix // ps) * ps
                ids = np.zeros((1, bucket), np.int32)
                ids[0, :suffix] = req.prompt[cached:]
                logits = self.runner.prefill_cached(
                    ids, suffix, cached, row,
                    adapter_row=req._adapter_row)
            req.num_cached_tokens = cached
            req.prefill_cached_tokens += cached
            req.prefill_computed_tokens += plen - cached
            self._note_gap(plen - cached)
            _M_HOST_SYNCS.labels("prefill").inc()
            logits_row = np.asarray(logits)[0]
            if (self.faults is not None
                    and self.faults.check("nan_logits", req=req.id,
                                          slot=slot,
                                          phase="prefill") is not None):
                logits_row = np.full_like(logits_row, np.nan)
            tok = self._pick_token(req, logits_row)
        except Exception as e:
            # a failed prefill kills ONE request, never the process:
            # pages release, the slot parks, the batch keeps running
            self._note_phase("prefill", time.perf_counter() - t0)
            self._quarantine(slot, req, e, self._clock())
            return
        now = self._clock()
        self._ttft.observe(now - req.arrival_time)
        self._note_phase("prefill", time.perf_counter() - t0)
        if req.timeline is not None:
            req.timeline.note_prefill(now, cached=cached,
                                      computed=plen - cached, slot=slot)
        _obs.tracer().record_span(
            "engine.prefill", t0, time.perf_counter(),
            parent=req.root_span,
            attributes={"req": req.id, "slot": slot, "bucket": bucket,
                        "cached_tokens": cached,
                        "kind": "cached_suffix" if cached else "full",
                        "cow": meta["cow_src"] is not None})
        if req.root_span is not None:
            req.decode_span = _obs.tracer().start_span(
                "engine.decode", parent=req.root_span,
                attributes={"req": req.id, "slot": slot})
        _obs.flight("engine", "prefill", req=req.id, slot=slot,
                    bucket=bucket, cached=cached)
        self.table[slot] = row
        self._pos[slot] = plen
        self._tok[slot] = tok
        self._active[slot] = 1
        self._aidx[slot] = req._adapter_row
        self._push_slot(slot)
        req.state = RequestState.DECODE
        if self._proposer is not None:
            # seed the drafter with the prompt; emitted tokens extend
            # the history through _emit
            self._proposer.register(req.id, req.prompt)
        self._emit(slot, req, tok, now)

    # --------------------------------------------------- chunked prefill
    def _note_gap(self, tokens: int):
        """Account ``tokens`` prompt tokens prefilled since the last
        decode step — the overload-degradation witness: chunking bounds
        this by ``prefill_chunk``; without it one long prompt stalls
        every decoding slot for its whole length."""
        self._prefill_since_decode += int(tokens)
        if self._prefill_since_decode > self.max_prefill_gap:
            self.max_prefill_gap = self._prefill_since_decode

    def _begin_chunks(self, slot: int, req: Request, ids_all, done: int,
                      row, *, resume_tok: int | None = None):
        """Arm chunked prefill for ``slot`` and run its first chunk:
        ``ids_all`` past position ``done`` pages in ``prefill_chunk``
        tokens at a time, one chunk per engine step.  ``resume_tok``
        marks a preempted-request resume — the final chunk's logits are
        discarded and decode re-enters with that token instead of
        sampling a new one."""
        self._chunking[slot] = {
            "req": req, "ids": np.asarray(ids_all, np.int32).reshape(-1),
            "done": int(done), "row": row, "resume_tok": resume_tok,
            "chunks": 0, "t0": time.perf_counter()}
        self._advance_chunk(slot)

    def _advance_chunk(self, slot: int):
        """Run ONE prefill chunk for an in-flight admission.  Chunk K
        attends chunks 1..K-1 through the existing cached-prefill jit
        (arbitrary non-aligned boundaries — no new traced program
        shapes); intermediate chunks never fetch logits, so they cost
        no host sync.  Between chunks the engine keeps decoding and
        ``progress`` keeps heartbeating, so a long prompt neither
        stalls resident TPOT nor trips the watchdog."""
        st = self._chunking[slot]
        req = st["req"]
        if req.timeline is not None:
            # time since the last chunk (decode steps for other slots
            # ran in between) is this request's chunk-gap cost
            req.timeline.note("chunk_gap", self._clock(),
                              then="prefill_compute")
        ids_all = st["ids"]
        n = int(ids_all.size)
        done = st["done"]
        this = min(self.prefill_chunk, n - done)
        last = done + this >= n
        ps = self.page_size
        self.current_phase = "prefill_chunk"
        t0 = time.perf_counter()
        try:
            bucket = -(-this // ps) * ps
            ids = np.zeros((1, bucket), np.int32)
            ids[0, :this] = ids_all[done:done + this]
            if done == 0:
                logits = self.runner.prefill(
                    ids, this, st["row"],
                    adapter_row=getattr(req, "_adapter_row", 0))
            else:
                logits = self.runner.prefill_cached(
                    ids, this, done, st["row"],
                    adapter_row=getattr(req, "_adapter_row", 0))
            st["chunks"] += 1
            self.prefill_chunks += 1
            req.prefill_chunks += 1
            _M_CHUNKS.inc()
            self._note_gap(this)
            if not last:
                st["done"] = done + this
                self._note_phase("prefill", time.perf_counter() - t0)
                if req.timeline is not None:
                    req.timeline.note(
                        "prefill_compute", self._clock(), event="chunk",
                        slot=slot, done=done + this, total=n,
                        then="chunk_gap")
                _obs.flight("engine", "prefill_chunk", req=req.id,
                            slot=slot, done=done + this, total=n)
                return
            if st["resume_tok"] is None:
                # admission: the first output token samples from the
                # final chunk's last-position logits
                _M_HOST_SYNCS.labels("prefill").inc()
                logits_row = np.asarray(logits)[0]
                if (self.faults is not None
                        and self.faults.check(
                            "nan_logits", req=req.id, slot=slot,
                            phase="prefill") is not None):
                    logits_row = np.full_like(logits_row, np.nan)
                tok = self._pick_token(req, logits_row)
            else:
                # resume: the last emitted token re-enters as the next
                # decode input; the replay logits are discarded
                tok = int(st["resume_tok"])
        except Exception as e:
            self._note_phase("prefill", time.perf_counter() - t0)
            self._chunking.pop(slot, None)
            self._quarantine(slot, req, e, self._clock())
            return
        self._chunking.pop(slot, None)
        # the full chunked prefix is device-resident now — register it
        # in the prefix-cache chain (deferred at allocate_seq)
        self.blocks.publish_seq(req.id, ids_all)
        now = self._clock()
        self._note_phase("prefill", time.perf_counter() - t0)
        if req.timeline is not None:
            req.timeline.note("prefill_compute", now, event="chunk",
                              slot=slot, done=n, total=n,
                              chunks=st["chunks"], then="decode")
        _obs.tracer().record_span(
            "engine.prefill", st["t0"], time.perf_counter(),
            parent=req.root_span,
            attributes={"req": req.id, "slot": slot,
                        "chunks": st["chunks"],
                        "cached_tokens": req.num_cached_tokens,
                        "kind": "chunked",
                        "resume": st["resume_tok"] is not None})
        _obs.flight("engine", "prefill", req=req.id, slot=slot,
                    chunks=st["chunks"], cached=req.num_cached_tokens)
        self._enter_decode(slot, req, st["row"], n, tok, now)
        if st["resume_tok"] is None:
            self._ttft.observe(now - req.arrival_time)
            if self._proposer is not None:
                self._proposer.register(req.id, req.prompt)
            self._emit(slot, req, tok, now)
        elif self._proposer is not None:
            self._proposer.register(req.id, np.append(ids_all, tok))

    def _enter_decode(self, slot: int, req: Request, row, pos: int,
                      tok: int, now: float):
        """Flip an admitted request into decode: patch the slot mirrors
        + the device row, open the decode span."""
        self.table[slot] = row
        self._pos[slot] = pos
        self._tok[slot] = tok
        self._active[slot] = 1
        self._aidx[slot] = req._adapter_row
        self._push_slot(slot)
        req.state = RequestState.DECODE
        if req.root_span is not None:
            req.decode_span = _obs.tracer().start_span(
                "engine.decode", parent=req.root_span,
                attributes={"req": req.id, "slot": slot})

    # -------------------------------------------------------- preemption
    def _preempt(self, slot: int) -> bool:
        """Scheduler callback behind preempt-and-swap: spill ``slot``'s
        exclusive committed KV pages to the BlockManager host tier,
        release its pages (complete chunks re-register in the prefix-
        cache chain when the cache is on), and park the slot.  Returns
        False — victim untouched, preemption aborted — when a page copy
        fails (the ``spill_fail`` chaos site); parked copies from the
        aborted attempt are discarded, so the pool census stays exact."""
        req = self.scheduler.slots[slot]
        if req is None or req.state != RequestState.DECODE:
            return False
        t0 = time.perf_counter()
        if req.timeline is not None:
            # decoding ends here; the spill loop below (and, if the
            # preemption lands, the re-queue wait and the restore)
            # charges to the preempted bucket
            req.timeline.note("decode", self._clock(), then="preempted")
        tokens = req.resume_tokens()
        parked: list[str] = []
        for page, digest in self.blocks.spill_plan(req.id, tokens):
            if (self.faults is not None
                    and self.faults.check("spill_fail", req=req.id,
                                          page=page) is not None):
                self.blocks.host_discard(parked)
                self.spill_aborts += 1
                if req.timeline is not None:
                    # the aborted spill attempt was still preemption
                    # cost; the request goes back to decoding
                    req.timeline.note("preempted", self._clock(),
                                      event="spill_abort", slot=slot,
                                      then="decode")
                _obs.flight("engine", "spill_abort", req=req.id,
                            slot=slot, page=page,
                            parked_dropped=len(parked))
                return False
            arrays = self.runner.read_page(page)
            self.blocks.host_put(digest, *arrays)
            # ledger: charged per page parked, mirroring host_put's
            # global counters (an abort on a LATER page keeps both) —
            # int8 pages park (k, v, kscale, vscale) and the byte sum
            # reflects the quantized footprint
            req.spilled_pages += 1
            req.spill_bytes += sum(a.nbytes for a in arrays)
            if self.usage is not None:
                self.usage.on_host_park(req, digest)
            parked.append(digest)
        self.blocks.release_preempted(req.id, tokens)
        self._park(slot)
        self.preemptions += 1
        # back to the queue: the ledger's queue-wait anchor restarts so
        # queue_seconds sums this wait too
        req._queued_since = self._clock()
        if req.timeline is not None:
            req.timeline.note("preempted", req._queued_since,
                              event="preempt", slot=slot,
                              pages=len(parked), then="preempted")
        if self._proposer is not None:
            self._proposer.drop(req.id)  # resume re-registers history
        if req.decode_span is not None:
            req.decode_span.set_attribute("preempted", True)
            req.decode_span.set_attribute("generated", req.num_generated)
            req.decode_span.end()
            req.decode_span = None
        if req.root_span is not None:
            # back to the queue: a fresh queue-wait span covers the
            # time until re-admission
            req.queue_span = _obs.tracer().start_span(
                "scheduler.queue_wait", parent=req.root_span,
                attributes={"resume": True})
        _obs.tracer().record_span(
            "engine.preempt_spill", t0, time.perf_counter(),
            parent=req.root_span,
            attributes={"req": req.id, "slot": slot,
                        "pages": len(parked)})
        _obs.flight("engine", "preempt_spill", req=req.id, slot=slot,
                    pages=len(parked))
        return True

    def _resume(self, slot: int, req: Request):
        """Re-admit a preempted request.  Its effective prompt is
        prompt + generated-so-far; device KV rebuilds from, in order,
        the prefix-cache match recorded at allocate_seq, the host spill
        tier (page-granular, content-addressed), and a re-prefill of
        whatever remains — then decode continues with the last emitted
        token as the next input, token-for-token identical to an
        uninterrupted greedy run (parity asserted in tests)."""
        self.current_phase = "prefill"
        t0 = time.perf_counter()
        ps = self.page_size
        if self.usage is not None:
            # this request is no longer waiting on its parked pages —
            # per-request host-tier accrual stops here (the tenant keeps
            # paying until the digests fall out of the host LRU)
            self.usage.on_host_release(req)
        tokens = req.resume_tokens()
        ids_all = tokens[:-1]
        n = int(ids_all.size)
        meta = self.blocks.seq_meta(req.id)
        # ledger: the uncapped match length is what allocate_seq added
        # to the global cached_tokens counter for this resume
        req.prefill_cached_tokens += int(meta["cached_len"])
        cached = min(int(meta["cached_len"]), n)
        row = self.blocks.table_row(req.id, self.table_width)
        restored = 0
        try:
            if meta["cow_src"] is not None:
                # tail CoW page from the admission match: duplicate it
                # before any writes land (same rule as fresh admission)
                self.runner.copy_page(int(meta["cow_src"]),
                                      int(row[cached // ps]))
            else:
                # host-tier unpark: extend coverage page by page past
                # the cache match while parked complete chunks exist
                while cached % ps == 0 and cached + ps <= n:
                    c = cached // ps
                    entry = self.blocks.host_get(
                        self.blocks.spill_digest(tokens, c))
                    if entry is None:
                        break
                    self.runner.write_page(int(row[c]), *entry)
                    self.blocks.note_restored()
                    req.restored_pages += 1
                    req.restore_bytes += sum(a.nbytes for a in entry)
                    restored += 1
                    cached += ps
        except Exception as e:
            self._note_phase("prefill", time.perf_counter() - t0)
            self._quarantine(slot, req, e, self._clock())
            return
        suffix = n - cached
        tok = int(tokens[-1])
        # ledger: the re-prefilled remainder runs on device (chunked or
        # single-shot alike)
        req.prefill_computed_tokens += suffix
        if self.prefill_chunk and suffix > self.prefill_chunk:
            # a long replay suffix chunks exactly like a long prompt —
            # resumes must not reintroduce the TPOT stall either
            self._note_phase("prefill", time.perf_counter() - t0)
            if req.timeline is not None:
                # restore work so far charges to preempted; the chunked
                # re-prefill accounts like any chunked admission
                req.timeline.note("preempted", self._clock(),
                                  event="resume", slot=slot,
                                  restored=restored, cached=cached,
                                  chunked=True, then="prefill_compute")
            _obs.flight("engine", "resume", req=req.id, slot=slot,
                        tokens=n, cached=cached, restored=restored,
                        chunked=True)
            self._begin_chunks(slot, req, ids_all, cached, row,
                               resume_tok=tok)
            return
        try:
            if suffix > 0:
                bucket = -(-suffix // ps) * ps
                ids = np.zeros((1, bucket), np.int32)
                ids[0, :suffix] = ids_all[cached:]
                if cached == 0:
                    self.runner.prefill(ids, suffix, row,
                                        adapter_row=req._adapter_row)
                else:
                    self.runner.prefill_cached(
                        ids, suffix, cached, row,
                        adapter_row=req._adapter_row)
                self._note_gap(suffix)
            # the resume logits are discarded (the last token is
            # already known) — no host sync happens here
        except Exception as e:
            self._note_phase("prefill", time.perf_counter() - t0)
            self._quarantine(slot, req, e, self._clock())
            return
        # allocate_seq defers on plen while the chunk test above uses
        # the replay suffix, so a resume can be deferred yet single-shot
        # — publish here too (no-op when registration wasn't deferred)
        self.blocks.publish_seq(req.id, ids_all)
        now = self._clock()
        self._note_phase("prefill", time.perf_counter() - t0)
        if req.timeline is not None:
            req.timeline.note("preempted", now, event="resume",
                              slot=slot, restored=restored,
                              cached=cached, then="decode")
        self._enter_decode(slot, req, row, n, tok, now)
        if self._proposer is not None:
            self._proposer.register(req.id, tokens)
        _obs.tracer().record_span(
            "engine.resume", t0, time.perf_counter(),
            parent=req.root_span,
            attributes={"req": req.id, "slot": slot, "tokens": n,
                        "cached_tokens": cached,
                        "restored_pages": restored})
        _obs.flight("engine", "resume", req=req.id, slot=slot,
                    tokens=n, cached=cached, restored=restored)

    # ------------------------------------------------------------ decode
    def _decode(self, active: list[int]):
        self.current_phase = "decode"
        if self.faults is not None:
            f = self.faults.check("slow_step", step=self.decode_steps)
            if f is not None:
                time.sleep(float(f.get("seconds", 0.05)))
            # raise BEFORE any dispatch: the pools are never half-
            # donated, so recovery sees a consistent host mirror
            if self.faults.check("step_raise",
                                 step=self.decode_steps) is not None:
                raise InjectedFault(
                    f"injected poisoned decode step "
                    f"(step {self.decode_steps})")
        if self._seg_span is None:
            # one span per host-sync interval, NOT per device step —
            # segments are the engine's visible unit of decode work
            self._seg_span = _obs.tracer().start_span(
                "engine.decode_segment", parent=None,
                attributes={"slots": len(active)})
            self._seg_steps = 0
        self._seg_steps += 1
        reqs = [(s, self.scheduler.slots[s]) for s in active]
        drafts = self._propose(reqs)
        if drafts:
            self._decode_spec(reqs, drafts)
            return
        step_t0 = time.perf_counter()
        logits = self.runner.decode_step()
        self._note_phase("decode", time.perf_counter() - step_t0)
        self.decode_steps += 1
        self._prefill_since_decode = 0      # gap witness: decode ran
        _M_STEPS.inc()
        self._pages_hist.observe(self.blocks.pages_in_use)
        for slot in active:
            self._pos[slot] += 1            # mirror of pos + active
        self._pending.append((self._ring_cursor, reqs, None))
        self._ring_cursor = (self._ring_cursor + 1) % self.sync_interval
        self._last_logits = logits if self.emit_logits else None
        # any active sampling request needs its token fed back before
        # the next step, so sampling degrades to a per-step sync
        eff = 1 if any(r.gen.do_sample for _, r in reqs) \
            else self.sync_interval
        if len(self._pending) >= eff:
            self._sync()

    def _propose(self, reqs) -> dict:
        """Collect this step's drafts: ``{slot: [tokens]}``.  Empty —
        take the plain step — when speculation is off, when unsynced
        ring rows are outstanding (the drafter indexes only tokens the
        host has seen; a verify step always syncs immediately, so the
        mirrors it needs are exact), or when an active request samples
        (greedy verification only, for now)."""
        if (self._proposer is None or self._pending
                or any(r.gen.do_sample for _, r in reqs)):
            return {}
        drafts = {}
        for slot, req in reqs:
            # cap so even a fully-accepted draft commits at most the
            # tokens the request may still emit (rem), keeping every KV
            # write inside the admission reservation
            cap = req.gen.max_new_tokens - req.num_generated - 1
            if cap <= 0:
                continue
            ds = self._proposer.propose(req.id, cap)
            if ds:
                drafts[slot] = ds
        return drafts

    def _decode_spec(self, reqs, drafts: dict):
        """One verify step: upload the draft grid, score k+1 positions
        per slot, then sync immediately — acceptance needs the ring row
        before the next proposal anyway, and the step commits up to k+1
        tokens, so the sync amortizes exactly like deferred plain
        steps."""
        self.current_phase = "verify"
        draft_arr = np.zeros((self.max_slots, self.spec_k), np.int32)
        dlen = np.zeros((self.max_slots,), np.int32)
        for slot, ds in drafts.items():
            draft_arr[slot, :len(ds)] = ds
            dlen[slot] = len(ds)
        step_t0 = time.perf_counter()
        self.runner.verify_step(draft_arr, dlen)
        self._note_phase("decode", time.perf_counter() - step_t0)
        self.decode_steps += 1
        self._prefill_since_decode = 0      # gap witness: decode ran
        _M_STEPS.inc()
        self._spec.record_step()
        self._pages_hist.observe(self.blocks.pages_in_use)
        # speculative multi-token append: charge the whole candidate
        # span now; the rejected suffix rolls back at the sync below
        for slot, req in reqs:
            ds = drafts.get(slot)
            if ds:
                self.blocks.append(req.id, len(ds) + 1)
        self._pending.append((self._ring_cursor, reqs, drafts))
        self._ring_cursor = (self._ring_cursor + 1) % self.sync_interval
        self._last_logits = None
        self._sync()

    def _sync(self):
        """Drain the device token ring: ONE [sync_interval, slots] int32
        transfer covers every decode step since the previous sync."""
        self.current_phase = "host_sync"
        sync_t0 = time.perf_counter()
        ring = self.runner.fetch_ring()
        sync_s = time.perf_counter() - sync_t0
        self.host_syncs += 1
        self._note_phase("host_sync", sync_s)
        _M_HOST_SYNCS.labels("ring").inc()
        poll = int(FLAGS.get("FLAGS_resource_memory_poll_steps") or 0)
        if poll > 0 and self.host_syncs % poll == 0:
            resource_tracker().sample_memory()
        # wide-ring rows: [slots, k+1] candidate grids (speculation on);
        # narrow rows: [slots] sampled tokens.  Re-derive each verify
        # row's acceptance from the drafts the host already holds — the
        # same integer comparison the device ran, no extra transfer.
        wide = ring.ndim == 3
        accepted: dict[int, tuple[int, int]] = {}
        for ridx, entries, drafts in self._pending:
            if drafts is None:
                continue
            for slot, req in entries:
                if req.is_finished() or req.state != RequestState.DECODE:
                    continue
                a = 0
                for j, d in enumerate(drafts.get(slot, ())):
                    if int(ring[ridx, slot, j]) != int(d):
                        break
                    a += 1
                accepted[slot] = (len(drafts.get(slot, ())), a)
        if self._seg_span is not None:
            # the ring fetch above blocked on the device — the segment
            # span ends here, covering dispatch through host sync
            self._seg_span.set_attribute("steps", self._seg_steps)
            if accepted:
                self._seg_span.set_attribute(
                    "spec_proposed", sum(p for p, _ in accepted.values()))
                self._seg_span.set_attribute(
                    "spec_accepted", sum(a for _, a in accepted.values()))
            self._seg_span.end()
            self._seg_span = None
        _obs.flight("engine", "host_sync", rows=len(self._pending),
                    steps=self._seg_steps, sync_s=round(sync_s, 6))
        sample_t0 = None
        logits_np = None
        now = self._clock()
        n_rows = len(self._pending)
        if self.requestlog is not None:
            # one timeline charge per live request per sync: decode
            # dispatch up to the blocking ring fetch, then the sync
            # itself — overlapping requests each experience the full
            # wall interval, so per-request conservation still holds
            seen: set[int] = set()
            for _, entries, _ in self._pending:
                for _slot, _req in entries:
                    if (_req.id in seen or _req.is_finished()
                            or _req.state != RequestState.DECODE
                            or _req.timeline is None):
                        continue
                    seen.add(_req.id)
                    _req.timeline.note_sync(now, sync_s)
        corrections = []
        for row_i, (ridx, entries, drafts) in enumerate(self._pending):
            for slot, req in entries:
                if req.is_finished() or req.state != RequestState.DECODE:
                    continue        # evicted/finished: overrun discarded
                if drafts is not None:
                    self._accept(slot, req, ring[ridx, slot],
                                 *accepted[slot], now)
                    continue
                tok = raw = int(ring[ridx, slot, 0]) if wide \
                    else int(ring[ridx, slot])
                if req.gen.do_sample:
                    # sampling rows only exist under eff-interval 1, so
                    # the step's logits handle is always the right row
                    if logits_np is None:
                        sample_t0 = time.perf_counter()
                        logits_np = np.asarray(self._last_logits)
                        self.logit_fetches += 1
                        _M_HOST_SYNCS.labels("logits").inc()
                    row_logits = logits_np[slot]
                    if (self.faults is not None
                            and self.faults.check(
                                "nan_logits", req=req.id, slot=slot,
                                phase="decode") is not None):
                        row_logits = np.full_like(row_logits, np.nan)
                    try:
                        tok = self._pick_token(req, row_logits)
                    except NonFiniteLogitsError as e:
                        # fail ONLY the offending request — the other
                        # slots in this sync keep their tokens
                        self._quarantine(slot, req, e, now)
                        continue
                    if tok != raw:
                        corrections.append((slot, tok))
                prev = req.last_token_at
                if prev is not None:
                    # batched sync: spread the interval over the tokens
                    # it covers so TPOT keeps per-token semantics
                    self._tpot.observe((now - prev) / (n_rows - row_i))
                self._tok[slot] = tok
                self._emit(slot, req, tok, now)
        self._pending.clear()
        if sample_t0 is not None:
            # host-side sampling for this sync: logits fetch + per-
            # request pick (argmax/top-k/top-p) + any device feedback
            _obs.tracer().record_span(
                "engine.sample", sample_t0, time.perf_counter(),
                attributes={"corrections": len(corrections)})
        if corrections:
            self.runner.correct_tokens(corrections)

    def _accept(self, slot: int, req: Request, row, proposed: int,
                a: int, now: float):
        """Commit one verify-row slot: roll back the rejected draft
        suffix (the ledger then charges pages for accepted tokens
        only), advance the pos mirror by the accepted prefix + the
        correction/bonus token, and emit those ``a + 1`` tokens in
        order — stopping at max_new/EOS exactly where sequential decode
        would have stopped."""
        if proposed:
            self.blocks.rollback(req.id, proposed - a)
            self._spec.record(proposed, a)
            req.spec_proposed_tokens += proposed
            req.spec_accepted_tokens += a
        self._pos[slot] += a + 1        # mirror of pos + (acc+1)*active
        prev = req.last_token_at
        dt = None if prev is None else (now - prev) / (a + 1)
        for j in range(a + 1):
            tok = int(row[j])
            if dt is not None:
                # one verify step emitted a+1 tokens: spread the
                # interval so TPOT keeps per-token semantics
                self._tpot.observe(dt)
            self._tok[slot] = tok
            # drafted slots were charged up front at dispatch;
            # ride-along slots (no draft) charge per emit as usual
            self._emit(slot, req, tok, now, charge=proposed == 0)
            if req.is_finished():
                break

    def _note_phase(self, phase: str, seconds: float):
        """Charge engine wall time to a phase: the per-engine mirror,
        the serving_step_phase_seconds_total counter, and the process
        tracker's throughput denominator."""
        seconds = max(float(seconds), 0.0)
        self.timings[phase + "_s"] += seconds
        _M_PHASE_SECONDS.labels(phase).inc(seconds)
        resource_tracker().note_phase(phase, seconds)

    def _emit(self, slot: int, req: Request, tok: int, now: float,
              charge: bool = True):
        if req.timeline is not None and req.first_token_at is None:
            req.timeline.mark("first_token", now)   # the TTFT moment
        req._emit(tok, now)
        _M_TOKENS.inc()
        resource_tracker().note_tokens(1)
        if charge:
            # committed-token ledger: one durable token per emit (the
            # speculative path charges its whole span at dispatch and
            # rolls the rejected suffix back instead)
            self.blocks.append(req.id, 1)
        if self._proposer is not None:
            self._proposer.extend(req.id, tok)
        eos = req.gen.eos_token_id
        if req.num_generated >= req.gen.max_new_tokens:
            self._finalize(req, "length", now)
            self.scheduler.evict(slot, "finished", now)
        elif eos is not None and tok == eos:
            self._finalize(req, "eos", now)
            self.scheduler.evict(slot, "finished", now)

    def _park(self, slot: int):
        """Return a slot to the idle state: all writes/reads go to the
        dump page until the next admission."""
        # an eviction mid-chunked-prefill abandons the chunk state (the
        # pages are gone; the request was finalized by the scheduler)
        self._chunking.pop(slot, None)
        self.table[slot] = self.blocks.empty_row(self.table_width)
        self._pos[slot] = 0
        self._tok[slot] = 0
        self._active[slot] = 0
        self._aidx[slot] = 0
        self._push_slot(slot)

    def _push_slot(self, slot: int):
        """Patch ONE slot's row of the device-resident decode state from
        the host mirrors (admission / eviction only — never per step)."""
        self.runner.push_slot(slot, self.table[slot],
                              int(self._pos[slot]), int(self._tok[slot]),
                              int(self._active[slot]),
                              adapter_row=int(self._aidx[slot]))

    # --------------------------------------------------------- sampling
    def _pick_token(self, req: Request, logits: np.ndarray) -> int:
        g = req.gen
        if not g.do_sample:
            # argmax over NaN silently returns the NaN's index (NaN
            # propagates as the max) — poisoned logits must fail the
            # request loudly, not emit a garbage token
            if np.isnan(logits).any() or not np.isfinite(logits).any():
                raise NonFiniteLogitsError(
                    f"request {req.id}: non-finite logits from the "
                    "model (greedy decode)")
            return int(np.argmax(logits))
        rng = self._rngs.get(req.id)
        if rng is None:
            rng = self._rngs[req.id] = np.random.default_rng(
                (g.seed, req.id))
        logits = logits.astype(np.float64)
        if g.temperature != 1.0:
            logits = logits / max(g.temperature, 1e-6)
        if g.top_k and g.top_k > 0:
            k = min(g.top_k, logits.size)
            kth = np.sort(logits)[-k]
            logits = np.where(logits < kth, -np.inf, logits)
        if g.top_p < 1.0:
            order = np.argsort(logits)[::-1]
            probs = _softmax(logits[order])
            cum = np.cumsum(probs)
            cutoff_idx = int(np.sum(cum < g.top_p))
            cutoff = logits[order[min(cutoff_idx, logits.size - 1)]]
            logits = np.where(logits < cutoff, -np.inf, logits)
        if not np.isfinite(logits).any():
            raise NonFiniteLogitsError(
                f"request {req.id}: no finite logits to sample from — "
                "the model emitted non-finite logits (or top_k/top_p "
                "masked every candidate)")
        return int(rng.choice(logits.size, p=_softmax(logits)))

    # -------------------------------------------------------- lifecycle
    def _finalize(self, req: Request, reason: str, now: float):
        if req.is_finished():
            return
        req.finish_reason = reason
        req.state = RequestState.CANCELLED \
            if reason in ("cancelled", "deadline") else RequestState.DONE
        req.finished_at = now
        if self.lora is not None and req.adapter is not None:
            # unpin the bank row (acquired at submit); the weights stay
            # resident until LRU pressure evicts them
            self.lora.release(req.adapter)
        self._rngs.pop(req.id, None)
        if self._proposer is not None:
            self._proposer.drop(req.id)
        self._e2e.observe(now - req.arrival_time)
        _M_REQUESTS.labels(reason).inc()
        _M_FINISH.labels(reason).inc()
        resource_tracker().note_finish(reason, req.num_generated)
        if self.requestlog is not None:
            # close the timeline (residual charge + conservation check)
            # BEFORE slo.observe, so a violation exemplar snapshots the
            # finished attribution, not a half-charged one
            self.requestlog.on_finish(req, reason, now)
        if self.slo is not None:
            self.slo.observe(req, now)
        if self.usage is not None:
            # after slo.observe so per-tenant verdicts land first; the
            # page-seconds accumulator folds when the pages release
            self.usage.on_finish(req, reason, now)
        _obs.flight("engine", "finish", req=req.id, reason=reason,
                    generated=req.num_generated)
        if req.queue_span is not None:      # dropped while still queued
            req.queue_span.set_attribute("dropped", True)
            req.queue_span.end()
            req.queue_span = None
        if req.decode_span is not None:
            req.decode_span.set_attribute("generated", req.num_generated)
            req.decode_span.end()
            req.decode_span = None
        if req.root_span is not None:
            rs = req.root_span
            rs.set_attribute("finish_reason", reason)
            rs.set_attribute("generated", req.num_generated)
            rs.set_attribute("cached_tokens", req.num_cached_tokens)
            if reason == "deadline" and req.deadline is not None:
                # how far past its deadline the request was when the
                # scheduler finally evicted it (engine clock)
                rs.set_attribute("deadline_overrun_s",
                                 round(now - req.deadline, 6))
            rs.end()

    # -------------------------------------------------------- self-healing
    def _quarantine(self, slot: int, req: Request, why, now: float):
        """Fail ONE request in place: finish_reason='error', pages
        released, slot parked — the batch keeps running.  The failure
        detail lands on ``req.error`` for the server's error payload."""
        req.error = str(why)
        self.quarantines += 1
        _M_RECOVERY.labels("quarantine").inc()
        _obs.flight("engine", "quarantine", req=req.id, slot=slot,
                    error=str(why)[:160])
        self._finalize(req, "error", now)
        self.scheduler.evict(slot, "error", now)

    def recover(self) -> dict:
        """Rebuild the ModelRunner after a poisoned step and replay
        every in-flight request.

        The BlockManager is entirely host-side, so page ownership, block
        tables, and the committed-token ledger all survive — only the
        device KV *content* is gone.  Each DECODE-state request re-runs
        its committed tokens (prompt + generated so far, minus the last
        token, which re-enters as the next decode input) through the
        prefill path; the prefix-cache chain is flushed first (it
        described dead KV) and re-registered by the replays themselves,
        so sequences sharing prefix pages replay the shared part once.
        Requests that cannot be replayed are quarantined.  Typically
        called by the :class:`~.supervisor.EngineSupervisor`, not
        user code."""
        now = self._clock()
        t0 = time.perf_counter()
        if self._seg_span is not None:
            self._seg_span.set_attribute("aborted", True)
            self._seg_span.end()
            self._seg_span = None
        self._seg_steps = 0
        # drop un-synced device state: the ring rows and logits handle
        # belong to the dead runner (the pos mirrors they would have
        # advanced are recomputed from request state below)
        self._pending.clear()
        self._ring_cursor = 0
        self._last_logits = None
        flushed = self.blocks.flush_prefix_cache()
        self.runner = ModelRunner(self.config, self.state,
                                  **self._runner_kw)
        if self.lora is not None:
            # the fresh runner's bank is zeroed — re-upload every
            # resident adapter before any replayed prefill reads it
            self.lora.attach(self.runner)
        replayed = 0
        for slot, req in enumerate(self.scheduler.slots):
            if req is None:
                self._park(slot)        # sync the fresh decode state
                continue
            if req.state != RequestState.DECODE or not req.output_tokens:
                self._quarantine(slot, req,
                                 "not replayable at runner rebuild", now)
                continue
            try:
                self._replay(slot, req)
                replayed += 1
                self.replayed_requests += 1
            except Exception as e:
                self._quarantine(slot, req, f"replay failed: {e}", now)
        self.recoveries += 1
        _obs.flight("engine", "recover", replayed=replayed,
                    flushed_cached_pages=flushed)
        _obs.tracer().record_span(
            "engine.recover", t0, time.perf_counter(),
            attributes={"replayed": replayed,
                        "flushed_cached_pages": flushed})
        return {"replayed": replayed, "flushed_cached_pages": flushed}

    def _replay(self, slot: int, req: Request):
        """Re-prefill one in-flight request's committed tokens into the
        rebuilt runner.  Restores the decode invariant exactly: device
        KV covers positions ``0..pos-1`` where ``pos = prompt +
        generated - 1``, and the last generated token re-enters as the
        next step's input — decode then continues token-for-token as if
        the fault never happened (greedy parity is asserted in tests)."""
        self.current_phase = "prefill"
        t0 = time.perf_counter()
        tokens = [int(t) for t in req.prompt] + list(req.output_tokens)
        ids_all = tokens[:-1]
        n = len(ids_all)
        plan = self.blocks.replay_plan(req.id, ids_all)
        cached = int(plan["cached_len"])
        # ledger: recovery replays re-run committed tokens; the cache
        # match mirrors replay_plan's global cached_tokens bump
        req.replays += 1
        req.prefill_cached_tokens += cached
        req.prefill_computed_tokens += n - cached
        row = self.blocks.table_row(req.id, self.table_width)
        ps = self.page_size
        arow = getattr(req, "_adapter_row", 0)
        if cached == 0:
            bucket = -(-n // ps) * ps
            ids = np.zeros((1, bucket), np.int32)
            ids[0, :n] = ids_all
            self.runner.prefill(ids, n, row, adapter_row=arow)
        else:
            suffix = n - cached
            bucket = -(-suffix // ps) * ps
            ids = np.zeros((1, bucket), np.int32)
            ids[0, :suffix] = ids_all[cached:]
            self.runner.prefill_cached(ids, suffix, cached, row,
                                       adapter_row=arow)
        # the replay's logits are discarded (the last token is already
        # known), so no host sync happens here
        drift = self.blocks.committed_tokens(req.id) - len(tokens)
        if drift > 0:
            # a fault between a speculative dispatch and its sync left
            # uncommitted draft positions charged — roll them back
            self.blocks.rollback(req.id, drift)
        self.table[slot] = row
        self._pos[slot] = n
        self._tok[slot] = tokens[-1]
        self._active[slot] = 1
        self._aidx[slot] = getattr(req, "_adapter_row", 0)
        self._push_slot(slot)
        self._note_phase("prefill", time.perf_counter() - t0)
        if req.timeline is not None:
            # everything since the last charge — the poisoned step, the
            # runner rebuild's share, and this replay — was recovery
            req.timeline.note("recovery", self._clock(), event="replay",
                              slot=slot, tokens=n, cached=cached,
                              then="decode")
        _obs.tracer().record_span(
            "engine.replay", t0, time.perf_counter(),
            parent=req.root_span,
            attributes={"req": req.id, "slot": slot, "tokens": n,
                        "cached_tokens": cached})
        _obs.flight("engine", "replay", req=req.id, slot=slot,
                    tokens=n, cached=cached)

    # -------------------------------------------------------------- info
    def stats(self) -> dict:
        b = self.blocks
        spec = {"spec_k": self.spec_k,
                "verify_traces": self.runner.verify_traces}
        if self._spec is not None:
            spec.update(self._spec.snapshot())
        return {
            **spec,
            "queued": len(self.scheduler.queue),
            "active": self.scheduler.active_count,
            "pages_in_use": b.pages_in_use,
            "pages_total": b.num_pages,
            "decode_traces": self.decode_traces,
            "prefill_buckets": sorted(self._prefill_fns),
            "cached_prefill_buckets": sorted(self._prefill_cached_fns),
            "prefix_hits": b.prefix_hits,
            "prefix_misses": b.prefix_misses,
            "prefix_evictions": b.prefix_evictions,
            "cow_copies": b.cow_copies,
            "cached_tokens": b.cached_tokens,
            "cached_pages": b.cached_pages,
            "host_syncs": self.host_syncs,
            "logit_fetches": self.logit_fetches,
            "decode_steps": self.decode_steps,
            "pages_allocated": b.pages_allocated,
            "prefill_chunk": self.prefill_chunk,
            "prefill_chunks": self.prefill_chunks,
            "max_prefill_gap": self.max_prefill_gap,
            "preemptions": self.preemptions,
            "spill_aborts": self.spill_aborts,
            "spilled_pages": b.spilled_pages,
            "restored_pages": b.restored_pages,
            "spill_bytes": b.spill_bytes,
            "host_parked_pages": b.host_parked,
            "mesh_tp": self.tp,
            "quant": self.quant,
            "kv_quant": self.kv_quant,
            "lora": (self.lora.snapshot()
                     if self.lora is not None else None),
            "timings": {k: round(v, 6) for k, v in self.timings.items()},
            "progress": self.progress,
            "slo": self.slo.stats() if self.slo is not None else None,
            "recoveries": self.recoveries,
            "quarantines": self.quarantines,
            "replayed_requests": self.replayed_requests,
            "faults_injected": (dict(self.faults.injected)
                                if self.faults is not None else {}),
        }

    def _page_bytes(self, *, dense: bool = False) -> int:
        """Bytes one KV page pair (k + v, full heads) occupies — the
        unit every spill/restore moves.  Under ``kv_quant`` that is the
        int8 elements plus the per-(page-row, head) f32 scale rows;
        ``dense=True`` prices the same page at the checkpoint dtype
        (the savings baseline)."""
        cfg = self.config
        rows = (cfg.num_hidden_layers * cfg.num_key_value_heads
                * self.page_size)
        elems = rows * cfg.head_dim
        if self.kv_quant and not dense:
            return 2 * elems + 2 * rows * 4
        return 2 * elems * self._embed_itemsize

    def quant_snapshot(self) -> dict:
        """The ``quant.json`` side-file: what is quantized, the
        per-page byte math, and the spill-tier savings vs what the same
        traffic would have moved with dense pages."""
        b = self.blocks
        dense_page = self._page_bytes(dense=True)
        return {
            "weight_kind": self.quant or "dense",
            "kv_quant": self.kv_quant,
            "page_bytes": self._page_bytes(),
            "dense_page_bytes": dense_page,
            "spilled_pages": b.spilled_pages,
            "spill_bytes": b.spill_bytes,
            "spill_bytes_dense_estimate": b.spilled_pages * dense_page,
        }

    def lora_snapshot(self) -> dict:
        """The ``lora.json`` side-file: the adapter store's residency
        census plus the device bank footprint."""
        snap = self.lora.snapshot() if self.lora is not None else {}
        snap["bank_bytes_device"] = self.runner.lora_bank_bytes()
        return snap

    def resource_snapshot(self) -> dict:
        """Engine-local half of ``GET /debug/resources``: the exact
        pool census (live/cached/free with a leak check), per-resident-
        request page footprints, fragmentation against the queue head,
        per-mesh-device memory from the runner, and the phase timing
        breakdown.  The process-wide tracker snapshot (memory/compiles/
        goodput) complements it."""
        b = self.blocks
        head_need = None
        if self.scheduler.queue:
            head = self.scheduler.queue[0]
            head_need = b.pages_needed(head.prompt.size,
                                       head.gen.max_new_tokens)
        requests = {}
        for slot, req in enumerate(self.scheduler.slots):
            if req is not None:
                fp = b.seq_footprint(req.id)
                fp["slot"] = slot
                requests[str(req.id)] = fp
        pool = b.pool_accounting()
        pool["fragmentation_ratio"] = round(b.fragmentation(head_need), 6)
        return {
            "pool": pool,
            "requests": requests,
            "mesh": self.runner.mesh_info(),
            "lora": (self.lora_snapshot()
                     if self.lora is not None else None),
            "timings": {k: round(v, 6) for k, v in self.timings.items()},
            "counters": {
                "decode_steps": self.decode_steps,
                "decode_traces": self.decode_traces,
                "host_syncs": self.host_syncs,
                "logit_fetches": self.logit_fetches,
                "pages_allocated": b.pages_allocated,
                "recoveries": self.recoveries,
                "quarantines": self.quarantines,
                "prefill_chunks": self.prefill_chunks,
                "preemptions": self.preemptions,
                "spilled_pages": b.spilled_pages,
                "restored_pages": b.restored_pages,
            },
        }


def _softmax(x):
    x = x - np.max(x[np.isfinite(x)]) if np.isfinite(x).any() else x
    e = np.exp(np.where(np.isfinite(x), x, -np.inf))
    return e / e.sum()


def _pages_buckets(num_pages):
    """Integer page-count buckets spanning the pool (pages-in-use is a
    count, not a latency; the default ms-scale buckets would collapse)."""
    n = max(num_pages, 1)
    edges = sorted({max(1, round(n * f))
                    for f in (0.125, 0.25, 0.375, 0.5, 0.625, 0.75,
                              0.875, 1.0)})
    return tuple(float(e) for e in edges)


def create_engine(model, *, max_slots: int = 4, page_size: int = 64,
                  num_pages: int | None = None,
                  max_model_len: int | None = None,
                  emit_logits: bool = False,
                  enable_prefix_cache: bool = False,
                  sync_interval: int = 1, clock=time.monotonic,
                  slo=None, mesh=None,
                  spec_k: int | None = None,
                  prefill_chunk: int | None = None,
                  preempt: bool | None = None, faults=None,
                  usage=None, quant: str | None = None,
                  kv_quant: bool | None = None, lora=None,
                  requestlog=None) -> Engine:
    """`create_predictor`-style entry point: build a continuous-batching
    engine over a LlamaForCausalLM (or any model exposing ``config`` and
    ``functional_state()`` with the llama state-dict layout).

    ``enable_prefix_cache=True`` turns on automatic prefix caching:
    prompts sharing page-aligned prefixes reuse resident KV pages and
    prefill only their uncached suffix.  ``sync_interval=N`` lets the
    greedy decode loop run N device steps between host syncs (tokens
    stream out in bursts of N — lower sync overhead, higher streaming
    latency; sampling requests force per-step syncs regardless).

    ``spec_k=K`` (default ``FLAGS_serving_spec_k``) turns on
    speculative decoding: a host-side prompt-lookup (n-gram) drafter
    proposes up to K tokens per slot and one jitted verify step scores
    all K+1 positions, committing the longest matching prefix plus a
    correction token.  Greedy outputs are token-for-token identical to
    ``spec_k=0``; the win is tokens-per-step > 1 on repetitive text.

    ``prefill_chunk=N`` (default ``FLAGS_serving_prefill_chunk``)
    splits admission prefill into N-token chunks interleaved with
    decode steps — one long prompt can no longer stall every decoding
    slot's TPOT; greedy outputs are token-for-token identical to
    whole-prompt prefill.  ``preempt`` (default
    ``FLAGS_serving_preempt``) enables priority preempt-and-swap:
    when a higher-priority ``submit(..., priority=...)`` cannot be
    placed, the lowest-priority most-recently-admitted resident spills
    its KV to host RAM and re-queues for a parity-preserving resume.

    ``mesh`` selects the tensor-parallel mesh: an int / ``"tp=N"`` /
    1-tuple tp size (default: ``FLAGS_serving_mesh_tp``).  ``tp>1``
    shards attention heads, the FFN hidden dim, and the paged KV pool
    across the first N local devices; greedy outputs are token-exact
    against ``tp=1``.  For CPU testing export
    ``XLA_FLAGS=--xla_force_host_platform_device_count=8`` first.

    ``quant`` (default ``FLAGS_serving_quant``) turns on weight-only
    quantized serving: ``'int8'`` or ``'int4'`` converts the dense
    checkpoint at construction via
    :func:`paddle_tpu.serving.quantize_state` (per-projection matmul
    weights only; embeddings/norms/lm_head stay dense) and composes
    with any ``tp``.  ``kv_quant`` (default
    ``FLAGS_serving_kv_quant``) switches the paged KV pools to int8
    with per-(page-row, head) f32 scales — quantize-on-write inside
    the jitted step, dequant fused into the attention gather, and
    spill/restore moving the quantized bytes.  Both default off, and
    off means the dense programs are byte-identical to a build without
    these knobs; greedy outputs under quant match dense within a small
    token tolerance (pinned by the ``quant_decode`` perf-gate
    scenario).

    ``lora`` attaches a :class:`~paddle_tpu.serving.lora.AdapterStore`:
    the runner allocates a packed ``capacity + 1``-row adapter bank
    beside the base weights (row 0 stays zero — the no-adapter row),
    ``submit(..., adapter='name')`` pins the adapter's row for the
    request's lifetime, and every slot in the shared decode step
    gathers its own adapter's (A, B) pair — mixed-adapter batches run
    in the single jitted program.  ``lora=None`` (the default) passes
    empty pytrees through every program: the dense jaxprs are
    byte-identical to a build without the knob.

    ``requestlog`` attaches a
    :class:`~paddle_tpu.observability.requestlog.RequestLog` for
    tail-latency forensics: per-request lifecycle timelines whose
    critical-path attribution buckets sum exactly to the measured E2E,
    plus a worst-K SLO-violation exemplar reservoir (behind
    ``GET /debug/requests/<id>`` and ``GET /debug/exemplars``).
    ``requestlog=None`` (the default, or ``FLAGS_serving_request_log``
    unset under ``serve()``) records nothing and every seam costs one
    ``is not None`` test.

    Example::

        engine = create_engine(model, max_slots=8, page_size=64,
                               enable_prefix_cache=True, sync_interval=8)
        req = engine.submit([1, 2, 3], GenerationConfig(max_new_tokens=32))
        for tok in req.stream():
            ...
    """
    return Engine(model, max_slots=max_slots, page_size=page_size,
                  num_pages=num_pages, max_model_len=max_model_len,
                  emit_logits=emit_logits,
                  enable_prefix_cache=enable_prefix_cache,
                  sync_interval=sync_interval, clock=clock, slo=slo,
                  mesh=mesh, spec_k=spec_k, prefill_chunk=prefill_chunk,
                  preempt=preempt, faults=faults, usage=usage,
                  quant=quant, kv_quant=kv_quant, lora=lora,
                  requestlog=requestlog)
