"""Continuous-batching inference engine over the paged KV pool.

The design that turns the paged kernels into a serving system (Orca's
iteration-level scheduling over vLLM-style PagedAttention, mapped onto
the reference block_multi_head_attention serving path):

  * ONE jitted single-token decode step over a fixed number of decode
    slots and one shared page pool.  Slot occupancy, positions, and
    block tables are *data* (int32 arrays), never shapes — admitting or
    evicting a request between steps re-traces nothing.  The step
    reuses ``_decode_layer_paged`` from ``models/generation.py``
    verbatim, so engine numerics match the one-shot
    ``build_generate_fn_paged`` token for token under greedy decoding.
  * prefill-on-admit: an admitted request's prompt runs through
    ``_prefill_layer`` (padded to a page-multiple bucket; one trace per
    bucket) and pages its KV straight into the shared pool; the token
    sampled from the prompt's last logits is the request's first output
    (its TTFT mark).  With ``enable_prefix_cache=True`` the admission
    only reserves pages for (and prefills) the prompt's UNCACHED
    suffix: shared prefix pages come straight from the
    :class:`BlockManager` chain index, a matching partial tail page is
    copied (copy-on-write) on device, and the suffix runs through a
    cached-prefill jit that attends over the resident prefix KV.
  * device-resident decode state: ``table``/``pos``/``tok``, the active
    mask, and a ``[sync_interval, slots]`` sampled-token ring live on
    device and are donated through the step — a steady-state decode
    iteration uploads nothing and downloads nothing.  The host fetches
    the ring once every ``sync_interval`` steps (greedy path) and the
    ``[slots, V]`` logits only when an active request actually samples;
    admissions and evictions patch single slot rows in place.
  * idle slots park on the dump page (table row all-dump, pos 0): their
    lockstep writes land in scratch, their outputs are discarded
    host-side — no masking inside the program.

Sampling is host-side per request (greedy = argmax of the step's f32
logits, matching ``_sample``'s greedy branch exactly; stochastic
requests draw from a per-request numpy RNG so results do not depend on
batch composition).  Set ``emit_logits=True`` at engine construction to
serve ``do_sample`` requests — any active sampling request forces a
per-step sync (the host must feed the sampled token back before the
next step), so ``sync_interval`` only pays off on greedy traffic.
"""
from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from .. import observability as _obs
from ..flags import FLAGS
from ..observability.resources import record_compile, resource_tracker
from ..models.generation import (GenerationConfig, _decode_layer_paged,
                                 _layer_weights, _mm, _prefill_layer,
                                 _qkv_proj, _rope_at)
from ..models.llama import LlamaConfig, _rope_tables, _rotate_half
from ..models.llama_hybrid import _rms
from ..ops.pallas.paged_attention import gather_kv_pages
from .block_manager import BlockManager
from .request import Request, RequestState
from .scheduler import Scheduler

__all__ = ["Engine", "create_engine"]

_M_STEP_TRACES = _obs.counter(
    "serving_decode_step_traces_total",
    "decode-step jit traces — continuous batching keeps this at 1 per "
    "engine; growth means admissions are re-tracing")
_M_PREFILL_TRACES = _obs.counter(
    "serving_prefill_traces_total",
    "prefill jit traces (one per prompt-length bucket)", ("bucket",))
_M_STEPS = _obs.counter(
    "serving_decode_steps_total", "engine decode iterations")
_M_TOKENS = _obs.counter(
    "serving_tokens_total", "tokens emitted to requests")
_M_REQUESTS = _obs.counter(
    "serving_requests_total", "finished requests", ("outcome",))
_M_FINISH = _obs.counter(
    "serving_finish_total",
    "finished requests by finish_reason "
    "(length|eos|cancelled|deadline)", ("reason",))
_M_HOST_SYNCS = _obs.counter(
    "serving_host_syncs_total",
    "device->host transfers on the serving hot path: 'ring' = sampled-"
    "token ring fetch (one per sync_interval decode steps on the greedy "
    "path), 'logits' = [slots, V] logits fetch (only when an active "
    "request samples), 'prefill' = first-token logits at admission",
    ("kind",))
_M_PHASE_SECONDS = _obs.counter(
    "serving_step_phase_seconds_total",
    "engine wall seconds by phase: 'prefill' jit calls (incl. CoW "
    "copies), 'decode' step dispatch, 'host_sync' blocking ring "
    "fetches — the resource tracker's tokens/s and MFU denominator",
    ("phase",))


def _serving_hists():
    buckets = _obs.registry.SERVING_LATENCY_BUCKETS
    ttft = _obs.histogram(
        "serving_ttft_seconds", "request arrival -> first token",
        buckets=buckets)
    tpot = _obs.histogram(
        "serving_tpot_seconds", "inter-token latency during decode",
        buckets=buckets)
    e2e = _obs.histogram(
        "serving_e2e_seconds", "request arrival -> completion",
        buckets=buckets)
    return ttft, tpot, e2e


class Engine:
    """Drives admission, prefill, and the shared decode step.

    Static shapes (fixed at construction — the no-retrace contract):
    ``max_slots`` decode slots, ``table_width`` pages per sequence,
    ``num_pages (+ dump)`` pool rows, ``sync_interval`` ring rows, and
    the per-bucket prefill widths.  Everything per-request is data.
    """

    def __init__(self, model=None, *, config: LlamaConfig = None,
                 state: dict | None = None, max_slots: int = 4,
                 page_size: int = 64, num_pages: int | None = None,
                 max_model_len: int | None = None,
                 emit_logits: bool = False,
                 enable_prefix_cache: bool = False,
                 sync_interval: int = 1, clock=time.monotonic,
                 slo=None):
        if model is not None:
            from ..framework.tensor import Tensor
            config = model.config
            state = {k: (v._data if isinstance(v, Tensor) else v)
                     for k, v in model.functional_state().items()}
        if config is None or state is None:
            raise ValueError("pass a model, or both config= and state=")
        self.config = config
        self.state = state
        self.max_slots = int(max_slots)
        self.page_size = int(page_size)
        self.max_model_len = int(max_model_len
                                 or config.max_position_embeddings)
        if self.max_model_len > config.max_position_embeddings:
            raise ValueError(
                f"max_model_len {self.max_model_len} exceeds the model's "
                f"max_position_embeddings {config.max_position_embeddings}")
        self.table_width = -(-self.max_model_len // self.page_size)
        if num_pages is None:       # full residency: every slot can run
            num_pages = self.max_slots * self.table_width  # at max length
        self.emit_logits = bool(emit_logits)
        self.enable_prefix_cache = bool(enable_prefix_cache)
        self.sync_interval = int(sync_interval)
        if self.sync_interval < 1:
            raise ValueError(
                f"sync_interval must be >= 1, got {sync_interval}")
        self._clock = clock

        self.blocks = BlockManager(
            num_pages, self.page_size,
            enable_prefix_cache=self.enable_prefix_cache)
        self.scheduler = Scheduler(self.blocks, self.max_slots)
        self.scheduler._finalize = self._finalize
        # every eviction parks its slot — not just the length/eos path in
        # _emit.  A cancel/deadline eviction inside scheduler.schedule()
        # would otherwise leave the slot's table/pos pointing at freed
        # pages, and the lockstep decode step (which writes KV for every
        # slot) would corrupt them once reallocated to a new request.
        self.scheduler._on_evict = self._park

        L = config.num_hidden_layers
        kvh, hd = config.num_key_value_heads, config.head_dim
        dtype = state["llama.embed_tokens.weight"].dtype
        pool_rows = self.blocks.num_pages + 1        # + dump page
        self.kpool = jnp.zeros((L, pool_rows, kvh, self.page_size, hd),
                               dtype)
        self.vpool = jnp.zeros((L, pool_rows, kvh, self.page_size, hd),
                               dtype)
        self._rope_len = self.table_width * self.page_size
        cos, sin = _rope_tables(self._rope_len, hd, config.rope_theta)
        self._cos = cos.astype(jnp.float32)
        self._sin = sin.astype(jnp.float32)

        # host-side mirrors of the slot state (bookkeeping + targeted
        # device patches on admit/evict; NEVER re-uploaded per step)
        self.table = np.tile(self.blocks.empty_row(self.table_width),
                             (self.max_slots, 1))
        self._pos = np.zeros((self.max_slots,), np.int32)
        self._tok = np.zeros((self.max_slots,), np.int32)
        self._active = np.zeros((self.max_slots,), np.int32)
        # ... and the device-resident truth the decode step runs on
        self._table_dev = jnp.asarray(self.table)
        self._pos_dev = jnp.asarray(self._pos)
        self._tok_dev = jnp.asarray(self._tok)
        self._active_dev = jnp.asarray(self._active)
        self._ring_dev = jnp.zeros((self.sync_interval, self.max_slots),
                                   jnp.int32)
        self._ridx_dev = jnp.zeros((), jnp.int32)
        self._ring_cursor = 0           # host mirror of _ridx_dev
        # ring rows the host has not consumed yet:
        # [(ring row, [(slot, request), ...]), ...] in decode order
        self._pending: list[tuple[int, list]] = []
        self._last_logits = None        # device handle, fetched lazily

        self.decode_traces = 0      # python-side mirror of _M_STEP_TRACES
        self.decode_steps = 0       # mirror of serving_decode_steps_total
        self.host_syncs = 0         # ring fetches (1 per sync_interval)
        self.logit_fetches = 0      # [slots, V] transfers (sampling only)
        # per-phase wall seconds (mirror of serving_step_phase_seconds_
        # total; resource_snapshot() reports them per engine)
        self.timings = {"prefill_s": 0.0, "decode_s": 0.0,
                        "host_sync_s": 0.0}
        # monotonically increasing iteration counter.  The serving
        # watchdog reads it lock-free (comparing against active_count)
        # to detect a wedged decode loop — never reset.
        self.progress = 0
        self.slo = slo              # optional slo.SLOTracker
        # open "engine.decode_segment" span covering the device steps
        # since the last host sync (None between segments)
        self._seg_span = None
        self._seg_steps = 0
        self._rngs: dict[int, np.random.Generator] = {}
        self._ttft, self._tpot, self._e2e = _serving_hists()
        self._pages_hist = _obs.histogram(
            "serving_pages_in_use_hist",
            "pages-in-use sampled at each decode step",
            buckets=_pages_buckets(self.blocks.num_pages))

        # donate everything the step rewrites: pools, pos/tok, the ring
        # and its cursor — steady-state decode double-buffers nothing
        self._step_fn = jax.jit(self._build_step(),
                                donate_argnums=(1, 2, 4, 5, 7, 8))
        self._prefill_fns: dict[int, object] = {}   # bucket -> jitted fn
        self._prefill_cached_fns: dict[int, object] = {}
        # CoW page copy: src/dst are data — one trace for the engine
        self._copy_page_fn = jax.jit(
            lambda kp, vp, src, dst: (kp.at[:, dst].set(kp[:, src]),
                                      vp.at[:, dst].set(vp[:, src])),
            donate_argnums=(0, 1))
        self._copy_page_compiled = False    # compile-ledger first-call

        # resource tracker: model size + device kind feed the MFU
        # estimate (tokens/s * 2 * n_params / peak_flops)
        n_params = sum(int(np.prod(v.shape))
                       for v in state.values() if hasattr(v, "shape"))
        try:
            device_kind = jax.devices()[0].device_kind
        except Exception:
            device_kind = None
        resource_tracker().set_model(n_params=n_params,
                                     device_kind=device_kind)

    # ------------------------------------------------------ jitted bodies
    def _build_step(self):
        cfg = self.config
        L = cfg.num_hidden_layers
        emit_logits = self.emit_logits
        rope_len = self._rope_len
        engine = self

        def step(state, kpool, vpool, table, pos, tok, active, ring,
                 ridx, cos, sin):
            # python body runs at trace time only: a second execution of
            # this line means an admission/eviction re-traced the step
            engine.decode_traces += 1
            _M_STEP_TRACES.inc()
            # a finished slot keeps decoding until the next host sync
            # (deferred-sync overrun); clamp so its rope/table lookups
            # stay in range — overrun writes land in the slot's own
            # reserved tail or the dump page, never another sequence
            posc = jnp.minimum(pos, rope_len - 1)
            emb = jnp.take(state["llama.embed_tokens.weight"], tok, axis=0)
            cos1, sin1 = _rope_at(cos, sin, posc)
            h = emb
            kps, vps = [], []
            for i in range(L):
                w = _layer_weights(state, i)
                h, kp_, vp_ = _decode_layer_paged(
                    w, h, kpool[i], vpool[i], table, cos1, sin1, posc, cfg)
                kps.append(kp_)
                vps.append(vp_)
            kpool = jnp.stack(kps)
            vpool = jnp.stack(vps)
            h = _rms(h[:, None], state["llama.norm.weight"],
                     cfg.rms_norm_eps)[:, 0]
            logits = _logits_of(state, h).astype(jnp.float32)
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            act = active.astype(bool)
            pos2 = pos + active                 # idle slots stay parked
            tok2 = jnp.where(act, nxt, tok)     # greedy chains on device
            ring2 = ring.at[ridx].set(nxt)
            ridx2 = (ridx + 1) % ring.shape[0]
            return (kpool, vpool, pos2, tok2, ring2, ridx2,
                    logits if emit_logits else jnp.zeros((), jnp.float32))

        return step

    def _prefill_fn(self, bucket: int):
        fn = self._prefill_fns.get(bucket)
        if fn is not None:
            return fn
        cfg = self.config
        L = cfg.num_hidden_layers
        ps = self.page_size
        n_pages = bucket // ps

        def prefill(state, ids, length, table_row, kpool, vpool, cos, sin):
            _M_PREFILL_TRACES.labels(str(bucket)).inc()
            x = jnp.take(state["llama.embed_tokens.weight"], ids, axis=0)
            pmask = jnp.arange(bucket)[None, :] < length
            for i in range(L):
                w = _layer_weights(state, i)
                x, k, v = _prefill_layer(w, x, cos[:bucket], sin[:bucket],
                                         pmask, cfg)
                for p in range(n_pages):
                    rows_k = k[0, p * ps:(p + 1) * ps].swapaxes(0, 1)
                    rows_v = v[0, p * ps:(p + 1) * ps].swapaxes(0, 1)
                    kpool = kpool.at[i, table_row[p]].set(rows_k)
                    vpool = vpool.at[i, table_row[p]].set(rows_v)
            x = _rms(x, state["llama.norm.weight"], cfg.rms_norm_eps)
            last = jnp.take_along_axis(
                x, (length - 1)[:, None, None].astype(jnp.int32),
                axis=1)[:, 0]
            logits = _logits_of(state, last).astype(jnp.float32)
            return kpool, vpool, logits

        # kpool/vpool donation: prefill updates the pool in place instead
        # of double-buffering the engine's whole KV footprint per admit
        fn = jax.jit(prefill, donate_argnums=(4, 5))
        self._prefill_fns[bucket] = fn
        return fn

    def _prefill_cached_fn(self, bucket: int):
        """Suffix prefill for a prompt whose first ``cached_len`` tokens
        are already resident in the pool (shared prefix pages and/or a
        CoW-copied tail).  One trace per suffix bucket: the prefix
        length, table row, and positions are all data."""
        fn = self._prefill_cached_fns.get(bucket)
        if fn is not None:
            return fn
        cfg = self.config
        L = cfg.num_hidden_layers
        kvh = cfg.num_key_value_heads
        ps = self.page_size
        W = self.table_width
        dump = self.blocks.dump_page
        rope_len = self._rope_len

        def prefill(state, ids, length, cached_len, row, kpool, vpool,
                    cos, sin):
            _M_PREFILL_TRACES.labels(f"cached:{bucket}").inc()
            x = jnp.take(state["llama.embed_tokens.weight"], ids, axis=0)
            j = jnp.arange(bucket)
            absp = cached_len + j               # absolute positions
            posc = jnp.minimum(absp, rope_len - 1)
            cos_s = jnp.take(cos, posc, axis=0)
            sin_s = jnp.take(sin, posc, axis=0)
            # suffix queries see: resident prefix keys (< cached_len),
            # then causal within the (padded) suffix
            t_pre = jnp.arange(W * ps)
            pre_ok = jnp.broadcast_to(t_pre[None, :] < cached_len,
                                      (bucket, W * ps))
            suf_ok = (j[None, :] <= j[:, None]) & (j[None, :] < length[0])
            mask = jnp.concatenate([pre_ok, suf_ok], axis=1)[None, None]
            # per-token write targets (padding lands on the dump page)
            valid = j < length[0]
            page_w = jnp.where(valid,
                               row[jnp.minimum(absp // ps, W - 1)], dump)
            off = absp % ps
            heads = jnp.arange(kvh)
            for i in range(L):
                w = _layer_weights(state, i)
                kpre = gather_kv_pages(kpool[i], row)
                vpre = gather_kv_pages(vpool[i], row)
                x, k, v = _prefill_layer_cached(
                    w, x, kpre[None], vpre[None], cos_s, sin_s, mask, cfg)
                kpool = kpool.at[i, page_w[:, None], heads[None, :],
                                 off[:, None]].set(k[0])
                vpool = vpool.at[i, page_w[:, None], heads[None, :],
                                 off[:, None]].set(v[0])
            x = _rms(x, state["llama.norm.weight"], cfg.rms_norm_eps)
            last = jnp.take_along_axis(
                x, (length - 1)[:, None, None].astype(jnp.int32),
                axis=1)[:, 0]
            logits = _logits_of(state, last).astype(jnp.float32)
            return kpool, vpool, logits

        fn = jax.jit(prefill, donate_argnums=(5, 6))
        self._prefill_cached_fns[bucket] = fn
        return fn

    # ----------------------------------------------------------- intake
    def submit(self, prompt, gen: GenerationConfig | None = None, *,
               deadline: float | None = None, on_token=None,
               arrival_time: float | None = None, trace=None) -> Request:
        """``trace`` is an optional tracing.SpanContext (or Span) the
        request's root span is parented under — the server passes the
        extracted ``traceparent`` here so the engine-side spans join the
        caller's distributed trace.  Without it the root span inherits
        the submitting thread's current span, if any."""
        req = Request(prompt, gen, deadline=deadline, on_token=on_token,
                      arrival_time=(self._clock() if arrival_time is None
                                    else arrival_time))
        total = req.prompt.size + req.gen.max_new_tokens
        if total > self.max_model_len:
            raise ValueError(
                f"prompt ({req.prompt.size}) + max_new_tokens "
                f"({req.gen.max_new_tokens}) = {total} exceeds "
                f"max_model_len {self.max_model_len}")
        need = self.blocks.pages_needed(req.prompt.size,
                                        req.gen.max_new_tokens)
        if need > self.blocks.num_pages:
            raise ValueError(
                f"request needs {need} KV pages but the pool only has "
                f"{self.blocks.num_pages}; it could never be admitted "
                "(raise num_pages or lower max_new_tokens)")
        if req.gen.do_sample and not self.emit_logits:
            raise ValueError(
                "do_sample requests need an engine built with "
                "emit_logits=True (host-side sampling reads the logits)")
        req._engine = self
        # spans only after every validation — a rejected submit must not
        # leave dangling open spans
        tr = _obs.tracer()
        attrs = {"req": req.id, "prompt_len": int(req.prompt.size),
                 "max_new_tokens": int(req.gen.max_new_tokens)}
        req.trace_parent = trace
        if trace is not None:
            req.root_span = tr.start_span("request", parent=trace,
                                          attributes=attrs)
        else:
            req.root_span = tr.start_span("request", attributes=attrs)
        req.queue_span = tr.start_span("scheduler.queue_wait",
                                       parent=req.root_span)
        _obs.flight("engine", "submit", req=req.id,
                    prompt_len=int(req.prompt.size),
                    trace=req.root_span.trace_id)
        self.scheduler.submit(req)
        return req

    # -------------------------------------------------------- main loop
    def step(self) -> bool:
        """One engine iteration: evict/admit (scheduler pass), prefill
        admissions, then one lockstep decode step over the active slots.
        Returns whether any work happened."""
        now = self._clock()
        admitted = self.scheduler.schedule(now)
        for slot, req in admitted:
            self._prefill(slot, req)
        active = [i for i, r in enumerate(self.scheduler.slots)
                  if r is not None and r.state == RequestState.DECODE]
        if active:
            self._decode(active)
        self.progress += 1          # watchdog heartbeat
        return bool(admitted) or bool(active)

    def run_until_complete(self, max_steps: int | None = None):
        """Drive step() until no live or queued work remains."""
        steps = 0
        while self.scheduler.has_work():
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                raise RuntimeError(
                    f"engine did not quiesce within {max_steps} steps")

    def drain(self):
        """Graceful drain: stop admitting; finish what is running.
        Queued requests stay queued until :meth:`resume`."""
        self.scheduler.drain()
        while self.scheduler.active_count:
            self.step()

    def resume(self):
        self.scheduler.resume()

    # ----------------------------------------------------------- prefill
    def _prefill(self, slot: int, req: Request):
        if req.queue_span is not None:      # queue wait ends at admission
            req.queue_span.end()
            req.queue_span = None
        t0 = time.perf_counter()
        ps = self.page_size
        plen = req.prompt.size
        meta = self.blocks.seq_meta(req.id)
        cached = int(meta["cached_len"])
        row = self.blocks.table_row(req.id, self.table_width)
        if meta["cow_src"] is not None:
            # copy-on-write: duplicate the matching tail page into this
            # request's own tail before any of its writes land there
            cow_fresh = not self._copy_page_compiled
            cow_t0 = time.perf_counter()
            self.kpool, self.vpool = self._copy_page_fn(
                self.kpool, self.vpool,
                jnp.asarray(meta["cow_src"], jnp.int32),
                jnp.asarray(int(row[cached // ps]), jnp.int32))
            if cow_fresh:
                self._copy_page_compiled = True
                record_compile("copy_page", cow_t0,
                               signature=f"pool={self.kpool.shape}")
        if cached == 0:
            bucket = -(-plen // ps) * ps
            ids = np.zeros((1, bucket), np.int32)
            ids[0, :plen] = req.prompt
            jit_fresh = bucket not in self._prefill_fns
            fn = self._prefill_fn(bucket)
            jit_t0 = time.perf_counter()
            self.kpool, self.vpool, logits = fn(
                self.state, jnp.asarray(ids),
                jnp.asarray([plen], jnp.int32),
                jnp.asarray(row[:bucket // ps]),
                self.kpool, self.vpool, self._cos, self._sin)
            if jit_fresh:
                record_compile(f"prefill[{bucket}]", jit_t0,
                               signature=f"ids=[1,{bucket}]")
        else:
            suffix = plen - cached
            bucket = -(-suffix // ps) * ps
            ids = np.zeros((1, bucket), np.int32)
            ids[0, :suffix] = req.prompt[cached:]
            jit_fresh = bucket not in self._prefill_cached_fns
            fn = self._prefill_cached_fn(bucket)
            jit_t0 = time.perf_counter()
            self.kpool, self.vpool, logits = fn(
                self.state, jnp.asarray(ids),
                jnp.asarray([suffix], jnp.int32),
                jnp.asarray(cached, jnp.int32), jnp.asarray(row),
                self.kpool, self.vpool, self._cos, self._sin)
            if jit_fresh:
                record_compile(f"prefill_cached[{bucket}]", jit_t0,
                               signature=f"ids=[1,{bucket}]")
        req.num_cached_tokens = cached
        _M_HOST_SYNCS.labels("prefill").inc()
        tok = self._pick_token(req, np.asarray(logits)[0])
        now = self._clock()
        self._ttft.observe(now - req.arrival_time)
        self._note_phase("prefill", time.perf_counter() - t0)
        _obs.tracer().record_span(
            "engine.prefill", t0, time.perf_counter(),
            parent=req.root_span,
            attributes={"req": req.id, "slot": slot, "bucket": bucket,
                        "cached_tokens": cached,
                        "kind": "cached_suffix" if cached else "full",
                        "cow": meta["cow_src"] is not None})
        if req.root_span is not None:
            req.decode_span = _obs.tracer().start_span(
                "engine.decode", parent=req.root_span,
                attributes={"req": req.id, "slot": slot})
        _obs.flight("engine", "prefill", req=req.id, slot=slot,
                    bucket=bucket, cached=cached)
        self.table[slot] = row
        self._pos[slot] = plen
        self._tok[slot] = tok
        self._active[slot] = 1
        self._push_slot(slot)
        req.state = RequestState.DECODE
        self._emit(slot, req, tok, now)

    # ------------------------------------------------------------ decode
    def _decode(self, active: list[int]):
        if self._seg_span is None:
            # one span per host-sync interval, NOT per device step —
            # segments are the engine's visible unit of decode work
            self._seg_span = _obs.tracer().start_span(
                "engine.decode_segment", parent=None,
                attributes={"slots": len(active)})
            self._seg_steps = 0
        self._seg_steps += 1
        reqs = [(s, self.scheduler.slots[s]) for s in active]
        traces_before = self.decode_traces
        step_t0 = time.perf_counter()
        (self.kpool, self.vpool, self._pos_dev, self._tok_dev,
         self._ring_dev, self._ridx_dev, logits) = self._step_fn(
            self.state, self.kpool, self.vpool, self._table_dev,
            self._pos_dev, self._tok_dev, self._active_dev,
            self._ring_dev, self._ridx_dev, self._cos, self._sin)
        if self.decode_traces != traces_before:
            record_compile(
                "decode_step", step_t0,
                signature=f"slots={self.max_slots} "
                          f"ring={self.sync_interval}")
        self._note_phase("decode", time.perf_counter() - step_t0)
        self.decode_steps += 1
        _M_STEPS.inc()
        self._pages_hist.observe(self.blocks.pages_in_use)
        for slot in active:
            self._pos[slot] += 1            # mirror of pos + active
        self._pending.append((self._ring_cursor, reqs))
        self._ring_cursor = (self._ring_cursor + 1) % self.sync_interval
        self._last_logits = logits if self.emit_logits else None
        # any active sampling request needs its token fed back before
        # the next step, so sampling degrades to a per-step sync
        eff = 1 if any(r.gen.do_sample for _, r in reqs) \
            else self.sync_interval
        if len(self._pending) >= eff:
            self._sync()

    def _sync(self):
        """Drain the device token ring: ONE [sync_interval, slots] int32
        transfer covers every decode step since the previous sync."""
        sync_t0 = time.perf_counter()
        ring = np.asarray(self._ring_dev)
        sync_s = time.perf_counter() - sync_t0
        self.host_syncs += 1
        self._note_phase("host_sync", sync_s)
        _M_HOST_SYNCS.labels("ring").inc()
        poll = int(FLAGS.get("FLAGS_resource_memory_poll_steps") or 0)
        if poll > 0 and self.host_syncs % poll == 0:
            resource_tracker().sample_memory()
        if self._seg_span is not None:
            # the ring fetch above blocked on the device — the segment
            # span ends here, covering dispatch through host sync
            self._seg_span.set_attribute("steps", self._seg_steps)
            self._seg_span.end()
            self._seg_span = None
        _obs.flight("engine", "host_sync", rows=len(self._pending),
                    steps=self._seg_steps, sync_s=round(sync_s, 6))
        sample_t0 = None
        logits_np = None
        now = self._clock()
        n_rows = len(self._pending)
        corrections = []
        for row_i, (ridx, entries) in enumerate(self._pending):
            for slot, req in entries:
                if req.is_finished() or req.state != RequestState.DECODE:
                    continue        # evicted/finished: overrun discarded
                tok = int(ring[ridx, slot])
                if req.gen.do_sample:
                    # sampling rows only exist under eff-interval 1, so
                    # the step's logits handle is always the right row
                    if logits_np is None:
                        sample_t0 = time.perf_counter()
                        logits_np = np.asarray(self._last_logits)
                        self.logit_fetches += 1
                        _M_HOST_SYNCS.labels("logits").inc()
                    tok = self._pick_token(req, logits_np[slot])
                    if tok != int(ring[ridx, slot]):
                        corrections.append((slot, tok))
                prev = req.last_token_at
                if prev is not None:
                    # batched sync: spread the interval over the tokens
                    # it covers so TPOT keeps per-token semantics
                    self._tpot.observe((now - prev) / (n_rows - row_i))
                self._tok[slot] = tok
                self._emit(slot, req, tok, now)
        self._pending.clear()
        if sample_t0 is not None:
            # host-side sampling for this sync: logits fetch + per-
            # request pick (argmax/top-k/top-p) + any device feedback
            _obs.tracer().record_span(
                "engine.sample", sample_t0, time.perf_counter(),
                attributes={"corrections": len(corrections)})
        if corrections:
            idx = jnp.asarray([s for s, _ in corrections], jnp.int32)
            val = jnp.asarray([t for _, t in corrections], jnp.int32)
            self._tok_dev = self._tok_dev.at[idx].set(val)

    def _note_phase(self, phase: str, seconds: float):
        """Charge engine wall time to a phase: the per-engine mirror,
        the serving_step_phase_seconds_total counter, and the process
        tracker's throughput denominator."""
        seconds = max(float(seconds), 0.0)
        self.timings[phase + "_s"] += seconds
        _M_PHASE_SECONDS.labels(phase).inc(seconds)
        resource_tracker().note_phase(phase, seconds)

    def _emit(self, slot: int, req: Request, tok: int, now: float):
        req._emit(tok, now)
        _M_TOKENS.inc()
        resource_tracker().note_tokens(1)
        eos = req.gen.eos_token_id
        if req.num_generated >= req.gen.max_new_tokens:
            self._finalize(req, "length", now)
            self.scheduler.evict(slot, "finished", now)
        elif eos is not None and tok == eos:
            self._finalize(req, "eos", now)
            self.scheduler.evict(slot, "finished", now)

    def _park(self, slot: int):
        """Return a slot to the idle state: all writes/reads go to the
        dump page until the next admission."""
        self.table[slot] = self.blocks.empty_row(self.table_width)
        self._pos[slot] = 0
        self._tok[slot] = 0
        self._active[slot] = 0
        self._push_slot(slot)

    def _push_slot(self, slot: int):
        """Patch ONE slot's row of the device-resident decode state from
        the host mirrors (admission / eviction only — never per step)."""
        self._table_dev = self._table_dev.at[slot].set(
            jnp.asarray(self.table[slot]))
        self._pos_dev = self._pos_dev.at[slot].set(int(self._pos[slot]))
        self._tok_dev = self._tok_dev.at[slot].set(int(self._tok[slot]))
        self._active_dev = self._active_dev.at[slot].set(
            int(self._active[slot]))

    # --------------------------------------------------------- sampling
    def _pick_token(self, req: Request, logits: np.ndarray) -> int:
        g = req.gen
        if not g.do_sample:
            return int(np.argmax(logits))
        rng = self._rngs.get(req.id)
        if rng is None:
            rng = self._rngs[req.id] = np.random.default_rng(
                (g.seed, req.id))
        logits = logits.astype(np.float64)
        if g.temperature != 1.0:
            logits = logits / max(g.temperature, 1e-6)
        if g.top_k and g.top_k > 0:
            k = min(g.top_k, logits.size)
            kth = np.sort(logits)[-k]
            logits = np.where(logits < kth, -np.inf, logits)
        if g.top_p < 1.0:
            order = np.argsort(logits)[::-1]
            probs = _softmax(logits[order])
            cum = np.cumsum(probs)
            cutoff_idx = int(np.sum(cum < g.top_p))
            cutoff = logits[order[min(cutoff_idx, logits.size - 1)]]
            logits = np.where(logits < cutoff, -np.inf, logits)
        if not np.isfinite(logits).any():
            raise ValueError(
                f"request {req.id}: no finite logits to sample from — "
                "the model emitted non-finite logits (or top_k/top_p "
                "masked every candidate)")
        return int(rng.choice(logits.size, p=_softmax(logits)))

    # -------------------------------------------------------- lifecycle
    def _finalize(self, req: Request, reason: str, now: float):
        if req.is_finished():
            return
        req.finish_reason = reason
        req.state = RequestState.CANCELLED \
            if reason in ("cancelled", "deadline") else RequestState.DONE
        req.finished_at = now
        self._rngs.pop(req.id, None)
        self._e2e.observe(now - req.arrival_time)
        _M_REQUESTS.labels(reason).inc()
        _M_FINISH.labels(reason).inc()
        resource_tracker().note_finish(reason, req.num_generated)
        if self.slo is not None:
            self.slo.observe(req, now)
        _obs.flight("engine", "finish", req=req.id, reason=reason,
                    generated=req.num_generated)
        if req.queue_span is not None:      # dropped while still queued
            req.queue_span.set_attribute("dropped", True)
            req.queue_span.end()
            req.queue_span = None
        if req.decode_span is not None:
            req.decode_span.set_attribute("generated", req.num_generated)
            req.decode_span.end()
            req.decode_span = None
        if req.root_span is not None:
            rs = req.root_span
            rs.set_attribute("finish_reason", reason)
            rs.set_attribute("generated", req.num_generated)
            rs.set_attribute("cached_tokens", req.num_cached_tokens)
            if reason == "deadline" and req.deadline is not None:
                # how far past its deadline the request was when the
                # scheduler finally evicted it (engine clock)
                rs.set_attribute("deadline_overrun_s",
                                 round(now - req.deadline, 6))
            rs.end()

    # -------------------------------------------------------------- info
    def stats(self) -> dict:
        b = self.blocks
        return {
            "queued": len(self.scheduler.queue),
            "active": self.scheduler.active_count,
            "pages_in_use": b.pages_in_use,
            "pages_total": b.num_pages,
            "decode_traces": self.decode_traces,
            "prefill_buckets": sorted(self._prefill_fns),
            "cached_prefill_buckets": sorted(self._prefill_cached_fns),
            "prefix_hits": b.prefix_hits,
            "prefix_misses": b.prefix_misses,
            "prefix_evictions": b.prefix_evictions,
            "cow_copies": b.cow_copies,
            "cached_tokens": b.cached_tokens,
            "cached_pages": b.cached_pages,
            "host_syncs": self.host_syncs,
            "logit_fetches": self.logit_fetches,
            "decode_steps": self.decode_steps,
            "pages_allocated": b.pages_allocated,
            "timings": {k: round(v, 6) for k, v in self.timings.items()},
            "progress": self.progress,
            "slo": self.slo.stats() if self.slo is not None else None,
        }

    def resource_snapshot(self) -> dict:
        """Engine-local half of ``GET /debug/resources``: the exact
        pool census (live/cached/free with a leak check), per-resident-
        request page footprints, fragmentation against the queue head,
        and the phase timing breakdown.  The process-wide tracker
        snapshot (memory/compiles/goodput) complements it."""
        b = self.blocks
        head_need = None
        if self.scheduler.queue:
            head = self.scheduler.queue[0]
            head_need = b.pages_needed(head.prompt.size,
                                       head.gen.max_new_tokens)
        requests = {}
        for slot, req in enumerate(self.scheduler.slots):
            if req is not None:
                fp = b.seq_footprint(req.id)
                fp["slot"] = slot
                requests[str(req.id)] = fp
        pool = b.pool_accounting()
        pool["fragmentation_ratio"] = round(b.fragmentation(head_need), 6)
        return {
            "pool": pool,
            "requests": requests,
            "timings": {k: round(v, 6) for k, v in self.timings.items()},
            "counters": {
                "decode_steps": self.decode_steps,
                "decode_traces": self.decode_traces,
                "host_syncs": self.host_syncs,
                "logit_fetches": self.logit_fetches,
                "pages_allocated": b.pages_allocated,
            },
        }


def _prefill_layer_cached(w, x, kpre, vpre, cos_s, sin_s, mask,
                          cfg: LlamaConfig):
    """One transformer layer of suffix prefill against a resident
    prefix: ``x`` [1, S, H] suffix hidden, ``kpre``/``vpre``
    [1, Tpre, kvH, D] prefix KV gathered from the pool (keys already
    rotary-encoded at their absolute positions, exactly as prefill and
    decode wrote them), ``mask`` [1, 1, S, Tpre+S] bool.  Returns
    (out, k_suffix, v_suffix) — mirror of ``_prefill_layer``."""
    b, s, _ = x.shape
    nh, kvh, hd = (cfg.num_attention_heads, cfg.num_key_value_heads,
                   cfg.head_dim)
    h = _rms(x, w["ln1"], cfg.rms_norm_eps)
    qp, kp, vp = _qkv_proj(w, h, nh, kvh, hd)
    q = qp.reshape(b, s, nh, hd)
    k = kp.reshape(b, s, kvh, hd)
    v = vp.reshape(b, s, kvh, hd)
    cos_c = cos_s[None, :, None, :].astype(q.dtype)
    sin_c = sin_s[None, :, None, :].astype(q.dtype)
    q = q * cos_c + _rotate_half(q) * sin_c
    k = k * cos_c + _rotate_half(k) * sin_c

    from ..ops.pallas.flash_attention import sdpa
    kcat = jnp.concatenate([kpre.astype(k.dtype), k], axis=1)
    vcat = jnp.concatenate([vpre.astype(v.dtype), v], axis=1)
    attn = sdpa(q, kcat, vcat, attn_mask=mask,
                is_causal=False).reshape(b, s, nh * hd)
    x = x + _mm(attn, w["o"])
    h = _rms(x, w["ln2"], cfg.rms_norm_eps)
    from ..models.generation import _ffn
    return (x + _ffn(w, h), k, v)


def _softmax(x):
    x = x - np.max(x[np.isfinite(x)]) if np.isfinite(x).any() else x
    e = np.exp(np.where(np.isfinite(x), x, -np.inf))
    return e / e.sum()


def _logits_of(state, h):
    head = state.get("lm_head.weight")
    if head is not None:
        return _mm(h, head)
    return h @ state["llama.embed_tokens.weight"].T


def _pages_buckets(num_pages):
    """Integer page-count buckets spanning the pool (pages-in-use is a
    count, not a latency; the default ms-scale buckets would collapse)."""
    n = max(num_pages, 1)
    edges = sorted({max(1, round(n * f))
                    for f in (0.125, 0.25, 0.375, 0.5, 0.625, 0.75,
                              0.875, 1.0)})
    return tuple(float(e) for e in edges)


def create_engine(model, *, max_slots: int = 4, page_size: int = 64,
                  num_pages: int | None = None,
                  max_model_len: int | None = None,
                  emit_logits: bool = False,
                  enable_prefix_cache: bool = False,
                  sync_interval: int = 1, clock=time.monotonic,
                  slo=None) -> Engine:
    """`create_predictor`-style entry point: build a continuous-batching
    engine over a LlamaForCausalLM (or any model exposing ``config`` and
    ``functional_state()`` with the llama state-dict layout).

    ``enable_prefix_cache=True`` turns on automatic prefix caching:
    prompts sharing page-aligned prefixes reuse resident KV pages and
    prefill only their uncached suffix.  ``sync_interval=N`` lets the
    greedy decode loop run N device steps between host syncs (tokens
    stream out in bursts of N — lower sync overhead, higher streaming
    latency; sampling requests force per-step syncs regardless).

    Example::

        engine = create_engine(model, max_slots=8, page_size=64,
                               enable_prefix_cache=True, sync_interval=8)
        req = engine.submit([1, 2, 3], GenerationConfig(max_new_tokens=32))
        for tok in req.stream():
            ...
    """
    return Engine(model, max_slots=max_slots, page_size=page_size,
                  num_pages=num_pages, max_model_len=max_model_len,
                  emit_logits=emit_logits,
                  enable_prefix_cache=enable_prefix_cache,
                  sync_interval=sync_interval, clock=clock, slo=slo)
