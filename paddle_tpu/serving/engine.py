"""Continuous-batching inference engine over the paged KV pool.

The design that turns the paged kernels into a serving system (Orca's
iteration-level scheduling over vLLM-style PagedAttention, mapped onto
the reference block_multi_head_attention serving path):

  * ONE jitted single-token decode step over a fixed number of decode
    slots and one shared page pool.  Slot occupancy, positions, and
    block tables are *data* (int32 arrays), never shapes — admitting or
    evicting a request between steps re-traces nothing.  The step
    reuses ``_decode_layer_paged`` from ``models/generation.py``
    verbatim, so engine numerics match the one-shot
    ``build_generate_fn_paged`` token for token under greedy decoding.
  * prefill-on-admit: an admitted request's prompt runs through
    ``_prefill_layer`` (padded to a page-multiple bucket; one trace per
    bucket) and pages its KV straight into the shared pool; the token
    sampled from the prompt's last logits is the request's first output
    (its TTFT mark).
  * idle slots park on the dump page (table row all-dump, pos 0): their
    lockstep writes land in scratch, their outputs are discarded
    host-side — no masking inside the program.

Sampling is host-side per request (greedy = argmax of the step's f32
logits, matching ``_sample``'s greedy branch exactly; stochastic
requests draw from a per-request numpy RNG so results do not depend on
batch composition).  Set ``emit_logits=True`` at engine construction to
serve ``do_sample`` requests — the step then returns the [slots, V]
logits each iteration.
"""
from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from .. import observability as _obs
from ..models.generation import (GenerationConfig, _decode_layer_paged,
                                 _layer_weights, _mm, _prefill_layer,
                                 _rope_at)
from ..models.llama import LlamaConfig, _rope_tables
from ..models.llama_hybrid import _rms
from .block_manager import BlockManager
from .request import Request, RequestState
from .scheduler import Scheduler

__all__ = ["Engine", "create_engine"]

_M_STEP_TRACES = _obs.counter(
    "serving_decode_step_traces_total",
    "decode-step jit traces — continuous batching keeps this at 1 per "
    "engine; growth means admissions are re-tracing")
_M_PREFILL_TRACES = _obs.counter(
    "serving_prefill_traces_total",
    "prefill jit traces (one per prompt-length bucket)", ("bucket",))
_M_STEPS = _obs.counter(
    "serving_decode_steps_total", "engine decode iterations")
_M_TOKENS = _obs.counter(
    "serving_tokens_total", "tokens emitted to requests")
_M_REQUESTS = _obs.counter(
    "serving_requests_total", "finished requests", ("outcome",))


def _serving_hists():
    buckets = _obs.registry.SERVING_LATENCY_BUCKETS
    ttft = _obs.histogram(
        "serving_ttft_seconds", "request arrival -> first token",
        buckets=buckets)
    tpot = _obs.histogram(
        "serving_tpot_seconds", "inter-token latency during decode",
        buckets=buckets)
    e2e = _obs.histogram(
        "serving_e2e_seconds", "request arrival -> completion",
        buckets=buckets)
    return ttft, tpot, e2e


class Engine:
    """Drives admission, prefill, and the shared decode step.

    Static shapes (fixed at construction — the no-retrace contract):
    ``max_slots`` decode slots, ``table_width`` pages per sequence,
    ``num_pages (+ dump)`` pool rows, and the per-bucket prefill widths.
    Everything per-request is data.
    """

    def __init__(self, model=None, *, config: LlamaConfig = None,
                 state: dict | None = None, max_slots: int = 4,
                 page_size: int = 64, num_pages: int | None = None,
                 max_model_len: int | None = None,
                 emit_logits: bool = False, clock=time.monotonic):
        if model is not None:
            from ..framework.tensor import Tensor
            config = model.config
            state = {k: (v._data if isinstance(v, Tensor) else v)
                     for k, v in model.functional_state().items()}
        if config is None or state is None:
            raise ValueError("pass a model, or both config= and state=")
        self.config = config
        self.state = state
        self.max_slots = int(max_slots)
        self.page_size = int(page_size)
        self.max_model_len = int(max_model_len
                                 or config.max_position_embeddings)
        if self.max_model_len > config.max_position_embeddings:
            raise ValueError(
                f"max_model_len {self.max_model_len} exceeds the model's "
                f"max_position_embeddings {config.max_position_embeddings}")
        self.table_width = -(-self.max_model_len // self.page_size)
        if num_pages is None:       # full residency: every slot can run
            num_pages = self.max_slots * self.table_width  # at max length
        self.emit_logits = bool(emit_logits)
        self._clock = clock

        self.blocks = BlockManager(num_pages, self.page_size)
        self.scheduler = Scheduler(self.blocks, self.max_slots)
        self.scheduler._finalize = self._finalize
        # every eviction parks its slot — not just the length/eos path in
        # _emit.  A cancel/deadline eviction inside scheduler.schedule()
        # would otherwise leave the slot's table/pos pointing at freed
        # pages, and the lockstep decode step (which writes KV for every
        # slot) would corrupt them once reallocated to a new request.
        self.scheduler._on_evict = self._park

        L = config.num_hidden_layers
        kvh, hd = config.num_key_value_heads, config.head_dim
        dtype = state["llama.embed_tokens.weight"].dtype
        pool_rows = self.blocks.num_pages + 1        # + dump page
        self.kpool = jnp.zeros((L, pool_rows, kvh, self.page_size, hd),
                               dtype)
        self.vpool = jnp.zeros((L, pool_rows, kvh, self.page_size, hd),
                               dtype)
        rope_len = self.table_width * self.page_size
        cos, sin = _rope_tables(rope_len, hd, config.rope_theta)
        self._cos = cos.astype(jnp.float32)
        self._sin = sin.astype(jnp.float32)

        # host-side slot state (shipped to device each step; tiny)
        self.table = np.tile(self.blocks.empty_row(self.table_width),
                             (self.max_slots, 1))
        self._pos = np.zeros((self.max_slots,), np.int32)
        self._tok = np.zeros((self.max_slots,), np.int32)

        self.decode_traces = 0      # python-side mirror of _M_STEP_TRACES
        self._rngs: dict[int, np.random.Generator] = {}
        self._ttft, self._tpot, self._e2e = _serving_hists()
        self._pages_hist = _obs.histogram(
            "serving_pages_in_use_hist",
            "pages-in-use sampled at each decode step",
            buckets=_pages_buckets(self.blocks.num_pages))

        self._step_fn = jax.jit(self._build_step(), donate_argnums=(1, 2))
        self._prefill_fns: dict[int, object] = {}   # bucket -> jitted fn

    # ------------------------------------------------------ jitted bodies
    def _build_step(self):
        cfg = self.config
        L = cfg.num_hidden_layers
        emit_logits = self.emit_logits
        engine = self

        def step(state, kpool, vpool, table, pos, tok, cos, sin):
            # python body runs at trace time only: a second execution of
            # this line means an admission/eviction re-traced the step
            engine.decode_traces += 1
            _M_STEP_TRACES.inc()
            emb = jnp.take(state["llama.embed_tokens.weight"], tok, axis=0)
            cos1, sin1 = _rope_at(cos, sin, pos)
            h = emb
            kps, vps = [], []
            for i in range(L):
                w = _layer_weights(state, i)
                h, kp_, vp_ = _decode_layer_paged(
                    w, h, kpool[i], vpool[i], table, cos1, sin1, pos, cfg)
                kps.append(kp_)
                vps.append(vp_)
            kpool = jnp.stack(kps)
            vpool = jnp.stack(vps)
            h = _rms(h[:, None], state["llama.norm.weight"],
                     cfg.rms_norm_eps)[:, 0]
            logits = _logits_of(state, h).astype(jnp.float32)
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return (kpool, vpool, nxt,
                    logits if emit_logits else jnp.zeros((), jnp.float32))

        return step

    def _prefill_fn(self, bucket: int):
        fn = self._prefill_fns.get(bucket)
        if fn is not None:
            return fn
        cfg = self.config
        L = cfg.num_hidden_layers
        ps = self.page_size
        n_pages = bucket // ps

        def prefill(state, ids, length, table_row, kpool, vpool, cos, sin):
            _M_PREFILL_TRACES.labels(str(bucket)).inc()
            x = jnp.take(state["llama.embed_tokens.weight"], ids, axis=0)
            pmask = jnp.arange(bucket)[None, :] < length
            for i in range(L):
                w = _layer_weights(state, i)
                x, k, v = _prefill_layer(w, x, cos[:bucket], sin[:bucket],
                                         pmask, cfg)
                for p in range(n_pages):
                    rows_k = k[0, p * ps:(p + 1) * ps].swapaxes(0, 1)
                    rows_v = v[0, p * ps:(p + 1) * ps].swapaxes(0, 1)
                    kpool = kpool.at[i, table_row[p]].set(rows_k)
                    vpool = vpool.at[i, table_row[p]].set(rows_v)
            x = _rms(x, state["llama.norm.weight"], cfg.rms_norm_eps)
            last = jnp.take_along_axis(
                x, (length - 1)[:, None, None].astype(jnp.int32),
                axis=1)[:, 0]
            logits = _logits_of(state, last).astype(jnp.float32)
            return kpool, vpool, logits

        fn = jax.jit(prefill, donate_argnums=(4, 5))
        self._prefill_fns[bucket] = fn
        return fn

    # ----------------------------------------------------------- intake
    def submit(self, prompt, gen: GenerationConfig | None = None, *,
               deadline: float | None = None, on_token=None,
               arrival_time: float | None = None) -> Request:
        req = Request(prompt, gen, deadline=deadline, on_token=on_token,
                      arrival_time=(self._clock() if arrival_time is None
                                    else arrival_time))
        total = req.prompt.size + req.gen.max_new_tokens
        if total > self.max_model_len:
            raise ValueError(
                f"prompt ({req.prompt.size}) + max_new_tokens "
                f"({req.gen.max_new_tokens}) = {total} exceeds "
                f"max_model_len {self.max_model_len}")
        need = self.blocks.pages_needed(req.prompt.size,
                                        req.gen.max_new_tokens)
        if need > self.blocks.num_pages:
            raise ValueError(
                f"request needs {need} KV pages but the pool only has "
                f"{self.blocks.num_pages}; it could never be admitted "
                "(raise num_pages or lower max_new_tokens)")
        if req.gen.do_sample and not self.emit_logits:
            raise ValueError(
                "do_sample requests need an engine built with "
                "emit_logits=True (host-side sampling reads the logits)")
        req._engine = self
        self.scheduler.submit(req)
        return req

    # -------------------------------------------------------- main loop
    def step(self) -> bool:
        """One engine iteration: evict/admit (scheduler pass), prefill
        admissions, then one lockstep decode step over the active slots.
        Returns whether any work happened."""
        now = self._clock()
        admitted = self.scheduler.schedule(now)
        for slot, req in admitted:
            self._prefill(slot, req)
        active = [i for i, r in enumerate(self.scheduler.slots)
                  if r is not None and r.state == RequestState.DECODE]
        if active:
            self._decode(active)
        return bool(admitted) or bool(active)

    def run_until_complete(self, max_steps: int | None = None):
        """Drive step() until no live or queued work remains."""
        steps = 0
        while self.scheduler.has_work():
            self.step()
            steps += 1
            if max_steps is not None and steps >= max_steps:
                raise RuntimeError(
                    f"engine did not quiesce within {max_steps} steps")

    def drain(self):
        """Graceful drain: stop admitting; finish what is running.
        Queued requests stay queued until :meth:`resume`."""
        self.scheduler.drain()
        while self.scheduler.active_count:
            self.step()

    def resume(self):
        self.scheduler.resume()

    # ----------------------------------------------------------- prefill
    def _prefill(self, slot: int, req: Request):
        ps = self.page_size
        plen = req.prompt.size
        bucket = -(-plen // ps) * ps
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :plen] = req.prompt
        row = self.blocks.table_row(req.id, self.table_width)
        fn = self._prefill_fn(bucket)
        self.kpool, self.vpool, logits = fn(
            self.state, jnp.asarray(ids),
            jnp.asarray([plen], jnp.int32),
            jnp.asarray(row[:bucket // ps]),
            self.kpool, self.vpool, self._cos, self._sin)
        tok = self._pick_token(req, np.asarray(logits)[0])
        now = self._clock()
        self._ttft.observe(now - req.arrival_time)
        self.table[slot] = row
        self._pos[slot] = plen
        self._tok[slot] = tok
        req.state = RequestState.DECODE
        self._emit(slot, req, tok, now)

    # ------------------------------------------------------------ decode
    def _decode(self, active: list[int]):
        self.kpool, self.vpool, nxt, logits = self._step_fn(
            self.state, self.kpool, self.vpool,
            jnp.asarray(self.table), jnp.asarray(self._pos),
            jnp.asarray(self._tok), self._cos, self._sin)
        _M_STEPS.inc()
        self._pages_hist.observe(self.blocks.pages_in_use)
        nxt = np.asarray(nxt)
        logits = np.asarray(logits) if self.emit_logits else None
        now = self._clock()
        for slot in active:
            req = self.scheduler.slots[slot]
            if req.gen.do_sample:
                tok = self._pick_token(req, logits[slot])
            else:
                tok = int(nxt[slot])
            prev = req.last_token_at
            if prev is not None:
                self._tpot.observe(now - prev)
            self._pos[slot] += 1
            self._tok[slot] = tok
            self._emit(slot, req, tok, now)

    def _emit(self, slot: int, req: Request, tok: int, now: float):
        req._emit(tok, now)
        _M_TOKENS.inc()
        eos = req.gen.eos_token_id
        if req.num_generated >= req.gen.max_new_tokens:
            self._finalize(req, "length", now)
            self.scheduler.evict(slot, "finished", now)
        elif eos is not None and tok == eos:
            self._finalize(req, "eos", now)
            self.scheduler.evict(slot, "finished", now)

    def _park(self, slot: int):
        """Return a slot to the idle state: all writes/reads go to the
        dump page until the next admission."""
        self.table[slot] = self.blocks.empty_row(self.table_width)
        self._pos[slot] = 0
        self._tok[slot] = 0

    # --------------------------------------------------------- sampling
    def _pick_token(self, req: Request, logits: np.ndarray) -> int:
        g = req.gen
        if not g.do_sample:
            return int(np.argmax(logits))
        rng = self._rngs.get(req.id)
        if rng is None:
            rng = self._rngs[req.id] = np.random.default_rng(
                (g.seed, req.id))
        logits = logits.astype(np.float64)
        if g.temperature != 1.0:
            logits = logits / max(g.temperature, 1e-6)
        if g.top_k and g.top_k > 0:
            k = min(g.top_k, logits.size)
            kth = np.sort(logits)[-k]
            logits = np.where(logits < kth, -np.inf, logits)
        if g.top_p < 1.0:
            order = np.argsort(logits)[::-1]
            probs = _softmax(logits[order])
            cum = np.cumsum(probs)
            cutoff_idx = int(np.sum(cum < g.top_p))
            cutoff = logits[order[min(cutoff_idx, logits.size - 1)]]
            logits = np.where(logits < cutoff, -np.inf, logits)
        if not np.isfinite(logits).any():
            raise ValueError(
                f"request {req.id}: no finite logits to sample from — "
                "the model emitted non-finite logits (or top_k/top_p "
                "masked every candidate)")
        return int(rng.choice(logits.size, p=_softmax(logits)))

    # -------------------------------------------------------- lifecycle
    def _finalize(self, req: Request, reason: str, now: float):
        if req.is_finished():
            return
        req.finish_reason = reason
        req.state = RequestState.CANCELLED \
            if reason in ("cancelled", "deadline") else RequestState.DONE
        req.finished_at = now
        self._rngs.pop(req.id, None)
        self._e2e.observe(now - req.arrival_time)
        _M_REQUESTS.labels(reason).inc()

    # -------------------------------------------------------------- info
    def stats(self) -> dict:
        return {
            "queued": len(self.scheduler.queue),
            "active": self.scheduler.active_count,
            "pages_in_use": self.blocks.pages_in_use,
            "pages_total": self.blocks.num_pages,
            "decode_traces": self.decode_traces,
            "prefill_buckets": sorted(self._prefill_fns),
        }


def _softmax(x):
    x = x - np.max(x[np.isfinite(x)]) if np.isfinite(x).any() else x
    e = np.exp(np.where(np.isfinite(x), x, -np.inf))
    return e / e.sum()


def _logits_of(state, h):
    head = state.get("lm_head.weight")
    if head is not None:
        return _mm(h, head)
    return h @ state["llama.embed_tokens.weight"].T


def _pages_buckets(num_pages):
    """Integer page-count buckets spanning the pool (pages-in-use is a
    count, not a latency; the default ms-scale buckets would collapse)."""
    n = max(num_pages, 1)
    edges = sorted({max(1, round(n * f))
                    for f in (0.125, 0.25, 0.375, 0.5, 0.625, 0.75,
                              0.875, 1.0)})
    return tuple(float(e) for e in edges)


def create_engine(model, *, max_slots: int = 4, page_size: int = 64,
                  num_pages: int | None = None,
                  max_model_len: int | None = None,
                  emit_logits: bool = False, clock=time.monotonic
                  ) -> Engine:
    """`create_predictor`-style entry point: build a continuous-batching
    engine over a LlamaForCausalLM (or any model exposing ``config`` and
    ``functional_state()`` with the llama state-dict layout).

    Example::

        engine = create_engine(model, max_slots=8, page_size=64)
        req = engine.submit([1, 2, 3], GenerationConfig(max_new_tokens=32))
        for tok in req.stream():
            ...
    """
    return Engine(model, max_slots=max_slots, page_size=page_size,
                  num_pages=num_pages, max_model_len=max_model_len,
                  emit_logits=emit_logits, clock=clock)
