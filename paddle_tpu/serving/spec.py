"""Speculative decoding: host-side drafting + acceptance bookkeeping.

The draft-and-verify scheme (Leviathan et al. 2023): a cheap drafter
proposes up to ``k`` continuation tokens per slot, the target model
scores all ``k+1`` positions in ONE device step (the runner's verify
program), and the engine keeps the longest proposed prefix that matches
the target's own greedy choices plus the one correction/bonus token the
verify step produced at the first divergence.  Greedy outputs are
token-for-token identical to non-speculative decoding by construction:
position ``j`` is accepted only when the draft token equals the argmax
the target computed from exactly the context a plain decode would have
had, so the accepted chain IS the plain greedy chain — speculation can
only change how many steps it takes, never which tokens come out.

The first proposer is model-free **prompt lookup / n-gram drafting**
(Saxena 2023; vLLM's `ngram` speculative method): each request's prompt
+ generated tokens are indexed by their trailing n-grams, and when the
current tail n-gram has occurred before, the tokens that followed the
previous occurrence become the draft.  This costs microseconds on the
host, needs no second model, and shines exactly where decode is most
wasteful — repetitive spans (code, JSON, extractive summaries, chat
echoes) — while degrading to plain decode (empty drafts) on novel text.

The :class:`NgramProposer` is deliberately a narrow interface
(``register / extend / propose / drop`` keyed by request id) so a later
draft-model proposer — or the parallel-sampling (n>1) verify described
in the ROADMAP — can slot in behind the same engine hooks unchanged.

:class:`SpecStats` owns the ``serving_spec_*`` metrics and the
python-side mirrors the engine's ``stats()`` / perf gate read.
"""
from __future__ import annotations

from .. import observability as _obs

__all__ = ["NgramProposer", "SpecStats"]

_M_SPEC_TOKENS = _obs.counter(
    "serving_spec_tokens_total",
    "speculative draft tokens by outcome (proposed / accepted / "
    "rejected); accepted + rejected == proposed once all verifies land",
    ("result",))
_M_SPEC_STEPS = _obs.counter(
    "serving_spec_verify_steps_total",
    "verify-program device steps (each scores k+1 positions per slot)")
_M_SPEC_RATE = _obs.gauge(
    "serving_spec_acceptance_rate",
    "cumulative accepted / proposed draft tokens (0 when none proposed)")
# tokens, not seconds/bytes — the unit-suffix convention has no token
# suffix and this distribution is the headline speculation win
# tpu-lint: disable=metric-suffix
_M_SPEC_PER_STEP = _obs.histogram(
    "serving_spec_tokens_per_step",
    "tokens committed per verify step (accepted + 1 correction/bonus)",
    buckets=(1, 2, 3, 4, 6, 8, 12, 16))


class NgramProposer:
    """Prompt-lookup drafter: index every request's token history by
    trailing n-grams; propose the continuation of the most recent prior
    occurrence of the current tail.

    For each ``n`` in ``max_n .. min_n`` (longest first, so the most
    specific context wins) the index maps an n-gram to the position
    *after* its latest completed occurrence.  The tail n-gram of the
    live history always maps to the end of the history (an empty
    continuation), so the index also keeps the previous occurrence —
    that one has real continuation tokens to draft from.  Updates are
    O(max_n) per token; proposals are O(max_n) dict probes, independent
    of history length.
    """

    def __init__(self, k: int, *, max_n: int = 3, min_n: int = 1):
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        if not 1 <= min_n <= max_n:
            raise ValueError(
                f"need 1 <= min_n <= max_n, got {min_n}..{max_n}")
        self.k = int(k)
        self.max_n = int(max_n)
        self.min_n = int(min_n)
        self._hist: dict[int, list[int]] = {}
        # (n-gram tuple) -> continuation start of its latest occurrence,
        # plus the occurrence before that (the tail's own entry always
        # points at the history end, where nothing follows yet)
        self._idx: dict[int, dict[tuple, int]] = {}
        self._prev: dict[int, dict[tuple, int]] = {}

    # ------------------------------------------------------------ history
    def register(self, req_id: int, tokens) -> None:
        """Seed a request's history with its prompt."""
        self._hist[req_id] = []
        self._idx[req_id] = {}
        self._prev[req_id] = {}
        for t in tokens:
            self.extend(req_id, int(t))

    def extend(self, req_id: int, token: int) -> None:
        """Append one generated (or prompt) token and index the n-grams
        it completes."""
        hist = self._hist[req_id]
        hist.append(int(token))
        L = len(hist)
        idx, prev = self._idx[req_id], self._prev[req_id]
        for n in range(self.min_n, self.max_n + 1):
            if L < n:
                break
            ng = tuple(hist[L - n:])
            old = idx.get(ng)
            if old is not None:
                prev[ng] = old
            idx[ng] = L          # continuation starts after the n-gram
        return None

    def drop(self, req_id: int) -> None:
        """Forget a request (finished or evicted).  Idempotent."""
        self._hist.pop(req_id, None)
        self._idx.pop(req_id, None)
        self._prev.pop(req_id, None)

    def history_len(self, req_id: int) -> int:
        return len(self._hist.get(req_id, ()))

    # ----------------------------------------------------------- proposal
    def propose(self, req_id: int, max_tokens: int | None = None):
        """Draft up to ``min(k, max_tokens)`` continuation tokens for
        ``req_id``, or ``[]`` when its tail n-gram has no prior
        occurrence (the engine then takes the plain decode step)."""
        hist = self._hist.get(req_id)
        if not hist:
            return []
        cap = self.k if max_tokens is None else min(self.k, max_tokens)
        if cap <= 0:
            return []
        L = len(hist)
        idx, prev = self._idx[req_id], self._prev[req_id]
        for n in range(min(self.max_n, L), self.min_n - 1, -1):
            ng = tuple(hist[L - n:])
            start = idx.get(ng)
            if start == L:                  # the tail matching itself
                start = prev.get(ng)
            if start is None or start >= L:
                continue
            return list(hist[start:start + cap])
        return []


class SpecStats:
    """Acceptance bookkeeping: one ``record`` per verify-step slot, with
    python mirrors for ``Engine.stats()`` and the perf gate."""

    def __init__(self):
        self.proposed = 0
        self.accepted = 0
        self.rejected = 0
        self.verify_steps = 0
        self.committed_tokens = 0   # accepted + correction/bonus tokens

    def record_step(self) -> None:
        """One verify-program device step (any number of drafted slots)."""
        self.verify_steps += 1
        _M_SPEC_STEPS.inc()

    def record(self, proposed: int, accepted: int) -> None:
        """One slot's outcome inside a verify step: ``proposed`` draft
        tokens, of which ``accepted`` matched the target; the slot also
        committed one correction/bonus token on top."""
        rejected = proposed - accepted
        self.proposed += proposed
        self.accepted += accepted
        self.rejected += rejected
        self.committed_tokens += accepted + 1
        if proposed:
            _M_SPEC_TOKENS.labels("proposed").inc(proposed)
        if accepted:
            _M_SPEC_TOKENS.labels("accepted").inc(accepted)
        if rejected:
            _M_SPEC_TOKENS.labels("rejected").inc(rejected)
        _M_SPEC_PER_STEP.observe(accepted + 1)
        _M_SPEC_RATE.set(self.acceptance_rate)

    @property
    def acceptance_rate(self) -> float:
        return self.accepted / self.proposed if self.proposed else 0.0

    def snapshot(self) -> dict:
        return {"spec_proposed": self.proposed,
                "spec_accepted": self.accepted,
                "spec_rejected": self.rejected,
                "spec_verify_steps": self.verify_steps,
                "spec_committed_tokens": self.committed_tokens,
                "spec_acceptance_rate": self.acceptance_rate}
