"""HTTP serving front-end for the continuous-batching engine.

Turns an in-process :class:`~paddle_tpu.serving.Engine` into a network
service with zero new dependencies (stdlib ``http.server`` only):

  * ``POST /v1/completions`` — OpenAI-compatible completion endpoint
    over token ids (this layer has no tokenizer): blocking JSON or
    ``"stream": true`` SSE (``data: {...}`` chunks, terminated by
    ``data: [DONE]``).  Per-request ``timeout`` wires straight into the
    engine's deadline/cancel machinery; a client that disconnects
    mid-stream cancels its request at the next iteration boundary.
  * admission control — when the scheduler's queue is full the server
    answers ``429`` with a ``Retry-After`` header (backpressure is a
    protocol answer, never a hang or a 500); while draining it answers
    ``503``.
  * ``POST /v1/batches`` — the offline lane: a JSONL job (inline
    records or a server-side file) drip-fed at the ``"batch"``
    priority class, preempted by interactive traffic, with
    ``GET /v1/batches/<id>`` progress and a JSONL output file.
  * ``GET /healthz`` (engine stats + drain state), ``GET /metrics``
    (the observability registry's Prometheus export),
    ``GET /debug/resources`` (resource-tracker snapshot + engine pool
    census), ``GET /debug/profile`` (on-demand phase-attributed
    sampling-profiler window, folded / chrome / json),
    ``GET /debug/captures`` (alert-triggered diagnostic capture
    bundles), ``POST /drain`` /
    ``POST /resume`` (rolling restarts), and graceful drain on SIGTERM:
    in-flight streams finish, queued requests are failed fast, then the
    listener closes.

Threading model: the engine stays single-threaded.  One
:class:`EngineWorker` thread owns it and drives ``engine.step()``;
HTTP handler threads (``ThreadingHTTPServer``) only ever call
``worker.submit()`` under the worker lock and then consume tokens from
a per-request ``queue.Queue`` fed by the engine thread through the
request's ``on_token`` callback.
"""
from __future__ import annotations

import json
import queue
import signal
import socket
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from .. import observability as _obs
from ..flags import FLAGS
from ..sanitizer import make_condition, make_rlock
from .engine import Engine
from .lora.batch import BATCH_PRIORITY, BatchJob
from .request import GenerationConfig, Request
from .supervisor import EngineSupervisor
from .watchdog import Watchdog

__all__ = ["BackpressureError", "DrainingError", "EngineWorker",
           "ServingServer", "serve"]

_M_HTTP_REQS = _obs.counter(
    "serving_http_requests_total", "HTTP requests by route and status",
    ("route", "code"))
_M_HTTP_REJECT = _obs.counter(
    "serving_http_rejections_total",
    "completions rejected before admission: 'backpressure' -> 429, "
    "'draining' -> 503, 'invalid' -> 400", ("reason",))
_M_HTTP_INFLIGHT = _obs.gauge(
    "serving_http_inflight",
    "completion requests currently held by handler threads")
_M_HTTP_CANCELS = _obs.counter(
    "serving_http_stream_cancels_total",
    "SSE streams cancelled by client disconnect")
_M_SLO_SHED = _obs.counter(
    "serving_slo_shed_total",
    "admissions refused (429) because an SLO dimension's burn rate "
    "crossed FLAGS_serving_shed_burn_rate, by priority class (only "
    "classes <= FLAGS_serving_shed_max_priority are shed)", ("class",))

# wire-level priority classes <-> scheduler integers; arbitrary ints
# are also accepted in request bodies for finer-grained fleets.
# "batch" is the offline lane: below every interactive class, so batch
# residents lose every admission race and preempt first.
_PRIORITY_NAMES = {"low": -1, "normal": 0, "high": 1,
                   "batch": BATCH_PRIORITY}
_PRIORITY_CLASS = {v: k for k, v in _PRIORITY_NAMES.items()}


def _priority_class(priority: int) -> str:
    """Metric label for a priority int (named classes stay readable)."""
    return _PRIORITY_CLASS.get(int(priority), str(int(priority)))


def _http_latency_hist():
    return _obs.histogram(
        "serving_http_request_seconds",
        "completion handler wall time (request read -> response end)",
        buckets=_obs.registry.SERVING_LATENCY_BUCKETS)


class BackpressureError(RuntimeError):
    """Admission queue full — surfaces as HTTP 429 + Retry-After."""


class DrainingError(RuntimeError):
    """Server is draining — surfaces as HTTP 503."""


class EngineWorker:
    """Owns an :class:`Engine` and drives it from ONE background thread.

    The engine is single-threaded by design (jitted step, host-side
    slot mirrors), so every touch goes through :attr:`lock`: the worker
    thread holds it across ``engine.step()``, handler threads hold it
    for the (cheap) ``submit()``.  Token delivery back to handlers is
    lock-free — the engine thread runs each request's ``on_token``
    callback, which pushes into that handler's private queue.
    """

    def __init__(self, engine: Engine, *, max_queue: int = 64,
                 idle_wait: float = 0.005,
                 supervisor: EngineSupervisor | None = None):
        self.engine = engine
        # every step goes through the supervisor: a poisoned step costs
        # a runner rebuild + replay, not the worker thread
        self.supervisor = supervisor or EngineSupervisor(engine)
        self.max_queue = int(max_queue)
        self.lock = make_rlock("EngineWorker.lock")
        self._wake = make_condition(self.lock, name="EngineWorker._wake")
        self._stop = False
        self._started = False
        self._idle_wait = float(idle_wait)
        # recent Request objects, newest last (introspection + tests)
        self.requests: deque[Request] = deque(maxlen=512)
        # offline batch jobs by id: pumped by the worker thread between
        # steps, introspected by GET /v1/batches/<id>
        self.batches: dict[str, BatchJob] = {}
        # take over the engine's lora.json provider slot so the dump
        # also carries batch-job progress (engine registers itself at
        # construction; the worker wraps it — last writer wins)
        if engine.lora is not None:
            _obs.set_active_lora(self)
        # burn-rate sheds by priority class (mirror of
        # serving_slo_shed_total; /debug/fleet's scheduling block)
        self.shed_by_class: dict[str, int] = {}
        self._stall_until = 0.0     # inject_stall test hook
        self._thread = threading.Thread(
            target=self._loop, name="engine-worker", daemon=True)

    # --------------------------------------------------------- lifecycle
    def start(self) -> "EngineWorker":
        if not self._started:
            self._started = True
            self._thread.start()
        return self

    def stop(self, timeout: float = 10.0):
        with self._wake:
            self._stop = True
            self._wake.notify_all()
        if self._started:
            self._thread.join(timeout=timeout)

    def _loop(self):
        while True:
            with self._wake:
                if self._stop:
                    return
                now = time.monotonic()
                if now < self._stall_until:
                    # inject_stall in effect: hold the loop without
                    # stepping — active slots persist while progress
                    # freezes, which is exactly the watchdog's trigger
                    self._wake.wait(min(self._stall_until - now, 0.05))
                    continue
                # the offline lane: top every live job's window back up
                # before stepping — batch submissions land at
                # BATCH_PRIORITY, so interactive arrivals still win the
                # admission race inside the scheduler pass
                if self.batches and not self.engine.scheduler.draining:
                    for job in list(self.batches.values()):
                        if not job.done:
                            job.pump(self.engine.submit)
                if not self.engine.scheduler.has_work():
                    self._wake.wait(self._idle_wait)
                    continue
                self.supervisor.step()

    def inject_stall(self, seconds: float):
        """TEST HOOK: wedge the decode loop for ``seconds`` — the worker
        thread keeps running but stops calling ``engine.step()``, so an
        in-flight request sits in its slot making zero progress (the
        condition the serving watchdog exists to catch)."""
        with self._wake:
            self._stall_until = time.monotonic() + float(seconds)
            self._wake.notify_all()

    # ------------------------------------------------------------ intake
    @property
    def draining(self) -> bool:
        return self.engine.scheduler.draining

    def submit(self, prompt, gen: GenerationConfig | None = None, *,
               timeout_s: float | None = None, on_token=None,
               trace=None, priority: int = 0,
               tenant: str | None = None,
               adapter: str | None = None) -> Request:
        """Thread-safe admission with backpressure: raises
        :class:`DrainingError` / :class:`BackpressureError` instead of
        queueing unboundedly; ``timeout_s`` becomes an absolute engine
        deadline (the existing cancel machinery enforces it).  ``trace``
        (a tracing.SpanContext) parents the engine-side request spans —
        the handler passes its ``server.request`` span context so the
        trace survives the hop onto the engine thread.  ``priority``
        is the scheduling class: burn-rate shedding only rejects
        classes <= ``FLAGS_serving_shed_max_priority``, and higher
        classes may preempt lower residents inside the engine.
        ``tenant`` is the usage-meter billing dimension; with
        ``FLAGS_serving_fair_share`` set and a meter wired, burn-rate
        shedding only refuses the heaviest-page-second tenant's
        requests within the shedable classes.  ``adapter`` names a
        registered LoRA adapter (unknown names reject with 400 at the
        HTTP layer via the engine's KeyError)."""
        priority = int(priority)
        with self._wake:
            if self.engine.scheduler.draining:
                raise DrainingError(
                    "server is draining; not admitting new requests")
            if len(self.engine.scheduler.queue) >= self.max_queue:
                raise BackpressureError(
                    f"admission queue full ({self.max_queue} waiting)")
            # SLO-driven shedding: refuse BEFORE the queue fills when
            # the live burn rate says admitted requests are already
            # missing their targets (429 + Retry-After, like queue-full).
            # Only the shedable classes are refused — high-priority
            # traffic keeps flowing and relies on preemption for room.
            shed = float(FLAGS.get("FLAGS_serving_shed_burn_rate") or 0.0)
            shed_max = int(
                FLAGS.get("FLAGS_serving_shed_max_priority") or 0)
            if shed > 0 and self.engine.slo is not None \
                    and priority <= shed_max:
                burn = self.engine.slo.max_burn_rate()
                if burn >= shed and self._should_shed(tenant):
                    cls = _priority_class(priority)
                    _M_SLO_SHED.labels(cls).inc()
                    self.shed_by_class[cls] = \
                        self.shed_by_class.get(cls, 0) + 1
                    _obs.flight("server", "slo_shed", burn=round(burn, 3),
                                threshold=shed, priority=priority)
                    raise BackpressureError(
                        f"SLO burn rate {burn:.2f} at/over shed "
                        f"threshold {shed:g}")
            deadline = (None if timeout_s is None
                        else self.engine._clock() + float(timeout_s))
            req = self.engine.submit(prompt, gen, deadline=deadline,
                                     on_token=on_token, trace=trace,
                                     priority=priority, tenant=tenant,
                                     adapter=adapter)
            self.requests.append(req)
            self._wake.notify_all()
        return req

    def submit_batch(self, job: BatchJob) -> BatchJob:
        """Register an offline batch job: the worker thread drip-feeds
        its records at BATCH_PRIORITY between engine steps (first
        window tops up at the next loop iteration)."""
        with self._wake:
            if self.engine.scheduler.draining:
                raise DrainingError(
                    "server is draining; not accepting batch jobs")
            self.batches[job.id] = job
            # batch lane works on dense engines too — make sure the
            # lora.json provider is wired so the dump carries the jobs
            _obs.set_active_lora(self)
            self._wake.notify_all()
        return job

    def lora_snapshot(self) -> dict:
        """``lora.json`` provider: the engine's adapter census plus
        every offline batch job's progress (the engine alone cannot
        see the jobs — they live on the worker)."""
        snap = self.engine.lora_snapshot()
        with self._wake:
            snap["batch_jobs"] = {jid: j.progress()
                                  for jid, j in self.batches.items()}
        return snap

    def _should_shed(self, tenant) -> bool:
        """Fair-share gate for burn-rate shedding: with
        ``FLAGS_serving_fair_share`` set and a usage meter wired, only
        the heaviest-page-second tenant's requests are refused — the
        tenant that consumed the most KV residency absorbs the overload
        first.  Everything sheds (the pre-existing behavior) when the
        flag or the meter is off, or no tenant has any history yet."""
        meter = self.engine.usage
        if meter is None:
            return True
        name = meter.tenants.canonical(tenant)
        if FLAGS.get("FLAGS_serving_fair_share"):
            heavy = meter.heaviest_tenant()
            if heavy is not None and name != heavy:
                return False
        # lock order is worker.lock -> meter._lock everywhere (the
        # engine's own meter calls nest the same way) and the meter
        # never calls back into the worker, so this cannot deadlock
        # tpu-lint: disable=callback-under-lock
        meter.on_shed(name)
        return True

    # ------------------------------------------------------------- drain
    def drain(self, timeout: float | None = None) -> bool:
        """Graceful drain: stop admitting, let in-flight sequences run
        to completion, then fail the never-admitted queued requests fast
        (their handlers would otherwise wait on a queue that drain will
        never schedule).  Returns False if ``timeout`` elapsed first."""
        with self.lock:
            self.engine.scheduler.drain()
        t0 = time.monotonic()
        while True:
            with self.lock:
                if self.engine.scheduler.active_count == 0:
                    break
            if timeout is not None and time.monotonic() - t0 > timeout:
                return False
            time.sleep(0.002)
        with self.lock:
            now = self.engine._clock()
            while self.engine.scheduler.queue:
                r = self.engine.scheduler.queue.popleft()
                self.engine.scheduler._finish(r, "cancelled", now)
        return True

    def resume(self):
        with self._wake:
            self.engine.scheduler.resume()
            self._wake.notify_all()

    # -------------------------------------------------------------- info
    def stats(self) -> dict:
        with self.lock:
            st = self.engine.stats()
            st["draining"] = self.engine.scheduler.draining
            st["max_queue"] = self.max_queue
        st["supervisor"] = self.supervisor.stats()
        return st


# --------------------------------------------------------------- protocol
def _parse_priority(value) -> int:
    """Priority from a body field or header: a named class
    (low/normal/high) or any int.  Raises ValueError otherwise."""
    if isinstance(value, str):
        name = value.strip().lower()
        if name in _PRIORITY_NAMES:
            return _PRIORITY_NAMES[name]
        try:
            return int(name)
        except ValueError:
            raise ValueError(
                f"invalid 'priority' {value!r}: use low/normal/high "
                "or an integer") from None
    if isinstance(value, bool) or not isinstance(value, int):
        raise ValueError(
            f"invalid 'priority' {value!r}: use low/normal/high or "
            "an integer")
    return int(value)


def _parse_tenant(value) -> str | None:
    """Tenant id from a body field or the X-Tenant header: any
    non-empty string (whitespace-stripped); None / "" mean unset (the
    engine canonicalizes to "anon")."""
    if value is None:
        return None
    if not isinstance(value, str):
        raise ValueError(
            f"invalid 'tenant' {value!r}: must be a string")
    return value.strip() or None


def _parse_adapter(value) -> str | None:
    """LoRA adapter name from a body field or the X-Adapter header:
    any non-empty string (whitespace-stripped); None / "" mean the
    dense base model."""
    if value is None:
        return None
    if not isinstance(value, str):
        raise ValueError(
            f"invalid 'adapter' {value!r}: must be a string")
    return value.strip() or None


def _parse_completion(body: dict):
    """Validate a /v1/completions body -> (prompt, gen, stream,
    timeout_s, priority, tenant, adapter).  Raises ValueError with a
    client-facing message."""
    if not isinstance(body, dict):
        raise ValueError("request body must be a JSON object")
    prompt = body.get("prompt")
    if prompt is None:
        raise ValueError("missing 'prompt' (a list of token ids)")
    if isinstance(prompt, str):
        raise ValueError(
            "text prompts are not supported — this server speaks token "
            "ids (pass 'prompt' as a list of ints)")
    prompt = np.asarray(prompt, np.int32).reshape(-1)
    temperature = float(body.get("temperature", 1.0))
    do_sample = body.get("do_sample")
    if do_sample is None:
        # OpenAI semantics: temperature 0 means greedy.  Sampling stays
        # opt-in ('do_sample' or an explicit non-default temperature)
        # because it needs an engine built with emit_logits=True.
        do_sample = "temperature" in body and temperature > 0.0
    gen = GenerationConfig(
        max_new_tokens=int(body.get("max_tokens", 16)),
        do_sample=bool(do_sample),
        temperature=temperature if temperature > 0 else 1.0,
        top_k=int(body.get("top_k", 0)),
        top_p=float(body.get("top_p", 1.0)),
        eos_token_id=(None if body.get("eos_token_id") is None
                      else int(body["eos_token_id"])),
        seed=int(body.get("seed", 0)))
    timeout_s = body.get("timeout")
    if timeout_s is not None:
        timeout_s = float(timeout_s)
        if timeout_s <= 0:
            raise ValueError("'timeout' must be > 0 seconds")
    priority = _parse_priority(body.get("priority", 0))
    tenant = _parse_tenant(body.get("tenant"))
    adapter = _parse_adapter(body.get("adapter"))
    return prompt, gen, bool(body.get("stream", False)), timeout_s, \
        priority, tenant, adapter


_FINISH_REASON = {"length": "length", "eos": "stop",
                  "cancelled": "cancelled", "deadline": "timeout",
                  "error": "error"}


def _finish_reason(req: Request) -> str | None:
    if req.finish_reason is None:
        return None
    return _FINISH_REASON.get(req.finish_reason, req.finish_reason)


def _usage_json(req: Request) -> dict:
    """The enriched OpenAI-style ``usage`` block: token totals plus the
    per-request cost ledger highlights (cached prompt split, queue
    wait, speculation yield)."""
    plen = int(req.prompt.size)
    return {"prompt_tokens": plen,
            "completion_tokens": req.num_generated,
            "total_tokens": plen + req.num_generated,
            "prompt_tokens_cached": req.num_cached_tokens,
            "queue_ms": round(req.queue_seconds * 1e3, 3),
            "spec_accepted_tokens": req.spec_accepted_tokens,
            # adapter label only when one served the request, so dense
            # responses keep their exact pre-LoRA shape
            **({"adapter": req.adapter} if req.adapter else {})}


def _completion_json(model_name: str, req: Request) -> dict:
    return {
        "id": f"cmpl-{req.id}",
        "object": "text_completion",
        "created": int(time.time()),
        "model": model_name,
        "choices": [{
            "index": 0,
            "text": " ".join(str(t) for t in req.output_tokens),
            "token_ids": list(req.output_tokens),
            "finish_reason": _finish_reason(req),
        }],
        "usage": _usage_json(req),
        # deprecated (one release): moved into usage.prompt_tokens_cached
        "num_cached_tokens": req.num_cached_tokens,
        **({"error": req.error} if req.error else {}),
    }


def _chunk_json(model_name: str, req: Request, tok: int | None,
                final: bool) -> dict:
    out = {
        "id": f"cmpl-{req.id}",
        "object": "text_completion.chunk",
        "model": model_name,
        "choices": [{
            "index": 0,
            "text": "" if tok is None else f"{tok} ",
            "token_ids": [] if tok is None else [int(tok)],
            "finish_reason": _finish_reason(req) if final else None,
        }],
    }
    if final:
        # the final SSE chunk mirrors the blocking response's usage
        # block, so streaming clients get the same cost attribution
        out["usage"] = _usage_json(req)
    return out


# ----------------------------------------------------------------- server
class ServingServer(ThreadingHTTPServer):
    """Threaded HTTP front door over one :class:`EngineWorker`.

    ``port=0`` binds an ephemeral port (tests); :attr:`address` reports
    the bound ``host:port``.  ``start()`` spawns both the engine worker
    and the accept loop; ``stop()`` is the graceful SIGTERM path —
    drain (finish in-flight streams), then close the listener.
    """

    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, worker: EngineWorker, host: str = "127.0.0.1",
                 port: int = 0, *, retry_after_s: float = 1.0,
                 hard_timeout_s: float = 600.0,
                 model_name: str = "paddle-tpu",
                 watchdog_s: float | None = None,
                 timeseries_interval_s: float | None = None,
                 profile_interval_s: float | None = None):
        self.worker = worker
        self.retry_after_s = float(retry_after_s)
        self.hard_timeout_s = float(hard_timeout_s)
        self.model_name = model_name
        if watchdog_s is None:
            watchdog_s = float(
                FLAGS.get("FLAGS_serving_watchdog_seconds") or 0.0)
        self.watchdog = Watchdog(worker.engine, watchdog_s)
        # stall -> self-healing: the watchdog flags the supervisor, the
        # engine thread performs the recovery at its next step
        self.watchdog.on_stall = worker.supervisor.note_stall
        # fleet telemetry: with the interval unset NOTHING is built —
        # no store, no sampler thread, no per-request cost beyond the
        # `is not None` tests below (the faults/sanitizer contract)
        if timeseries_interval_s is None:
            timeseries_interval_s = float(
                FLAGS.get("FLAGS_obs_timeseries_interval_s") or 0.0)
        self._ts_interval = float(timeseries_interval_s)
        self.timeseries = None
        if self._ts_interval > 0:
            store = _obs.serving_sources(_obs.TimeSeriesStore())
            for rule in _obs.default_rules():
                store.add_rule(rule)
            self.timeseries = store
        # continuous phase-attributed profiling — same contract: with
        # the interval unset no profiler object or sweep thread exists
        if profile_interval_s is None:
            profile_interval_s = float(
                FLAGS.get("FLAGS_obs_profile_interval_s") or 0.0)
        self._profile_interval = float(profile_interval_s)
        self.profiler = None
        if self._profile_interval > 0:
            self.profiler = _obs.set_active_profiler(
                _obs.SamplingProfiler(self._profile_interval,
                                      phases=self._engine_phases))
        # alert-triggered diagnostic capture rides the timeseries
        # store's fire hook: no alerts -> no capture object either
        self.capture = None
        if self.timeseries is not None:
            self.capture = _obs.set_active_capture(
                _obs.DiagnosticCapture(profiler=self.profiler)
                .attach(self.timeseries))
        self._latency = _http_latency_hist()
        self._serve_thread: threading.Thread | None = None
        self._stop_thread: threading.Thread | None = None
        super().__init__((host, port), _Handler)

    @property
    def address(self) -> str:
        return f"{self.server_address[0]}:{self.server_address[1]}"

    def _engine_phases(self) -> dict:
        """Thread-ident -> phase map for the sampling profiler: the
        engine worker thread reports ``engine.current_phase``.  Plain
        attribute reads, lock-free — the watchdog contract."""
        t = self.worker._thread
        if t is None or t.ident is None:
            return {}
        return {t.ident: self.worker.engine.current_phase}

    def start(self) -> "ServingServer":
        self.worker.start()
        self.watchdog.start()       # no-op when watchdog_s <= 0
        if self.timeseries is not None:
            self.timeseries.start_sampling(self._ts_interval)
        if self.profiler is not None:
            self.profiler.start_sampling()
        self._serve_thread = threading.Thread(
            target=self.serve_forever, name=f"http:{self.address}",
            daemon=True)
        self._serve_thread.start()
        return self

    def stop(self, *, drain_timeout: float | None = None):
        """Graceful shutdown: drain in-flight work, then close."""
        self.watchdog.stop()
        if self.timeseries is not None:
            self.timeseries.stop()
        if self.profiler is not None:
            self.profiler.stop()
        self.worker.drain(timeout=drain_timeout)
        self.shutdown()
        if self._serve_thread is not None:
            self._serve_thread.join(timeout=5.0)
        self.worker.stop()
        self.server_close()

    def install_signal_handlers(self,
                                sigs=(signal.SIGTERM, signal.SIGINT)):
        """SIGTERM/SIGINT => graceful drain-then-exit.  Only callable
        from the main thread (signal module restriction).  The handler
        must return immediately, so stop() runs on its own thread; the
        handle is retained (``_stop_thread``) so the foreground path
        can join it, and a second signal during a drain is a no-op
        instead of racing a second stop() against the first."""
        def _graceful(signum, frame):
            if self._stop_thread is not None:
                return          # already draining; don't stack stops
            self._stop_thread = threading.Thread(
                target=self.stop, name="server-shutdown", daemon=True)
            self._stop_thread.start()
        for s in sigs:
            signal.signal(s, _graceful)

    def fleet_summary(self) -> dict:
        """Compact replica summary for ``GET /debug/fleet``: pool
        census + fragmentation, cached-chain digest, slots/queue
        headroom, SLO burn rates, spec acceptance, recovery counts,
        firing alerts, and recent time-series windows.  The engine half
        walks scheduler state, so it runs under the worker lock; the
        telemetry half reads the store lock-free."""
        worker = self.worker
        with worker.lock:
            eng = worker.engine
            b = eng.blocks
            pool = b.pool_accounting()
            head_need = None
            if eng.scheduler.queue:
                head = eng.scheduler.queue[0]
                head_need = b.pages_needed(head.prompt.size,
                                           head.gen.max_new_tokens)
            pool["fragmentation_ratio"] = round(
                b.fragmentation(head_need), 6)
            prefix = b.prefix_digest()
            lookups = b.prefix_hits + b.prefix_misses
            prefix["hits"] = b.prefix_hits
            prefix["misses"] = b.prefix_misses
            prefix["hit_rate"] = (round(b.prefix_hits / lookups, 6)
                                  if lookups else None)
            active = eng.scheduler.active_count
            slots = {"active": active, "max": eng.scheduler.max_slots,
                     "free": eng.scheduler.max_slots - active}
            queue = {"depth": len(eng.scheduler.queue),
                     "max": worker.max_queue}
            slo = None
            if eng.slo is not None:
                slo = {"burn_rates": {
                           d: round(r, 6)
                           for d, r in eng.slo.burn_rates().items()},
                       "max_burn_rate": round(eng.slo.max_burn_rate(),
                                              6)}
            spec = {"spec_k": eng.spec_k}
            if eng._spec is not None:
                spec.update(eng._spec.snapshot())
            recovery = {"recoveries": eng.recoveries,
                        "quarantines": eng.quarantines,
                        "replayed_requests": eng.replayed_requests}
            scheduling = {"prefill_chunk": eng.prefill_chunk,
                          "prefill_chunks": eng.prefill_chunks,
                          "max_prefill_gap": eng.max_prefill_gap,
                          "preemptions": eng.preemptions,
                          "spill_aborts": eng.spill_aborts,
                          "spilled_pages": b.spilled_pages,
                          "restored_pages": b.restored_pages,
                          "spill_bytes": b.spill_bytes,
                          "host_parked_pages": b.host_parked,
                          "shed_by_class": dict(worker.shed_by_class)}
            usage = (eng.usage.snapshot()
                     if eng.usage is not None else None)
            # tail forensics: dominant latency cause + worst exemplar
            # (age on the engine clock) for the dashboard's tail line
            tail = (eng.requestlog.tail_summary(now=eng._clock())
                    if eng.requestlog is not None else None)
            # adapter residency census: the router folds this into its
            # expected-hit-rate score so adapter traffic sticks to
            # replicas already holding the weights
            adapters = (eng.lora_snapshot()
                        if eng.lora is not None else None)
            batches = {jid: j.progress()
                       for jid, j in worker.batches.items()}
            draining = eng.scheduler.draining
        # raw cumulative latency buckets, not quantiles: consumers
        # (dashboard, router) merge buckets ACROSS replicas and then
        # estimate — averaging per-replica quantiles would be wrong
        latency = {}
        reg = _obs.default_registry()
        for key, mname in (("ttft", "serving_ttft_seconds"),
                           ("e2e", "serving_e2e_seconds")):
            fam = reg.get(mname)
            if fam is None:
                continue
            merged, count, total = _obs.merge_series_buckets(
                [child.snapshot() for _, child in fam._series()])
            if count:
                latency[key] = {"buckets": merged, "count": count,
                                "sum": round(total, 9)}
        ts = self.timeseries
        return {"kind": "replica", "model": self.model_name,
                "address": self.address, "draining": draining,
                "pool": pool, "prefix": prefix, "slots": slots,
                "queue": queue, "slo": slo, "spec": spec,
                "recovery": recovery, "scheduling": scheduling,
                "usage": usage, "tail": tail, "adapters": adapters,
                "batches": batches, "latency": latency,
                "watchdog": self.watchdog.state(),
                "alerts": ({"firing": ts.firing(),
                            "fired_total": ts.alerts_fired,
                            "ticks": ts.ticks}
                           if ts is not None else None),
                "profiling": (self.profiler.stats()
                              if self.profiler is not None else None),
                "captures": (self.capture.index()
                             if self.capture is not None else None),
                "series": ts.windows() if ts is not None else {}}


# one-line descriptions for GET /debug/ — operators stop guessing paths
_DEBUG_INDEX = {
    "/debug/": "this index",
    "/debug/trace": "span ring + sampled counter tracks "
                    "(chrome://tracing loadable)",
    "/debug/flight": "flight-recorder event ring + watchdog state",
    "/debug/resources": "resource-tracker snapshot + engine pool census",
    "/debug/fleet": "compact replica summary: pool census, prefix "
                    "digest, burn rates, alerts, series windows",
    "/debug/profile": "sample a phase-attributed profile window: "
                      "?seconds=N&format=folded|chrome|json",
    "/debug/captures": "alert-triggered diagnostic capture index + "
                       "retained evidence bundles",
    "/debug/usage": "per-tenant usage table (tokens, page-seconds, "
                    "goodput) + the page-seconds conservation check",
    "/debug/requests/<id>": "one request's lifecycle waterfall + "
                            "critical-path attribution "
                            "(?format=chrome for chrome://tracing)",
    "/debug/exemplars": "worst-K SLO-violation exemplars per dimension "
                        "+ the attribution conservation census",
}


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server: ServingServer

    def log_message(self, fmt, *args):      # metrics, not stderr noise
        pass

    # ----------------------------------------------------------- helpers
    def _json(self, code: int, obj: dict, route: str, headers=()):
        body = json.dumps(obj).encode()
        try:
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in headers:
                self.send_header(k, str(v))
            self.end_headers()
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError,
                ConnectionAbortedError):
            pass
        _M_HTTP_REQS.labels(route, str(code)).inc()

    def _error(self, code: int, message: str, route: str, *,
               etype: str = "invalid_request_error", headers=()):
        self._json(code, {"error": {"message": message, "type": etype,
                                    "code": code}}, route,
                   headers=headers)

    def _read_body(self) -> dict:
        n = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(n) if n > 0 else b"{}"
        return json.loads(raw.decode() or "{}")

    # ------------------------------------------------------------ routes
    def do_GET(self):
        if self.path == "/healthz":
            st = self.worker_stats()
            st["status"] = "draining" if st["draining"] else "ok"
            st["watchdog"] = self.server.watchdog.state()
            ts = self.server.timeseries
            if ts is not None:
                st["alerts"] = {"firing": ts.firing(),
                                "fired_total": ts.alerts_fired}
            self._json(200, st, "/healthz")
        elif self.path == "/metrics":
            text = _obs.default_registry().to_prometheus().encode()
            try:
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(text)))
                self.end_headers()
                self.wfile.write(text)
            except (BrokenPipeError, ConnectionResetError):
                pass
            _M_HTTP_REQS.labels("/metrics", "200").inc()
        elif self.path == "/debug/flight":
            fr = _obs.flight_recorder()
            self._json(200, {"capacity": fr.capacity,
                             "events": fr.snapshot(),
                             "watchdog": self.server.watchdog.state()},
                       "/debug/flight")
        elif self.path == "/debug/trace":
            # curl -s :port/debug/trace > t.json  ->  chrome://tracing
            self._json(200, {"traceEvents":
                             (_obs.tracer().chrome_events()
                              + _obs.chrome_counter_events())},
                       "/debug/trace")
        elif self.path == "/debug/resources":
            # process tracker (memory/compiles/goodput/throughput) plus
            # the engine-local pool census; the engine half walks
            # scheduler state, so it runs under the worker lock
            snap = _obs.resource_tracker().snapshot()
            worker = self.server.worker
            with worker.lock:
                snap["engine"] = worker.engine.resource_snapshot()
            self._json(200, snap, "/debug/resources")
        elif self.path == "/debug/fleet":
            self._json(200, self.server.fleet_summary(), "/debug/fleet")
        elif self.path.split("?", 1)[0] == "/debug/profile":
            self._profile()
        elif self.path.split("?", 1)[0] == "/debug/captures":
            cap = self.server.capture
            if cap is None:
                self._error(
                    404, "diagnostic capture disabled (set "
                    "FLAGS_obs_timeseries_interval_s > 0)",
                    "/debug/captures")
            else:
                self._json(200, {"kind": "replica", "index": cap.index(),
                                 "recent": cap.recent()},
                           "/debug/captures")
        elif self.path == "/debug/usage":
            worker = self.server.worker
            meter = worker.engine.usage
            if meter is None:
                self._error(
                    404, "usage metering disabled (set "
                    "FLAGS_serving_usage_meter or pass usage= to the "
                    "engine)", "/debug/usage")
            else:
                with worker.lock:
                    snap = meter.snapshot()
                self._json(200, dict(snap, kind="replica"),
                           "/debug/usage")
        elif self.path == "/debug/exemplars":
            worker = self.server.worker
            log = worker.engine.requestlog
            if log is None:
                self._error(
                    404, "request log disabled (set "
                    "FLAGS_serving_request_log or pass requestlog= to "
                    "the engine)", "/debug/exemplars")
            else:
                with worker.lock:
                    snap = log.snapshot()
                self._json(200, dict(snap, kind="replica"),
                           "/debug/exemplars")
        elif self.path.split("?", 1)[0].startswith("/debug/requests/"):
            self._request_waterfall()
        elif self.path == "/v1/batches":
            worker = self.server.worker
            with worker.lock:
                jobs = {jid: j.progress()
                        for jid, j in worker.batches.items()}
            self._json(200, {"jobs": jobs}, "/v1/batches")
        elif self.path.startswith("/v1/batches/"):
            jid = self.path[len("/v1/batches/"):]
            worker = self.server.worker
            with worker.lock:
                job = worker.batches.get(jid)
                prog = job.progress() if job is not None else None
            if prog is None:
                self._error(404, f"no batch job {jid!r}", "/v1/batches")
            else:
                self._json(200, prog, "/v1/batches")
        elif self.path in ("/debug", "/debug/"):
            self._json(200, {"endpoints": _DEBUG_INDEX}, "/debug/")
        else:
            self._error(404, f"no route {self.path}", self.path)

    def _request_waterfall(self):
        """``GET /debug/requests/<id>[?format=chrome]``: one request's
        lifecycle waterfall — the event list + the critical-path
        attribution whose buckets sum to its measured E2E — or the
        chrome://tracing-loadable export of the same timeline."""
        from urllib.parse import parse_qs, urlparse
        u = urlparse(self.path)
        route = "/debug/requests"         # bounded metric label
        worker = self.server.worker
        log = worker.engine.requestlog
        if log is None:
            self._error(404, "request log disabled (set "
                        "FLAGS_serving_request_log or pass requestlog= "
                        "to the engine)", route)
            return
        rid_s = u.path[len("/debug/requests/"):]
        try:
            rid = int(rid_s)
        except ValueError:
            self._error(400, "request id must be an integer, got "
                        f"{rid_s!r}", route)
            return
        fmt = parse_qs(u.query).get("format", ["json"])[0]
        if fmt not in ("json", "chrome"):
            self._error(400, f"unknown format {fmt!r} (json | chrome)",
                        route)
            return
        with worker.lock:
            tl = log.get(rid)
            doc = None if tl is None else (
                tl.chrome_trace() if fmt == "chrome" else tl.to_dict())
        if doc is None:
            self._error(404, f"no timeline for request {rid} (never "
                        "submitted here, or evicted from the bounded "
                        "log)", route)
        else:
            if fmt != "chrome":
                doc = dict(doc, kind="replica")
            self._json(200, doc, route)

    def _profile(self):
        """``GET /debug/profile?seconds=N[&format=...]``: sample a
        fresh phase-attributed window from THIS handler thread (the
        continuous profiler, when armed, keeps running independently)
        and render it folded (flamegraph text, the default), as a
        chrome-trace merge with the span ring, or as the JSON snapshot
        (what the router fan-out aggregates)."""
        from urllib.parse import parse_qs, urlparse
        q = parse_qs(urlparse(self.path).query)
        try:
            seconds = float(q.get("seconds", ["1.0"])[0])
        except ValueError:
            self._error(400, "seconds must be a number",
                        "/debug/profile")
            return
        fmt = q.get("format", ["folded"])[0]
        if fmt not in ("folded", "chrome", "json"):
            self._error(400, f"unknown format {fmt!r} (folded | "
                        "chrome | json)", "/debug/profile")
            return
        interval = (self.server._profile_interval
                    if self.server._profile_interval > 0 else 0.01)
        prof = _obs.SamplingProfiler(
            interval, phases=self.server._engine_phases)
        prof.profile_for(seconds)
        if fmt == "json":
            self._json(200, dict(prof.snapshot(), kind="replica"),
                       "/debug/profile")
            return
        if fmt == "chrome":
            self._json(200, {"traceEvents":
                             (_obs.tracer().chrome_events()
                              + prof.chrome_events()),
                             "stats": prof.stats()},
                       "/debug/profile")
            return
        text = (prof.folded() + "\n").encode()
        try:
            self.send_response(200)
            self.send_header("Content-Type", "text/plain")
            self.send_header("Content-Length", str(len(text)))
            self.end_headers()
            self.wfile.write(text)
        except (BrokenPipeError, ConnectionResetError,
                ConnectionAbortedError):
            pass
        _M_HTTP_REQS.labels("/debug/profile", "200").inc()

    def worker_stats(self) -> dict:
        return self.server.worker.stats()

    def do_POST(self):
        if self.path == "/v1/completions":
            self._completions()
        elif self.path == "/v1/batches":
            self._batches()
        elif self.path == "/drain":
            try:
                body = self._read_body()
            except (ValueError, json.JSONDecodeError):
                body = {}
            ok = self.server.worker.drain(timeout=body.get("timeout"))
            self._json(200 if ok else 504, {"drained": ok}, "/drain")
        elif self.path == "/resume":
            self.server.worker.resume()
            self._json(200, {"resumed": True}, "/resume")
        else:
            self._error(404, f"no route {self.path}", self.path)

    # ----------------------------------------------------------- batches
    def _batches(self):
        """``POST /v1/batches``: start an offline batch job.  Body:
        ``{"records": [{"prompt": [ids], ...}, ...]}`` for inline
        records or ``{"input_path": "file.jsonl"}`` for a server-side
        JSONL file; optional ``window`` / ``max_tokens`` / ``tenant`` /
        ``adapter`` / ``output_path``.  The job drip-feeds at the
        "batch" priority class (below every interactive name) and
        ``GET /v1/batches/<id>`` reports progress."""
        route = "/v1/batches"
        try:
            body = self._read_body()
        except (ValueError, json.JSONDecodeError):
            return self._error(400, "invalid JSON body", route)
        try:
            if not isinstance(body, dict):
                raise ValueError("request body must be a JSON object")
            kw = {"window": int(body.get("window", 2)),
                  "max_tokens": int(body.get("max_tokens", 16)),
                  "tenant": _parse_tenant(body.get("tenant")),
                  "adapter": _parse_adapter(body.get("adapter"))}
            if body.get("output_path") is not None:
                kw["output_path"] = str(body["output_path"])
            path = body.get("input_path")
            if path is not None:
                job = BatchJob.from_jsonl(str(path), **kw)
            elif isinstance(body.get("records"), list):
                job = BatchJob(body["records"], **kw)
            else:
                raise ValueError(
                    "pass 'records' (a list of {'prompt': [ids]} "
                    "objects) or 'input_path' (a server-side JSONL "
                    "file)")
        except OSError as e:
            return self._error(400, f"cannot read input_path: {e}",
                               route)
        except (ValueError, TypeError) as e:
            return self._error(400, str(e), route)
        try:
            self.server.worker.submit_batch(job)
        except DrainingError as e:
            return self._error(503, str(e), route,
                               etype="overloaded_error")
        _obs.flight("server", "batch_submit", job=job.id,
                    records=len(job.records))
        self._json(200, job.progress(), route)

    # ------------------------------------------------------- completions
    def _completions(self):
        # join the caller's distributed trace (W3C traceparent) — or
        # start a fresh one when the request arrived untraced
        parent = _obs.parse_traceparent(self.headers.get("traceparent"))
        span = _obs.tracer().start_span(
            "server.request", parent=parent,
            attributes={"route": "/v1/completions",
                        "model": self.server.model_name,
                        "remote": parent is not None})
        with span:
            self._completions_traced(span)

    def _completions_traced(self, span):
        route = "/v1/completions"
        t0 = time.monotonic()
        faults = self.server.worker.engine.faults
        if faults is not None and \
                faults.check("conn_reset", route=route) is not None:
            # synthetic peer reset before any response bytes: the client
            # sees RemoteDisconnected, the router's pre-response retry
            # path re-dispatches to another replica
            span.set_attribute("fault", "conn_reset")
            self._drop_connection()
            return
        try:
            body = self._read_body()
        except (ValueError, json.JSONDecodeError):
            _M_HTTP_REJECT.labels("invalid").inc()
            span.set_attribute("status", 400)
            return self._error(400, "invalid JSON body", route)
        try:
            prompt, gen, stream, timeout_s, priority, tenant, adapter = \
                _parse_completion(body)
            # the X-Priority / X-Tenant / X-Adapter headers override
            # the body (gateways tag traffic classes, billing
            # dimensions, and adapter routes without rewriting payloads)
            hdr = self.headers.get("X-Priority")
            if hdr is not None:
                priority = _parse_priority(hdr)
            hdr = self.headers.get("X-Tenant")
            if hdr is not None:
                tenant = _parse_tenant(hdr) or tenant
            hdr = self.headers.get("X-Adapter")
            if hdr is not None:
                adapter = _parse_adapter(hdr) or adapter
        except (ValueError, TypeError) as e:
            _M_HTTP_REJECT.labels("invalid").inc()
            span.set_attribute("status", 400)
            return self._error(400, str(e), route)
        span.set_attribute("stream", stream)
        if priority:
            span.set_attribute("priority", priority)
        if tenant:
            span.set_attribute("tenant", tenant)
        if adapter:
            span.set_attribute("adapter", adapter)

        toks: queue.Queue = queue.Queue()
        try:
            req = self.server.worker.submit(
                prompt, gen, timeout_s=timeout_s, trace=span.context,
                priority=priority, tenant=tenant, adapter=adapter,
                on_token=lambda r, t: toks.put(int(t)))
        except DrainingError as e:
            _M_HTTP_REJECT.labels("draining").inc()
            span.set_attribute("status", 503)
            return self._error(
                503, str(e), route, etype="overloaded_error",
                headers=[("Retry-After", f"{self.server.retry_after_s:g}")])
        except BackpressureError as e:
            _M_HTTP_REJECT.labels("backpressure").inc()
            span.set_attribute("status", 429)
            return self._error(
                429, str(e), route, etype="overloaded_error",
                headers=[("Retry-After", f"{self.server.retry_after_s:g}")])
        except (ValueError, TypeError, KeyError) as e:
            # engine-side validation; KeyError is an unknown adapter
            # name from the AdapterStore
            _M_HTTP_REJECT.labels("invalid").inc()
            span.set_attribute("status", 400)
            msg = e.args[0] if isinstance(e, KeyError) and e.args \
                else str(e)
            return self._error(400, str(msg), route)
        span.set_attribute("req", req.id)

        hard_deadline = t0 + (timeout_s or self.server.hard_timeout_s) \
            + 5.0
        _M_HTTP_INFLIGHT.inc()
        try:
            if stream:
                self._stream(req, toks, route, hard_deadline)
            else:
                self._blocking(req, toks, route, hard_deadline)
            if req.finish_reason is not None:
                span.set_attribute("finish_reason", req.finish_reason)
        finally:
            _M_HTTP_INFLIGHT.dec()
            self.server._latency.observe(time.monotonic() - t0)

    def _wait_token(self, req: Request, toks: queue.Queue,
                    hard_deadline: float):
        """Next token, or None when the request is finished and its
        queue is fully drained.  The hard deadline is a backstop for a
        wedged engine — the per-request timeout normally fires first
        through the engine's own deadline eviction."""
        while True:
            try:
                return toks.get(timeout=0.05)
            except queue.Empty:
                # on_token runs BEFORE finalize, so once is_finished()
                # is observed every token is already in the queue
                if req.is_finished() and toks.empty():
                    return None
                if time.monotonic() > hard_deadline:
                    req.cancel()
                    return None

    def _blocking(self, req: Request, toks: queue.Queue, route: str,
                  hard_deadline: float):
        while self._wait_token(req, toks, hard_deadline) is not None:
            pass
        if not req.is_finished():       # hard-timeout backstop tripped
            return self._error(504, "request timed out server-side",
                               route, etype="timeout_error")
        self._json(200, _completion_json(self.server.model_name, req),
                   route)

    def _stream(self, req: Request, toks: queue.Queue, route: str,
                hard_deadline: float):
        try:
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.send_header("Connection", "close")
            self.end_headers()
        except (OSError, ValueError):
            req.cancel()
            _M_HTTP_CANCELS.inc()
            return
        _M_HTTP_REQS.labels(route, "200").inc()
        self.close_connection = True
        name = self.server.model_name
        faults = self.server.worker.engine.faults
        sent = 0
        with _obs.tracer().start_span("server.stream") as ss:
            try:
                while True:
                    tok = self._wait_token(req, toks, hard_deadline)
                    if tok is None:
                        break
                    self._send_event(_chunk_json(name, req, tok, False))
                    sent += 1
                    if faults is not None and faults.check(
                            "stream_hangup", sent=sent,
                            req=req.id) is not None:
                        # synthetic mid-SSE hangup: hard-shutdown the
                        # socket so the NEXT write fails exactly like a
                        # real peer reset (the except below takes the
                        # cancel path, freeing the slot and its pages)
                        ss.set_attribute("fault", "stream_hangup")
                        self._drop_connection()
                self._send_event(_chunk_json(name, req, None, True))
                self.wfile.write(b"data: [DONE]\n\n")
                self.wfile.flush()
            except (OSError, ValueError):
                # OSError covers the peer-reset family (BrokenPipe/
                # ConnectionReset/ConnectionAborted/EBADF); ValueError is
                # "write to closed file" after an injected hangup
                # client went away mid-stream: cancel so the engine
                # frees the slot/pages at the next iteration boundary
                req.cancel()
                ss.set_attribute("cancelled", True)
                _M_HTTP_CANCELS.inc()
            ss.set_attribute("tokens", sent)

    def _send_event(self, obj: dict):
        self.wfile.write(b"data: " + json.dumps(obj).encode() + b"\n\n")
        # flush per event: SSE latency AND prompt disconnect detection
        self.wfile.flush()

    def _drop_connection(self):
        """Fault-injection helper: kill the client connection like a
        dying process would.  ``shutdown`` (not ``close``) — rfile/wfile
        hold the fd alive through socket refcounting, so a plain close
        would leave writes silently succeeding."""
        self.close_connection = True
        try:
            self.connection.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass


def serve(model=None, *, engine: Engine | None = None,
          host: str = "127.0.0.1", port: int = 0, max_queue: int = 64,
          retry_after_s: float = 1.0, model_name: str = "paddle-tpu",
          watchdog_s: float | None = None,
          timeseries_interval_s: float | None = None,
          profile_interval_s: float | None = None,
          start: bool = True, **engine_kw) -> ServingServer:
    """One-call server bring-up::

        server = serve(model, port=8000, max_slots=8,
                       enable_prefix_cache=True)
        print("listening on", server.address)

    Pass either a model (``engine_kw`` forwards to
    :func:`~paddle_tpu.serving.create_engine`) or a prebuilt
    ``engine=``.  With ``start=False`` the caller wires signals and
    starts the server itself.  ``watchdog_s`` arms the decode-loop
    watchdog (default: ``FLAGS_serving_watchdog_seconds``; 0 off),
    ``timeseries_interval_s`` arms the fleet-telemetry sampler
    (default: ``FLAGS_obs_timeseries_interval_s``; 0 off — nothing is
    built; with it on, alert fires also trigger diagnostic captures),
    ``profile_interval_s`` arms the continuous phase-attributed
    sampling profiler (default: ``FLAGS_obs_profile_interval_s``;
    0 off — nothing is built), and
    when the ``FLAGS_serving_slo_*`` targets are set the engine gets an
    :class:`~paddle_tpu.serving.slo.SLOTracker` automatically.
    """
    if engine is None:
        if model is None:
            raise ValueError("pass a model or engine=")
        from .engine import create_engine
        if "slo" not in engine_kw:
            from .slo import SLOConfig, SLOTracker
            slo_cfg = SLOConfig.from_flags()
            if slo_cfg.enabled:
                engine_kw["slo"] = SLOTracker(slo_cfg)
        if "usage" not in engine_kw \
                and FLAGS.get("FLAGS_serving_usage_meter"):
            from ..observability.usage import UsageMeter
            engine_kw["usage"] = UsageMeter(max_tenants=int(
                FLAGS.get("FLAGS_serving_usage_max_tenants") or 64))
        if "requestlog" not in engine_kw \
                and FLAGS.get("FLAGS_serving_request_log"):
            from ..observability.requestlog import RequestLog
            engine_kw["requestlog"] = RequestLog(
                k=int(FLAGS.get("FLAGS_serving_exemplars_k") or 8))
        engine = create_engine(model, **engine_kw)
    elif engine_kw:
        raise ValueError(f"engine= given; unexpected {sorted(engine_kw)}")
    worker = EngineWorker(engine, max_queue=max_queue)
    server = ServingServer(worker, host, port,
                           retry_after_s=retry_after_s,
                           model_name=model_name, watchdog_s=watchdog_s,
                           timeseries_interval_s=timeseries_interval_s,
                           profile_interval_s=profile_interval_s)
    if start:
        server.start()
    return server


def _main(argv=None):
    """Demo entry point: serve a randomly initialized tiny llama (no
    checkpoint needed) — the curl-able counterpart of
    tools/serve_bench.py::

        python -m paddle_tpu.serving.server --port 8000
        curl -s localhost:8000/v1/completions -d \\
            '{"prompt": [1,2,3], "max_tokens": 8}'
    """
    import argparse

    ap = argparse.ArgumentParser(description=_main.__doc__.splitlines()[0])
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8000)
    ap.add_argument("--max-slots", type=int, default=4)
    ap.add_argument("--page-size", type=int, default=16)
    ap.add_argument("--max-queue", type=int, default=64)
    ap.add_argument("--max-model-len", type=int, default=256)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--vocab", type=int, default=128)
    ap.add_argument("--prefix-cache",
                    action=argparse.BooleanOptionalAction, default=True)
    ap.add_argument("--sync-interval", type=int, default=1)
    ap.add_argument("--prefill-chunk", type=int, default=None,
                    help="chunked prefill: at most N prompt tokens per "
                    "engine step (0 = whole prompt; default "
                    "FLAGS_serving_prefill_chunk)")
    ap.add_argument("--preempt",
                    action=argparse.BooleanOptionalAction, default=None,
                    help="priority preempt-and-swap (default "
                    "FLAGS_serving_preempt); requests pick a class via "
                    "body 'priority' or the X-Priority header")
    ap.add_argument("--spec-k", type=int, default=None,
                    help="speculative decoding draft length (0 = off; "
                    "default FLAGS_serving_spec_k); greedy outputs are "
                    "identical either way")
    ap.add_argument("--emit-logits", action="store_true",
                    help="enable do_sample requests")
    ap.add_argument("--mesh", default=None,
                    help="tensor-parallel mesh size (e.g. 4 or tp=4); "
                    "default FLAGS_serving_mesh_tp.  CPU testing: "
                    "export XLA_FLAGS=--xla_force_host_platform_"
                    "device_count=N first")
    ap.add_argument("--quant", choices=("none", "int8", "int4"),
                    default="none",
                    help="weight-only quantized serving (default "
                    "FLAGS_serving_quant): int8/int4 QuantizedWeight "
                    "shards, embeddings/norms/lm_head stay dense")
    ap.add_argument("--kv-quant",
                    action=argparse.BooleanOptionalAction, default=None,
                    help="int8 KV pages with per-(page-row, head) f32 "
                    "scales (default FLAGS_serving_kv_quant)")
    args = ap.parse_args(argv)

    import paddle_tpu as paddle
    from ..models.llama import LlamaForCausalLM, llama_tiny
    paddle.seed(0)
    cfg = llama_tiny(num_hidden_layers=args.layers,
                     hidden_size=args.hidden,
                     intermediate_size=2 * args.hidden,
                     vocab_size=args.vocab, num_attention_heads=4,
                     num_key_value_heads=2,
                     max_position_embeddings=args.max_model_len)
    model = LlamaForCausalLM(cfg)
    model.eval()
    server = serve(model, host=args.host, port=args.port,
                   max_queue=args.max_queue, max_slots=args.max_slots,
                   page_size=args.page_size,
                   max_model_len=args.max_model_len,
                   emit_logits=args.emit_logits,
                   enable_prefix_cache=args.prefix_cache,
                   sync_interval=args.sync_interval, mesh=args.mesh,
                   spec_k=args.spec_k,
                   prefill_chunk=args.prefill_chunk,
                   preempt=args.preempt,
                   quant=(None if args.quant == "none" else args.quant),
                   kv_quant=args.kv_quant, start=False)
    server.install_signal_handlers()
    server.start()
    print(f"serving on http://{server.address} "
          f"(SIGTERM drains gracefully)")
    try:
        while server._serve_thread.is_alive():
            server._serve_thread.join(timeout=1.0)
    except KeyboardInterrupt:
        server.stop()
    if server._stop_thread is not None:     # signal-driven shutdown:
        server._stop_thread.join(timeout=30.0)  # let the drain finish
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(_main())
