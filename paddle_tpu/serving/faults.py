"""Deterministic fault injection for the serving stack (chaos harness).

A :class:`FaultPlan` holds a list of *entries*, each naming an
injection **site** (a string checked at an existing seam) plus a firing
rule — ``at=N`` (fire on the Nth matching check, deterministic) or
``p=P`` (fire with probability P per check, from a seeded RNG) — and
optional match/behavior params.  Every seam asks
``plan.check(site, **ctx)`` and gets back the entry's params dict when
the fault fires, ``None`` otherwise.

Zero overhead when off, same model as the sanitizer factories: holders
keep ``faults = None`` by default and every site guards with
``if self.faults is not None`` — no plan object, no call, no branch
beyond the None test.  Plans come from ``FLAGS_serving_fault_plan``
(env-settable) via :func:`fault_plan_from_flags`, or are built
programmatically in tests/benchmarks.

Known sites (the seam that checks each one):

===============  ====================================================
site             seam
===============  ====================================================
``step_raise``   engine decode: raise :class:`InjectedFault` before
                 dispatching the jitted decode step (poisoned runner)
``nan_logits``   engine sampling: overwrite one slot's logits row with
                 NaN before token selection (params: ``slot``)
``page_alloc``   BlockManager page acquisition: report synthetic
                 device-OOM (allocation returns None → backpressure)
``slow_step``    engine decode: sleep ``seconds`` before the step
                 (watchdog/stall food; params: ``seconds``)
``conn_reset``   HTTP server: close the client connection before any
                 response bytes (connection reset)
``stream_hangup``  HTTP server: kill the socket mid-SSE after
                 ``sent`` streamed tokens (dead replica mid-stream)
``spill_fail``   engine preemption: fail the device→host KV page copy
                 of a preempt-and-swap spill — the preemption must
                 abort cleanly (victim keeps its device pages and
                 slot; pool census leak stays 0; params: ``req``,
                 ``page`` match filters)
===============  ====================================================

Every firing increments ``serving_fault_injected_total{site}`` and
stamps a ``fault`` event into the flight recorder, so injected chaos is
visible in /metrics and /debug/flight exactly like organic failures.
"""
from __future__ import annotations

import random

from .. import observability as _obs
from ..flags import FLAGS

__all__ = ["FaultPlan", "InjectedFault", "fault_plan_from_flags"]

_M_INJECTED = _obs.counter(
    "serving_fault_injected_total",
    "faults injected by the chaos harness, by site",
    ("site",))


class InjectedFault(RuntimeError):
    """Raised by seams that inject a failure by raising (step_raise).

    Deliberately a RuntimeError subclass: recovery paths must treat it
    exactly like an organic poisoned-step error — tests that catch
    InjectedFault specially would prove nothing about real faults.
    """


class _Entry:
    __slots__ = ("site", "at", "times", "p", "params", "match", "seen",
                 "fired")

    def __init__(self, site, at, times, p, params, match):
        self.site = site
        self.at = at          # fire on the at-th matching check (1-based)
        self.times = times    # consecutive firings once triggered
        self.p = p            # per-check probability (alternative to at)
        self.params = params  # behavior params handed to the seam
        self.match = match    # ctx keys that must equal to count a check
        self.seen = 0         # matching checks so far
        self.fired = 0        # firings so far


class FaultPlan:
    """Seedable, deterministic fault schedule.

    Not thread-safe by design: entry counters are simple ints mutated
    on the engine/server threads that own each site.  Probabilistic
    entries draw from one ``random.Random(seed)`` in check order, so a
    fixed seed plus a deterministic driver replays the same faults.
    """

    def __init__(self, seed: int = 0):
        self.seed = seed
        self._rng = random.Random(seed)
        self._entries: list[_Entry] = []
        self.injected: dict[str, int] = {}  # site -> count (test mirror)

    # -------------------------------------------------------- building
    def add(self, site: str, *, at: int | None = None,
            p: float | None = None, times: int = 1, **params):
        """Schedule a fault at ``site``.

        ``at=N`` fires on the Nth matching check; ``p=P`` fires each
        check with probability P (exactly one of the two).  ``times``
        extends an ``at`` firing to N..N+times-1.  Non-rule keyword args
        are params: keys the seam passes in ``check(**ctx)`` act as
        match filters (e.g. ``slot=1`` only counts checks for slot 1),
        the rest ride along in the returned dict (e.g. ``seconds=0.2``).
        """
        if (at is None) == (p is None):
            raise ValueError(
                f"fault {site!r}: exactly one of at= or p= required")
        if at is not None and at < 1:
            raise ValueError(f"fault {site!r}: at= is 1-based, got {at}")
        self._entries.append(_Entry(site, at, times, p, dict(params), None))
        return self

    @classmethod
    def from_spec(cls, spec: str) -> "FaultPlan":
        """Parse the ``FLAGS_serving_fault_plan`` grammar.

        Comma-separated entries: ``seed=S`` sets the plan seed,
        ``site@N[:k=v]*`` is ``add(site, at=N, ...)``,
        ``site~P[:k=v]*`` is ``add(site, p=P, ...)``.  Param values
        parse as int, then float, else string.
        """
        seed = 0
        pending = []
        for raw in spec.split(","):
            raw = raw.strip()
            if not raw:
                continue
            if raw.startswith("seed="):
                seed = int(raw[5:])
                continue
            head, *parts = raw.split(":")
            if "@" in head:
                site, _, n = head.partition("@")
                rule = {"at": int(n)}
            elif "~" in head:
                site, _, prob = head.partition("~")
                rule = {"p": float(prob)}
            else:
                raise ValueError(
                    f"fault spec entry {raw!r}: need site@N or site~P")
            params = {}
            for part in parts:
                k, _, v = part.partition("=")
                params[k] = _parse_value(v)
            pending.append((site, rule, params))
        plan = cls(seed=seed)
        for site, rule, params in pending:
            plan.add(site, **rule, **params)
        return plan

    # -------------------------------------------------------- checking
    def check(self, site: str, **ctx):
        """Ask whether a fault fires at ``site`` for this check.

        Returns the entry's params dict when one fires (seams read
        behavior params like ``seconds`` from it), else None.  Match
        params — entry params whose key appears in ``ctx`` — must equal
        the ctx value for the check to count against that entry.
        """
        for e in self._entries:
            if e.site != site:
                continue
            matched = True
            for k, v in e.params.items():
                if k in ctx and ctx[k] != v:
                    matched = False
                    break
            if not matched:
                continue
            e.seen += 1
            if e.p is not None:
                fire = self._rng.random() < e.p
            else:
                fire = e.at <= e.seen < e.at + e.times
            if fire:
                e.fired += 1
                self.injected[site] = self.injected.get(site, 0) + 1
                _M_INJECTED.labels(site=site).inc()
                _obs.flight("fault", "injected", site=site,
                            **{k: v for k, v in ctx.items()
                               if isinstance(v, (int, float, str))})
                return e.params
        return None

    def stats(self) -> dict:
        return {"seed": self.seed, "injected": dict(self.injected),
                "entries": [{"site": e.site, "at": e.at, "p": e.p,
                             "times": e.times, "seen": e.seen,
                             "fired": e.fired, "params": dict(e.params)}
                            for e in self._entries]}

    def __repr__(self):
        sites = ",".join(sorted({e.site for e in self._entries}))
        return f"FaultPlan(seed={self.seed}, sites=[{sites}])"


def _parse_value(v: str):
    for t in (int, float):
        try:
            return t(v)
        except ValueError:
            pass
    return v


def fault_plan_from_flags() -> FaultPlan | None:
    """Build a plan from ``FLAGS_serving_fault_plan``; None when the
    flag is empty — the holder then skips every site check entirely."""
    spec = FLAGS["FLAGS_serving_fault_plan"]
    return FaultPlan.from_spec(spec) if spec else None
