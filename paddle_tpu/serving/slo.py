"""Per-request SLO tracking for the serving engine.

Every finished request is checked against configurable latency targets
— TTFT (arrival -> first token), TPOT (average inter-token latency),
and E2E (arrival -> completion) — and the verdicts feed:

  * ``serving_slo_requests_total{dimension, result}`` — good /
    violation counters per dimension (the raw SLI);
  * ``serving_slo_burn_rate{dimension}`` — violation rate over the
    last ``window`` finished requests divided by the error budget
    ``1 - objective``.  Burn rate 1.0 means the service is consuming
    its budget exactly as fast as the objective allows; > 1.0 means
    an alert-worthy burn (the multiwindow-burn-rate alerting input).

Targets come from an explicit :class:`SLOConfig` or from flags
(``FLAGS_serving_slo_ttft_ms`` / ``_tpot_ms`` / ``_e2e_ms``, with
``FLAGS_serving_slo_objective``); a dimension with target 0 is not
checked.  A request that finishes without ever producing a first token
(cancelled / deadline-evicted while queued) counts as a TTFT and E2E
violation when those targets are set — it never met any latency bar.
"""
from __future__ import annotations

from collections import deque
from dataclasses import dataclass

from .. import observability as _obs
from ..sanitizer import make_lock

__all__ = ["SLOConfig", "SLOTracker"]

_M_SLO = _obs.counter(
    "serving_slo_requests_total",
    "per-request SLO verdicts by dimension (ttft/tpot/e2e) and result "
    "(good/violation)", ("dimension", "result"))
_M_BURN = _obs.gauge(
    "serving_slo_burn_rate",
    "violation rate over the recent request window / error budget "
    "(1-objective); sustained > 1.0 burns the SLO", ("dimension",))


@dataclass(frozen=True)
class SLOConfig:
    """Latency targets in seconds; 0 disables a dimension."""
    ttft_s: float = 0.0
    tpot_s: float = 0.0
    e2e_s: float = 0.0
    objective: float = 0.99

    @classmethod
    def from_flags(cls) -> "SLOConfig":
        from ..flags import FLAGS
        return cls(
            ttft_s=float(FLAGS.get("FLAGS_serving_slo_ttft_ms") or 0.0)
            / 1e3,
            tpot_s=float(FLAGS.get("FLAGS_serving_slo_tpot_ms") or 0.0)
            / 1e3,
            e2e_s=float(FLAGS.get("FLAGS_serving_slo_e2e_ms") or 0.0)
            / 1e3,
            objective=float(FLAGS.get("FLAGS_serving_slo_objective")
                            or 0.99))

    @property
    def enabled(self) -> bool:
        return self.ttft_s > 0 or self.tpot_s > 0 or self.e2e_s > 0


class SLOTracker:
    """Sliding-window SLO accounting.  ``observe(req, now)`` is called
    once per finished request from ``Engine._finalize``."""

    def __init__(self, config: SLOConfig, window: int = 256):
        if not 0.0 < config.objective < 1.0:
            raise ValueError(
                f"objective must be in (0, 1), got {config.objective}")
        self.config = config
        self.window = int(window)
        self._lock = make_lock("SLOTracker._lock")
        self._recent: dict[str, deque] = {
            d: deque(maxlen=self.window) for d in ("ttft", "tpot", "e2e")}
        # python-side mirrors (stats()/tests without registry spelunking)
        self.good: dict[str, int] = {d: 0 for d in self._recent}
        self.violations: dict[str, int] = {d: 0 for d in self._recent}
        # optional per-verdict mirror, called as (req, dimension, ok)
        # alongside each _check — the usage meter wires it to attribute
        # SLO verdicts to the request's tenant (None = off)
        self.verdict_hook = None
        # optional violation-exemplar hook, called as (req, dimension,
        # ok, measured_seconds) — the request log wires it to snapshot
        # the violating request's timeline + attribution, carrying its
        # trace id so /debug/trace and /debug/exemplars join on one id
        # (None = off; measured is None when no first token landed)
        self.exemplar_hook = None

    def observe(self, req, now: float):
        cfg = self.config
        ttft = (None if req.first_token_at is None
                else req.first_token_at - req.arrival_time)
        tpot = None
        if req.num_generated > 1 and req.first_token_at is not None \
                and req.last_token_at is not None:
            tpot = ((req.last_token_at - req.first_token_at)
                    / (req.num_generated - 1))
        e2e = now - req.arrival_time
        if cfg.ttft_s > 0:
            # no first token at all = the request never met ANY bar
            self._verdict(req, "ttft",
                          ttft is not None and ttft <= cfg.ttft_s,
                          ttft)
        if cfg.tpot_s > 0 and tpot is not None:
            self._verdict(req, "tpot", tpot <= cfg.tpot_s, tpot)
        if cfg.e2e_s > 0:
            self._verdict(req, "e2e", e2e <= cfg.e2e_s, e2e)

    def _verdict(self, req, dim: str, ok: bool,
                 value: float | None = None):
        self._check(dim, ok)
        if self.verdict_hook is not None:
            self.verdict_hook(req, dim, ok)
        if self.exemplar_hook is not None:
            self.exemplar_hook(req, dim, ok, value)

    def _check(self, dim: str, ok: bool):
        budget = max(1.0 - self.config.objective, 1e-9)
        with self._lock:
            win = self._recent[dim]
            win.append(0 if ok else 1)
            if ok:
                self.good[dim] += 1
            else:
                self.violations[dim] += 1
            rate = sum(win) / len(win)
        _M_SLO.labels(dim, "good" if ok else "violation").inc()
        _M_BURN.labels(dim).set(rate / budget)

    def burn_rate(self, dim: str) -> float:
        budget = max(1.0 - self.config.objective, 1e-9)
        with self._lock:
            win = self._recent[dim]
            rate = (sum(win) / len(win)) if win else 0.0
        return rate / budget

    def burn_rates(self) -> dict:
        """Per-dimension burn rate for every configured dimension —
        the fleet summary block /debug/fleet publishes per replica."""
        cfg = self.config
        return {d: self.burn_rate(d)
                for d, t in (("ttft", cfg.ttft_s), ("tpot", cfg.tpot_s),
                             ("e2e", cfg.e2e_s)) if t > 0}

    def max_burn_rate(self) -> float:
        """Worst burn rate across the configured dimensions — the load-
        shedding signal (``FLAGS_serving_shed_burn_rate``).  0.0 when no
        dimension has a target or nothing finished yet."""
        cfg = self.config
        dims = [d for d, t in (("ttft", cfg.ttft_s), ("tpot", cfg.tpot_s),
                               ("e2e", cfg.e2e_s)) if t > 0]
        if not dims:
            return 0.0
        return max(self.burn_rate(d) for d in dims)

    def stats(self) -> dict:
        burn = {d: round(r, 6) for d, r in self.burn_rates().items()}
        with self._lock:
            return {"targets": {"ttft_s": self.config.ttft_s,
                                "tpot_s": self.config.tpot_s,
                                "e2e_s": self.config.e2e_s,
                                "objective": self.config.objective},
                    "good": dict(self.good),
                    "violations": dict(self.violations),
                    "burn_rates": burn}
