"""Multi-replica router: prefix-affinity load balancing + health circuit.

The scaling layer above ``server.py``: N independent engine replicas
(each its own process or in-process server), one front door.

  * **Prefix-affinity routing** — requests whose prompts share the same
    page-aligned leading chunk rendezvous-hash to the same replica, so
    the PR-3 prefix cache keeps its hit rate under multi-replica
    scale-out (a shared system prompt's KV pages stay hot on ONE
    replica instead of being rebuilt on all of them).  Prompts shorter
    than a page, or whose affinity target is down, fall back to the
    least-loaded replica.  Requests naming a LoRA adapter salt the
    rendezvous key with the adapter name, so each adapter's traffic
    concentrates where its bank row is already resident.
  * **Health probing + circuit breaking** — a prober hits each
    replica's ``/healthz``; ``fail_threshold`` consecutive failures
    open the circuit (replica leaves rotation), and the replica is
    re-admitted after ``cooldown_s`` (or immediately on a successful
    probe).  Request-level transport failures count toward the same
    circuit.
  * **Bounded retry** — a transport failure *before any response
    bytes* (connection refused/reset at send) is idempotent to retry:
    the router retries on up to ``max_retries`` other replicas.
    HTTP-level answers (429 backpressure, 400 validation) are never
    retried — the replica spoke.

Use programmatically (:meth:`Router.completion`) or as an HTTP
front-end (:meth:`Router.serve` — same wire protocol as ``server.py``,
so :class:`~paddle_tpu.serving.ServingClient` points at either).
"""
from __future__ import annotations

import hashlib
import http.client
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from .. import observability as _obs
from ..sanitizer import make_lock
from .client import ServingClient, ServingHTTPError

__all__ = ["NoReplicaAvailable", "Replica", "Router", "RouterServer"]

_M_REQS = _obs.counter(
    "router_requests_total",
    "requests routed, by replica and outcome ('ok', 'error', or "
    "'http_<status>' when the replica answered non-2xx)",
    ("replica", "outcome"))
_M_RETRIES = _obs.counter(
    "router_retries_total",
    "requests retried on another replica after an idempotent "
    "transport failure")
_M_FAILOVERS = _obs.counter(
    "router_failovers_total",
    "mid-stream failovers: a replica died after response bytes flowed "
    "and the stream was resumed on a healthy replica by re-submitting "
    "prompt + delivered tokens (idempotent requests only: greedy, or "
    "sampled with an explicit seed)")
_M_UP = _obs.gauge(
    "router_replica_up",
    "1 = replica in rotation, 0 = circuit open", ("replica",))
_M_PROBES = _obs.counter(
    "router_probes_total", "health probes", ("replica", "result"))
_M_PICKS = _obs.counter(
    "router_picks_total",
    "replica selection path: 'affinity' (prefix hash target), "
    "'least_loaded' (no page-aligned prefix, or target down)",
    ("kind",))
_M_FLEET = _obs.counter(
    "router_fleet_collections_total",
    "/debug/fleet summary fetches piggybacked on the health sweep "
    "('fail' degrades the cluster view, never the circuit)",
    ("replica", "result"))
_M_EXPECTED_HIT = _obs.gauge(
    "router_expected_prefix_hit_rate",
    "last expected-prefix-hit-rate estimate per replica: 1.0 when the "
    "prompt's root chunk digest is in the replica's published prefix "
    "digest, else the replica's observed hit rate as a prior",
    ("replica",))


class NoReplicaAvailable(RuntimeError):
    """Every replica is excluded or circuit-open."""


class Replica:
    """One backend endpoint + its circuit-breaker state."""

    def __init__(self, address):
        self.address = ServingClient(address).address   # normalized
        self.fails = 0              # consecutive probe/request failures
        self.down_until = 0.0       # monotonic; 0 = in rotation
        self.inflight = 0
        self.last_error: str | None = None
        self.stats: dict = {}       # last /healthz payload
        self.fleet: dict | None = None  # last /debug/fleet summary
        self.fleet_at = 0.0         # monotonic collection time
        _M_UP.labels(self.address).set(1)

    def available(self, now: float) -> bool:
        return now >= self.down_until

    def snapshot(self, now: float) -> dict:
        return {"address": self.address,
                "up": self.available(now),
                "fails": self.fails,
                "inflight": self.inflight,
                "cooldown_remaining_s": max(0.0, self.down_until - now),
                "last_error": self.last_error}


class Router:
    """Load balancer over N serving replicas.

    ``addresses`` are ``host:port`` strings.  ``page_size`` must match
    the replicas' engine page size — the affinity key is the prompt's
    first ``affinity_pages`` full pages, so only page-aligned sharing
    (what the prefix cache can actually reuse) influences routing.
    """

    def __init__(self, addresses, *, page_size: int = 64,
                 affinity_pages: int = 1, fail_threshold: int = 3,
                 cooldown_s: float = 2.0, max_retries: int = 1,
                 probe_interval_s: float = 1.0,
                 probe_timeout_s: float = 2.0,
                 request_timeout_s: float = 120.0,
                 clock=time.monotonic):
        if not addresses:
            raise ValueError("router needs at least one replica address")
        if fail_threshold < 1:
            raise ValueError("fail_threshold must be >= 1")
        self.replicas = [Replica(a) for a in addresses]
        self.page_size = int(page_size)
        self.affinity_pages = int(affinity_pages)
        self.fail_threshold = int(fail_threshold)
        self.cooldown_s = float(cooldown_s)
        self.max_retries = int(max_retries)
        self.probe_interval_s = float(probe_interval_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.request_timeout_s = float(request_timeout_s)
        self._clock = clock
        self._lock = make_lock("Router._lock")
        self._probe_stop = threading.Event()
        self._probe_thread: threading.Thread | None = None
        self.failovers = 0          # mirror of router_failovers_total

    # ------------------------------------------------------- selection
    def _affinity_key(self, prompt, adapter: str | None = None) \
            -> bytes | None:
        """Rendezvous key: the prompt's page-aligned leading chunk,
        salted with the adapter name when one is set — adapter traffic
        sticks to one replica (its bank row stays loaded there), and
        two adapters over the same shared prompt can land on different
        replicas instead of thrashing one bank.  Dense requests keep
        the exact pre-LoRA key bytes."""
        ids = np.asarray(prompt, np.int32).reshape(-1)
        aligned = (ids.size // self.page_size) * self.page_size
        take = min(aligned, self.affinity_pages * self.page_size)
        chunk = ids[:take].tobytes() if take > 0 else b""
        if not chunk and adapter is None:
            return None
        tag = b"" if adapter is None else adapter.encode() + b"\x00"
        return hashlib.sha1(tag + chunk).digest()

    @staticmethod
    def _rendezvous_score(key: bytes, address: str) -> int:
        h = hashlib.sha1(key + address.encode()).digest()
        return int.from_bytes(h[:8], "big")

    @staticmethod
    def resumable(kw: dict) -> bool:
        """Whether a request may be re-dispatched after tokens flowed:
        greedy requests resume exactly (same prompt prefix -> same
        continuation); sampled requests only when the caller pinned an
        explicit seed (best effort — the replica mixes the request id
        into its RNG stream, so the resumed suffix is *a* valid sample,
        not bit-identical to the lost one)."""
        do_sample = kw.get("do_sample")
        if do_sample is None:
            do_sample = ("temperature" in kw
                         and float(kw.get("temperature") or 0.0) > 0.0)
        return (not do_sample) or (kw.get("seed") is not None)

    def pick(self, prompt, exclude=(),
             adapter: str | None = None) -> Replica:
        """Choose a replica for this prompt (and adapter, when the
        request names one).  Raises :class:`NoReplicaAvailable` when
        nothing is in rotation."""
        now = self._clock()
        with self._lock:
            avail = [r for r in self.replicas
                     if r not in exclude and r.available(now)]
            if not avail:
                raise NoReplicaAvailable(
                    "no replica available: "
                    + ", ".join(f"{r.address} "
                                f"(fails={r.fails}, "
                                f"excluded={r in exclude})"
                                for r in self.replicas))
            key = self._affinity_key(prompt, adapter)
            if key is not None:
                # rendezvous over the FULL replica set (stable as
                # replicas flap), honored only while the winner is up
                winner = max(self.replicas,
                             key=lambda r: self._rendezvous_score(
                                 key, r.address))
                if winner in avail:
                    _M_PICKS.labels("affinity").inc()
                    return winner
            chosen = min(avail, key=lambda r: (r.inflight, r.address))
            _M_PICKS.labels("least_loaded").inc()
            return chosen

    # --------------------------------------------------------- circuit
    def _mark_success(self, rep: Replica):
        with self._lock:
            rep.fails = 0
            rep.down_until = 0.0
            rep.last_error = None
        _M_UP.labels(rep.address).set(1)

    def _mark_failure(self, rep: Replica, err: BaseException):
        with self._lock:
            rep.fails += 1
            rep.last_error = repr(err)
            if rep.fails >= self.fail_threshold:
                rep.down_until = self._clock() + self.cooldown_s
                opened = True
            else:
                opened = False
        if opened:
            _M_UP.labels(rep.address).set(0)

    # --------------------------------------------------------- probing
    def probe_once(self):
        """One health sweep over every replica (the prober thread calls
        this every ``probe_interval_s``; tests call it directly).  Each
        healthy probe also piggybacks a ``/debug/fleet`` summary fetch
        on the same sweep — the replica just answered /healthz, so a
        fleet failure (e.g. an older build without the route) only
        degrades the cluster view, never the circuit."""
        for rep in self.replicas:
            client = ServingClient(rep.address,
                                   timeout=self.probe_timeout_s)
            try:
                st = client.healthz()
                rep.stats = st
                self._mark_success(rep)
                _M_PROBES.labels(rep.address, "ok").inc()
            except Exception as e:      # refused, reset, timeout, 5xx
                self._mark_failure(rep, e)
                _M_PROBES.labels(rep.address, "fail").inc()
                rep.fleet = None        # stale census must not linger
                continue
            try:
                rep.fleet = client.request("GET", "/debug/fleet")
                rep.fleet_at = self._clock()
                _M_FLEET.labels(rep.address, "ok").inc()
            except Exception:
                rep.fleet = None
                _M_FLEET.labels(rep.address, "fail").inc()

    def start_probing(self) -> "Router":
        if self._probe_thread is None:
            def loop():
                while not self._probe_stop.wait(self.probe_interval_s):
                    self.probe_once()
            self._probe_thread = threading.Thread(
                target=loop, name="router-prober", daemon=True)
            self._probe_thread.start()
        return self

    def stop(self):
        self._probe_stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=5.0)
            self._probe_thread = None

    # ------------------------------------------------------ completion
    def completion(self, prompt, *, stream: bool = False, **kw):
        """Route one completion.  Transport failures before any
        response bytes retry on up to ``max_retries`` other replicas;
        HTTP answers (429/503/400...) propagate as ServingHTTPError.

        Opens a ``router.request`` span covering pick + retry; the
        :class:`ServingClient` call inside nests under it (contextvar)
        and carries the trace to the replica as a traceparent header."""
        with _obs.tracer().start_span(
                "router.request",
                attributes={"stream": bool(stream)}) as span:
            return self._completion_traced(span, prompt, stream=stream,
                                           **kw)

    def _completion_traced(self, span, prompt, *, stream, **kw):
        tried: list[Replica] = []
        last_exc: BaseException | None = None
        adapter = kw.get("adapter")
        for attempt in range(self.max_retries + 1):
            try:
                rep = self.pick(prompt, exclude=tried, adapter=adapter)
            except NoReplicaAvailable:
                if last_exc is None:
                    raise
                raise NoReplicaAvailable(
                    "all retry candidates failed "
                    f"(last: {last_exc!r})") from last_exc
            span.set_attribute("replica", rep.address)
            span.set_attribute("attempts", attempt + 1)
            client = ServingClient(rep.address,
                                   timeout=self.request_timeout_s)
            with self._lock:
                rep.inflight += 1
            try:
                if stream:
                    # connection + status check happen before the
                    # generator is returned, so a refused/reset replica
                    # still lands in the retry path below
                    events = client.completion(prompt, stream=True, **kw)
                    return self._stream_through(rep, events,
                                                prompt=prompt, kw=kw,
                                                tried=tried)
                out = client.completion(prompt, **kw)
            except ServingHTTPError as e:
                # the replica ANSWERED — it is alive; never retried
                with self._lock:
                    rep.inflight -= 1
                self._mark_success(rep)
                _M_REQS.labels(rep.address, f"http_{e.status}").inc()
                raise
            except OSError as e:
                with self._lock:
                    rep.inflight -= 1
                self._mark_failure(rep, e)
                _M_REQS.labels(rep.address, "error").inc()
                tried.append(rep)
                last_exc = e
                if attempt < self.max_retries:
                    _M_RETRIES.inc()
                    span.add_event("retry", replica=rep.address,
                                   error=repr(e))
                continue
            with self._lock:
                rep.inflight -= 1
            self._mark_success(rep)
            _M_REQS.labels(rep.address, "ok").inc()
            return out
        raise NoReplicaAvailable(
            f"request failed on {len(tried)} replica(s) "
            f"(last: {last_exc!r})") from last_exc

    def _stream_through(self, rep: Replica, events, *, prompt=None,
                        kw=None, tried=None):
        """Wrap a replica's SSE stream: success/failure feeds the
        circuit, inflight releases when the stream ends.

        Mid-stream death of the replica — a transport error, or the
        stream ending before the final (finish_reason-bearing) chunk —
        **fails over** when the request is :meth:`resumable`: the
        router re-submits ``prompt + delivered tokens`` (with
        ``max_tokens`` reduced accordingly) to a healthy replica and
        keeps yielding, so the consumer sees one complete token
        sequence.  Non-resumable streams keep the old semantics: the
        error (or truncation) surfaces to the caller."""
        kw = dict(kw or {})
        can_resume = prompt is not None and self.resumable(kw)
        max_tokens = int(kw.get("max_tokens", 16))
        tried = list(tried or [])

        def gen():
            cur_rep, cur_events = rep, events
            delivered: list[int] = []
            failovers_left = self.max_retries
            while True:
                finished = False
                err: BaseException | None = None
                try:
                    try:
                        for ev in cur_events:
                            ch = ev["choices"][0]
                            delivered.extend(
                                int(t) for t in (ch.get("token_ids")
                                                 or ()))
                            if ch.get("finish_reason") is not None:
                                finished = True
                            yield ev
                    except OSError as e:
                        err = e
                except BaseException:
                    # GeneratorExit (consumer closed the stream) or an
                    # error thrown in: release inflight and propagate
                    with self._lock:
                        cur_rep.inflight -= 1
                    raise
                with self._lock:
                    cur_rep.inflight -= 1
                if err is None and finished:
                    self._mark_success(cur_rep)
                    _M_REQS.labels(cur_rep.address, "ok").inc()
                    return
                # the replica died mid-stream (transport error, or EOF
                # before the final chunk — a hangup surfaces as a clean
                # close on the client side)
                if err is None:
                    err = ConnectionError(
                        "stream ended before the final chunk")
                self._mark_failure(cur_rep, err)
                _M_REQS.labels(cur_rep.address, "error").inc()
                tried.append(cur_rep)
                if not can_resume or failovers_left <= 0:
                    raise err
                remaining = max_tokens - len(delivered)
                if remaining <= 0:
                    return      # every token was already delivered
                resume_prompt = [int(t) for t in prompt] + delivered
                resume_kw = dict(kw, max_tokens=remaining,
                                 resume_from=len(delivered))
                switched = False
                while failovers_left > 0 and not switched:
                    failovers_left -= 1
                    try:
                        nxt = self.pick(resume_prompt, exclude=tried,
                                        adapter=kw.get("adapter"))
                    except NoReplicaAvailable:
                        break
                    client = ServingClient(
                        nxt.address, timeout=self.request_timeout_s)
                    with self._lock:
                        nxt.inflight += 1
                    try:
                        cur_events = client.completion(resume_prompt,
                                                       stream=True,
                                                       **resume_kw)
                    except (OSError, ServingHTTPError) as e:
                        with self._lock:
                            nxt.inflight -= 1
                        self._mark_failure(nxt, e)
                        _M_REQS.labels(nxt.address, "error").inc()
                        tried.append(nxt)
                        continue
                    switched = True
                if not switched:
                    raise err
                with self._lock:
                    self.failovers += 1
                _M_FAILOVERS.inc()
                _obs.flight("router", "failover",
                            from_=cur_rep.address, to=nxt.address,
                            delivered=len(delivered),
                            remaining=remaining)
                cur_rep = nxt
        return gen()

    # ------------------------------------------------------- fleet view
    def _root_chunk_digest(self, prompt) -> str | None:
        """sha1 (16 hex chars) of the prompt's first full page chunk —
        the exact hash replicas publish for their root-level cached
        chunks (BlockManager.prefix_digest), so digest equality means
        the replica already holds this prompt's leading KV pages."""
        ids = np.asarray(prompt, np.int32).reshape(-1)
        if ids.size < self.page_size:
            return None
        return hashlib.sha1(
            ids[:self.page_size].tobytes()).hexdigest()[:16]

    def prefix_hit_estimate(self, prompt,
                            adapter: str | None = None) -> dict:
        """Per-replica expected-hit-rate estimate for a prompt: 1.0
        when the prompt's root chunk digest appears in the replica's
        published prefix digest, else the replica's observed hit rate
        as a prior (0.0 with no summary).  When the request names an
        ``adapter``, the estimate blends in adapter-bank residency
        (the replica's fleet summary publishes its resident adapter
        names): a replica that would have to LRU-load the adapter
        before serving averages its prefix estimate with 0.  This is
        the routing signal cluster-scale KV scheduling consumes;
        estimates are also recorded on
        ``router_expected_prefix_hit_rate{replica}``."""
        digest = self._root_chunk_digest(prompt)
        out = {}
        for rep in self.replicas:
            prefix = (rep.fleet or {}).get("prefix") or {}
            published = (prefix.get("roots") or []
                         if prefix.get("page_size") == self.page_size
                         else [])
            if digest is not None and digest in published:
                est = 1.0
            else:
                est = float(prefix.get("hit_rate") or 0.0)
            if adapter is not None:
                resident = ((rep.fleet or {}).get("adapters")
                            or {}).get("resident") or []
                est = (est + (1.0 if adapter in resident else 0.0)) / 2.0
            out[rep.address] = round(est, 6)
            _M_EXPECTED_HIT.labels(rep.address).set(est)
        return out

    def fleet(self) -> dict:
        """Aggregate cluster view over the last collected per-replica
        summaries — served by the router's own ``GET /debug/fleet``."""
        now = self._clock()
        replicas, alerts = {}, []
        pages = {"total": 0, "live": 0, "cached": 0, "free": 0}
        slots = {"active": 0, "max": 0, "free": 0}
        queue_depth, burn_max, summaries = 0, 0.0, 0
        digests: set = set()
        for rep in self.replicas:
            entry = rep.snapshot(now)
            fl = rep.fleet
            if fl:
                summaries += 1
                entry["summary"] = fl
                entry["summary_age_s"] = round(
                    max(0.0, now - rep.fleet_at), 3)
                pool = fl.get("pool") or {}
                for k in pages:
                    pages[k] += int(pool.get(k) or 0)
                for k in slots:
                    slots[k] += int((fl.get("slots") or {}).get(k) or 0)
                queue_depth += int((fl.get("queue") or {}).get("depth")
                                   or 0)
                burn_max = max(burn_max, float(
                    (fl.get("slo") or {}).get("max_burn_rate") or 0.0))
                for a in (fl.get("alerts") or {}).get("firing") or []:
                    alerts.append({"replica": rep.address, **a})
                prefix = fl.get("prefix") or {}
                digests.update(prefix.get("roots") or [])
                entry["expected_prefix_hit_rate"] = prefix.get(
                    "hit_rate")
            replicas[rep.address] = entry
        with self._lock:
            failovers = self.failovers
        return {"kind": "router", "replicas": replicas,
                "failovers": failovers,
                "cluster": {
                    "replicas": len(self.replicas),
                    "up": sum(1 for r in replicas.values() if r["up"]),
                    "summaries": summaries,
                    "pages": pages, "slots": slots,
                    "queue_depth": queue_depth,
                    "max_burn_rate": round(burn_max, 6),
                    "alerts_firing": alerts,
                    "prefix_digests": len(digests)}}

    def usage(self) -> dict:
        """Merged per-tenant usage table across the replicas' last
        collected fleet summaries — raw-merge discipline (counters
        sum, never averaged), the same rule as the latency-bucket
        merge.  A probe failure nulls the dead replica's summary, so
        a stale table never double-counts into the cluster view."""
        merged = _obs.merge_usage(
            (rep.fleet or {}).get("usage") for rep in self.replicas)
        merged["kind"] = "router"
        return merged

    # ------------------------------------------------------------ info
    def stats(self) -> dict:
        now = self._clock()
        reps = [r.snapshot(now) for r in self.replicas]
        with self._lock:
            failovers = self.failovers
        return {"replicas": reps,
                "up": sum(1 for r in reps if r["up"]),
                "total": len(reps),
                "failovers": failovers}

    def serve(self, host: str = "127.0.0.1", port: int = 0,
              start: bool = True) -> "RouterServer":
        server = RouterServer(self, host, port)
        if start:
            self.start_probing()
            server.start()
        return server


# ------------------------------------------------------------ HTTP proxy
class RouterServer(ThreadingHTTPServer):
    """HTTP front-end over a :class:`Router` — the same wire protocol
    as ``server.py``, so clients cannot tell a router from a replica:
    ``POST /v1/completions`` proxies to the picked replica (SSE relayed
    chunk-by-chunk, so a client disconnect at the router propagates to
    the replica as a cancel), ``/drain``/``/resume`` broadcast to every
    replica, ``/healthz`` reports per-replica circuit state."""

    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, router: Router, host: str = "127.0.0.1",
                 port: int = 0):
        self.router = router
        self._serve_thread: threading.Thread | None = None
        super().__init__((host, port), _RouterHandler)

    @property
    def address(self) -> str:
        return f"{self.server_address[0]}:{self.server_address[1]}"

    def start(self) -> "RouterServer":
        self._serve_thread = threading.Thread(
            target=self.serve_forever, name=f"router:{self.address}",
            daemon=True)
        self._serve_thread.start()
        return self

    def stop(self):
        self.router.stop()
        self.shutdown()
        if self._serve_thread is not None:
            self._serve_thread.join(timeout=5.0)
        self.server_close()


_ROUTER_DEBUG_INDEX = {
    "/debug/": "this index",
    "/debug/trace": "chrome-trace spans + counter tracks for the "
                    "router process",
    "/debug/fleet": "aggregate cluster view: per-replica summaries, "
                    "pooled page/slot/queue census, max SLO burn "
                    "rate, firing alerts",
    "/debug/profile": "fan out ?seconds=N to every replica and "
                      "aggregate the phase-attributed profile "
                      "snapshots per replica",
    "/debug/captures": "fan out to every replica and aggregate the "
                       "diagnostic-capture indexes per replica",
    "/debug/usage": "per-tenant usage table raw-merged across the "
                    "replicas' last collected summaries",
    "/debug/exemplars": "worst-K SLO-violation exemplars raw-merged "
                        "(worst-first re-rank, counters sum) across "
                        "every replica's bounded exemplar store",
    "/debug/requests/<id>": "per-request lifecycle waterfall fanned "
                            "out to every replica (the one that "
                            "served the request answers); "
                            "?format=chrome returns the found trace "
                            "verbatim for chrome://tracing",
}


class _RouterHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server: RouterServer

    def log_message(self, fmt, *args):
        pass

    def _json(self, code: int, obj: dict, headers=()):
        body = json.dumps(obj).encode()
        try:
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in headers:
                self.send_header(k, str(v))
            self.end_headers()
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError,
                ConnectionAbortedError):
            pass

    def do_GET(self):
        router = self.server.router
        if self.path == "/healthz":
            st = router.stats()
            st["status"] = "ok" if st["up"] else "unavailable"
            self._json(200 if st["up"] else 503, st)
        elif self.path == "/metrics":
            text = _obs.default_registry().to_prometheus().encode()
            try:
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(text)))
                self.end_headers()
                self.wfile.write(text)
            except (BrokenPipeError, ConnectionResetError):
                pass
        elif self.path == "/debug/trace":
            self._json(200, {"traceEvents":
                             (_obs.tracer().chrome_events()
                              + _obs.chrome_counter_events())})
        elif self.path == "/debug/fleet":
            self._json(200, router.fleet())
        elif self.path.split("?", 1)[0] == "/debug/profile":
            self._fanout_profile()
        elif self.path.split("?", 1)[0] == "/debug/captures":
            self._json(200, {"kind": "router",
                             "replicas": self._fanout_get(
                                 "/debug/captures")})
        elif self.path == "/debug/usage":
            self._json(200, router.usage())
        elif self.path == "/debug/exemplars":
            self._merged_exemplars()
        elif self.path.split("?", 1)[0].startswith("/debug/requests/"):
            self._fanout_request()
        elif self.path in ("/debug", "/debug/"):
            self._json(200, {"endpoints": _ROUTER_DEBUG_INDEX})
        else:
            self._json(404, {"error": {"message": f"no route {self.path}",
                                       "code": 404}})

    def _fanout_get(self, path: str, timeout: float | None = None):
        """GET ``path`` on every replica, one entry per replica
        address; a failing replica degrades to an error record, same
        shape as the POST broadcast."""
        router = self.server.router
        results = {}
        for rep in router.replicas:
            try:
                results[rep.address] = ServingClient(
                    rep.address,
                    timeout=timeout or router.request_timeout_s
                ).request("GET", path)
            except Exception as e:
                results[rep.address] = {"error": repr(e)}
        return results

    def _merged_exemplars(self):
        """Fan ``/debug/exemplars`` out to every replica and raw-merge
        the worst-K tables: concatenate, re-rank worst-first, sum the
        offered/kept counters — never average (the usage-merge rule).
        A dead or forensics-off replica degrades to an error record in
        ``replicas`` and is skipped by the merge, so a stale table
        never pollutes the cluster view."""
        from ..observability.requestlog import merge_exemplars
        results = self._fanout_get("/debug/exemplars")
        merged = merge_exemplars(
            r.get("exemplars") if isinstance(r, dict) else None
            for r in results.values())
        self._json(200, {"kind": "router", "replicas": results,
                         "merged": merged})

    def _fanout_request(self):
        """Forward ``/debug/requests/<id>`` (query string included) to
        every replica.  Exactly one replica served the request, so at
        most one answers with a timeline; the rest 404 into error
        records.  JSON asks get the found waterfall plus the
        per-replica map; ``?format=chrome`` relays the found trace
        verbatim so the payload loads straight into chrome://tracing."""
        from urllib.parse import parse_qs, urlparse
        results = self._fanout_get(self.path)
        found = next((r for r in results.values()
                      if isinstance(r, dict) and "error" not in r), None)
        fmt = parse_qs(urlparse(self.path).query).get(
            "format", ["json"])[0]
        if fmt == "chrome":
            if found is None:
                self._json(404, {"error": {
                    "message": "no replica holds a timeline for "
                               + self.path.split("?", 1)[0],
                    "code": 404}})
                return
            self._json(200, found)
            return
        self._json(200 if found is not None else 404,
                   {"kind": "router", "found": found,
                    "replicas": results})

    def _fanout_profile(self):
        """``GET /debug/profile?seconds=N``: each replica blocks for
        the whole N-second window, so the fan-out runs on one thread
        per replica and joins — every replica samples the SAME window
        and the router handler's wall time stays ~N, not N x fleet."""
        from urllib.parse import parse_qs, urlparse
        q = parse_qs(urlparse(self.path).query)
        try:
            seconds = float(q.get("seconds", ["1.0"])[0])
        except ValueError:
            self._json(400, {"error": {"message":
                                       "seconds must be a number",
                                       "code": 400}})
            return
        router = self.server.router
        path = (f"/debug/profile?seconds={seconds:g}&format=json")
        timeout = max(router.request_timeout_s, seconds + 10.0)
        results = {}

        def one(rep):
            try:
                results[rep.address] = ServingClient(
                    rep.address, timeout=timeout).request("GET", path)
            except Exception as e:
                results[rep.address] = {"error": repr(e)}

        threads = [threading.Thread(target=one, args=(rep,),
                                    daemon=True)
                   for rep in router.replicas]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=timeout + 5.0)
        self._json(200, {"kind": "router", "seconds": seconds,
                         "replicas": results})

    def do_POST(self):
        if self.path == "/v1/completions":
            self._proxy_completion()
        elif self.path in ("/drain", "/resume"):
            self._broadcast(self.path)
        else:
            self._json(404, {"error": {"message": f"no route {self.path}",
                                       "code": 404}})

    def _broadcast(self, path: str):
        results = {}
        for rep in self.server.router.replicas:
            try:
                results[rep.address] = ServingClient(
                    rep.address,
                    timeout=self.server.router.request_timeout_s
                ).request("POST", path, {})
            except Exception as e:
                results[rep.address] = {"error": repr(e)}
        self._json(200, {"replicas": results})

    def _proxy_completion(self):
        # join the client's trace (or start one) and hand OUR span id
        # downstream: client -> router -> replica becomes one trace
        parent = _obs.parse_traceparent(self.headers.get("traceparent"))
        span = _obs.tracer().start_span(
            "router.request", parent=parent,
            attributes={"proxy": True, "remote": parent is not None})
        with span:
            self._proxy_completion_traced(span)

    def _proxy_completion_traced(self, span):
        router = self.server.router
        try:
            n = int(self.headers.get("Content-Length") or 0)
            raw = self.rfile.read(n) if n > 0 else b"{}"
            body = json.loads(raw.decode() or "{}")
            prompt = body.get("prompt")
            if prompt is None or isinstance(prompt, str):
                raise ValueError("'prompt' must be a list of token ids")
        except (ValueError, json.JSONDecodeError) as e:
            span.set_attribute("status", 400)
            return self._json(400, {"error": {"message": str(e),
                                              "code": 400}})

        upstream_headers = {
            "Content-Type": "application/json",
            "traceparent": _obs.format_traceparent(span.context)}
        # gateway tags ride through to the replica (priority class,
        # usage-meter billing tenant, LoRA adapter selection)
        for key in ("X-Priority", "X-Tenant", "X-Adapter"):
            if self.headers.get(key):
                upstream_headers[key] = self.headers[key]
        # the adapter influences routing too (affinity-keyed so a
        # tenant's adapter stays loaded on one replica); header wins
        # over the body field, matching the replica's precedence
        adapter = (self.headers.get("X-Adapter") or "").strip() \
            or (str(body.get("adapter")).strip()
                if body.get("adapter") else None) or None
        tried: list[Replica] = []
        last_exc: BaseException | None = None
        for attempt in range(router.max_retries + 1):
            try:
                rep = router.pick(prompt, exclude=tried, adapter=adapter)
            except NoReplicaAvailable as e:
                span.set_attribute("status", 503)
                return self._json(
                    503, {"error": {"message": str(last_exc or e),
                                    "type": "overloaded_error",
                                    "code": 503}},
                    headers=[("Retry-After", f"{router.cooldown_s:g}")])
            span.set_attribute("replica", rep.address)
            span.set_attribute("attempts", attempt + 1)
            host, _, port = rep.address.rpartition(":")
            conn = http.client.HTTPConnection(
                host, int(port), timeout=router.request_timeout_s)
            with router._lock:
                rep.inflight += 1
            try:
                conn.request("POST", "/v1/completions", raw,
                             upstream_headers)
                resp = conn.getresponse()
            except OSError as e:
                conn.close()
                with router._lock:
                    rep.inflight -= 1
                router._mark_failure(rep, e)
                _M_REQS.labels(rep.address, "error").inc()
                tried.append(rep)
                last_exc = e
                if attempt < router.max_retries:
                    _M_RETRIES.inc()
                    span.add_event("retry", replica=rep.address,
                                   error=repr(e))
                continue
            try:
                span.set_attribute("status", resp.status)
                self._relay(rep, resp, body=body, tried=tried + [rep],
                            headers=upstream_headers)
            finally:
                conn.close()
                with router._lock:
                    rep.inflight -= 1
            return
        span.set_attribute("status", 503)
        self._json(503, {"error": {"message": f"request failed on "
                                              f"{len(tried)} replica(s) "
                                              f"(last: {last_exc!r})",
                                   "type": "overloaded_error",
                                   "code": 503}},
                   headers=[("Retry-After", f"{router.cooldown_s:g}")])

    def _relay(self, rep: Replica, resp, *, body=None, tried=None,
               headers=None):
        """Stream the replica's response back verbatim.  Closing the
        upstream connection on OUR client's disconnect is what turns a
        router-side hangup into a replica-side cancel.

        When the UPSTREAM dies mid-SSE (read error, or EOF before
        ``[DONE]``) and the request is :meth:`Router.resumable`, the
        relay fails over: it re-POSTs ``prompt + delivered tokens`` to
        a healthy replica and keeps relaying that stream, so the
        downstream client receives one complete token sequence."""
        router = self.server.router
        streaming = "text/event-stream" in (
            resp.headers.get("Content-Type") or "")
        try:
            self.send_response(resp.status)
            for key in ("Content-Type", "Retry-After"):
                if resp.headers.get(key):
                    self.send_header(key, resp.headers[key])
            if streaming:
                self.send_header("Connection", "close")
                self.close_connection = True
                self.end_headers()
            else:
                payload = resp.read()
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)
        except (BrokenPipeError, ConnectionResetError,
                ConnectionAbortedError):
            _M_REQS.labels(rep.address, "client_cancelled").inc()
            return
        if not streaming:
            router._mark_success(rep)
            outcome = "ok" if 200 <= resp.status < 300 \
                else f"http_{resp.status}"
            _M_REQS.labels(rep.address, outcome).inc()
            return
        self._relay_stream(rep, resp, body=body, tried=tried,
                           headers=headers)

    def _relay_stream(self, rep: Replica, resp, *, body, tried, headers):
        router = self.server.router
        body = body or {}
        can_resume = bool(body.get("prompt")) and router.resumable(body)
        max_tokens = int(body.get("max_tokens", 16))
        prompt = [int(t) for t in (body.get("prompt") or [])]
        delivered: list[int] = []
        tried = list(tried or [])
        failovers_left = router.max_retries
        cur_rep, cur_resp = rep, resp
        extra_conns: list = []      # failover connections we opened
        extra_reps: list = []       # ... and their inflight holds
        try:
            while True:
                done = False
                upstream_err: BaseException | None = None
                while True:
                    try:
                        line = cur_resp.readline()
                    except (OSError, http.client.HTTPException) as e:
                        upstream_err = e
                        break
                    if not line:
                        break           # upstream closed
                    s = line.strip()
                    if s.startswith(b"data:"):
                        data = s[len(b"data:"):].strip()
                        if data == b"[DONE]":
                            done = True
                        else:
                            try:
                                ev = json.loads(data.decode())
                                ch = ev["choices"][0]
                                delivered.extend(
                                    int(t) for t in (ch.get("token_ids")
                                                     or ()))
                            except (ValueError, KeyError, TypeError,
                                    IndexError):
                                pass
                    try:
                        self.wfile.write(line)
                        if line == b"\n":
                            self.wfile.flush()
                    except (OSError, ValueError):
                        # OUR client went away: stop, upstream conn
                        # close (in the caller) cancels the replica side
                        _M_REQS.labels(cur_rep.address,
                                       "client_cancelled").inc()
                        return
                    if done:
                        break
                if done and upstream_err is None:
                    router._mark_success(cur_rep)
                    _M_REQS.labels(cur_rep.address, "ok").inc()
                    return
                # upstream died mid-stream
                if not can_resume or failovers_left <= 0:
                    # cannot resume: keep the pre-failover behavior —
                    # the truncated stream simply ends (transport
                    # errors still feed the circuit)
                    if upstream_err is not None:
                        router._mark_failure(cur_rep, upstream_err)
                        _M_REQS.labels(cur_rep.address, "error").inc()
                    else:
                        router._mark_success(cur_rep)
                        _M_REQS.labels(cur_rep.address, "ok").inc()
                    return
                err = upstream_err or ConnectionError(
                    "upstream stream ended before [DONE]")
                router._mark_failure(cur_rep, err)
                _M_REQS.labels(cur_rep.address, "error").inc()
                tried.append(cur_rep)
                remaining = max_tokens - len(delivered)
                if remaining <= 0:
                    # every token made it out; synthesize the final
                    # frame the dead replica never sent
                    self._finish_stream()
                    return
                resume = dict(body, prompt=prompt + delivered,
                              max_tokens=remaining,
                              resume_from=len(delivered))
                raw = json.dumps(resume).encode()
                switched = False
                while failovers_left > 0 and not switched:
                    failovers_left -= 1
                    try:
                        nxt = router.pick(resume["prompt"],
                                          exclude=tried,
                                          adapter=resume.get("adapter"))
                    except NoReplicaAvailable:
                        break
                    host, _, port = nxt.address.rpartition(":")
                    conn = http.client.HTTPConnection(
                        host, int(port),
                        timeout=router.request_timeout_s)
                    with router._lock:
                        nxt.inflight += 1
                    extra_conns.append(conn)
                    extra_reps.append(nxt)
                    try:
                        conn.request("POST", "/v1/completions", raw,
                                     headers or {"Content-Type":
                                                 "application/json"})
                        r2 = conn.getresponse()
                    except OSError as e:
                        router._mark_failure(nxt, e)
                        _M_REQS.labels(nxt.address, "error").inc()
                        tried.append(nxt)
                        continue
                    if r2.status != 200 or "text/event-stream" not in (
                            r2.headers.get("Content-Type") or ""):
                        # the replica answered (alive) but refused the
                        # resume — give up, the stream stays truncated
                        _M_REQS.labels(
                            nxt.address, f"http_{r2.status}").inc()
                        return
                    switched = True
                if not switched:
                    return              # truncated — nothing healthy
                with router._lock:
                    router.failovers += 1
                _M_FAILOVERS.inc()
                _obs.flight("router", "failover",
                            from_=cur_rep.address, to=nxt.address,
                            delivered=len(delivered),
                            remaining=remaining)
                cur_rep, cur_resp = nxt, r2
        finally:
            for conn in extra_conns:
                try:
                    conn.close()
                except OSError:
                    pass
            with router._lock:
                for r in extra_reps:
                    r.inflight -= 1

    def _finish_stream(self):
        """Synthesized stream tail: the dead replica delivered every
        token but not the final frame."""
        final = {"object": "text_completion.chunk",
                 "choices": [{"index": 0, "text": "", "token_ids": [],
                              "finish_reason": "length"}]}
        try:
            self.wfile.write(b"data: " + json.dumps(final).encode()
                             + b"\n\ndata: [DONE]\n\n")
            self.wfile.flush()
        except (OSError, ValueError):
            pass
