"""Engine supervisor: step-level failure containment + self-healing.

Wraps :meth:`Engine.step` for the worker loop.  A step that raises (a
poisoned jit call, non-finite device state, an injected
:class:`~.faults.InjectedFault`) — or a stall the watchdog declared —
triggers a **recovery**: the engine rebuilds its ModelRunner and
replays every in-flight request from its committed tokens
(:meth:`Engine.recover`), so one bad step costs one re-prefill pass,
not the process.  Recoveries are budgeted
(``FLAGS_serving_max_recoveries``); when the budget is exhausted the
supervisor **escalates to drain**: in-flight requests finish with
``finish_reason="error"``, admission stops, and the replica reports
itself unhealthy — the router's circuit breaker then routes around it.

Every event lands on ``serving_recovery_total{kind}``
(quarantine | rebuild | stall | drain), in the flight recorder, and as
``supervisor.recover`` spans in the trace ring (``/debug/trace``).

Threading: :meth:`step` runs on the single engine thread (the
EngineWorker loop); :meth:`note_stall` is called from the watchdog
thread and only flips a flag — recovery itself always happens on the
engine thread, preserving the engine's single-threaded contract.
"""
from __future__ import annotations

import time

from .. import observability as _obs
from ..flags import FLAGS
from ..sanitizer import make_lock

__all__ = ["EngineSupervisor"]

_M_RECOVERY = _obs.counter(
    "serving_recovery_total",
    "self-healing events: 'quarantine' = one request failed in place "
    "(finish_reason='error', batch kept running), 'rebuild' = runner "
    "rebuilt + in-flight requests replayed, 'stall' = rebuild declared "
    "by the watchdog, 'drain' = restart budget exhausted, escalated",
    ("kind",))


class EngineSupervisor:
    """Self-healing wrapper around one engine's step loop.

    ``max_recoveries`` bounds runner rebuilds per process (default
    ``FLAGS_serving_max_recoveries``); past it, failures escalate to
    drain instead of looping forever on a persistently broken device.
    """

    def __init__(self, engine, *, max_recoveries: int | None = None,
                 clock=time.monotonic):
        self.engine = engine
        if max_recoveries is None:
            max_recoveries = int(
                FLAGS.get("FLAGS_serving_max_recoveries") or 0)
        self.max_recoveries = int(max_recoveries)
        self._clock = clock
        # guards the counters below: step() mutates on the engine
        # thread, note_stall() on the watchdog thread, stats() on
        # handler threads
        self._lock = make_lock("EngineSupervisor._lock")
        self._stall_pending = False
        self.recoveries = 0          # rebuilds performed (mirror)
        self.escalated = False       # budget exhausted -> draining
        self.last_error: str | None = None

    # ------------------------------------------------------------ inputs
    def note_stall(self, *_args, **_kw):
        """Watchdog callback (``watchdog.on_stall``): request a recovery
        at the next :meth:`step`.  Never recovers inline — the watchdog
        thread must not touch engine state."""
        with self._lock:
            self._stall_pending = True

    # -------------------------------------------------------------- loop
    def step(self) -> bool:
        """One supervised engine iteration.  Returns whether work
        happened (recovery counts as work — the loop must not sleep
        through it)."""
        with self._lock:
            stalled = self._stall_pending
            self._stall_pending = False
        if stalled:
            self._recover("stall", "watchdog-declared stall")
            return True
        try:
            return self.engine.step()
        except Exception as e:
            self._recover("step_error", e)
            return True

    # ---------------------------------------------------------- recovery
    def _recover(self, kind: str, err):
        with self._lock:
            self.last_error = f"{kind}: {err}"
            exhausted = (self.escalated
                         or self.recoveries >= self.max_recoveries)
            if not exhausted:
                self.recoveries += 1
        if exhausted:
            self._escalate(err)
            return
        label = "stall" if kind == "stall" else "rebuild"
        _M_RECOVERY.labels(label).inc()
        _obs.flight("supervisor", "recover", kind=kind,
                    error=str(err)[:160],
                    budget_left=self.max_recoveries - self.recoveries)
        t0 = time.perf_counter()
        try:
            result = self.engine.recover()
        except Exception as e:
            # the rebuild itself failed: the device is gone for good —
            # escalate instead of crashing the worker loop
            self._escalate(e)
            return
        _obs.tracer().record_span(
            "supervisor.recover", t0, time.perf_counter(),
            attributes={"kind": kind, **result})
        log = getattr(self.engine, "requestlog", None)
        if log is not None:
            # forensics: count the sweep (per-request replay seconds
            # already landed in each timeline's recovery bucket)
            log.note_recovery(result)

    def _escalate(self, err):
        """Restart budget exhausted: stop admitting, fail what is in
        flight, and leave the replica up but draining — /healthz shows
        it, the router's breaker routes around it."""
        with self._lock:
            first = not self.escalated
            self.escalated = True
        if not first:
            return
        _M_RECOVERY.labels("drain").inc()
        now = self._clock()
        eng = self.engine
        eng.scheduler.drain()
        for slot, req in enumerate(eng.scheduler.slots):
            if req is not None:
                eng._quarantine(
                    slot, req,
                    f"recovery budget exhausted after "
                    f"{self.recoveries} rebuilds ({err})", now)
        _obs.flight("supervisor", "escalate", error=str(err)[:160],
                    recoveries=self.recoveries)

    # -------------------------------------------------------------- info
    def stats(self) -> dict:
        with self._lock:
            return {"recoveries": self.recoveries,
                    "max_recoveries": self.max_recoveries,
                    "escalated": self.escalated,
                    "stall_pending": self._stall_pending,
                    "last_error": self.last_error}
