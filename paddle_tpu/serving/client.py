"""Blocking/streaming HTTP client for the serving front-end.

Stdlib-only (``http.client``) counterpart of ``server.py``'s wire
protocol, used by tests, ``tools/serve_bench.py --http``, and the
router's programmatic path::

    client = ServingClient("127.0.0.1:8000")
    out = client.completion([1, 2, 3], max_tokens=8)
    out["choices"][0]["token_ids"]

    for ev in client.completion([1, 2, 3], max_tokens=8, stream=True):
        ev["choices"][0]["token_ids"]   # one token per SSE event

Transport failures (connection refused/reset before a response) raise
``OSError`` subclasses — the router retries those on another replica.
An HTTP-level error (429 backpressure, 503 draining, 400 validation)
raises :class:`ServingHTTPError` carrying status, parsed body, and any
``Retry-After`` — the replica answered, so the router does NOT retry.
"""
from __future__ import annotations

import http.client
import json

from ..observability import tracing as _tracing

__all__ = ["ServingClient", "ServingHTTPError"]


class ServingHTTPError(Exception):
    """Non-2xx HTTP response from a serving endpoint."""

    def __init__(self, status: int, body, retry_after: float | None = None):
        self.status = int(status)
        self.body = body
        self.retry_after = retry_after
        msg = body
        if isinstance(body, dict):
            msg = (body.get("error") or {}).get("message", body)
        super().__init__(f"HTTP {status}: {msg}")


def _parse_address(address) -> tuple[str, int]:
    if isinstance(address, (tuple, list)):
        return str(address[0]), int(address[1])
    addr = str(address)
    for scheme in ("http://", "https://"):
        if addr.startswith(scheme):
            addr = addr[len(scheme):]
    addr = addr.rstrip("/")
    host, _, port = addr.rpartition(":")
    if not host:
        raise ValueError(f"address must be host:port, got {address!r}")
    return host, int(port)


class ServingClient:
    """One serving endpoint (a replica, or a router front-end)."""

    def __init__(self, address, timeout: float = 60.0):
        self.host, self.port = _parse_address(address)
        self.address = f"{self.host}:{self.port}"
        self.timeout = float(timeout)

    def _connect(self) -> http.client.HTTPConnection:
        return http.client.HTTPConnection(self.host, self.port,
                                          timeout=self.timeout)

    # ------------------------------------------------------ plain JSON
    def request(self, method: str, path: str, body: dict | None = None,
                headers: dict | None = None):
        """One JSON round trip; raises ServingHTTPError on non-2xx."""
        conn = self._connect()
        try:
            payload = None if body is None else json.dumps(body).encode()
            hdrs = {"Content-Type": "application/json"}
            if headers:
                hdrs.update(headers)
            conn.request(method, path, body=payload, headers=hdrs)
            resp = conn.getresponse()
            raw = resp.read()
            return self._decode(resp, raw)
        finally:
            conn.close()

    @staticmethod
    def _decode(resp, raw: bytes):
        try:
            parsed = json.loads(raw.decode() or "null")
        except (ValueError, UnicodeDecodeError):
            parsed = raw.decode(errors="replace")
        if not 200 <= resp.status < 300:
            ra = resp.headers.get("Retry-After")
            raise ServingHTTPError(resp.status, parsed,
                                   retry_after=float(ra) if ra else None)
        return parsed

    # ----------------------------------------------------- completions
    def completion(self, prompt, *, max_tokens: int = 16,
                   stream: bool = False, timeout: float | None = None,
                   **gen_kw):
        """POST /v1/completions.  Blocking: the parsed response dict.
        ``stream=True``: a generator of parsed SSE events (one token
        per event; closing the generator drops the connection, which
        cancels the request server-side)."""
        body = {"prompt": [int(t) for t in prompt],
                "max_tokens": int(max_tokens), "stream": bool(stream)}
        if timeout is not None:
            body["timeout"] = float(timeout)
        body.update(gen_kw)
        # every completion opens a "client.completion" span (nesting
        # under the caller's current span, e.g. router.request) and
        # carries its context to the server as a traceparent header —
        # the client end of the distributed trace
        span = _tracing.tracer().start_span(
            "client.completion",
            attributes={"address": self.address, "stream": bool(stream)})
        hdrs = {_tracing.TRACEPARENT_HEADER:
                _tracing.format_traceparent(span.context)}
        if not stream:
            try:
                return self.request("POST", "/v1/completions", body,
                                    headers=hdrs)
            finally:
                span.end()
        try:
            return self._stream_completion(body, hdrs, span)
        except BaseException:
            span.end()
            raise

    def _stream_completion(self, body: dict, headers: dict, span=None):
        conn = self._connect()
        try:
            hdrs = {"Content-Type": "application/json"}
            hdrs.update(headers)
            conn.request("POST", "/v1/completions",
                         body=json.dumps(body).encode(), headers=hdrs)
            resp = conn.getresponse()
            if resp.status != 200:
                self._decode(resp, resp.read())     # raises
        except BaseException:
            conn.close()
            raise
        return self._iter_sse(conn, resp, span)

    @staticmethod
    def _iter_sse(conn, resp, span=None):
        n = 0
        try:
            while True:
                line = resp.readline()
                if not line:            # server closed the stream
                    return
                line = line.strip()
                if not line.startswith(b"data:"):
                    continue
                data = line[len(b"data:"):].strip()
                if data == b"[DONE]":
                    return
                n += 1
                yield json.loads(data.decode())
        finally:
            conn.close()
            if span is not None:        # span covers the full stream
                span.set_attribute("events", n)
                span.end()

    def completion_tokens(self, prompt, **kw) -> list[int]:
        """Blocking completion, returning just the generated token ids."""
        out = self.completion(prompt, **kw)
        return list(out["choices"][0]["token_ids"])

    # ------------------------------------------------------- utilities
    def healthz(self) -> dict:
        return self.request("GET", "/healthz")

    def metrics_text(self) -> str:
        conn = self._connect()
        try:
            conn.request("GET", "/metrics")
            resp = conn.getresponse()
            raw = resp.read()
            if resp.status != 200:
                raise ServingHTTPError(resp.status,
                                       raw.decode(errors="replace"))
            return raw.decode()
        finally:
            conn.close()

    def drain(self, timeout: float | None = None) -> dict:
        body = {} if timeout is None else {"timeout": timeout}
        return self.request("POST", "/drain", body)

    def resume(self) -> dict:
        return self.request("POST", "/resume")
