"""Blocking/streaming HTTP client for the serving front-end.

Stdlib-only (``http.client``) counterpart of ``server.py``'s wire
protocol, used by tests, ``tools/serve_bench.py --http``, and the
router's programmatic path::

    client = ServingClient("127.0.0.1:8000")
    out = client.completion([1, 2, 3], max_tokens=8)
    out["choices"][0]["token_ids"]

    for ev in client.completion([1, 2, 3], max_tokens=8, stream=True):
        ev["choices"][0]["token_ids"]   # one token per SSE event

Transport failures (connection refused/reset before a response) raise
``OSError`` subclasses — the router retries those on another replica.
An HTTP-level error (429 backpressure, 503 draining, 400 validation)
raises :class:`ServingHTTPError` carrying status, parsed body, and any
``Retry-After`` — the replica answered, so the router does NOT retry.

With ``retries > 0`` the client itself retries **429/503** answers
(backpressure / draining / SLO shedding — the retryable overload
family) with jittered exponential backoff, honoring the server's
``Retry-After`` as a lower bound on each sleep.  Attempts are bounded
and each attempt keeps the per-request ``timeout``; the default
``retries=0`` preserves fail-fast semantics for the router, which does
its own replica-level retrying.
"""
from __future__ import annotations

import http.client
import json
import random
import time

from ..observability import tracing as _tracing

__all__ = ["ServingClient", "ServingHTTPError"]


class ServingHTTPError(Exception):
    """Non-2xx HTTP response from a serving endpoint."""

    def __init__(self, status: int, body, retry_after: float | None = None):
        self.status = int(status)
        self.body = body
        self.retry_after = retry_after
        msg = body
        if isinstance(body, dict):
            msg = (body.get("error") or {}).get("message", body)
        super().__init__(f"HTTP {status}: {msg}")


def _parse_address(address) -> tuple[str, int]:
    if isinstance(address, (tuple, list)):
        return str(address[0]), int(address[1])
    addr = str(address)
    for scheme in ("http://", "https://"):
        if addr.startswith(scheme):
            addr = addr[len(scheme):]
    addr = addr.rstrip("/")
    host, _, port = addr.rpartition(":")
    if not host:
        raise ValueError(f"address must be host:port, got {address!r}")
    return host, int(port)


_RETRYABLE = (429, 503)         # backpressure / draining / shedding


class ServingClient:
    """One serving endpoint (a replica, or a router front-end).

    ``retries`` bounds how many times a 429/503 answer is retried
    (0 = fail fast); sleeps grow as jittered exponential backoff from
    ``backoff_s`` capped at ``backoff_max_s``, never below the server's
    ``Retry-After``.  ``rng`` pins the jitter for deterministic tests.
    """

    def __init__(self, address, timeout: float = 60.0, *,
                 retries: int = 0, backoff_s: float = 0.05,
                 backoff_max_s: float = 2.0, rng=None):
        self.host, self.port = _parse_address(address)
        self.address = f"{self.host}:{self.port}"
        self.timeout = float(timeout)
        self.retries = int(retries)
        self.backoff_s = float(backoff_s)
        self.backoff_max_s = float(backoff_max_s)
        self._rng = rng if rng is not None else random.Random()

    def _connect(self) -> http.client.HTTPConnection:
        return http.client.HTTPConnection(self.host, self.port,
                                          timeout=self.timeout)

    # --------------------------------------------------------- backoff
    def _retry_delay(self, attempt: int,
                     retry_after: float | None) -> float:
        """Jittered exponential backoff (50-100% of the exponential
        step), floored at the server's Retry-After when it sent one."""
        base = min(self.backoff_max_s, self.backoff_s * (2 ** attempt))
        delay = base * (0.5 + 0.5 * self._rng.random())
        if retry_after:
            delay = max(delay, float(retry_after))
        return delay

    def _with_retries(self, fn):
        """Run ``fn()`` with the 429/503 retry policy.  Each attempt is
        a fresh connection with the full per-attempt timeout; transport
        errors are never retried here (the router owns those)."""
        attempt = 0
        while True:
            try:
                return fn()
            except ServingHTTPError as e:
                if e.status not in _RETRYABLE or attempt >= self.retries:
                    raise
                time.sleep(self._retry_delay(attempt, e.retry_after))
                attempt += 1

    # ------------------------------------------------------ plain JSON
    def request(self, method: str, path: str, body: dict | None = None,
                headers: dict | None = None):
        """One JSON round trip; raises ServingHTTPError on non-2xx
        (retrying 429/503 first when ``retries > 0``)."""
        return self._with_retries(
            lambda: self._request_once(method, path, body, headers))

    def _request_once(self, method: str, path: str,
                      body: dict | None = None,
                      headers: dict | None = None):
        conn = self._connect()
        try:
            payload = None if body is None else json.dumps(body).encode()
            hdrs = {"Content-Type": "application/json"}
            if headers:
                hdrs.update(headers)
            conn.request(method, path, body=payload, headers=hdrs)
            resp = conn.getresponse()
            raw = resp.read()
            return self._decode(resp, raw)
        finally:
            conn.close()

    @staticmethod
    def _decode(resp, raw: bytes):
        try:
            parsed = json.loads(raw.decode() or "null")
        except (ValueError, UnicodeDecodeError):
            parsed = raw.decode(errors="replace")
        if not 200 <= resp.status < 300:
            ra = resp.headers.get("Retry-After")
            raise ServingHTTPError(resp.status, parsed,
                                   retry_after=float(ra) if ra else None)
        return parsed

    # ----------------------------------------------------- completions
    def completion(self, prompt, *, max_tokens: int = 16,
                   stream: bool = False, timeout: float | None = None,
                   tenant: str | None = None,
                   adapter: str | None = None, **gen_kw):
        """POST /v1/completions.  Blocking: the parsed response dict.
        ``stream=True``: a generator of parsed SSE events (one token
        per event; closing the generator drops the connection, which
        cancels the request server-side).  ``tenant`` tags the request
        for the server's usage meter (body field; the X-Tenant header
        overrides it at the server).  ``adapter`` selects a registered
        LoRA adapter by name (body field; X-Adapter overrides)."""
        body = {"prompt": [int(t) for t in prompt],
                "max_tokens": int(max_tokens), "stream": bool(stream)}
        if timeout is not None:
            body["timeout"] = float(timeout)
        if tenant is not None:
            body["tenant"] = str(tenant)
        if adapter is not None:
            body["adapter"] = str(adapter)
        body.update(gen_kw)
        # every completion opens a "client.completion" span (nesting
        # under the caller's current span, e.g. router.request) and
        # carries its context to the server as a traceparent header —
        # the client end of the distributed trace
        span = _tracing.tracer().start_span(
            "client.completion",
            attributes={"address": self.address, "stream": bool(stream)})
        try:
            hdrs = {_tracing.TRACEPARENT_HEADER:
                    _tracing.format_traceparent(span.context)}
        except BaseException:
            span.end()
            raise
        if not stream:
            try:
                return self.request("POST", "/v1/completions", body,
                                    headers=hdrs)
            finally:
                span.end()
        try:
            # the retry policy covers the connect + status check (a 429
            # raises before any event flows); once streaming, failures
            # are mid-stream and no longer retryable here
            return self._with_retries(
                lambda: self._stream_completion(body, hdrs, span))
        except BaseException:
            span.end()
            raise

    def _stream_completion(self, body: dict, headers: dict, span=None):
        conn = self._connect()
        try:
            hdrs = {"Content-Type": "application/json"}
            hdrs.update(headers)
            conn.request("POST", "/v1/completions",
                         body=json.dumps(body).encode(), headers=hdrs)
            resp = conn.getresponse()
            if resp.status != 200:
                self._decode(resp, resp.read())     # raises
        except BaseException:
            conn.close()
            raise
        return self._iter_sse(conn, resp, span)

    @staticmethod
    def _iter_sse(conn, resp, span=None):
        n = 0
        try:
            while True:
                line = resp.readline()
                if not line:            # server closed the stream
                    return
                line = line.strip()
                if not line.startswith(b"data:"):
                    continue
                data = line[len(b"data:"):].strip()
                if data == b"[DONE]":
                    return
                n += 1
                yield json.loads(data.decode())
        finally:
            conn.close()
            if span is not None:        # span covers the full stream
                span.set_attribute("events", n)
                span.end()

    def completion_tokens(self, prompt, **kw) -> list[int]:
        """Blocking completion, returning just the generated token ids."""
        out = self.completion(prompt, **kw)
        return list(out["choices"][0]["token_ids"])

    # ---------------------------------------------------------- batches
    def submit_batch(self, *, records=None, input_path: str | None = None,
                     **kw) -> dict:
        """``POST /v1/batches``: start an offline batch job from inline
        ``records`` or a server-side ``input_path`` JSONL file.  ``kw``
        passes through (window / max_tokens / tenant / adapter /
        output_path).  Returns the job's initial progress dict."""
        body = dict(kw)
        if records is not None:
            body["records"] = list(records)
        if input_path is not None:
            body["input_path"] = str(input_path)
        return self.request("POST", "/v1/batches", body)

    def batch_status(self, job_id: str) -> dict:
        """``GET /v1/batches/<id>`` — one job's progress."""
        return self.request("GET", f"/v1/batches/{job_id}")

    # ------------------------------------------------------- utilities
    def healthz(self) -> dict:
        return self.request("GET", "/healthz")

    def usage(self) -> dict:
        """``GET /debug/usage`` — the per-tenant usage table (replica)
        or the raw-merged cluster table (router)."""
        return self.request("GET", "/debug/usage")

    def metrics_text(self) -> str:
        conn = self._connect()
        try:
            conn.request("GET", "/metrics")
            resp = conn.getresponse()
            raw = resp.read()
            if resp.status != 200:
                raise ServingHTTPError(resp.status,
                                       raw.decode(errors="replace"))
            return raw.decode()
        finally:
            conn.close()

    def drain(self, timeout: float | None = None) -> dict:
        body = {} if timeout is None else {"timeout": timeout}
        return self.request("POST", "/drain", body)

    def resume(self) -> dict:
        return self.request("POST", "/resume")
