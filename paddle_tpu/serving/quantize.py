"""Dense checkpoint -> quantized serving state conversion.

Reference analog: running a deploy model through ``weight_quantize``
(python/paddle/nn/quant) before handing it to the predictor — the
serving engine consumes the converted state directly.

Unlike :func:`paddle_tpu.models.generation.quantize_state` (the
single-chip generate-path converter, which ALSO emits fused
``qkv_fused``/``gateup_fused`` keys and quantizes ``lm_head``), this
converter targets the serving runner:

  * only the per-projection matmul weights (q/k/v/o, gate/up/down)
    become :class:`~paddle_tpu.ops.pallas.quant_matmul.QuantizedWeight`
    leaves — the ``tp > 1`` runner shards each projection individually
    (columns + per-output-channel scale for q/k/v and gate/up, rows
    with a replicated scale for o/down), and fused keys cannot be
    head-sharded;
  * embeddings and norms stay dense (gathers and elementwise ops, not
    matmuls) and so does ``lm_head`` — its logits feed the greedy
    argmax, where weight error moves emitted tokens the most.
"""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["quantize_state"]

# the per-projection matmul weights the serving runner knows how to
# shard; everything else (embeddings, norms, lm_head) stays dense
_MATMUL_KEYS = ("self_attn.q_proj.weight", "self_attn.k_proj.weight",
                "self_attn.v_proj.weight", "self_attn.o_proj.weight",
                "mlp.gate_proj.weight", "mlp.up_proj.weight",
                "mlp.down_proj.weight")


def quantize_state(state: dict, kind: str = "int8", skip=()) -> dict:
    """Convert a dense llama state dict into a quantized serving state.

    Every per-projection matmul weight becomes a ``QuantizedWeight``
    (``kind="int8"``: int8 values + per-output-channel f32 scale;
    ``kind="int4"``: the same, nibble-packed ``[K/2, N]`` — a quarter
    of the dense HBM footprint).  ``skip`` names key suffixes to keep
    dense (e.g. ``skip=("mlp.down_proj.weight",)``).  Leaves that are
    already ``QuantizedWeight`` pass through untouched, so the
    conversion is idempotent.  The returned dict drops nothing: it is
    a drop-in replacement for the dense state at ``create_engine`` /
    ``ModelRunner`` construction, for any ``tp``.
    """
    from ..nn.quant import weight_quantize
    from ..ops.pallas.quant_matmul import QuantizedWeight

    if kind not in ("int8", "int4"):
        raise ValueError(
            f"quant kind must be 'int8' or 'int4', got {kind!r}")
    algo = f"weight_only_{kind}"
    skip = tuple(skip)
    out = {}
    for name, arr in state.items():
        if (not name.endswith(_MATMUL_KEYS)
                or (skip and name.endswith(skip))
                or isinstance(arr, QuantizedWeight)):
            out[name] = arr
            continue
        if kind == "int4" and arr.shape[0] % 2:
            raise ValueError(
                f"{name!r}: int4 nibble packing needs an even K, got "
                f"{arr.shape[0]}")
        q, scale = weight_quantize.__op_body__(jnp.asarray(arr), algo)
        out[name] = QuantizedWeight(q, scale, kind=kind, k=arr.shape[0])
    return out
