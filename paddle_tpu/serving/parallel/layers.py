"""Tensor-parallel transformer layers for the serving runner.

Per-shard mirrors of ``models/generation._decode_layer_paged``,
``_prefill_layer``, and ``serving/engine._prefill_layer_cached``,
written to run inside a ``shard_map`` over the ``tp`` mesh axis:

  * q/k/v, gate, and up are column-sharded — each device projects its
    own ``nh/tp`` query heads, ``kvh/tp`` KV heads, and ``I/tp`` FFN
    columns, so local head counts come from the weight shard shapes;
  * attention over the paged pool is head-parallel (each head's softmax
    sees its full sequence locally — the pool is sharded on the head
    axis, not the token axis), so no collective runs inside attention;
  * o and down are row-sharded; their partial products are the ONLY two
    all-reduce points per layer (``psum`` over ``tp``), exactly where
    Megatron-style TP places them.

FUSED weight paths are intentionally absent (the runner rejects fused
states for ``tp>1`` up front), but every matmul routes through
``models.generation._mm``: per-projection ``QuantizedWeight`` shards
(int8/int4 + per-output-channel scale) take the weight-only matmul
path, and plain arrays lower to the identical ``@`` the bodies always
used — the dense jaxpr is unchanged.

The ``*_quant`` bodies are the int8-KV-page mirrors: pools are int8
with per-(page-row, head) f32 scale arrays, new KV quantizes on write
inside the same traced step, and attention dequantizes fused into the
page gather.  They serve BOTH construction modes (``axis=None`` is the
single-chip runner; an axis name marks the shard_map context), so the
dense bodies stay byte-identical when quantization is off.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...models.generation import _ffn, _mm, _qkv_proj
from ...models.llama import _rotate_half
from ...models.llama_hybrid import _rms
from ...ops.pallas.lora_matmul import lora_delta
from ...ops.pallas.paged_attention import (gather_kv_pages,
                                           gather_kv_pages_quant,
                                           paged_attention_quant,
                                           quantize_kv_rows,
                                           select_paged_attention)

__all__ = ["decode_layer_paged_tp", "prefill_layer_tp",
           "prefill_layer_cached_tp", "decode_layer_paged_quant",
           "prefill_layer_cached_quant"]


def _local_qkv(w, h, hd, lora=(), aidx=None, li=0):
    """Project with the local weight shards; head counts are derived
    from the shard widths (``nh_local = nh / tp`` etc.).  LoRA bank B
    tensors for q/k/v are column-sharded exactly like the base
    weights, so the deltas land on this shard's own heads."""
    q, k, v = _mm(h, w["q"]), _mm(h, w["k"]), _mm(h, w["v"])
    if lora:
        q = q + lora_delta(lora, "q", li, h, aidx)
        k = k + lora_delta(lora, "k", li, h, aidx)
        v = v + lora_delta(lora, "v", li, h, aidx)
    return q, k, v, q.shape[-1] // hd, k.shape[-1] // hd


def _ffn_tp(w, h, axis, lora=(), aidx=None, li=0):
    """Column-sharded gate/up, row-sharded down: the partial down
    product is one of the layer's two all-reduces.  The down adapter's
    A is row-sharded like the base weight, so its partial delta joins
    the SAME psum (contraction splits linearly) — LoRA adds zero
    collectives."""
    g, u = _mm(h, w["gate"]), _mm(h, w["up"])
    if lora:
        g = g + lora_delta(lora, "gate", li, h, aidx)
        u = u + lora_delta(lora, "up", li, h, aidx)
    act = jax.nn.silu(g) * u
    part = _mm(act, w["down"])
    if lora:
        part = part + lora_delta(lora, "down", li, act, aidx)
    return jax.lax.psum(part, axis)


def decode_layer_paged_tp(w, x, kpool, vpool, table, cos1, sin1, pos,
                          cfg, axis, lora=(), aidx=None, li=0):
    """Per-shard paged decode layer: ``x`` [B, H] replicated, pools
    [P, kvH/tp, ps, D] local, ``table``/``pos`` replicated.  Returns
    (out replicated, kpool, vpool local) — mirror of
    ``_decode_layer_paged`` with the o/down all-reduces."""
    b = x.shape[0]
    hd = cfg.head_dim
    ps = kpool.shape[2]
    h = _rms(x[:, None], w["ln1"], cfg.rms_norm_eps)[:, 0]
    qp, kp, vp, nh_l, kvh_l = _local_qkv(w, h, hd, lora, aidx, li)
    q = qp.reshape(b, nh_l, hd)
    k = kp.reshape(b, kvh_l, hd)
    v = vp.reshape(b, kvh_l, hd)
    cos_c = cos1[:, None, :].astype(q.dtype)
    sin_c = sin1[:, None, :].astype(q.dtype)
    q = q * cos_c + _rotate_half(q) * sin_c
    k = k * cos_c + _rotate_half(k) * sin_c

    page = jnp.take_along_axis(table, (pos // ps)[:, None], axis=1)[:, 0]
    off = pos % ps
    heads = jnp.arange(kvh_l)
    kpool = kpool.at[page[:, None], heads[None, :], off[:, None]].set(k)
    vpool = vpool.at[page[:, None], heads[None, :], off[:, None]].set(v)

    attn = select_paged_attention(tp_axis=axis)(
        q, kpool, vpool, table, pos + 1).reshape(b, nh_l * hd)
    part = _mm(attn, w["o"])
    if lora:          # o's A is row-sharded: partial delta, same psum
        part = part + lora_delta(lora, "o", li, attn, aidx)
    x = x + jax.lax.psum(part, axis)
    h = _rms(x[:, None], w["ln2"], cfg.rms_norm_eps)[:, 0]
    return x + _ffn_tp(w, h, axis, lora, aidx, li), kpool, vpool


def prefill_layer_tp(w, x, cos, sin, mask, cfg, axis, lora=(),
                     aidx=None, li=0):
    """Per-shard prefill layer: ``x`` [B, S, H] replicated; returns
    (out replicated, k/v caches [B, S, kvH/tp, D] local)."""
    b, s, _ = x.shape
    hd = cfg.head_dim
    h = _rms(x, w["ln1"], cfg.rms_norm_eps)
    qp, kp, vp, nh_l, kvh_l = _local_qkv(w, h, hd, lora, aidx, li)
    q = qp.reshape(b, s, nh_l, hd)
    k = kp.reshape(b, s, kvh_l, hd)
    v = vp.reshape(b, s, kvh_l, hd)
    cos_c = cos[None, :, None, :].astype(q.dtype)
    sin_c = sin[None, :, None, :].astype(q.dtype)
    q = q * cos_c + _rotate_half(q) * sin_c
    k = k * cos_c + _rotate_half(k) * sin_c

    from ...ops.pallas.flash_attention import sdpa
    attn = sdpa(q, k, v, attn_mask=mask[:, None, None, :],
                is_causal=True).reshape(b, s, nh_l * hd)
    part = _mm(attn, w["o"])
    if lora:
        part = part + lora_delta(lora, "o", li, attn, aidx)
    x = x + jax.lax.psum(part, axis)
    h = _rms(x, w["ln2"], cfg.rms_norm_eps)
    return x + _ffn_tp(w, h, axis, lora, aidx, li), k, v


def prefill_layer_cached_tp(w, x, kpool, vpool, row, cos_s, sin_s, mask,
                            cfg, axis, lora=(), aidx=None, li=0):
    """Per-shard cached-suffix prefill layer: suffix queries attend the
    resident prefix gathered from the LOCAL pool shard (prefix keys for
    this device's heads live on this device) concatenated with the
    suffix's own k/v.  Mirror of ``engine._prefill_layer_cached`` plus
    the o/down all-reduces; returns (out, k_suffix, v_suffix local)."""
    b, s, _ = x.shape
    hd = cfg.head_dim
    h = _rms(x, w["ln1"], cfg.rms_norm_eps)
    qp, kp, vp, nh_l, kvh_l = _local_qkv(w, h, hd, lora, aidx, li)
    q = qp.reshape(b, s, nh_l, hd)
    k = kp.reshape(b, s, kvh_l, hd)
    v = vp.reshape(b, s, kvh_l, hd)
    cos_c = cos_s[None, :, None, :].astype(q.dtype)
    sin_c = sin_s[None, :, None, :].astype(q.dtype)
    q = q * cos_c + _rotate_half(q) * sin_c
    k = k * cos_c + _rotate_half(k) * sin_c

    kpre = gather_kv_pages(kpool, row)[None]
    vpre = gather_kv_pages(vpool, row)[None]
    from ...ops.pallas.flash_attention import sdpa
    kcat = jnp.concatenate([kpre.astype(k.dtype), k], axis=1)
    vcat = jnp.concatenate([vpre.astype(v.dtype), v], axis=1)
    attn = sdpa(q, kcat, vcat, attn_mask=mask,
                is_causal=False).reshape(b, s, nh_l * hd)
    part = _mm(attn, w["o"])
    if lora:
        part = part + lora_delta(lora, "o", li, attn, aidx)
    x = x + jax.lax.psum(part, axis)
    h = _rms(x, w["ln2"], cfg.rms_norm_eps)
    return x + _ffn_tp(w, h, axis, lora, aidx, li), k, v


# ------------------------------------------------- int8 KV page bodies
def _proj_qkv(w, h, cfg, axis, lora=(), aidx=None, li=0):
    """(q, k, v, nh_local, kvh_local) for either construction mode:
    single-chip (``axis=None``) goes through ``_qkv_proj`` so fused
    quantized states keep their one-GEMV path; per-shard derives local
    head counts from the shard widths like ``_local_qkv``.  LoRA
    deltas stay f32/bf16 ON TOP of the weight-only matmuls — quantized
    base weights compose with any adapter."""
    hd = cfg.head_dim
    if axis is None:
        qp, kp, vp = _qkv_proj(w, h, cfg.num_attention_heads,
                               cfg.num_key_value_heads, hd, lora, aidx,
                               li)
    else:
        qp, kp, vp = _mm(h, w["q"]), _mm(h, w["k"]), _mm(h, w["v"])
        if lora:
            qp = qp + lora_delta(lora, "q", li, h, aidx)
            kp = kp + lora_delta(lora, "k", li, h, aidx)
            vp = vp + lora_delta(lora, "v", li, h, aidx)
    return qp, kp, vp, qp.shape[-1] // hd, kp.shape[-1] // hd


def _out_reduce(part, axis):
    """Row-sharded output projection: psum inside a shard_map, identity
    on the single-chip path."""
    return part if axis is None else jax.lax.psum(part, axis)


def _ffn_quant(w, h, axis, lora=(), aidx=None, li=0):
    if axis is None:
        return _ffn(w, h, lora, aidx, li)
    return _ffn_tp(w, h, axis, lora, aidx, li)


def decode_layer_paged_quant(w, x, kpool, vpool, kscale, vscale, table,
                             cos1, sin1, pos, cfg, axis=None, lora=(),
                             aidx=None, li=0):
    """Paged decode layer over int8 KV pools: quantize this token's
    k/v rows on write (per-(token, head) scale into the scale pools —
    same traced step, no extra host sync), attend through the
    dequantizing gather.  ``axis=None`` is the tp=1 runner; an axis
    name runs the same body per-shard with the o/down all-reduces.
    Returns (out, kpool, vpool, kscale, vscale)."""
    b = x.shape[0]
    hd = cfg.head_dim
    ps = kpool.shape[2]
    h = _rms(x[:, None], w["ln1"], cfg.rms_norm_eps)[:, 0]
    qp, kp, vp, nh_l, kvh_l = _proj_qkv(w, h, cfg, axis, lora, aidx, li)
    q = qp.reshape(b, nh_l, hd)
    k = kp.reshape(b, kvh_l, hd)
    v = vp.reshape(b, kvh_l, hd)
    cos_c = cos1[:, None, :].astype(q.dtype)
    sin_c = sin1[:, None, :].astype(q.dtype)
    q = q * cos_c + _rotate_half(q) * sin_c
    k = k * cos_c + _rotate_half(k) * sin_c

    page = jnp.take_along_axis(table, (pos // ps)[:, None], axis=1)[:, 0]
    off = pos % ps
    heads = jnp.arange(kvh_l)
    qk, sk = quantize_kv_rows(k)
    qv, sv = quantize_kv_rows(v)
    idx = (page[:, None], heads[None, :], off[:, None])
    kpool = kpool.at[idx].set(qk)
    vpool = vpool.at[idx].set(qv)
    kscale = kscale.at[idx].set(sk)
    vscale = vscale.at[idx].set(sv)

    attn = paged_attention_quant(
        q, kpool, vpool, kscale, vscale, table, pos + 1,
        tp_axis=axis).reshape(b, nh_l * hd)
    part = _mm(attn, w["o"])
    if lora:
        part = part + lora_delta(lora, "o", li, attn, aidx)
    x = x + _out_reduce(part, axis)
    h = _rms(x[:, None], w["ln2"], cfg.rms_norm_eps)[:, 0]
    return (x + _ffn_quant(w, h, axis, lora, aidx, li), kpool, vpool,
            kscale, vscale)


def prefill_layer_cached_quant(w, x, kpool, vpool, kscale, vscale, row,
                               cos_s, sin_s, mask, cfg, axis=None,
                               lora=(), aidx=None, li=0):
    """Cached-suffix prefill layer over int8 KV pools: the resident
    prefix dequantizes through the scale-aware gather; the suffix's own
    k/v stay float here (the runner quantizes them at the pool write).
    Returns (out, k_suffix, v_suffix) like the dense mirrors."""
    b, s, _ = x.shape
    hd = cfg.head_dim
    h = _rms(x, w["ln1"], cfg.rms_norm_eps)
    qp, kp, vp, nh_l, kvh_l = _proj_qkv(w, h, cfg, axis, lora, aidx, li)
    q = qp.reshape(b, s, nh_l, hd)
    k = kp.reshape(b, s, kvh_l, hd)
    v = vp.reshape(b, s, kvh_l, hd)
    cos_c = cos_s[None, :, None, :].astype(q.dtype)
    sin_c = sin_s[None, :, None, :].astype(q.dtype)
    q = q * cos_c + _rotate_half(q) * sin_c
    k = k * cos_c + _rotate_half(k) * sin_c

    kpre = gather_kv_pages_quant(kpool, kscale, row, k.dtype)[None]
    vpre = gather_kv_pages_quant(vpool, vscale, row, v.dtype)[None]
    from ...ops.pallas.flash_attention import sdpa
    kcat = jnp.concatenate([kpre, k], axis=1)
    vcat = jnp.concatenate([vpre, v], axis=1)
    attn = sdpa(q, kcat, vcat, attn_mask=mask,
                is_causal=False).reshape(b, s, nh_l * hd)
    part = _mm(attn, w["o"])
    if lora:
        part = part + lora_delta(lora, "o", li, attn, aidx)
    x = x + _out_reduce(part, axis)
    h = _rms(x, w["ln2"], cfg.rms_norm_eps)
    return x + _ffn_quant(w, h, axis, lora, aidx, li), k, v
