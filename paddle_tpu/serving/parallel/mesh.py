"""Serving-mesh construction and validation.

One axis (``tp``) is enough for the serving runner: attention heads and
the FFN hidden dimension shard along it, everything per-token stays
replicated.  ``parse_mesh`` accepts every knob spelling the CLI and
``create_engine`` take (``4``, ``"4"``, ``"tp=4"``, ``(4,)``) so the
flag, the server argument, and the Python API agree on one parser.
"""
from __future__ import annotations

__all__ = ["parse_mesh", "validate_tp", "mesh_devices", "TP_AXIS"]

TP_AXIS = "tp"


def parse_mesh(mesh) -> int:
    """Normalize a mesh knob to the tp size.

    Accepted: ``None`` (-> 1), an int, ``"4"``, ``"tp=4"``, and a
    1-tuple/list ``(4,)`` (the ISSUE's ``mesh_shape=(1,)`` spelling).
    """
    if mesh is None:
        return 1
    if isinstance(mesh, (tuple, list)):
        if len(mesh) != 1:
            raise ValueError(
                f"serving mesh has a single tp axis; got shape {mesh!r}")
        mesh = mesh[0]
    if isinstance(mesh, str):
        s = mesh.strip().lower()
        if s.startswith("tp="):
            s = s[3:]
        try:
            mesh = int(s)
        except ValueError:
            raise ValueError(
                f"cannot parse mesh spec {mesh!r}; expected an int, "
                f"'tp=N', or a 1-tuple") from None
    tp = int(mesh)
    if tp < 1:
        raise ValueError(f"mesh tp size must be >= 1, got {tp}")
    return tp


def validate_tp(config, tp: int) -> None:
    """The head-sharded layout's divisibility contract, checked loudly
    at engine construction instead of as a shape error mid-trace."""
    if tp == 1:
        return
    nh = config.num_attention_heads
    kvh = config.num_key_value_heads
    inter = config.intermediate_size
    for what, n in (("num_attention_heads", nh),
                    ("num_key_value_heads", kvh),
                    ("intermediate_size", inter)):
        if n % tp:
            raise ValueError(
                f"tp={tp} must divide {what}={n} (attention heads and "
                "the FFN hidden dim shard along the tp axis)")


def mesh_devices(tp: int):
    """The first ``tp`` local devices, validated against what the
    backend actually exposes (on CPU: set ``XLA_FLAGS=--xla_force_"
    "host_platform_device_count=N`` before jax initializes)."""
    import jax

    devices = jax.devices()
    if len(devices) < tp:
        raise ValueError(
            f"mesh tp={tp} needs {tp} devices but the "
            f"{devices[0].platform if devices else '?'} backend exposes "
            f"{len(devices)} (for CPU testing set XLA_FLAGS="
            f"--xla_force_host_platform_device_count={tp} before jax "
            "initializes)")
    return devices[:tp]
