"""Mesh-aware serving: tensor-parallel model runner over shard_map.

The engine (``serving/engine.py``) drives admission and scheduling but
no longer owns its jitted programs — it calls a
:class:`~paddle_tpu.serving.parallel.runner.ModelRunner`, which owns
the ``jax.sharding.Mesh`` (a single ``tp`` axis), places the weights
with ``NamedSharding`` (attention heads and the FFN hidden dim sharded
on ``tp``; embeddings, norms, and the LM head replicated), shards the
paged KV pool along the head axis, and runs decode / prefill /
cached-prefill / CoW-copy as ``shard_map`` computations with an
all-reduce only at the attention and FFN output projections.

``tp=1`` takes the exact single-chip code path (no mesh, no
``shard_map``) so the subsystem reduces to today's behavior; ``tp>1``
is CPU-testable via ``XLA_FLAGS=--xla_force_host_platform_device_count=8``.
"""
from .mesh import mesh_devices, parse_mesh, validate_tp
from .runner import ModelRunner

__all__ = ["ModelRunner", "mesh_devices", "parse_mesh", "validate_tp"]
