"""Mesh-aware model runner: the engine's device half.

The :class:`ModelRunner` owns everything that lives on (or is traced
for) the accelerator side of the serving engine: the weights and their
placement, the paged KV pools, the rope tables, the device-resident
decode state (table/pos/tok/active + the sampled-token ring), and the
four jit families — decode step, per-bucket prefill, per-bucket cached
prefill, and the CoW page copy.  The engine keeps the host half:
scheduler, block manager, host mirrors, sampling, and request
lifecycle.

Two construction modes, selected by ``tp``:

``tp == 1``
    The exact single-chip programs the engine owned before the runner
    seam existed — no mesh, no ``shard_map``, no ``device_put`` — so
    ``mesh_shape=(1,)`` reduces bit-for-bit to the previous behavior.

``tp > 1``
    A 1-axis ``jax.sharding.Mesh`` over the first ``tp`` devices.
    q/k/v/gate/up are column-sharded and o/down row-sharded with
    ``NamedSharding``; embeddings, norms, and the LM head are
    replicated; the KV pools shard along the head axis
    (``[L, pages+1, kvh/tp, page_size, hd]`` per device) so the
    BlockManager's page table stays host-side and mesh-agnostic.  All
    four jit families run as ``shard_map`` computations whose only
    collectives are the attention-output and FFN-down ``psum``s
    (see ``layers.py``).

The engine's serving invariants carry over unchanged: slot occupancy /
positions / tables are data (ONE decode trace per engine lifetime —
``decode_traces`` counts them), the decode state is donated through the
step, and admissions/evictions patch single slot rows in place.
"""
from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from ... import observability as _obs
from ...observability.resources import record_compile, resource_tracker
from ...models.generation import (_decode_layer_paged, _ffn,
                                  _layer_weights, _mm, _prefill_layer,
                                  _qkv_proj, _rope_at)
from ...models.llama import _rope_tables, _rotate_half
from ...models.llama_hybrid import _rms
from ...ops.pallas.paged_attention import (gather_kv_pages,
                                           quantize_kv_rows)
from ...ops.pallas.quant_matmul import QuantizedWeight
from .layers import (decode_layer_paged_quant, decode_layer_paged_tp,
                     prefill_layer_cached_quant, prefill_layer_cached_tp,
                     prefill_layer_tp)
from .mesh import TP_AXIS, mesh_devices, validate_tp

__all__ = ["ModelRunner"]

_M_STEP_TRACES = _obs.counter(
    "serving_decode_step_traces_total",
    "decode-step jit traces — continuous batching keeps this at 1 per "
    "engine (2 with speculative decoding: the plain step + the verify "
    "program); growth means admissions are re-tracing")
_M_VERIFY_TRACES = _obs.counter(
    "serving_spec_verify_traces_total",
    "verify-program jit traces — exactly 1 per speculative engine; "
    "growth means drafts are leaking into shapes")
_M_PREFILL_TRACES = _obs.counter(
    "serving_prefill_traces_total",
    "prefill jit traces (one per prompt-length bucket)", ("bucket",))

# weight suffixes sharded on tp: columns for the input-side projections
# (each device owns nh/tp query heads, kvh/tp KV heads, I/tp FFN
# columns), rows for the output-side projections whose partial products
# the layer all-reduces
_COL_SHARDED = ("self_attn.q_proj.weight", "self_attn.k_proj.weight",
                "self_attn.v_proj.weight", "mlp.gate_proj.weight",
                "mlp.up_proj.weight")
_ROW_SHARDED = ("self_attn.o_proj.weight", "mlp.down_proj.weight")
_FUSED_KEYS = ("self_attn.qkv_fused.weight", "mlp.gateup_fused.weight")
# LoRA bank keys whose BASE weight is row-sharded: the adapter's A
# (which contracts the sharded input) splits with it, B replicates;
# every other key shards B's output columns and replicates A
_LORA_ROW_KEYS = ("o", "down")


def _leaf_bytes(v) -> int:
    """Device bytes of one weight leaf: QuantizedWeight counts its int8
    (or nibble-packed int4) values plus the f32 scale vector; dense
    arrays count shape * itemsize; shapeless leaves count 0."""
    if isinstance(v, QuantizedWeight):
        return (int(np.prod(v.q.shape)) * jnp.dtype(v.q.dtype).itemsize
                + int(np.prod(v.scale.shape))
                * jnp.dtype(v.scale.dtype).itemsize)
    if hasattr(v, "shape"):
        return int(np.prod(v.shape)) * jnp.dtype(v.dtype).itemsize
    return 0


class ModelRunner:
    """Device-side serving runner (see module docstring).

    The engine talks to it through a narrow seam: :meth:`decode_step`,
    :meth:`prefill`, :meth:`prefill_cached`, :meth:`copy_page`,
    :meth:`push_slot`, :meth:`fetch_ring`, :meth:`correct_tokens`.
    """

    def __init__(self, config, state: dict, *, tp: int = 1,
                 max_slots: int, page_size: int, table_width: int,
                 num_pages: int, dump_page: int, sync_interval: int = 1,
                 emit_logits: bool = False, spec_k: int = 0,
                 kv_quant: bool = False, lora_slots: int = 0,
                 lora_rank: int = 0,
                 per_device_pool_bytes: int | None = None):
        self.config = config
        self.tp = int(tp)
        self.max_slots = int(max_slots)
        self.page_size = int(page_size)
        self.table_width = int(table_width)
        self.num_pages = int(num_pages)
        self.dump_page = int(dump_page)
        self.sync_interval = int(sync_interval)
        self.emit_logits = bool(emit_logits)
        self.spec_k = int(spec_k)
        self.kv_quant = bool(kv_quant)
        # LoRA adapter bank: lora_slots usable rows + the zeroed
        # no-adapter row 0, one static rank axis.  lora_slots == 0 is
        # the off mode: the bank and the per-slot index vector are
        # empty tuples — zero pytree leaves in every jitted signature,
        # so the dense jaxprs stay byte-identical (the kv_quant trick).
        self.lora_slots = int(lora_slots)
        self.lora_rank = int(lora_rank)
        if self.lora_slots and self.lora_rank < 1:
            raise ValueError(
                f"lora_slots={self.lora_slots} requires lora_rank >= 1,"
                f" got {self.lora_rank}")
        validate_tp(config, self.tp)
        self._validate_quantized_state(state)

        L = config.num_hidden_layers
        kvh, hd = config.num_key_value_heads, config.head_dim
        dtype = state["llama.embed_tokens.weight"].dtype
        pool_rows = self.num_pages + 1               # + dump page
        pool_shape = (L, pool_rows, kvh, self.page_size, hd)
        # int8 KV page mode: pools store int8, one f32 scale per
        # (layer, page row, head, slot) rides in separate scale pools.
        # Dense mode keeps EXACTLY the old arrays — the scale members
        # become empty tuples, which contribute zero pytree leaves to
        # every jitted signature, so the dense jaxprs are unchanged.
        pool_dtype = jnp.int8 if self.kv_quant else dtype
        scale_shape = (L, pool_rows, kvh, self.page_size)
        self._rope_len = self.table_width * self.page_size
        cos, sin = _rope_tables(self._rope_len, hd, config.rope_theta)
        cos = cos.astype(jnp.float32)
        sin = sin.astype(jnp.float32)
        table0 = np.full((self.max_slots, self.table_width),
                         self.dump_page, np.int32)
        # with speculation the ring rows are WIDE ([slots, k+1]: a verify
        # step deposits every candidate token; the plain step uses column
        # 0) so the host sync stays ONE transfer either way
        ring_shape = ((self.sync_interval, self.max_slots)
                      if self.spec_k == 0 else
                      (self.sync_interval, self.max_slots,
                       self.spec_k + 1))

        if self.tp == 1:
            self.mesh = None
            self.devices = list(jax.devices()[:1]) if jax.devices() else []
            self.state = state
            self.kpool = jnp.zeros(pool_shape, pool_dtype)
            self.vpool = jnp.zeros(pool_shape, pool_dtype)
            if self.kv_quant:
                self.kscale = jnp.zeros(scale_shape, jnp.float32)
                self.vscale = jnp.zeros(scale_shape, jnp.float32)
            else:
                self.kscale = self.vscale = ()
            if self.lora_slots:
                self.lora = self._build_lora_bank()
                self._aidx_dev = jnp.zeros((self.max_slots,), jnp.int32)
            else:
                self.lora = self._aidx_dev = ()
            self._cos, self._sin = cos, sin
            self._table_dev = jnp.asarray(table0)
            self._pos_dev = jnp.zeros((self.max_slots,), jnp.int32)
            self._tok_dev = jnp.zeros((self.max_slots,), jnp.int32)
            self._active_dev = jnp.zeros((self.max_slots,), jnp.int32)
            self._ring_dev = jnp.zeros(ring_shape, jnp.int32)
            self._ridx_dev = jnp.zeros((), jnp.int32)
        else:
            from jax.sharding import Mesh, NamedSharding, PartitionSpec
            self._check_state_shardable(state)
            self.devices = mesh_devices(self.tp)
            self.mesh = Mesh(np.asarray(self.devices), (TP_AXIS,))
            self._pool_pspec = PartitionSpec(
                None, None, TP_AXIS, None, None)
            self._scale_pspec = PartitionSpec(None, None, TP_AXIS, None)
            rep = NamedSharding(self.mesh, PartitionSpec())
            self.state = {k: self._place(k, v) for k, v in state.items()}
            pool_sh = NamedSharding(self.mesh, self._pool_pspec)
            self.kpool = jax.device_put(jnp.zeros(pool_shape, pool_dtype),
                                        pool_sh)
            self.vpool = jax.device_put(jnp.zeros(pool_shape, pool_dtype),
                                        pool_sh)
            if self.kv_quant:
                scale_sh = NamedSharding(self.mesh, self._scale_pspec)
                self.kscale = jax.device_put(
                    jnp.zeros(scale_shape, jnp.float32), scale_sh)
                self.vscale = jax.device_put(
                    jnp.zeros(scale_shape, jnp.float32), scale_sh)
            else:
                self.kscale = self.vscale = ()
            if self.lora_slots:
                specs = self._lora_pspecs()
                bank = self._build_lora_bank()
                self.lora = {
                    "a": {k: jax.device_put(
                        v, NamedSharding(self.mesh, specs["a"][k]))
                        for k, v in bank["a"].items()},
                    "b": {k: jax.device_put(
                        v, NamedSharding(self.mesh, specs["b"][k]))
                        for k, v in bank["b"].items()},
                    "scale": jax.device_put(bank["scale"], rep),
                }
                self._aidx_dev = jax.device_put(
                    jnp.zeros((self.max_slots,), jnp.int32), rep)
            else:
                self.lora = self._aidx_dev = ()
            self._cos = jax.device_put(cos, rep)
            self._sin = jax.device_put(sin, rep)
            self._table_dev = jax.device_put(jnp.asarray(table0), rep)
            self._pos_dev = jax.device_put(
                jnp.zeros((self.max_slots,), jnp.int32), rep)
            self._tok_dev = jax.device_put(
                jnp.zeros((self.max_slots,), jnp.int32), rep)
            self._active_dev = jax.device_put(
                jnp.zeros((self.max_slots,), jnp.int32), rep)
            self._ring_dev = jax.device_put(
                jnp.zeros(ring_shape, jnp.int32), rep)
            self._ridx_dev = jax.device_put(
                jnp.zeros((), jnp.int32), rep)

        self.decode_traces = 0      # python mirror of _M_STEP_TRACES
        self.verify_traces = 0      # python mirror of _M_VERIFY_TRACES
        self._step_fn = self._make_step_fn()
        self._verify_fn = (self._make_verify_fn() if self.spec_k
                           else None)
        self._prefill_fns: dict[int, object] = {}   # bucket -> jitted fn
        self._prefill_cached_fns: dict[int, object] = {}
        self._copy_page_fn = self._make_copy_page_fn()
        self._copy_page_compiled = False    # compile-ledger first-call

        # per-device footprint estimates + mesh-position registration for
        # the resource snapshot (CPU devices export no memory_stats, so
        # /debug/resources reports these alongside whatever stats exist)
        itemsize = jnp.dtype(pool_dtype).itemsize
        pool_total = 2 * int(np.prod(pool_shape)) * itemsize
        if self.kv_quant:           # + the f32 scale pools
            pool_total += 2 * int(np.prod(scale_shape)) * 4
        self._pool_bytes_per_device = (
            int(per_device_pool_bytes) if per_device_pool_bytes
            else pool_total // self.tp)
        sharded = sum(
            _leaf_bytes(v) for k, v in state.items()
            if k.endswith(_COL_SHARDED) or k.endswith(_ROW_SHARDED))
        replicated = sum(_leaf_bytes(v)
                         for v in state.values()) - sharded
        self._weight_bytes_per_device = sharded // self.tp + replicated
        if self.lora_slots:
            # bank halves shard like their base weights: A for the
            # row-sharded projections, B for the column-sharded ones
            lora_sharded = sum(
                _leaf_bytes(self.lora["a"][k]) for k in _LORA_ROW_KEYS
            ) + sum(_leaf_bytes(self.lora["b"][k])
                    for k in self.lora["b"] if k not in _LORA_ROW_KEYS)
            lora_total = sum(_leaf_bytes(v) for v in
                             jax.tree_util.tree_leaves(self.lora))
            self._lora_bytes_per_device = (
                lora_sharded // self.tp + (lora_total - lora_sharded))
        else:
            self._lora_bytes_per_device = 0
        resource_tracker().set_mesh({
            f"{d.platform}:{d.id}": {TP_AXIS: i}
            for i, d in enumerate(self.devices)})

    # ----------------------------------------------------------- placement
    @staticmethod
    def _spec_for(key: str):
        from jax.sharding import PartitionSpec
        if key.endswith(_COL_SHARDED):
            return PartitionSpec(None, TP_AXIS)
        if key.endswith(_ROW_SHARDED):
            return PartitionSpec(TP_AXIS, None)
        return PartitionSpec()      # embeddings / norms / lm_head

    @staticmethod
    def _validate_quantized_state(state: dict):
        """Loud construction-time rejection of MALFORMED quantized
        leaves (both tp modes): a broken QuantizedWeight would otherwise
        surface as an opaque shape error deep inside the first trace."""
        for key, v in state.items():
            if not isinstance(v, QuantizedWeight):
                continue
            if v.kind not in ("int8", "int4"):
                raise ValueError(
                    f"state[{key!r}]: unsupported quant kind {v.kind!r}"
                    " (expected 'int8' or 'int4')")
            if not (hasattr(v.q, "shape") and hasattr(v.scale, "shape")):
                raise ValueError(
                    f"state[{key!r}]: QuantizedWeight q/scale must be "
                    "arrays (missing scale?)")
            if v.q.ndim != 2:
                raise ValueError(
                    f"state[{key!r}]: quantized values must be 2-D, "
                    f"got shape {tuple(v.q.shape)}")
            if v.scale.ndim != 1 or v.scale.shape[0] != v.q.shape[1]:
                raise ValueError(
                    f"state[{key!r}]: scale shape "
                    f"{tuple(v.scale.shape)} does not match one scale "
                    f"per output channel (expected ({v.q.shape[1]},))")
            rows = v.k // 2 if v.kind == "int4" else v.k
            if v.q.shape[0] != rows:
                raise ValueError(
                    f"state[{key!r}]: {v.kind} values have "
                    f"{v.q.shape[0]} rows, expected {rows} for "
                    f"K={v.k}")

    def _check_state_shardable(self, state: dict):
        for k, v in state.items():
            if k.endswith(_FUSED_KEYS):
                raise ValueError(
                    f"state has fused weight {k!r}: fused serving "
                    "states are single-chip only (tp=1) — the tp>1 "
                    "runner shards the per-projection q/k/v and "
                    "gate/up weights individually")
            if isinstance(v, QuantizedWeight):
                if k.endswith(_ROW_SHARDED):
                    if v.q.shape[0] % self.tp:
                        raise ValueError(
                            f"state[{k!r}]: quantized K rows "
                            f"{v.q.shape[0]} not divisible by tp="
                            f"{self.tp}" + (
                                " (int4 packs two K rows per int8 "
                                "byte — K/2 must divide)"
                                if v.kind == "int4" else ""))
                elif k.endswith(_COL_SHARDED):
                    if v.q.shape[1] % self.tp:
                        raise ValueError(
                            f"state[{k!r}]: quantized N columns "
                            f"{v.q.shape[1]} not divisible by tp="
                            f"{self.tp}")
                continue
            if not isinstance(v, (np.ndarray, jnp.ndarray)):
                raise ValueError(
                    f"state[{k!r}] is {type(v).__name__}, not an array "
                    "or QuantizedWeight — cannot be head-sharded")

    def _quant_specs(self, key: str, v: QuantizedWeight):
        """(q_spec, scale_spec, local_k) for one quantized leaf.

        Column-sharded projections split q and the per-output-channel
        scale along N and keep the global K.  Row-sharded projections
        split q along K — each shard's ``weight_only_matmul`` K-check
        must see the LOCAL contraction length, so the placed leaf's aux
        ``k`` becomes ``k // tp`` — while the per-N scale replicates
        (it multiplies the partial products before the psum, which is
        linear, so scaling per shard is exact)."""
        from jax.sharding import PartitionSpec
        if key.endswith(_COL_SHARDED):
            return (PartitionSpec(None, TP_AXIS),
                    PartitionSpec(TP_AXIS), v.k)
        if key.endswith(_ROW_SHARDED):
            return (PartitionSpec(TP_AXIS, None), PartitionSpec(),
                    v.k // self.tp)
        return PartitionSpec(), PartitionSpec(), v.k

    def _place(self, key: str, v):
        """device_put one weight leaf with its tp sharding."""
        from jax.sharding import NamedSharding
        if isinstance(v, QuantizedWeight):
            qspec, sspec, k_local = self._quant_specs(key, v)
            q = jax.device_put(jnp.asarray(v.q),
                               NamedSharding(self.mesh, qspec))
            scale = jax.device_put(jnp.asarray(v.scale),
                                   NamedSharding(self.mesh, sspec))
            return QuantizedWeight(q, scale, kind=v.kind, k=k_local)
        return jax.device_put(
            jnp.asarray(v), NamedSharding(self.mesh, self._spec_for(key)))

    def _state_specs(self):
        """Pytree of shard_map in_specs mirroring the placed state:
        QuantizedWeight leaves become QuantizedWeight-of-PartitionSpecs
        whose aux (kind, k) copies the PLACED leaf — row shards already
        carry the local k — so the spec tree and the argument tree
        flatten identically."""
        specs = {}
        for k, v in self.state.items():
            if isinstance(v, QuantizedWeight):
                qspec, sspec, _ = self._quant_specs(k, v)
                specs[k] = QuantizedWeight(qspec, sspec, kind=v.kind,
                                           k=v.k)
            else:
                specs[k] = self._spec_for(k)
        return specs

    # ---------------------------------------------------------- LoRA bank
    def _build_lora_bank(self):
        """Zeroed packed bank ``{"a": {key: [L, rows, r, in]}, "b":
        {key: [L, rows, r, out]}, "scale": [rows]}`` — row 0 stays all
        zero forever (the no-adapter row), so a mixed batch indexes one
        bank in ONE traced program.  f32 regardless of base dtype: the
        delta matmuls accumulate in f32 anyway and the bank is tiny."""
        from ..lora.store import lora_key_dims
        dims = lora_key_dims(self.config)
        L = self.config.num_hidden_layers
        rows, r = self.lora_slots + 1, self.lora_rank
        return {
            "a": {k: jnp.zeros((L, rows, r, ind), jnp.float32)
                  for k, (ind, _) in dims.items()},
            "b": {k: jnp.zeros((L, rows, r, outd), jnp.float32)
                  for k, (_, outd) in dims.items()},
            "scale": jnp.zeros((rows,), jnp.float32),
        }

    def _lora_pspecs(self):
        """shard_map/placement specs mirroring the bank pytree: B
        column-sharded for q/k/v/gate/up, A row-sharded for o/down
        (both on the trailing dim axis of [L, rows, r, dim]), scale
        replicated — the existing o/down psums stay the only
        collectives.  Off mode collapses to one P() broadcast over the
        empty tuple."""
        from jax.sharding import PartitionSpec as P
        if not self.lora_slots:
            return P()
        from ..lora.store import lora_key_dims
        keys = list(lora_key_dims(self.config))
        col = P(None, None, None, TP_AXIS)
        return {
            "a": {k: (col if k in _LORA_ROW_KEYS else P())
                  for k in keys},
            "b": {k: (P() if k in _LORA_ROW_KEYS else col)
                  for k in keys},
            "scale": P(),
        }

    def load_adapter(self, row: int, a: dict, b: dict, scale: float):
        """Write one adapter into bank row ``row`` (eager ``.at[].set``
        per leaf — admission-path, never per step).  ``a``/``b`` map
        each projection key to its full [L, r, dim] host tensor; on a
        mesh the updated leaves re-pin to their bank sharding so the
        next traced step sees the layout it was traced for."""
        if not self.lora_slots:
            raise RuntimeError(
                "runner built with lora_slots=0 has no adapter bank")
        if not 1 <= int(row) <= self.lora_slots:
            raise ValueError(
                f"bank row {row} out of range 1..{self.lora_slots} "
                "(row 0 is the reserved no-adapter row)")
        new_a = {k: v.at[:, row].set(jnp.asarray(a[k], jnp.float32))
                 for k, v in self.lora["a"].items()}
        new_b = {k: v.at[:, row].set(jnp.asarray(b[k], jnp.float32))
                 for k, v in self.lora["b"].items()}
        scale_arr = self.lora["scale"].at[row].set(float(scale))
        if self.mesh is not None:
            from jax.sharding import NamedSharding
            specs = self._lora_pspecs()
            new_a = {k: jax.device_put(
                v, NamedSharding(self.mesh, specs["a"][k]))
                for k, v in new_a.items()}
            new_b = {k: jax.device_put(
                v, NamedSharding(self.mesh, specs["b"][k]))
                for k, v in new_b.items()}
            scale_arr = jax.device_put(
                scale_arr, NamedSharding(self.mesh, specs["scale"]))
        self.lora = {"a": new_a, "b": new_b, "scale": scale_arr}

    def lora_bank_bytes(self) -> int:
        """Total device bytes of the adapter bank (0 when off)."""
        if not self.lora_slots:
            return 0
        return sum(_leaf_bytes(v)
                   for v in jax.tree_util.tree_leaves(self.lora))

    # ------------------------------------------------------- jitted bodies
    # Every jitted signature threads (kscale, vscale) right after the
    # pools, and (lora, aidx) at the tail.  Off modes pass the empty
    # tuples stored at construction: zero pytree leaves, so the
    # flattened argument list — and therefore the jaxpr — is
    # byte-identical to the pre-quant / pre-LoRA program.  The
    # shard_map specs use P() for those positions (a pspec broadcasts
    # over an empty subtree).
    def _make_step_fn(self):
        if self.tp == 1:
            return jax.jit(self._build_step(),
                           donate_argnums=(1, 2, 3, 4, 6, 7, 9, 10))
        from jax.sharding import PartitionSpec as P
        pool = self._pool_pspec
        sspec = self._scale_pspec if self.kv_quant else P()
        mapped = jax.shard_map(
            self._build_step_tp(), mesh=self.mesh,
            in_specs=(self._state_specs(), pool, pool, sspec, sspec,
                      P(), P(), P(), P(), P(), P(), P(), P(),
                      self._lora_pspecs(), P()),
            out_specs=(pool, pool, sspec, sspec, P(), P(), P(), P(),
                       P()),
            check_vma=False)
        return jax.jit(mapped, donate_argnums=(1, 2, 3, 4, 6, 7, 9, 10))

    def _build_step(self):
        cfg = self.config
        L = cfg.num_hidden_layers
        emit_logits = self.emit_logits
        rope_len = self._rope_len
        wide_ring = self.spec_k > 0
        kv_quant = self.kv_quant
        runner = self

        def step(state, kpool, vpool, kscale, vscale, table, pos, tok,
                 active, ring, ridx, cos, sin, lora, aidx):
            # python body runs at trace time only: a second execution of
            # this line means an admission/eviction re-traced the step
            runner.decode_traces += 1
            _M_STEP_TRACES.inc()
            # a finished slot keeps decoding until the next host sync
            # (deferred-sync overrun); clamp so its rope/table lookups
            # stay in range — overrun writes land in the slot's own
            # reserved tail or the dump page, never another sequence
            posc = jnp.minimum(pos, rope_len - 1)
            emb = jnp.take(state["llama.embed_tokens.weight"], tok,
                           axis=0)
            cos1, sin1 = _rope_at(cos, sin, posc)
            h = emb
            kps, vps, kss, vss = [], [], [], []
            for i in range(L):
                w = _layer_weights(state, i)
                if kv_quant:
                    h, kp_, vp_, ks_, vs_ = decode_layer_paged_quant(
                        w, h, kpool[i], vpool[i], kscale[i], vscale[i],
                        table, cos1, sin1, posc, cfg, None, lora, aidx,
                        i)
                    kss.append(ks_)
                    vss.append(vs_)
                else:
                    h, kp_, vp_ = _decode_layer_paged(
                        w, h, kpool[i], vpool[i], table, cos1, sin1,
                        posc, cfg, lora, aidx, i)
                kps.append(kp_)
                vps.append(vp_)
            kpool = jnp.stack(kps)
            vpool = jnp.stack(vps)
            if kv_quant:
                kscale = jnp.stack(kss)
                vscale = jnp.stack(vss)
            h = _rms(h[:, None], state["llama.norm.weight"],
                     cfg.rms_norm_eps)[:, 0]
            logits = _logits_of(state, h).astype(jnp.float32)
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            act = active.astype(bool)
            pos2 = pos + active                 # idle slots stay parked
            tok2 = jnp.where(act, nxt, tok)     # greedy chains on device
            ring2 = (ring.at[ridx, :, 0].set(nxt) if wide_ring
                     else ring.at[ridx].set(nxt))
            ridx2 = (ridx + 1) % ring.shape[0]
            return (kpool, vpool, kscale, vscale, pos2, tok2, ring2,
                    ridx2, logits if emit_logits
                    else jnp.zeros((), jnp.float32))

        return step

    def _build_step_tp(self):
        """The shard_map body: same step, per-shard layers.  Everything
        except the pools is replicated; the head-parallel layers psum at
        the o/down projections, so the post-norm logits (and therefore
        the argmax'd next token and the ring) are device-invariant."""
        cfg = self.config
        L = cfg.num_hidden_layers
        emit_logits = self.emit_logits
        rope_len = self._rope_len
        wide_ring = self.spec_k > 0
        kv_quant = self.kv_quant
        runner = self

        def step(state, kpool, vpool, kscale, vscale, table, pos, tok,
                 active, ring, ridx, cos, sin, lora, aidx):
            runner.decode_traces += 1
            _M_STEP_TRACES.inc()
            posc = jnp.minimum(pos, rope_len - 1)
            emb = jnp.take(state["llama.embed_tokens.weight"], tok,
                           axis=0)
            cos1, sin1 = _rope_at(cos, sin, posc)
            h = emb
            kps, vps, kss, vss = [], [], [], []
            for i in range(L):
                w = _layer_weights(state, i)
                if kv_quant:
                    h, kp_, vp_, ks_, vs_ = decode_layer_paged_quant(
                        w, h, kpool[i], vpool[i], kscale[i], vscale[i],
                        table, cos1, sin1, posc, cfg, TP_AXIS, lora,
                        aidx, i)
                    kss.append(ks_)
                    vss.append(vs_)
                else:
                    h, kp_, vp_ = decode_layer_paged_tp(
                        w, h, kpool[i], vpool[i], table, cos1, sin1,
                        posc, cfg, TP_AXIS, lora, aidx, i)
                kps.append(kp_)
                vps.append(vp_)
            kpool = jnp.stack(kps)
            vpool = jnp.stack(vps)
            if kv_quant:
                kscale = jnp.stack(kss)
                vscale = jnp.stack(vss)
            h = _rms(h[:, None], state["llama.norm.weight"],
                     cfg.rms_norm_eps)[:, 0]
            logits = _logits_of(state, h).astype(jnp.float32)
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            act = active.astype(bool)
            pos2 = pos + active
            tok2 = jnp.where(act, nxt, tok)
            ring2 = (ring.at[ridx, :, 0].set(nxt) if wide_ring
                     else ring.at[ridx].set(nxt))
            ridx2 = (ridx + 1) % ring.shape[0]
            return (kpool, vpool, kscale, vscale, pos2, tok2, ring2,
                    ridx2, logits if emit_logits
                    else jnp.zeros((), jnp.float32))

        return step

    def _make_verify_fn(self):
        if self.tp == 1:
            return jax.jit(self._build_verify(tp=False),
                           donate_argnums=(1, 2, 3, 4, 6, 7, 9, 10))
        from jax.sharding import PartitionSpec as P
        pool = self._pool_pspec
        sspec = self._scale_pspec if self.kv_quant else P()
        mapped = jax.shard_map(
            self._build_verify(tp=True), mesh=self.mesh,
            in_specs=(self._state_specs(), pool, pool, sspec, sspec,
                      P(), P(), P(), P(), P(), P(), P(), P(), P(),
                      P(), self._lora_pspecs(), P()),
            out_specs=(pool, pool, sspec, sspec, P(), P(), P(), P()),
            check_vma=False)
        return jax.jit(mapped, donate_argnums=(1, 2, 3, 4, 6, 7, 9, 10))

    def _build_verify(self, *, tp: bool):
        """The speculative verify program: score ``k+1`` candidate
        positions per slot in ONE step.

        The ``[slots, k+1]`` token grid (the slot's current token +
        its ``k`` draft tokens) flattens to a ``[slots*(k+1)]`` batch
        that runs the SAME paged decode layer as the plain step — every
        row writes its token's KV at ``pos + j`` first, then attends
        with ``lens = pos + j + 1``, so row ``j`` sees exactly the
        prefix a sequential decode would have seen (its own slot's
        writes ``j' <= j``; later rows' writes sit past ``lens`` and
        rejected rows' stale KV is masked the same way until a later
        step overwrites it in place — KV rollback is free).  Acceptance
        is computed on device: the longest prefix where the draft
        matches the target argmax, ``+1`` for the correction/bonus
        token, advances pos/tok; the full candidate row lands in the
        wide ring for the host to re-derive the same acceptance without
        an extra transfer.  Slots with no draft (``dlen == 0``) reduce
        exactly to the plain step.  Shapes depend only on
        ``(slots, k)`` — drafts and their lengths are data, so this
        traces ONCE; with the plain step that makes ``decode_traces``
        exactly 2 for a speculative engine.

        A draft-model proposer or parallel sampling (n>1) later reuses
        this program unchanged: both only change how the ``draft`` grid
        is filled on the host, not how it is scored."""
        cfg = self.config
        L = cfg.num_hidden_layers
        rope_len = self._rope_len
        k = self.spec_k
        M = k + 1
        kv_quant = self.kv_quant
        runner = self

        def verify(state, kpool, vpool, kscale, vscale, table, pos,
                   tok, active, ring, ridx, draft, dlen, cos, sin,
                   lora, aidx):
            # trace-time counters, exactly like the plain step body
            runner.decode_traces += 1
            runner.verify_traces += 1
            _M_STEP_TRACES.inc()
            _M_VERIFY_TRACES.inc()
            S = tok.shape[0]
            # [S, M] candidate grid: column 0 is the slot's current
            # token (the plain step's input), columns 1..k its drafts
            grid = jnp.concatenate([tok[:, None], draft], axis=1)
            offs = jnp.arange(M, dtype=jnp.int32)
            pos_f = (pos[:, None] + offs[None, :]).reshape(-1)
            posc = jnp.minimum(pos_f, rope_len - 1)
            tok_f = grid.reshape(-1)
            table_f = jnp.repeat(table, M, axis=0)
            # every candidate row of a slot shares its adapter; `lora`
            # is a pytree whose STRUCTURE (empty vs non-empty tuple)
            # carries the on/off bit — truthiness is trace-time static
            # tpu-lint: disable=jit-traced-branch
            aidx_f = jnp.repeat(aidx, M) if lora else aidx
            emb = jnp.take(state["llama.embed_tokens.weight"], tok_f,
                           axis=0)
            cos1, sin1 = _rope_at(cos, sin, posc)
            h = emb
            kps, vps, kss, vss = [], [], [], []
            for i in range(L):
                w = _layer_weights(state, i)
                if kv_quant:
                    h, kp_, vp_, ks_, vs_ = decode_layer_paged_quant(
                        w, h, kpool[i], vpool[i], kscale[i], vscale[i],
                        table_f, cos1, sin1, posc, cfg,
                        TP_AXIS if tp else None, lora, aidx_f, i)
                    kss.append(ks_)
                    vss.append(vs_)
                elif tp:
                    h, kp_, vp_ = decode_layer_paged_tp(
                        w, h, kpool[i], vpool[i], table_f, cos1, sin1,
                        posc, cfg, TP_AXIS, lora, aidx_f, i)
                else:
                    h, kp_, vp_ = _decode_layer_paged(
                        w, h, kpool[i], vpool[i], table_f, cos1, sin1,
                        posc, cfg, lora, aidx_f, i)
                kps.append(kp_)
                vps.append(vp_)
            kpool = jnp.stack(kps)
            vpool = jnp.stack(vps)
            if kv_quant:
                kscale = jnp.stack(kss)
                vscale = jnp.stack(vss)
            h = _rms(h[:, None], state["llama.norm.weight"],
                     cfg.rms_norm_eps)[:, 0]
            logits = _logits_of(state, h).astype(jnp.float32)
            y = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            y = y.reshape(S, M)
            # longest matching prefix: draft[:, j] proposed what the
            # target's own argmax y[:, j] confirms (or not)
            m = ((draft == y[:, :k]) &
                 (offs[None, :k] < dlen[:, None])).astype(jnp.int32)
            # cast back: cumprod/sum promote to int64 under x64, which
            # would change pos2's dtype and re-trace the plain step
            acc = jnp.cumprod(m, axis=1).sum(axis=1).astype(jnp.int32)
            commit = (acc + 1) * active                     # [S]; idle: 0
            pos2 = pos + commit
            tok_new = jnp.take_along_axis(y, acc[:, None], axis=1)[:, 0]
            tok2 = jnp.where(active.astype(bool), tok_new, tok)
            ring2 = ring.at[ridx].set(y)
            ridx2 = (ridx + 1) % ring.shape[0]
            return (kpool, vpool, kscale, vscale, pos2, tok2, ring2,
                    ridx2)

        return verify

    def _make_copy_page_fn(self):
        kv_quant = self.kv_quant

        def copy(kp, vp, ks, vs, src, dst):
            kp2 = kp.at[:, dst].set(kp[:, src])
            vp2 = vp.at[:, dst].set(vp[:, src])
            if kv_quant:        # scale rows travel with their page
                ks = ks.at[:, dst].set(ks[:, src])
                vs = vs.at[:, dst].set(vs[:, src])
            return kp2, vp2, ks, vs

        if self.tp == 1:
            # CoW page copy: src/dst are data — one trace for the engine
            return jax.jit(copy, donate_argnums=(0, 1, 2, 3))
        from jax.sharding import PartitionSpec as P
        pool = self._pool_pspec
        sspec = self._scale_pspec if kv_quant else P()
        # per-shard copy: a page holds every local head's rows, so the
        # CoW duplicate is collective-free
        mapped = jax.shard_map(
            copy, mesh=self.mesh,
            in_specs=(pool, pool, sspec, sspec, P(), P()),
            out_specs=(pool, pool, sspec, sspec), check_vma=False)
        return jax.jit(mapped, donate_argnums=(0, 1, 2, 3))

    def _prefill_fn(self, bucket: int):
        fn = self._prefill_fns.get(bucket)
        if fn is not None:
            return fn
        cfg = self.config
        L = cfg.num_hidden_layers
        ps = self.page_size
        n_pages = bucket // ps
        tp = self.tp
        kv_quant = self.kv_quant

        def prefill(state, ids, length, table_row, kpool, vpool,
                    kscale, vscale, cos, sin, lora, aidx):
            _M_PREFILL_TRACES.labels(str(bucket)).inc()
            x = jnp.take(state["llama.embed_tokens.weight"], ids, axis=0)
            pmask = jnp.arange(bucket)[None, :] < length
            for i in range(L):
                w = _layer_weights(state, i)
                if tp == 1:
                    x, k, v = _prefill_layer(w, x, cos[:bucket],
                                             sin[:bucket], pmask, cfg,
                                             lora, aidx, i)
                else:
                    x, k, v = prefill_layer_tp(w, x, cos[:bucket],
                                               sin[:bucket], pmask, cfg,
                                               TP_AXIS, lora, aidx, i)
                if kv_quant:
                    # quantize the whole prompt's KV once per layer,
                    # then page the int8 rows + their scales
                    qk, sk = quantize_kv_rows(k[0])
                    qv, sv = quantize_kv_rows(v[0])
                    k, v = qk[None], qv[None]
                for p in range(n_pages):
                    sl = slice(p * ps, (p + 1) * ps)
                    rows_k = k[0, sl].swapaxes(0, 1)
                    rows_v = v[0, sl].swapaxes(0, 1)
                    kpool = kpool.at[i, table_row[p]].set(
                        rows_k.astype(kpool.dtype))
                    vpool = vpool.at[i, table_row[p]].set(
                        rows_v.astype(vpool.dtype))
                    if kv_quant:
                        kscale = kscale.at[i, table_row[p]].set(
                            sk[sl].swapaxes(0, 1))
                        vscale = vscale.at[i, table_row[p]].set(
                            sv[sl].swapaxes(0, 1))
            x = _rms(x, state["llama.norm.weight"], cfg.rms_norm_eps)
            last = jnp.take_along_axis(
                x, (length - 1)[:, None, None].astype(jnp.int32),
                axis=1)[:, 0]
            logits = _logits_of(state, last).astype(jnp.float32)
            return kpool, vpool, kscale, vscale, logits

        # kpool/vpool donation: prefill updates the pool in place instead
        # of double-buffering the engine's whole KV footprint per admit
        if tp == 1:
            fn = jax.jit(prefill, donate_argnums=(4, 5, 6, 7))
        else:
            from jax.sharding import PartitionSpec as P
            pool = self._pool_pspec
            sspec = self._scale_pspec if kv_quant else P()
            mapped = jax.shard_map(
                prefill, mesh=self.mesh,
                in_specs=(self._state_specs(), P(), P(), P(), pool,
                          pool, sspec, sspec, P(), P(),
                          self._lora_pspecs(), P()),
                out_specs=(pool, pool, sspec, sspec, P()),
                check_vma=False)
            fn = jax.jit(mapped, donate_argnums=(4, 5, 6, 7))
        self._prefill_fns[bucket] = fn
        return fn

    def _prefill_cached_fn(self, bucket: int):
        """Suffix prefill for a prompt whose first ``cached_len`` tokens
        are already resident in the pool (shared prefix pages and/or a
        CoW-copied tail).  One trace per suffix bucket: the prefix
        length, table row, and positions are all data."""
        fn = self._prefill_cached_fns.get(bucket)
        if fn is not None:
            return fn
        cfg = self.config
        L = cfg.num_hidden_layers
        kvh_l = cfg.num_key_value_heads // self.tp
        ps = self.page_size
        W = self.table_width
        dump = self.dump_page
        rope_len = self._rope_len
        tp = self.tp

        kv_quant = self.kv_quant

        def prefill(state, ids, length, cached_len, row, kpool, vpool,
                    kscale, vscale, cos, sin, lora, aidx):
            _M_PREFILL_TRACES.labels(f"cached:{bucket}").inc()
            x = jnp.take(state["llama.embed_tokens.weight"], ids, axis=0)
            j = jnp.arange(bucket)
            absp = cached_len + j               # absolute positions
            posc = jnp.minimum(absp, rope_len - 1)
            cos_s = jnp.take(cos, posc, axis=0)
            sin_s = jnp.take(sin, posc, axis=0)
            # suffix queries see: resident prefix keys (< cached_len),
            # then causal within the (padded) suffix
            t_pre = jnp.arange(W * ps)
            pre_ok = jnp.broadcast_to(t_pre[None, :] < cached_len,
                                      (bucket, W * ps))
            suf_ok = (j[None, :] <= j[:, None]) & (j[None, :] < length[0])
            mask = jnp.concatenate([pre_ok, suf_ok], axis=1)[None, None]
            # per-token write targets (padding lands on the dump page)
            valid = j < length[0]
            page_w = jnp.where(valid,
                               row[jnp.minimum(absp // ps, W - 1)], dump)
            off = absp % ps
            heads = jnp.arange(kvh_l)
            widx = (page_w[:, None], heads[None, :], off[:, None])
            for i in range(L):
                w = _layer_weights(state, i)
                if kv_quant:
                    x, k, v = prefill_layer_cached_quant(
                        w, x, kpool[i], vpool[i], kscale[i], vscale[i],
                        row, cos_s, sin_s, mask, cfg,
                        TP_AXIS if tp > 1 else None, lora, aidx, i)
                    qk, sk = quantize_kv_rows(k[0])
                    qv, sv = quantize_kv_rows(v[0])
                    kpool = kpool.at[(i,) + widx].set(qk)
                    vpool = vpool.at[(i,) + widx].set(qv)
                    kscale = kscale.at[(i,) + widx].set(sk)
                    vscale = vscale.at[(i,) + widx].set(sv)
                    continue
                if tp == 1:
                    kpre = gather_kv_pages(kpool[i], row)
                    vpre = gather_kv_pages(vpool[i], row)
                    x, k, v = _prefill_layer_cached(
                        w, x, kpre[None], vpre[None], cos_s, sin_s,
                        mask, cfg, lora, aidx, i)
                else:
                    x, k, v = prefill_layer_cached_tp(
                        w, x, kpool[i], vpool[i], row, cos_s, sin_s,
                        mask, cfg, TP_AXIS, lora, aidx, i)
                kpool = kpool.at[i, page_w[:, None], heads[None, :],
                                 off[:, None]].set(k[0])
                vpool = vpool.at[i, page_w[:, None], heads[None, :],
                                 off[:, None]].set(v[0])
            x = _rms(x, state["llama.norm.weight"], cfg.rms_norm_eps)
            last = jnp.take_along_axis(
                x, (length - 1)[:, None, None].astype(jnp.int32),
                axis=1)[:, 0]
            logits = _logits_of(state, last).astype(jnp.float32)
            return kpool, vpool, kscale, vscale, logits

        if tp == 1:
            fn = jax.jit(prefill, donate_argnums=(5, 6, 7, 8))
        else:
            from jax.sharding import PartitionSpec as P
            pool = self._pool_pspec
            sspec = self._scale_pspec if kv_quant else P()
            mapped = jax.shard_map(
                prefill, mesh=self.mesh,
                in_specs=(self._state_specs(), P(), P(), P(), P(), pool,
                          pool, sspec, sspec, P(), P(),
                          self._lora_pspecs(), P()),
                out_specs=(pool, pool, sspec, sspec, P()),
                check_vma=False)
            fn = jax.jit(mapped, donate_argnums=(5, 6, 7, 8))
        self._prefill_cached_fns[bucket] = fn
        return fn

    # ------------------------------------------------------------ the seam
    def decode_step(self):
        """One lockstep decode step over every slot.  Returns the step's
        [slots, V] logits handle when the runner emits logits, else
        None.  First call after a (re)trace lands in the compile
        ledger."""
        traces_before = self.decode_traces
        t0 = time.perf_counter()
        (self.kpool, self.vpool, self.kscale, self.vscale,
         self._pos_dev, self._tok_dev, self._ring_dev, self._ridx_dev,
         logits) = self._step_fn(
            self.state, self.kpool, self.vpool, self.kscale,
            self.vscale, self._table_dev, self._pos_dev, self._tok_dev,
            self._active_dev, self._ring_dev, self._ridx_dev,
            self._cos, self._sin, self.lora, self._aidx_dev)
        if self.decode_traces != traces_before:
            sig = f"slots={self.max_slots} ring={self.sync_interval}"
            if self.tp > 1:
                sig += f" tp={self.tp}"
            record_compile("decode_step", t0, signature=sig)
        return logits if self.emit_logits else None

    def verify_step(self, draft: np.ndarray, dlen: np.ndarray):
        """One speculative verify step: ``draft`` [slots, k] int32
        candidate tokens, ``dlen`` [slots] int32 drafted counts (0 =
        the slot takes the plain-step path inside the program).  The
        uploads are data — shapes are fixed at construction, so this
        traces once.  Acceptance happens on device (pos/tok advance by
        the accepted count + 1); the host re-derives it from the wide
        ring row at the next sync."""
        if self._verify_fn is None:
            raise RuntimeError("runner built with spec_k=0 has no "
                               "verify program")
        traces_before = self.verify_traces
        t0 = time.perf_counter()
        (self.kpool, self.vpool, self.kscale, self.vscale,
         self._pos_dev, self._tok_dev, self._ring_dev,
         self._ridx_dev) = self._verify_fn(
            self.state, self.kpool, self.vpool, self.kscale,
            self.vscale, self._table_dev, self._pos_dev, self._tok_dev,
            self._active_dev, self._ring_dev, self._ridx_dev,
            jnp.asarray(draft, jnp.int32), jnp.asarray(dlen, jnp.int32),
            self._cos, self._sin, self.lora, self._aidx_dev)
        if self.verify_traces != traces_before:
            sig = (f"slots={self.max_slots} k={self.spec_k} "
                   f"ring={self.sync_interval}")
            if self.tp > 1:
                sig += f" tp={self.tp}"
            record_compile("verify_step", t0, signature=sig)

    def _prefill_aidx(self, adapter_row: int):
        """Scalar bank index for a whole-prompt prefill (one request =
        one adapter); the empty tuple in off mode keeps the jitted
        signature leaf-free."""
        if not self.lora_slots:
            return ()
        return jnp.asarray(int(adapter_row), jnp.int32)

    def prefill(self, ids: np.ndarray, plen: int, row: np.ndarray,
                adapter_row: int = 0):
        """Full-prompt prefill: pages the prompt's KV into the pool and
        returns the last-token logits handle.  ``ids`` is the
        [1, bucket] padded prompt."""
        bucket = ids.shape[1]
        fresh = bucket not in self._prefill_fns
        fn = self._prefill_fn(bucket)
        t0 = time.perf_counter()
        (self.kpool, self.vpool, self.kscale, self.vscale,
         logits) = fn(
            self.state, jnp.asarray(ids),
            jnp.asarray([plen], jnp.int32),
            jnp.asarray(row[:bucket // self.page_size]),
            self.kpool, self.vpool, self.kscale, self.vscale,
            self._cos, self._sin, self.lora,
            self._prefill_aidx(adapter_row))
        if fresh:
            record_compile(f"prefill[{bucket}]", t0,
                           signature=f"ids=[1,{bucket}]")
        return logits

    def prefill_cached(self, ids: np.ndarray, suffix_len: int,
                       cached_len: int, row: np.ndarray,
                       adapter_row: int = 0):
        """Cached-suffix prefill against the resident prefix pages."""
        bucket = ids.shape[1]
        fresh = bucket not in self._prefill_cached_fns
        fn = self._prefill_cached_fn(bucket)
        t0 = time.perf_counter()
        (self.kpool, self.vpool, self.kscale, self.vscale,
         logits) = fn(
            self.state, jnp.asarray(ids),
            jnp.asarray([suffix_len], jnp.int32),
            jnp.asarray(cached_len, jnp.int32), jnp.asarray(row),
            self.kpool, self.vpool, self.kscale, self.vscale,
            self._cos, self._sin, self.lora,
            self._prefill_aidx(adapter_row))
        if fresh:
            record_compile(f"prefill_cached[{bucket}]", t0,
                           signature=f"ids=[1,{bucket}]")
        return logits

    def copy_page(self, src: int, dst: int):
        """Copy-on-write page duplicate (head-local on the mesh)."""
        fresh = not self._copy_page_compiled
        t0 = time.perf_counter()
        (self.kpool, self.vpool, self.kscale,
         self.vscale) = self._copy_page_fn(
            self.kpool, self.vpool, self.kscale, self.vscale,
            jnp.asarray(src, jnp.int32), jnp.asarray(dst, jnp.int32))
        if fresh:
            self._copy_page_compiled = True
            record_compile("copy_page", t0,
                           signature=f"pool={self.kpool.shape}")

    def read_page(self, page: int):
        """Device -> host copy of one KV page: ``(k, v)`` numpy arrays
        of shape [L, kvh, page_size, hd] (full heads — shards gather
        transparently on the mesh), plus ``(kscale, vscale)``
        [L, kvh, page_size] f32 when the pools are int8 — the spill
        tier moves the quantized bytes, never a dequantized copy.
        Preemption-spill only: this is a host sync per call, never on
        the steady decode path."""
        if self.kv_quant:
            return (np.asarray(self.kpool[:, page]),
                    np.asarray(self.vpool[:, page]),
                    np.asarray(self.kscale[:, page]),
                    np.asarray(self.vscale[:, page]))
        return (np.asarray(self.kpool[:, page]),
                np.asarray(self.vpool[:, page]))

    def write_page(self, page: int, k, v, kscale=None, vscale=None):
        """Host -> device copy of one KV page (preempted-request resume
        unparking a host-tier copy).  Eager per-call dispatch is fine —
        this runs once per restored page at admission, not per step."""
        kpool = self.kpool.at[:, page].set(
            jnp.asarray(k, self.kpool.dtype))
        vpool = self.vpool.at[:, page].set(
            jnp.asarray(v, self.vpool.dtype))
        if self.kv_quant:
            if kscale is None or vscale is None:
                raise ValueError(
                    "int8 KV pages restore with their scales: "
                    "write_page(page, k, v, kscale, vscale)")
            kscale_p = self.kscale.at[:, page].set(
                jnp.asarray(kscale, jnp.float32))
            vscale_p = self.vscale.at[:, page].set(
                jnp.asarray(vscale, jnp.float32))
        if self.mesh is not None:
            # pin the result back to the head-sharded pool layout so the
            # next shard_map program sees the sharding it was traced for
            from jax.sharding import NamedSharding
            sh = NamedSharding(self.mesh, self._pool_pspec)
            kpool = jax.device_put(kpool, sh)
            vpool = jax.device_put(vpool, sh)
            if self.kv_quant:
                ssh = NamedSharding(self.mesh, self._scale_pspec)
                kscale_p = jax.device_put(kscale_p, ssh)
                vscale_p = jax.device_put(vscale_p, ssh)
        self.kpool = kpool
        self.vpool = vpool
        if self.kv_quant:
            self.kscale = kscale_p
            self.vscale = vscale_p

    def push_slot(self, slot: int, row: np.ndarray, pos: int, tok: int,
                  active: int, adapter_row: int = 0):
        """Patch ONE slot's row of the device-resident decode state
        (admission / eviction only — never per step)."""
        self._table_dev = self._table_dev.at[slot].set(jnp.asarray(row))
        self._pos_dev = self._pos_dev.at[slot].set(int(pos))
        self._tok_dev = self._tok_dev.at[slot].set(int(tok))
        self._active_dev = self._active_dev.at[slot].set(int(active))
        if self.lora_slots:
            self._aidx_dev = self._aidx_dev.at[slot].set(
                int(adapter_row))

    def fetch_ring(self) -> np.ndarray:
        """The host sync: ONE [sync_interval, slots] int32 transfer."""
        return np.asarray(self._ring_dev)

    def correct_tokens(self, corrections: list[tuple[int, int]]):
        """Push host-side sampling picks back into the device token
        state before the next step."""
        idx = jnp.asarray([s for s, _ in corrections], jnp.int32)
        val = jnp.asarray([t for _, t in corrections], jnp.int32)
        self._tok_dev = self._tok_dev.at[idx].set(val)

    def reinject_step(self):
        """Rebuild the decode-step jit (perf-gate hook: forces a fresh
        trace so retrace detection can be exercised deterministically)."""
        self._step_fn = self._make_step_fn()

    # ---------------------------------------------------------------- info
    def mesh_info(self) -> dict:
        """Per-device memory keyed by mesh position: footprint estimates
        (KV pool shard + weight shard/replica bytes) merged with live
        ``memory_stats()`` where the backend exports them."""
        devices = []
        for i, d in enumerate(self.devices):
            entry = {
                "device": f"{d.platform}:{d.id}", TP_AXIS: i,
                "kv_pool_bytes": self._pool_bytes_per_device,
                "weight_bytes": self._weight_bytes_per_device,
                "lora_bank_bytes": self._lora_bytes_per_device,
            }
            try:
                stats = d.memory_stats() or {}
            except Exception:
                stats = {}
            if "bytes_in_use" in stats:
                entry["bytes_in_use"] = int(stats["bytes_in_use"])
            if "peak_bytes_in_use" in stats:
                entry["peak_bytes_in_use"] = int(
                    stats["peak_bytes_in_use"])
            devices.append(entry)
        return {"tp": self.tp, "axis": TP_AXIS,
                "kv_quant": self.kv_quant, "devices": devices}


def _prefill_layer_cached(w, x, kpre, vpre, cos_s, sin_s, mask, cfg,
                          lora=(), aidx=None, li=0):
    """One transformer layer of suffix prefill against a resident
    prefix: ``x`` [1, S, H] suffix hidden, ``kpre``/``vpre``
    [1, Tpre, kvH, D] prefix KV gathered from the pool (keys already
    rotary-encoded at their absolute positions, exactly as prefill and
    decode wrote them), ``mask`` [1, 1, S, Tpre+S] bool.  Returns
    (out, k_suffix, v_suffix) — mirror of ``_prefill_layer``."""
    b, s, _ = x.shape
    nh, kvh, hd = (cfg.num_attention_heads, cfg.num_key_value_heads,
                   cfg.head_dim)
    h = _rms(x, w["ln1"], cfg.rms_norm_eps)
    qp, kp, vp = _qkv_proj(w, h, nh, kvh, hd, lora, aidx, li)
    q = qp.reshape(b, s, nh, hd)
    k = kp.reshape(b, s, kvh, hd)
    v = vp.reshape(b, s, kvh, hd)
    cos_c = cos_s[None, :, None, :].astype(q.dtype)
    sin_c = sin_s[None, :, None, :].astype(q.dtype)
    q = q * cos_c + _rotate_half(q) * sin_c
    k = k * cos_c + _rotate_half(k) * sin_c

    from ...ops.pallas.flash_attention import sdpa
    kcat = jnp.concatenate([kpre.astype(k.dtype), k], axis=1)
    vcat = jnp.concatenate([vpre.astype(v.dtype), v], axis=1)
    attn = sdpa(q, kcat, vcat, attn_mask=mask,
                is_causal=False).reshape(b, s, nh * hd)
    o = _mm(attn, w["o"])
    # `lora` pytree structure (empty tuple = off) is trace-time static
    # tpu-lint: disable=jit-traced-branch
    if lora:
        from ...ops.pallas.lora_matmul import lora_delta
        o = o + lora_delta(lora, "o", li, attn, aidx)
    x = x + o
    h = _rms(x, w["ln2"], cfg.rms_norm_eps)
    return (x + _ffn(w, h, lora, aidx, li), k, v)


def _logits_of(state, h):
    head = state.get("lm_head.weight")
    if head is not None:
        return _mm(h, head)
    return h @ state["llama.embed_tokens.weight"].T
