"""Iteration-level FCFS scheduler (Orca-style continuous batching).

Each engine iteration the scheduler (1) evicts finished / cancelled /
past-deadline sequences so their pages and slot free immediately,
(2) admits queued requests FCFS into free decode slots, reserving their
whole page budget up front (all-or-nothing: an admitted request can
never exhaust the pool mid-decode), and (3) reports backpressure when
the head of the queue cannot be placed.  Admission order is strict
FCFS — a head request that does not fit blocks the queue rather than
being overtaken (no starvation of large requests).
"""
from __future__ import annotations

from collections import deque

from .. import observability as _obs
from .block_manager import BlockManager
from .request import Request, RequestState

__all__ = ["Scheduler"]

_M_QUEUE_DEPTH = _obs.gauge(
    "serving_queue_depth", "requests waiting for a decode slot")
_M_ACTIVE = _obs.gauge(
    "serving_active_slots", "decode slots occupied by live sequences")
_M_ADMITTED = _obs.counter(
    "serving_admissions_total", "requests admitted into decode slots")
_M_EVICTED = _obs.counter(
    "serving_evictions_total", "sequences evicted from decode slots",
    ("reason",))
_M_BACKPRESSURE = _obs.counter(
    "serving_backpressure_total",
    "scheduling passes where the queue head could not be placed",
    ("reason",))


class Scheduler:
    def __init__(self, blocks: BlockManager, max_slots: int):
        if max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {max_slots}")
        self.blocks = blocks
        self.max_slots = int(max_slots)
        self.slots: list[Request | None] = [None] * self.max_slots
        self.queue: deque[Request] = deque()
        self.draining = False
        self._finalize = None      # engine callback: (req, reason, now)
        self._on_evict = None      # engine callback: (slot,) — park it

    # ------------------------------------------------------------ intake
    def submit(self, req: Request):
        self.queue.append(req)
        _M_QUEUE_DEPTH.set(len(self.queue))

    def drain(self):
        """Stop admitting; running sequences finish, queued ones wait
        (resume() re-opens admission)."""
        self.draining = True

    def resume(self):
        self.draining = False

    def has_work(self) -> bool:
        if any(r is not None for r in self.slots):
            return True
        return bool(self.queue) and not self.draining

    @property
    def active_count(self) -> int:
        return sum(r is not None for r in self.slots)

    # ---------------------------------------------------------- one pass
    def schedule(self, now: float) -> list[tuple[int, Request]]:
        """One scheduling pass: evict dead sequences, expire deadlines,
        admit FCFS.  Returns the newly admitted ``(slot, request)``
        pairs — the engine prefills them before the next decode step."""
        # 1) iteration-level eviction of cancelled / expired residents
        for i, req in enumerate(self.slots):
            if req is None:
                continue
            if req.cancel_requested:
                self.evict(i, "cancelled", now)
            elif req.deadline is not None and now > req.deadline:
                req.cancel_requested = True
                self.evict(i, "deadline", now)

        # 2) drop queued requests that were cancelled or expired
        kept = deque()
        for req in self.queue:
            if req.cancel_requested:
                _obs.flight("scheduler", "queue_drop", req=req.id,
                            reason="cancelled")
                self._finish(req, "cancelled", now)
            elif req.deadline is not None and now > req.deadline:
                _obs.flight("scheduler", "queue_drop", req=req.id,
                            reason="deadline")
                self._finish(req, "deadline", now)
            else:
                kept.append(req)
        self.queue = kept

        # 3) FCFS admission
        admitted: list[tuple[int, Request]] = []
        while self.queue and not self.draining:
            free = [i for i, r in enumerate(self.slots) if r is None]
            if not free:
                _M_BACKPRESSURE.labels("slots").inc()
                _obs.flight("scheduler", "backpressure", reason="slots",
                            head=self.queue[0].id, queued=len(self.queue))
                break
            head = self.queue[0]
            # prefix-cache-aware reservation: shared prefix pages are
            # refcounted, only the uncached suffix is charged against
            # the pool — with caching off this is the plain page count
            pages = self.blocks.allocate_seq(head.id, head.prompt,
                                             head.gen.max_new_tokens)
            if pages is None:
                # pool exhausted: the head waits (and blocks the queue —
                # strict FCFS), surfaced as backpressure, not an error
                _M_BACKPRESSURE.labels("pages").inc()
                _obs.flight("scheduler", "backpressure", reason="pages",
                            head=self.queue[0].id, queued=len(self.queue))
                break
            self.queue.popleft()
            slot = free[0]
            self.slots[slot] = head
            head.state = RequestState.PREFILL
            head.admitted_at = now
            _M_ADMITTED.inc()
            _obs.flight("scheduler", "admit", req=head.id, slot=slot,
                        pages=len(pages), queued=len(self.queue))
            if head.root_span is not None:
                head.root_span.add_event("scheduler.admit", slot=slot,
                                         pages=len(pages))
            admitted.append((slot, head))

        _M_QUEUE_DEPTH.set(len(self.queue))
        _M_ACTIVE.set(self.active_count)
        # fragmentation against the queue head's demand: idle pages the
        # blocked request cannot use (0.0 when nothing waits)
        head_need = None
        if self.queue:
            head = self.queue[0]
            head_need = self.blocks.pages_needed(head.prompt.size,
                                                 head.gen.max_new_tokens)
        self.blocks.record_fragmentation(head_need)
        return admitted

    # ---------------------------------------------------------- eviction
    def evict(self, slot: int, reason: str, now: float):
        """Free a slot and its pages; finalizes the request unless it
        already finished (reason 'finished' keeps its finish_reason)."""
        req = self.slots[slot]
        if req is None:
            return
        self.slots[slot] = None
        self.blocks.free_seq(req.id)
        if self._on_evict is not None:
            self._on_evict(slot)
        _M_EVICTED.labels(reason).inc()
        _obs.flight("scheduler", "evict", req=req.id, slot=slot,
                    reason=reason, generated=req.num_generated)
        if req.root_span is not None:
            req.root_span.add_event("scheduler.evict", slot=slot,
                                    reason=reason)
        _M_ACTIVE.set(self.active_count)
        if not req.is_finished():
            self._finish(req, reason, now)

    def _finish(self, req: Request, reason: str, now: float):
        if self._finalize is not None:
            self._finalize(req, reason, now)
        else:                       # standalone scheduler (tests)
            req.finish_reason = reason
            req.state = RequestState.CANCELLED \
                if reason in ("cancelled", "deadline") else RequestState.DONE
            req.finished_at = now
