"""Iteration-level priority scheduler (Orca-style continuous batching).

Each engine iteration the scheduler (1) evicts finished / cancelled /
past-deadline sequences so their pages and slot free immediately,
(2) admits queued requests into free decode slots in (priority, FCFS)
order, reserving their whole page budget up front (all-or-nothing: an
admitted request can never exhaust the pool mid-decode), and
(3) reports backpressure when the head of the queue cannot be placed.
Within a priority class admission is strict FCFS — a head request that
does not fit blocks the queue rather than being overtaken (no
starvation of large requests).

When the head outranks a resident and cannot be placed, the scheduler
preempts: the lowest-priority, most-recently-admitted DECODE resident
is handed to the engine's ``_preempt`` callback (which spills its
exclusive KV pages to the BlockManager host tier and parks the slot)
and re-queued ahead of later arrivals of its class; on re-admission
the engine resumes it from prompt + generated-so-far with greedy
token-for-token parity.  All-default-priority traffic never preempts
and degenerates to the exact FCFS order this scheduler always had.
"""
from __future__ import annotations

import time
from collections import deque

from .. import observability as _obs
from .block_manager import BlockManager
from .request import Request, RequestState

__all__ = ["Scheduler"]

_M_QUEUE_DEPTH = _obs.gauge(
    "serving_queue_depth", "requests waiting for a decode slot")
_M_ACTIVE = _obs.gauge(
    "serving_active_slots", "decode slots occupied by live sequences")
_M_ADMITTED = _obs.counter(
    "serving_admissions_total", "requests admitted into decode slots")
_M_EVICTED = _obs.counter(
    "serving_evictions_total", "sequences evicted from decode slots",
    ("reason",))
_M_BACKPRESSURE = _obs.counter(
    "serving_backpressure_total",
    "scheduling passes where the queue head could not be placed",
    ("reason",))
_M_PREEMPTED = _obs.counter(
    "serving_preemptions_total",
    "residents evicted for a higher-priority request (KV spilled to "
    "host, request re-queued for resume)")


class Scheduler:
    def __init__(self, blocks: BlockManager, max_slots: int, *,
                 clock=None, preempt_enabled: bool = True):
        if max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {max_slots}")
        self.blocks = blocks
        self.max_slots = int(max_slots)
        self.slots: list[Request | None] = [None] * self.max_slots
        self.queue: deque[Request] = deque()
        self.draining = False
        self.preempt_enabled = bool(preempt_enabled)
        self._clock = clock or time.monotonic
        self._arrivals = 0         # FIFO stamps handed out by submit()
        self._finalize = None      # engine callback: (req, reason, now)
        self._on_evict = None      # engine callback: (slot,) — park it
        self._preempt = None       # engine callback: (slot,) -> bool
        # usage meter (observability.usage), wired by the engine when
        # metering is on; with FLAGS_serving_fair_share it biases
        # victim selection toward the heaviest-page-second tenant
        self.usage = None

    # ------------------------------------------------------------ intake
    @staticmethod
    def _key(req: Request):
        # total admission order: higher priority first, FCFS (by the
        # submit-time arrival stamp — NOT the Request id, which is
        # construction order) within a class; a preempted victim keeps
        # its original stamp and so re-queues ahead of later arrivals
        # of its class
        return (-req.priority, req.arrival_seq)

    def submit(self, req: Request):
        if req.arrival_seq is None:
            req.arrival_seq = self._arrivals
            self._arrivals += 1
        key = self._key(req)
        if not self.queue or key >= self._key(self.queue[-1]):
            self.queue.append(req)      # the common (all-FCFS) path
        else:
            items = list(self.queue)
            for i, q in enumerate(items):
                if self._key(q) > key:
                    items.insert(i, req)
                    break
            self.queue = deque(items)
        _M_QUEUE_DEPTH.set(len(self.queue))

    def drain(self):
        """Stop admitting; running sequences finish, queued ones wait
        (resume() re-opens admission)."""
        self.draining = True

    def resume(self):
        self.draining = False

    def has_work(self) -> bool:
        if any(r is not None for r in self.slots):
            return True
        if not self.queue:
            return False
        if not self.draining:
            return True
        # drain: queued requests wait for resume(), but cancelled or
        # past-deadline ones must still be dropped — deadline drops only
        # run inside schedule(), so reporting "no work" here would
        # starve them until resume() and blow their deadlines silently
        now = self._clock()
        return any(r.cancel_requested
                   or (r.deadline is not None and now > r.deadline)
                   for r in self.queue)

    @property
    def active_count(self) -> int:
        return sum(r is not None for r in self.slots)

    # ---------------------------------------------------------- one pass
    def schedule(self, now: float) -> list[tuple[int, Request]]:
        """One scheduling pass: evict dead sequences, expire deadlines,
        admit FCFS.  Returns the newly admitted ``(slot, request)``
        pairs — the engine prefills them before the next decode step."""
        # 1) iteration-level eviction of cancelled / expired residents
        for i, req in enumerate(self.slots):
            if req is None:
                continue
            if req.cancel_requested:
                self.evict(i, "cancelled", now)
            elif req.deadline is not None and now > req.deadline:
                req.cancel_requested = True
                self.evict(i, "deadline", now)

        # 2) drop queued requests that were cancelled or expired
        kept = deque()
        for req in self.queue:
            if req.cancel_requested:
                _obs.flight("scheduler", "queue_drop", req=req.id,
                            reason="cancelled")
                self._finish(req, "cancelled", now)
            elif req.deadline is not None and now > req.deadline:
                _obs.flight("scheduler", "queue_drop", req=req.id,
                            reason="deadline")
                self._finish(req, "deadline", now)
            else:
                kept.append(req)
        self.queue = kept

        # 3) (priority, FCFS) admission
        admitted: list[tuple[int, Request]] = []
        while self.queue and not self.draining:
            head = self.queue[0]
            free = [i for i, r in enumerate(self.slots) if r is None]
            if not free:
                if self._try_preempt(head, now):
                    continue
                _M_BACKPRESSURE.labels("slots").inc()
                _obs.flight("scheduler", "backpressure", reason="slots",
                            head=self.queue[0].id, queued=len(self.queue))
                break
            # prefix-cache-aware reservation: shared prefix pages are
            # refcounted, only the uncached suffix is charged against
            # the pool — with caching off this is the plain page count.
            # A resume (preempted victim) re-reserves for its effective
            # prompt (original + generated) and its remaining budget —
            # for a fresh request these are exactly prompt/max_new
            pages = self.blocks.allocate_seq(head.id, head.resume_tokens(),
                                             head.remaining_new_tokens)
            if pages is None:
                # pool exhausted: the head waits (and blocks the queue —
                # strict FCFS), surfaced as backpressure, not an error
                if self._try_preempt(head, now):
                    continue
                _M_BACKPRESSURE.labels("pages").inc()
                _obs.flight("scheduler", "backpressure", reason="pages",
                            head=self.queue[0].id, queued=len(self.queue))
                break
            self.queue.popleft()
            slot = free[0]
            self.slots[slot] = head
            head.state = RequestState.PREFILL
            head.admitted_at = now
            _M_ADMITTED.inc()
            _obs.flight("scheduler", "admit", req=head.id, slot=slot,
                        pages=len(pages), queued=len(self.queue))
            if head.root_span is not None:
                head.root_span.add_event("scheduler.admit", slot=slot,
                                         pages=len(pages))
            admitted.append((slot, head))

        _M_QUEUE_DEPTH.set(len(self.queue))
        _M_ACTIVE.set(self.active_count)
        # fragmentation against the queue head's demand: idle pages the
        # blocked request cannot use (0.0 when nothing waits)
        head_need = None
        if self.queue:
            head = self.queue[0]
            head_need = self.blocks.pages_needed(
                head.resume_tokens().size, head.remaining_new_tokens)
        self.blocks.record_fragmentation(head_need)
        return admitted

    # -------------------------------------------------------- preemption
    def _try_preempt(self, head: Request, now: float) -> bool:
        """Make room for ``head`` by preempting a lower-priority DECODE
        resident: lowest class first, most-recently-admitted within the
        class (it has the least sunk work).  With
        ``FLAGS_serving_fair_share`` set and a usage meter wired, the
        heaviest-page-second tenant's residents are preferred within
        the lowest class — the tenant that consumed the most KV
        residency pays for the displacement first.  The engine callback
        spills the victim's exclusive pages to host RAM and parks the
        slot; a False return (spill failed / no engine) leaves the
        victim untouched.  On success the victim is re-queued for
        resume."""
        if not self.preempt_enabled or self._preempt is None:
            return False
        victims = [(i, r) for i, r in enumerate(self.slots)
                   if r is not None and r.state == RequestState.DECODE
                   and r.priority < head.priority]
        if not victims:
            return False
        heavy = None
        if self.usage is not None:
            from ..flags import FLAGS
            if FLAGS.get("FLAGS_serving_fair_share"):
                heavy = self.usage.heaviest_tenant()
        slot, victim = min(
            victims, key=lambda ir: (
                ir[1].priority,
                0 if getattr(ir[1], "tenant", None) == heavy else 1,
                -(ir[1].admitted_at or 0.0)))
        if not self._preempt(slot):
            return False
        self.slots[slot] = None
        victim.state = RequestState.QUEUED
        victim.admitted_at = None
        victim.preemptions += 1
        _M_PREEMPTED.inc()
        _obs.flight("scheduler", "preempt", req=victim.id, slot=slot,
                    by=head.id, generated=victim.num_generated)
        if victim.root_span is not None:
            victim.root_span.add_event("scheduler.preempt", slot=slot,
                                       by=head.id)
        self.submit(victim)
        _M_ACTIVE.set(self.active_count)
        return True

    # ---------------------------------------------------------- eviction
    def evict(self, slot: int, reason: str, now: float):
        """Free a slot and its pages; finalizes the request unless it
        already finished (reason 'finished' keeps its finish_reason)."""
        req = self.slots[slot]
        if req is None:
            return
        self.slots[slot] = None
        self.blocks.free_seq(req.id)
        if self._on_evict is not None:
            self._on_evict(slot)
        _M_EVICTED.labels(reason).inc()
        if req.timeline is not None and reason != "finished":
            # non-finish evictions (cancel/deadline/error) mark the
            # waterfall — the reason a timeline ends mid-lifecycle
            req.timeline.mark("evict", now, slot=slot, reason=reason)
        _obs.flight("scheduler", "evict", req=req.id, slot=slot,
                    reason=reason, generated=req.num_generated)
        if req.root_span is not None:
            req.root_span.add_event("scheduler.evict", slot=slot,
                                    reason=reason)
        _M_ACTIVE.set(self.active_count)
        if not req.is_finished():
            self._finish(req, reason, now)

    def _finish(self, req: Request, reason: str, now: float):
        if self._finalize is not None:
            self._finalize(req, reason, now)
        else:                       # standalone scheduler (tests)
            req.finish_reason = reason
            req.state = RequestState.CANCELLED \
                if reason in ("cancelled", "deadline") else RequestState.DONE
            req.finished_at = now
