"""paddle_tpu.serving — continuous-batching LLM inference engine.

Orca-style iteration-level scheduling over the paged KV machinery
(ops/pallas/paged_attention.py + models/generation.py), the layer that
turns "can run a batch" into "can serve traffic": requests are
admitted, interleaved, streamed, and cancelled between single-token
decode steps of ONE jitted program.

    from paddle_tpu.serving import create_engine, GenerationConfig
    engine = create_engine(model, max_slots=8, page_size=64,
                           enable_prefix_cache=True, sync_interval=8)
    req = engine.submit(prompt_ids, GenerationConfig(max_new_tokens=32))
    for tok in req.stream():
        ...

``enable_prefix_cache=True`` adds automatic prefix caching (vLLM-style
hash-chained page reuse + copy-on-write tails + LRU eviction): prompts
sharing page-aligned prefixes skip prefill for the shared part and are
charged pages only for their uncached suffix.  ``sync_interval=N``
batches host synchronization on the greedy path: decode state lives on
device and the host drains a sampled-token ring once every N steps.

Under overload the stack degrades in a defined order instead of all at
once: long prefills chunk in behind decode
(``FLAGS_serving_prefill_chunk``), low-priority decoding residents
preempt-and-swap their KV to a pinned-host tier and resume with greedy
token-for-token parity (``FLAGS_serving_preempt``; ``submit`` takes
``priority=``), and burn-rate shedding 429s the lowest queued class —
see README "Overload handling".

Modules:
  * request.py       — request lifecycle + streaming
  * block_manager.py — KV pages: free list / block tables / prefix
                       cache (refcounts, chain index, CoW, LRU)
  * scheduler.py     — priority admission (FIFO within class),
                       iteration-level eviction, preempt-and-swap
                       victim selection, drain
  * engine.py        — the prefill/decode driver (host scheduling,
                       deferred host sync, chunked prefill, preempted-
                       KV spill/restore) over a parallel.ModelRunner
  * quantize.py      — dense checkpoint -> quantized serving state
                       (int8/int4 QuantizedWeight per projection;
                       embeddings/norms/lm_head stay dense); pairs
                       with ``create_engine(quant=..., kv_quant=...)``
                       for int8 KV pages with per-page scales
  * spec.py          — speculative decoding: prompt-lookup (n-gram)
                       drafter + acceptance bookkeeping; the runner's
                       verify program scores k+1 positions per step
                       with bit-identical greedy outputs
  * lora/            — multi-LoRA serving: AdapterStore (LRU device
                       bank + host parking, per-request row pinning),
                       batched gather-LoRA matmul over the seven
                       projections (``submit(adapter=...)``), and the
                       offline batch lane (BatchJob JSONL drip-feed at
                       ``BATCH_PRIORITY``, ``POST /v1/batches``);
                       ``lora=None`` keeps dense jaxprs byte-identical
  * parallel/        — mesh-aware ModelRunner: tensor-parallel weight
                       placement, head-sharded KV pools, and every
                       jitted program (tp=1 == exact single-chip path)
  * server.py        — OpenAI-compatible HTTP front-end (SSE streaming,
                       backpressure, graceful drain) over one engine
  * router.py        — multi-replica router: prefix-affinity routing,
                       health probing + circuit breaking, bounded retry
  * client.py        — stdlib blocking/streaming HTTP client
  * watchdog.py      — stalled-decode-loop detector (flight-recorder +
                       thread-stack hang dumps)
  * slo.py           — per-request TTFT/TPOT/E2E SLO verdicts and
                       burn-rate gauges
  * faults.py        — deterministic fault injection (seedable
                       FaultPlan firing named faults at existing seams;
                       zero overhead when off)
  * supervisor.py    — engine self-healing: step-failure/stall recovery
                       via runner rebuild + in-flight replay, bounded
                       restart budget, escalate-to-drain

Every request is traced end to end (observability.tracing): the client,
router, server, and engine each open spans under ONE trace id carried
in the W3C ``traceparent`` header; ``GET /debug/trace`` on any replica
or router returns a chrome://tracing-loadable JSON of recent spans,
``GET /debug/flight`` the engine flight-recorder ring.

The engine additionally publishes ``current_phase`` (prefill /
prefill_chunk / decode / verify / host_sync / idle) as a plain
attribute at the same seams that charge
``serving_step_phase_seconds_total``, feeding the phase-attributed
sampling profiler (``FLAGS_obs_profile_interval_s``;
``GET /debug/profile?seconds=N`` on a replica, fanned out by the
router).  Alert fires snapshot evidence bundles via
``observability.capture`` — ``GET /debug/captures`` lists them (see
README "Continuous profiling & diagnostic capture").

Reference analog: the block_multi_head_attention serving path +
paddle_infer predictors, restructured as a vLLM/Orca-style engine.
"""
from __future__ import annotations

from .block_manager import BlockManager  # noqa: F401
from .client import ServingClient, ServingHTTPError  # noqa: F401
from .engine import (  # noqa: F401
    Engine, NonFiniteLogitsError, create_engine)
from .faults import (  # noqa: F401
    FaultPlan, InjectedFault, fault_plan_from_flags)
from .lora import (  # noqa: F401
    AdapterStore, BATCH_PRIORITY, BatchJob, merge_adapter,
    random_adapter)
from .parallel import ModelRunner, parse_mesh  # noqa: F401
from .quantize import quantize_state  # noqa: F401
from .request import GenerationConfig, Request, RequestState  # noqa: F401
from .router import (  # noqa: F401
    NoReplicaAvailable, Replica, Router, RouterServer)
from .scheduler import Scheduler  # noqa: F401
from .server import (  # noqa: F401
    BackpressureError, DrainingError, EngineWorker, ServingServer, serve)
from .slo import SLOConfig, SLOTracker  # noqa: F401
from .spec import NgramProposer, SpecStats  # noqa: F401
from .supervisor import EngineSupervisor  # noqa: F401
from .watchdog import Watchdog  # noqa: F401

__all__ = ["AdapterStore", "BATCH_PRIORITY", "BackpressureError",
           "BatchJob", "BlockManager", "DrainingError", "Engine",
           "EngineSupervisor", "EngineWorker", "FaultPlan",
           "GenerationConfig", "InjectedFault", "ModelRunner",
           "NgramProposer", "NoReplicaAvailable", "NonFiniteLogitsError",
           "Replica", "Request", "RequestState", "Router", "RouterServer",
           "SLOConfig", "SLOTracker", "Scheduler", "ServingClient",
           "ServingHTTPError", "ServingServer", "SpecStats", "Watchdog",
           "create_engine", "fault_plan_from_flags", "merge_adapter",
           "parse_mesh", "quantize_state", "random_adapter", "serve"]
