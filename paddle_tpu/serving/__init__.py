"""paddle_tpu.serving — continuous-batching LLM inference engine.

Orca-style iteration-level scheduling over the paged KV machinery
(ops/pallas/paged_attention.py + models/generation.py), the layer that
turns "can run a batch" into "can serve traffic": requests are
admitted, interleaved, streamed, and cancelled between single-token
decode steps of ONE jitted program.

    from paddle_tpu.serving import create_engine, GenerationConfig
    engine = create_engine(model, max_slots=8, page_size=64)
    req = engine.submit(prompt_ids, GenerationConfig(max_new_tokens=32))
    for tok in req.stream():
        ...

Modules:
  * request.py       — request lifecycle + streaming
  * block_manager.py — KV-page free list / block tables / backpressure
  * scheduler.py     — FCFS admission, iteration-level eviction, drain
  * engine.py        — the jitted prefill/decode driver

Reference analog: the block_multi_head_attention serving path +
paddle_infer predictors, restructured as a vLLM/Orca-style engine.
"""
from __future__ import annotations

from .block_manager import BlockManager  # noqa: F401
from .engine import Engine, create_engine  # noqa: F401
from .request import GenerationConfig, Request, RequestState  # noqa: F401
from .scheduler import Scheduler  # noqa: F401

__all__ = ["BlockManager", "Engine", "GenerationConfig", "Request",
           "RequestState", "Scheduler", "create_engine"]
