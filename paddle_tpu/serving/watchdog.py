"""Serving watchdog: detect a stalled decode loop and dump state.

Reference analog: the distributed CommTaskManager watchdog (and its
Paddle ancestor) — a side thread that notices work not progressing and
dumps diagnostics while the hang is live, instead of leaving only a
killed process to autopsy.

Detection rule: the engine's ``progress`` counter (incremented at the
end of every ``Engine.step``) has not moved for ``stall_seconds`` while
the scheduler still holds active slots.  Both reads are plain attribute
loads — the watchdog NEVER takes the worker lock, because the wedged
engine thread is usually the one holding it; a locking watchdog would
hang right alongside the thing it is meant to report.

On a trip the watchdog writes ``watchdog_<n>.json`` into
``FLAGS_metrics_dir`` (when set) containing the flight-recorder ring
(the scheduler/engine/block-manager events leading up to the stall),
every thread's current stack, and the last observed progress/active
values; bumps ``serving_watchdog_stalls_total``; and latches the
``serving_watchdog_stalled`` gauge until progress resumes.  One dump
per stall episode — a 60-second hang is one event, not sixty.

``check(now)`` is the whole detection step and takes an explicit
timestamp, so unit tests drive it with a fake clock in milliseconds;
``start()`` just runs ``check`` on a daemon-thread poll loop.
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time
import traceback

from .. import observability as _obs
from ..sanitizer import make_lock

__all__ = ["Watchdog"]

_M_STALLS = _obs.counter(
    "serving_watchdog_stalls_total",
    "decode-loop stalls detected (active slots, no step progress)")
_M_STALLED = _obs.gauge(
    "serving_watchdog_stalled",
    "1 while the decode loop is currently considered stalled")


class Watchdog:
    """Monitors one :class:`~paddle_tpu.serving.engine.Engine`.

    ``stall_seconds`` <= 0 disables the poll loop entirely (``start``
    becomes a no-op); ``check`` still works for tests.
    """

    def __init__(self, engine, stall_seconds: float, *,
                 poll_interval: float | None = None, dump_dir=None,
                 clock=time.monotonic):
        self.engine = engine
        self.stall_seconds = float(stall_seconds)
        self.poll_interval = (poll_interval if poll_interval is not None
                              else max(self.stall_seconds / 4, 0.05))
        self._dump_dir = dump_dir
        self._clock = clock
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._lock = make_lock("Watchdog._lock")  # guards only watchdog state
        self._last_progress = -1
        self._last_change: float | None = None
        self._tripped = False           # latched for the current episode
        self.stalls = 0                 # python-side mirror of _M_STALLS
        self.last_dump_path: str | None = None
        # optional stall callback (EngineSupervisor.note_stall): invoked
        # once per episode, AFTER the dump, from the watchdog thread —
        # the callee must only flag state, never touch the engine
        self.on_stall = None

    # --------------------------------------------------------- detection
    def check(self, now: float | None = None) -> bool:
        """One detection step; returns True when THIS call detected a
        new stall episode (and dumped).  Lock-free against the engine:
        reads ``engine.progress`` and ``scheduler.active_count`` only.
        """
        now = self._clock() if now is None else now
        progress = self.engine.progress
        active = self.engine.scheduler.active_count
        with self._lock:
            if progress != self._last_progress or active == 0:
                # moving (or idle — an idle engine is not stalled)
                self._last_progress = progress
                self._last_change = now
                if self._tripped:
                    self._tripped = False
                    _M_STALLED.set(0)
                return False
            if self._last_change is None:
                self._last_change = now
                return False
            if now - self._last_change < self.stall_seconds:
                return False
            if self._tripped:
                return False            # one dump per episode
            self._tripped = True
            stalled_for = now - self._last_change
            self.stalls += 1
            n = self.stalls
        _M_STALLS.inc()
        _M_STALLED.set(1)
        _obs.flight("watchdog", "stall", progress=progress,
                    active=active, stalled_for=round(stalled_for, 3))
        self.last_dump_path = self._dump(progress, active, stalled_for, n)
        if self.on_stall is not None:
            try:
                self.on_stall()
            except Exception:       # a broken callback must not break
                traceback.print_exc()   # stall detection itself
        return True

    def state(self) -> dict:
        with self._lock:
            return {"enabled": self.stall_seconds > 0,
                    "stall_seconds": self.stall_seconds,
                    "stalled": self._tripped,
                    "stalls": self.stalls,
                    "last_progress": self._last_progress,
                    "last_dump": self.last_dump_path}

    # -------------------------------------------------------------- dump
    def _dump(self, progress, active, stalled_for, n) -> str | None:
        """Assemble the hang report.  Everything read here must be safe
        against a wedged engine: flight ring (own lock, never held by
        the engine), thread stacks (interpreter-level), and plain
        attribute reads — NOT ``engine.stats()``, which walks scheduler
        structures the stuck thread may be mutating."""
        try:
            # tracker takes only its own lock (+ registry read-back) —
            # safe against the wedged engine, same as the flight ring
            resources = _obs.resource_tracker().snapshot()
        except Exception:
            resources = None
        try:
            # who holds / waits on every sanitized lock right now; with
            # FLAGS_sanitizer off there are no instrumented locks and
            # this is an empty graph.  Reads only the sanitizer's own
            # bookkeeping lock — a wedged engine cannot block it.
            from ..sanitizer import lock_wait_graph
            lock_graph = lock_wait_graph()
        except Exception:
            lock_graph = None
        report = {
            "stalled_for_s": round(stalled_for, 3),
            "progress": progress,
            "active_slots": active,
            "threads": self._thread_stacks(),
            "flight": {"capacity": _obs.flight_recorder().capacity,
                       "events": _obs.flight_recorder().snapshot()},
            "resources": resources,
            "lock_wait_graph": lock_graph,
        }
        dir_ = self._dump_dir
        if dir_ is None:
            from ..flags import FLAGS
            dir_ = FLAGS.get("FLAGS_metrics_dir") or None
        if not dir_:
            return None
        try:
            os.makedirs(dir_, exist_ok=True)
            path = os.path.join(dir_, f"watchdog_{n}.json")
            with open(path, "w") as f:
                json.dump(report, f, indent=2)
            return path
        except OSError:
            return None

    @staticmethod
    def _thread_stacks() -> list[dict]:
        frames = sys._current_frames()
        names = {t.ident: t.name for t in threading.enumerate()}
        out = []
        for ident, frame in frames.items():
            out.append({
                "thread_id": ident,
                "name": names.get(ident, "?"),
                "stack": [ln.rstrip() for ln in
                          traceback.format_stack(frame)],
            })
        return out

    # --------------------------------------------------------- poll loop
    def start(self):
        if self.stall_seconds <= 0 or self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="serving-watchdog")
        self._thread.start()

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    def _run(self):
        while not self._stop.wait(self.poll_interval):
            try:
                self.check()
            except Exception:       # a broken watchdog must not crash
                traceback.print_exc()   # the server it watches
