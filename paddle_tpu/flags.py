"""Global flag system.

Reference: PHI_DEFINE_EXPORTED_* registry (paddle/common/flags.h:343,
flags.cc — ~243 env-settable flags) + paddle.set_flags/get_flags.  Here a
plain dict registry; flags are seeded from the environment at import
(FLAGS_xxx env vars) like the reference.
"""
from __future__ import annotations

import os
from typing import Any

__all__ = ["define_flag", "set_flags", "get_flags", "FLAGS"]

FLAGS: dict[str, Any] = {}
_DEFS: dict[str, tuple[type, Any, str]] = {}


_BOOL_TRUE = ("1", "true", "yes")
_BOOL_FALSE = ("0", "false", "no")


def _coerce(name: str, value, t: type):
    """Coerce ``value`` to the registered flag type, loudly.

    Bools are strict: only the canonical spellings parse — ``"2"`` or
    ``"on"`` raise instead of silently becoming False (the pre-fix
    behavior), and non-bool truthy objects are rejected rather than
    cast.  Other types go through the constructor (so ``"4096"`` is a
    fine int), with failures re-raised as a flag-specific error.
    """
    if t is bool:
        if isinstance(value, bool):
            return value
        if isinstance(value, str):
            low = value.strip().lower()
            if low in _BOOL_TRUE:
                return True
            if low in _BOOL_FALSE:
                return False
        raise ValueError(
            f"flag {name!r} is a bool; got {value!r} (accepted: "
            f"{'/'.join(_BOOL_TRUE)} or {'/'.join(_BOOL_FALSE)})")
    # bool-as-number for int/float flags is almost always a mistake
    # (checked first: bool IS an int subclass, isinstance would pass it)
    if isinstance(value, bool):
        raise TypeError(
            f"flag {name!r} expects {t.__name__}, got bool {value!r}")
    if isinstance(value, t):
        return value
    try:
        return t(value)
    except (TypeError, ValueError) as e:
        raise TypeError(
            f"flag {name!r} expects {t.__name__}, got "
            f"{type(value).__name__} {value!r}: {e}") from None


def define_flag(name: str, default, help_: str = "", type_=None):
    t = type_ or type(default)
    _DEFS[name] = (t, default, help_)
    env = os.environ.get(name)
    if env is not None:
        FLAGS[name] = _coerce(name, env, t)
    else:
        FLAGS[name] = default
    return name


def set_flags(flags: dict):
    # validate the whole batch before mutating: a bad entry must not
    # leave a half-applied update behind
    coerced = {}
    for k, v in flags.items():
        if k not in _DEFS:
            raise ValueError(f"unknown flag {k!r}")
        coerced[k] = _coerce(k, v, _DEFS[k][0])
    FLAGS.update(coerced)


def get_flags(keys):
    if isinstance(keys, str):
        keys = [keys]
    return {k: FLAGS[k] for k in keys}


# Core flags (subset of reference paddle/common/flags.cc with same names).
define_flag("FLAGS_check_nan_inf", False, "check op outputs for nan/inf")
define_flag("FLAGS_check_nan_inf_level", 0, "0: abort on nan/inf; 3: log only")
define_flag("FLAGS_benchmark", False, "sync after every op for benchmarking")
define_flag("FLAGS_use_deterministic_algorithms", False, "determinism switch")
define_flag("FLAGS_embedding_deterministic", 0, "deterministic embedding grad")
define_flag("FLAGS_cudnn_deterministic", False, "compat alias on TPU")
define_flag("FLAGS_log_level", 0, "vlog level")
define_flag("FLAGS_strict_view_semantics", False,
            "error on in-place mutation with live views (the aliasing "
            "policy divergence becomes loud; README 'Compatibility "
            "policy')")
define_flag("FLAGS_allocator_strategy", "auto_growth", "compat; XLA BFC governs")
define_flag("FLAGS_fraction_of_gpu_memory_to_use", 0.92, "compat")
define_flag("FLAGS_tpu_matmul_precision", "default",
            "jax default_matmul_precision for MXU")
define_flag("FLAGS_metrics_dir", "",
            "directory observability.dump() writes metrics.prom/"
            "metrics.json/retraces.json into (empty: no dump)")
define_flag("FLAGS_host_trace", False,
            "enable the native host tracer at import "
            "(profiler.enable_host_tracing)")
define_flag("FLAGS_comm_timeout_seconds", 1800.0,
            "default CommTask timeout for the comm watchdog "
            "(PADDLE_COMM_TIMEOUT_SECONDS env overrides)")
define_flag("FLAGS_trace_buffer_size", 4096,
            "tracing: capacity of the per-process finished-span ring "
            "(observability.tracing.Tracer)")
define_flag("FLAGS_flight_recorder_size", 512,
            "capacity of the engine flight-recorder event ring "
            "(dumped by /debug/flight and the serving watchdog)")
define_flag("FLAGS_serving_watchdog_seconds", 0.0,
            "serving watchdog: seconds of zero decode-loop progress "
            "with active slots before a hang dump (0 disables)")
define_flag("FLAGS_serving_slo_ttft_ms", 0.0,
            "SLO target for time-to-first-token, ms (0 disables)")
define_flag("FLAGS_serving_slo_tpot_ms", 0.0,
            "SLO target for per-output-token latency, ms (0 disables)")
define_flag("FLAGS_serving_slo_e2e_ms", 0.0,
            "SLO target for request end-to-end latency, ms (0 disables)")
define_flag("FLAGS_selected_devices", "",
            "device ordinal(s) this process should use; exported into "
            "child env by distributed.launch (reference "
            "FLAGS_selected_gpus/xpus analogue)")
define_flag("FLAGS_serving_slo_objective", 0.99,
            "SLO objective (fraction of requests that must meet each "
            "target) — burn rate = violation rate / (1 - objective)")
define_flag("FLAGS_resource_peak_tflops", 0.0,
            "peak accelerator TFLOP/s for the resource tracker's MFU "
            "estimate (0: look the device kind up in the built-in "
            "table; unknown devices report mfu=null)")
define_flag("FLAGS_resource_memory_poll_steps", 16,
            "sample device memory_stats()/host RSS every N engine host "
            "syncs (a host round-trip per device; 0 disables polling)")
define_flag("FLAGS_serving_mesh_tp", 1,
            "serving tensor-parallel mesh size: shard attention heads, "
            "the FFN hidden dim, and the paged KV pool across the "
            "first N local devices (1 = single-chip; create_engine/"
            "serve --mesh overrides; CPU testing needs XLA_FLAGS="
            "--xla_force_host_platform_device_count=N)")
define_flag("FLAGS_serving_spec_k", 0,
            "speculative decoding draft length: the serving engine's "
            "prompt-lookup (n-gram) drafter proposes up to K tokens per "
            "slot and one verify step scores all K+1 positions (0 = "
            "off; greedy outputs are identical either way; "
            "create_engine/serve --spec-k overrides)")
define_flag("FLAGS_serving_fault_plan", "",
            "deterministic fault injection plan for the serving stack "
            "(chaos testing): comma-separated entries 'site@N' (inject "
            "on the Nth check of that site), 'site~P' (inject with "
            "probability P per check, seeded), plus 'seed=S'; entries "
            "take ':key=value' params (e.g. 'nan_logits@2:slot=1'). "
            "Empty = no injection and zero overhead (no plan object is "
            "built; every site guards on 'faults is not None')")
define_flag("FLAGS_serving_max_recoveries", 3,
            "EngineSupervisor restart budget: runner rebuild + in-flight "
            "re-prefill recoveries allowed per process before escalating "
            "to drain (in-flight requests then finish with "
            "finish_reason='error' and the worker stops admitting)")
define_flag("FLAGS_serving_shed_burn_rate", 0.0,
            "shed load with 429 when any SLO dimension's burn rate "
            "(violation rate / error budget, slo.py) reaches this "
            "threshold — backpressure kicks in before the queue is "
            "full (0 disables; needs SLO targets configured)")
define_flag("FLAGS_obs_timeseries_interval_s", 0.0,
            "fleet-observability sampler: seconds between time-series "
            "ticks (each tick samples the registered serving counters/"
            "gauges into bounded rings and evaluates the alert rules; "
            "0 disables — no store or sampler thread is built and the "
            "serving path pays zero overhead)")
define_flag("FLAGS_obs_timeseries_capacity", 512,
            "fleet-observability time-series ring capacity: points "
            "kept per series (older samples fall off the ring)")
define_flag("FLAGS_obs_fleet_window", 32,
            "recent time-series points each replica publishes per "
            "series in its GET /debug/fleet summary (the router and "
            "the dashboard consume these windows)")
define_flag("FLAGS_obs_profile_interval_s", 0.0,
            "continuous sampling profiler: seconds between stack "
            "sweeps (each sweep walks sys._current_frames and "
            "aggregates phase-attributed per-thread stacks; serve "
            "them via GET /debug/profile or dump() profile.json; "
            "0 disables — no profiler or sweep thread is built and "
            "the serving path pays zero overhead)")
define_flag("FLAGS_obs_capture_dir", "",
            "directory for alert-triggered diagnostic capture bundles "
            "(capture_<n>.json: profile window, flight ring, resource "
            "snapshot, lock-wait graph, series windows; empty falls "
            "back to FLAGS_metrics_dir; with neither set bundles stay "
            "in the bounded in-memory ring behind GET /debug/captures)")
define_flag("FLAGS_obs_capture_min_interval_s", 60.0,
            "per-rule rate limit for diagnostic captures: a rule that "
            "re-fires within this many seconds of its last capture is "
            "counted (obs_captures_rate_limited_total) but captures "
            "no new bundle — a flapping alert cannot fill a disk")
define_flag("FLAGS_obs_capture_max", 8,
            "diagnostic-capture retention: bundles kept on disk and "
            "in the in-memory ring; writing bundle N+1 deletes the "
            "oldest capture_<n>.json")
define_flag("FLAGS_serving_prefill_chunk", 0,
            "chunked prefill: split admission prefill into chunks of at "
            "most N prompt tokens, interleaved with decode steps so one "
            "long prompt cannot stall every decoding slot's TPOT (chunk "
            "K attends chunks 1..K-1 through the cached-prefill jit — "
            "no new traced program; 0 = whole-prompt prefill; "
            "create_engine/serve --prefill-chunk overrides)")
define_flag("FLAGS_serving_preempt", True,
            "priority preempt-and-swap: when a higher-priority request "
            "cannot be placed, evict the lowest-priority most-recently-"
            "admitted resident, spill its exclusive KV pages to host "
            "RAM, and re-queue it for a parity-preserving resume "
            "(False = strict FCFS within the priority order)")
define_flag("FLAGS_serving_shed_max_priority", 0,
            "burn-rate load shedding only rejects requests with "
            "priority <= this class (higher classes are admitted even "
            "while shedding; used with FLAGS_serving_shed_burn_rate)")
define_flag("FLAGS_serving_host_pages", 4096,
            "capacity of the BlockManager host-RAM spill tier in KV "
            "pages: preempted requests' exclusive pages park here "
            "(content-addressed, LRU) and unpark on resume without "
            "recomputing prefill (0 disables spilling to host)")
define_flag("FLAGS_serving_usage_meter", False,
            "per-request cost attribution + tenant usage metering: "
            "build a UsageMeter (observability/usage.py) that tracks "
            "queue/prefill/decode/speculation costs, KV page-seconds "
            "(device + host spill tier), and per-tenant rollups behind "
            "GET /debug/usage and serving_usage_* metrics; off (the "
            "default) builds no meter and the serving path pays only "
            "is-not-None tests")
define_flag("FLAGS_serving_usage_max_tenants", 64,
            "LRU bound on distinct tenant labels the usage meter "
            "tracks: admitting tenant N+1 folds the least-recently-"
            "seen tenant's aggregates and metric series into the "
            "(evicted) rollup, so hostile clients cycling X-Tenant "
            "values cannot explode the metrics registry")
define_flag("FLAGS_serving_request_log", False,
            "tail-latency forensics: build a RequestLog "
            "(observability/requestlog.py) that records per-request "
            "lifecycle timelines on the engine clock, folds them into "
            "critical-path attribution buckets that sum exactly to the "
            "measured E2E, and keeps worst-K SLO-violation exemplars — "
            "behind GET /debug/requests/<id>, GET /debug/exemplars, "
            "and serving_latency_attribution_seconds_total; off (the "
            "default) builds no log and the serving path pays only "
            "is-not-None tests")
define_flag("FLAGS_serving_exemplars_k", 8,
            "worst-K reservoir depth per SLO dimension "
            "(ttft/tpot/e2e/error) for the request log's exemplar "
            "store (requires FLAGS_serving_request_log)")
define_flag("FLAGS_serving_fair_share", False,
            "fair-share admission/preemption bias: when burn-rate "
            "shedding fires, only the heaviest-page-second tenant's "
            "requests are shed within the shed-eligible class, and "
            "preemption victim selection prefers that tenant's "
            "residents within the lowest priority class (requires "
            "FLAGS_serving_usage_meter; off = zero behavior change)")
define_flag("FLAGS_sanitizer", False,
            "enable the runtime concurrency sanitizer: serving/"
            "observability locks become instrumented wrappers that "
            "track held-lock stacks, detect runtime ABBA inversions "
            "and lockset-empty shared accesses (Eraser-style), and "
            "export a lock-wait graph for watchdog hang dumps; zero "
            "overhead when off (plain threading primitives)")
define_flag("FLAGS_serving_quant", "",
            "weight-only quantized serving: 'int8' or 'int4' converts "
            "the dense checkpoint at engine construction "
            "(serving/quantize.quantize_state: per-projection matmul "
            "weights become QuantizedWeight leaves, embeddings/norms/"
            "lm_head stay dense) and serves it through the "
            "weight_only_matmul decode path on any tp; empty (the "
            "default) leaves the state untouched — zero behavior "
            "change")
define_flag("FLAGS_serving_kv_quant", False,
            "int8 KV pages: the serving runner's paged KV pools store "
            "int8 with per-(page-row, head) f32 scales, quantized on "
            "write inside the jitted step and dequantized fused into "
            "the attention gather; spill/restore move the quantized "
            "bytes, roughly halving page traffic at f32 checkpoints; "
            "off (the default) keeps the dense pools byte-identical")
