"""AMP package (reference: python/paddle/amp)."""
from .auto_cast import auto_cast, amp_guard, WHITE_LIST, BLACK_LIST, amp_state
from .grad_scaler import GradScaler, AmpScaler

__all__ = ["auto_cast", "amp_guard", "GradScaler", "AmpScaler",
           "WHITE_LIST", "BLACK_LIST"]
