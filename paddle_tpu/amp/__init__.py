"""AMP package (reference: python/paddle/amp)."""
from .auto_cast import auto_cast, amp_guard, WHITE_LIST, BLACK_LIST, amp_state
from .grad_scaler import GradScaler, AmpScaler

__all__ = ["auto_cast", "amp_guard", "GradScaler", "AmpScaler",
           "WHITE_LIST", "BLACK_LIST"]


def is_bfloat16_supported(place=None):
    """TPUs compute natively in bfloat16 (reference amp/__init__.py checks
    CUDA compute capability)."""
    return True


def is_float16_supported(place=None):
    return True  # native on TPU, emulated on the CPU backend


def decorate(models, optimizers=None, level="O1", dtype="float16",
             master_weight=None, save_dtype=None, master_grad=False,
             excluded_layers=None):
    """AMP O2 decoration (reference amp/auto_cast.py decorate): cast model
    params to the low-precision dtype; optimizers already keep fp32 master
    weights (optimizer.py multi_precision)."""
    from ..framework.dtype import to_np_dtype
    import jax.numpy as jnp
    if level not in ("O1", "O2"):
        raise ValueError("level must be O1 or O2")
    single = not isinstance(models, (list, tuple))
    model_list = [models] if single else list(models)
    if level == "O2":
        from ..nn import (BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D,
                          LayerNorm, GroupNorm, InstanceNorm1D,
                          InstanceNorm2D, InstanceNorm3D, SyncBatchNorm)
        norm_types = (BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D,
                      LayerNorm, GroupNorm, InstanceNorm1D, InstanceNorm2D,
                      InstanceNorm3D, SyncBatchNorm)
        excluded = []
        for e in (excluded_layers or []):
            excluded += [e] if not isinstance(e, type) else []
        excluded_types = tuple(e for e in (excluded_layers or [])
                               if isinstance(e, type))
        np_dtype = to_np_dtype("bfloat16" if dtype == "bfloat16"
                               else "float16")
        for m in model_list:
            skip_ids = set()
            for lyr in m.sublayers(include_self=True):
                # norm layers keep fp32 params (reference decorate keeps
                # norms full precision), as do excluded layers
                if isinstance(lyr, norm_types) or lyr in excluded \
                        or (excluded_types
                            and isinstance(lyr, excluded_types)):
                    skip_ids |= {id(p) for p in lyr.parameters()}
            for p in m.parameters():
                if id(p) in skip_ids:
                    continue
                if jnp.issubdtype(p._data.dtype, jnp.floating):
                    p._data = p._data.astype(np_dtype)
    if optimizers is None:
        return models if single else model_list
    return (models if single else model_list), optimizers
