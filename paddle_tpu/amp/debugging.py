"""Numerical debugging (reference: python/paddle/amp/debugging.py —
TensorCheckerConfig:173, enable_tensor_checker:361, check_numerics,
collect_operator_stats:481).

Two mechanisms on TPU:
  * eager per-op scan — FLAGS_check_nan_inf hooks the op registry
    (ops/registry.py _maybe_check_nan_inf), like the reference's
    eager/nan_inf_utils.cc;
  * `check_numerics(x)` — explicit, works inside jit via checkify-style
    pure reporting (returns stats, raises eagerly).
"""
from __future__ import annotations

import enum

import jax
import jax.numpy as jnp
import numpy as np

from ..flags import set_flags, FLAGS
from ..framework.tensor import Tensor

__all__ = ["DebugMode", "TensorCheckerConfig", "enable_tensor_checker",
           "disable_tensor_checker", "check_numerics",
           "enable_operator_stats_collection",
           "disable_operator_stats_collection", "collect_operator_stats"]


class DebugMode(enum.Enum):
    CHECK_NAN_INF_AND_ABORT = 0
    CHECK_NAN_INF = 1
    CHECK_ALL = 3


class TensorCheckerConfig:
    def __init__(self, enable=True, debug_mode=DebugMode.CHECK_NAN_INF_AND_ABORT,
                 output_dir=None, checked_op_list=None,
                 skipped_op_list=None, debug_step=None, stack_height_limit=1):
        self.enable = enable
        self.debug_mode = debug_mode
        self.output_dir = output_dir
        self.checked_op_list = checked_op_list
        self.skipped_op_list = skipped_op_list

    def _level(self):
        return 0 if self.debug_mode == DebugMode.CHECK_NAN_INF_AND_ABORT \
            else 3


def enable_tensor_checker(checker_config: TensorCheckerConfig):
    set_flags({"FLAGS_check_nan_inf": checker_config.enable,
               "FLAGS_check_nan_inf_level": checker_config._level()})


def disable_tensor_checker():
    set_flags({"FLAGS_check_nan_inf": False})


def check_numerics(tensor, op_type="", var_name="", debug_mode=None):
    """Count nan/inf and min/max/mean; raises on nan/inf when abort mode.
    Returns (num_nan, num_inf, num_zero) like the reference."""
    a = tensor._data if isinstance(tensor, Tensor) else jnp.asarray(tensor)
    af = a.astype(jnp.float32)
    n_nan = jnp.sum(jnp.isnan(af)).astype(jnp.int64)
    n_inf = jnp.sum(jnp.isinf(af)).astype(jnp.int64)
    n_zero = jnp.sum(af == 0).astype(jnp.int64)
    if not isinstance(n_nan, jax.core.Tracer):
        bad = int(n_nan) + int(n_inf)
        abort = debug_mode is None or \
            debug_mode == DebugMode.CHECK_NAN_INF_AND_ABORT
        if bad and abort:
            raise FloatingPointError(
                f"[check_numerics] {op_type}:{var_name} has "
                f"{int(n_nan)} nan, {int(n_inf)} inf "
                f"(shape {np.shape(a)})")
    return Tensor(n_nan), Tensor(n_inf), Tensor(n_zero)


# --------------------------------------------------- operator stats
_op_stats: dict | None = None


def enable_operator_stats_collection():
    """Count per-op calls by dtype (reference debugging.py:481)."""
    global _op_stats
    _op_stats = {}
    from ..ops import registry

    if getattr(registry, "_stats_hooked", False):
        return
    registry._stats_hooked = True
    orig = registry.apply_op

    def hooked(opname, body, args, kwargs):
        out = orig(opname, body, args, kwargs)
        if _op_stats is not None:
            leaves = jax.tree_util.tree_leaves(
                out, is_leaf=lambda x: isinstance(x, Tensor))
            dt = str(leaves[0].dtype) if leaves else "?"
            _op_stats[(opname, dt)] = _op_stats.get((opname, dt), 0) + 1
        return out

    registry.apply_op = hooked
    # re-point already-registered wrappers' closure is unnecessary: all
    # wrappers call registry.apply_op dynamically? They captured apply_op
    # by module-global lookup inside wrapper body, so patching the module
    # attribute is enough.


def disable_operator_stats_collection():
    global _op_stats
    stats = _op_stats or {}
    _op_stats = None
    if stats:
        print("<------------------ op list ------------------->")
        for (name, dt), n in sorted(stats.items()):
            print(f"  {name:<30} {dt:<12} calls={n}")
    return stats


class collect_operator_stats:
    def __enter__(self):
        enable_operator_stats_collection()
        return self

    def __exit__(self, *exc):
        disable_operator_stats_collection()
