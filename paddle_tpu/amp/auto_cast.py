"""Automatic mixed precision.

Reference: python/paddle/amp/auto_cast.py + C++ AmpAutoCast inserted by eager
codegen (paddle/fluid/eager/auto_code_generator/generator/eager_gen.py:1607,
paddle/fluid/imperative/amp_auto_cast.cc).  O1 casts white-list ops (matmul,
conv) to low precision per-op; O2 casts almost everything except blacklist
(softmax/norm/exp...).  On TPU the low-precision dtype of choice is bfloat16
(MXU-native, no GradScaler strictly required since bf16 has fp32 exponent
range — GradScaler is still provided for float16 parity).
"""
from __future__ import annotations

import threading
from ..framework import dtype as dtypes

__all__ = ["auto_cast", "amp_state", "maybe_amp_cast", "amp_guard",
           "WHITE_LIST", "BLACK_LIST"]

# Ops cast *to* low precision in O1 (reference amp_lists.py white_list).
WHITE_LIST = {
    "matmul", "conv2d", "conv1d", "conv3d", "conv2d_transpose", "bmm", "mm",
    "einsum", "linear", "flash_attention", "addmm", "mv",
}
# Ops forced to float32 (reference black_list): numerically sensitive.
BLACK_LIST = {
    "exp", "square", "log", "log2", "log10", "log1p", "mean", "sum", "cos_sim",
    "softmax", "log_softmax", "softmax_with_cross_entropy",
    "cross_entropy", "cross_entropy_with_softmax", "sigmoid_cross_entropy_with_logits",
    "c_softmax_with_cross_entropy", "layer_norm", "rms_norm", "reduce_sum",
    "linear_interp_v2", "nearest_interp_v2", "bilinear_interp_v2",
}


class _AmpState(threading.local):
    def __init__(self):
        self.enabled = False
        self.level = "O0"
        self.dtype = dtypes.bfloat16
        self.custom_white = set()
        self.custom_black = set()


_state = _AmpState()


def amp_state():
    return _state


class amp_guard:
    def __init__(self, enable=True, custom_white_list=None,
                 custom_black_list=None, level="O1", dtype="bfloat16"):
        if level not in ("O0", "O1", "O2"):
            raise ValueError(f"amp level must be O0/O1/O2, got {level}")
        self._cfg = (bool(enable) and level != "O0", level,
                     dtypes.dtype(dtype),
                     set(custom_white_list or ()), set(custom_black_list or ()))

    def __enter__(self):
        self._saved = (_state.enabled, _state.level, _state.dtype,
                       _state.custom_white, _state.custom_black)
        (_state.enabled, _state.level, _state.dtype,
         _state.custom_white, _state.custom_black) = self._cfg
        return self

    def __exit__(self, *exc):
        (_state.enabled, _state.level, _state.dtype,
         _state.custom_white, _state.custom_black) = self._saved
        return False


def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16", use_promote=True):
    """paddle.amp.auto_cast-shaped context manager."""
    return amp_guard(enable, custom_white_list, custom_black_list, level, dtype)


def _cast_tree(args, kwargs, target_np):
    from ..framework.tensor import Tensor
    from jax.tree_util import tree_flatten, tree_unflatten
    import numpy as np

    flat, treedef = tree_flatten((args, kwargs),
                                 is_leaf=lambda x: isinstance(x, Tensor))
    out = []
    for x in flat:
        if isinstance(x, Tensor) and x._data.dtype in _CASTABLE \
                and x._data.dtype != target_np:
            out.append(x.astype(target_np))
        else:
            out.append(x)
    return tree_unflatten(treedef, out)


import numpy as _np
_CASTABLE = {_np.dtype("float16"), _np.dtype("bfloat16"), _np.dtype("float32")}


def maybe_amp_cast(opname, args, kwargs):
    """Per-op AMP insertion point, called by the op dispatcher."""
    if not _state.enabled or opname == "cast":
        return args, kwargs
    white = (WHITE_LIST | _state.custom_white) - _state.custom_black
    black = (BLACK_LIST | _state.custom_black) - _state.custom_white
    low = _state.dtype.np_dtype
    if _state.level == "O1":
        if opname in white:
            return _cast_tree(args, kwargs, low)
        if opname in black:
            return _cast_tree(args, kwargs, _np.dtype("float32"))
        return args, kwargs
    # O2: everything low precision except blacklist.
    if opname in black:
        return _cast_tree(args, kwargs, _np.dtype("float32"))
    return _cast_tree(args, kwargs, low)
