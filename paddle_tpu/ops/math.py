"""Elementwise & scalar math ops (reference: paddle/phi/kernels/elementwise_*,
activation kernels; python/paddle/tensor/math.py).  Bodies are pure jax;
broadcasting/type-promotion follow jnp which matches Paddle's numpy-style
semantics."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import op


def _promote_binary(x, y):
    # Paddle promotes python scalars to tensor dtype (not float64).
    if not hasattr(x, "dtype") and hasattr(y, "dtype"):
        x = jnp.asarray(x, dtype=y.dtype) if isinstance(x, (int, float, bool)) else x
    if not hasattr(y, "dtype") and hasattr(x, "dtype"):
        y = jnp.asarray(y, dtype=x.dtype) if isinstance(y, (int, float, bool)) else y
    return x, y


@op
def add(x, y, name=None):
    x, y = _promote_binary(x, y)
    return jnp.add(x, y)


@op
def subtract(x, y, name=None):
    x, y = _promote_binary(x, y)
    return jnp.subtract(x, y)


@op
def multiply(x, y, name=None):
    x, y = _promote_binary(x, y)
    return jnp.multiply(x, y)


@op
def divide(x, y, name=None):
    x, y = _promote_binary(x, y)
    if jnp.issubdtype(jnp.result_type(x), jnp.integer) and \
       jnp.issubdtype(jnp.result_type(y), jnp.integer):
        return jnp.true_divide(x, y).astype(jnp.float32)
    return jnp.true_divide(x, y)


@op
def floor_divide(x, y, name=None):
    x, y = _promote_binary(x, y)
    return jnp.floor_divide(x, y)


@op
def remainder(x, y, name=None):
    x, y = _promote_binary(x, y)
    return jnp.remainder(x, y)


mod = remainder


@op
def pow(x, y, name=None):
    x, y = _promote_binary(x, y)
    return jnp.power(x, y)


@op
def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    return jnp.matmul(x, y)


@op
def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    s = jnp.asarray(scale, dtype=x.dtype) if not hasattr(scale, "dtype") else scale.astype(x.dtype)
    b = jnp.asarray(bias, dtype=x.dtype)
    out = x * s + b if bias_after_scale else (x + b) * s
    return out


# --- unary ---
def _unary(name, fn):
    @op(name=name)
    def _f(x, name=None, _fn=fn):
        return _fn(x)
    _f.__name__ = name
    return _f


neg = _unary("neg", jnp.negative)
abs = _unary("abs", jnp.abs)
exp = _unary("exp", jnp.exp)
expm1 = _unary("expm1", jnp.expm1)
log = _unary("log", jnp.log)
log2 = _unary("log2", jnp.log2)
log10 = _unary("log10", jnp.log10)
log1p = _unary("log1p", jnp.log1p)
sqrt = _unary("sqrt", jnp.sqrt)
rsqrt = _unary("rsqrt", jax.lax.rsqrt)
square = _unary("square", jnp.square)
sin = _unary("sin", jnp.sin)
cos = _unary("cos", jnp.cos)
tan = _unary("tan", jnp.tan)
asin = _unary("asin", jnp.arcsin)
acos = _unary("acos", jnp.arccos)
atan = _unary("atan", jnp.arctan)
sinh = _unary("sinh", jnp.sinh)
cosh = _unary("cosh", jnp.cosh)
tanh = _unary("tanh", jnp.tanh)
asinh = _unary("asinh", jnp.arcsinh)
acosh = _unary("acosh", jnp.arccosh)
atanh = _unary("atanh", jnp.arctanh)
erf = _unary("erf", jax.scipy.special.erf)
erfinv = _unary("erfinv", jax.scipy.special.erfinv)
floor = _unary("floor", jnp.floor)
ceil = _unary("ceil", jnp.ceil)
round = _unary("round", jnp.round)
trunc = _unary("trunc", jnp.trunc)
sign = _unary("sign", jnp.sign)
reciprocal = _unary("reciprocal", jnp.reciprocal)
sigmoid = _unary("sigmoid", jax.nn.sigmoid)
digamma = _unary("digamma", jax.scipy.special.digamma)
lgamma = _unary("lgamma", jax.scipy.special.gammaln)
i0 = _unary("i0", lambda x: jax.scipy.special.i0(x))
frac = _unary("frac", lambda x: x - jnp.trunc(x))
deg2rad = _unary("deg2rad", jnp.deg2rad)
rad2deg = _unary("rad2deg", jnp.rad2deg)
angle = _unary("angle", jnp.angle)
conj = _unary("conj", jnp.conj)
real = _unary("real", jnp.real)
imag = _unary("imag", jnp.imag)


@op
def clip(x, min=None, max=None, name=None):
    lo = None if min is None else jnp.asarray(min, x.dtype if hasattr(x, "dtype") else None)
    hi = None if max is None else jnp.asarray(max, x.dtype if hasattr(x, "dtype") else None)
    return jnp.clip(x, lo, hi)


@op
def maximum(x, y, name=None):
    x, y = _promote_binary(x, y)
    return jnp.maximum(x, y)


@op
def minimum(x, y, name=None):
    x, y = _promote_binary(x, y)
    return jnp.minimum(x, y)


@op
def fmax(x, y, name=None):
    x, y = _promote_binary(x, y)
    return jnp.fmax(x, y)


@op
def fmin(x, y, name=None):
    x, y = _promote_binary(x, y)
    return jnp.fmin(x, y)


@op
def atan2(x, y, name=None):
    x, y = _promote_binary(x, y)
    return jnp.arctan2(x, y)


@op
def hypot(x, y, name=None):
    return jnp.hypot(x, y)


@op
def lerp(x, y, weight, name=None):
    return x + (y - x) * weight


@op
def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return scale_b * jnp.tanh(scale_a * x)


@op
def logit(x, eps=None, name=None):
    if eps is not None:
        x = jnp.clip(x, eps, 1.0 - eps)
    return jnp.log(x / (1.0 - x))


@op
def multiplex(inputs, index, name=None):
    stacked = jnp.stack(inputs, axis=0)
    idx = index.reshape(-1)
    return stacked[idx, jnp.arange(stacked.shape[1])]


@op
def isnan(x, name=None):
    return jnp.isnan(x)


@op
def isinf(x, name=None):
    return jnp.isinf(x)


@op
def isfinite(x, name=None):
    return jnp.isfinite(x)


@op
def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return jnp.nan_to_num(x, nan=nan, posinf=posinf, neginf=neginf)


@op
def cumsum(x, axis=None, dtype=None, name=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    out = jnp.cumsum(x, axis=axis)
    return out.astype(dtype) if dtype is not None else out


@op
def cumprod(x, dim=None, dtype=None, name=None):
    if dim is None:
        x = x.reshape(-1)
        dim = 0
    out = jnp.cumprod(x, axis=dim)
    return out.astype(dtype) if dtype is not None else out


@op
def cummax(x, axis=None, dtype="int64", name=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    vals = jax.lax.cummax(x, axis=axis)
    import numpy as np
    n = x.shape[axis]
    idx = jnp.arange(n).reshape([-1 if i == axis % x.ndim else 1 for i in range(x.ndim)])
    idx = jnp.broadcast_to(idx, x.shape)
    is_new = x == vals
    ind = jax.lax.cummax(jnp.where(is_new, idx, 0), axis=axis)
    return vals, ind.astype(np.dtype(dtype))


@op
def cummin(x, axis=None, dtype="int64", name=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    vals = jax.lax.cummin(x, axis=axis)
    import numpy as np
    n = x.shape[axis]
    idx = jnp.arange(n).reshape([-1 if i == axis % x.ndim else 1 for i in range(x.ndim)])
    idx = jnp.broadcast_to(idx, x.shape)
    is_new = x == vals
    ind = jax.lax.cummax(jnp.where(is_new, idx, 0), axis=axis)
    return vals, ind.astype(np.dtype(dtype))


@op
def logcumsumexp(x, axis=None, dtype=None, name=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    return jax.lax.cumlogsumexp(x, axis=axis)


@op
def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return beta * input + alpha * jnp.matmul(x, y)


@op
def inner(x, y, name=None):
    return jnp.inner(x, y)


@op
def outer(x, y, name=None):
    return jnp.outer(x, y)


@op
def heaviside(x, y, name=None):
    return jnp.heaviside(x, y)


@op
def gcd(x, y, name=None):
    x, y = _promote_binary(x, y)
    return jnp.gcd(x, y)


@op
def lcm(x, y, name=None):
    x, y = _promote_binary(x, y)
    return jnp.lcm(x, y)


@op
def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    return jnp.diff(x, n=n, axis=axis, prepend=prepend, append=append)


@op
def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return jnp.trace(x, offset=offset, axis1=axis1, axis2=axis2)


@op
def kron(x, y, name=None):
    return jnp.kron(x, y)


@op
def cross(x, y, axis=9, name=None):
    if axis == 9:
        axis = next(i for i, s in enumerate(x.shape) if s == 3)
    return jnp.cross(x, y, axis=axis)


@op
def dot(x, y, name=None):
    return jnp.sum(x * y, axis=-1)


@op
def polygamma(x, n, name=None):
    return jax.scipy.special.polygamma(n, x)
