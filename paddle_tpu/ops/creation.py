"""Creation & random ops (reference: python/paddle/tensor/creation.py,
random.py; phi kernels full/uniform/gaussian/randint/randperm).  Random ops
draw keys from the global generator (framework/random.py) so `paddle.seed`
reproduces, and stay traceable under a trace_key_guard."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import op
from ..framework.dtype import to_np_dtype
from ..framework import random as _random


def _shape(shape):
    if isinstance(shape, int):
        return (shape,)
    if hasattr(shape, "__jax_array__") or isinstance(shape, (jax.Array, np.ndarray)):
        return tuple(int(s) for s in np.asarray(shape).reshape(-1))
    return tuple(int(s) for s in shape)


@op
def zeros(shape, dtype="float32", name=None):
    return jnp.zeros(_shape(shape), to_np_dtype(dtype or "float32"))


@op
def ones(shape, dtype="float32", name=None):
    return jnp.ones(_shape(shape), to_np_dtype(dtype or "float32"))


@op
def full(shape, fill_value, dtype=None, name=None):
    if dtype is None:
        if isinstance(fill_value, bool):
            dtype = "bool"
        elif isinstance(fill_value, int):
            dtype = "int64"
        else:
            dtype = "float32"
    return jnp.full(_shape(shape), fill_value, to_np_dtype(dtype))


@op
def empty(shape, dtype="float32", name=None):
    return jnp.zeros(_shape(shape), to_np_dtype(dtype or "float32"))


@op
def zeros_like(x, dtype=None, name=None):
    return jnp.zeros_like(x, dtype=to_np_dtype(dtype) if dtype else None)


@op
def ones_like(x, dtype=None, name=None):
    return jnp.ones_like(x, dtype=to_np_dtype(dtype) if dtype else None)


@op
def full_like(x, fill_value, dtype=None, name=None):
    return jnp.full_like(x, fill_value,
                         dtype=to_np_dtype(dtype) if dtype else None)


@op
def empty_like(x, dtype=None, name=None):
    return jnp.zeros_like(x, dtype=to_np_dtype(dtype) if dtype else None)


@op
def arange(start=0, end=None, step=1, dtype=None, name=None):
    def _v(a):
        return a.item() if hasattr(a, "item") else a
    start, end, step = _v(start), _v(end), _v(step)
    if end is None:
        start, end = 0, start
    if dtype is None:
        dtype = "int64" if all(isinstance(v, int) for v in (start, end, step)) \
            else "float32"
    return jnp.arange(start, end, step, dtype=to_np_dtype(dtype))


@op
def linspace(start, stop, num, dtype=None, name=None):
    dtype = to_np_dtype(dtype or "float32")
    return jnp.linspace(float(start), float(stop), int(num), dtype=dtype)


@op
def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    dtype = to_np_dtype(dtype or "float32")
    return jnp.logspace(float(start), float(stop), int(num), base=float(base),
                        dtype=dtype)


@op
def eye(num_rows, num_columns=None, dtype="float32", name=None):
    return jnp.eye(int(num_rows),
                   int(num_columns) if num_columns is not None else None,
                   dtype=to_np_dtype(dtype or "float32"))


@op
def clone(x, name=None):
    return x + jnp.zeros((), x.dtype)  # differentiable identity copy


@op
def complex(real, imag, name=None):
    return jax.lax.complex(real, imag)


@op
def polar(abs, angle, name=None):
    return jax.lax.complex(abs * jnp.cos(angle), abs * jnp.sin(angle))


# ----------------------------------------------------------------- random
@op
def rand(shape, dtype="float32", name=None):
    return jax.random.uniform(_random.split_key(), _shape(shape),
                              to_np_dtype(dtype or "float32"))


@op
def randn(shape, dtype="float32", name=None):
    return jax.random.normal(_random.split_key(), _shape(shape),
                             to_np_dtype(dtype or "float32"))


@op
def uniform(shape, dtype="float32", min=-1.0, max=1.0, seed=0, name=None):
    key = jax.random.key(seed) if seed else _random.split_key()
    return jax.random.uniform(key, _shape(shape), to_np_dtype(dtype or "float32"),
                              minval=float(min), maxval=float(max))


@op
def normal(mean=0.0, std=1.0, shape=None, name=None):
    if hasattr(mean, "shape") and getattr(mean, "shape", ()) != ():
        shape = mean.shape
    elif hasattr(std, "shape") and getattr(std, "shape", ()) != ():
        shape = std.shape
    shape = _shape(shape) if shape is not None else ()
    z = jax.random.normal(_random.split_key(), shape, jnp.float32)
    return z * std + mean


@op
def gaussian(shape, mean=0.0, std=1.0, seed=0, dtype="float32", name=None):
    key = jax.random.key(seed) if seed else _random.split_key()
    z = jax.random.normal(key, _shape(shape), to_np_dtype(dtype))
    return z * std + mean


@op
def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    return jax.random.randint(_random.split_key(), _shape(shape), int(low),
                              int(high), to_np_dtype(dtype or "int64"))


@op
def randint_like(x, low=0, high=None, dtype=None, name=None):
    if high is None:
        low, high = 0, low
    dt = to_np_dtype(dtype) if dtype else x.dtype
    return jax.random.randint(_random.split_key(), x.shape, int(low), int(high),
                              dt)


@op
def randperm(n, dtype="int64", name=None):
    return jax.random.permutation(_random.split_key(), int(n)).astype(
        to_np_dtype(dtype or "int64"))


@op
def multinomial(x, num_samples=1, replacement=False, name=None):
    key = _random.split_key()
    if x.ndim == 1:
        return jax.random.choice(key, x.shape[0], (num_samples,),
                                 replace=replacement, p=x / x.sum()).astype(jnp.int64)
    keys = jax.random.split(key, x.shape[0])
    def row(k, p):
        return jax.random.choice(k, x.shape[1], (num_samples,),
                                 replace=replacement, p=p / p.sum())
    return jax.vmap(row)(keys, x).astype(jnp.int64)


@op
def bernoulli(x, name=None):
    return jax.random.bernoulli(_random.split_key(), x).astype(x.dtype)


@op
def poisson(x, name=None):
    return jax.random.poisson(_random.split_key(), x).astype(x.dtype)


@op
def standard_normal(shape, dtype="float32", name=None):
    return jax.random.normal(_random.split_key(), _shape(shape),
                             to_np_dtype(dtype or "float32"))


@op
def standard_gamma(x, name=None):
    return jax.random.gamma(_random.split_key(), x).astype(x.dtype)


@op
def exponential_(x, lam=1.0, name=None):
    u = jax.random.uniform(_random.split_key(), x.shape, jnp.float32)
    return (-jnp.log1p(-u) / lam).astype(x.dtype)
