"""Framework op layer: one registry over pure-jax op bodies.

Replaces the reference's YAML + 4-way codegen (API/eager/static/dist —
paddle/phi/ops/yaml, paddle/phi/api/generator/) with direct registration;
`registry.OPS` is the introspectable op inventory.
"""
from . import registry
from .registry import op, OPS

from . import math
from . import reduction
from . import manipulation
from . import creation
from . import linalg
from . import comparison
from . import indexing

__all__ = ["op", "OPS", "math", "reduction", "manipulation", "creation",
           "linalg", "comparison", "indexing"]
