"""Comparison & logical ops (reference: python/paddle/tensor/logic.py)."""
from __future__ import annotations

import jax.numpy as jnp

from .registry import op


def _co(x, y):
    if not hasattr(x, "dtype") and hasattr(y, "dtype"):
        x = jnp.asarray(x, y.dtype) if isinstance(x, (int, float, bool)) else x
    if not hasattr(y, "dtype") and hasattr(x, "dtype"):
        y = jnp.asarray(y, x.dtype) if isinstance(y, (int, float, bool)) else y
    return x, y


@op
def equal(x, y, name=None):
    x, y = _co(x, y)
    return jnp.equal(x, y)


@op
def not_equal(x, y, name=None):
    x, y = _co(x, y)
    return jnp.not_equal(x, y)


@op
def greater_than(x, y, name=None):
    x, y = _co(x, y)
    return jnp.greater(x, y)


@op
def greater_equal(x, y, name=None):
    x, y = _co(x, y)
    return jnp.greater_equal(x, y)


@op
def less_than(x, y, name=None):
    x, y = _co(x, y)
    return jnp.less(x, y)


@op
def less_equal(x, y, name=None):
    x, y = _co(x, y)
    return jnp.less_equal(x, y)


@op
def equal_all(x, y, name=None):
    return jnp.array_equal(x, y)


@op
def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return jnp.allclose(x, y, rtol=float(rtol), atol=float(atol),
                        equal_nan=equal_nan)


@op
def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return jnp.isclose(x, y, rtol=float(rtol), atol=float(atol),
                       equal_nan=equal_nan)


@op
def logical_and(x, y, out=None, name=None):
    return jnp.logical_and(x, y)


@op
def logical_or(x, y, out=None, name=None):
    return jnp.logical_or(x, y)


@op
def logical_xor(x, y, out=None, name=None):
    return jnp.logical_xor(x, y)


@op
def logical_not(x, out=None, name=None):
    return jnp.logical_not(x)


@op
def is_empty(x, name=None):
    return jnp.asarray(any(s == 0 for s in x.shape))
