"""Reduction ops (reference: paddle/phi/kernels/reduce_*; python/paddle/tensor/math.py,
search.py).  Paddle's `axis=None` reduces all dims; `keepdim` keeps rank."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import op


def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    if hasattr(axis, "astype"):  # traced/array axis must be concrete
        return tuple(np.asarray(axis).reshape(-1).astype(int).tolist())
    return int(axis)


@op(name="sum")
def sum_(x, axis=None, dtype=None, keepdim=False, name=None):
    out = jnp.sum(x, axis=_axis(axis), keepdims=keepdim)
    if dtype is not None:
        from ..framework.dtype import to_np_dtype
        out = out.astype(to_np_dtype(dtype))
    elif jnp.issubdtype(x.dtype, jnp.bool_):
        out = out.astype(jnp.int64)
    return out


@op
def mean(x, axis=None, keepdim=False, name=None):
    return jnp.mean(x, axis=_axis(axis), keepdims=keepdim)


@op(name="max")
def max_(x, axis=None, keepdim=False, name=None):
    return jnp.max(x, axis=_axis(axis), keepdims=keepdim)


@op(name="min")
def min_(x, axis=None, keepdim=False, name=None):
    return jnp.min(x, axis=_axis(axis), keepdims=keepdim)


@op
def amax(x, axis=None, keepdim=False, name=None):
    return jnp.amax(x, axis=_axis(axis), keepdims=keepdim)


@op
def amin(x, axis=None, keepdim=False, name=None):
    return jnp.amin(x, axis=_axis(axis), keepdims=keepdim)


@op
def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    out = jnp.prod(x, axis=_axis(axis), keepdims=keepdim)
    if dtype is not None:
        from ..framework.dtype import to_np_dtype
        out = out.astype(to_np_dtype(dtype))
    return out


@op(name="all")
def all_(x, axis=None, keepdim=False, name=None):
    return jnp.all(x, axis=_axis(axis), keepdims=keepdim)


@op(name="any")
def any_(x, axis=None, keepdim=False, name=None):
    return jnp.any(x, axis=_axis(axis), keepdims=keepdim)


@op
def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return jnp.var(x, axis=_axis(axis), ddof=1 if unbiased else 0,
                   keepdims=keepdim)


@op
def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return jnp.std(x, axis=_axis(axis), ddof=1 if unbiased else 0,
                   keepdims=keepdim)


@op
def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    out = jnp.nansum(x, axis=_axis(axis), keepdims=keepdim)
    if dtype is not None:
        from ..framework.dtype import to_np_dtype
        out = out.astype(to_np_dtype(dtype))
    return out


@op
def nanmean(x, axis=None, keepdim=False, name=None):
    return jnp.nanmean(x, axis=_axis(axis), keepdims=keepdim)


@op
def count_nonzero(x, axis=None, keepdim=False, name=None):
    return jnp.count_nonzero(x, axis=_axis(axis), keepdims=keepdim).astype(jnp.int64)


@op
def logsumexp(x, axis=None, keepdim=False, name=None):
    return jax.scipy.special.logsumexp(x, axis=_axis(axis), keepdims=keepdim)


@op
def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    from ..framework.dtype import to_np_dtype
    if axis is None:
        out = jnp.argmax(x.reshape(-1))
        if keepdim:
            out = out.reshape([1] * x.ndim)
    else:
        out = jnp.argmax(x, axis=int(axis), keepdims=keepdim)
    return out.astype(to_np_dtype(dtype))


@op
def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    from ..framework.dtype import to_np_dtype
    if axis is None:
        out = jnp.argmin(x.reshape(-1))
        if keepdim:
            out = out.reshape([1] * x.ndim)
    else:
        out = jnp.argmin(x, axis=int(axis), keepdims=keepdim)
    return out.astype(to_np_dtype(dtype))


@op
def median(x, axis=None, keepdim=False, mode="avg", name=None):
    if mode == "avg":
        return jnp.median(x, axis=_axis(axis), keepdims=keepdim)
    # 'min' mode: lower of the two middle values, plus indices — subset support
    return jnp.median(x, axis=_axis(axis), keepdims=keepdim)


@op
def nanmedian(x, axis=None, keepdim=False, name=None):
    return jnp.nanmedian(x, axis=_axis(axis), keepdims=keepdim)


@op
def quantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    return jnp.quantile(x, jnp.asarray(q), axis=_axis(axis), keepdims=keepdim,
                        method=interpolation)


@op
def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    vals = jnp.sort(x, axis=axis)
    idxs = jnp.argsort(x, axis=axis)
    taken = jnp.take(vals, k - 1, axis=axis)
    taken_i = jnp.take(idxs, k - 1, axis=axis).astype(jnp.int64)
    if keepdim:
        taken = jnp.expand_dims(taken, axis)
        taken_i = jnp.expand_dims(taken_i, axis)
    return taken, taken_i


@op
def mode(x, axis=-1, keepdim=False, name=None):
    sorted_x = jnp.sort(x, axis=axis)
    n = x.shape[axis]
    # run-length trick: count occurrences via equality with shifted
    def _mode_1d(v):
        vals, counts = jnp.unique(v, return_counts=True, size=v.shape[0])
        i = jnp.argmax(counts)
        val = vals[i]
        idx = jnp.max(jnp.where(v == val, jnp.arange(v.shape[0]), -1))
        return val, idx.astype(jnp.int64)
    moved = jnp.moveaxis(x, axis, -1)
    flat = moved.reshape(-1, n)
    vals, idxs = jax.vmap(_mode_1d)(flat)
    out_shape = moved.shape[:-1]
    vals = vals.reshape(out_shape)
    idxs = idxs.reshape(out_shape)
    if keepdim:
        vals = jnp.expand_dims(vals, axis)
        idxs = jnp.expand_dims(idxs, axis)
    return vals, idxs
