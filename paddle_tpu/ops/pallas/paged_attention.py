"""Paged (block-table) KV-cache attention — the serving-path kernel.

Reference analog: paddle/phi/kernels/fusion/gpu/
block_multi_head_attention_kernel.cu — decode attention over a paged KV
cache: each sequence owns a list of fixed-size pages in a shared pool,
so HBM scales with sum(seq_len) instead of batch * max_len, and ragged
batches stop paying for the longest sequence.

TPU formulation: the page gather CANNOT be one dense einsum (the dense
path's whole trick), so this is where a kernel is the only option — and
the one place the r2 decode kernel's blockwise structure pays off
(VERDICT r2 weak #7).  The block table rides Pallas scalar prefetch:
BlockSpec index maps read `table[b, i]` to pick the page each grid step
streams, i.e. the gather happens in the pipeline's block fetches.  Table
padding points at a shared DUMP page (never a real one: page-granular
prefill scatters through padded slots must not alias a sequence's real
tokens); consecutive padded steps map to the same dump block, so Mosaic
re-fetches it at most once per sequence and `pl.when` gates the math.

Layout: pool [num_pages, kvH, page_size, D] (trailing dims tile), table
[B, max_pages] int32, lens [B] = tokens visible per sequence.
Inference-only (no VJP).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .flash_attention import NUM_LANES

__all__ = ["paged_attention", "PagedPool", "select_paged_attention",
           "gather_kv_pages", "quantize_kv_rows", "gather_scale_pages",
           "gather_kv_pages_quant", "paged_attention_quant"]

_INTERPRET = False


def select_paged_attention(tp_axis: str | None = None):
    """The paged-attention callable for the active backend: the Pallas
    scalar-prefetch kernel on TPU (or under interpret mode), the
    dense-gather XLA reference on CPU.  Single chooser shared by the
    one-shot paged generate and the serving engine so both always take
    the same numeric path.

    ``tp_axis`` selects the head-parallel path for callers running
    inside a ``shard_map`` over a tensor-parallel mesh axis: the pools
    are sharded on the KV-head axis, so each device's q heads attend
    their own KV heads' pages with the full sequence visible locally —
    softmax is per-head and the page gather is head-local, so the SAME
    per-shard kernel applies with NO collective inside attention (the
    axis name is only used to validate the caller's context).  The
    wrapper additionally checks that the LOCAL head counts still divide
    (nh/tp grouped onto kvh/tp), which holds whenever tp divides both —
    the runner's ``validate_tp`` contract."""
    if jax.default_backend() not in ("cpu",) or _INTERPRET:
        base = paged_attention
    else:
        base = paged_attention_xla
    if tp_axis is None:
        return base

    def head_parallel(q, kpool, vpool, table, lens):
        nh_l, kvh_l = q.shape[1], kpool.shape[1]
        if kvh_l == 0 or nh_l % kvh_l:
            raise ValueError(
                f"head-parallel paged attention: local q heads {nh_l} "
                f"do not group onto local KV heads {kvh_l} — the tp "
                "size must divide both head counts")
        return base(q, kpool, vpool, table, lens)

    return head_parallel


def _paged_kernel(table_ref, lens_ref, q_ref, k_ref, v_ref, o_ref,
                  acc_ref, m_ref, l_ref, *, page_size, sm_scale,
                  max_pages):
    from jax.experimental import pallas as pl

    b = pl.program_id(0)
    i = pl.program_id(2)
    q = q_ref[...]                                  # [rep, D]
    rep, d = q.shape
    n_tok = lens_ref[b]                             # visible tokens
    n_pages = (n_tok + page_size - 1) // page_size

    @pl.when(i == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(i < n_pages)
    def _compute():
        k = k_ref[...]                              # [page_size, D]
        v = v_ref[...]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * jnp.float32(sm_scale)
        t_ids = i * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (rep, page_size), 1)
        s = jnp.where(t_ids < n_tok, s, -jnp.inf)
        m_prev = m_ref[:, 0]
        l_prev = l_ref[:, 0]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1))
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.exp(s - m_cur[:, None])
        l_cur = l_prev * alpha + jnp.sum(p, axis=1)
        acc_ref[...] = acc_ref[...] * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = jnp.broadcast_to(m_cur[:, None], m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_cur[:, None], l_ref.shape)

    @pl.when(i == max_pages - 1)
    def _finalize():
        l_safe = jnp.where(l_ref[:, 0] == 0.0, 1.0, l_ref[:, 0])
        o_ref[...] = (acc_ref[...] / l_safe[:, None]).astype(o_ref.dtype)


def paged_attention(q, kpool, vpool, table, lens):
    """q [B, nh, D]; pools [P, kvH, page_size, D]; table [B, max_pages]
    int32 page ids (padding = a dump page id, as PagedPool builds it —
    never a real page); lens [B] visible tokens.  Returns [B, nh, D]."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, nh, d = q.shape
    kvh, page_size = kpool.shape[1], kpool.shape[2]
    rep = nh // kvh
    max_pages = table.shape[1]
    qg = q.reshape(b, kvh, rep, d)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, kvh, max_pages),
        in_specs=[
            pl.BlockSpec((None, None, rep, d),
                         lambda b_, g, i, tbl, ln: (b_, g, 0, 0)),
            # the paged gather: scalar-prefetched table drives the fetch
            pl.BlockSpec((None, None, page_size, d),
                         lambda b_, g, i, tbl, ln: (tbl[b_, i], g, 0, 0)),
            pl.BlockSpec((None, None, page_size, d),
                         lambda b_, g, i, tbl, ln: (tbl[b_, i], g, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, None, rep, d),
                               lambda b_, g, i, tbl, ln: (b_, g, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((rep, d), jnp.float32),
            pltpu.VMEM((rep, NUM_LANES), jnp.float32),
            pltpu.VMEM((rep, NUM_LANES), jnp.float32),
        ],
    )
    with jax.enable_x64(False):   # see flash_attention._flash_fwd
        out = pl.pallas_call(
            functools.partial(_paged_kernel, page_size=page_size,
                              sm_scale=1.0 / np.sqrt(d),
                              max_pages=max_pages),
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((b, kvh, rep, d), q.dtype),
            interpret=_INTERPRET,
        )(table.astype(jnp.int32), lens.astype(jnp.int32), qg, kpool,
          vpool)
    return out.reshape(b, nh, d)


def gather_kv_pages(pool, table):
    """Materialize a block table's pages token-major: ``pool``
    [P, kvH, page_size, D], ``table`` [..., W] int32 page ids
    (dump-padded) -> [..., W * page_size, kvH, D].  The dense-gather
    building block shared by :func:`paged_attention_xla` and the serving
    engine's cached prefill (which attends suffix queries over the
    resident prefix pages it gathers here)."""
    kvh, ps, d = pool.shape[1:]
    g = pool[table]                            # [..., W, kvh, ps, d]
    g = jnp.swapaxes(g, -3, -2)                # [..., W, ps, kvh, d]
    return g.reshape(table.shape[:-1] + (table.shape[-1] * ps, kvh, d))


def paged_attention_xla(q, kpool, vpool, table, lens):
    """Dense-gather reference (identical numerics): materializes each
    sequence's pages — O(B * max_pages * page_size) HBM — used off-TPU
    and by the parity tests."""
    b, nh, d = q.shape
    kvh, ps = kpool.shape[1], kpool.shape[2]
    rep = nh // kvh
    # [B, W*ps, kvh, D] -> [B, kvh, W*ps, D]
    kb = gather_kv_pages(kpool, table).transpose(0, 2, 1, 3)
    vb = gather_kv_pages(vpool, table).transpose(0, 2, 1, 3)
    kq = jnp.repeat(kb, rep, axis=1)
    vq = jnp.repeat(vb, rep, axis=1)
    logits = jnp.einsum("bhd,bhtd->bht", q, kq,
                        preferred_element_type=jnp.float32) / np.sqrt(d)
    tpos = jnp.arange(kb.shape[2])
    valid = tpos[None, None, :] < lens[:, None, None]
    logits = jnp.where(valid, logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bht,bhtd->bhd", probs, vq)


# --------------------------------------------------- int8 KV page mode
def quantize_kv_rows(x):
    """Symmetric per-(token, head) int8 quantization of new KV rows:
    ``x`` [..., D] float -> (q int8 [..., D], scale f32 [...]).  The
    amax reduction runs on the FLOAT input (never over int8 — a
    narrow-int reduction would promote under x64 and silently clip
    without it; see the dtype_flow lint rule), the scale is floored so
    all-zero rows divide cleanly, and values round into [-127, 127].
    Runs inside the jitted decode/prefill step, so the scale update
    costs no extra host sync."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.maximum(amax / 127.0, 1e-8)
    q = jnp.clip(jnp.round(xf / scale[..., None]), -127.0, 127.0)
    return q.astype(jnp.int8), scale


def gather_scale_pages(scale, table):
    """Scale-pool mirror of :func:`gather_kv_pages`: ``scale``
    [P, kvH, page_size] f32 per-(page-row, head) scales, ``table``
    [..., W] int32 -> [..., W * page_size, kvH] token-major."""
    kvh, ps = scale.shape[1:]
    g = scale[table]                           # [..., W, kvh, ps]
    g = jnp.swapaxes(g, -2, -1)                # [..., W, ps, kvh]
    return g.reshape(table.shape[:-1] + (table.shape[-1] * ps, kvh))


def gather_kv_pages_quant(pool, scale, table, dtype=jnp.float32):
    """Dequantizing gather: int8 ``pool`` + per-row ``scale`` ->
    float token-major [..., W * page_size, kvH, D].  The dequant is
    fused into the gather (one elementwise multiply on the gathered
    block), so downstream attention sees the same layout the dense
    :func:`gather_kv_pages` produces."""
    g = gather_kv_pages(pool, table).astype(jnp.float32)
    s = gather_scale_pages(scale, table)
    return (g * s[..., None]).astype(dtype)


def paged_attention_quant(q, kpool, vpool, kscale, vscale, table, lens,
                          tp_axis=None):
    """Paged attention over int8 KV pools with per-(page-row, head) f32
    scales: the dense-gather formulation of :func:`paged_attention_xla`
    with dequantization fused into the page gather.  ``tp_axis`` marks
    a head-parallel caller inside a ``shard_map`` (pools sharded on the
    KV-head axis); like the dense chooser it only validates the local
    head grouping — attention itself needs no collective."""
    if tp_axis is not None:
        nh_l, kvh_l = q.shape[1], kpool.shape[1]
        if kvh_l == 0 or nh_l % kvh_l:
            raise ValueError(
                f"head-parallel paged attention: local q heads {nh_l} "
                f"do not group onto local KV heads {kvh_l} — the tp "
                "size must divide both head counts")
    b, nh, d = q.shape
    kvh = kpool.shape[1]
    rep = nh // kvh
    # [B, W*ps, kvh, D] -> [B, kvh, W*ps, D], dequantized at the gather
    kb = gather_kv_pages_quant(kpool, kscale, table,
                               q.dtype).transpose(0, 2, 1, 3)
    vb = gather_kv_pages_quant(vpool, vscale, table,
                               q.dtype).transpose(0, 2, 1, 3)
    kq = jnp.repeat(kb, rep, axis=1)
    vq = jnp.repeat(vb, rep, axis=1)
    logits = jnp.einsum("bhd,bhtd->bht", q, kq,
                        preferred_element_type=jnp.float32) / np.sqrt(d)
    tpos = jnp.arange(kb.shape[2])
    valid = tpos[None, None, :] < lens[:, None, None]
    logits = jnp.where(valid, logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bht,bhtd->bhd", probs, vq)


class PagedPool:
    """Host-side page allocator (reference: the block tables
    block_multi_head_attention takes as inputs).  Static shapes: each
    sequence reserves ceil((len + max_new) / page_size) pages up front;
    the shared pool holds exactly the reserved pages, so HBM scales
    with sum of lengths, not batch * max_len."""

    def __init__(self, lengths, max_new_tokens, page_size=128,
                 min_table_width=0):
        lengths = np.asarray(lengths, np.int64)
        self.page_size = int(page_size)
        need = -(-(lengths + max_new_tokens) // self.page_size)
        # one extra DUMP page absorbs writes/reads through table padding
        # (a padded prompt's page-granular prefill scatters must never
        # alias a sequence's real pages — repeating a real id would let
        # padding rows clobber real tokens); consecutive grid steps
        # mapping to the same dump id still skip the block re-fetch
        self.dump_page = int(need.sum())
        self.num_pages = self.dump_page + 1
        self.max_pages = max(int(need.max()), int(min_table_width))
        table = np.full((len(lengths), self.max_pages), self.dump_page,
                        np.int32)
        start = 0
        for i, n in enumerate(need):
            table[i, :n] = np.arange(start, start + n)
            start += n
        self.table = table
        self.reserved = need
