"""Pallas weight-only quantized matmul for the memory-bound decode path.

Reference analog: paddle/phi/kernels/funcs/weight_only_gemv.cu +
weight_only_linear_kernel.h — the fused int8/int4-weight x half-activation
GEMV that wins decode by halving (int8) or quartering (int4) weight HBM
traffic, with dequantization fused into the matmul prologue.

TPU formulation: one `pallas_call` per matmul, grid over output-column
blocks.  Each program DMAs an int8 weight tile [K, bn] from HBM into
VMEM (this is the only HBM traffic that matters at decode's M<=8 row
counts), upconverts in-register, runs the MXU dot at bf16, and applies
the per-output-channel scale to the f32 accumulator before writing the
bf16 result.  int4 weights are stored nibble-packed [K/2, N] (row 2k in
the low nibble, row 2k+1 in the high nibble — the reference packs along
K the same way); the kernel splits the activation rows even/odd and
issues two half-K dots against the unpacked nibble planes, so no
interleave materializes.

The XLA fallback (`lax.dot_general` on the int8 weight + scale on the
result) is used off-TPU and for prefill-shaped calls (large M), where
the matmul is MXU-bound and streaming tricks buy nothing.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["QuantizedWeight", "pack_int4", "unpack_int4",
           "weight_only_matmul"]

_INTERPRET = False
# decode-shaped calls (M rows at most this) take the Pallas kernel;
# larger M is compute-bound and runs the XLA dequant-into-matmul path
_GEMV_MAX_ROWS = 64


@jax.tree_util.register_pytree_node_class
class QuantizedWeight:
    """A weight-only-quantized matmul weight: int8 values (nibble-packed
    for int4) + per-output-channel f32 scale.  Registered as a pytree so
    it flows through jit/scan state like the dense weight it replaces."""

    def __init__(self, q, scale, kind="int8", k=None):
        self.q = q
        self.scale = scale
        self.kind = kind                      # "int8" | "int4"
        self.k = int(k if k is not None else q.shape[0])   # logical K

    def tree_flatten(self):
        return (self.q, self.scale), (self.kind, self.k)

    @classmethod
    def tree_unflatten(cls, aux, children):
        q, scale = children
        return cls(q, scale, kind=aux[0], k=aux[1])

    @property
    def shape(self):
        return (self.k, self.q.shape[1])

    def dequantize(self, dtype=jnp.bfloat16):
        q = unpack_int4(self.q, self.k) if self.kind == "int4" else self.q
        return (q.astype(jnp.float32) * self.scale.astype(
            jnp.float32)).astype(dtype)


def pack_int4(q):
    """[K, N] int8 values in [-8, 7] -> [K/2, N] int8, row 2k in the low
    nibble and row 2k+1 in the high nibble (reference weight_quantize's
    int4 layout packs along K)."""
    k = q.shape[0]
    if k % 2:
        raise ValueError(f"int4 packing needs even K, got {k}")
    lo = q[0::2].astype(jnp.uint8) & 0xF
    hi = (q[1::2].astype(jnp.uint8) & 0xF) << 4
    return (lo | hi).astype(jnp.int8)


def unpack_int4(packed, k=None):
    """Inverse of :func:`pack_int4` -> [K, N] int8 (sign-extended)."""
    u = packed.astype(jnp.uint8)
    lo = _sext4(u & 0xF)
    hi = _sext4(u >> 4)
    out = jnp.stack([lo, hi], axis=1).reshape(-1, packed.shape[1])
    return out if k is None else out[:k]


def _sext4(nib):
    """uint8 nibble -> sign-extended int8."""
    nib = nib.astype(jnp.int8)
    return jnp.where(nib >= 8, nib - 16, nib)


# ------------------------------------------------------------ int8 kernel
def _int8_kernel(x_ref, q_ref, s_ref, o_ref):
    w = q_ref[...].astype(jnp.bfloat16)            # int8 -> bf16 in VMEM
    acc = jax.lax.dot_general(
        x_ref[...], w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    o_ref[...] = (acc * s_ref[...]).astype(o_ref.dtype)


# ------------------------------------------------------------ int4 kernel
def _int4_kernel(xe_ref, xo_ref, p_ref, s_ref, o_ref):
    """Packed tile p [K/2, bn]: low nibble = even K rows, high = odd.

    The unpack widens the byte to i32 FIRST and does the bit ops there:
    i32 shifts/masks are native VPU lanes, while i8 shift formulations
    lower through multi-pass emulation (measured 45 us vs 8.9 us per
    2048x5632 matmul — the difference between the int4 kernel beating
    the int8 one and losing to dense bf16)."""
    w = p_ref[...].astype(jnp.int32)
    hi = (w >> 4).astype(jnp.bfloat16)            # arithmetic: already sext
    lo = (((w & 15) ^ 8) - 8).astype(jnp.bfloat16)   # sext of low nibble
    dims = (((1,), (0,)), ((), ()))
    acc = jax.lax.dot_general(xe_ref[...], lo, dims,
                              preferred_element_type=jnp.float32)
    acc += jax.lax.dot_general(xo_ref[...], hi, dims,
                               preferred_element_type=jnp.float32)
    o_ref[...] = (acc * s_ref[...]).astype(o_ref.dtype)


def _block_n(n, cap=2048):
    """Largest multiple of 128 that divides n, capped (tile VMEM)."""
    best = 0
    for m in range(128, cap + 1, 128):
        if n % m == 0:
            best = m
    return best


def _block_n_int4(n, kh):
    """int4 tile cap: the in-kernel i32 widen MATERIALIZES 4*kh*bn bytes
    of scoped VMEM (the int8 kernel's bf16 convert fuses into the dot
    and never does), so bn is budgeted to keep that under ~8 MB of the
    16 MB scoped limit."""
    cap = max(128, (8 * 2**20 // (4 * kh)) // 128 * 128)
    return _block_n(n, cap)


def _pallas_int8(x, q, scale, bn):
    from jax.experimental import pallas as pl

    m, k = x.shape
    n = q.shape[1]
    s2 = scale.reshape(1, n).astype(jnp.float32)
    with jax.enable_x64(False):
        return pl.pallas_call(
            _int8_kernel,
            grid=(n // bn,),
            in_specs=[pl.BlockSpec((m, k), lambda i: (0, 0)),
                      pl.BlockSpec((k, bn), lambda i: (0, i)),
                      pl.BlockSpec((1, bn), lambda i: (0, i))],
            out_specs=pl.BlockSpec((m, bn), lambda i: (0, i)),
            out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
            interpret=_INTERPRET,
        )(x, q, s2)


def _pallas_int4(x, packed, scale, k, bn):
    from jax.experimental import pallas as pl

    m = x.shape[0]
    n = packed.shape[1]
    xe = x[:, 0::2]                                 # [M, K/2] even rows
    xo = x[:, 1::2]
    s2 = scale.reshape(1, n).astype(jnp.float32)
    kh = k // 2
    with jax.enable_x64(False):
        return pl.pallas_call(
            _int4_kernel,
            grid=(n // bn,),
            in_specs=[pl.BlockSpec((m, kh), lambda i: (0, 0)),
                      pl.BlockSpec((m, kh), lambda i: (0, 0)),
                      pl.BlockSpec((kh, bn), lambda i: (0, i)),
                      pl.BlockSpec((1, bn), lambda i: (0, i))],
            out_specs=pl.BlockSpec((m, bn), lambda i: (0, i)),
            out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
            interpret=_INTERPRET,
        )(xe, xo, packed, s2)


def _xla_fallback(x, w: QuantizedWeight):
    if w.kind == "int4":
        q = unpack_int4(w.q, w.k)
    else:
        q = w.q
    out = jax.lax.dot_general(
        x, q, (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return (out * w.scale.astype(jnp.float32)).astype(x.dtype)


_PROBE_OK = None


def _probe():
    global _PROBE_OK
    if _PROBE_OK is None:
        from .flash_attention import run_probe

        def smoke():
            x = jnp.zeros((8, 256), jnp.bfloat16)
            q8 = jnp.zeros((256, 256), jnp.int8)
            s = jnp.zeros((256,), jnp.float32)
            jax.jit(lambda a, b, c: _pallas_int8(a, b, c, 128))(
                x, q8, s).block_until_ready()
            p4 = jnp.zeros((128, 256), jnp.int8)
            jax.jit(lambda a, b, c: _pallas_int4(a, b, c, 256, 128))(
                x, p4, s).block_until_ready()

        _PROBE_OK = run_probe(smoke)
    return _PROBE_OK


def weight_only_matmul(x, w: QuantizedWeight):
    """x [..., K] @ dequant(w) -> [..., N] — Pallas GEMV kernel at
    decode shapes on TPU, XLA dequant-matmul otherwise."""
    lead = x.shape[:-1]
    k = x.shape[-1]
    if k != w.shape[0]:
        raise ValueError(f"matmul K mismatch: x has {k}, weight "
                         f"{w.shape[0]}")
    n = w.shape[1]
    x2 = x.reshape(-1, k)
    m = x2.shape[0]
    bn = _block_n_int4(n, k // 2) if w.kind == "int4" else _block_n(n)
    use_pallas = (
        (bn > 0)
        and m <= _GEMV_MAX_ROWS
        and (w.kind == "int8" or k % 2 == 0)
        and (_INTERPRET or (jax.default_backend() not in ("cpu",)
                            and _probe())))
    if use_pallas:
        try:
            if w.kind == "int4":
                out = _pallas_int4(x2, w.q, w.scale, k, bn)
            else:
                out = _pallas_int8(x2, w.q, w.scale, bn)
            return out.reshape(*lead, n)
        except Exception:
            from .flash_attention import _warn_fallback_once
            _warn_fallback_once()
    return _xla_fallback(x2, w).reshape(*lead, n)
