"""Pallas batched gather-LoRA matmul for multi-adapter decode.

Reference analog: the grouped per-request adapter GEMVs of multi-LoRA
serving stacks (Punica's BGMV / vLLM's multi-LoRA shrink+expand) — one
base-model matmul plus a rank-r delta per request, where each request
may point at a *different* adapter:

    delta[s] = (x[s] @ A[idx[s]].T) @ B[idx[s]] * scale[idx[s]]

The adapter bank is packed ``A [N, r, K]`` / ``B [N, r, M]`` with bank
row 0 zeroed (the "no adapter" row), so mixed batches — including
slots with no adapter at all — run through ONE jitted program with the
per-slot index vector as plain data.

TPU formulation: one ``pallas_call`` gridded over slots with the index
vector as a scalar-prefetch argument; the BlockSpec index maps use
``idx_ref[s]`` to DMA exactly the two rank-r adapter tiles this slot
needs from the bank in HBM — the gather never materializes ``[S, r, K]``.
Decode row counts are tiny (S = max_slots), so the kernel is gather-
latency bound, which is precisely what the block-level DMA hides.

The XLA fallback (``take`` + two einsums) runs off-TPU and for
prefill-shaped calls, and is the reference semantics the kernel is
tested against.  Math accumulates in f32 regardless of bank dtype.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["lora_delta", "lora_gather_matmul"]

_INTERPRET = False
# decode-shaped calls (at most this many slot rows) take the Pallas
# kernel; larger row counts are prefill-shaped and MXU-bound, where the
# plain XLA gather-einsum is already optimal
_GATHER_MAX_ROWS = 64


def _xla_gather_matmul(x, a, b, scale, idx):
    """take + einsum reference path: [S, K] x banks -> [S, M]."""
    xf = x.astype(jnp.float32)
    aw = jnp.take(a, idx, axis=0).astype(jnp.float32)   # [S, r, K]
    bw = jnp.take(b, idx, axis=0).astype(jnp.float32)   # [S, r, M]
    h = jnp.einsum("sk,srk->sr", xf, aw)
    out = jnp.einsum("sr,srm->sm", h, bw)
    return (out * scale[idx].astype(jnp.float32)[:, None]).astype(x.dtype)


def _lora_kernel(idx_ref, x_ref, a_ref, b_ref, s_ref, o_ref):
    """One slot per program: both rank-r tiles arrive via the
    idx-indexed BlockSpecs, so the body is two tiny dots + a scale."""
    del idx_ref                       # consumed by the index maps
    a = a_ref[0]                                        # [r, K]
    b = b_ref[0]                                        # [r, M]
    h = jax.lax.dot_general(
        x_ref[...], a, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)             # [1, r]
    acc = jax.lax.dot_general(
        h.astype(b.dtype), b, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)             # [1, M]
    o_ref[...] = (acc * s_ref[0, 0]).astype(o_ref.dtype)


def _pallas_gather_matmul(x, a, b, scale, idx):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    s, k = x.shape
    _, r, m = b.shape
    svec = scale[idx].astype(jnp.float32).reshape(s, 1)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(s,),
        in_specs=[
            pl.BlockSpec((1, k), lambda i, idx_ref: (i, 0)),
            pl.BlockSpec((1, r, k), lambda i, idx_ref: (idx_ref[i], 0, 0)),
            pl.BlockSpec((1, r, m), lambda i, idx_ref: (idx_ref[i], 0, 0)),
            pl.BlockSpec((1, 1), lambda i, idx_ref: (i, 0)),
        ],
        out_specs=pl.BlockSpec((1, m), lambda i, idx_ref: (i, 0)),
    )
    with jax.enable_x64(False):
        return pl.pallas_call(
            _lora_kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((s, m), x.dtype),
            interpret=_INTERPRET,
        )(idx.astype(jnp.int32), x, a, b, svec)


_PROBE_OK = None


def _probe():
    global _PROBE_OK
    if _PROBE_OK is None:
        from .flash_attention import run_probe

        def smoke():
            x = jnp.zeros((4, 256), jnp.bfloat16)
            a = jnp.zeros((3, 8, 256), jnp.bfloat16)
            b = jnp.zeros((3, 8, 256), jnp.bfloat16)
            sc = jnp.zeros((3,), jnp.float32)
            idx = jnp.zeros((4,), jnp.int32)
            jax.jit(_pallas_gather_matmul)(
                x, a, b, sc, idx).block_until_ready()

        _PROBE_OK = run_probe(smoke)
    return _PROBE_OK


def lora_gather_matmul(x, a, b, scale, idx):
    """Per-row adapter delta: ``x [S, K]`` against banks ``a [N, r, K]``
    / ``b [N, r, M]`` with per-bank-row ``scale [N]`` (alpha / r) and
    per-slot bank indices ``idx [S]`` -> ``[S, M]`` in ``x.dtype``.

    Bank row 0 is the zeroed no-adapter row by convention, so a mixed
    batch (some slots dense, some adapterized) is one program."""
    if x.ndim != 2:
        raise ValueError(f"x must be [S, K], got {x.shape}")
    if a.shape[0] != b.shape[0] or a.shape[1] != b.shape[1]:
        raise ValueError(f"bank mismatch: a {a.shape} vs b {b.shape}")
    if x.shape[1] != a.shape[2]:
        raise ValueError(f"matmul K mismatch: x has {x.shape[1]}, "
                         f"bank A {a.shape[2]}")
    use_pallas = (
        x.shape[0] <= _GATHER_MAX_ROWS
        and (_INTERPRET or (jax.default_backend() not in ("cpu",)
                            and _probe())))
    if use_pallas:
        try:
            return _pallas_gather_matmul(x, a, b, scale, idx)
        except Exception:
            from .flash_attention import _warn_fallback_once
            _warn_fallback_once()
    return _xla_gather_matmul(x, a, b, scale, idx)


def lora_delta(lora, key, li, x, idx):
    """Adapter delta for projection ``key`` at layer ``li`` of a packed
    LoRA bank (``serving.lora`` layout: ``lora["a"][key] [L, N, r, K]``,
    ``lora["b"][key] [L, N, r, M]``, ``lora["scale"] [N]``).

    ``x`` is ``[..., K]``; ``idx`` is an int32 per-row bank-index vector
    aligned with ``x``'s flattened leading dims, or a scalar (whole call
    under one adapter — the per-sequence prefill shape)."""
    a = lora["a"][key][li]                              # [N, r, K]
    b = lora["b"][key][li]                              # [N, r, M]
    scale = lora["scale"]
    lead = x.shape[:-1]
    x2 = x.reshape(-1, x.shape[-1])
    idx = jnp.asarray(idx, jnp.int32)
    if idx.ndim == 0:
        # single-adapter call: one dynamic bank row, plain dense matmuls
        aw = a[idx].astype(jnp.float32)                 # [r, K]
        bw = b[idx].astype(jnp.float32)                 # [r, M]
        h = jax.lax.dot_general(
            x2.astype(jnp.float32), aw, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        out = jax.lax.dot_general(
            h, bw, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        out = (out * scale[idx].astype(jnp.float32)).astype(x.dtype)
    else:
        out = lora_gather_matmul(x2, a, b, scale, idx)
    return out.reshape(*lead, out.shape[-1])
