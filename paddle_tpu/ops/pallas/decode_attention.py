"""Pallas decode-step attention over the KV cache.

Reference analog: paddle/phi/kernels/fusion/gpu/
block_multi_head_attention_kernel.cu + masked_multihead_attention — the
serving-path kernels that attend ONE query token against the cache
without materializing head-repeated K/V or an [T] softmax round-trip.

Two implementations, measured head-to-head on a v5e chip
(B=8, T=8192, 32 q / 8 kv heads, D=128, bf16):

  * the DEFAULT path is XLA: kv-head-major [B, kvh, T, D] caches with a
    head-repeat + batched-GEMV einsum — XLA fuses mask+softmax+PV into
    the matmul pipeline at full HBM bandwidth (6.8 ms/step; the old
    [B, T, kvh, D] layout cost 9.0 ms).  At decode's one-row-per-head
    shapes this fused path is the fastest formulation on current
    hardware.
  * the Pallas kernel (enable with PALLAS_DECODE=True): grid
    (batch, kv_head, T/block_t), online softmax in f32 scratch, blocks
    past `pos` skip compute.  Numerically verified on TPU, but the
    sequential grid's per-step overhead loses to the fused XLA path at
    these shapes (85 ms measured) — it exists as the foundation for
    paged/block-table attention, where the cache gather cannot be
    expressed as one dense einsum and a kernel is the only option.

Inference-only (no VJP).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .flash_attention import NUM_LANES

__all__ = ["decode_attention"]

_INTERPRET = False
PALLAS_DECODE = False   # opt-in: see module docstring for the measured
                        # XLA-vs-kernel numbers behind this default


def _decode_kernel(q_ref, k_ref, v_ref, pos_ref, o_ref, acc_ref, m_ref,
                   l_ref, *, block_t, sm_scale, nblk):
    from jax.experimental import pallas as pl

    i = pl.program_id(2)
    q = q_ref[...]                                  # [rep, D]
    rep, d = q.shape
    pos = pos_ref[0, 0]                             # scalar int32

    @pl.when(i == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(i * block_t <= pos)     # blocks past pos skip their compute
    def _compute():
        k = k_ref[...]                              # [block_t, D]
        v = v_ref[...]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * jnp.float32(sm_scale)
        t_ids = i * block_t + jax.lax.broadcasted_iota(
            jnp.int32, (rep, block_t), 1)
        s = jnp.where(t_ids <= pos, s, -jnp.inf)
        m_prev = m_ref[:, 0]
        l_prev = l_ref[:, 0]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1))
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.exp(s - m_cur[:, None])
        l_cur = l_prev * alpha + jnp.sum(p, axis=1)
        acc_ref[...] = acc_ref[...] * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = jnp.broadcast_to(m_cur[:, None], m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_cur[:, None], l_ref.shape)

    @pl.when(i == nblk - 1)
    def _finalize():
        o_ref[...] = (acc_ref[...] / l_ref[:, 0][:, None]).astype(
            o_ref.dtype)


def _pallas_decode(q, kcache, vcache, pos, block_t):
    """q [B, nh, D]; caches [B, kvh, T, D] (kv-head-major, so the
    [block_t, D] tiles are the trailing dims Mosaic can tile);
    pos [B] -> [B, nh, D]."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, nh, d = q.shape
    kvh, t = kcache.shape[1], kcache.shape[2]
    rep = nh // kvh
    nblk = t // block_t
    qg = q.reshape(b, kvh, rep, d)
    # [B, 8, 128] so the pos block meets Mosaic's (8, 128) tiling
    pos_b = jnp.broadcast_to(
        pos.astype(jnp.int32)[:, None, None], (b, 8, NUM_LANES))

    with jax.enable_x64(False):   # see flash_attention._flash_fwd
        out = pl.pallas_call(
            functools.partial(_decode_kernel, block_t=block_t,
                              sm_scale=1.0 / np.sqrt(d), nblk=nblk),
            grid=(b, kvh, nblk),
            in_specs=[
                pl.BlockSpec((None, None, rep, d),
                             lambda b_, g, i: (b_, g, 0, 0)),
                pl.BlockSpec((None, None, block_t, d),
                             lambda b_, g, i: (b_, g, i, 0)),
                pl.BlockSpec((None, None, block_t, d),
                             lambda b_, g, i: (b_, g, i, 0)),
                pl.BlockSpec((None, 8, NUM_LANES),
                             lambda b_, g, i: (b_, 0, 0)),
            ],
            out_specs=pl.BlockSpec((None, None, rep, d),
                                   lambda b_, g, i: (b_, g, 0, 0)),
            out_shape=jax.ShapeDtypeStruct((b, kvh, rep, d), q.dtype),
            scratch_shapes=[
                pltpu.VMEM((rep, d), jnp.float32),
                pltpu.VMEM((rep, NUM_LANES), jnp.float32),
                pltpu.VMEM((rep, NUM_LANES), jnp.float32),
            ],
            interpret=_INTERPRET,
        )(qg, kcache, vcache, pos_b)
    return out.reshape(b, nh, d)


_PROBE_OK = None


def _probe():
    global _PROBE_OK
    if _PROBE_OK is None:
        from .flash_attention import run_probe

        def smoke():
            z = jnp.zeros((1, 4, 64), jnp.bfloat16)
            c = jnp.zeros((1, 2, 256, 64), jnp.bfloat16)
            p = jnp.zeros((1,), jnp.int32)
            jax.jit(lambda q, k, v, s: _pallas_decode(
                q, k, v, s, 256))(z, c, c, p).block_until_ready()

        _PROBE_OK = run_probe(smoke)
    return _PROBE_OK


def decode_attention(q, kcache, vcache, pos):
    """One-token cache attention: q [B, nh, D], caches [B, kvh, T, D]
    (kv-head-major serving layout),
    pos [B] (index of the CURRENT token; entries t <= pos attend).
    Returns [B, nh, D].  Pallas path when shapes/backend allow, XLA
    einsum fallback otherwise (identical numerics).

    Caveat: when this is traced inside an outer jit, only trace-time
    failures fall back here — a Mosaic compile error at the outer jit's
    compile would surface to the caller.  The probe compiles the real
    streamed kernel and VMEM use is O(block_t) regardless of cache
    length, which removes the known shape-dependent failure modes."""
    b, nh, d = q.shape
    kvh, t = kcache.shape[1], kcache.shape[2]
    block_t = 256 if t % 256 == 0 else (128 if t % 128 == 0 else None)
    use_pallas = (
        (PALLAS_DECODE or _INTERPRET)
        and block_t is not None
        and d in (64, 128, 256)
        and nh % kvh == 0
        and q.dtype == kcache.dtype == vcache.dtype
        and (jax.default_backend() not in ("cpu",) or _INTERPRET)
        and (_INTERPRET or _probe()))
    if use_pallas:
        try:
            return _pallas_decode(q, kcache, vcache, pos, block_t)
        except Exception:
            from .flash_attention import _warn_fallback_once
            _warn_fallback_once()   # advisor r2: silent kernel loss is
    return _xla_decode(q, kcache, vcache, pos)   # a perf-bug magnet


def _xla_decode(q, kcache, vcache, pos):
    b, nh, d = q.shape
    kvh = kcache.shape[1]
    rep = nh // kvh
    kq = jnp.repeat(kcache, rep, axis=1)            # [B, nh, T, D]
    vq = jnp.repeat(vcache, rep, axis=1)
    logits = jnp.einsum("bhd,bhtd->bht", q, kq,
                        preferred_element_type=jnp.float32) / np.sqrt(d)
    tpos = jnp.arange(kcache.shape[2])
    valid = tpos[None, None, :] <= pos[:, None, None]
    logits = jnp.where(valid, logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bht,bhtd->bhd", probs, vq)
