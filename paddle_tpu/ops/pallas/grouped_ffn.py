"""Dropless grouped expert FFN (megablocks-style) Pallas kernel.

Reference: the fused/grouped expert GEMM the reference serves MoE with
(python/paddle/incubate/nn/functional/fused_moe.py:1, CUTLASS grouped
GEMM under paddle/phi/kernels/fusion/cutlass) — no capacity factor, no
dropped tokens.

TPU formulation: tokens are counting-sorted by expert into a
TILE-ALIGNED buffer (each expert's rows padded up to the 128-row tile,
so every row tile belongs to exactly ONE expert).  One kernel computes
``silu(x_t @ w1[e]) @ w2[e]`` per row tile with the expert chosen by a
scalar-prefetched tile->expert map — both GEMMs fused, the [tile, F]
intermediate never touches HBM.  The backward kernel recomputes the
intermediate and accumulates dw1/dw2/db into expert blocks
(same-expert tiles are CONTIGUOUS in the sorted order, so the
revisit-accumulation pattern is safe on the sequential TPU grid).

Padding waste is <= E*(tile-1) rows (~6% at the bench shape) versus
the capacity formulation's 25% — and zero drops.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_INTERPRET = False
TILE = 128


def _silu_grad_parts(s):
    sig = jax.nn.sigmoid(s)
    return s * sig, sig * (1.0 + s * (1.0 - sig))


def _fwd_kernel(emap_ref, x_ref, w1_ref, b1_ref, w2_ref, b2_ref, o_ref,
                *, gated):
    x = x_ref[...]
    h = jnp.dot(x, w1_ref[...], preferred_element_type=jnp.float32)
    h = h + b1_ref[...].astype(jnp.float32)
    if gated:
        half = h.shape[-1] // 2
        h = jax.nn.silu(h[:, :half]) * h[:, half:]
    else:
        h = jax.nn.silu(h)
    out = jnp.dot(h.astype(x.dtype), w2_ref[...],
                  preferred_element_type=jnp.float32)
    o_ref[...] = (out + b2_ref[...].astype(jnp.float32)).astype(
        o_ref.dtype)


def _bwd_kernel(emap_ref, x_ref, dy_ref, w1_ref, b1_ref, w2_ref,
                dx_ref, dw1_ref, dw2_ref, db1_ref, db2_ref, *, gated):
    from jax.experimental import pallas as pl

    i = pl.program_id(0)
    x = x_ref[...]
    dyf = dy_ref[...].astype(jnp.float32)
    dy = dy_ref[...]
    s = jnp.dot(x, w1_ref[...], preferred_element_type=jnp.float32) \
        + b1_ref[...].astype(jnp.float32)
    dh = jnp.dot(dy, w2_ref[...].swapaxes(-1, -2),
                 preferred_element_type=jnp.float32)
    if gated:
        half = s.shape[-1] // 2
        u, g = s[:, :half], s[:, half:]
        su, du = _silu_grad_parts(u)
        h = su * g
        ds = jnp.concatenate([dh * g * du, dh * su], axis=-1)
    else:
        h, du = _silu_grad_parts(s)
        ds = dh * du

    dsx = ds.astype(x.dtype)
    dx_ref[...] = jnp.dot(dsx, w1_ref[...].swapaxes(-1, -2),
                          preferred_element_type=jnp.float32) \
        .astype(dx_ref.dtype)

    # expert-block accumulation: zero at each expert's first tile
    # (same-expert tiles are contiguous in the sorted order)
    @pl.when(jnp.logical_or(i == 0, emap_ref[i] != emap_ref[i - 1]))
    def _init():
        dw1_ref[...] = jnp.zeros_like(dw1_ref)
        dw2_ref[...] = jnp.zeros_like(dw2_ref)
        db1_ref[...] = jnp.zeros_like(db1_ref)
        db2_ref[...] = jnp.zeros_like(db2_ref)

    dw1_ref[...] += jnp.dot(x.swapaxes(-1, -2), dsx,
                            preferred_element_type=jnp.float32)
    dw2_ref[...] += jnp.dot(h.astype(x.dtype).swapaxes(-1, -2), dy,
                            preferred_element_type=jnp.float32)
    db1_ref[...] += jnp.sum(ds, axis=0)
    db2_ref[...] += jnp.sum(dyf, axis=0)


def _call_fwd(x_buf, w1, b1, w2, b2, emap, gated):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    r, d = x_buf.shape
    f2 = w1.shape[2]
    fin, dout = w2.shape[1], w2.shape[2]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(r // TILE,),
        in_specs=[
            pl.BlockSpec((TILE, d), lambda i, emap: (i, 0)),
            pl.BlockSpec((None, d, f2), lambda i, emap: (emap[i], 0, 0)),
            pl.BlockSpec((None, f2), lambda i, emap: (emap[i], 0)),
            pl.BlockSpec((None, fin, dout),
                         lambda i, emap: (emap[i], 0, 0)),
            pl.BlockSpec((None, dout), lambda i, emap: (emap[i], 0)),
        ],
        out_specs=pl.BlockSpec((TILE, dout), lambda i, emap: (i, 0)),
    )
    with jax.enable_x64(False):
        return pl.pallas_call(
            functools.partial(_fwd_kernel, gated=gated),
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((r, dout), x_buf.dtype),
            interpret=_INTERPRET,
        )(emap.astype(jnp.int32), x_buf, w1, b1, w2, b2)


def _call_bwd(x_buf, dy, w1, b1, w2, emap, gated):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    r, d = x_buf.shape
    e, _, f2 = w1.shape
    fin, dout = w2.shape[1], w2.shape[2]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(r // TILE,),
        in_specs=[
            pl.BlockSpec((TILE, d), lambda i, emap: (i, 0)),
            pl.BlockSpec((TILE, dout), lambda i, emap: (i, 0)),
            pl.BlockSpec((None, d, f2), lambda i, emap: (emap[i], 0, 0)),
            pl.BlockSpec((None, f2), lambda i, emap: (emap[i], 0)),
            pl.BlockSpec((None, fin, dout),
                         lambda i, emap: (emap[i], 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((TILE, d), lambda i, emap: (i, 0)),
            pl.BlockSpec((None, d, f2), lambda i, emap: (emap[i], 0, 0)),
            pl.BlockSpec((None, fin, dout),
                         lambda i, emap: (emap[i], 0, 0)),
            pl.BlockSpec((None, f2), lambda i, emap: (emap[i], 0)),
            pl.BlockSpec((None, dout), lambda i, emap: (emap[i], 0)),
        ],
    )
    f32 = jnp.float32
    with jax.enable_x64(False):
        return pl.pallas_call(
            functools.partial(_bwd_kernel, gated=gated),
            grid_spec=grid_spec,
            out_shape=[
                jax.ShapeDtypeStruct((r, d), x_buf.dtype),
                jax.ShapeDtypeStruct((e, d, f2), f32),
                jax.ShapeDtypeStruct((e, fin, dout), f32),
                jax.ShapeDtypeStruct((e, f2), f32),
                jax.ShapeDtypeStruct((e, dout), f32),
            ],
            interpret=_INTERPRET,
        )(emap.astype(jnp.int32), x_buf, dy, w1, b1, w2)


@functools.partial(jax.custom_vjp, nondiff_argnums=(6,))
def grouped_ffn(x_buf, w1, b1, w2, b2, emap, gated=False):
    """Tile-aligned grouped expert FFN: x_buf [R, D] (R % 128 == 0, the
    rows of row-tile t belong to expert emap[t]), w1 [E, D, F(*2)],
    b1 [E, F(*2)], w2 [E, F, D'], b2 [E, D'].  gated=True treats w1's
    output as [up | gate] halves (swiglu).  Returns [R, D']."""
    return _call_fwd(x_buf, w1, b1, w2, b2, emap, gated)


def _gffn_fwd(x_buf, w1, b1, w2, b2, emap, gated):
    out = _call_fwd(x_buf, w1, b1, w2, b2, emap, gated)
    # zero-width dtype carrier: residuals must be jax types
    return out, (x_buf, w1, b1, w2, jnp.zeros((0,), b2.dtype), emap)


def _gffn_bwd(gated, res, dy):
    x_buf, w1, b1, w2, b2_ref, emap = res
    dx, dw1, dw2, db1, db2 = _call_bwd(x_buf, dy, w1, b1, w2, emap,
                                       gated)
    # experts with zero tiles never ran: their accumulator blocks are
    # uninitialized memory — zero them by visited mask.  Cotangent
    # dtypes must match each PRIMAL's dtype (biases may be f32 while
    # weights are bf16).
    e = w1.shape[0]
    visited = jnp.zeros((e,), bool).at[emap].set(True)
    dw1 = jnp.where(visited[:, None, None], dw1, 0).astype(w1.dtype)
    dw2 = jnp.where(visited[:, None, None], dw2, 0).astype(w2.dtype)
    db1 = jnp.where(visited[:, None], db1, 0).astype(b1.dtype)
    db2 = jnp.where(visited[:, None], db2, 0).astype(b2_ref.dtype)
    return dx, dw1, db1, dw2, db2, None


grouped_ffn.defvjp(_gffn_fwd, _gffn_bwd)


def grouped_ffn_xla(x_buf, w1, b1, w2, b2, emap, gated=False):
    """Dense-gather XLA reference (identical numerics): materializes
    per-tile expert weights — parity tests and the off-TPU fallback."""
    r, d = x_buf.shape
    nt = r // TILE
    xt = x_buf.reshape(nt, TILE, d)
    h = jnp.einsum("tbd,tdf->tbf", xt, w1[emap],
                   preferred_element_type=jnp.float32)
    h = h + b1[emap][:, None, :].astype(jnp.float32)
    if gated:
        half = h.shape[-1] // 2
        h = jax.nn.silu(h[..., :half]) * h[..., half:]
    else:
        h = jax.nn.silu(h)
    out = jnp.einsum("tbf,tfd->tbd", h.astype(x_buf.dtype), w2[emap],
                     preferred_element_type=jnp.float32)
    out = out + b2[emap][:, None, :].astype(jnp.float32)
    return out.reshape(r, w2.shape[2]).astype(x_buf.dtype)
