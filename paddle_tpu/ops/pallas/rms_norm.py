"""Fused RMSNorm Pallas kernel.

Reference: paddle fused_rms_norm (paddle/phi/kernels/fusion/gpu, python
incubate/nn/functional/fused_rms_norm.py).  One pass over HBM: read x, write
normalized output; stats in fp32 on-chip.  Falls back to the XLA body on CPU
(XLA fuses it well there anyway).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _rms_kernel(x_ref, w_ref, o_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    y = x * jax.lax.rsqrt(var + eps)
    o_ref[...] = (y * w_ref[...].astype(jnp.float32)).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("eps",))
def _pallas_rms(x2d, w, eps):
    from jax.experimental import pallas as pl

    n, d = x2d.shape
    block = 512 if n % 512 == 0 else (256 if n % 256 == 0 else 8)
    while n % block:
        block //= 2
    block = max(block, 1)
    return pl.pallas_call(
        functools.partial(_rms_kernel, eps=eps),
        grid=(n // block,),
        in_specs=[pl.BlockSpec((block, d), lambda i: (i, 0)),
                  pl.BlockSpec((d,), lambda i: (0,))],
        out_specs=pl.BlockSpec((block, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, d), x2d.dtype),
    )(x2d, w)


def rms_norm(x, weight, eps=1e-6):
    """[..., d] fused rmsnorm; weight [d]."""
    if jax.default_backend() == "cpu" or x.shape[-1] % 128:
        xf = x.astype(jnp.float32)
        var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
        return ((xf * jax.lax.rsqrt(var + eps)).astype(x.dtype) * weight)
    shape = x.shape
    out = _pallas_rms(x.reshape(-1, shape[-1]), weight, eps)
    return out.reshape(shape)
