"""Fused RMSNorm Pallas kernel.

Reference: paddle fused_rms_norm (paddle/phi/kernels/fusion/gpu, python
incubate/nn/functional/fused_rms_norm.py).  One pass over HBM: read x, write
normalized output; stats in fp32 on-chip.  Falls back to the XLA body on CPU
(XLA fuses it well there anyway).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _rms_kernel(x_ref, w_ref, o_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    y = x * jax.lax.rsqrt(var + eps)
    o_ref[...] = (y * w_ref[...].astype(jnp.float32)).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("eps",))
def _pallas_rms(x2d, w, eps):
    from jax.experimental import pallas as pl

    n, d = x2d.shape
    block = 512 if n % 512 == 0 else (256 if n % 256 == 0 else 8)
    while n % block:
        block //= 2
    block = max(block, 1)
    with jax.enable_x64(False):   # see flash_attention._flash_fwd
        return pl.pallas_call(
            functools.partial(_rms_kernel, eps=eps),
            grid=(n // block,),
            in_specs=[pl.BlockSpec((block, d), lambda i: (i, 0)),
                      pl.BlockSpec((1, d), lambda i: (0, 0))],
            out_specs=pl.BlockSpec((block, d), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((n, d), x2d.dtype),
        )(x2d, w.reshape(1, d))


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def rms_norm(x, weight, eps=1e-6):
    """[..., d] fused rmsnorm; weight [d].  Differentiable: the forward
    runs the Pallas kernel on TPU, the backward is the closed-form
    jnp vjp (XLA fuses it into one pass)."""
    return _rms_fwd_impl(x, weight, eps)


def _rms_fwd_impl(x, weight, eps):
    if jax.default_backend() == "cpu" or x.shape[-1] % 128:
        xf = x.astype(jnp.float32)
        var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
        return ((xf * jax.lax.rsqrt(var + eps)).astype(x.dtype) * weight)
    shape = x.shape
    out = _pallas_rms(x.reshape(-1, shape[-1]), weight, eps)
    return out.reshape(shape)


def _rms_vjp_fwd(x, weight, eps):
    return _rms_fwd_impl(x, weight, eps), (x, weight)


def _rms_vjp_bwd(eps, res, g):
    x, w = res
    xf = x.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    wf = w.astype(jnp.float32)
    d = xf.shape[-1]
    r = jax.lax.rsqrt(jnp.mean(jnp.square(xf), axis=-1,
                               keepdims=True) + eps)
    gw = gf * wf                                       # [..., d]
    dx = (gw * r - xf * (jnp.sum(gw * xf, axis=-1, keepdims=True)
                         * (r ** 3) / d)).astype(x.dtype)
    dw = jnp.sum((xf * r * gf).reshape(-1, d), axis=0).astype(w.dtype)
    return dx, dw


rms_norm.defvjp(_rms_vjp_fwd, _rms_vjp_bwd)
