"""Flash attention for TPU — Pallas forward AND backward kernels.

Reference analog: paddle/phi/kernels/gpu/flash_attn_kernel.cu +
flash_attn_grad_kernel.cu (dynloaded CUDA flashattn library); layout
[batch, seqlen, num_heads, head_dim], causal flag, optional dense mask.

TPU formulation: a blockwise streaming kernel pair.
  * forward: online-softmax over K/V blocks; emits out + per-row
    log-sum-exp (lse, lane-broadcast to [B,H,S,128] per Mosaic tiling).
  * backward: flash-style recompute — a dQ kernel streaming K/V blocks
    and a dK/dV kernel streaming Q blocks, both re-deriving the softmax
    from the saved lse instead of storing [S,S] probabilities.
  * wired together with jax.custom_vjp so jax.grad never materializes
    the quadratic score matrix (the OOM the naive path hits at 2k+ seq).

The XLA fallback (`_xla_sdpa`) keeps full semantics (arbitrary masks,
dropout) and is numerically the flash reference: fp32 softmax, input
dtype matmuls.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

NUM_LANES = 128


def _ab_t(a, b):
    """a @ b.T with f32 accumulation (operands keep their dtype so bf16
    runs the MXU at full rate)."""
    return jax.lax.dot_general(a, b, (((1,), (1,)), ((), ())),
                               preferred_element_type=jnp.float32)


def _ab(a, b):
    """a @ b with f32 accumulation."""
    return jax.lax.dot_general(a, b, (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)


def _at_b(a, b):
    """a.T @ b with f32 accumulation."""
    return jax.lax.dot_general(a, b, (((0,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)


def _xla_sdpa(q, k, v, attn_mask=None, is_causal=False, dropout_p=0.0,
              training=True, key=None):
    # [B, S, H, D] -> [B, H, S, D]
    qh = jnp.swapaxes(q, 1, 2)
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    d = q.shape[-1]
    scale = 1.0 / np.sqrt(d)
    # grouped-query attention: broadcast kv heads if fewer than q heads
    if kh.shape[1] != qh.shape[1]:
        rep = qh.shape[1] // kh.shape[1]
        kh = jnp.repeat(kh, rep, axis=1)
        vh = jnp.repeat(vh, rep, axis=1)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh,
                        preferred_element_type=jnp.float32) * scale
    if is_causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        cmask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(cmask, logits, -jnp.inf)
    if attn_mask is not None:
        if attn_mask.dtype == jnp.bool_:
            logits = jnp.where(attn_mask, logits, -jnp.inf)
        else:
            logits = logits + attn_mask.astype(logits.dtype)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    if dropout_p > 0.0 and training:
        from ...framework import random as _random
        keep = jax.random.bernoulli(
            key if key is not None else _random.split_key(),
            1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p),
                          jnp.zeros((), probs.dtype))
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vh)
    return jnp.swapaxes(out, 1, 2)


_PALLAS_OK = None   # lazily probed once per process


def run_probe(fn):
    """Compile+run `fn` once in a FRESH THREAD and report success.  jax
    trace state is thread-local, so the probe stays eager (and
    catchable) even when reached while tracing a caller's jit.  Shared
    by every pallas kernel family's availability gate."""
    import threading

    box = {}

    def run():
        try:
            fn()
            box["ok"] = True
        except Exception:
            box["ok"] = False

    t = threading.Thread(target=run)
    t.start()
    t.join()
    return box.get("ok", False)   # thread died on BaseException -> no


def _probe_pallas():
    """Compile+run a tiny fwd AND grad once. The bwd kernels are traced
    outside any caller's try (when the cotangent is pulled back at
    jit-compile time), so a bwd lowering failure would otherwise crash
    training instead of falling back to the XLA path."""
    global _PALLAS_OK
    if _PALLAS_OK is None:
        def smoke():
            z = jnp.zeros((1, 256, 1, 64), jnp.bfloat16)
            # grad wrt q, k AND v so none of the three bwd kernels is
            # dead code the jaxpr DCE could skip lowering for
            jax.jit(jax.grad(
                lambda q, k, v: jnp.sum(_pallas_sdpa(q, k, v, True)
                                        .astype(jnp.float32)),
                argnums=(0, 1, 2)))(z, z, z)[0].block_until_ready()
            # the no-grad path uses the separate need_lse=False forward
            # variant; compile that too
            jax.jit(lambda q: _pallas_sdpa(q, z, z, True))(
                z).block_until_ready()

        _PALLAS_OK = run_probe(smoke)
    return _PALLAS_OK


def sdpa(q, k, v, attn_mask=None, dropout_p=0.0, is_causal=False,
         training=True, flashmask=None):
    """Paddle-layout scaled-dot-product attention: [B, S, H, D] in/out.

    Masked inputs route to the Pallas kernels where the mask is
    expressible without the [S, S] score matrix:
      * flashmask: column-interval mask_vecs [B|1, H|1, 2|4, Sk] int32
        (see ops.pallas.flash_mask) — O(S) memory;
      * a bool key-padding attn_mask [B, 1|H, 1, Sk] auto-converts to
        flashmask;
      * a floating attn_mask [B|1, H|1, Sq, Sk] becomes the dense-bias
        kernel (streamed blockwise, no softmax residuals).
    Anything else (dropout, arbitrary bool masks, odd shapes) falls back
    to the XLA path."""
    shapes_ok = (
        dropout_p == 0.0
        and q.dtype == k.dtype == v.dtype   # kernels matmul in input dtype
        and q.shape[-1] in (64, 128, 256)
        and q.shape[1] >= 256 and q.shape[1] % 256 == 0
        and k.shape[1] % 256 == 0
        and (not is_causal or q.shape[1] == k.shape[1])
        and jax.default_backend() not in ("cpu",))

    mask_vecs = flashmask
    bias = None
    if attn_mask is not None and mask_vecs is None and shapes_ok:
        am = jnp.asarray(attn_mask)
        if (am.dtype == jnp.bool_ and am.ndim == 4 and am.shape[2] == 1
                and am.shape[-1] == k.shape[1]):
            # key-padding mask (per-batch or per-head): columns allowed
            # for all rows or none
            from .flash_mask import padding_mask_to_intervals
            mask_vecs = padding_mask_to_intervals(am[:, :, 0, :],
                                                  q.shape[1])
        elif (jnp.issubdtype(am.dtype, jnp.floating) and am.ndim == 4
                and am.shape[-2:] == (q.shape[1], k.shape[1])):
            bias = am

    if shapes_ok and (attn_mask is None or mask_vecs is not None
                      or bias is not None) and _probe_pallas():
        try:
            if mask_vecs is not None:
                return _pallas_sdpa_masked(q, k, v, mask_vecs, is_causal)
            if bias is not None:
                return _pallas_sdpa_biased(q, k, v, bias, is_causal)
            return _pallas_sdpa(q, k, v, is_causal)
        except Exception:
            pass
    if attn_mask is None and flashmask is not None:
        # keep flashmask semantics on the fallback path (dense, O(S^2)).
        # Additive -1e9 (not bool -inf) keeps fully-masked rows finite;
        # zeroing them afterwards matches the kernel's convention.
        from .flash_mask import dense_mask_from_intervals
        allowed = dense_mask_from_intervals(flashmask, q.shape[1],
                                            k.shape[1])
        bias = jnp.where(allowed, 0.0, -1e9).astype(jnp.float32)
        out = _xla_sdpa(q, k, v, attn_mask=bias, is_causal=is_causal,
                        dropout_p=dropout_p, training=training)
        row_ok = jnp.any(allowed, axis=-1)            # [B|1, H|1, Sq]
        row_ok = jnp.swapaxes(row_ok, 1, 2)[..., None]  # [B,Sq,H|1,1]
        return jnp.where(row_ok, out, jnp.zeros((), out.dtype))
    return _xla_sdpa(q, k, v, attn_mask=attn_mask, is_causal=is_causal,
                     dropout_p=dropout_p, training=training)


def _pallas_sdpa(q, k, v, causal):
    """[B, S, H, D] wrapper: GQA head-repeat + layout transposes live
    outside the custom_vjp, so their VJPs (sum over repeats / transpose)
    are handled by jax."""
    qt, kt, vt = _gqa_bhsd(q, k, v)
    out = flash_mha(qt, kt, vt, causal, 1.0 / np.sqrt(q.shape[-1]))
    return jnp.swapaxes(out, 1, 2)


def _gqa_bhsd(q, k, v):
    h, hk = q.shape[2], k.shape[2]
    if hk != h:
        k = jnp.repeat(k, h // hk, axis=2)
        v = jnp.repeat(v, h // hk, axis=2)
    return (jnp.swapaxes(q, 1, 2), jnp.swapaxes(k, 1, 2),
            jnp.swapaxes(v, 1, 2))


def _pallas_sdpa_masked(q, k, v, mask_vecs, causal):
    from .flash_mask import flash_mha_masked
    h, hm = q.shape[2], mask_vecs.shape[1]
    if hm not in (1, h):                 # per-kv-head mask under GQA
        mask_vecs = jnp.repeat(mask_vecs, h // hm, axis=1)
    qt, kt, vt = _gqa_bhsd(q, k, v)
    out = flash_mha_masked(qt, kt, vt, mask_vecs, causal,
                           1.0 / np.sqrt(q.shape[-1]))
    return jnp.swapaxes(out, 1, 2)


def _pallas_sdpa_biased(q, k, v, bias, causal):
    from .flash_mask import flash_mha_biased
    h, hb = q.shape[2], bias.shape[1]
    if hb not in (1, h):
        bias = jnp.repeat(bias, h // hb, axis=1)
    qt, kt, vt = _gqa_bhsd(q, k, v)
    out = flash_mha_biased(qt, kt, vt, bias, causal,
                           1.0 / np.sqrt(q.shape[-1]))
    return jnp.swapaxes(out, 1, 2)


# ---------------------------------------------------------------- forward
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, causal, block_k,
                sm_scale):
    # lse_ref is None for the inference-only variant (no residual needed)
    from jax.experimental import pallas as pl

    q = q_ref[...]                                         # [bq, d]
    bq, d = q.shape
    kv_len = k_ref.shape[0]
    nblk = kv_len // block_k
    q_blk = pl.program_id(2)

    def body(i, carry):
        acc, m_prev, l_prev = carry
        k = k_ref[pl.dslice(i * block_k, block_k), :]
        v = v_ref[pl.dslice(i * block_k, block_k), :]
        s = _ab_t(q, k) * jnp.float32(sm_scale)
        if causal:
            q_ids = q_blk * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            k_ids = i * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_ids >= k_ids, s, -jnp.inf)
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1))
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.exp(s - m_cur[:, None])
        l_cur = l_prev * alpha + jnp.sum(p, axis=1)
        acc = acc * alpha[:, None] + _ab(p.astype(v.dtype), v)
        return acc, m_cur, l_cur

    acc0 = jnp.zeros((bq, d), jnp.float32)
    m0 = jnp.full((bq,), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    if causal:
        upper = ((q_blk + 1) * bq + block_k - 1) // block_k
    else:
        upper = nblk
    acc, m, l = jax.lax.fori_loop(0, upper, body, (acc0, m0, l0))
    o_ref[...] = (acc / l[:, None]).astype(o_ref.dtype)
    if lse_ref is not None:
        lse = m + jnp.log(l)
        lse_ref[...] = jnp.broadcast_to(lse[:, None], (bq, NUM_LANES))


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k,
               need_lse=True):
    # jax 0.9.0: Mosaic lowering infinitely recurses under jax_enable_x64
    # (the framework's global default); trace the kernel in 32-bit mode.
    with jax.enable_x64(False):
        return _flash_fwd_x32(q, k, v, causal, sm_scale, block_q, block_k,
                              need_lse)


def _flash_fwd_x32(q, k, v, causal, sm_scale, block_q, block_k, need_lse):
    from jax.experimental import pallas as pl

    b, h, sq, d = q.shape
    sk = k.shape[2]
    blk = pl.BlockSpec((None, None, block_q, d),
                       lambda b_, h_, i: (b_, h_, i, 0))
    out_specs = [blk]
    out_shape = [jax.ShapeDtypeStruct(q.shape, q.dtype)]
    if need_lse:
        out_specs.append(pl.BlockSpec((None, None, block_q, NUM_LANES),
                                      lambda b_, h_, i: (b_, h_, i, 0)))
        out_shape.append(
            jax.ShapeDtypeStruct((b, h, sq, NUM_LANES), jnp.float32))
    kernel = functools.partial(_fwd_kernel, causal=causal, block_k=block_k,
                               sm_scale=sm_scale)
    res = pl.pallas_call(
        kernel if need_lse else
        (lambda q_ref, k_ref, v_ref, o_ref: kernel(q_ref, k_ref, v_ref,
                                                   o_ref, None)),
        grid=(b, h, sq // block_q),
        in_specs=[
            blk,
            pl.BlockSpec((None, None, sk, d),
                         lambda b_, h_, i: (b_, h_, 0, 0)),
            pl.BlockSpec((None, None, sk, d),
                         lambda b_, h_, i: (b_, h_, 0, 0)),
        ],
        out_specs=out_specs if need_lse else out_specs[0],
        out_shape=out_shape if need_lse else out_shape[0],
    )(q, k, v)
    return res if need_lse else (res, None)


# --------------------------------------------------------------- backward
def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, dq_ref, *,
                   causal, block_k, sm_scale):
    from jax.experimental import pallas as pl

    q = q_ref[...]                                          # [bq, d]
    do = do_ref[...]
    lse = lse_ref[:, 0]                                     # [bq]
    delta = dl_ref[:, 0]
    bq, d = q.shape
    kv_len = k_ref.shape[0]
    nblk = kv_len // block_k
    q_blk = pl.program_id(2)

    def body(i, dq):
        k = k_ref[pl.dslice(i * block_k, block_k), :]
        v = v_ref[pl.dslice(i * block_k, block_k), :]
        s = _ab_t(q, k) * jnp.float32(sm_scale)
        if causal:
            q_ids = q_blk * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            k_ids = i * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_ids >= k_ids, s, -jnp.inf)
        p = jnp.exp(s - lse[:, None])                       # masked -> 0
        dp = _ab_t(do, v)
        ds = p * (dp - delta[:, None]) * jnp.float32(sm_scale)
        return dq + _ab(ds.astype(k.dtype), k)

    upper = ((q_blk + 1) * bq + block_k - 1) // block_k if causal else nblk
    dq = jax.lax.fori_loop(0, upper, body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[...] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, dk_ref,
                    dv_ref, *, causal, block_q, sm_scale):
    from jax.experimental import pallas as pl

    k = k_ref[...]                                          # [bk, d]
    v = v_ref[...]
    bk, d = k.shape
    q_len = q_ref.shape[0]
    nblk = q_len // block_q
    k_blk = pl.program_id(2)

    def body(i, carry):
        dk, dv = carry
        q = q_ref[pl.dslice(i * block_q, block_q), :]
        do = do_ref[pl.dslice(i * block_q, block_q), :]
        lse = lse_ref[pl.dslice(i * block_q, block_q), 0]
        delta = dl_ref[pl.dslice(i * block_q, block_q), 0]
        s = _ab_t(q, k) * jnp.float32(sm_scale)
        if causal:
            q_ids = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, bk), 0)
            k_ids = k_blk * bk + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, bk), 1)
            s = jnp.where(q_ids >= k_ids, s, -jnp.inf)
        p = jnp.exp(s - lse[:, None])
        dv = dv + _at_b(p.astype(do.dtype), do)
        dp = _ab_t(do, v)
        ds = p * (dp - delta[:, None]) * jnp.float32(sm_scale)
        dk = dk + _at_b(ds.astype(q.dtype), q)
        return dk, dv

    lower = (k_blk * bk) // block_q if causal else 0
    dk, dv = jax.lax.fori_loop(
        lower, nblk, body,
        (jnp.zeros((bk, d), jnp.float32), jnp.zeros((bk, d), jnp.float32)))
    dk_ref[...] = dk.astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)


def _flash_bwd(q, k, v, out, lse, g, causal, sm_scale, block_q, block_k):
    with jax.enable_x64(False):   # see _flash_fwd
        return _flash_bwd_x32(q, k, v, out, lse, g, causal, sm_scale,
                              block_q, block_k)


def _flash_bwd_x32(q, k, v, out, lse, g, causal, sm_scale, block_q, block_k):
    from jax.experimental import pallas as pl

    b, h, sq, d = q.shape
    # the residual is stored un-broadcast ([B,H,S]); restore kernel tiling
    lse = jnp.broadcast_to(lse[..., None], (b, h, sq, NUM_LANES))
    sk = k.shape[2]
    delta = jnp.sum(out.astype(jnp.float32) * g.astype(jnp.float32),
                    axis=-1)                                 # [B, H, Sq]
    delta = jnp.broadcast_to(delta[..., None], (b, h, sq, NUM_LANES))

    full = lambda s: pl.BlockSpec((None, None, s, d),
                                  lambda b_, h_, i: (b_, h_, 0, 0))
    full_l = pl.BlockSpec((None, None, sq, NUM_LANES),
                          lambda b_, h_, i: (b_, h_, 0, 0))
    blk_q = lambda: pl.BlockSpec((None, None, block_q, d),
                                 lambda b_, h_, i: (b_, h_, i, 0))
    blk_l = pl.BlockSpec((None, None, block_q, NUM_LANES),
                         lambda b_, h_, i: (b_, h_, i, 0))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, causal=causal, block_k=block_k,
                          sm_scale=sm_scale),
        grid=(b, h, sq // block_q),
        in_specs=[blk_q(), full(sk), full(sk), blk_q(), blk_l, blk_l],
        out_specs=blk_q(),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
    )(q, k, v, g, lse, delta)

    blk_k = lambda: pl.BlockSpec((None, None, block_k, d),
                                 lambda b_, h_, i: (b_, h_, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, causal=causal, block_q=block_q,
                          sm_scale=sm_scale),
        grid=(b, h, sk // block_k),
        in_specs=[full(sq), blk_k(), blk_k(), full(sq), full_l, full_l],
        out_specs=[blk_k(), blk_k()],
        out_shape=[jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)],
    )(q, k, v, g, lse, delta)
    return dq, dk, dv


# ------------------------------------------------------------- custom_vjp
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_mha(q, k, v, causal, sm_scale):
    """[B, H, S, D] flash attention; differentiable, O(S) memory."""
    out, _ = _flash_fwd(q, k, v, causal, sm_scale,
                        *_block_sizes(q.shape[2], k.shape[2]),
                        need_lse=False)   # no-grad path: skip the residual
    return out


def _block_sizes(sq, sk):
    bq = 512 if sq % 512 == 0 else 256
    bk = 512 if sk % 512 == 0 else 256
    return min(bq, sq), min(bk, sk)


def _flash_mha_fwd(q, k, v, causal, sm_scale):
    out, lse = _flash_fwd(q, k, v, causal, sm_scale,
                          *_block_sizes(q.shape[2], k.shape[2]))
    # the lane broadcast is a Mosaic tiling artifact; keep 1/128 of it
    # as the residual and re-broadcast in the backward wrapper
    return out, (q, k, v, out, lse[..., 0])


def _flash_mha_bwd(causal, sm_scale, res, g):
    q, k, v, out, lse = res
    dq, dk, dv = _flash_bwd(q, k, v, out, lse, g, causal, sm_scale,
                            *_block_sizes(q.shape[2], k.shape[2]))
    return dq, dk, dv


flash_mha.defvjp(_flash_mha_fwd, _flash_mha_bwd)
