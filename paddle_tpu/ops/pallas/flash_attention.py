"""Flash attention for TPU.

Reference: paddle/phi/kernels/gpu/flash_attn_kernel.cu (dynloaded CUDA
flashattn); layout [batch, seqlen, num_heads, head_dim], causal flag,
optional dense mask.  Here:

  * `sdpa(...)` — public entry, Paddle flash_attention layout/semantics.
  * On TPU with supported shapes it calls a Pallas blockwise
    (memory-streaming) kernel; otherwise an XLA path that is already
    fusion-friendly (one softmax, bf16 matmuls on the MXU).

The XLA fallback is numerically the flash reference: softmax in fp32,
matmuls in input dtype.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def _xla_sdpa(q, k, v, attn_mask=None, is_causal=False, dropout_p=0.0,
              training=True, key=None):
    # [B, S, H, D] -> [B, H, S, D]
    qh = jnp.swapaxes(q, 1, 2)
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    d = q.shape[-1]
    scale = 1.0 / np.sqrt(d)
    # grouped-query attention: broadcast kv heads if fewer than q heads
    if kh.shape[1] != qh.shape[1]:
        rep = qh.shape[1] // kh.shape[1]
        kh = jnp.repeat(kh, rep, axis=1)
        vh = jnp.repeat(vh, rep, axis=1)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh,
                        preferred_element_type=jnp.float32) * scale
    if is_causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        cmask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(cmask, logits, -jnp.inf)
    if attn_mask is not None:
        if attn_mask.dtype == jnp.bool_:
            logits = jnp.where(attn_mask, logits, -jnp.inf)
        else:
            logits = logits + attn_mask.astype(logits.dtype)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    if dropout_p > 0.0 and training:
        from ...framework import random as _random
        keep = jax.random.bernoulli(key if key is not None else _random.split_key(),
                                    1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p),
                          jnp.zeros((), probs.dtype))
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vh)
    return jnp.swapaxes(out, 1, 2)


def sdpa(q, k, v, attn_mask=None, dropout_p=0.0, is_causal=False,
         training=True):
    """Paddle-layout scaled-dot-product attention: [B, S, H, D] in/out."""
    use_pallas = (
        attn_mask is None and dropout_p == 0.0
        and q.shape[-1] in (64, 128, 256)
        and q.shape[1] >= 512 and q.shape[1] % 512 == 0
        and k.shape[1] % 512 == 0
        and (not is_causal or q.shape[1] == k.shape[1])
        and jax.default_backend() not in ("cpu",))
    if use_pallas:
        try:
            return _pallas_mha(q, k, v, is_causal)
        except Exception:
            pass
    return _xla_sdpa(q, k, v, attn_mask=attn_mask, is_causal=is_causal,
                     dropout_p=dropout_p, training=training)


# --------------------------------------------------------------------------
# Pallas blockwise attention kernel (forward); backward falls back to XLA via
# custom_vjp recomputation (flash-style: recompute probs per block).
# --------------------------------------------------------------------------

def _attn_forward_kernel(q_ref, k_ref, v_ref, o_ref, *, causal, block_k,
                         sm_scale):
    from jax.experimental import pallas as pl

    q = q_ref[...].astype(jnp.float32) * sm_scale          # [bq, d]
    bq, d = q.shape
    kv_len = k_ref.shape[0]
    nblk = kv_len // block_k

    q_blk = pl.program_id(2)

    def body(i, carry):
        acc, m_prev, l_prev = carry
        k = k_ref[pl.dslice(i * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[pl.dslice(i * block_k, block_k), :].astype(jnp.float32)
        s = q @ k.T                                         # [bq, bk]
        if causal:
            q_ids = q_blk * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            k_ids = i * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
            s = jnp.where(q_ids >= k_ids, s, -jnp.inf)
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1))
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.exp(s - m_cur[:, None])
        l_cur = l_prev * alpha + jnp.sum(p, axis=1)
        acc = acc * alpha[:, None] + p @ v
        return acc, m_cur, l_cur

    acc0 = jnp.zeros((bq, d), jnp.float32)
    m0 = jnp.full((bq,), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    if causal:
        # only iterate K blocks up to (and including) the diagonal
        upper = ((q_blk + 1) * bq + block_k - 1) // block_k
    else:
        upper = nblk
    acc, m, l = jax.lax.fori_loop(0, upper, body, (acc0, m0, l0))
    o_ref[...] = (acc / l[:, None]).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("causal",))
def _pallas_mha(q, k, v, causal):
    from jax.experimental import pallas as pl

    b, sq, h, d = q.shape
    sk = k.shape[1]
    hk = k.shape[2]
    if hk != h:
        k = jnp.repeat(k, h // hk, axis=2)
        v = jnp.repeat(v, h // hk, axis=2)
    # [B, S, H, D] -> [B, H, S, D]
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)

    block_q = min(512, sq)
    block_k = min(512, sk)
    sm_scale = 1.0 / np.sqrt(d)

    kernel = functools.partial(_attn_forward_kernel, causal=causal,
                               block_k=block_k, sm_scale=sm_scale)
    out = pl.pallas_call(
        kernel,
        grid=(b, h, sq // block_q),
        in_specs=[
            pl.BlockSpec((None, None, block_q, d), lambda b_, h_, i: (b_, h_, i, 0)),
            pl.BlockSpec((None, None, sk, d), lambda b_, h_, i: (b_, h_, 0, 0)),
            pl.BlockSpec((None, None, sk, d), lambda b_, h_, i: (b_, h_, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, None, block_q, d),
                               lambda b_, h_, i: (b_, h_, i, 0)),
        out_shape=jax.ShapeDtypeStruct(qt.shape, q.dtype),
    )(qt, kt, vt)
    return jnp.swapaxes(out, 1, 2)
