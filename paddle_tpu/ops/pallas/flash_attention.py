"""Flash attention for TPU — Pallas forward AND backward kernels.

Reference analog: paddle/phi/kernels/gpu/flash_attn_kernel.cu +
flash_attn_grad_kernel.cu (dynloaded CUDA flashattn library); layout
[batch, seqlen, num_heads, head_dim], causal flag, optional dense mask.

TPU formulation: a blockwise streaming kernel pair.
  * forward: online-softmax over K/V blocks; emits out + per-row
    log-sum-exp (lse, lane-broadcast to [B,H,S,128] per Mosaic tiling).
  * backward: flash-style recompute — a dQ kernel streaming K/V blocks
    and a dK/dV kernel streaming Q blocks, both re-deriving the softmax
    from the saved lse instead of storing [S,S] probabilities.
  * wired together with jax.custom_vjp so jax.grad never materializes
    the quadratic score matrix (the OOM the naive path hits at 2k+ seq).

Arbitrary sequence lengths: the wrapper pads Sq/Sk up to block multiples
and bakes the REAL lengths into the kernels as static constants; tail
K columns are masked in-kernel, padded Q rows produce finite garbage
that is sliced off (their cotangents are zero in backward, so they
contribute nothing to dK/dV).  Sq != Sk causal uses the reference's
bottom-right alignment (row i sees keys <= i + Sk - Sq); rows with no
visible key (Sq > Sk) emit zeros, matching the flash contract.

Grouped-query attention runs in-kernel: the K/V BlockSpec index map
sends q-head h to kv-head h // group, so K/V are never materialized at
q-head width.  dK/dV are emitted per q-head and group-summed outside.

The XLA fallback (`_xla_sdpa`) keeps full semantics (arbitrary masks,
dropout) and is numerically the flash reference: fp32 softmax, input
dtype matmuls.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

NUM_LANES = 128
# Finite stand-in for -inf so blockwise max/exp arithmetic never forms
# (-inf) - (-inf): masked logits underflow exp() to exactly 0.
MASK_VAL = -0.7 * float(np.finfo(np.float32).max)
# lse sentinel for rows with no visible key: exp(s - BIG) == 0 for any
# representable s, so backward treats the whole row as zero-probability.
LSE_INVALID = float(np.finfo(np.float32).max) * 0.5


def _ab_t(a, b):
    """a @ b.T with f32 accumulation (operands keep their dtype so bf16
    runs the MXU at full rate)."""
    return jax.lax.dot_general(a, b, (((1,), (1,)), ((), ())),
                               preferred_element_type=jnp.float32)


def _ab(a, b):
    """a @ b with f32 accumulation."""
    return jax.lax.dot_general(a, b, (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)


def _at_b(a, b):
    """a.T @ b with f32 accumulation."""
    return jax.lax.dot_general(a, b, (((0,), (0,)), ((), ())),
                               preferred_element_type=jnp.float32)


def _xla_sdpa(q, k, v, attn_mask=None, is_causal=False, dropout_p=0.0,
              training=True, key=None):
    # [B, S, H, D] -> [B, H, S, D]
    qh = jnp.swapaxes(q, 1, 2)
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    d = q.shape[-1]
    scale = 1.0 / np.sqrt(d)
    # grouped-query attention: broadcast kv heads if fewer than q heads
    if kh.shape[1] != qh.shape[1]:
        rep = qh.shape[1] // kh.shape[1]
        kh = jnp.repeat(kh, rep, axis=1)
        vh = jnp.repeat(vh, rep, axis=1)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh,
                        preferred_element_type=jnp.float32) * scale
    masked = None
    if is_causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        cmask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(cmask, logits, -jnp.inf)
        masked = jnp.broadcast_to(cmask, logits.shape)
    if attn_mask is not None:
        if attn_mask.dtype == jnp.bool_:
            logits = jnp.where(attn_mask, logits, -jnp.inf)
            am = jnp.broadcast_to(attn_mask, logits.shape)
            masked = am if masked is None else masked & am
        else:
            logits = logits + attn_mask.astype(logits.dtype)
    if masked is not None:
        # rows with no visible key softmax over all -inf -> NaN in BOTH
        # directions (the softmax VJP turns NaN*0 cotangents into NaN);
        # rewrite those rows to finite logits first, then zero the probs,
        # so forward AND backward match the flash kernels' zero-row
        # convention
        row_ok = jnp.any(masked, axis=-1, keepdims=True)
        logits = jnp.where(row_ok, logits, jnp.zeros((), logits.dtype))
        probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
        probs = jnp.where(row_ok, probs, jnp.zeros((), probs.dtype))
    else:
        probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    if dropout_p > 0.0 and training:
        from ...framework import random as _random
        keep = jax.random.bernoulli(
            key if key is not None else _random.split_key(),
            1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p),
                          jnp.zeros((), probs.dtype))
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vh)
    return jnp.swapaxes(out, 1, 2)


_PALLAS_OK = None   # lazily probed once per process
_INTERPRET = False  # tests: run the kernels anywhere via interpret mode


def run_probe(fn):
    """Compile+run `fn` once in a FRESH THREAD and report success.  jax
    trace state is thread-local, so the probe stays eager (and
    catchable) even when reached while tracing a caller's jit.  Shared
    by every pallas kernel family's availability gate."""
    import threading

    box = {}

    def run():
        try:
            fn()
            box["ok"] = True
        except Exception:
            box["ok"] = False

    t = threading.Thread(target=run)
    t.start()
    t.join()
    return box.get("ok", False)   # thread died on BaseException -> no


def _probe_pallas():
    """Compile+run a tiny fwd AND grad once. The bwd kernels are traced
    outside any caller's try (when the cotangent is pulled back at
    jit-compile time), so a bwd lowering failure would otherwise crash
    training instead of falling back to the XLA path."""
    global _PALLAS_OK
    if _PALLAS_OK is None:
        def smoke():
            # ragged seq (tail-masked) + GQA (2 q heads per kv head) +
            # causal: exercises every generalized code path
            q = jnp.zeros((1, 320, 2, 64), jnp.bfloat16)
            z = jnp.zeros((1, 320, 1, 64), jnp.bfloat16)
            # grad wrt q, k AND v so none of the three bwd kernels is
            # dead code the jaxpr DCE could skip lowering for
            jax.jit(jax.grad(
                lambda q, k, v: jnp.sum(_pallas_sdpa(q, k, v, True)
                                        .astype(jnp.float32)),
                argnums=(0, 1, 2)))(q, z, z)[0].block_until_ready()
            # the no-grad path uses the separate need_lse=False forward
            # variant; compile that too
            jax.jit(lambda q: _pallas_sdpa(q, z, z, True))(
                q).block_until_ready()

        _PALLAS_OK = run_probe(smoke)
    return _PALLAS_OK


_MASKED_STREAM_OK: dict = {}


def _probe_masked_stream(hd=64, nvec=2):
    """Compile+run the STREAMED masked/biased kernels (fwd and grad)
    once per (head_dim, mask-vec arity) at the PRODUCTION block
    configuration, so the long-seq masked dispatch can trust them
    (their Mosaic compile happens at the caller's jit compile, where
    failure is uncatchable).

    Probe shapes derive from the call site (r4 advisor: a S=256/nvec=2
    smoke test left S>4k nvec=4 hd=128 failures to surface at the
    caller): S=512 selects the same 512-wide blocks _block_sizes picks
    for every long padded sequence, and hd/nvec come in from the
    dispatch."""
    key = (int(hd), int(nvec))
    if key not in _MASKED_STREAM_OK:
        from . import flash_mask as FM

        def smoke():
            global _FORCE_STREAM
            saved = _FORCE_STREAM
            _FORCE_STREAM = True
            try:
                s = 512          # -> 512-blocks, the long-seq config
                q = jnp.zeros((1, s, 2, hd), jnp.bfloat16)
                kv = jnp.zeros((1, s, 1, hd), jnp.bfloat16)
                vec = jnp.zeros((1, 1, nvec, s), jnp.int32)
                bias = jnp.zeros((1, 1, s, s), jnp.float32)
                sc = 0.125

                def loss_m(q, k, v):
                    return jnp.sum(FM.flash_mha_masked(
                        jnp.swapaxes(q, 1, 2), jnp.swapaxes(k, 1, 2),
                        jnp.swapaxes(v, 1, 2), vec, True, sc)
                        .astype(jnp.float32))

                jax.jit(jax.grad(loss_m, argnums=(0, 1, 2)))(
                    q, kv, kv)[0].block_until_ready()

                def loss_b(q, k, v, bias):
                    return jnp.sum(FM.flash_mha_biased(
                        jnp.swapaxes(q, 1, 2), jnp.swapaxes(k, 1, 2),
                        jnp.swapaxes(v, 1, 2), bias, False, sc)
                        .astype(jnp.float32))

                jax.jit(jax.grad(loss_b, argnums=(0, 1, 2, 3)))(
                    q, kv, kv, bias)[0].block_until_ready()
            finally:
                _FORCE_STREAM = saved

        _MASKED_STREAM_OK[key] = run_probe(smoke)
    return _MASKED_STREAM_OK[key]


def _pad_len(s, mult=128):
    """Pad to a lane-tileable length: 128-multiples suffice for Mosaic
    (block sizes need not be powers of two — seq 384 runs unpadded with
    384-wide blocks instead of paying 33% padding to reach 512)."""
    return max(mult, -(-s // mult) * mult)


def _pad_seq(x, target):
    s = x.shape[1]
    if s == target:
        return x
    return jnp.pad(x, ((0, 0), (0, target - s), (0, 0), (0, 0)))


# below this max-seq, plain unmasked sdpa routes to XLA's fused
# attention instead of the flash kernel.  Default OFF: the isolated
# S=512 microbench favors XLA 2.4x, but the end-to-end MoE-step A/B
# (same session, route toggled) measured the XLA path 13 ms SLOWER in
# the full scanned program — only an in-context A/B decides this knob.
_SHORT_SEQ_XLA = 0


def sdpa(q, k, v, attn_mask=None, dropout_p=0.0, is_causal=False,
         training=True, flashmask=None):
    """Paddle-layout scaled-dot-product attention: [B, S, H, D] in/out.

    Masked inputs route to the Pallas kernels where the mask is
    expressible without the [S, S] score matrix:
      * flashmask: column-interval mask_vecs [B|1, H|1, 2|4, Sk] int32
        (see ops.pallas.flash_mask) — O(S) memory;
      * a bool key-padding attn_mask [B, 1|H, 1, Sk] auto-converts to
        flashmask;
      * a floating attn_mask [B|1, H|1, Sq, Sk] becomes the dense-bias
        kernel (streamed blockwise, no softmax residuals).
    Sequence lengths are arbitrary (>= 128): inputs are padded to block
    multiples and the tails masked in-kernel.  Anything else (dropout,
    arbitrary bool masks, tiny shapes) falls back to the XLA path."""
    shapes_ok = (
        dropout_p == 0.0
        and q.dtype == k.dtype == v.dtype   # kernels matmul in input dtype
        and q.shape[-1] in (64, 128, 256)
        and q.shape[1] >= 128 and k.shape[1] >= 128
        and jax.default_backend() not in ("cpu",))

    mask_vecs = flashmask
    bias = None
    if attn_mask is not None and mask_vecs is None and shapes_ok:
        am = jnp.asarray(attn_mask)
        if (am.dtype == jnp.bool_ and am.ndim == 4 and am.shape[2] == 1
                and am.shape[-1] == k.shape[1]):
            # key-padding mask (per-batch or per-head): columns allowed
            # for all rows or none
            from .flash_mask import padding_mask_to_intervals
            mask_vecs = padding_mask_to_intervals(am[:, :, 0, :],
                                                  q.shape[1])
        elif (jnp.issubdtype(am.dtype, jnp.floating) and am.ndim == 4
                and am.shape[-2:] == (q.shape[1], k.shape[1])):
            bias = am

    # short-sequence route: below ~1024 the flash grid is too small to
    # pipeline and XLA's fused attention wins (measured on v5e, hd=128:
    # S=512 f+b 0.87 ms vs 2.14 ms pallas; pallas wins 2-5x from 1024 up)
    if (shapes_ok and attn_mask is None and mask_vecs is None
            and max(q.shape[1], k.shape[1]) < _SHORT_SEQ_XLA
            and q.shape[2] % k.shape[2] == 0):
        try:
            return jax.nn.dot_product_attention(q, k, v,
                                                is_causal=is_causal)
        except Exception:
            pass

    long_seq = max(q.shape[1], k.shape[1]) > _STREAM_SEQ
    if shapes_ok and (attn_mask is None or mask_vecs is not None
                      or bias is not None) and _probe_pallas():
        masked = mask_vecs is not None or bias is not None
        # past _STREAM_SEQ the masked kernels switch to their streamed
        # variants (inner-grid K/V iteration, VMEM independent of S);
        # gate them behind their own compile probe so a Mosaic failure
        # at the CALLER's jit-compile can't crash training
        stream_ok = (not (masked and long_seq)) or _probe_masked_stream(
            hd=q.shape[-1],
            nvec=(mask_vecs.shape[2] if mask_vecs is not None else 2))
        if stream_ok:
            try:
                if mask_vecs is not None:
                    return _pallas_sdpa_masked(q, k, v, mask_vecs,
                                               is_causal)
                if bias is not None:
                    return _pallas_sdpa_biased(q, k, v, bias, is_causal)
                return _pallas_sdpa(q, k, v, is_causal)
            except Exception:
                _warn_fallback_once()
    if shapes_ok and long_seq and (mask_vecs is not None
                                   or bias is not None):
        # masked long-seq with the kernels unavailable: the chunked-XLA
        # online-softmax path keeps O(S) forward memory at any length
        return _xla_sdpa_streamed(q, k, v, is_causal, bias=bias,
                                  mask_vecs=mask_vecs)
    if attn_mask is None and flashmask is not None:
        # keep flashmask semantics on the fallback path (dense, O(S^2)).
        # Additive -1e9 (not bool -inf) keeps fully-masked rows finite;
        # zeroing them afterwards matches the kernel's convention.
        from .flash_mask import dense_mask_from_intervals
        allowed = dense_mask_from_intervals(flashmask, q.shape[1],
                                            k.shape[1])
        bias = jnp.where(allowed, 0.0, -1e9).astype(jnp.float32)
        out = _xla_sdpa(q, k, v, attn_mask=bias, is_causal=is_causal,
                        dropout_p=dropout_p, training=training)
        row_ok = jnp.any(allowed, axis=-1)            # [B|1, H|1, Sq]
        row_ok = jnp.swapaxes(row_ok, 1, 2)[..., None]  # [B,Sq,H|1,1]
        return jnp.where(row_ok, out, jnp.zeros((), out.dtype))
    return _xla_sdpa(q, k, v, attn_mask=attn_mask, is_causal=is_causal,
                     dropout_p=dropout_p, training=training)


def _xla_sdpa_streamed(q, k, v, is_causal, bias=None, mask_vecs=None,
                       chunk=512):
    """O(S)-memory masked attention in plain XLA: lax.scan over key
    chunks with the online-softmax recurrence.  The long-sequence
    masked fallback when the streamed Pallas kernels are unavailable.
    Supports float bias [B|1, H|1, Sq, Sk] and flashmask interval vecs
    [B|1, H|1, 2|4, Sk]; per-chunk slices keep every transient at
    [B, H, Sq, chunk].  The step is jax.checkpoint-ed: without it the
    scan saves per-chunk s/p residuals for backward — O(Sq*Sk) total,
    the very blowup this path exists to avoid (advisor r3)."""
    qh = jnp.swapaxes(q, 1, 2).astype(jnp.float32)   # [B, H, Sq, D]
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    b, hq, sq, d = qh.shape
    hk = kh.shape[1]
    if hq != hk:                                      # GQA
        rep = hq // hk
        kh = jnp.repeat(kh, rep, axis=1)
        vh = jnp.repeat(vh, rep, axis=1)
    sk = kh.shape[2]
    scale = 1.0 / np.sqrt(d)
    pad = (-sk) % chunk
    if pad:
        kh = jnp.pad(kh, ((0, 0), (0, 0), (0, pad), (0, 0)))
        vh = jnp.pad(vh, ((0, 0), (0, 0), (0, pad), (0, 0)))
        if bias is not None:
            bias = jnp.pad(bias, ((0, 0), (0, 0), (0, 0), (0, pad)))
        if mask_vecs is not None:
            mask_vecs = jnp.pad(mask_vecs,
                                ((0, 0), (0, 0), (0, 0), (0, pad)))
    nc = kh.shape[2] // chunk
    ko = sk - sq
    q_ids = jnp.arange(sq)[:, None]                  # [Sq, 1]

    def step(carry, c):
        m_prev, l_prev, acc = carry
        c0 = c * chunk
        kc = jax.lax.dynamic_slice_in_dim(kh, c0, chunk, axis=2)
        vc = jax.lax.dynamic_slice_in_dim(vh, c0, chunk, axis=2)
        s = jnp.einsum("bhqd,bhkd->bhqk", qh,
                       kc.astype(jnp.float32)) * scale
        k_ids = c0 + jnp.arange(chunk)[None, :]      # [1, chunk]
        ok = k_ids < sk                               # padded tail
        if is_causal:
            ok = ok & (k_ids <= q_ids + ko)
        if bias is not None:
            s = s + jax.lax.dynamic_slice_in_dim(
                bias, c0, chunk, axis=3).astype(jnp.float32)
        if mask_vecs is not None:
            from .flash_mask import dense_mask_from_intervals
            vec_c = jax.lax.dynamic_slice_in_dim(mask_vecs, c0, chunk,
                                                 axis=3)
            # interval semantics are per-COLUMN (row bounds in the vec
            # entries), so column slicing composes exactly
            allowed = dense_mask_from_intervals(vec_c, sq, chunk)
            s = jnp.where(allowed, s, MASK_VAL)
        s = jnp.where(ok[None, None], s, MASK_VAL)
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.exp(s - m_cur[..., None])
        l_cur = l_prev * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, vc.astype(jnp.float32))
        # pin carry dtypes: the framework's global x64 mode promotes
        # somewhere in the reductions
        return (m_cur.astype(jnp.float32), l_cur.astype(jnp.float32),
                acc.astype(jnp.float32)), None

    m0 = jnp.full((b, hq, sq), MASK_VAL, jnp.float32)
    l0 = jnp.zeros((b, hq, sq), jnp.float32)
    acc0 = jnp.zeros((b, hq, sq, d), jnp.float32)
    (m, l, acc), _ = jax.lax.scan(jax.checkpoint(step), (m0, l0, acc0),
                                  jnp.arange(nc))
    row_ok = (m > MASK_VAL * 0.5) & (l > 0.0)
    out = jnp.where(row_ok[..., None],
                    acc / jnp.where(row_ok, l, 1.0)[..., None], 0.0)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


_WARNED_FALLBACK = False


def _warn_fallback_once():
    """A pallas trace/compile failure silently degrading to the XLA path
    is a perf bug magnet (advisor r2): surface it once."""
    global _WARNED_FALLBACK
    if not _WARNED_FALLBACK:
        _WARNED_FALLBACK = True
        import logging
        import traceback
        logging.getLogger("paddle_tpu").warning(
            "pallas flash-attention raised at trace time; falling back "
            "to the XLA path for this and similar calls:\n%s",
            traceback.format_exc())


def _pallas_sdpa(q, k, v, causal):
    """[B, S, H, D] wrapper: pads seqlens to block multiples and
    transposes to [B, H, S, D]; the pad/slice VJPs (zero-pad the
    cotangent / slice the grad) are handled by jax outside custom_vjp."""
    sq, sk = q.shape[1], k.shape[1]
    sq_p, sk_p = _pad_len(sq), _pad_len(sk)
    qt = jnp.swapaxes(_pad_seq(q, sq_p), 1, 2)
    kt = jnp.swapaxes(_pad_seq(k, sk_p), 1, 2)
    vt = jnp.swapaxes(_pad_seq(v, sk_p), 1, 2)
    out = flash_mha(qt, kt, vt, causal, 1.0 / np.sqrt(q.shape[-1]), sq, sk)
    return jnp.swapaxes(out, 1, 2)[:, :sq]


def _pallas_sdpa_masked(q, k, v, mask_vecs, causal):
    from .flash_mask import flash_mha_masked, pad_intervals
    sq, sk = q.shape[1], k.shape[1]
    sq_p, sk_p = _pad_len(sq), _pad_len(sk)
    h, hm = q.shape[2], mask_vecs.shape[1]
    if hm not in (1, h):                 # per-kv-head mask under GQA
        mask_vecs = jnp.repeat(mask_vecs, h // hm, axis=1)
    mask_vecs = pad_intervals(mask_vecs, sk_p)
    qt = jnp.swapaxes(_pad_seq(q, sq_p), 1, 2)
    kt = jnp.swapaxes(_pad_seq(k, sk_p), 1, 2)
    vt = jnp.swapaxes(_pad_seq(v, sk_p), 1, 2)
    out = flash_mha_masked(qt, kt, vt, mask_vecs, causal,
                           1.0 / np.sqrt(q.shape[-1]), sq, sk)
    return jnp.swapaxes(out, 1, 2)[:, :sq]


def _pallas_sdpa_biased(q, k, v, bias, causal):
    from .flash_mask import flash_mha_biased
    sq, sk = q.shape[1], k.shape[1]
    sq_p, sk_p = _pad_len(sq), _pad_len(sk)
    h, hb = q.shape[2], bias.shape[1]
    if hb not in (1, h):
        bias = jnp.repeat(bias, h // hb, axis=1)
    if (sq_p, sk_p) != (sq, sk):
        # padded K columns masked via the bias itself (finite large-neg)
        bias = jnp.pad(bias, ((0, 0), (0, 0), (0, sq_p - sq),
                              (0, sk_p - sk)), constant_values=-1e9)
    qt = jnp.swapaxes(_pad_seq(q, sq_p), 1, 2)
    kt = jnp.swapaxes(_pad_seq(k, sk_p), 1, 2)
    vt = jnp.swapaxes(_pad_seq(v, sk_p), 1, 2)
    out = flash_mha_biased(qt, kt, vt, bias, causal,
                           1.0 / np.sqrt(q.shape[-1]), sq, sk)
    return jnp.swapaxes(out, 1, 2)[:, :sq]


def _visible(q_ids, k_ids, causal, sk_real, ko):
    """The mask every kernel shares: tail K columns are invisible, and
    causal visibility is bottom-right aligned (offset ko = sk - sq)."""
    vis = k_ids < sk_real
    if causal:
        vis &= k_ids <= q_ids + ko
    return vis


def _q_trip_count(q_blk, bq, block_k, causal, sq_real, sk_real):
    """K-block trip count for a Q-block program (fwd/dq/dbias grids):
    skips the padded K tail, the causal upper triangle, and — when the
    whole Q block is padding — everything."""
    nblk = -(-sk_real // block_k)
    if causal:
        ko = sk_real - sq_real
        upper = jnp.clip(
            (q_blk * bq + bq + ko + block_k - 1) // block_k, 0, nblk)
    else:
        upper = nblk
    return jnp.where(q_blk * bq >= sq_real, 0, upper)


def _k_trip_bounds(k_blk, bk, block_q, causal, sq_real, sk_real):
    """(lower, upper) Q-block bounds for a K-block program (dkv grid):
    skips the causal lower triangle, the padded Q tail (zero cotangent),
    and fully-padded K blocks."""
    nblk = -(-sq_real // block_q)
    if causal:
        ko = sk_real - sq_real
        lower = jnp.clip((k_blk * bk - ko) // block_q, 0, nblk)
    else:
        lower = 0
    return jnp.where(k_blk * bk >= sk_real, nblk, lower), nblk


# ---------------------------------------------------------------- forward
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, causal, block_k,
                sm_scale, sq_real, sk_real):
    # lse_ref is None for the inference-only variant (no residual needed)
    from jax.experimental import pallas as pl

    q = q_ref[...]                                         # [bq, d]
    bq, d = q.shape
    ko = sk_real - sq_real
    q_blk = pl.program_id(2)

    def body(i, carry):
        acc, m_prev, l_prev = carry
        k = k_ref[pl.dslice(i * block_k, block_k), :]
        v = v_ref[pl.dslice(i * block_k, block_k), :]
        s = _ab_t(q, k) * jnp.float32(sm_scale)
        q_ids = q_blk * bq + jax.lax.broadcasted_iota(
            jnp.int32, (bq, block_k), 0)
        k_ids = i * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (bq, block_k), 1)
        s = jnp.where(_visible(q_ids, k_ids, causal, sk_real, ko),
                      s, MASK_VAL)
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1))
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.exp(s - m_cur[:, None])
        l_cur = l_prev * alpha + jnp.sum(p, axis=1)
        acc = acc * alpha[:, None] + _ab(p.astype(v.dtype), v)
        return acc, m_cur, l_cur

    acc0 = jnp.zeros((bq, d), jnp.float32)
    m0 = jnp.full((bq,), MASK_VAL, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    upper = _q_trip_count(q_blk, bq, block_k, causal, sq_real, sk_real)
    acc, m, l = jax.lax.fori_loop(0, upper, body, (acc0, m0, l0))
    # rows with no visible key (causal with sq > sk, or padded rows when
    # upper == 0): m stayed at MASK_VAL -> emit zeros, poison-free
    row_ok = (m > MASK_VAL * 0.5) & (l > 0.0)
    o_ref[...] = jnp.where(row_ok[:, None], acc / jnp.where(
        row_ok, l, 1.0)[:, None], 0.0).astype(o_ref.dtype)
    if lse_ref is not None:
        lse = jnp.where(row_ok, m + jnp.log(jnp.where(row_ok, l, 1.0)),
                        LSE_INVALID)
        lse_ref[...] = jnp.broadcast_to(lse[:, None], (bq, NUM_LANES))


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k, sq_real,
               sk_real, need_lse=True):
    # jax 0.9.0: Mosaic lowering infinitely recurses under jax_enable_x64
    # (the framework's global default); trace the kernel in 32-bit mode.
    with jax.enable_x64(False):
        return _flash_fwd_x32(q, k, v, causal, sm_scale, block_q, block_k,
                              sq_real, sk_real, need_lse)


def _flash_fwd_x32(q, k, v, causal, sm_scale, block_q, block_k, sq_real,
                   sk_real, need_lse):
    from jax.experimental import pallas as pl

    if _stream_wanted(k.shape[2]):
        # whole-K/V VMEM residency would exceed scoped VMEM: stream the
        # key blocks through the grid instead
        return _flash_fwd_stream(q, k, v, causal, sm_scale, block_q,
                                 block_k, sq_real, sk_real, need_lse)

    b, h, sq, d = q.shape
    hk = k.shape[1]
    g = h // hk                           # q heads per kv head (GQA)
    sk = k.shape[2]
    blk = pl.BlockSpec((None, None, block_q, d),
                       lambda b_, h_, i: (b_, h_, i, 0))
    kv = pl.BlockSpec((None, None, sk, d),
                      lambda b_, h_, i: (b_, h_ // g, 0, 0))
    out_specs = [blk]
    out_shape = [jax.ShapeDtypeStruct(q.shape, q.dtype)]
    if need_lse:
        out_specs.append(pl.BlockSpec((None, None, block_q, NUM_LANES),
                                      lambda b_, h_, i: (b_, h_, i, 0)))
        out_shape.append(
            jax.ShapeDtypeStruct((b, h, sq, NUM_LANES), jnp.float32))
    kernel = functools.partial(_fwd_kernel, causal=causal, block_k=block_k,
                               sm_scale=sm_scale, sq_real=sq_real,
                               sk_real=sk_real)
    res = pl.pallas_call(
        kernel if need_lse else
        (lambda q_ref, k_ref, v_ref, o_ref: kernel(q_ref, k_ref, v_ref,
                                                   o_ref, None)),
        grid=(b, h, sq // block_q),
        in_specs=[blk, kv, kv],
        out_specs=out_specs if need_lse else out_specs[0],
        out_shape=out_shape if need_lse else out_shape[0],
        interpret=_INTERPRET,
    )(q, k, v)
    return res if need_lse else (res, None)


# -------------------------------------------- streamed (long-seq) variants
# The block kernels above hold one full non-blocked operand in VMEM (K/V
# for fwd+dq, Q/dO/O for dkv) — ideal below ~4k tokens, beyond Mosaic's
# scoped-VMEM limit past it (measured: seq 8192 bwd needs 20.75M of the
# 16M budget).  The streamed variants below iterate that operand through
# an inner GRID dimension instead, carrying the online-softmax state /
# gradient accumulators across grid steps in f32 VMEM scratch, so VMEM
# use is independent of sequence length — the flash recurrence proper.
_STREAM_SEQ = 4096     # switch point (full-VMEM path is faster below it)
_FORCE_STREAM = False  # tests: exercise the streamed path at tiny shapes


def _stream_wanted(s):
    return _FORCE_STREAM or s > _STREAM_SEQ


def causal_kv_clamp(block_q, block_k, ko, nk, causal):
    """Clamp the kv-block grid index j for a q-block program: causally
    invisible cells re-request the PREVIOUS block so Mosaic elides the
    repeated DMA (pl.when skips compute, but NOT the fetch — without
    the clamp the upper triangle costs ~2x K/V HBM traffic).  Shared by
    every streamed-grid BlockSpec (plain/masked/biased, fwd/dq)."""
    if not causal:
        return lambda i, j: j

    def f(i, j):
        jmax = jnp.clip((i * block_q + block_q - 1 + ko) // block_k,
                        0, nk - 1)
        return jnp.minimum(j, jmax)
    return f


def causal_q_clamp(block_q, block_k, ko, nq, causal):
    """Mirror clamp for a k-block program's q-side fetches (dkv grid):
    cells below the k block's first visible q block re-request the
    previous q/do/o/lse blocks."""
    if not causal:
        return lambda i, j: j

    def f(i, j):
        jmin = jnp.clip((i * block_k - ko) // block_q, 0, nq - 1)
        return jnp.maximum(j, jmin)
    return f


def _fwd_kernel_stream(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref,
                       m_ref, l_ref, *, causal, sm_scale, sq_real,
                       sk_real, nk):
    from jax.experimental import pallas as pl

    i = pl.program_id(2)
    j = pl.program_id(3)
    bq, d = q_ref.shape
    bk = k_ref.shape[0]
    ko = sk_real - sq_real

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, MASK_VAL)
        l_ref[...] = jnp.zeros_like(l_ref)

    q_lo = i * bq
    k_lo = j * bk
    vis = (q_lo < sq_real) & (k_lo < sk_real)
    if causal:
        vis = vis & (q_lo + bq - 1 + ko >= k_lo)

    @pl.when(vis)
    def _compute():
        q = q_ref[...]
        k = k_ref[...]
        v = v_ref[...]
        s = _ab_t(q, k) * jnp.float32(sm_scale)
        q_ids = q_lo + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        k_ids = k_lo + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        s = jnp.where(_visible(q_ids, k_ids, causal, sk_real, ko),
                      s, MASK_VAL)
        m_prev = m_ref[:, 0]
        l_prev = l_ref[:, 0]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1))
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.exp(s - m_cur[:, None])
        l_cur = l_prev * alpha + jnp.sum(p, axis=1)
        acc_ref[...] = acc_ref[...] * alpha[:, None] \
            + _ab(p.astype(v.dtype), v).astype(jnp.float32)
        m_ref[...] = jnp.broadcast_to(m_cur[:, None], m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_cur[:, None], l_ref.shape)

    @pl.when(j == nk - 1)
    def _finalize():
        m = m_ref[:, 0]
        l = l_ref[:, 0]
        row_ok = (m > MASK_VAL * 0.5) & (l > 0.0)
        o_ref[...] = jnp.where(
            row_ok[:, None],
            acc_ref[...] / jnp.where(row_ok, l, 1.0)[:, None],
            0.0).astype(o_ref.dtype)
        if lse_ref is not None:
            lse = jnp.where(row_ok, m + jnp.log(jnp.where(row_ok, l, 1.0)),
                            LSE_INVALID)
            lse_ref[...] = jnp.broadcast_to(lse[:, None], lse_ref.shape)


def _flash_fwd_stream(q, k, v, causal, sm_scale, block_q, block_k,
                      sq_real, sk_real, need_lse):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, sq, d = q.shape
    hk = k.shape[1]
    g = h // hk
    sk = k.shape[2]
    nk = sk // block_k
    jc = causal_kv_clamp(block_q, block_k, sk_real - sq_real, nk, causal)
    blk = pl.BlockSpec((None, None, block_q, d),
                       lambda b_, h_, i, j: (b_, h_, i, 0))
    kv = pl.BlockSpec((None, None, block_k, d),
                      lambda b_, h_, i, j: (b_, h_ // g, jc(i, j), 0))
    out_specs = [blk]
    out_shape = [jax.ShapeDtypeStruct(q.shape, q.dtype)]
    if need_lse:
        out_specs.append(pl.BlockSpec(
            (None, None, block_q, NUM_LANES),
            lambda b_, h_, i, j: (b_, h_, i, 0)))
        out_shape.append(
            jax.ShapeDtypeStruct((b, h, sq, NUM_LANES), jnp.float32))
    kernel = functools.partial(_fwd_kernel_stream, causal=causal,
                               sm_scale=sm_scale, sq_real=sq_real,
                               sk_real=sk_real, nk=nk)
    res = pl.pallas_call(
        kernel if need_lse else
        (lambda q_ref, k_ref, v_ref, o_ref, acc, m, l:
         kernel(q_ref, k_ref, v_ref, o_ref, None, acc, m, l)),
        grid=(b, h, sq // block_q, nk),
        in_specs=[blk, kv, kv],
        out_specs=out_specs if need_lse else out_specs[0],
        out_shape=out_shape if need_lse else out_shape[0],
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32),
                        pltpu.VMEM((block_q, NUM_LANES), jnp.float32),
                        pltpu.VMEM((block_q, NUM_LANES), jnp.float32)],
        interpret=_INTERPRET,
    )(q, k, v)
    return res if need_lse else (res, None)


def _bwd_dq_kernel_stream(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref,
                          dq_ref, acc_ref, delta_ref, *, causal, sm_scale,
                          sq_real, sk_real, nk):
    from jax.experimental import pallas as pl

    i = pl.program_id(2)
    j = pl.program_id(3)
    bq, d = q_ref.shape
    bk = k_ref.shape[0]
    ko = sk_real - sq_real

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        # delta depends only on the q block: compute once, not nk times
        delta = jnp.sum(o_ref[...].astype(jnp.float32)
                        * do_ref[...].astype(jnp.float32), axis=1)
        delta_ref[...] = jnp.broadcast_to(delta[:, None], delta_ref.shape)

    q_lo = i * bq
    k_lo = j * bk
    vis = (q_lo < sq_real) & (k_lo < sk_real)
    if causal:
        vis = vis & (q_lo + bq - 1 + ko >= k_lo)

    @pl.when(vis)
    def _compute():
        q = q_ref[...]
        do = do_ref[...]
        lse = lse_ref[:, 0]
        delta = delta_ref[:, 0]
        k = k_ref[...]
        v = v_ref[...]
        s = _ab_t(q, k) * jnp.float32(sm_scale)
        q_ids = q_lo + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        k_ids = k_lo + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        s = jnp.where(_visible(q_ids, k_ids, causal, sk_real, ko),
                      s, MASK_VAL)
        p = jnp.exp(s - lse[:, None])
        dp = _ab_t(do, v)
        ds = p * (dp - delta[:, None]) * jnp.float32(sm_scale)
        acc_ref[...] = acc_ref[...] + \
            _ab(ds.astype(k.dtype), k).astype(jnp.float32)

    @pl.when(j == nk - 1)
    def _finalize():
        dq_ref[...] = acc_ref[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel_stream(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref,
                           dk_ref, dv_ref, dk_acc, dv_acc, *, causal,
                           sm_scale, sq_real, sk_real, nq):
    from jax.experimental import pallas as pl

    i = pl.program_id(2)   # k block
    j = pl.program_id(3)   # q block
    bk, d = k_ref.shape
    bq = q_ref.shape[0]
    ko = sk_real - sq_real

    @pl.when(j == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    q_lo = j * bq
    k_lo = i * bk
    vis = (q_lo < sq_real) & (k_lo < sk_real)
    if causal:
        vis = vis & (q_lo + bq - 1 + ko >= k_lo)

    @pl.when(vis)
    def _compute():
        k = k_ref[...]
        v = v_ref[...]
        q = q_ref[...]
        do = do_ref[...]
        lse = lse_ref[:, 0]
        delta = jnp.sum(o_ref[...].astype(jnp.float32)
                        * do.astype(jnp.float32), axis=1)
        s = _ab_t(q, k) * jnp.float32(sm_scale)
        q_ids = q_lo + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        k_ids = k_lo + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        s = jnp.where(_visible(q_ids, k_ids, causal, sk_real, ko),
                      s, MASK_VAL)
        p = jnp.exp(s - lse[:, None])
        dv_acc[...] = dv_acc[...] + \
            _at_b(p.astype(do.dtype), do).astype(jnp.float32)
        dp = _ab_t(do, v)
        ds = p * (dp - delta[:, None]) * jnp.float32(sm_scale)
        dk_acc[...] = dk_acc[...] + \
            _at_b(ds.astype(q.dtype), q).astype(jnp.float32)

    @pl.when(j == nq - 1)
    def _finalize():
        dk_ref[...] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_acc[...].astype(dv_ref.dtype)


def _flash_bwd_stream(q, k, v, out, lse, g, causal, sm_scale, block_q,
                      block_k, sq_real, sk_real):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, sq, d = q.shape
    hk = k.shape[1]
    grp = h // hk
    sk = k.shape[2]
    nk = sk // block_k
    nq = sq // block_q
    lse = jnp.broadcast_to(lse[..., None], (b, h, sq, NUM_LANES))

    ko = sk_real - sq_real
    jc = causal_kv_clamp(block_q, block_k, ko, nk, causal)
    blk_q4 = pl.BlockSpec((None, None, block_q, d),
                          lambda b_, h_, i, j: (b_, h_, i, 0))
    blk_l4 = pl.BlockSpec((None, None, block_q, NUM_LANES),
                          lambda b_, h_, i, j: (b_, h_, i, 0))
    kv4 = pl.BlockSpec((None, None, block_k, d),
                       lambda b_, h_, i, j: (b_, h_ // grp, jc(i, j), 0))
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel_stream, causal=causal,
                          sm_scale=sm_scale, sq_real=sq_real,
                          sk_real=sk_real, nk=nk),
        grid=(b, h, nq, nk),
        in_specs=[blk_q4, kv4, kv4, blk_q4, blk_q4, blk_l4],
        out_specs=blk_q4,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32),
                        pltpu.VMEM((block_q, NUM_LANES), jnp.float32)],
        interpret=_INTERPRET,
    )(q, k, v, g, out, lse)

    blk_k4 = pl.BlockSpec((None, None, block_k, d),
                          lambda b_, h_, i, j: (b_, h_, i, 0))
    kv_i4 = pl.BlockSpec((None, None, block_k, d),
                         lambda b_, h_, i, j: (b_, h_ // grp, i, 0))
    qc = causal_q_clamp(block_q, block_k, ko, nq, causal)
    q_j4 = pl.BlockSpec((None, None, block_q, d),
                        lambda b_, h_, i, j: (b_, h_, qc(i, j), 0))
    l_j4 = pl.BlockSpec((None, None, block_q, NUM_LANES),
                        lambda b_, h_, i, j: (b_, h_, qc(i, j), 0))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel_stream, causal=causal,
                          sm_scale=sm_scale, sq_real=sq_real,
                          sk_real=sk_real, nq=nq),
        grid=(b, h, sk // block_k, nq),
        in_specs=[q_j4, kv_i4, kv_i4, q_j4, q_j4, l_j4],
        out_specs=[blk_k4, blk_k4],
        out_shape=[jax.ShapeDtypeStruct((b, h, sk, d), k.dtype),
                   jax.ShapeDtypeStruct((b, h, sk, d), v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=_INTERPRET,
    )(q, k, v, g, out, lse)
    if grp > 1:
        dk = dk.reshape(b, hk, grp, sk, d).sum(axis=2)
        dv = dv.reshape(b, hk, grp, sk, d).sum(axis=2)
    return dq, dk, dv


# --------------------------------------------------------------- backward
def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref, dq_ref, *,
                   causal, block_k, sm_scale, sq_real, sk_real):
    from jax.experimental import pallas as pl

    q = q_ref[...]                                          # [bq, d]
    do = do_ref[...]
    lse = lse_ref[:, 0]                                     # [bq]
    # delta = rowsum(out * dout), derived in-kernel from the streamed
    # blocks instead of a separate materialized [B,H,S,128] pass
    delta = jnp.sum(o_ref[...].astype(jnp.float32)
                    * do.astype(jnp.float32), axis=1)
    bq, d = q.shape
    ko = sk_real - sq_real
    q_blk = pl.program_id(2)

    def body(i, dq):
        k = k_ref[pl.dslice(i * block_k, block_k), :]
        v = v_ref[pl.dslice(i * block_k, block_k), :]
        s = _ab_t(q, k) * jnp.float32(sm_scale)
        q_ids = q_blk * bq + jax.lax.broadcasted_iota(
            jnp.int32, (bq, block_k), 0)
        k_ids = i * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (bq, block_k), 1)
        s = jnp.where(_visible(q_ids, k_ids, causal, sk_real, ko),
                      s, MASK_VAL)
        p = jnp.exp(s - lse[:, None])                       # masked -> 0
        dp = _ab_t(do, v)
        ds = p * (dp - delta[:, None]) * jnp.float32(sm_scale)
        return dq + _ab(ds.astype(k.dtype), k)

    upper = _q_trip_count(q_blk, bq, block_k, causal, sq_real, sk_real)
    dq = jax.lax.fori_loop(0, upper, body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[...] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref, dk_ref,
                    dv_ref, *, causal, block_q, sm_scale, sq_real, sk_real):
    from jax.experimental import pallas as pl

    k = k_ref[...]                                          # [bk, d]
    v = v_ref[...]
    bk, d = k.shape
    q_len = q_ref.shape[0]
    ko = sk_real - sq_real
    k_blk = pl.program_id(2)

    def body(i, carry):
        dk, dv = carry
        q = q_ref[pl.dslice(i * block_q, block_q), :]
        do = do_ref[pl.dslice(i * block_q, block_q), :]
        lse = lse_ref[pl.dslice(i * block_q, block_q), 0]
        delta = jnp.sum(
            o_ref[pl.dslice(i * block_q, block_q), :].astype(jnp.float32)
            * do.astype(jnp.float32), axis=1)
        s = _ab_t(q, k) * jnp.float32(sm_scale)
        q_ids = i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, bk), 0)
        k_ids = k_blk * bk + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, bk), 1)
        s = jnp.where(_visible(q_ids, k_ids, causal, sk_real, ko),
                      s, MASK_VAL)
        p = jnp.exp(s - lse[:, None])
        dv = dv + _at_b(p.astype(do.dtype), do)
        dp = _ab_t(do, v)
        ds = p * (dp - delta[:, None]) * jnp.float32(sm_scale)
        dk = dk + _at_b(ds.astype(q.dtype), q)
        return dk, dv

    lower, nblk = _k_trip_bounds(k_blk, bk, block_q, causal, sq_real,
                                 sk_real)
    dk, dv = jax.lax.fori_loop(
        lower, nblk, body,
        (jnp.zeros((bk, d), jnp.float32), jnp.zeros((bk, d), jnp.float32)))
    dk_ref[...] = dk.astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)


def _flash_bwd(q, k, v, out, lse, g, causal, sm_scale, block_q, block_k,
               sq_real, sk_real):
    with jax.enable_x64(False):   # see _flash_fwd
        return _flash_bwd_x32(q, k, v, out, lse, g, causal, sm_scale,
                              block_q, block_k, sq_real, sk_real)


def _flash_bwd_x32(q, k, v, out, lse, g, causal, sm_scale, block_q, block_k,
                   sq_real, sk_real):
    from jax.experimental import pallas as pl

    if _stream_wanted(max(q.shape[2], k.shape[2])):
        return _flash_bwd_stream(q, k, v, out, lse, g, causal, sm_scale,
                                 block_q, block_k, sq_real, sk_real)

    b, h, sq, d = q.shape
    hk = k.shape[1]
    grp = h // hk
    sk = k.shape[2]
    # restore the kernels' lane tiling (transient, freed per layer);
    # delta is derived in-kernel from the out/dout streams
    lse = jnp.broadcast_to(lse[..., None], (b, h, sq, NUM_LANES))

    full = lambda s: pl.BlockSpec((None, None, s, d),
                                  lambda b_, h_, i: (b_, h_, 0, 0))
    full_kv = pl.BlockSpec((None, None, sk, d),
                           lambda b_, h_, i: (b_, h_ // grp, 0, 0))
    full_l = pl.BlockSpec((None, None, sq, NUM_LANES),
                          lambda b_, h_, i: (b_, h_, 0, 0))
    blk_q = lambda: pl.BlockSpec((None, None, block_q, d),
                                 lambda b_, h_, i: (b_, h_, i, 0))
    blk_l = pl.BlockSpec((None, None, block_q, NUM_LANES),
                         lambda b_, h_, i: (b_, h_, i, 0))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, causal=causal, block_k=block_k,
                          sm_scale=sm_scale, sq_real=sq_real,
                          sk_real=sk_real),
        grid=(b, h, sq // block_q),
        in_specs=[blk_q(), full_kv, full_kv, blk_q(), blk_q(), blk_l],
        out_specs=blk_q(),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=_INTERPRET,
    )(q, k, v, g, out, lse)

    blk_k = lambda: pl.BlockSpec((None, None, block_k, d),
                                 lambda b_, h_, i: (b_, h_, i, 0))
    kv_blk = pl.BlockSpec((None, None, block_k, d),
                          lambda b_, h_, i: (b_, h_ // grp, i, 0))
    # dK/dV are emitted per Q head (grid over h) and group-summed below;
    # K/V themselves are read at kv-head width via the index map
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, causal=causal, block_q=block_q,
                          sm_scale=sm_scale, sq_real=sq_real,
                          sk_real=sk_real),
        grid=(b, h, sk // block_k),
        in_specs=[full(sq), kv_blk, kv_blk, full(sq), full(sq), full_l],
        out_specs=[blk_k(), blk_k()],
        out_shape=[jax.ShapeDtypeStruct((b, h, sk, d), k.dtype),
                   jax.ShapeDtypeStruct((b, h, sk, d), v.dtype)],
        interpret=_INTERPRET,
    )(q, k, v, g, out, lse)
    if grp > 1:
        dk = dk.reshape(b, hk, grp, sk, d).sum(axis=2)
        dv = dv.reshape(b, hk, grp, sk, d).sum(axis=2)
    return dq, dk, dv


# ------------------------------------------------------------- custom_vjp
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_mha(q, k, v, causal, sm_scale, sq_real, sk_real):
    """[B, H, S, D] flash attention; differentiable, O(S) memory.
    S dims must be block multiples (the sdpa wrapper pads); sq_real /
    sk_real are the true lengths baked into the kernels for masking.
    K/V may carry fewer heads than Q (GQA) — no repeat happens."""
    out, _ = _flash_fwd(q, k, v, causal, sm_scale,
                        *_block_sizes(q.shape[2], k.shape[2]),
                        sq_real, sk_real,
                        need_lse=False)   # no-grad path: skip the residual
    return out


def _block_sizes(sq, sk):
    """Largest 128-multiple divisor <= 512 per axis (the padded lengths
    are 128-multiples, so 128 always divides)."""
    def pick(n):
        for b in (512, 384, 256, 128):
            if n % b == 0:
                return b
        return 128
    return min(pick(sq), sq), min(pick(sk), sk)


def _flash_mha_fwd(q, k, v, causal, sm_scale, sq_real, sk_real):
    out, lse = _flash_fwd(q, k, v, causal, sm_scale,
                          *_block_sizes(q.shape[2], k.shape[2]),
                          sq_real, sk_real)
    # the lane broadcast is a Mosaic tiling artifact; keep 1/128 of it
    # as the residual (holding it whole would pin 128x fp32 activation
    # memory per layer) and re-broadcast transiently in the backward
    return out, (q, k, v, out, lse[..., 0])


def _flash_mha_bwd(causal, sm_scale, sq_real, sk_real, res, g):
    q, k, v, out, lse = res
    dq, dk, dv = _flash_bwd(q, k, v, out, lse, g, causal, sm_scale,
                            *_block_sizes(q.shape[2], k.shape[2]),
                            sq_real, sk_real)
    return dq, dk, dv


flash_mha.defvjp(_flash_mha_fwd, _flash_mha_bwd)
