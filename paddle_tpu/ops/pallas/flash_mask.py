"""Masked flash attention variants — flashmask intervals + dense bias.

Reference analog: paddle's flashmask_attention
(python/paddle/nn/functional/flash_attention.py, kernel surface
paddle/phi/kernels/gpu/flash_attn_kernel.cu) — an O(S) column-interval
encoding of attention masks (padding, sliding window, packed documents,
causal documents) so masked training never materializes the [S, S]
score matrix; plus a dense additive-bias path for ALiBi/relative-pos
biases.

TPU formulation (kernels in flash_attention.py style):
  * flashmask: the reference's column-interval encoding — for kv column
    j, query rows in [lts[j], lte[j]) are MASKED (and, non-causal, also
    [uts[j], ute[j])).  Passed as ONE stacked int32 array
    mask_vecs [B|1, H|1, nvec, Sk] with nvec = 2 (one interval) or
    4 (two intervals) — O(S) memory.  Fully-masked rows produce zero
    output and lse = -inf, and the backward treats them as zero-grad.
  * bias: an additive [B|1, H|1, Sq, Sk] term streamed blockwise into
    the logits; dbias is produced by a separate kernel pass so XLA can
    DCE it when the bias is a constant (ALiBi).

Both compose with `causal`.  See `sdpa` in flash_attention.py for the
dispatch rules and the bool-mask -> flashmask auto-conversion.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .flash_attention import (_ab, _ab_t, _at_b, _visible,
                              _q_trip_count, _k_trip_bounds, NUM_LANES,
                              MASK_VAL, LSE_INVALID, _stream_wanted,
                              causal_kv_clamp, causal_q_clamp)

__all__ = ["flash_mha_masked", "flash_mha_biased", "padding_mask_to_intervals",
           "sliding_window_intervals", "segment_intervals", "pad_intervals"]


# ------------------------------------------------------------ mask helpers
def padding_mask_to_intervals(key_mask, sq):
    """[B, Sk] or [B, H, Sk] bool key-padding mask -> mask_vecs
    [B, 1|H, 2, Sk]: masked columns exclude every query row ([0, sq)),
    valid columns none ([sq, sq))."""
    key_mask = jnp.asarray(key_mask)
    if key_mask.ndim == 2:
        key_mask = key_mask[:, None, :]
    lts = jnp.where(key_mask, jnp.int32(sq), jnp.int32(0))
    lte = jnp.full_like(lts, sq)
    return jnp.stack([lts, lte], axis=2)


def sliding_window_intervals(sk, window, batch=1):
    """Causal sliding-window attention (combine with causal=True): row r
    attends keys [r - window, r] — paddle's window convention (window+1
    keys incl. the diagonal), so column j masks rows > j + window."""
    j = jnp.arange(sk, dtype=jnp.int32)
    lts = jnp.broadcast_to(j + jnp.int32(window) + 1, (batch, 1, sk))
    lte = jnp.full_like(lts, sk)
    return jnp.stack([lts, lte], axis=2)


def segment_intervals(segment_ids, causal=True):
    """[B, S] int segment ids (contiguous packing) -> mask_vecs keeping
    attention within each segment (reference flashmask 'document mask').
    causal=True yields nvec=2 (rows past the segment are already masked
    by the triangle); causal=False yields nvec=4."""
    seg = jnp.asarray(segment_ids)
    b, s = seg.shape
    pos = jnp.arange(s, dtype=jnp.int32)
    same = seg[:, :, None] == seg[:, None, :]          # [B, S, S] bool
    # per-column segment bounds — the O(S^2) bool is a transient XLA
    # fusion; the kernel inputs stay O(S)
    first = jnp.min(jnp.where(same, pos[None, :, None], s), axis=1)
    last1 = jnp.max(jnp.where(same, pos[None, :, None], -1), axis=1) + 1
    lts = last1.astype(jnp.int32)          # mask rows at/after seg end
    lte = jnp.full_like(lts, s)
    if causal:
        vec = jnp.stack([lts, lte], axis=1)
    else:
        uts = jnp.zeros_like(lts)          # mask rows before seg start
        ute = first.astype(jnp.int32)
        vec = jnp.stack([lts, lte, uts, ute], axis=1)
    return vec[:, None]


def pad_intervals(mask_vecs, sk_padded):
    """Extend mask_vecs [B|1, H|1, nvec, Sk] to a padded key length.
    Tail values are irrelevant — every kernel masks k_ids >= sk_real
    itself — only the padded SHAPE matters for the BlockSpecs."""
    vec = jnp.asarray(mask_vecs)
    pad = sk_padded - vec.shape[-1]
    if pad <= 0:
        return vec
    return jnp.pad(vec, ((0, 0), (0, 0), (0, 0), (0, pad)))


def _mask_spec(mask_vecs, sk):
    """BlockSpec for [B|1, H|1, nvec, Sk] mask arrays (broadcast-aware)."""
    from jax.experimental import pallas as pl
    bb, hb, nvec = mask_vecs.shape[:3]

    def imap(b_, h_, i):
        return (b_ if bb > 1 else 0, h_ if hb > 1 else 0, 0, 0)

    return pl.BlockSpec((None, None, nvec, sk), imap)


def _bias_spec(bias, block_q, sk, blocked=True):
    from jax.experimental import pallas as pl
    bb, hb = bias.shape[0], bias.shape[1]

    def imap(b_, h_, i):
        return (b_ if bb > 1 else 0, h_ if hb > 1 else 0,
                i if blocked else 0, 0)

    return pl.BlockSpec((None, None, block_q if blocked else bias.shape[2],
                         sk), imap)


def _safe(m):
    return jnp.where(jnp.isfinite(m), m, jnp.zeros_like(m))


def _mask_block(s, mask_ref, q_ids, col0, ncols, nvec):
    """Apply the [lts,lte(,uts,ute)) masked-intervals for columns
    [col0, col0+ncols) to the score block s."""
    from jax.experimental import pallas as pl
    for i in range(nvec // 2):
        start = mask_ref[2 * i, pl.dslice(col0, ncols)]
        end = mask_ref[2 * i + 1, pl.dslice(col0, ncols)]
        hit = jnp.logical_and(q_ids >= start[None, :],
                              q_ids < end[None, :])
        s = jnp.where(hit, -jnp.inf, s)
    return s


# ---------------------------------------------------------------- forward
def _fwd_kernel(q_ref, k_ref, v_ref, *rest, causal, block_k, sm_scale,
                nvec, has_bias, need_lse, sq_real, sk_real):
    from jax.experimental import pallas as pl

    it = iter(rest)
    mask_ref = next(it) if nvec else None
    bias_ref = next(it) if has_bias else None
    o_ref = next(it)
    lse_ref = next(it) if need_lse else None

    q = q_ref[...]                                         # [bq, d]
    bq, d = q.shape
    ko = sk_real - sq_real              # bottom-right causal alignment
    q_blk = pl.program_id(2)

    def body(i, carry):
        acc, m_prev, l_prev = carry
        k = k_ref[pl.dslice(i * block_k, block_k), :]
        v = v_ref[pl.dslice(i * block_k, block_k), :]
        s = _ab_t(q, k) * jnp.float32(sm_scale)
        if has_bias:
            s = s + bias_ref[:, pl.dslice(i * block_k, block_k)].astype(
                jnp.float32)
        q_ids = q_blk * bq + jax.lax.broadcasted_iota(
            jnp.int32, (bq, block_k), 0)
        k_ids = i * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (bq, block_k), 1)
        s = jnp.where(_visible(q_ids, k_ids, causal, sk_real, ko),
                      s, -jnp.inf)
        if nvec:
            s = _mask_block(s, mask_ref, q_ids, i * block_k, block_k, nvec)
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1))
        # fully-masked-so-far rows: keep the exp argument finite
        alpha = jnp.where(jnp.isfinite(m_cur),
                          jnp.exp(m_prev - m_cur), 1.0)
        p = jnp.exp(s - _safe(m_cur)[:, None])
        l_cur = l_prev * alpha + jnp.sum(p, axis=1)
        acc = acc * alpha[:, None] + _ab(p.astype(v.dtype), v)
        return acc, m_cur, l_cur

    acc0 = jnp.zeros((bq, d), jnp.float32)
    m0 = jnp.full((bq,), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    upper = _q_trip_count(q_blk, bq, block_k, causal, sq_real, sk_real)
    acc, m, l = jax.lax.fori_loop(0, upper, body, (acc0, m0, l0))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[...] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    if lse_ref is not None:
        lse = jnp.where(l == 0.0, -jnp.inf, m + jnp.log(l_safe))
        lse_ref[...] = jnp.broadcast_to(lse[:, None], (bq, NUM_LANES))


def _masked_fwd(q, k, v, mask_vecs, bias, causal, sm_scale, block_q,
                block_k, sq_real, sk_real, need_lse=True, interpret=False):
    from jax.experimental import pallas as pl

    if _stream_wanted(max(q.shape[2], k.shape[2])):
        # whole-K/V VMEM residency exceeds scoped VMEM past ~4k: stream
        # the key blocks through the grid (VERDICT r3 #2 — masked
        # long-context training stays in Pallas)
        return _masked_fwd_stream(q, k, v, mask_vecs, bias, causal,
                                  sm_scale, block_q, block_k, sq_real,
                                  sk_real, need_lse, interpret)

    b, h, sq, d = q.shape
    g = h // k.shape[1]                  # q heads per kv head (GQA)
    sk = k.shape[2]
    nvec = mask_vecs.shape[2] if mask_vecs is not None else 0
    has_bias = bias is not None
    blk = pl.BlockSpec((None, None, block_q, d),
                       lambda b_, h_, i: (b_, h_, i, 0))
    kv = pl.BlockSpec((None, None, sk, d),
                      lambda b_, h_, i: (b_, h_ // g, 0, 0))
    in_specs = [blk, kv, kv]
    args = [q, k, v]
    if nvec:
        in_specs.append(_mask_spec(mask_vecs, sk))
        args.append(mask_vecs)
    if has_bias:
        in_specs.append(_bias_spec(bias, block_q, sk))
        args.append(bias)
    out_specs = [blk]
    out_shape = [jax.ShapeDtypeStruct(q.shape, q.dtype)]
    if need_lse:
        out_specs.append(pl.BlockSpec((None, None, block_q, NUM_LANES),
                                      lambda b_, h_, i: (b_, h_, i, 0)))
        out_shape.append(
            jax.ShapeDtypeStruct((b, h, sq, NUM_LANES), jnp.float32))
    kernel = functools.partial(_fwd_kernel, causal=causal, block_k=block_k,
                               sm_scale=sm_scale, nvec=nvec,
                               has_bias=has_bias, need_lse=need_lse,
                               sq_real=sq_real, sk_real=sk_real)
    with jax.enable_x64(False):   # see flash_attention._flash_fwd
        res = pl.pallas_call(
            kernel, grid=(b, h, sq // block_q),
            in_specs=in_specs,
            out_specs=out_specs if need_lse else out_specs[0],
            out_shape=out_shape if need_lse else out_shape[0],
            interpret=interpret,
        )(*args)
    return res if need_lse else (res, None)


# -------------------------------------------- streamed (long-seq) variants
# Same design as flash_attention's streamed kernels: the K/V (fwd+dq) or
# Q/dO (dkv) operand iterates through an inner GRID dimension with the
# online-softmax / gradient state carried in f32 VMEM scratch, so VMEM
# use is independent of sequence length.  Mask intervals ride along as
# [nvec, block_k] column blocks; bias as [block_q, block_k] tiles.
# Conventions follow the plain streamed kernels (MASK_VAL finite -inf,
# LSE_INVALID for empty rows) rather than the legacy masked kernels'
# -inf arithmetic — @pl.when branches must not poison scratch carries.


def _mask_block_stream(s, mask_ref, q_ids, nvec):
    """Interval mask for a streamed step: mask_ref holds THIS k block's
    columns [nvec, bk]; masked cells get MASK_VAL (finite)."""
    for i in range(nvec // 2):
        start = mask_ref[2 * i, :]
        end = mask_ref[2 * i + 1, :]
        hit = jnp.logical_and(q_ids >= start[None, :],
                              q_ids < end[None, :])
        s = jnp.where(hit, MASK_VAL, s)
    return s


def _fwd_kernel_stream(q_ref, k_ref, v_ref, *rest, causal, sm_scale,
                       nvec, has_bias, need_lse, sq_real, sk_real, nk):
    from jax.experimental import pallas as pl

    it = iter(rest)
    mask_ref = next(it) if nvec else None
    bias_ref = next(it) if has_bias else None
    o_ref = next(it)
    lse_ref = next(it) if need_lse else None
    acc_ref, m_ref, l_ref = it

    i = pl.program_id(2)
    j = pl.program_id(3)
    bq, d = q_ref.shape
    bk = k_ref.shape[0]
    ko = sk_real - sq_real

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, MASK_VAL)
        l_ref[...] = jnp.zeros_like(l_ref)

    q_lo = i * bq
    k_lo = j * bk
    vis = (q_lo < sq_real) & (k_lo < sk_real)
    if causal:
        vis = vis & (q_lo + bq - 1 + ko >= k_lo)

    @pl.when(vis)
    def _compute():
        q = q_ref[...]
        k = k_ref[...]
        v = v_ref[...]
        s = _ab_t(q, k) * jnp.float32(sm_scale)
        if has_bias:
            s = s + bias_ref[...].astype(jnp.float32)
        q_ids = q_lo + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        k_ids = k_lo + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        s = jnp.where(_visible(q_ids, k_ids, causal, sk_real, ko),
                      s, MASK_VAL)
        if nvec:
            s = _mask_block_stream(s, mask_ref, q_ids, nvec)
        m_prev = m_ref[:, 0]
        l_prev = l_ref[:, 0]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1))
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.exp(s - m_cur[:, None])
        l_cur = l_prev * alpha + jnp.sum(p, axis=1)
        acc_ref[...] = acc_ref[...] * alpha[:, None] \
            + _ab(p.astype(v.dtype), v).astype(jnp.float32)
        m_ref[...] = jnp.broadcast_to(m_cur[:, None], m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_cur[:, None], l_ref.shape)

    @pl.when(j == nk - 1)
    def _finalize():
        m = m_ref[:, 0]
        l = l_ref[:, 0]
        row_ok = (m > MASK_VAL * 0.5) & (l > 0.0)
        o_ref[...] = jnp.where(
            row_ok[:, None],
            acc_ref[...] / jnp.where(row_ok, l, 1.0)[:, None],
            0.0).astype(o_ref.dtype)
        if lse_ref is not None:
            lse = jnp.where(row_ok, m + jnp.log(jnp.where(row_ok, l, 1.0)),
                            LSE_INVALID)
            lse_ref[...] = jnp.broadcast_to(lse[:, None], lse_ref.shape)


def _bwd_dq_kernel_stream(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref,
                          *rest, causal, sm_scale, nvec, has_bias,
                          sq_real, sk_real, nk):
    from jax.experimental import pallas as pl

    it = iter(rest)
    mask_ref = next(it) if nvec else None
    bias_ref = next(it) if has_bias else None
    dq_ref = next(it)
    acc_ref, delta_ref = it

    i = pl.program_id(2)
    j = pl.program_id(3)
    bq, d = q_ref.shape
    bk = k_ref.shape[0]
    ko = sk_real - sq_real

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        delta = jnp.sum(o_ref[...].astype(jnp.float32)
                        * do_ref[...].astype(jnp.float32), axis=1)
        delta_ref[...] = jnp.broadcast_to(delta[:, None], delta_ref.shape)

    q_lo = i * bq
    k_lo = j * bk
    vis = (q_lo < sq_real) & (k_lo < sk_real)
    if causal:
        vis = vis & (q_lo + bq - 1 + ko >= k_lo)

    @pl.when(vis)
    def _compute():
        q = q_ref[...]
        do = do_ref[...]
        lse = lse_ref[:, 0]
        delta = delta_ref[:, 0]
        k = k_ref[...]
        v = v_ref[...]
        s = _ab_t(q, k) * jnp.float32(sm_scale)
        if has_bias:
            s = s + bias_ref[...].astype(jnp.float32)
        q_ids = q_lo + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        k_ids = k_lo + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        s = jnp.where(_visible(q_ids, k_ids, causal, sk_real, ko),
                      s, MASK_VAL)
        if nvec:
            s = _mask_block_stream(s, mask_ref, q_ids, nvec)
        p = jnp.exp(s - lse[:, None])      # empty rows: lse=LSE_INVALID->0
        dp = _ab_t(do, v)
        ds = p * (dp - delta[:, None]) * jnp.float32(sm_scale)
        acc_ref[...] = acc_ref[...] + \
            _ab(ds.astype(k.dtype), k).astype(jnp.float32)

    @pl.when(j == nk - 1)
    def _finalize():
        dq_ref[...] = acc_ref[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel_stream(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref,
                           *rest, causal, sm_scale, nvec, has_bias,
                           sq_real, sk_real, nq):
    from jax.experimental import pallas as pl

    it = iter(rest)
    mask_ref = next(it) if nvec else None
    bias_ref = next(it) if has_bias else None
    dk_ref = next(it)
    dv_ref = next(it)
    dk_acc, dv_acc = it

    i = pl.program_id(2)   # k block
    j = pl.program_id(3)   # q block
    bk, d = k_ref.shape
    bq = q_ref.shape[0]
    ko = sk_real - sq_real

    @pl.when(j == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    q_lo = j * bq
    k_lo = i * bk
    vis = (q_lo < sq_real) & (k_lo < sk_real)
    if causal:
        vis = vis & (q_lo + bq - 1 + ko >= k_lo)

    @pl.when(vis)
    def _compute():
        k = k_ref[...]
        v = v_ref[...]
        q = q_ref[...]
        do = do_ref[...]
        lse = lse_ref[:, 0]
        delta = jnp.sum(o_ref[...].astype(jnp.float32)
                        * do.astype(jnp.float32), axis=1)
        s = _ab_t(q, k) * jnp.float32(sm_scale)
        if has_bias:
            s = s + bias_ref[...].astype(jnp.float32)
        q_ids = q_lo + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        k_ids = k_lo + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        s = jnp.where(_visible(q_ids, k_ids, causal, sk_real, ko),
                      s, MASK_VAL)
        if nvec:
            s = _mask_block_stream(s, mask_ref, q_ids, nvec)
        p = jnp.exp(s - lse[:, None])
        dv_acc[...] = dv_acc[...] + \
            _at_b(p.astype(do.dtype), do).astype(jnp.float32)
        dp = _ab_t(do, v)
        ds = p * (dp - delta[:, None]) * jnp.float32(sm_scale)
        dk_acc[...] = dk_acc[...] + \
            _at_b(ds.astype(q.dtype), q).astype(jnp.float32)

    @pl.when(j == nq - 1)
    def _finalize():
        dk_ref[...] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[...] = dv_acc[...].astype(dv_ref.dtype)


def _bwd_dbias_kernel_stream(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref,
                             *rest, causal, sm_scale, nvec, sq_real,
                             sk_real, bb, hb, nb, nh):
    """dbias at the bias's OWN broadcast shape: grid (nq, nk, B, H) with
    b/h INNERMOST, so each (i, j) tile's reduction group is consecutive
    — broadcast dims (bb/hb == 1) accumulate into VMEM scratch and write
    once, instead of materializing a full [B, H, Sq, Sk] then summing
    (4.3 GB f32 at seq 8k — the review-caught regression)."""
    from jax.experimental import pallas as pl

    it = iter(rest)
    mask_ref = next(it) if nvec else None
    bias_ref = next(it)
    dbias_ref = next(it)
    (acc_ref,) = it

    i = pl.program_id(0)
    j = pl.program_id(1)
    # reduced (broadcast) dims sit INNERMOST so each (i, j) tile's
    # accumulation group is consecutive; when only b reduces, the grid
    # is (nq, nk, h, b) — see the swap_bh flag in the caller
    if bb == 1 and hb > 1:
        h_ = pl.program_id(2)
        b_ = pl.program_id(3)
    else:
        b_ = pl.program_id(2)
        h_ = pl.program_id(3)
    bq, d = q_ref.shape
    bk = k_ref.shape[0]
    ko = sk_real - sq_real
    q_lo = i * bq
    k_lo = j * bk
    vis = (q_lo < sq_real) & (k_lo < sk_real)
    if causal:
        vis = vis & (q_lo + bq - 1 + ko >= k_lo)

    first = jnp.bool_(True)
    last = jnp.bool_(True)
    if bb == 1:                 # b is a reduced (broadcast) dim
        first = first & (b_ == 0)
        last = last & (b_ == nb - 1)
    if hb == 1:
        first = first & (h_ == 0)
        last = last & (h_ == nh - 1)

    @pl.when(first)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(vis)
    def _compute():
        q = q_ref[...]
        do = do_ref[...]
        lse = lse_ref[:, 0]
        delta = jnp.sum(o_ref[...].astype(jnp.float32)
                        * do.astype(jnp.float32), axis=1)
        k = k_ref[...]
        v = v_ref[...]
        s = _ab_t(q, k) * jnp.float32(sm_scale)
        s = s + bias_ref[...].astype(jnp.float32)
        q_ids = q_lo + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        k_ids = k_lo + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        s = jnp.where(_visible(q_ids, k_ids, causal, sk_real, ko),
                      s, MASK_VAL)
        if nvec:
            s = _mask_block_stream(s, mask_ref, q_ids, nvec)
        p = jnp.exp(s - lse[:, None])
        dp = _ab_t(do, v)
        acc_ref[...] = acc_ref[...] + p * (dp - delta[:, None])

    @pl.when(last)
    def _finalize():
        dbias_ref[...] = acc_ref[...].astype(dbias_ref.dtype)


def _stream_specs(mask_vecs, bias, block_q, block_k, nq, nk, causal,
                  ko, transposed=False):
    """Streamed-grid BlockSpecs for mask/bias (broadcast-aware).
    transposed=True builds specs for the dkv grid (b, h, k_blk, q_blk)."""
    from jax.experimental import pallas as pl

    specs = []
    _jclamp = causal_kv_clamp(block_q, block_k, ko, nk,
                              causal and not transposed)
    _qclamp = causal_q_clamp(block_q, block_k, ko, nq,
                             causal and transposed)
    if mask_vecs is not None:
        bb, hb, nvec = mask_vecs.shape[:3]
        if transposed:
            def imap_m(b_, h_, i, j, _bb=bb, _hb=hb):
                return (b_ if _bb > 1 else 0, h_ if _hb > 1 else 0, 0, i)
        else:
            def imap_m(b_, h_, i, j, _bb=bb, _hb=hb):
                return (b_ if _bb > 1 else 0, h_ if _hb > 1 else 0, 0,
                        _jclamp(i, j))
        specs.append(pl.BlockSpec((None, None, nvec, block_k), imap_m))
    if bias is not None:
        bb, hb = bias.shape[0], bias.shape[1]
        if transposed:
            def imap_b(b_, h_, i, j, _bb=bb, _hb=hb):
                return (b_ if _bb > 1 else 0, h_ if _hb > 1 else 0,
                        _qclamp(i, j), i)
        else:
            def imap_b(b_, h_, i, j, _bb=bb, _hb=hb):
                return (b_ if _bb > 1 else 0, h_ if _hb > 1 else 0, i,
                        _jclamp(i, j))
        specs.append(pl.BlockSpec((None, None, block_q, block_k), imap_b))
    return specs


def _masked_fwd_stream(q, k, v, mask_vecs, bias, causal, sm_scale,
                       block_q, block_k, sq_real, sk_real, need_lse,
                       interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, sq, d = q.shape
    g = h // k.shape[1]
    sk = k.shape[2]
    nk = sk // block_k
    nq = sq // block_q
    nvec = mask_vecs.shape[2] if mask_vecs is not None else 0
    has_bias = bias is not None
    ko = sk_real - sq_real

    jc = causal_kv_clamp(block_q, block_k, ko, nk, causal)
    blk = pl.BlockSpec((None, None, block_q, d),
                       lambda b_, h_, i, j: (b_, h_, i, 0))
    kv = pl.BlockSpec((None, None, block_k, d),
                      lambda b_, h_, i, j: (b_, h_ // g, jc(i, j), 0))
    in_specs = [blk, kv, kv] + _stream_specs(
        mask_vecs, bias, block_q, block_k, nq, nk, causal, ko)
    args = [q, k, v] + [a for a in (mask_vecs, bias) if a is not None]
    out_specs = [blk]
    out_shape = [jax.ShapeDtypeStruct(q.shape, q.dtype)]
    if need_lse:
        out_specs.append(pl.BlockSpec((None, None, block_q, NUM_LANES),
                                      lambda b_, h_, i, j: (b_, h_, i, 0)))
        out_shape.append(
            jax.ShapeDtypeStruct((b, h, sq, NUM_LANES), jnp.float32))
    kernel = functools.partial(_fwd_kernel_stream, causal=causal,
                               sm_scale=sm_scale, nvec=nvec,
                               has_bias=has_bias, need_lse=need_lse,
                               sq_real=sq_real, sk_real=sk_real, nk=nk)
    with jax.enable_x64(False):
        res = pl.pallas_call(
            kernel, grid=(b, h, nq, nk),
            in_specs=in_specs,
            out_specs=out_specs if need_lse else out_specs[0],
            out_shape=out_shape if need_lse else out_shape[0],
            scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32),
                            pltpu.VMEM((block_q, NUM_LANES), jnp.float32),
                            pltpu.VMEM((block_q, NUM_LANES), jnp.float32)],
            interpret=interpret,
        )(*args)
    return res if need_lse else (res, None)


def _masked_bwd_stream(q, k, v, out, lse, g, mask_vecs, bias, causal,
                       sm_scale, block_q, block_k, sq_real, sk_real,
                       need_dbias, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, sq, d = q.shape
    hk = k.shape[1]
    grp = h // hk
    sk = k.shape[2]
    nk = sk // block_k
    nq = sq // block_q
    nvec = mask_vecs.shape[2] if mask_vecs is not None else 0
    has_bias = bias is not None
    ko = sk_real - sq_real
    lse_b = jnp.broadcast_to(lse[..., None], (b, h, sq, NUM_LANES))

    jc = causal_kv_clamp(block_q, block_k, ko, nk, causal)
    blk_q4 = pl.BlockSpec((None, None, block_q, d),
                          lambda b_, h_, i, j: (b_, h_, i, 0))
    blk_l4 = pl.BlockSpec((None, None, block_q, NUM_LANES),
                          lambda b_, h_, i, j: (b_, h_, i, 0))
    kv4 = pl.BlockSpec((None, None, block_k, d),
                       lambda b_, h_, i, j: (b_, h_ // grp, jc(i, j), 0))
    mb_specs = _stream_specs(mask_vecs, bias, block_q, block_k,
                             nq, nk, causal, ko)
    mb_args = [a for a in (mask_vecs, bias) if a is not None]

    with jax.enable_x64(False):
        dq = pl.pallas_call(
            functools.partial(_bwd_dq_kernel_stream, causal=causal,
                              sm_scale=sm_scale, nvec=nvec,
                              has_bias=has_bias, sq_real=sq_real,
                              sk_real=sk_real, nk=nk),
            grid=(b, h, nq, nk),
            in_specs=[blk_q4, kv4, kv4, blk_q4, blk_q4, blk_l4] + mb_specs,
            out_specs=blk_q4,
            out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
            scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32),
                            pltpu.VMEM((block_q, NUM_LANES), jnp.float32)],
            interpret=interpret,
        )(q, k, v, g, out, lse_b, *mb_args)

        blk_k4 = pl.BlockSpec((None, None, block_k, d),
                              lambda b_, h_, i, j: (b_, h_, i, 0))
        kv_i4 = pl.BlockSpec((None, None, block_k, d),
                             lambda b_, h_, i, j: (b_, h_ // grp, i, 0))
        qc = causal_q_clamp(block_q, block_k, ko, nq, causal)
        q_j4 = pl.BlockSpec(
            (None, None, block_q, d),
            lambda b_, h_, i, j: (b_, h_, qc(i, j), 0))
        l_j4 = pl.BlockSpec(
            (None, None, block_q, NUM_LANES),
            lambda b_, h_, i, j: (b_, h_, qc(i, j), 0))
        mb_specs_t = _stream_specs(mask_vecs, bias, block_q, block_k,
                                   nq, nk, causal, ko,
                                   transposed=True)
        dk, dv = pl.pallas_call(
            functools.partial(_bwd_dkv_kernel_stream, causal=causal,
                              sm_scale=sm_scale, nvec=nvec,
                              has_bias=has_bias, sq_real=sq_real,
                              sk_real=sk_real, nq=nq),
            grid=(b, h, nk, nq),
            in_specs=[q_j4, kv_i4, kv_i4, q_j4, q_j4, l_j4] + mb_specs_t,
            out_specs=[blk_k4, blk_k4],
            out_shape=[jax.ShapeDtypeStruct((b, h, sk, d), k.dtype),
                       jax.ShapeDtypeStruct((b, h, sk, d), v.dtype)],
            scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                            pltpu.VMEM((block_k, d), jnp.float32)],
            interpret=interpret,
        )(q, k, v, g, out, lse_b, *mb_args)
        if grp > 1:
            dk = dk.reshape(b, hk, grp, sk, d).sum(axis=2)
            dv = dv.reshape(b, hk, grp, sk, d).sum(axis=2)

        dbias = None
        if need_dbias:
            # grid (nq, nk, ·, ·) with the REDUCED broadcast dims
            # innermost, so each (i, j) tile's accumulation group is
            # consecutive; dbias comes out at the bias's own shape
            bb, hb = bias.shape[0], bias.shape[1]
            swap_bh = bb == 1 and hb > 1      # only-b reduces: b inner

            def _bh(g2, g3):
                return (g3, g2) if swap_bh else (g2, g3)

            jcd = causal_kv_clamp(block_q, block_k, ko, nk, causal)

            def spec(shape, f):
                return pl.BlockSpec(shape, lambda i, j, g2, g3: f(
                    i, j, *_bh(g2, g3)))

            qd = spec((None, None, block_q, d),
                      lambda i, j, b_, h_: (b_, h_, i, 0))
            ld = spec((None, None, block_q, NUM_LANES),
                      lambda i, j, b_, h_: (b_, h_, i, 0))
            kvd = spec((None, None, block_k, d),
                       lambda i, j, b_, h_: (b_, h_ // grp, jcd(i, j), 0))
            d_specs = [qd, kvd, kvd, qd, qd, ld]
            d_args = [q, k, v, g, out, lse_b]
            if nvec:
                mb_, mh_ = mask_vecs.shape[0], mask_vecs.shape[1]
                d_specs.append(spec(
                    (None, None, nvec, block_k),
                    lambda i, j, b_, h_, _mb=mb_, _mh=mh_:
                    (b_ if _mb > 1 else 0, h_ if _mh > 1 else 0, 0,
                     jcd(i, j))))
                d_args.append(mask_vecs)
            d_specs.append(spec(
                (None, None, block_q, block_k),
                lambda i, j, b_, h_, _bb=bb, _hb=hb:
                (b_ if _bb > 1 else 0, h_ if _hb > 1 else 0, i,
                 jcd(i, j))))
            d_args.append(bias)
            dbias = pl.pallas_call(
                functools.partial(_bwd_dbias_kernel_stream, causal=causal,
                                  sm_scale=sm_scale, nvec=nvec,
                                  sq_real=sq_real, sk_real=sk_real,
                                  bb=bb, hb=hb, nb=b, nh=h),
                grid=(nq, nk, h, b) if swap_bh else (nq, nk, b, h),
                in_specs=d_specs,
                out_specs=spec(
                    (None, None, block_q, block_k),
                    lambda i, j, b_, h_, _bb=bb, _hb=hb:
                    (b_ if _bb > 1 else 0, h_ if _hb > 1 else 0, i, j)),
                out_shape=jax.ShapeDtypeStruct((bb, hb, sq, sk),
                                               jnp.float32),
                scratch_shapes=[pltpu.VMEM((block_q, block_k),
                                           jnp.float32)],
                interpret=interpret,
            )(*d_args).astype(bias.dtype)
    return dq, dk, dv, dbias


# --------------------------------------------------------------- backward
def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, *rest,
                   causal, block_k, sm_scale, nvec, has_bias, sq_real,
                   sk_real):
    from jax.experimental import pallas as pl

    it = iter(rest)
    mask_ref = next(it) if nvec else None
    bias_ref = next(it) if has_bias else None
    dq_ref = next(it)

    q = q_ref[...]
    do = do_ref[...]
    lse = _safe(lse_ref[:, 0])
    delta = dl_ref[:, 0]
    bq, d = q.shape
    ko = sk_real - sq_real
    q_blk = pl.program_id(2)

    def body(i, dq):
        k = k_ref[pl.dslice(i * block_k, block_k), :]
        v = v_ref[pl.dslice(i * block_k, block_k), :]
        s = _ab_t(q, k) * jnp.float32(sm_scale)
        if has_bias:
            s = s + bias_ref[:, pl.dslice(i * block_k, block_k)].astype(
                jnp.float32)
        q_ids = q_blk * bq + jax.lax.broadcasted_iota(
            jnp.int32, (bq, block_k), 0)
        k_ids = i * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (bq, block_k), 1)
        s = jnp.where(_visible(q_ids, k_ids, causal, sk_real, ko),
                      s, -jnp.inf)
        if nvec:
            s = _mask_block(s, mask_ref, q_ids, i * block_k, block_k, nvec)
        p = jnp.exp(s - lse[:, None])                       # masked -> 0
        dp = _ab_t(do, v)
        ds = p * (dp - delta[:, None]) * jnp.float32(sm_scale)
        return dq + _ab(ds.astype(k.dtype), k)

    upper = _q_trip_count(q_blk, bq, block_k, causal, sq_real, sk_real)
    dq = jax.lax.fori_loop(0, upper, body, jnp.zeros((bq, d), jnp.float32))
    dq_ref[...] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, *rest,
                    causal, block_q, sm_scale, nvec, has_bias, sq_real,
                    sk_real):
    from jax.experimental import pallas as pl

    it = iter(rest)
    mask_ref = next(it) if nvec else None
    bias_ref = next(it) if has_bias else None
    dk_ref = next(it)
    dv_ref = next(it)

    k = k_ref[...]
    v = v_ref[...]
    bk, d = k.shape
    ko = sk_real - sq_real
    k_blk = pl.program_id(2)

    def body(i, carry):
        dk, dv = carry
        q = q_ref[pl.dslice(i * block_q, block_q), :]
        do = do_ref[pl.dslice(i * block_q, block_q), :]
        lse = _safe(lse_ref[pl.dslice(i * block_q, block_q), 0])
        delta = dl_ref[pl.dslice(i * block_q, block_q), 0]
        s = _ab_t(q, k) * jnp.float32(sm_scale)
        if has_bias:
            s = s + bias_ref[pl.dslice(i * block_q, block_q),
                             pl.dslice(k_blk * bk, bk)].astype(jnp.float32)
        q_ids = i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, bk), 0)
        k_ids = k_blk * bk + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, bk), 1)
        s = jnp.where(_visible(q_ids, k_ids, causal, sk_real, ko),
                      s, -jnp.inf)
        if nvec:
            # this kernel's block covers k columns [k_blk*bk, k_blk*bk+bk)
            s = _mask_block(s, mask_ref, q_ids, 0, bk, nvec)
        p = jnp.exp(s - lse[:, None])
        dv = dv + _at_b(p.astype(do.dtype), do)
        dp = _ab_t(do, v)
        ds = p * (dp - delta[:, None]) * jnp.float32(sm_scale)
        dk = dk + _at_b(ds.astype(q.dtype), q)
        return dk, dv

    lower, nblk = _k_trip_bounds(k_blk, bk, block_q, causal, sq_real,
                                 sk_real)
    dk, dv = jax.lax.fori_loop(
        lower, nblk, body,
        (jnp.zeros((bk, d), jnp.float32), jnp.zeros((bk, d), jnp.float32)))
    dk_ref[...] = dk.astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)


def _bwd_dbias_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, *rest,
                      causal, block_k, sm_scale, nvec, sq_real, sk_real):
    """ds per q block, written to a [block_q, Sk] dbias row; its own
    pallas_call so constant-bias training DCEs the whole pass."""
    from jax.experimental import pallas as pl

    it = iter(rest)
    mask_ref = next(it) if nvec else None
    bias_ref = next(it)
    dbias_ref = next(it)

    q = q_ref[...]
    do = do_ref[...]
    lse = _safe(lse_ref[:, 0])
    delta = dl_ref[:, 0]
    bq, d = q.shape
    ko = sk_real - sq_real
    q_blk = pl.program_id(2)
    dbias_ref[...] = jnp.zeros_like(dbias_ref)

    def body(i, _):
        k = k_ref[pl.dslice(i * block_k, block_k), :]
        v = v_ref[pl.dslice(i * block_k, block_k), :]
        s = _ab_t(q, k) * jnp.float32(sm_scale)
        s = s + bias_ref[:, pl.dslice(i * block_k, block_k)].astype(
            jnp.float32)
        q_ids = q_blk * bq + jax.lax.broadcasted_iota(
            jnp.int32, (bq, block_k), 0)
        k_ids = i * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (bq, block_k), 1)
        s = jnp.where(_visible(q_ids, k_ids, causal, sk_real, ko),
                      s, -jnp.inf)
        if nvec:
            s = _mask_block(s, mask_ref, q_ids, i * block_k, block_k, nvec)
        p = jnp.exp(s - lse[:, None])
        dp = _ab_t(do, v)
        ds = p * (dp - delta[:, None])
        dbias_ref[:, pl.dslice(i * block_k, block_k)] = \
            ds.astype(dbias_ref.dtype)
        return 0

    upper = _q_trip_count(q_blk, bq, block_k, causal, sq_real, sk_real)
    jax.lax.fori_loop(0, upper, body, 0)


def _masked_bwd(q, k, v, out, lse, g, mask_vecs, bias, causal, sm_scale,
                block_q, block_k, sq_real, sk_real, need_dbias,
                interpret=False):
    from jax.experimental import pallas as pl

    if _stream_wanted(max(q.shape[2], k.shape[2])):
        return _masked_bwd_stream(q, k, v, out, lse, g, mask_vecs, bias,
                                  causal, sm_scale, block_q, block_k,
                                  sq_real, sk_real, need_dbias, interpret)

    b, h, sq, d = q.shape
    hk = k.shape[1]
    grp = h // hk
    sk = k.shape[2]
    nvec = mask_vecs.shape[2] if mask_vecs is not None else 0
    has_bias = bias is not None
    lse_b = jnp.broadcast_to(lse[..., None], (b, h, sq, NUM_LANES))
    delta = jnp.sum(out.astype(jnp.float32) * g.astype(jnp.float32),
                    axis=-1)
    delta = jnp.broadcast_to(delta[..., None], (b, h, sq, NUM_LANES))

    full = lambda s: pl.BlockSpec((None, None, s, d),          # noqa: E731
                                  lambda b_, h_, i: (b_, h_, 0, 0))
    full_kv = pl.BlockSpec((None, None, sk, d),
                           lambda b_, h_, i: (b_, h_ // grp, 0, 0))
    full_l = pl.BlockSpec((None, None, sq, NUM_LANES),
                          lambda b_, h_, i: (b_, h_, 0, 0))
    blk_q = pl.BlockSpec((None, None, block_q, d),
                         lambda b_, h_, i: (b_, h_, i, 0))
    blk_l = pl.BlockSpec((None, None, block_q, NUM_LANES),
                         lambda b_, h_, i: (b_, h_, i, 0))

    tail_specs = []
    tail_args = []
    if nvec:
        tail_specs.append(_mask_spec(mask_vecs, sk))
        tail_args.append(mask_vecs)

    with jax.enable_x64(False):
        dq = pl.pallas_call(
            functools.partial(
                _bwd_dq_kernel, causal=causal, block_k=block_k,
                sm_scale=sm_scale, nvec=nvec, has_bias=has_bias,
                sq_real=sq_real, sk_real=sk_real),
            grid=(b, h, sq // block_q),
            in_specs=[blk_q, full_kv, full_kv, blk_q, blk_l, blk_l]
            + tail_specs
            + ([_bias_spec(bias, block_q, sk)] if has_bias else []),
            out_specs=blk_q,
            out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
            interpret=interpret,
        )(q, k, v, g, lse_b, delta,
          *(tail_args + ([bias] if has_bias else [])))

        blk_k = pl.BlockSpec((None, None, block_k, d),
                             lambda b_, h_, i: (b_, h_, i, 0))
        kv_blk = pl.BlockSpec((None, None, block_k, d),
                              lambda b_, h_, i: (b_, h_ // grp, i, 0))
        kv_tail_specs = []
        if nvec:
            bb, hb = mask_vecs.shape[0], mask_vecs.shape[1]
            kv_tail_specs.append(pl.BlockSpec(
                (None, None, nvec, block_k),
                lambda b_, h_, i, _bb=bb, _hb=hb:
                (b_ if _bb > 1 else 0, h_ if _hb > 1 else 0, 0, i)))
        # dK/dV emitted per Q head (grid over h), group-summed below
        dk, dv = pl.pallas_call(
            functools.partial(
                _bwd_dkv_kernel, causal=causal, block_q=block_q,
                sm_scale=sm_scale, nvec=nvec, has_bias=has_bias,
                sq_real=sq_real, sk_real=sk_real),
            grid=(b, h, sk // block_k),
            in_specs=[full(sq), kv_blk, kv_blk, full(sq), full_l, full_l]
            + kv_tail_specs
            + ([_bias_spec(bias, block_q, sk, blocked=False)]
               if has_bias else []),
            out_specs=[blk_k, blk_k],
            out_shape=[jax.ShapeDtypeStruct((b, h, sk, d), k.dtype),
                       jax.ShapeDtypeStruct((b, h, sk, d), v.dtype)],
            interpret=interpret,
        )(q, k, v, g, lse_b, delta,
          *(tail_args + ([bias] if has_bias else [])))
        if grp > 1:
            dk = dk.reshape(b, hk, grp, sk, d).sum(axis=2)
            dv = dv.reshape(b, hk, grp, sk, d).sum(axis=2)

        dbias = None
        if need_dbias:
            dbias_full = pl.pallas_call(
                functools.partial(
                    _bwd_dbias_kernel, causal=causal, block_k=block_k,
                    sm_scale=sm_scale, nvec=nvec,
                    sq_real=sq_real, sk_real=sk_real),
                grid=(b, h, sq // block_q),
                in_specs=[blk_q, full_kv, full_kv, blk_q, blk_l, blk_l]
                + tail_specs + [_bias_spec(bias, block_q, sk)],
                out_specs=pl.BlockSpec((None, None, block_q, sk),
                                       lambda b_, h_, i: (b_, h_, i, 0)),
                out_shape=jax.ShapeDtypeStruct((b, h, sq, sk),
                                               jnp.float32),
                interpret=interpret,
            )(q, k, v, g, lse_b, delta, *(tail_args + [bias]))
            # reduce over broadcast dims back to the bias shape
            red = []
            if bias.shape[0] == 1 and b > 1:
                red.append(0)
            if bias.shape[1] == 1 and h > 1:
                red.append(1)
            dbias = (jnp.sum(dbias_full, axis=tuple(red), keepdims=True)
                     if red else dbias_full).astype(bias.dtype)
    return dq, dk, dv, dbias


# ------------------------------------------------------------- custom_vjp
_INTERPRET = False   # set True in tests to run the kernels anywhere


def _blocks(sq, sk):
    from .flash_attention import _block_sizes
    return _block_sizes(sq, sk)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def flash_mha_masked(q, k, v, mask_vecs, causal, sm_scale, sq_real=None,
                     sk_real=None):
    """[B, H, S, D] flash attention with the flashmask column-interval
    encoding (mask_vecs [B|1, H|1, 2 or 4, Sk] int32); differentiable,
    O(S) mask memory.  S dims must be block multiples (the sdpa wrapper
    pads and extends mask_vecs via pad_intervals); sq_real/sk_real are
    the true lengths.  K/V may carry fewer heads than Q (GQA)."""
    sq_real = sq_real if sq_real is not None else q.shape[2]
    sk_real = sk_real if sk_real is not None else k.shape[2]
    out, _ = _masked_fwd(q, k, v, mask_vecs, None, causal, sm_scale,
                         *_blocks(q.shape[2], k.shape[2]),
                         sq_real, sk_real, need_lse=False,
                         interpret=_INTERPRET)
    return out


def _masked_vjp_fwd(q, k, v, mask_vecs, causal, sm_scale, sq_real,
                    sk_real):
    sq_real = sq_real if sq_real is not None else q.shape[2]
    sk_real = sk_real if sk_real is not None else k.shape[2]
    out, lse = _masked_fwd(q, k, v, mask_vecs, None, causal, sm_scale,
                           *_blocks(q.shape[2], k.shape[2]),
                           sq_real, sk_real, interpret=_INTERPRET)
    return out, (q, k, v, mask_vecs, out, lse[..., 0])


def _masked_vjp_bwd(causal, sm_scale, sq_real, sk_real, res, g):
    q, k, v, mask_vecs, out, lse = res
    sq_real = sq_real if sq_real is not None else q.shape[2]
    sk_real = sk_real if sk_real is not None else k.shape[2]
    dq, dk, dv, _ = _masked_bwd(q, k, v, out, lse, g, mask_vecs, None,
                                causal, sm_scale,
                                *_blocks(q.shape[2], k.shape[2]),
                                sq_real, sk_real,
                                need_dbias=False, interpret=_INTERPRET)
    return dq, dk, dv, None


flash_mha_masked.defvjp(_masked_vjp_fwd, _masked_vjp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def flash_mha_biased(q, k, v, bias, causal, sm_scale, sq_real=None,
                     sk_real=None):
    """[B, H, S, D] flash attention with a dense additive bias
    [B|1, H|1, Sq, Sk]; differentiable (dbias materializes a
    [B,H,Sq,Sk] f32 transient only when the bias needs a gradient).
    S dims must be block multiples (the sdpa wrapper pads the bias with
    -1e9 on the key tail); sq_real/sk_real are the true lengths."""
    sq_real = sq_real if sq_real is not None else q.shape[2]
    sk_real = sk_real if sk_real is not None else k.shape[2]
    out, _ = _masked_fwd(q, k, v, None, bias, causal, sm_scale,
                         *_blocks(q.shape[2], k.shape[2]),
                         sq_real, sk_real, need_lse=False,
                         interpret=_INTERPRET)
    return out


def _biased_vjp_fwd(q, k, v, bias, causal, sm_scale, sq_real, sk_real):
    sq_real = sq_real if sq_real is not None else q.shape[2]
    sk_real = sk_real if sk_real is not None else k.shape[2]
    out, lse = _masked_fwd(q, k, v, None, bias, causal, sm_scale,
                           *_blocks(q.shape[2], k.shape[2]),
                           sq_real, sk_real, interpret=_INTERPRET)
    return out, (q, k, v, bias, out, lse[..., 0])


def _biased_vjp_bwd(causal, sm_scale, sq_real, sk_real, res, g):
    q, k, v, bias, out, lse = res
    sq_real = sq_real if sq_real is not None else q.shape[2]
    sk_real = sk_real if sk_real is not None else k.shape[2]
    dq, dk, dv, dbias = _masked_bwd(q, k, v, out, lse, g, None, bias,
                                    causal, sm_scale,
                                    *_blocks(q.shape[2], k.shape[2]),
                                    sq_real, sk_real,
                                    need_dbias=True, interpret=_INTERPRET)
    return dq, dk, dv, dbias


flash_mha_biased.defvjp(_biased_vjp_fwd, _biased_vjp_bwd)


def dense_mask_from_intervals(mask_vecs, sq, sk):
    """Dense bool mask (True = attend) equivalent to mask_vecs — the
    O(S^2) fallback used when the Pallas path is unavailable."""
    vec = jnp.asarray(mask_vecs)
    b, h, nvec, _ = vec.shape
    r = jnp.arange(sq)[:, None]
    allowed = jnp.ones((b, h, sq, sk), bool)
    for i in range(nvec // 2):
        start = vec[:, :, 2 * i][:, :, None, :]
        end = vec[:, :, 2 * i + 1][:, :, None, :]
        allowed = jnp.logical_and(
            allowed, ~jnp.logical_and(r[None, None] >= start,
                                      r[None, None] < end))
    return allowed
