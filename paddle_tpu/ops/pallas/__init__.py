"""Pallas TPU kernels for the hot fused ops.

Reference native fused kernels: paddle/phi/kernels/fusion/gpu (CUDA) and
paddle/phi/kernels/gpu/flash_attn_kernel.cu (flashattn dynload).  Here the
TPU equivalents are Pallas (Mosaic) kernels, with pure-XLA fallbacks used on
CPU and for shapes where the kernel doesn't apply.
"""
from . import flash_attention
from . import rms_norm
