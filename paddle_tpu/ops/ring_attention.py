"""Ring attention over an ICI mesh axis (context parallelism).

Reference gap (SURVEY.md §5 long-context): Paddle in-core has only the
`sep` topology axis + alltoall primitives; ring attention itself lives
downstream.  Here it is first-class, in the two standard TPU forms:

  * `ring_attention` — blockwise flash accumulation; kv chunks rotate
    around the ring via `lax.ppermute` while each device keeps its q chunk.
    Memory O(S/n) per device, exact softmax via running (m, l) rescaling —
    the RingAttention recipe (Liu et al. '23) on XLA collectives.
  * `ulysses_attention` — DeepSpeed-Ulysses: all_to_all trades the
    sequence sharding for a head sharding, runs dense local attention,
    and trades back.  Cheaper at moderate S, needs heads % n == 0.

Both differentiate through the collective loop with jax.grad — the
backward pass is the reverse ring, no hand-written schedule.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

__all__ = ["ring_attention", "ulysses_attention"]


def _block_attn(q, k, v, mask):
    """One q-chunk vs one kv-chunk, fp32 flash partials.
    q: [B,Sq,H,D], k/v: [B,Sk,H,D], mask: [Sq,Sk] bool or None.
    Returns (acc [B,Sq,H,D] f32, m [B,Sq,H] f32, l [B,Sq,H] f32)."""
    scale = 1.0 / (q.shape[-1] ** 0.5)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask[None, None], s, -jnp.inf)
    m = jnp.max(s, axis=-1)                                  # [B,H,Sq]
    # all-masked rows: keep m finite so exp() stays 0/0-free
    m = jnp.maximum(m, -1e30)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)                                  # [B,H,Sq]
    acc = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return acc, jnp.moveaxis(m, 1, 2), jnp.moveaxis(l, 1, 2)  # [B,Sq,H]


def _ring_body(q, k, v, axis_name, n, is_causal):
    """Manual (per-device) ring attention; q,k,v local chunks [B,Sl,H,D]."""
    idx = jax.lax.axis_index(axis_name)
    sl = q.shape[1]
    perm = [(i, (i + 1) % n) for i in range(n)]
    qf = q.astype(jnp.float32)

    def step(carry, i):
        o, m, l, kc, vc = carry
        src = (idx - i) % n  # whose chunk kc is now
        if is_causal:
            qpos = idx * sl + jax.lax.broadcasted_iota(jnp.int32, (sl, sl), 0)
            kpos = src * sl + jax.lax.broadcasted_iota(jnp.int32, (sl, sl), 1)
            mask = qpos >= kpos
        else:
            mask = None
        acc, bm, bl = _block_attn(qf, kc.astype(jnp.float32),
                                  vc.astype(jnp.float32), mask)
        new_m = jnp.maximum(m, bm)
        alpha = jnp.exp(m - new_m)
        beta = jnp.exp(bm - new_m)
        o = o * alpha[..., None] + acc * beta[..., None]
        l = l * alpha + bl * beta
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        return (o, new_m, l, kc, vc), None

    b, _, h, d = q.shape
    init = (jnp.zeros((b, sl, h, d), jnp.float32),
            jnp.full((b, sl, h), -jnp.inf, jnp.float32),
            jnp.zeros((b, sl, h), jnp.float32), k, v)
    (o, m, l, _, _), _ = jax.lax.scan(
        jax.checkpoint(step), init, jnp.arange(n))
    return (o / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)


def ring_attention(q, k, v, mesh, axis="sep", is_causal=True):
    """q,k,v: global [B,S,H,D] arrays, S sharded over `axis`; exact
    softmax attention with O(S/n) memory per device."""
    n = mesh.shape[axis]
    if n == 1:
        from .pallas.flash_attention import sdpa
        return sdpa(q, k, v, is_causal=is_causal)
    body = functools.partial(_ring_body, axis_name=axis, n=n,
                             is_causal=is_causal)
    spec = P(None, axis, None, None)
    return jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, axis_names=frozenset({axis}),
                         check_vma=False)(q, k, v)


def ulysses_attention(q, k, v, mesh, axis="sep", is_causal=True):
    """DeepSpeed-Ulysses: alltoall seq<->head resharding around dense local
    attention.  Heads must divide the axis size."""
    n = mesh.shape[axis]
    if n == 1:
        from .pallas.flash_attention import sdpa
        return sdpa(q, k, v, is_causal=is_causal)
    assert q.shape[2] % n == 0, "num_heads must be divisible by sep degree"

    def body(ql, kl, vl):
        # [B, S/n, H, D] -> [B, S, H/n, D]
        def fwd(t):
            return jax.lax.all_to_all(t, axis, split_axis=2, concat_axis=1,
                                      tiled=True)
        qg, kg, vg = fwd(ql), fwd(kl), fwd(vl)
        from .pallas.flash_attention import sdpa
        og = sdpa(qg, kg, vg, is_causal=is_causal)
        return jax.lax.all_to_all(og, axis, split_axis=1, concat_axis=2,
                                  tiled=True)

    spec = P(None, axis, None, None)
    return jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, axis_names=frozenset({axis}),
                         check_vma=False)(q, k, v)
