"""Tensor-API surface part 2 (reference: python/paddle/tensor/math.py,
manipulation.py — the long tail of paddle.* functions: special functions,
stack/split families, scatter variants, distances, dtype predicates).
Pure jnp bodies registered as framework ops."""
from __future__ import annotations

import itertools
import math as _math

import jax
import jax.numpy as jnp
import numpy as np
from jax.scipy import special as jsp

from .registry import op
from ..framework import random as _random

__all__ = [
    "logaddexp", "copysign", "ldexp", "nextafter", "signbit", "sinc",
    "frexp", "gammaln", "gammainc", "gammaincc", "multigammaln", "i0e",
    "i1", "i1e", "sgn", "isneginf", "isposinf", "isreal", "isin", "take",
    "trapezoid", "cumulative_trapezoid", "vander", "renorm", "nanquantile",
    "histogram_bin_edges", "floor_mod", "reduce_as", "add_n", "cdist",
    "pdist", "hsplit", "vsplit", "dsplit", "tensor_split", "hstack",
    "vstack", "dstack", "row_stack", "column_stack", "block_diag",
    "cartesian_prod", "combinations", "diagonal_scatter", "select_scatter",
    "slice_scatter", "masked_scatter", "index_fill", "reverse", "unflatten",
    "view_as", "as_complex", "as_real", "rank", "broadcast_shape",
    "shard_index", "log_normal", "binomial", "is_complex",
    "is_floating_point", "is_integer",
]


# ------------------------------------------------------------ special/math

@op
def logaddexp(x, y, name=None):
    return jnp.logaddexp(x, y)


@op
def copysign(x, y, name=None):
    return jnp.copysign(x, y)


@op
def ldexp(x, y, name=None):
    return (x * jnp.exp2(y.astype(jnp.float32))).astype(
        jnp.result_type(x.dtype, jnp.float32)
        if not jnp.issubdtype(x.dtype, jnp.floating) else x.dtype)


@op
def nextafter(x, y, name=None):
    return jnp.nextafter(x, y)


@op
def signbit(x, name=None):
    return jnp.signbit(x)


@op
def sinc(x, name=None):
    return jnp.sinc(x)


@op
def frexp(x, name=None):
    m, e = jnp.frexp(x)
    return m, e.astype(x.dtype)


@op
def gammaln(x, name=None):
    return jsp.gammaln(x)


@op
def gammainc(x, y, name=None):
    return jsp.gammainc(x, y)


@op
def gammaincc(x, y, name=None):
    return jsp.gammaincc(x, y)


@op
def multigammaln(x, p, name=None):
    out = 0.25 * p * (p - 1) * _math.log(_math.pi)
    for j in range(int(p)):
        out = out + jsp.gammaln(x - 0.5 * j)
    return out


@op
def i0e(x, name=None):
    return jsp.i0e(x)


@op
def i1(x, name=None):
    return jsp.i1(x)


@op
def i1e(x, name=None):
    return jsp.i1e(x)


@op
def sgn(x, name=None):
    if jnp.issubdtype(x.dtype, jnp.complexfloating):
        mag = jnp.abs(x)
        return jnp.where(mag == 0, jnp.zeros((), x.dtype), x / (mag + 1e-38))
    return jnp.sign(x)


@op
def isneginf(x, name=None):
    return jnp.isneginf(x)


@op
def isposinf(x, name=None):
    return jnp.isposinf(x)


@op
def isreal(x, name=None):
    return jnp.isreal(x)


@op
def isin(x, test_x, assume_unique=False, invert=False, name=None):
    return jnp.isin(x, test_x, assume_unique=assume_unique, invert=invert)


@op
def take(x, index, mode="raise", name=None):
    flat = jnp.reshape(x, (-1,))
    idx = index
    n = flat.shape[0]
    if mode == "wrap":
        idx = ((idx % n) + n) % n
    elif mode == "clip":
        idx = jnp.clip(idx, 0, n - 1)
    else:  # 'raise': paddle supports negative python-style indices here
        idx = jnp.where(idx < 0, idx + n, idx)
    return jnp.take(flat, idx)


@op
def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    if x is not None:
        return jnp.trapezoid(y, x=x, axis=axis)
    return jnp.trapezoid(y, dx=1.0 if dx is None else dx, axis=axis)


@op
def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    y0 = jax.lax.slice_in_dim(y, 0, y.shape[axis] - 1, axis=axis)
    y1 = jax.lax.slice_in_dim(y, 1, y.shape[axis], axis=axis)
    if x is not None:
        if x.ndim == 1:
            d = jnp.diff(x)
            shape = [1] * y.ndim
            shape[axis] = -1
            d = d.reshape(shape)
        else:
            d = jnp.diff(x, axis=axis)
        return jnp.cumsum(d * (y0 + y1) / 2.0, axis=axis)
    step = 1.0 if dx is None else dx
    return jnp.cumsum(step * (y0 + y1) / 2.0, axis=axis)


@op
def vander(x, n=None, increasing=False, name=None):
    return jnp.vander(x, N=n, increasing=increasing)


@op
def renorm(x, p, axis, max_norm, name=None):
    axes = tuple(i for i in range(x.ndim) if i != axis % x.ndim)
    norms = jnp.sum(jnp.abs(x) ** p, axis=axes, keepdims=True) ** (1.0 / p)
    factor = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
    return x * factor


@op
def nanquantile(x, q, axis=None, keepdim=False, interpolation="linear",
                name=None):
    return jnp.nanquantile(x, q, axis=axis, keepdims=keepdim,
                           method=interpolation)


@op
def histogram_bin_edges(x, bins=100, min=0, max=0, name=None):
    r = None if (min == 0 and max == 0) else (min, max)
    return jnp.histogram_bin_edges(jnp.reshape(x, (-1,)), bins=bins, range=r)


@op
def floor_mod(x, y, name=None):
    return jnp.mod(x, y)


@op
def reduce_as(x, target, name=None):
    """Sum-reduce x to target's shape (reference math.py:1624)."""
    tshape = np.shape(target)
    lead = x.ndim - len(tshape)
    axes = list(range(lead))
    for i, s in enumerate(tshape):
        if x.shape[lead + i] != s:
            axes.append(lead + i)
    out = jnp.sum(x, axis=tuple(axes), keepdims=True)
    return jnp.reshape(out, tshape)


@op
def add_n(inputs, name=None):
    if not isinstance(inputs, (list, tuple)):
        return inputs
    out = inputs[0]
    for t in inputs[1:]:
        out = out + t
    return out


@op
def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary",
          name=None):
    diff = x[..., :, None, :] - y[..., None, :, :]
    if p == 2.0:
        return jnp.sqrt(jnp.sum(jnp.square(diff), axis=-1) + 1e-30)
    if p == float("inf"):
        return jnp.max(jnp.abs(diff), axis=-1)
    if p == 0.0:
        return jnp.sum((diff != 0).astype(x.dtype), axis=-1)
    return jnp.sum(jnp.abs(diff) ** p, axis=-1) ** (1.0 / p)


@op
def pdist(x, p=2.0, name=None):
    n = x.shape[0]
    iu, ju = np.triu_indices(n, k=1)
    diff = x[iu] - x[ju]
    if p == 2.0:
        return jnp.sqrt(jnp.sum(jnp.square(diff), axis=-1) + 1e-30)
    if p == float("inf"):
        return jnp.max(jnp.abs(diff), axis=-1)
    return jnp.sum(jnp.abs(diff) ** p, axis=-1) ** (1.0 / p)


# ------------------------------------------------------------ split / stack

def _split_sections(total, num_or_sections):
    if isinstance(num_or_sections, int):
        return num_or_sections
    return np.cumsum([int(s) for s in num_or_sections])[:-1].tolist()


@op
def tensor_split(x, num_or_indices, axis=0, name=None):
    if isinstance(num_or_indices, int):
        return jnp.array_split(x, num_or_indices, axis=axis)
    return jnp.split(x, [int(i) for i in num_or_indices], axis=axis)


@op
def hsplit(x, num_or_indices, name=None):
    axis = 0 if x.ndim == 1 else 1
    return tensor_split.__op_body__(x, num_or_indices, axis=axis)


@op
def vsplit(x, num_or_indices, name=None):
    return tensor_split.__op_body__(x, num_or_indices, axis=0)


@op
def dsplit(x, num_or_indices, name=None):
    return tensor_split.__op_body__(x, num_or_indices, axis=2)


@op
def hstack(x, name=None):
    return jnp.hstack(x)


@op
def vstack(x, name=None):
    return jnp.vstack(x)


@op
def dstack(x, name=None):
    return jnp.dstack(x)


@op
def row_stack(x, name=None):
    return jnp.vstack(x)


@op
def column_stack(x, name=None):
    return jnp.column_stack(x)


@op
def block_diag(inputs, name=None):
    return jax.scipy.linalg.block_diag(*inputs)


@op
def cartesian_prod(x, name=None):
    grids = jnp.meshgrid(*x, indexing="ij")
    return jnp.stack([g.reshape(-1) for g in grids], axis=-1)


@op
def combinations(x, r=2, with_replacement=False, name=None):
    n = x.shape[0]
    if r == 0:
        return jnp.zeros((0,), x.dtype)
    gen = itertools.combinations_with_replacement(range(n), r) \
        if with_replacement else itertools.combinations(range(n), r)
    idx = np.array(list(gen), np.int32).reshape(-1, r)
    return x[idx]


# ---------------------------------------------------------- scatter variants

@op
def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None):
    moved = jnp.moveaxis(x, (axis1, axis2), (-2, -1))
    n, m = moved.shape[-2], moved.shape[-1]
    rows = jnp.arange(max(n, m))
    if offset >= 0:
        r, c = rows[:min(n, m - offset)], rows[:min(n, m - offset)] + offset
    else:
        r, c = rows[:min(n + offset, m)] - offset, rows[:min(n + offset, m)]
    # moved[..., r, c] has the diagonal as the trailing axis; y matches it
    out = moved.at[..., r, c].set(y)
    return jnp.moveaxis(out, (-2, -1), (axis1, axis2))


@op
def select_scatter(x, values, axis, index, name=None):
    idx = [slice(None)] * x.ndim
    idx[axis] = index
    return x.at[tuple(idx)].set(values)


@op
def slice_scatter(x, value, axes, starts, ends, strides, name=None):
    idx = [slice(None)] * x.ndim
    for ax, st, en, sr in zip(axes, starts, ends, strides):
        idx[ax] = slice(int(st), int(en), int(sr))
    return x.at[tuple(idx)].set(value)


@op
def masked_scatter(x, mask, value, name=None):
    maskb = jnp.broadcast_to(mask.astype(bool), x.shape)
    vflat = jnp.reshape(value, (-1,))
    pos = jnp.cumsum(jnp.reshape(maskb, (-1,)).astype(jnp.int32)) - 1
    pos = jnp.clip(pos, 0, vflat.shape[0] - 1)
    picked = jnp.take(vflat, pos).reshape(x.shape)
    return jnp.where(maskb, picked.astype(x.dtype), x)


@op
def index_fill(x, index, axis, value, name=None):
    idx = [slice(None)] * x.ndim
    idx[axis] = index
    return x.at[tuple(idx)].set(value)


# ----------------------------------------------------------- view-ish / misc

@op
def reverse(x, axis, name=None):
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    return jnp.flip(x, axis=axes)


@op
def unflatten(x, axis, shape, name=None):
    axis = axis % x.ndim
    new_shape = (list(x.shape[:axis]) + [int(s) for s in np.asarray(shape)
                                         .reshape(-1)]
                 + list(x.shape[axis + 1:]))
    return jnp.reshape(x, new_shape)


@op
def view_as(x, other, name=None):
    return jnp.reshape(x, np.shape(other))


@op
def as_complex(x, name=None):
    return jax.lax.complex(x[..., 0], x[..., 1])


@op
def as_real(x, name=None):
    return jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)


@op
def rank(x, name=None):
    return jnp.asarray(x.ndim, jnp.int32)


def broadcast_shape(x_shape, y_shape):
    return list(jnp.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


@op
def shard_index(input, index_num, nshards, shard_id, ignore_value=-1,
                name=None):
    if shard_id < 0 or shard_id >= nshards:
        raise ValueError("shard_id must be in [0, nshards)")
    shard_size = (index_num + nshards - 1) // nshards
    lo = shard_id * shard_size
    hi = lo + shard_size
    in_shard = (input >= lo) & (input < hi)
    return jnp.where(in_shard, input - lo,
                     jnp.asarray(ignore_value, input.dtype))


# ----------------------------------------------------------------- random

@op
def log_normal(mean=1.0, std=2.0, shape=None, name=None):
    sh = tuple(shape) if shape is not None else np.broadcast_shapes(
        np.shape(mean), np.shape(std))
    eps = jax.random.normal(_random.split_key(), sh)
    return jnp.exp(mean + std * eps)


@op
def binomial(count, prob, name=None):
    sh = jnp.broadcast_shapes(np.shape(count), np.shape(prob))
    n = jnp.broadcast_to(count, sh).astype(jnp.float32)
    p = jnp.broadcast_to(prob, sh).astype(jnp.float32)
    out = jax.random.binomial(_random.split_key(), n, p, shape=sh)
    return out.astype(jnp.int64)


# ------------------------------------------------------------ dtype queries

def is_complex(x):
    import jax.numpy as jnp
    d = x.dtype if not hasattr(x, "_data") else x._data.dtype
    return bool(jnp.issubdtype(d, jnp.complexfloating))


def is_floating_point(x):
    d = x.dtype if not hasattr(x, "_data") else x._data.dtype
    return bool(jnp.issubdtype(d, jnp.floating))


def is_integer(x):
    d = x.dtype if not hasattr(x, "_data") else x._data.dtype
    return bool(jnp.issubdtype(d, jnp.integer))
