"""__getitem__/__setitem__ ops (reference: python/paddle/base/variable_index.py,
phi set_value/slice kernels).  Implemented functionally over jnp `.at[]` —
the tape makes both differentiable."""
from __future__ import annotations

import jax.numpy as jnp

from .registry import op


@op
def getitem(x, idx):
    if isinstance(idx, list):
        idx = tuple(idx)
    return x[idx]


@op
def setitem(x, idx, value):
    if isinstance(idx, list):
        idx = tuple(idx)
    if hasattr(value, "dtype") and value.dtype != x.dtype:
        value = value.astype(x.dtype)
    return x.at[idx].set(value)
