"""Op registry + dispatch.

The reference declares ops in YAML (paddle/phi/ops/yaml/ops.yaml, 468 ops) and
generates C++ APIs, eager ad_funcs, and Python-C bindings from them
(paddle/phi/api/generator/api_gen.py, paddle/fluid/eager/auto_code_generator/).
Here one decorator replaces the whole pipeline: an op is a pure function of
jax arrays; the wrapper handles Tensor unwrap/wrap, AMP casting hooks, and
autograd-tape recording (the VJP comes from `jax.vjp`, replacing per-op
generated GradNodes).  Shape/dtype inference (InferMeta) and sharding rules
(SPMD) are inherited from jax/XLA's own tracing and GSPMD propagation.
"""
from __future__ import annotations

import functools
from typing import Callable

import jax
import numpy as np
from jax.tree_util import tree_flatten, tree_unflatten

from ..autograd import tape

__all__ = ["op", "OPS", "apply_op"]

# name -> public wrapper. Introspectable inventory of the op surface
# (parity check against reference ops.yaml).
OPS: dict[str, Callable] = {}


def _is_tensor(x):
    from ..framework.tensor import Tensor
    return isinstance(x, Tensor)


def _wrap(opname, arr, stop_gradient, node=None, index=0):
    from ..framework.tensor import Tensor
    t = Tensor(arr, stop_gradient=stop_gradient)
    if node is not None:
        t._grad_node = node
        t._out_index = index
    return t


def _float0_zeros(aval):
    if aval.dtype == jax.dtypes.float0:
        return np.zeros(aval.shape, jax.dtypes.float0)
    import jax.numpy as jnp
    return jnp.zeros(aval.shape, aval.dtype)


def op(fn=None, *, name: str | None = None):
    """Register ``fn`` (a pure function of jax arrays) as a framework op."""
    def deco(body):
        opname = name or body.__name__

        @functools.wraps(body)
        def wrapper(*args, **kwargs):
            return apply_op(opname, body, args, kwargs)

        wrapper.__op_body__ = body
        wrapper.__op_name__ = opname
        OPS[opname] = wrapper
        return wrapper

    return deco(fn) if fn is not None else deco


def apply_op(opname, body, args, kwargs):
    from ..framework.tensor import Tensor
    from ..amp.auto_cast import maybe_amp_cast

    # static-graph build: a symbolic Variable flowing in means "record,
    # don't execute" (the analog of appending a pd_op to a pir::Block;
    # see static/graph.py).  _ever_static keeps this scan off the hot
    # eager dispatch path in pure-dygraph processes.
    from ..static import graph as _sgraph
    if _sgraph._ever_static:
        flat0, _ = tree_flatten((args, kwargs),
                                is_leaf=lambda x: isinstance(
                                    x, _sgraph.Variable))
        if any(isinstance(x, _sgraph.Variable) for x in flat0):
            return _sgraph.build_node(opname, body, args, kwargs)

    args, kwargs = maybe_amp_cast(opname, args, kwargs)

    flat, treedef = tree_flatten((args, kwargs), is_leaf=_is_tensor)
    t_idx = [i for i, x in enumerate(flat) if isinstance(x, Tensor)]
    tensors = [flat[i] for i in t_idx]
    arrays = [t._data for t in tensors]

    record = tape.is_grad_enabled() and any(
        not t.stop_gradient for t in tensors)

    if not record:
        flat2 = list(flat)
        for i, a in zip(t_idx, arrays):
            flat2[i] = a
        a2, k2 = tree_unflatten(treedef, flat2)
        out = body(*a2, **k2)
        return _wrap_outputs(opname, out, node=None)

    diff_tensors = [t for t in tensors if not t.stop_gradient]
    diff_pos = [j for j, t in enumerate(tensors) if not t.stop_gradient]

    def closed(*diff_arrays):
        flat2 = list(flat)
        sub = dict(zip(diff_pos, diff_arrays))
        for k, (i, a) in enumerate(zip(t_idx, arrays)):
            flat2[i] = sub.get(k, a)
        a2, k2 = tree_unflatten(treedef, flat2)
        return body(*a2, **k2)

    out, raw_vjp = jax.vjp(closed, *[t._data for t in diff_tensors])

    out_flat, out_treedef = tree_flatten(out)
    out_avals = [jax.ShapeDtypeStruct(np.shape(a), _tangent_dtype(a))
                 for a in out_flat]

    hooks = tape.current_saved_tensors_hooks()
    if hooks is not None:
        # saved-tensors hooks (reference autograd/saved_tensors_hooks.py):
        # pack the saved inputs now; unpack right before backward runs
        pack, unpack = hooks
        packed = [pack(t) for t in diff_tensors]

        def vjp_fn(flat_cots):
            for t, ticket in zip(diff_tensors, packed):
                unpack(ticket)
            cots = tree_unflatten(out_treedef, list(flat_cots))
            return raw_vjp(cots)
    else:
        def vjp_fn(flat_cots):
            cots = tree_unflatten(out_treedef, list(flat_cots))
            return raw_vjp(cots)

    node = tape.GradNode(opname, vjp_fn, diff_tensors, out_avals)
    return _wrap_outputs(opname, out, node=node)


def _tangent_dtype(a):
    dt = np.result_type(a)
    if np.issubdtype(dt, np.inexact) or dt == np.dtype("bfloat16"):
        return dt
    return jax.dtypes.float0


def _wrap_outputs(opname, out, node):
    out_flat, out_treedef = tree_flatten(out)
    _maybe_check_nan_inf(opname, out_flat)
    wrapped = []
    for i, a in enumerate(out_flat):
        diff = node is not None and _tangent_dtype(a) != jax.dtypes.float0
        wrapped.append(
            _wrap(opname, a, stop_gradient=not diff,
                  node=node if diff else None, index=i))
    return tree_unflatten(out_treedef, wrapped)


def _maybe_check_nan_inf(opname, arrays):
    """Per-op output NaN/Inf scan (reference: FLAGS_check_nan_inf,
    paddle/fluid/eager/nan_inf_utils.cc; level semantics from
    paddle/common/flags.cc:60-100).  Eager path only — traced arrays are
    skipped (the jit path uses amp.debugging.check_numerics)."""
    from ..flags import FLAGS
    if not FLAGS.get("FLAGS_check_nan_inf"):
        return
    import jax.core as jcore
    for a in arrays:
        if isinstance(a, jcore.Tracer):
            return
        dt = np.result_type(a)
        if not (np.issubdtype(dt, np.inexact) or dt == np.dtype("bfloat16")):
            continue
        import jax.numpy as jnp
        bad = int(jnp.sum(~jnp.isfinite(a.astype(jnp.float32))))
        if bad:
            msg = (f"Operator {opname} output contains {bad} "
                   f"NaN/Inf value(s) (shape {np.shape(a)})")
            if FLAGS.get("FLAGS_check_nan_inf_level", 0) >= 3:
                import logging
                logging.getLogger("paddle_tpu").warning(msg)
            else:
                raise FloatingPointError(msg)
