"""Op registry + dispatch.

The reference declares ops in YAML (paddle/phi/ops/yaml/ops.yaml, 468 ops) and
generates C++ APIs, eager ad_funcs, and Python-C bindings from them
(paddle/phi/api/generator/api_gen.py, paddle/fluid/eager/auto_code_generator/).
Here one decorator replaces the whole pipeline: an op is a pure function of
jax arrays; the wrapper handles Tensor unwrap/wrap, AMP casting hooks, and
autograd-tape recording (the VJP comes from `jax.vjp`, replacing per-op
generated GradNodes).  Shape/dtype inference (InferMeta) and sharding rules
(SPMD) are inherited from jax/XLA's own tracing and GSPMD propagation.
"""
from __future__ import annotations

import functools
import time as _time
from typing import Callable

import jax
import numpy as np
from jax.tree_util import tree_flatten, tree_unflatten

from ..autograd import tape
from ..profiler import statistic as _stat
from .. import observability as _obs

__all__ = ["op", "OPS", "apply_op"]

# name -> public wrapper. Introspectable inventory of the op surface
# (parity check against reference ops.yaml).
OPS: dict[str, Callable] = {}


def _is_tensor(x):
    from ..framework.tensor import Tensor
    return isinstance(x, Tensor)


def _wrap(opname, arr, stop_gradient, node=None, index=0):
    from ..framework.tensor import Tensor
    t = Tensor(arr, stop_gradient=stop_gradient)
    if node is not None:
        t._grad_node = node
        t._out_index = index
    return t


def _float0_zeros(aval):
    if aval.dtype == jax.dtypes.float0:
        return np.zeros(aval.shape, jax.dtypes.float0)
    import jax.numpy as jnp
    return jnp.zeros(aval.shape, aval.dtype)


def op(fn=None, *, name: str | None = None, external: bool = False):
    """Register ``fn`` (a pure function of jax arrays) as a framework op.
    external=True marks runtime-registered ops from outside the framework
    op surface (custom C extensions, user plugins): they are exempt from
    registry-wide invariants like the FD gradient sweep."""
    def deco(body):
        opname = name or body.__name__

        @functools.wraps(body)
        def wrapper(*args, **kwargs):
            return apply_op(opname, body, args, kwargs)

        wrapper.__op_body__ = body
        wrapper.__op_name__ = opname
        wrapper.__op_external__ = external
        OPS[opname] = wrapper
        return wrapper

    return deco(fn) if fn is not None else deco


# --------------------------------------------------- eager dispatch cache
# The reference's whole PHI design goal is a lean eager hot path
# (paddle/phi/README.md §1.2): its generated ad_funcs dispatch straight
# into precompiled kernels.  Here the analog is caching a jitted
# (forward, vjp) pair per (op, input signature): steady-state dygraph
# training stops re-tracing `jax.vjp` on every op call.
EAGER_CACHE_ENABLED = True
_EAGER_CACHE: dict = {}           # signature -> jitted callable
_EAGER_CACHE_MAX = 4096
_UNCACHEABLE: set = set()         # ops that consume eager RNG / fail trace

# cache observability: pre-bound children so the hit path pays one lock
# + one float add (see observability/registry.py); the retrace log makes
# a recompilation storm visible (op + abstract signature per miss)
_M_HITS = _obs.counter(
    "eager_cache_hits_total", "eager dispatch cache hits")
_M_MISSES = _obs.counter(
    "eager_cache_misses_total",
    "eager dispatch cache misses that traced a new executable")
_M_EVICTIONS = _obs.counter(
    "eager_cache_evictions_total", "eager dispatch cache evictions")
_M_UNCACHEABLE = _obs.counter(
    "eager_cache_uncacheable_total",
    "dispatches that could not use the eager cache", ("reason",))
_M_SIZE = _obs.gauge(
    "eager_cache_size", "live entries in the eager dispatch cache")
_M_RETRACES = _obs.counter(
    "eager_cache_retraces_total",
    "new-signature traces per op (retrace-log entries)", ("op",))


def _sig_repr(sig_parts):
    """Human-readable abstract signature for the retrace log: shapes,
    dtypes, diff flags, and static fingerprints — never values."""
    out = []
    for p in sig_parts:
        if not isinstance(p, tuple):
            continue
        if p[0] == "t":
            _, shape, dt, diff = p
            out.append(f"{dt}{list(shape)}{'∂' if diff else ''}")
        elif p[0] == "a":
            _, shape, dt = p
            out.append(f"{dt}{list(shape)}")
        elif p[0] == "s":
            out.append(f"s:{p[1]!r}")
    return ", ".join(out)


class _Unhashable(Exception):
    pass


def _static_fingerprint(x):
    """Hashable key for a non-array leaf baked into a cached trace."""
    if isinstance(x, (str, int, float, bool, complex, bytes, type(None))):
        # type tag: True == 1 == 1.0 hash-equal, but an op whose static
        # scalar drives output dtype must not share their cache entry
        return (type(x).__name__, x)
    if isinstance(x, (list, tuple)):
        return (type(x).__name__,) + tuple(_static_fingerprint(i) for i in x)
    if isinstance(x, dict):
        return tuple(sorted((k, _static_fingerprint(v))
                            for k, v in x.items()))
    if isinstance(x, np.dtype):
        return ("npdt", str(x))
    from ..framework.dtype import DType
    if isinstance(x, DType):
        return ("pdt", x.name)
    if isinstance(x, slice):
        return ("sl", x.start, x.stop, x.step)
    raise _Unhashable(type(x))


def _dtype_str(a):
    # robust to typed PRNG-key arrays, whose dtype numpy can't interpret
    dt = getattr(a, "dtype", None)
    return str(dt) if dt is not None else str(np.result_type(a))


def _is_dynamic_leaf(x):
    """Leaves whose VALUES change call-to-call: device/host arrays."""
    return isinstance(x, (jax.Array, np.ndarray, np.generic))


# one shared jitted applier for cached vjp Partials: the Partial is a
# pytree (residual leaves + jaxpr-bearing treedef), so jit caches one
# backward executable per op signature
@jax.jit
def _apply_cached_vjp(vjp_fn, cots):
    return vjp_fn(cots)


def _eager_cached_call(opname, body, flat, treedef, t_idx, diff_flags,
                       record):
    """Dispatch via the per-signature jitted executable (build on miss).

    flat/treedef: the op's flattened (args, kwargs) with Tensors as
    leaves; t_idx/diff_flags: tensor positions and their requires-grad.
    Returns (out, raw_vjp|None) or None when this call is uncacheable.
    """
    from ..framework.tensor import Tensor

    dyn_pos = []          # positions in flat fed at call time
    dyn_vals = []
    # treedef is part of the signature: identical leaves can hide
    # different kwarg names / nesting (clip(min=) vs clip(max=))
    sig_parts = [opname, record, treedef]
    try:
        for i, x in enumerate(flat):
            if isinstance(x, Tensor):
                a = x._data
                if isinstance(a, jax.core.Tracer):
                    return None            # traced context: normal path
                diff = diff_flags.get(i, False)
                dyn_pos.append(i)
                dyn_vals.append(a)
                sig_parts.append(("t", np.shape(a), _dtype_str(a), diff))
            elif _is_dynamic_leaf(x):
                if isinstance(x, jax.core.Tracer):
                    return None
                dyn_pos.append(i)
                dyn_vals.append(x)
                sig_parts.append(("a", np.shape(x), _dtype_str(x)))
            else:
                sig_parts.append(("s", _static_fingerprint(x)))
    except _Unhashable:
        _M_UNCACHEABLE.labels("unhashable-static").inc()
        return None
    sig = tuple(sig_parts)

    fn = _EAGER_CACHE.get(sig)
    if fn is None:
        diff_idx = [j for j, p in enumerate(dyn_pos)
                    if diff_flags.get(p, False)]
        static_flat = [None if i in set(dyn_pos) else v
                       for i, v in enumerate(flat)]

        def run(dyn):
            def closed(*diff_vals):
                d2 = list(dyn)
                for j, v in zip(diff_idx, diff_vals):
                    d2[j] = v
                flat2 = list(static_flat)
                for p, v in zip(dyn_pos, d2):
                    flat2[p] = v
                a2, k2 = tree_unflatten(treedef, flat2)
                return body(*a2, **k2)

            if not record:
                return closed(*[dyn[j] for j in diff_idx]), None
            return jax.vjp(closed, *[dyn[j] for j in diff_idx])

        fn = jax.jit(run)
        # first call doubles as the trace probe: eager-RNG use or a
        # trace failure (data-dependent python control flow) marks the
        # op uncacheable and falls back to the normal path.  The
        # generator key is snapshotted because a body that splits it
        # under trace stores a tracer back into the generator — restore
        # and discard the traced result so the eager rerun draws the
        # stream the op would have seen without the probe.
        from ..framework import random as _random
        gen = _random.default_generator
        key_before = gen._key
        try:
            with _random.watch_rng_use() as w:
                result = fn(tuple(dyn_vals))
            if w.used:
                _UNCACHEABLE.add(opname)
                _M_UNCACHEABLE.labels("eager-rng").inc()
                gen._key = key_before
                return None
        except Exception:
            _UNCACHEABLE.add(opname)
            _M_UNCACHEABLE.labels("trace-failure").inc()
            gen._key = key_before
            return None
        if len(_EAGER_CACHE) >= _EAGER_CACHE_MAX:
            _EAGER_CACHE.pop(next(iter(_EAGER_CACHE)))
            _M_EVICTIONS.inc()
        _EAGER_CACHE[sig] = fn
        _M_MISSES.inc()
        _M_RETRACES.labels(opname).inc()
        _obs.retrace_log.record(opname, _sig_repr(sig_parts))
        _M_SIZE.set(len(_EAGER_CACHE))
        return result
    _M_HITS.inc()
    return fn(tuple(dyn_vals))


def _pinned_rule(opname):
    import sys
    mod = sys.modules.get("paddle_tpu.distributed.debug")
    if mod is None or not mod._state.rules:   # zero-cost until used
        return None
    return mod.get_pinned_rule(opname)


def _enforce_note(e, opname, flat):
    """PADDLE_ENFORCE-style context (reference paddle/phi/core/enforce.h:
    errors carry the failing op + a summary of its inputs): annotate any
    exception escaping op dispatch via PEP-678 notes — the exception
    class and control flow are untouched, so jax's tracer-conversion
    errors (which dy2static relies on) still propagate intact."""
    try:
        descs = []
        for x in flat:
            a = getattr(x, "_data", x)
            if hasattr(a, "shape") and hasattr(a, "dtype"):
                if len(descs) >= 6:      # truncate only when more remain
                    descs.append("...")
                    break
                descs.append(f"{getattr(a, 'dtype', '?')}{list(np.shape(a))}")
        e.add_note(f"[paddle_tpu] raised while running op "
                   f"'{opname}' (tensor inputs: {', '.join(descs) or 'none'})")
    except Exception:
        pass
    return e


def apply_op(opname, body, args, kwargs):
    from ..framework.tensor import Tensor
    from ..amp.auto_cast import maybe_amp_cast

    # static-graph build: a symbolic Variable flowing in means "record,
    # don't execute" (the analog of appending a pd_op to a pir::Block;
    # see static/graph.py).  _ever_static keeps this scan off the hot
    # eager dispatch path in pure-dygraph processes.
    from ..static import graph as _sgraph
    if _sgraph._ever_static:
        flat0, _ = tree_flatten((args, kwargs),
                                is_leaf=lambda x: isinstance(
                                    x, _sgraph.Variable))
        if any(isinstance(x, _sgraph.Variable) for x in flat0):
            return _sgraph.build_node(opname, body, args, kwargs)

    args, kwargs = maybe_amp_cast(opname, args, kwargs)

    # pinned SPMD rule (distributed.debug.sharding_rules): run the body
    # under shard_map with explicit specs; context-dependent, so the
    # eager cache is bypassed for the op while a rule is active
    rule = _pinned_rule(opname)
    if rule is not None:
        from ..distributed.debug import apply_rule
        orig_body = body

        def body(*a, **k):  # noqa: F811 — deliberate shadow
            return apply_rule(rule, orig_body, a, k)

    flat, treedef = tree_flatten((args, kwargs), is_leaf=_is_tensor)
    # ONE annotation point for every dispatch path below: anything that
    # escapes gains the op/input context note
    try:
        if _stat.ENABLED:
            t0 = _time.perf_counter()
            out = _dispatch(opname, body, flat, treedef, rule)
            _profile_span(opname, t0, out)
            return out
        return _dispatch(opname, body, flat, treedef, rule)
    except Exception as e:
        raise _enforce_note(e, opname, flat)


def _profile_span(opname, t0, out):
    """Close a profiler-statistics span over this dispatch: synchronize
    the outputs first so the span covers execution, not async dispatch
    (the reference op summary's CUDA-event-synchronized semantics)."""
    flat, _ = tree_flatten(out, is_leaf=_is_tensor)
    arrs = [x._data for x in flat if _is_tensor(x)]
    try:
        jax.block_until_ready(arrs)
    except Exception:
        pass
    _stat.record_span(opname, _time.perf_counter() - t0, "op")


def _make_closed(body, flat, treedef, diff_positions):
    """Snapshot a pure re-runnable forward closure of the diff arrays.

    Captures input *arrays* (not Tensor handles — in-place APIs rebind
    them) so ``create_graph=True`` backward can re-linearise the op
    (``jax.vjp`` of this closure) to build higher-order grads.  The
    argument order matches the node's recorded diff inputs: ascending
    flat position of the differentiable tensor args.
    """
    from ..framework.tensor import Tensor

    base = [x._data if isinstance(x, Tensor) else x for x in flat]

    def closed(*diff_arrays):
        flat2 = list(base)
        for p, a in zip(diff_positions, diff_arrays):
            flat2[p] = a
        a2, k2 = tree_unflatten(treedef, flat2)
        return body(*a2, **k2)

    return closed


def _dispatch(opname, body, flat, treedef, rule):
    from ..framework.tensor import Tensor

    t_idx = [i for i, x in enumerate(flat) if isinstance(x, Tensor)]
    tensors = [flat[i] for i in t_idx]
    arrays = [t._data for t in tensors]

    record = tape.is_grad_enabled() and any(
        not t.stop_gradient for t in tensors)

    if EAGER_CACHE_ENABLED and rule is None \
            and opname not in _UNCACHEABLE:
        diff_flags = {i: (record and not flat[i].stop_gradient)
                      for i in t_idx}
        cached = _eager_cached_call(opname, body, flat, treedef,
                                    t_idx, diff_flags, record)
        if cached is not None:
            out, raw_vjp = cached
            if not record:
                return _wrap_outputs(opname, out, node=None)
            diff_positions = [i for i in t_idx if diff_flags[i]]
            return _record_node(
                opname, out, raw_vjp,
                [flat[i] for i in diff_positions], jitted_vjp=True,
                fwd_closed=_make_closed(body, flat, treedef,
                                        diff_positions))

    if not record:
        flat2 = list(flat)
        for i, a in zip(t_idx, arrays):
            flat2[i] = a
        a2, k2 = tree_unflatten(treedef, flat2)
        out = body(*a2, **k2)
        return _wrap_outputs(opname, out, node=None)

    diff_tensors = [t for t in tensors if not t.stop_gradient]
    diff_pos = [j for j, t in enumerate(tensors) if not t.stop_gradient]

    def closed(*diff_arrays):
        flat2 = list(flat)
        sub = dict(zip(diff_pos, diff_arrays))
        for k, (i, a) in enumerate(zip(t_idx, arrays)):
            flat2[i] = sub.get(k, a)
        a2, k2 = tree_unflatten(treedef, flat2)
        return body(*a2, **k2)

    from ..framework import random as _random
    with _random.watch_rng_use() as w:
        out, raw_vjp = jax.vjp(closed, *[t._data for t in diff_tensors])
    # an op that drew eager RNG (dropout) can't be re-linearised — its
    # replay would redraw the stream; leave fwd_closed unset so
    # create_graph=True fails loudly instead of silently diverging
    fwd = None if w.used else _make_closed(
        body, flat, treedef, [t_idx[j] for j in diff_pos])
    return _record_node(opname, out, raw_vjp, diff_tensors, fwd_closed=fwd)


def _record_node(opname, out, raw_vjp, diff_tensors, jitted_vjp=False,
                 fwd_closed=None):
    """Attach a GradNode running ``raw_vjp`` at backward time.
    jitted_vjp: the vjp came out of a cached jit as a tree_util.Partial —
    apply it through the shared jitted applier so backward replays a
    compiled executable instead of interpreting the jaxpr per op."""
    out_flat, out_treedef = tree_flatten(out)
    out_avals = [jax.ShapeDtypeStruct(np.shape(a), _tangent_dtype(a))
                 for a in out_flat]

    apply_vjp = ((lambda cots: _apply_cached_vjp(raw_vjp, cots))
                 if jitted_vjp else raw_vjp)

    hooks = tape.current_saved_tensors_hooks()
    if hooks is not None:
        # saved-tensors hooks (reference autograd/saved_tensors_hooks.py):
        # pack the saved inputs now; unpack right before backward runs
        pack, unpack = hooks
        packed = [pack(t) for t in diff_tensors]

        def vjp_fn(flat_cots):
            for t, ticket in zip(diff_tensors, packed):
                unpack(ticket)
            cots = tree_unflatten(out_treedef, list(flat_cots))
            return apply_vjp(cots)
    else:
        def vjp_fn(flat_cots):
            cots = tree_unflatten(out_treedef, list(flat_cots))
            return apply_vjp(cots)

    node = tape.GradNode(opname, vjp_fn, diff_tensors, out_avals)
    node.fwd_closed = fwd_closed      # create_graph=True re-linearisation
    node.out_treedef = out_treedef
    if jitted_vjp and hooks is None:
        # expose the raw vjp Partial for the fused-backward replay
        # (tape._try_fused_backward): the whole reverse sweep retraces
        # into ONE executable instead of one dispatch per node
        node.raw_vjp = raw_vjp
    return _wrap_outputs(opname, out, node=node)


def _tangent_dtype(a):
    dt = np.result_type(a)
    if np.issubdtype(dt, np.inexact) or dt == np.dtype("bfloat16"):
        return dt
    return jax.dtypes.float0


def _wrap_outputs(opname, out, node):
    out_flat, out_treedef = tree_flatten(out)
    _maybe_check_nan_inf(opname, out_flat)
    wrapped = []
    for i, a in enumerate(out_flat):
        diff = node is not None and _tangent_dtype(a) != jax.dtypes.float0
        wrapped.append(
            _wrap(opname, a, stop_gradient=not diff,
                  node=node if diff else None, index=i))
    return tree_unflatten(out_treedef, wrapped)


def _maybe_check_nan_inf(opname, arrays):
    """Per-op output NaN/Inf scan (reference: FLAGS_check_nan_inf,
    paddle/fluid/eager/nan_inf_utils.cc; level semantics from
    paddle/common/flags.cc:60-100).  Eager path only — traced arrays are
    skipped (the jit path uses amp.debugging.check_numerics)."""
    from ..flags import FLAGS
    if not FLAGS.get("FLAGS_check_nan_inf"):
        return
    import jax.core as jcore
    for a in arrays:
        if isinstance(a, jcore.Tracer):
            return
        dt = np.result_type(a)
        if not (np.issubdtype(dt, np.inexact) or dt == np.dtype("bfloat16")):
            continue
        import jax.numpy as jnp
        bad = int(jnp.sum(~jnp.isfinite(a.astype(jnp.float32))))
        if bad:
            msg = (f"Operator {opname} output contains {bad} "
                   f"NaN/Inf value(s) (shape {np.shape(a)})")
            if FLAGS.get("FLAGS_check_nan_inf_level", 0) >= 3:
                import logging
                logging.getLogger("paddle_tpu").warning(msg)
            else:
                raise FloatingPointError(msg)
