"""Linear algebra ops (reference: python/paddle/tensor/linalg.py; phi kernels
matmul/cholesky/qr/svd/...).  Dense linalg maps to jnp.linalg (XLA custom
calls on TPU); matmul rides the MXU."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import op
from .math import matmul  # re-export; registered there
from .manipulation import transpose  # re-export


@op
def mm(input, mat2, name=None):
    return jnp.matmul(input, mat2)


@op
def bmm(x, y, name=None):
    return jnp.matmul(x, y)


@op
def mv(x, vec, name=None):
    return jnp.matmul(x, vec)


@op
def t(input, name=None):
    if input.ndim < 2:
        return input
    return input.T


@op
def einsum(equation, *operands):
    if len(operands) == 1 and isinstance(operands[0], (list, tuple)):
        operands = tuple(operands[0])
    return jnp.einsum(equation, *operands)


@op
def norm(x, p=None, axis=None, keepdim=False, name=None):
    if isinstance(axis, (list, tuple)):
        axis = tuple(axis)
    if p is None:
        p = "fro" if axis is None or isinstance(axis, tuple) else 2
    if p == "fro":
        return jnp.sqrt(jnp.sum(jnp.square(jnp.abs(x)), axis=axis, keepdims=keepdim))
    if p == "nuc":
        return jnp.sum(jnp.linalg.svd(x, compute_uv=False), axis=-1)
    if p == float("inf"):
        return jnp.max(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == float("-inf"):
        return jnp.min(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == 0:
        return jnp.sum((x != 0).astype(x.dtype), axis=axis, keepdims=keepdim)
    ax = axis if axis is not None else tuple(range(x.ndim))
    return jnp.power(jnp.sum(jnp.power(jnp.abs(x), p), axis=ax, keepdims=keepdim),
                     1.0 / p)


@op
def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    return jnp.linalg.vector_norm(x, ord=p, axis=axis, keepdims=keepdim)


@op
def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    return jnp.linalg.matrix_norm(x, ord=p, keepdims=keepdim)


@op
def dist(x, y, p=2, name=None):
    return jnp.linalg.norm((x - y).reshape(-1), ord=p)


@op
def cholesky(x, upper=False, name=None):
    L = jnp.linalg.cholesky(x)
    return jnp.swapaxes(L, -1, -2).conj() if upper else L


@op
def cholesky_solve(x, y, upper=False, name=None):
    return jax.scipy.linalg.cho_solve((y, not upper), x)


@op
def qr(x, mode="reduced", name=None):
    return jnp.linalg.qr(x, mode=mode)


@op
def svd(x, full_matrices=False, name=None):
    u, s, vh = jnp.linalg.svd(x, full_matrices=full_matrices)
    return u, s, jnp.swapaxes(vh, -1, -2).conj()  # paddle returns V not Vh


@op
def svdvals(x, name=None):
    return jnp.linalg.svd(x, compute_uv=False)


@op
def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    if q is None:
        q = min(6, x.shape[-2], x.shape[-1])
    if center:
        x = x - x.mean(axis=-2, keepdims=True)
    u, s, vh = jnp.linalg.svd(x, full_matrices=False)
    return u[..., :q], s[..., :q], jnp.swapaxes(vh, -1, -2)[..., :q]


@op
def inv(x, name=None):
    return jnp.linalg.inv(x)


@op
def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return jnp.linalg.pinv(x, rtol=rcond, hermitian=hermitian)


@op
def det(x, name=None):
    return jnp.linalg.det(x)


@op
def slogdet(x, name=None):
    sign, logabs = jnp.linalg.slogdet(x)
    return jnp.stack([sign, logabs])


@op
def solve(x, y, name=None):
    return jnp.linalg.solve(x, y)


@op
def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False,
                     name=None):
    return jax.scipy.linalg.solve_triangular(
        x, y, lower=not upper, trans=1 if transpose else 0,
        unit_diagonal=unitriangular)


@op
def lstsq(x, y, rcond=None, driver=None, name=None):
    sol, res, rank, sv = jnp.linalg.lstsq(x, y, rcond=rcond)
    return sol, res, rank, sv


@op
def lu(x, pivot=True, get_infos=False, name=None):
    lu_, piv = jax.scipy.linalg.lu_factor(x)
    piv = piv.astype(jnp.int32) + 1  # paddle returns 1-based pivots
    if get_infos:
        return lu_, piv, jnp.zeros((), jnp.int32)
    return lu_, piv


@op
def eig(x, name=None):
    w, v = jnp.linalg.eig(np.asarray(x))
    return jnp.asarray(w), jnp.asarray(v)


@op
def eigh(x, UPLO="L", name=None):
    return jnp.linalg.eigh(x, UPLO=UPLO)


@op
def eigvals(x, name=None):
    return jnp.asarray(np.linalg.eigvals(np.asarray(x)))


@op
def eigvalsh(x, UPLO="L", name=None):
    return jnp.linalg.eigvalsh(x, UPLO=UPLO)


@op
def matrix_power(x, n, name=None):
    return jnp.linalg.matrix_power(x, n)


@op
def matrix_rank(x, tol=None, hermitian=False, name=None):
    return jnp.linalg.matrix_rank(x, rtol=tol)


@op
def cond(x, p=None, name=None):
    return jnp.linalg.cond(x, p=p)


@op
def corrcoef(x, rowvar=True, name=None):
    return jnp.corrcoef(x, rowvar=rowvar)


@op
def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return jnp.cov(x, rowvar=rowvar, ddof=1 if ddof else 0,
                   fweights=fweights, aweights=aweights)


@op
def histogramdd(x, bins=10, ranges=None, density=False, weights=None, name=None):
    hist, edges = jnp.histogramdd(x, bins=bins, range=ranges, density=density,
                                  weights=weights)
    return hist, list(edges)


@op
def householder_product(x, tau, name=None):
    m, n = x.shape[-2], x.shape[-1]
    eye = jnp.eye(m, dtype=x.dtype)
    q = jnp.broadcast_to(eye, x.shape[:-2] + (m, m)).copy() if x.ndim > 2 else eye
    def apply(q, args):
        v_col, t = args
        return q @ (jnp.eye(m, dtype=x.dtype) - t * jnp.outer(v_col, v_col.conj())), None
    for i in range(n):
        v = jnp.zeros(x.shape[:-2] + (m,), x.dtype)
        v = v.at[..., i].set(1.0)
        v = v.at[..., i + 1:].set(x[..., i + 1:, i])
        H = jnp.eye(m, dtype=x.dtype) - tau[..., i, None, None] * (
            v[..., :, None] @ v[..., None, :].conj())
        q = q @ H
    return q[..., :, :n]


@op
def matrix_exp(x, name=None):
    return jax.scipy.linalg.expm(x)


@op
def bitwise_and(x, y, name=None):
    return jnp.bitwise_and(x, y)


@op
def bitwise_or(x, y, name=None):
    return jnp.bitwise_or(x, y)


@op
def bitwise_xor(x, y, name=None):
    return jnp.bitwise_xor(x, y)


@op
def bitwise_not(x, name=None):
    return jnp.bitwise_not(x)


@op
def bitwise_left_shift(x, y, is_arithmetic=True, name=None):
    return jnp.left_shift(x, y)


@op
def bitwise_right_shift(x, y, is_arithmetic=True, name=None):
    return jnp.right_shift(x, y)


# ----------------------------------------------------- surface part 2

@op
def cholesky_inverse(x, upper=False, name=None):
    """Inverse of A from its Cholesky factor (reference
    python/paddle/tensor/linalg.py cholesky_inverse)."""
    n = x.shape[-1]
    eye_ = jnp.eye(n, dtype=x.dtype)
    z = jax.scipy.linalg.solve_triangular(x, eye_, lower=not upper)
    # A = L L^T -> A^-1 = (L^-1)^T (L^-1);  A = U^T U -> A^-1 = U^-1 U^-T
    if upper:
        return jnp.matmul(z, jnp.swapaxes(z, -1, -2))
    return jnp.matmul(jnp.swapaxes(z, -1, -2), z)


@op
def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    """Unpack combined LU + 1-based pivots into P, L, U (reference
    python/paddle/tensor/linalg.py:3456)."""
    m, n = x.shape[-2], x.shape[-1]
    k = min(m, n)
    L = jnp.tril(x, -1)[..., :, :k] + jnp.eye(m, k, dtype=x.dtype)
    U = jnp.triu(x)[..., :k, :]
    # pivots -> permutation matrix: row swaps applied in order
    piv = y.astype(jnp.int32) - 1

    def build_p(piv1):
        perm0 = jnp.arange(m, dtype=jnp.int32)

        def swap(perm, i):
            j = piv1[i]
            pi, pj = perm[i], perm[j]
            return perm.at[i].set(pj).at[j].set(pi), None

        perm, _ = jax.lax.scan(swap, perm0, jnp.arange(piv1.shape[0]))
        return jnp.eye(m, dtype=x.dtype)[perm].T

    if piv.ndim == 1:
        P = build_p(piv)
    else:
        P = jax.vmap(build_p)(piv.reshape(-1, piv.shape[-1])).reshape(
            x.shape[:-2] + (m, m))
    return P, L, U


@op
def multi_dot(x, name=None):
    return jnp.linalg.multi_dot(x)


@op
def ormqr(x, tau, y, left=True, transpose=False, name=None):
    """Multiply y by the (full, implicit) Q of a QR given Householder
    reflectors (reference python/paddle/tensor/linalg.py ormqr): apply
    H_i = I - tau_i v_i v_i^T directly, never materializing Q."""
    m = x.shape[-2]
    k = tau.shape[-1]
    rows = jnp.arange(m)

    def reflector(i):
        v = jnp.where(rows < i, 0.0, jnp.where(rows == i, 1.0, x[..., :, i]))
        return v

    # Q = H_0 H_1 ... H_{k-1}:  Q y applies H_{k-1} first; Q^T y applies
    # H_0 first; right-multiplication reverses the order again.
    out = y
    ascending = (left and transpose) or (not left and not transpose)
    seq = range(k) if ascending else range(k - 1, -1, -1)
    for i in seq:
        v = reflector(i)
        ti = tau[..., i][..., None, None]
        if left:
            out = out - ti * (v[..., :, None] * jnp.einsum(
                "...m,...mn->...n", v, out)[..., None, :])
        else:
            out = out - ti * (jnp.einsum(
                "...nm,...m->...n", out, v)[..., :, None] * v[..., None, :])
    return out


@op
def svd_lowrank(x, q=6, niter=2, M=None, name=None):
    """Randomized truncated SVD (reference python/paddle/tensor/linalg.py
    svd_lowrank; Halko et al. subspace iteration)."""
    from ..framework import random as _random
    if M is not None:
        x = x - M
    m, n = x.shape[-2], x.shape[-1]
    q = min(q, m, n)
    xt = jnp.swapaxes(x, -1, -2)
    omega = jax.random.normal(_random.split_key(),
                              x.shape[:-2] + (n, q), dtype=x.dtype)
    Y = jnp.matmul(x, omega)
    Q, _ = jnp.linalg.qr(Y)
    for _ in range(niter):
        Z = jnp.matmul(xt, Q)
        Qz, _ = jnp.linalg.qr(Z)
        Y = jnp.matmul(x, Qz)
        Q, _ = jnp.linalg.qr(Y)
    B = jnp.matmul(jnp.swapaxes(Q, -1, -2), x)
    u_b, s, vh = jnp.linalg.svd(B, full_matrices=False)
    U = jnp.matmul(Q, u_b)
    return U, s, jnp.swapaxes(vh, -1, -2)


@op
def fp8_fp8_half_gemm_fused(x, y, transpose_x=False, transpose_y=False,
                            bias=None, scale=1.0, output_dtype="float16",
                            act="identity", name=None):
    """fp8xfp8 -> half gemm (reference tensor/linalg.py:329 binds a CUTLASS
    kernel).  On TPU: cast fp8 operands into the MXU-native dot with a
    bf16/f16 result dtype; XLA fuses scale/bias/act into the matmul."""
    import ml_dtypes
    out_np = ml_dtypes.bfloat16 if output_dtype == "bfloat16" \
        else np.float16
    a = jnp.swapaxes(x, -1, -2) if transpose_x else x
    b = jnp.swapaxes(y, -1, -2) if transpose_y else y
    out = jax.lax.dot_general(
        a.astype(jnp.float32), b.astype(jnp.float32),
        (((a.ndim - 1,), (b.ndim - 2,)), ((), ())),
        preferred_element_type=jnp.float32)
    out = out * scale
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    if act == "relu":
        out = jax.nn.relu(out)
    elif act == "gelu":
        out = jax.nn.gelu(out)
    return out.astype(out_np)
