"""Shape/layout manipulation ops (reference: python/paddle/tensor/manipulation.py,
paddle/phi/kernels/{reshape,transpose,concat,split,gather,scatter,...}).
All static-shape friendly: XLA requires concrete shapes, so size args coming
in as Tensors are concretized where Paddle allows dynamic ones."""
from __future__ import annotations

import builtins as _builtins

import jax
import jax.numpy as jnp
import numpy as np

from .registry import op
from ..framework.dtype import to_np_dtype


def _static_ints(v):
    """Concretize a shape-like argument (list may contain 0-d arrays)."""
    if hasattr(v, "__jax_array__") or isinstance(v, (jax.Array, np.ndarray)):
        return tuple(int(x) for x in np.asarray(v).reshape(-1))
    out = []
    for x in v:
        out.append(int(x) if not isinstance(x, int) else x)
    return tuple(out)


@op
def reshape(x, shape, name=None):
    shape = _static_ints(shape)
    # Paddle semantics: 0 means "copy this dim from input".
    shape = tuple(x.shape[i] if s == 0 else s for i, s in enumerate(shape))
    return jnp.reshape(x, shape)


@op
def transpose(x, perm, name=None):
    return jnp.transpose(x, _static_ints(perm))


@op
def concat(x, axis=0, name=None):
    axis = int(axis) if not isinstance(axis, int) else axis
    return jnp.concatenate(list(x), axis=axis)


@op
def stack(x, axis=0, name=None):
    return jnp.stack(list(x), axis=axis)


@op
def unstack(x, axis=0, num=None, name=None):
    n = num if num is not None else x.shape[axis]
    return [jnp.squeeze(s, axis=axis) for s in jnp.split(x, n, axis=axis)]


@op
def split(x, num_or_sections, axis=0, name=None):
    axis = int(axis)
    if isinstance(num_or_sections, int):
        return jnp.split(x, num_or_sections, axis=axis)
    secs = list(_static_ints(num_or_sections))
    # Paddle allows one -1 meaning "the rest".
    if -1 in secs:
        known = sum(s for s in secs if s != -1)
        secs[secs.index(-1)] = x.shape[axis] - known
    idx = np.cumsum(secs)[:-1].tolist()
    return jnp.split(x, idx, axis=axis)


@op
def chunk(x, chunks, axis=0, name=None):
    return jnp.array_split(x, chunks, axis=int(axis))


@op
def squeeze(x, axis=None, name=None):
    if axis is None:
        return jnp.squeeze(x)
    if isinstance(axis, (list, tuple)):
        ax = tuple(a for a in _static_ints(axis) if x.shape[a] == 1)
        return jnp.squeeze(x, axis=ax) if ax else x
    axis = int(axis)
    return jnp.squeeze(x, axis=axis) if x.shape[axis] == 1 else x


@op
def unsqueeze(x, axis, name=None):
    if isinstance(axis, (list, tuple)) or hasattr(axis, "__len__"):
        for a in sorted(_static_ints(axis)):
            x = jnp.expand_dims(x, a)
        return x
    return jnp.expand_dims(x, int(axis))


@op
def flatten(x, start_axis=0, stop_axis=-1, name=None):
    nd = x.ndim
    start = start_axis % nd if nd else 0
    stop = stop_axis % nd if nd else 0
    # static product, not -1: a -1 reshape is undefined when another dim
    # is 0 (empty batches), while the true shape is always known here
    mid = int(np.prod(x.shape[start:stop + 1], dtype=np.int64))
    shape = list(x.shape[:start]) + [mid] + list(x.shape[stop + 1:])
    return jnp.reshape(x, shape)


@op
def tile(x, repeat_times, name=None):
    return jnp.tile(x, _static_ints(repeat_times))


@op
def expand(x, shape, name=None):
    shape = _static_ints(shape)
    # -1 keeps the original dim
    nd_off = len(shape) - x.ndim
    shape = tuple(x.shape[i - nd_off] if s == -1 else s
                  for i, s in enumerate(shape))
    return jnp.broadcast_to(x, shape)


@op
def expand_as(x, y, name=None):
    return jnp.broadcast_to(x, y.shape)


@op
def broadcast_to(x, shape, name=None):
    return jnp.broadcast_to(x, _static_ints(shape))


@op
def broadcast_tensors(inputs, name=None):
    return list(jnp.broadcast_arrays(*inputs))


@op
def gather(x, index, axis=0, name=None):
    axis = int(axis)
    return jnp.take(x, index.reshape(-1) if index.ndim > 1 else index, axis=axis)


@op
def gather_nd(x, index, name=None):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x[idx]


@op
def scatter(x, index, updates, overwrite=True, name=None):
    index = index.reshape(-1)
    if overwrite:
        return x.at[index].set(updates)
    z = x.at[index].set(jnp.zeros_like(updates))
    return z.at[index].add(updates)


@op
def scatter_nd_add(x, index, updates, name=None):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x.at[idx].add(updates)


@op
def scatter_nd(index, updates, shape, name=None):
    zeros = jnp.zeros(_static_ints(shape), updates.dtype)
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return zeros.at[idx].add(updates)


@op
def index_select(x, index, axis=0, name=None):
    return jnp.take(x, index, axis=int(axis))


@op
def index_sample(x, index):
    return jnp.take_along_axis(x, index, axis=1)


@op
def index_add(x, index, axis, value, name=None):
    # NB: the module-level `slice` op shadows the builtin here
    sl = [_builtins.slice(None)] * x.ndim
    sl[axis] = index
    return x.at[tuple(sl)].add(value)


@op
def index_put(x, indices, value, accumulate=False, name=None):
    idx = tuple(indices)
    return x.at[idx].add(value) if accumulate else x.at[idx].set(value)


@op
def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    return jnp.take_along_axis(arr, indices, axis=axis)


@op
def put_along_axis(arr, indices, values, axis, reduce="assign",
                   include_self=True, broadcast=True, name=None):
    axis = axis % arr.ndim
    if broadcast:
        # paddle semantics: indices broadcast against arr on every dim
        # except `axis`
        tgt = list(arr.shape)
        tgt[axis] = indices.shape[axis]
        indices = jnp.broadcast_to(indices, tgt)
    if not hasattr(values, "shape") or values.shape != indices.shape:
        values = jnp.broadcast_to(jnp.asarray(values, arr.dtype),
                                  indices.shape)
    sl = jnp.take_along_axis(arr, indices, axis=axis)
    if reduce == "assign":
        new = values
    elif reduce == "add":
        new = sl + values if include_self else values
    elif reduce in ("mul", "multiply"):
        new = sl * values if include_self else values
    else:
        raise ValueError(f"unsupported reduce {reduce}")
    # scatter via explicit per-dim index grids (the axis dim carries the
    # user indices; other dims are their own coordinates)
    idx = []
    for d in range(arr.ndim):
        if d == axis:
            idx.append(indices)
        else:
            shp = [1] * arr.ndim
            shp[d] = arr.shape[d]
            idx.append(jnp.broadcast_to(
                jnp.arange(arr.shape[d]).reshape(shp), indices.shape))
    return arr.at[tuple(idx)].set(new)


@op
def flip(x, axis, name=None):
    if isinstance(axis, int):
        axis = [axis]
    return jnp.flip(x, axis=tuple(_static_ints(axis)))


@op
def roll(x, shifts, axis=None, name=None):
    if axis is not None and not isinstance(axis, int):
        axis = tuple(_static_ints(axis))
    if not isinstance(shifts, int):
        shifts = tuple(_static_ints(shifts))
    return jnp.roll(x, shifts, axis=axis)


@op
def rot90(x, k=1, axes=(0, 1), name=None):
    return jnp.rot90(x, k=k, axes=tuple(axes))


@op
def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return [i.astype(jnp.int64) for i in jnp.nonzero(condition)]
    if hasattr(x, "dtype") and hasattr(y, "dtype") and x.dtype != y.dtype:
        ct = jnp.promote_types(x.dtype, y.dtype)
        x, y = x.astype(ct), y.astype(ct)
    return jnp.where(condition, x, y)


@op
def nonzero(x, as_tuple=False, name=None):
    nz = jnp.nonzero(x)
    if as_tuple:
        return [i.astype(jnp.int64).reshape(-1, 1) for i in nz]
    return jnp.stack(nz, axis=1).astype(jnp.int64)


@op
def masked_select(x, mask, name=None):
    # dynamic output size — host-side only (not jit-safe), like reference CPU op
    xn = np.asarray(x)
    mn = np.asarray(mask)
    return jnp.asarray(xn[np.broadcast_to(mn, xn.shape)])


@op
def masked_fill(x, mask, value, name=None):
    v = jnp.asarray(value, x.dtype)
    return jnp.where(mask, v, x)


@op
def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    k = int(k)
    if axis is None:
        axis = -1
    axis = int(axis)
    if largest:
        vals, idxs = jax.lax.top_k(jnp.moveaxis(x, axis, -1), k)
    else:
        vals, idxs = jax.lax.top_k(-jnp.moveaxis(x, axis, -1), k)
        vals = -vals
    return (jnp.moveaxis(vals, -1, axis),
            jnp.moveaxis(idxs, -1, axis).astype(jnp.int64))


@op
def sort(x, axis=-1, descending=False, stable=False, name=None):
    out = jnp.sort(x, axis=axis, stable=True)
    return jnp.flip(out, axis=axis) if descending else out


@op
def argsort(x, axis=-1, descending=False, stable=False, name=None):
    out = jnp.argsort(x, axis=axis, stable=True, descending=descending)
    return out.astype(jnp.int64)


@op
def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    side = "right" if right else "left"
    if sorted_sequence.ndim == 1:
        out = jnp.searchsorted(sorted_sequence, values, side=side)
    else:
        out = jax.vmap(lambda s, v: jnp.searchsorted(s, v, side=side))(
            sorted_sequence.reshape(-1, sorted_sequence.shape[-1]),
            values.reshape(-1, values.shape[-1]))
        out = out.reshape(values.shape)
    return out.astype(jnp.int32 if out_int32 else jnp.int64)


@op
def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    out = jnp.searchsorted(sorted_sequence, x, side="right" if right else "left")
    return out.astype(jnp.int32 if out_int32 else jnp.int64)


@op
def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    # dynamic-shape: host-side like reference CPU kernel
    xn = np.asarray(x)
    res = np.unique(xn, return_index=return_index, return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return jnp.asarray(res)
    return tuple(jnp.asarray(r if i == 0 else r.astype(np.dtype(dtype)))
                 for i, r in enumerate(res))


@op
def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       dtype="int64", name=None):
    xn = np.asarray(x)
    if axis is None:
        xn = xn.reshape(-1)
        keep = np.ones(len(xn), bool)
        keep[1:] = xn[1:] != xn[:-1]
        out = [jnp.asarray(xn[keep])]
        if return_inverse:
            out.append(jnp.asarray(np.cumsum(keep) - 1, dtype=np.dtype(dtype)))
        if return_counts:
            idx = np.flatnonzero(keep)
            counts = np.diff(np.append(idx, len(xn)))
            out.append(jnp.asarray(counts, dtype=np.dtype(dtype)))
        return out[0] if len(out) == 1 else tuple(out)
    raise NotImplementedError("unique_consecutive with axis")


@op
def one_hot(x, num_classes, name=None):
    return jax.nn.one_hot(x, num_classes, dtype=jnp.float32)


@op
def tril(x, diagonal=0, name=None):
    return jnp.tril(x, k=diagonal)


@op
def triu(x, diagonal=0, name=None):
    return jnp.triu(x, k=diagonal)


@op
def tril_indices(row, col, offset=0, dtype="int64", name=None):
    r, c = jnp.tril_indices(row, k=offset, m=col)
    return jnp.stack([r, c]).astype(to_np_dtype(dtype))


@op
def triu_indices(row, col=None, offset=0, dtype="int64", name=None):
    if col is None:
        col = row
    r, c = jnp.triu_indices(row, k=offset, m=col)
    return jnp.stack([r, c]).astype(to_np_dtype(dtype))


@op
def diag(x, offset=0, padding_value=0, name=None):
    if x.ndim == 1:
        out = jnp.diag(x, k=offset)
        if padding_value != 0:
            mask = jnp.diag(jnp.ones_like(x), k=offset)
            out = jnp.where(mask.astype(bool), out,
                            jnp.asarray(padding_value, x.dtype))
        return out
    return jnp.diagonal(x, offset=offset)


@op
def diagflat(x, offset=0, name=None):
    return jnp.diagflat(x, k=offset)


@op
def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return jnp.diagonal(x, offset=offset, axis1=axis1, axis2=axis2)


@op
def diag_embed(x, offset=0, dim1=-2, dim2=-1, name=None):
    base = jnp.zeros(x.shape + (x.shape[-1] + abs(offset),), x.dtype)
    n = x.shape[-1]
    rows = jnp.arange(n) + max(-offset, 0)
    cols = jnp.arange(n) + max(offset, 0)
    out_dim = n + abs(offset)
    out = jnp.zeros(x.shape[:-1] + (out_dim, out_dim), x.dtype)
    out = out.at[..., rows, cols].set(x)
    # move the two new dims into requested positions
    nd = out.ndim
    d1, d2 = dim1 % nd, dim2 % nd
    if (d1, d2) != (nd - 2, nd - 1):
        out = jnp.moveaxis(out, (nd - 2, nd - 1), (d1, d2))
    return out


@op
def meshgrid(*args, name=None):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = tuple(args[0])
    return list(jnp.meshgrid(*args, indexing="ij"))


@op
def cast(x, dtype, name=None):
    return x.astype(to_np_dtype(dtype))


@op
def pad(x, pad, mode="constant", value=0.0, data_format="NCHW",
        pad_from_left_axis=True, name=None):
    nd = x.ndim
    if isinstance(pad, int):  # pad every spatial dim on both sides
        pad = [pad] * (2 * max(nd - 2, 1))
    pad = _static_ints(pad)
    if len(pad) == 2 * nd:
        # paddle layout: [before_0, after_0, before_1, after_1, ...]? No —
        # paddle uses per-axis pairs from the *last* axes when len==2*spatial;
        # full-rank form is [x0_before, x0_after, x1_before, ...]
        pairs = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
    else:
        # pad applies to spatial dims per data_format (NCHW -> last two dims)
        k = len(pad) // 2
        pairs = [(0, 0)] * nd
        if data_format.endswith("C") and nd >= 3:  # NHWC-style
            spatial = list(range(1, 1 + k))
        else:
            spatial = list(range(nd - k, nd))
        for i, d in enumerate(spatial):
            pairs[d] = (pad[2 * i], pad[2 * i + 1])
    if mode == "constant":
        return jnp.pad(x, pairs, constant_values=value)
    jmode = {"reflect": "reflect", "replicate": "edge", "circular": "wrap"}[mode]
    return jnp.pad(x, pairs, mode=jmode)


@op
def repeat_interleave(x, repeats, axis=None, name=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    if hasattr(repeats, "shape") and getattr(repeats, "ndim", 0) > 0:
        total = int(np.asarray(repeats).sum())
        return jnp.repeat(x, repeats, axis=axis, total_repeat_length=total)
    return jnp.repeat(x, int(repeats), axis=axis)


@op
def as_strided(x, shape, stride, offset=0, name=None):
    flat = x.reshape(-1)[offset:]
    shape = _static_ints(shape)
    stride = _static_ints(stride)
    idx = np.zeros(shape, dtype=np.int64)
    for d, (s, st) in enumerate(zip(shape, stride)):
        ix = np.arange(s) * st
        idx += ix.reshape([-1 if i == d else 1 for i in range(len(shape))])
    return flat[jnp.asarray(idx)]


@op
def moveaxis(x, source, destination, name=None):
    return jnp.moveaxis(x, source, destination)


@op
def swapaxes(x, axis0, axis1, name=None):
    return jnp.swapaxes(x, axis0, axis1)


@op
def atleast_1d(*inputs, name=None):
    out = [jnp.atleast_1d(i) for i in inputs]
    return out[0] if len(out) == 1 else out


@op
def atleast_2d(*inputs, name=None):
    out = [jnp.atleast_2d(i) for i in inputs]
    return out[0] if len(out) == 1 else out


@op
def atleast_3d(*inputs, name=None):
    out = [jnp.atleast_3d(i) for i in inputs]
    return out[0] if len(out) == 1 else out


@op
def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return jnp.reshape(x, _static_ints(shape_or_dtype))
    return x.view(to_np_dtype(shape_or_dtype))


@op
def unfold(x, axis, size, step, name=None):
    n = (x.shape[axis] - size) // step + 1
    starts = jnp.arange(n) * step
    def take(s):
        return jax.lax.dynamic_slice_in_dim(x, s, size, axis)
    out = jax.vmap(take)(starts)          # [n, ..., size at axis...]
    return jnp.moveaxis(out, 0, axis)


@op
def tensordot(x, y, axes=2, name=None):
    if hasattr(axes, "__len__") and not isinstance(axes, int):
        axes = tuple(tuple(_static_ints(a)) if hasattr(a, "__len__") else int(a)
                     for a in axes)
    return jnp.tensordot(x, y, axes=axes)


@op
def crop(x, shape=None, offsets=None, name=None):
    shape = _static_ints(shape)
    offsets = _static_ints(offsets) if offsets is not None else (0,) * x.ndim
    shape = tuple(x.shape[i] - offsets[i] if s == -1 else s
                  for i, s in enumerate(shape))
    return jax.lax.dynamic_slice(x, offsets, shape)




@op
def slice(input, axes, starts, ends, name=None):
    sl = [_builtins.slice(None)] * input.ndim
    for ax, st, en in zip(_static_ints(axes), _static_ints(starts), _static_ints(ends)):
        sl[ax] = _builtins.slice(st, en)
    return input[tuple(sl)]


@op
def strided_slice(x, axes, starts, ends, strides, name=None):
    sl = [_builtins.slice(None)] * x.ndim
    for ax, st, en, sd in zip(_static_ints(axes), _static_ints(starts),
                              _static_ints(ends), _static_ints(strides)):
        sl[ax] = _builtins.slice(st, en, sd)
    return x[tuple(sl)]


@op
def numel(x, name=None):
    return jnp.asarray(int(np.prod(x.shape)) if x.shape else 1, jnp.int64)


@op
def shape(input):
    return jnp.asarray(input.shape, jnp.int32)


@op
def increment(x, value=1.0, name=None):
    return x + jnp.asarray(value, x.dtype)


@op
def assign(x, output=None, name=None):
    return jnp.asarray(x)


@op
def bincount(x, weights=None, minlength=0, name=None):
    xn = np.asarray(x)
    length = max(int(xn.max()) + 1 if xn.size else 0, minlength)
    return jnp.bincount(jnp.asarray(xn), weights=weights, length=length)


@op
def histogram(input, bins=100, min=0, max=0, weight=None, density=False, name=None):
    lo, hi = float(min), float(max)
    if lo == 0.0 and hi == 0.0:
        a = np.asarray(input)
        lo, hi = float(a.min()), float(a.max())
    hist, _ = jnp.histogram(input.reshape(-1), bins=bins, range=(lo, hi),
                            weights=weight, density=density)
    return hist if density else hist.astype(jnp.int64)
