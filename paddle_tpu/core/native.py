"""ctypes loader for the native runtime core (csrc/ -> libpaddle_tpu_core.so).

TPU-native analogs of the reference's C++ runtime pieces:
  * TCPStore       — paddle/phi/core/distributed/store/tcp_store.h:121
  * shm ring       — mmap_allocator-based DataLoader shm channel
  * host tracer    — paddle/phi/api/profiler/event_tracing.h (HostTracer)

The library is compiled on demand with g++ (toolchain is part of the
image); if compilation is impossible the callers fall back to pure-Python
paths, so the framework never hard-fails on a missing compiler.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_lib = None
_lib_lock = threading.Lock()
_HERE = os.path.dirname(os.path.abspath(__file__))
_CSRC = os.path.normpath(os.path.join(_HERE, "..", "..", "csrc"))
_SO = os.path.join(_HERE, "libpaddle_tpu_core.so")


# the runtime-core sources only (csrc/Makefile SRCS) — csrc also holds
# separately-built libraries (inference_capi.cc links libpython) that
# must NOT be globbed into this .so
_CORE_SRCS = ("tcp_store.cc", "shm_ring.cc", "trace.cc")


def _core_srcs():
    srcs = [os.path.join(_CSRC, f) for f in _CORE_SRCS]
    return [s for s in srcs if os.path.exists(s)]


def _needs_build() -> bool:
    if not os.path.exists(_SO):
        return True
    so_mtime = os.path.getmtime(_SO)
    srcs = _core_srcs()
    if not srcs:
        return False  # installed without sources: use the shipped .so
    return any(os.path.getmtime(s) > so_mtime for s in srcs)


def _build() -> bool:
    srcs = _core_srcs()
    if not srcs:
        return False
    cmd = ["g++", "-O2", "-fPIC", "-std=c++17", "-pthread", "-shared",
           *srcs, "-o", _SO, "-lrt"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=300)
        return True
    except (subprocess.SubprocessError, FileNotFoundError):
        return False


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    c = ctypes
    # store
    lib.pt_store_server_start.restype = c.c_void_p
    lib.pt_store_server_start.argtypes = [c.c_int, c.POINTER(c.c_int)]
    lib.pt_store_server_stop.argtypes = [c.c_void_p]
    lib.pt_store_client_connect.restype = c.c_void_p
    lib.pt_store_client_connect.argtypes = [c.c_char_p, c.c_int, c.c_int]
    lib.pt_store_client_close.argtypes = [c.c_void_p]
    lib.pt_store_set.restype = c.c_int
    lib.pt_store_set.argtypes = [c.c_void_p, c.c_char_p, c.c_int,
                                 c.c_char_p, c.c_int]
    lib.pt_store_get.restype = c.c_int64
    lib.pt_store_get.argtypes = [c.c_void_p, c.c_char_p, c.c_int,
                                 c.POINTER(c.c_void_p)]
    lib.pt_store_add.restype = c.c_int64
    lib.pt_store_add.argtypes = [c.c_void_p, c.c_char_p, c.c_int, c.c_int64]
    lib.pt_store_wait.restype = c.c_int
    lib.pt_store_wait.argtypes = [c.c_void_p, c.c_char_p, c.c_int]
    lib.pt_store_check.restype = c.c_int
    lib.pt_store_check.argtypes = [c.c_void_p, c.c_char_p, c.c_int]
    lib.pt_store_delete.restype = c.c_int
    lib.pt_store_delete.argtypes = [c.c_void_p, c.c_char_p, c.c_int]
    lib.pt_store_num_keys.restype = c.c_int64
    lib.pt_store_num_keys.argtypes = [c.c_void_p]
    lib.pt_free.argtypes = [c.c_void_p]
    # ring
    lib.pt_ring_create.restype = c.c_void_p
    lib.pt_ring_create.argtypes = [c.c_char_p, c.c_uint64]
    lib.pt_ring_attach.restype = c.c_void_p
    lib.pt_ring_attach.argtypes = [c.c_char_p]
    lib.pt_ring_push.restype = c.c_int
    lib.pt_ring_push.argtypes = [c.c_void_p, c.c_char_p, c.c_uint64, c.c_int]
    lib.pt_ring_pop.restype = c.c_int64
    lib.pt_ring_pop.argtypes = [c.c_void_p, c.POINTER(c.c_void_p), c.c_int]
    lib.pt_ring_size.restype = c.c_uint64
    lib.pt_ring_size.argtypes = [c.c_void_p]
    lib.pt_ring_close.argtypes = [c.c_void_p]
    lib.pt_ring_free.argtypes = [c.c_void_p]
    # trace
    lib.pt_trace_enable.argtypes = [c.c_int]
    lib.pt_trace_enabled.restype = c.c_int
    lib.pt_trace_begin.argtypes = [c.c_char_p]
    lib.pt_trace_instant.argtypes = [c.c_char_p]
    lib.pt_trace_count.restype = c.c_int64
    lib.pt_trace_export.restype = c.c_int
    lib.pt_trace_export.argtypes = [c.c_char_p, c.c_int64]
    return lib


def load():
    """Return the native library, building it if needed; None on failure."""
    global _lib
    if _lib is not None:
        return _lib if _lib is not False else None
    with _lib_lock:
        if _lib is not None:
            return _lib if _lib is not False else None
        if _needs_build() and not _build():
            _lib = False
            return None
        try:
            _lib = _bind(ctypes.CDLL(_SO))
        except OSError:
            _lib = False
            return None
        return _lib


def available() -> bool:
    return load() is not None


class TCPStore:
    """Rendezvous KV store (API shape of paddle.distributed's TCPStore;
    reference tcp_store.h:121).  The master rank runs the embedded server."""

    def __init__(self, host: str, port: int, is_master: bool = False,
                 world_size: int = 1, timeout: float = 300.0):
        lib = load()
        if lib is None:
            raise RuntimeError("native core unavailable (g++ build failed)")
        self._lib = lib
        self._server = None
        self.host, self.port = host, int(port)
        self.world_size = world_size
        if is_master:
            out_port = ctypes.c_int(0)
            self._server = lib.pt_store_server_start(
                self.port, ctypes.byref(out_port))
            if not self._server:
                raise RuntimeError(f"TCPStore: cannot bind port {port}")
            self.port = out_port.value
        self._client = lib.pt_store_client_connect(
            host.encode(), self.port, int(timeout * 1000))
        if not self._client:
            if self._server:
                lib.pt_store_server_stop(self._server)
            raise RuntimeError(f"TCPStore: cannot connect {host}:{self.port}")

    def set(self, key: str, value) -> None:
        v = value if isinstance(value, bytes) else str(value).encode()
        k = key.encode()
        if self._lib.pt_store_set(self._client, k, len(k), v, len(v)) != 0:
            raise RuntimeError("TCPStore.set failed")

    def get(self, key: str) -> bytes:
        k = key.encode()
        out = ctypes.c_void_p()
        n = self._lib.pt_store_get(self._client, k, len(k), ctypes.byref(out))
        if n < 0:
            raise RuntimeError("TCPStore.get failed")
        try:
            return ctypes.string_at(out, n)
        finally:
            self._lib.pt_free(out)

    def add(self, key: str, delta: int) -> int:
        k = key.encode()
        v = self._lib.pt_store_add(self._client, k, len(k), int(delta))
        if v == -(2 ** 63):
            raise RuntimeError("TCPStore.add failed")
        return v

    def wait(self, keys) -> None:
        if isinstance(keys, str):
            keys = [keys]
        for key in keys:
            k = key.encode()
            if self._lib.pt_store_wait(self._client, k, len(k)) != 0:
                raise RuntimeError(f"TCPStore.wait({key}) failed")

    def check(self, key: str) -> bool:
        k = key.encode()
        r = self._lib.pt_store_check(self._client, k, len(k))
        if r < 0:
            raise RuntimeError("TCPStore.check failed")
        return bool(r)

    def delete_key(self, key: str) -> bool:
        k = key.encode()
        return self._lib.pt_store_delete(self._client, k, len(k)) > 0

    def num_keys(self) -> int:
        return self._lib.pt_store_num_keys(self._client)

    def barrier(self, tag: str = "default") -> None:
        """All world_size participants arrive, then proceed.  Reusable: each
        call on a tag is a new round (keys are round-scoped)."""
        rounds = getattr(self, "_barrier_rounds", None)
        if rounds is None:
            rounds = self._barrier_rounds = {}
        r = rounds.get(tag, 0)
        rounds[tag] = r + 1
        n = self.add(f"__barrier/{tag}/{r}/arrived", 1)
        if n >= self.world_size:
            self.set(f"__barrier/{tag}/{r}/go", b"1")
        self.wait(f"__barrier/{tag}/{r}/go")

    def close(self) -> None:
        if getattr(self, "_client", None):
            self._lib.pt_store_client_close(self._client)
            self._client = None
        if getattr(self, "_server", None):
            self._lib.pt_store_server_stop(self._server)
            self._server = None

    def __del__(self):  # pragma: no cover - gc timing
        try:
            self.close()
        except Exception:
            pass


class ShmRing:
    """Blocking byte-record ring over POSIX shm (create or attach)."""

    def __init__(self, name: str, capacity: int = 0, create: bool = False):
        lib = load()
        if lib is None:
            raise RuntimeError("native core unavailable")
        self._lib = lib
        self.name = name
        if create:
            self._h = lib.pt_ring_create(name.encode(), capacity)
        else:
            self._h = lib.pt_ring_attach(name.encode())
        if not self._h:
            raise RuntimeError(f"ShmRing: cannot open {name!r}")

    def push(self, data: bytes, timeout: float | None = None) -> None:
        t = -1 if timeout is None else int(timeout * 1000)
        rc = self._lib.pt_ring_push(self._h, data, len(data), t)
        if rc == -1:
            raise TimeoutError("ShmRing.push timeout")
        if rc == -2:
            raise BrokenPipeError("ShmRing closed")
        if rc == -3:
            raise ValueError("record larger than ring capacity")

    def pop(self, timeout: float | None = None) -> bytes:
        t = -1 if timeout is None else int(timeout * 1000)
        out = ctypes.c_void_p()
        n = self._lib.pt_ring_pop(self._h, ctypes.byref(out), t)
        if n == -1:
            raise TimeoutError("ShmRing.pop timeout")
        if n == -2:
            raise EOFError("ShmRing closed and drained")
        try:
            return ctypes.string_at(out, n)
        finally:
            self._lib.pt_free(out)

    def qsize(self) -> int:
        return self._lib.pt_ring_size(self._h)

    def close(self) -> None:
        if getattr(self, "_h", None):
            self._lib.pt_ring_close(self._h)

    def free(self) -> None:
        if getattr(self, "_h", None):
            self._lib.pt_ring_free(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover - gc timing
        try:
            self.free()
        except Exception:
            pass
