"""Native runtime core (C++ via ctypes) with pure-Python fallbacks.

Reference analogs: TCPStore (paddle/phi/core/distributed/store/tcp_store.h),
DataLoader shm channel (mmap_allocator), HostTracer profiler events.
"""
from .native import TCPStore, ShmRing, available, load

__all__ = ["TCPStore", "ShmRing", "available", "load"]
