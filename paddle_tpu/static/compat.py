"""paddle.static namespace completion (reference:
python/paddle/static/__init__.py): static autodiff surface, program
serialization, EMA, metrics ops, py_func, device-place helpers."""
from __future__ import annotations

import os
import pickle

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor
from ..nn.layer import ParamAttr
from . import graph
from .graph import Program, Variable, default_main_program

__all__ = [
    "append_backward", "gradients", "create_parameter", "create_global_var",
    "accuracy", "auc", "ctr_metric_bundle", "Print", "py_func",
    "BuildStrategy", "CompiledProgram", "ExponentialMovingAverage",
    "WeightNormParamAttr", "serialize_program", "deserialize_program",
    "serialize_persistables", "deserialize_persistables", "save_to_file",
    "load_from_file", "normalize_program", "load_program_state",
    "set_program_state", "cuda_places", "xpu_places", "IpuStrategy",
    "IpuCompiledProgram", "ipu_shard_guard", "set_ipu_shard",
]


# ------------------------------------------------------------ autodiff

def _grad_var(program, loss_var, wrt, name):
    v = Variable(program, np.shape(wrt._data) if isinstance(wrt, Tensor)
                 else wrt.shape,
                 wrt._data.dtype if isinstance(wrt, Tensor) else wrt.dtype,
                 name=name, source=("__grad__", (loss_var, wrt), {}, 1))
    program.vars[v.name] = v
    return v


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None):
    """Static backward build (reference python/paddle/base/backward.py
    append_backward): returns [(param, grad_var)] pairs whose grad_var is
    fetchable from Executor.run."""
    program = loss.program
    params = parameter_list or [
        p for p in program.all_parameters() if not p.stop_gradient]
    pairs = []
    for p in params:
        if no_grad_set and p.name in no_grad_set:
            continue
        g = _grad_var(program, loss, p, f"{p.name}@GRAD")
        pairs.append((p, g))
    program.version += 1
    return pairs


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """Static grads of sum(targets) w.r.t. inputs (reference
    base/backward.py gradients); target_gradients weight each target's
    cotangent."""
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if target_gradients is not None:
        import paddle_tpu as P
        tgs = target_gradients if isinstance(
            target_gradients, (list, tuple)) else [target_gradients]
        targets = [P.multiply(t_, g_) if g_ is not None else t_
                   for t_, g_ in zip(targets, tgs)]
    loss = targets[0]
    for extra in targets[1:]:
        import paddle_tpu as P
        loss = P.add(P.sum(loss), P.sum(extra))
    outs = []
    for x in inputs:
        if no_grad_set and getattr(x, "name", None) in no_grad_set:
            outs.append(None)
            continue
        outs.append(_grad_var(loss.program, loss, x,
                              f"{getattr(x, 'name', 'x')}@GRAD"))
    loss.program.version += 1
    return outs


# ----------------------------------------------------- vars and metrics

def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from ..nn.layer import Layer
    helper = Layer()
    p = helper.create_parameter(list(shape), attr=attr, dtype=dtype,
                                is_bias=is_bias,
                                default_initializer=default_initializer)
    if name:
        p.name = name
    default_main_program()._note_param(p)
    return p


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    t = Tensor(jnp.full(tuple(shape), value,
                        jnp.dtype(np.dtype(dtype))))
    t.persistable = persistable
    if name:
        t.name = name
    return t


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Top-k accuracy op (reference static/nn/metric.py accuracy)."""
    from ..ops.registry import apply_op

    def body(inp, lab):
        topk = jax.lax.top_k(inp, k)[1]
        lab2 = lab.reshape(-1, 1)
        hit = jnp.any(topk == lab2, axis=1)
        return jnp.mean(hit.astype(jnp.float32))

    return apply_op("accuracy", body, (input, label), {})


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1, name=None):
    """Batch AUC (reference static/nn/metric.py auc) — exact rank-based
    ROC-AUC over the batch."""
    from ..ops.registry import apply_op

    def body(inp, lab):
        score = inp[:, 1] if inp.ndim == 2 and inp.shape[1] == 2 \
            else inp.reshape(-1)
        y = lab.reshape(-1).astype(jnp.float32)
        order = jnp.argsort(score)
        ranks = jnp.empty_like(order).at[order].set(
            jnp.arange(1, score.shape[0] + 1))
        pos = jnp.sum(y)
        neg = y.shape[0] - pos
        sum_rank_pos = jnp.sum(ranks * y)
        auc_v = (sum_rank_pos - pos * (pos + 1) / 2) / \
            jnp.maximum(pos * neg, 1.0)
        return auc_v.astype(jnp.float32)

    a = apply_op("auc", body, (input, label), {})
    return a, [a]


def ctr_metric_bundle(input, label, ins_tag_weight=None):
    """(reference static/nn/metric.py ctr_metric_bundle): returns
    (auc, batch_auc, [stats...]) — the sparse-PS bundle reduced to its
    dense equivalents."""
    a, _ = auc(input, label)
    return a, a, [a]


# ------------------------------------------------------------------ ops

def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=True, print_phase="both"):
    """Debug print op (reference static/nn/common.py Print): host print
    via jax.debug.print; identity on data."""
    from ..ops.registry import apply_op

    def body(x):
        jax.debug.print((message or "Print") + ": {x}", x=x)
        return x

    return apply_op("print", body, (input,), {})


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Host-python op (reference static/nn/common.py py_func; custom-op C
    ABI analog).  Runs func on host via pure_callback."""
    from ..ops.registry import apply_op
    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = out if isinstance(out, (list, tuple)) else [out]
    shapes = [jax.ShapeDtypeStruct(tuple(o.shape), np.dtype(o.dtype)
                                   if not isinstance(o, Tensor)
                                   else o._data.dtype) for o in outs]

    def body(*arrs):
        res = jax.pure_callback(
            lambda *a: func(*[np.asarray(x_) for x_ in a]),
            shapes if len(shapes) > 1 else shapes[0], *arrs)
        return res

    return apply_op("py_func", body, tuple(xs), {})


# ----------------------------------------------------------- strategies

class BuildStrategy:
    """Graph-build options (reference framework/details/build_strategy.h).
    XLA owns fusion/memory decisions; fields are accepted and recorded."""

    def __init__(self):
        self.enable_inplace = True
        self.fuse_elewise_add_act_ops = False
        self.fuse_bn_act_ops = False
        self.memory_optimize = True
        self.reduce_strategy = 0
        self.build_cinn_pass = False


class CompiledProgram:
    """(reference base/compiler.py CompiledProgram): the Executor jit-caches
    per feed signature, so this is a recorded wrapper."""

    def __init__(self, program, build_strategy=None):
        self._program = program
        self._build_strategy = build_strategy or BuildStrategy()

    def __getattr__(self, item):
        return getattr(self._program, item)


class ExponentialMovingAverage:
    """EMA of trainable parameters (reference static/ema.py): update()
    after each step; apply()/restore() swap shadow weights in and out."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._shadow = {}
        self._backup = {}
        self._step = 0
        # capture the trainable params of the program being built NOW;
        # params register on first op capture, so keep extending lazily
        # (reference ema.py walks the current default program)
        self._program = default_main_program()
        self._captured = []
        self._recapture()

    def _recapture(self):
        seen = {id(p) for p in self._captured}
        for p in self._program.all_parameters():
            if not p.stop_gradient and id(p) not in seen:
                self._captured.append(p)

    def _params(self):
        self._recapture()
        return self._captured

    def update(self):
        self._step += 1
        d = min(self._decay, (1 + self._step) / (10 + self._step))
        for p in self._params():
            key = p.name
            prev = self._shadow.get(key, p._data)
            self._shadow[key] = d * prev + (1 - d) * p._data

    class _Guard:
        def __init__(self, ema, executor=None, need_restore=True):
            self._ema = ema
            self._need_restore = need_restore

        def __enter__(self):
            self._ema.apply_now()
            return self

        def __exit__(self, *e):
            if self._need_restore:
                self._ema.restore_now()
            return False

    def apply(self, executor=None, need_restore=True):
        return ExponentialMovingAverage._Guard(self, executor, need_restore)

    def apply_now(self):
        for p in self._params():
            if p.name in self._shadow:
                self._backup[p.name] = p._data
                p._data = self._shadow[p.name].astype(p._data.dtype)

    def restore_now(self):
        for p in self._params():
            if p.name in self._backup:
                p._data = self._backup.pop(p.name)

    def restore(self, executor=None):
        self.restore_now()


class WeightNormParamAttr(ParamAttr):
    """(reference static/nn/common.py WeightNormParamAttr): records the
    weight-norm dim; applied via paddle.nn.utils.weight_norm semantics."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        super().__init__(name=name, initializer=initializer,
                         learning_rate=learning_rate,
                         regularizer=regularizer, trainable=trainable,
                         need_clip=need_clip)
        self.dim = dim


# -------------------------------------------------------- serialization

def _encode_obj(x, body_to_name, node_ids):
    from .graph import Variable as _V
    if isinstance(x, _V):
        return ("__var__", x.name)
    if isinstance(x, Tensor):
        from ..nn.layer import Parameter
        return ("__tensor__", np.asarray(x._data),
                isinstance(x, Parameter), getattr(x, "name", None))
    if isinstance(x, (list, tuple)):
        kind = "__list__" if isinstance(x, list) else "__tuple__"
        return (kind, [_encode_obj(e, body_to_name, node_ids) for e in x])
    if isinstance(x, dict):
        return ("__dict__", {k: _encode_obj(v, body_to_name, node_ids)
                             for k, v in x.items()})
    return ("__lit__", x)


def _decode_obj(enc, vars_map, param_cache):
    kind = enc[0]
    if kind == "__var__":
        return vars_map[enc[1]]
    if kind == "__tensor__":
        _, arr, is_param, pname = enc
        key = (pname, arr.shape, str(arr.dtype))
        if key in param_cache:
            return param_cache[key]
        if is_param:
            from ..nn.layer import Parameter
            t = Parameter(jnp.asarray(arr), name=pname)
        else:
            t = Tensor(jnp.asarray(arr))
        param_cache[key] = t
        return t
    if kind == "__list__":
        return [_decode_obj(e, vars_map, param_cache) for e in enc[1]]
    if kind == "__tuple__":
        return tuple(_decode_obj(e, vars_map, param_cache) for e in enc[1])
    if kind == "__dict__":
        return {k: _decode_obj(v, vars_map, param_cache)
                for k, v in enc[1].items()}
    return enc[1]


def serialize_program(feed_vars=None, fetch_vars=None, program=None,
                      **kwargs):
    """Pickle a Program by op NAME (op bodies resolve through the registry
    at load; reference static/io.py serialize_program serializes the
    ProgramDesc proto the same way — by op type, not code)."""
    from ..ops.registry import OPS
    prog = program or default_main_program()
    body_to_name = {id(w.__op_body__): n for n, w in OPS.items()
                    if hasattr(w, "__op_body__")}
    nodes = {}
    vars_enc = {}
    for name, v in prog.vars.items():
        if v.source is None:
            src = None
        else:
            body = v.source[0]
            if id(v.source) not in nodes:
                opname = body_to_name.get(id(body))
                if opname is None:
                    raise ValueError(
                        f"cannot serialize op {getattr(body, '__name__', body)!r}: "
                        "only registry ops are serializable (custom local "
                        "bodies have no stable name)")
                nodes[id(v.source)] = {
                    "op": opname,
                    "args": _encode_obj(list(v.source[1]), body_to_name,
                                        nodes),
                    "kwargs": _encode_obj(dict(v.source[2]), body_to_name,
                                          nodes),
                    "n_outs": v.source[3]}
            src = id(v.source)
        vars_enc[name] = {"shape": list(v.shape), "dtype": str(v.dtype),
                          "out_index": v.out_index, "source": src}
    payload = {"vars": vars_enc, "nodes": nodes,
               "feed": list(prog.feed_vars.keys())}
    return pickle.dumps(payload)


def deserialize_program(data):
    from ..ops.registry import OPS
    from .graph import Program as _P, Variable as _V
    payload = pickle.loads(data)
    prog = _P()
    vars_map = {}
    for name, ve in payload["vars"].items():
        v = _V(prog, ve["shape"], ve["dtype"], name=name, source=None,
               out_index=ve["out_index"])
        prog.vars[name] = v
        vars_map[name] = v
    param_cache = {}
    node_cache = {}
    for name, ve in payload["vars"].items():
        if ve["source"] is None:
            continue
        nid = ve["source"]
        if nid not in node_cache:
            ne = payload["nodes"][nid]
            body = OPS[ne["op"]].__op_body__
            args = _decode_obj(ne["args"], vars_map, param_cache)
            kwargs = _decode_obj(ne["kwargs"], vars_map, param_cache)
            node_cache[nid] = (body, tuple(args), kwargs, ne["n_outs"])
        vars_map[name].source = node_cache[nid]
    for t in param_cache.values():
        from ..nn.layer import Parameter
        if isinstance(t, Parameter):
            prog._note_param(t)
    for fname in payload["feed"]:
        if fname in vars_map:
            prog.feed_vars[fname] = vars_map[fname]
    return prog


def serialize_persistables(feed_vars=None, fetch_vars=None, program=None,
                           **kwargs):
    prog = program or default_main_program()
    state = {p.name: np.asarray(p._data) for p in prog.all_parameters()}
    return pickle.dumps(state)


def deserialize_persistables(program, data, executor=None):
    state = pickle.loads(data)
    for p in program.all_parameters():
        if p.name in state:
            p._data = jnp.asarray(state[p.name])


def save_to_file(path, content):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path):
    with open(path, "rb") as f:
        return f.read()


def normalize_program(program, feed_vars, fetch_vars, **kwargs):
    """(reference static/io.py normalize_program): prune to the
    feed->fetch slice.  Evaluation is already demand-driven from fetches,
    so the program is returned as-is."""
    return program


def load_program_state(model_path, var_list=None):
    from ..framework.io import load
    path = model_path if model_path.endswith(".pdparams") \
        else model_path + ".pdparams"
    state = load(path)
    return {k: np.asarray(v._data if isinstance(v, Tensor) else v)
            for k, v in state.items()}


def set_program_state(program, state_dict):
    for p in program.all_parameters():
        if p.name in state_dict:
            p._data = jnp.asarray(state_dict[p.name]).astype(p._data.dtype)


# ------------------------------------------------------------ places/IPU

def cuda_places(device_ids=None):
    from ..device import CUDAPlace
    ids = device_ids if device_ids is not None else [0]
    return [CUDAPlace(i) for i in ids]


def xpu_places(device_ids=None):
    from ..device import XPUPlace
    ids = device_ids if device_ids is not None else [0]
    return [XPUPlace(i) for i in ids]


def _ipu_stub(name):
    class _Stub:
        def __init__(self, *a, **k):
            raise NotImplementedError(
                f"{name}: Graphcore IPU support has no TPU analog "
                "(reference static/__init__.py Ipu*)")
    _Stub.__name__ = name
    return _Stub


IpuStrategy = _ipu_stub("IpuStrategy")
IpuCompiledProgram = _ipu_stub("IpuCompiledProgram")


class ipu_shard_guard:
    def __init__(self, index=-1, stage=-1):
        raise NotImplementedError(
            "ipu_shard_guard: Graphcore IPU support has no TPU analog")


def set_ipu_shard(layer, index=-1, stage=-1):
    raise NotImplementedError(
        "set_ipu_shard: Graphcore IPU support has no TPU analog")
