"""Static Executor over the Program DAG.

Reference analog: python/paddle/base/executor.py (Executor:1234,
run:1695, _ExecutorCache:871) driving C++ StandaloneExecutor
(standalone_executor.cc:171).  Here "build the Plan" = compile the
fetched DAG slice with jax.jit (cached per program version + feed
signature); parameter updates from recorded train ops reuse the dygraph
optimizers by handing them jax-computed grads.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from . import graph
from ..framework.tensor import Tensor

__all__ = ["Executor", "scope_guard", "global_scope"]


class _Scope:
    def __init__(self):
        self._vars = {}

    def var(self, name):
        return self._vars.setdefault(name, None)

    def find_var(self, name):
        return self._vars.get(name)


_global_scope = _Scope()


def global_scope():
    return _global_scope


class scope_guard:
    def __init__(self, scope):
        self.scope = scope

    def __enter__(self):
        return self.scope

    def __exit__(self, *e):
        return False


class Executor:
    def __init__(self, place=None):
        self.place = place
        self._cache = {}

    def run(self, program=None, feed=None, fetch_list=None,
            return_numpy=True, scope=None):
        feed = feed or {}
        if program is None:
            program = graph.default_main_program()
        if program is graph.default_startup_program() or (
                isinstance(program, graph.Program)
                and not program.vars and not program.train_ops):
            # startup: parameters were initialized eagerly at layer
            # construction — nothing to run
            return []
        fetch_list = fetch_list or []
        fetch_vars = []
        for v in fetch_list:
            if isinstance(v, graph.Variable):
                fetch_vars.append(v)
            elif isinstance(v, str):
                if v not in program.vars:
                    raise KeyError(f"fetch name {v!r} not in program")
                fetch_vars.append(program.vars[v])
            else:
                raise TypeError(
                    f"fetch_list entries must be Variable or name, got "
                    f"{type(v).__name__}")

        feed_arrays = {k: jnp.asarray(np.asarray(v._data if isinstance(
            v, Tensor) else v)) for k, v in feed.items()}

        if program.train_ops:
            results = self._run_train(program, feed_arrays, fetch_vars)
        else:
            results = self._run_infer(program, feed_arrays, fetch_vars)

        if return_numpy:
            results = [np.asarray(r) for r in results]
        return results

    # ------------------------------------------------------------ infer
    def _cache_key(self, program, feed_arrays, fetch_vars, train):
        sig = tuple(sorted((k, v.shape, str(v.dtype))
                           for k, v in feed_arrays.items()))
        return (id(program), program.version, train,
                tuple(v.name for v in fetch_vars), sig)

    def _run_infer(self, program, feed_arrays, fetch_vars):
        key = self._cache_key(program, feed_arrays, fetch_vars, False)
        params = program.all_parameters()
        stat_bufs = [b for b, _ in program.stat_updates]
        stat_vars = [v for _, v in program.stat_updates]
        if key not in self._cache:
            def fn(feed, param_arrays, stat_arrays, rng_key):
                from ..framework import random as _random
                pmap = {id(p): a for p, a in zip(params, param_arrays)}
                pmap.update(
                    {id(b): a for b, a in zip(stat_bufs, stat_arrays)})
                with _random.trace_key_guard(rng_key):
                    outs = graph.evaluate(fetch_vars + stat_vars, feed, pmap)
                n = len(fetch_vars)
                return outs[:n], outs[n:]
            self._cache[key] = jax.jit(fn)
        from ..framework import random as _random
        outs, stats = self._cache[key](feed_arrays,
                                       [p._data for p in params],
                                       [b._data for b in stat_bufs],
                                       _random.default_generator.split())
        self._apply_stats(stat_bufs, stats)
        return outs

    @staticmethod
    def _apply_stats(stat_bufs, stats):
        # running-stat side effects (reference: the in-graph stat-update
        # ops static batch_norm appends)
        for b, new in zip(stat_bufs, stats):
            b._data = new

    # ------------------------------------------------------------ train
    def _run_train(self, program, feed_arrays, fetch_vars):
        optimizer, loss_var = program.train_ops[-1]
        params = [p for p in program.all_parameters() if not p.stop_gradient]
        stat_bufs = [b for b, _ in program.stat_updates]
        stat_vars = [v for _, v in program.stat_updates]
        key = self._cache_key(program, feed_arrays, fetch_vars, True)
        if key not in self._cache:
            def fwd(param_arrays, feed, stat_arrays, rng_key):
                from ..framework import random as _random
                pmap = {id(p): a for p, a in zip(params, param_arrays)}
                pmap.update(
                    {id(b): a for b, a in zip(stat_bufs, stat_arrays)})
                with _random.trace_key_guard(rng_key):
                    outs = graph.evaluate(
                        [loss_var] + fetch_vars + stat_vars, feed, pmap)
                n = 1 + len(fetch_vars)
                return outs[0].astype(jnp.float32).sum(), \
                    (outs[1:n], outs[n:])

            self._cache[key] = jax.jit(
                jax.value_and_grad(fwd, has_aux=True))
        from ..framework import random as _random
        (loss, (fetches, stats)), grads = self._cache[key](
            [p._data for p in params], feed_arrays,
            [b._data for b in stat_bufs],
            _random.default_generator.split())
        self._apply_stats(stat_bufs, stats)
        # hand grads to the dygraph optimizer (reference: the appended
        # optimizer ops in the static program do this in-graph)
        for p, g in zip(params, grads):
            p._grad = g
        optimizer.step()
        optimizer.clear_grad()
        # fetches is aligned with fetch_vars (loss was outs[0], dropped)
        return list(fetches)
