"""paddle.static compat surface.

Reference: python/paddle/static/ (Program/Executor/data/nn, 24.9k LoC).
The TPU rebuild keeps the API shape; the execution substrate is the jax
DAG recorder in graph.py + jit compile in executor.py (SURVEY §8: PIR +
StandaloneExecutor collapse into jaxpr + XLA executable).
"""
from __future__ import annotations

import numpy as np

from .graph import (  # noqa: F401
    Program, Variable, program_guard, default_main_program,
    default_startup_program, data)
from .executor import Executor, scope_guard, global_scope  # noqa: F401
from .compat import (  # noqa: F401
    append_backward, gradients, create_parameter, create_global_var,
    accuracy, auc, ctr_metric_bundle, Print, py_func, BuildStrategy,
    CompiledProgram, ExponentialMovingAverage, WeightNormParamAttr,
    serialize_program, deserialize_program, serialize_persistables,
    deserialize_persistables, save_to_file, load_from_file,
    normalize_program, load_program_state, set_program_state, cuda_places,
    xpu_places, IpuStrategy, IpuCompiledProgram, ipu_shard_guard,
    set_ipu_shard)
from ..jit.api import InputSpec  # noqa: F401
from . import nn  # noqa: F401

__all__ = ["Program", "Variable", "program_guard", "default_main_program",
           "default_startup_program", "data", "Executor", "scope_guard",
           "global_scope", "InputSpec", "nn", "name_scope", "save", "load",
           "save_inference_model", "load_inference_model", "cpu_places",
           "device_guard"]


class name_scope:
    def __init__(self, prefix=None):
        self.prefix = prefix

    def __enter__(self):
        return self

    def __exit__(self, *e):
        return False


def cpu_places(device_count=None):
    import jax
    n = device_count or len(jax.devices())
    return list(range(n))


class device_guard:
    def __init__(self, device=None):
        self.device = device

    def __enter__(self):
        return self

    def __exit__(self, *e):
        return False


def save(program, model_path, protocol=4, **configs):
    """Persist a Program's parameters (reference:
    python/paddle/static/io.py save -> .pdparams/.pdopt)."""
    import os
    import pickle

    d = os.path.dirname(model_path)
    if d:
        os.makedirs(d, exist_ok=True)
    params = {f"p{i}": np.asarray(p._data)
              for i, p in enumerate(program.all_parameters())}
    with open(model_path + ".pdparams", "wb") as f:
        pickle.dump(params, f, protocol=protocol)


def load(program, model_path, executor=None, var_list=None):
    """Restore parameters saved by static.save into the SAME program
    structure (positional match, like the reference's name match).
    var_list restricts the restore to those parameter tensors."""
    import pickle

    import jax.numpy as jnp

    with open(model_path + ".pdparams", "rb") as f:
        params = pickle.load(f)
    n_prog = len(program.all_parameters())
    if len(params) != n_prog:
        raise ValueError(
            f"checkpoint has {len(params)} parameters but the program "
            f"has {n_prog}; static.load requires the same program "
            "structure it was saved from")
    keep = None if var_list is None else {id(v) for v in var_list}
    for i, p in enumerate(program.all_parameters()):
        if keep is not None and id(p) not in keep:
            continue
        arr = params[f"p{i}"]
        if tuple(arr.shape) != tuple(p._data.shape):
            raise ValueError(
                f"param {i} shape mismatch: saved {arr.shape} vs program "
                f"{tuple(p._data.shape)}")
        p._data = jnp.asarray(arr, p._data.dtype)


def _npz_pack(arrays):
    """npz-safe view of a param dict: numpy cannot round-trip extension
    dtypes (a bfloat16 array reloads as void bytes), so such arrays are
    stored as same-width uint bit patterns plus a ``<name>.dtype`` tag
    that :func:`_npz_unpack` uses to view them back."""
    out = {}
    for name, arr in arrays.items():
        arr = np.asarray(arr)
        if arr.dtype.kind == "V":           # ml_dtypes (bfloat16, fp8…)
            out[name] = arr.view(f"uint{arr.dtype.itemsize * 8}")
            out[name + ".dtype"] = np.asarray(arr.dtype.name)
        else:
            out[name] = arr
    return out


def _npz_unpack(pz, name):
    arr = pz[name]
    tag = name + ".dtype"
    if tag in pz.files:
        import ml_dtypes  # noqa: F401  (registers the dtype names)
        arr = arr.view(np.dtype(str(pz[tag])))
    return arr


def _npz_param_count(pz):
    import re
    return sum(1 for k in pz.files if re.fullmatch(r"p\d+", k))


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor,
                         program=None, **kwargs):
    """Serialize the fetched DAG slice for deployment (reference:
    python/paddle/static/io.py save_inference_model -> .pdmodel/.pdiparams;
    here a pickled DAG + .npz params, executable by load_inference_model)."""
    import pickle

    program = program or default_main_program()
    feed_vars = feed_vars if isinstance(feed_vars, (list, tuple)) else \
        [feed_vars]
    fetch_vars = fetch_vars if isinstance(fetch_vars, (list, tuple)) else \
        [fetch_vars]
    params = program.all_parameters()
    pmap = {f"p{i}": np.asarray(p._data) for i, p in enumerate(params)}
    np.savez(path_prefix + ".pdiparams.npz", **_npz_pack(pmap))

    # swap concrete param tensors for symbolic markers before pickling
    from ..framework.tensor import Tensor

    def strip(obj, memo):
        if isinstance(obj, Tensor):
            for i, p in enumerate(params):
                if obj is p:
                    return ("__param__", i)
            return ("__const__", np.asarray(obj._data))
        if isinstance(obj, Variable):
            return ("__var__", obj.name)
        if isinstance(obj, (list, tuple)):
            t = [strip(x, memo) for x in obj]
            return tuple(t) if isinstance(obj, tuple) else t
        if isinstance(obj, dict):
            return {k: strip(v, memo) for k, v in obj.items()}
        return obj

    nodes = {}
    for v in program.vars.values():
        if v.source is None:
            nodes[v.name] = {"feed": True, "shape": v.shape,
                             "dtype": str(v.dtype)}
        else:
            body, args, kwargs_, n_outs = v.source
            nodes[v.name] = {
                "feed": False, "shape": v.shape, "dtype": str(v.dtype),
                "body": f"{body.__module__}:{body.__qualname__}",
                "args": strip(args, {}), "kwargs": strip(kwargs_, {}),
                "out_index": v.out_index, "n_outs": n_outs,
                "nid": id(v.source),   # sibling outputs share one node
            }
    meta = {
        "nodes": nodes,
        "feeds": [v.name for v in feed_vars],
        "fetches": [v.name for v in fetch_vars],
    }
    with open(path_prefix + ".pdmodel.pkl", "wb") as f:
        pickle.dump(meta, f)


def load_inference_model(path_prefix, executor=None, **kwargs):
    """Returns (program, feed_names, fetch_vars) per reference API."""
    import importlib
    import pickle

    from ..framework.tensor import Tensor

    with open(path_prefix + ".pdmodel.pkl", "rb") as f:
        meta = pickle.load(f)
    pz = np.load(path_prefix + ".pdiparams.npz")
    params = [Tensor(_npz_unpack(pz, f"p{i}"), stop_gradient=True)
              for i in range(_npz_param_count(pz))]

    prog = Program()
    made: dict[str, Variable] = {}

    def restore(obj):
        if isinstance(obj, tuple) and len(obj) == 2 and \
                obj[0] == "__param__":
            return params[obj[1]]
        if isinstance(obj, tuple) and len(obj) == 2 and \
                obj[0] == "__const__":
            return Tensor(obj[1], stop_gradient=True)
        if isinstance(obj, tuple) and len(obj) == 2 and obj[0] == "__var__":
            return build_var(obj[1])
        if isinstance(obj, (list, tuple)):
            t = [restore(x) for x in obj]
            return tuple(t) if isinstance(obj, tuple) else t
        if isinstance(obj, dict):
            return {k: restore(v) for k, v in obj.items()}
        return obj

    # sibling outputs of a multi-output op must share ONE source tuple so
    # graph.evaluate's sibling memoization (identity-keyed) works
    sources: dict[int, tuple] = {}

    def build_var(name):
        if name in made:
            return made[name]
        nd = meta["nodes"][name]
        if nd["feed"]:
            v = Variable(prog, nd["shape"], nd["dtype"], name=name)
            prog.feed_vars[name] = v
        else:
            if nd["nid"] not in sources:
                mod, qual = nd["body"].split(":")
                body = importlib.import_module(mod)
                for part in qual.split("."):
                    body = getattr(body, part)
                # module attrs hold the public @op wrapper under the
                # body's name; the graph stores/executes the pure body
                body = getattr(body, "__op_body__", body)
                sources[nd["nid"]] = (body, restore(nd["args"]),
                                      restore(nd["kwargs"]),
                                      nd.get("n_outs", 1))
            v = Variable(prog, nd["shape"], nd["dtype"], name=name,
                         source=sources[nd["nid"]],
                         out_index=nd["out_index"])
        made[name] = v
        prog.vars[name] = v
        return v

    for name in meta["nodes"]:
        build_var(name)
    for p in params:
        prog._note_param(p)
    fetch_vars = [made[n] for n in meta["fetches"]]
    return prog, meta["feeds"], fetch_vars
