"""Static-graph control flow: cond / while_loop / case / switch_case /
static_pylayer.

Reference: python/paddle/static/nn/control_flow.py (cond:1509,
while_loop:682, case:961, switch_case:1084, static_pylayer:1303) — the
reference records dedicated PIR control-flow ops
(paddle/fluid/pir/dialect/operator/ir/control_flow_op.cc) whose regions
hold sub-blocks.  TPU formulation: each branch/body is traced into a
sub-``Program`` (the region analog); the outer program records ONE node
whose evaluation lowers to ``jax.lax.cond`` / ``jax.lax.while_loop`` /
``jax.custom_vjp`` at executor-jit time, with captured outer Variables
bound by name through ``evaluate(env0=...)``.  Everything stays a single
XLA program — no host round-trips per branch.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from jax.tree_util import tree_flatten, tree_unflatten

from . import graph
from .graph import Program, Variable, program_guard, default_main_program

__all__ = ["cond", "while_loop", "case", "switch_case", "static_pylayer",
           "Print"]


def _is_leaf(x):
    from ..framework.tensor import Tensor
    return isinstance(x, (Variable, Tensor))


def _leaf_meta(x):
    """(shape, dtype) of an output leaf for building the outer Variable."""
    from ..framework.tensor import Tensor
    if isinstance(x, Variable):
        return list(x.shape), x.dtype
    if isinstance(x, Tensor):
        return list(x._data.shape), x._data.dtype
    a = np.asarray(x)
    return list(a.shape), a.dtype


def _node_ref_leaves(source):
    """Flat Variable/Tensor leaves a recorded node references (generic
    nodes flatten their args; control-flow nodes carry an explicit list in
    the third slot)."""
    tag = source[0]
    if isinstance(tag, str) and tag.startswith("__"):
        if tag == "__grad__":
            return [x for x in source[1] if _is_leaf(x)]
        return list(source[2] or [])
    _body, args, kwargs, _n = source
    flat, _ = tree_flatten((args, kwargs), is_leaf=_is_leaf)
    return [x for x in flat if _is_leaf(x)]


def _collect_externals(subs, exclude=(), extra_leaves=()):
    """Outer-scope Variables referenced by nodes of the sub-programs —
    plus the branch OUTPUT leaves (a branch may return a captured outer
    Variable directly, with no op recorded inside the region).  These
    are evaluated in the enclosing scope and bound by name inside the
    branch (the region's capture list)."""
    excl = {id(x) for x in exclude}
    ext, seen = [], set()

    def note(x):
        if isinstance(x, Variable) and id(x) not in seen \
                and id(x) not in excl and all(x.program is not s for s in subs):
            seen.add(id(x))
            ext.append(x)

    for sub in subs:
        for v in sub.vars.values():
            if v.source is None:
                continue
            for leaf in _node_ref_leaves(v.source):
                note(leaf)
    for leaf in extra_leaves:
        note(leaf)
    return ext


def _merge_params(sub, outer):
    for p in sub._param_refs:
        outer._note_param(p)


def _trace_subgraph(fn, args=()):
    """Run ``fn(*args)`` recording into a fresh sub-Program; returns
    (sub, flat_output_leaves, out_treedef)."""
    sub = Program()
    with program_guard(sub):
        outs = fn(*args)
    flat, treedef = tree_flatten(outs, is_leaf=_is_leaf)
    return sub, flat, treedef


def _record_ctrl(tag, payload, ref_leaves, out_metas, treedef, prog=None):
    """Append one control-flow node to the outer program and return its
    output Variables unflattened."""
    prog = prog or default_main_program()
    node = (tag, payload, list(ref_leaves), len(out_metas))
    outs = []
    for i, (shape, dtype) in enumerate(out_metas):
        v = Variable(prog, shape, dtype,
                     name=f"{tag.strip('_')}_{Variable._counter}",
                     source=node, out_index=i)
        v.stop_gradient = False
        prog.vars[v.name] = v
        outs.append(v)
    prog.version += 1
    return tree_unflatten(treedef, outs)


def cond(pred, true_fn=None, false_fn=None, name=None, return_names=None):
    """Branch on a boolean scalar (reference control_flow.py:1509).

    Both branches are traced as sub-programs and must return structures
    with matching shapes/dtypes (the same constraint the reference
    enforces via select_input); lowering is ``jax.lax.cond``.
    """
    if not isinstance(pred, Variable) and not graph._progs():
        # dygraph: plain python branch (reference does the same)
        flag = bool(pred.item() if hasattr(pred, "item") else pred)
        fn = true_fn if flag else false_fn
        return fn() if fn is not None else None

    sub_t, flat_t, tree_t = _trace_subgraph(true_fn or (lambda: None))
    sub_f, flat_f, tree_f = _trace_subgraph(false_fn or (lambda: None))
    if tree_t != tree_f:
        raise ValueError(
            "cond: true_fn and false_fn must return the same structure; "
            f"got {tree_t} vs {tree_f}")
    if not flat_t:
        return None
    metas_t = [_leaf_meta(x) for x in flat_t]
    metas_f = [_leaf_meta(x) for x in flat_f]
    for (st, dt), (sf, df) in zip(metas_t, metas_f):
        if [max(s, 1) for s in st] != [max(s, 1) for s in sf] \
                or jnp.dtype(dt) != jnp.dtype(df):
            raise ValueError(
                "cond: branch outputs must match in shape and dtype; got "
                f"{st}/{dt} vs {sf}/{df}")

    prog = default_main_program()
    _merge_params(sub_t, prog)
    _merge_params(sub_f, prog)
    ext = _collect_externals([sub_t, sub_f],
                             extra_leaves=list(flat_t) + list(flat_f))
    refs = [x for x in [pred] if _is_leaf(x)] + ext
    payload = (pred, flat_t, flat_f, ext)
    return _record_ctrl("__cond__", payload, refs, metas_t, tree_t, prog)


def while_loop(cond_fn, body_fn, loop_vars, is_test=False, name=None):
    """Static while loop (reference control_flow.py:682); lowering is
    ``jax.lax.while_loop``, so the carry must keep shapes/dtypes — the
    same invariance the reference demands of its loop-carried variables.

    Note: like ``jax.lax.while_loop``, the lowered loop is forward-only;
    training through a data-dependent-trip-count loop needs a bounded
    ``lax.scan`` formulation (use dy2static's converters for that).
    """
    if not graph._progs() and not any(
            isinstance(x, Variable)
            for x in tree_flatten(loop_vars, is_leaf=_is_leaf)[0]):
        # dygraph: honest python loop
        vals = loop_vars
        while True:
            c = cond_fn(*vals)
            if not bool(np.asarray(c.numpy() if hasattr(c, "numpy") else c)):
                break
            vals = body_fn(*vals)
            if not isinstance(vals, (list, tuple)):
                vals = [vals]
        return vals

    init_flat, init_tree = tree_flatten(loop_vars, is_leaf=_is_leaf)
    metas = [_leaf_meta(x) for x in init_flat]

    # one shared set of carry placeholders feeds BOTH traces so env0
    # name-binding hits them identically
    phprog = Program()
    phs = []
    for shape, dtype in metas:
        ph = Variable(phprog, shape, dtype)
        ph.stop_gradient = False
        phprog.vars[ph.name] = ph
        phs.append(ph)
    carried = tree_unflatten(init_tree, phs)
    if not isinstance(carried, (list, tuple)):
        carried = [carried]

    sub_c, flat_c, _ = _trace_subgraph(lambda: cond_fn(*carried))
    if len(flat_c) != 1:
        raise ValueError("while_loop: cond must return one boolean scalar")
    sub_b, flat_b, tree_b = _trace_subgraph(lambda: body_fn(*carried))
    if len(flat_b) != len(init_flat):
        raise ValueError(
            f"while_loop: body returned {len(flat_b)} values for "
            f"{len(init_flat)} loop_vars")
    for (s0, d0), x in zip(metas, flat_b):
        s1, d1 = _leaf_meta(x)
        if [max(s, 1) for s in s0] != [max(s, 1) for s in s1] \
                or jnp.dtype(d0) != jnp.dtype(d1):
            raise ValueError(
                "while_loop: loop_vars must keep shape/dtype across the "
                f"body; got {s0}/{d0} -> {s1}/{d1}")

    prog = default_main_program()
    _merge_params(sub_c, prog)
    _merge_params(sub_b, prog)
    ext = _collect_externals([sub_c, sub_b], exclude=phs,
                             extra_leaves=list(flat_c) + list(flat_b))
    refs = [x for x in init_flat if _is_leaf(x)] + ext
    payload = (flat_c[0], flat_b, phs, init_flat, ext)
    return _record_ctrl("__while__", payload, refs, metas, init_tree, prog)


def case(pred_fn_pairs, default=None, name=None):
    """First-match-wins chain (reference control_flow.py:961), desugared
    into nested ``cond`` records."""
    if not pred_fn_pairs:
        raise ValueError("case: pred_fn_pairs must be non-empty")
    pairs = list(pred_fn_pairs)
    if default is None:
        # reference semantics: the last pair's fn becomes the default
        # (and its pred is dropped — control_flow.py case pops it)
        _, default = pairs.pop()

    def build(i):
        if i >= len(pairs):
            return default()
        pred, fn = pairs[i]
        return cond(pred, fn, lambda: build(i + 1))

    return build(0)


def switch_case(branch_index, branch_fns, default=None, name=None):
    """Integer dispatch (reference control_flow.py:1084), desugared into
    an equality-cond chain (small fan-out; XLA folds it into a select
    tree)."""
    if isinstance(branch_fns, dict):
        items = sorted(branch_fns.items())
    else:
        fns = list(branch_fns)
        if fns and callable(fns[0]):
            items = list(enumerate(fns))
        else:
            items = [(int(k), f) for k, f in fns]
    if default is None:
        default = items.pop()[1]

    from ..ops.registry import apply_op

    def build(i):
        if i >= len(items):
            return default()
        idx, fn = items[i]
        eq = apply_op("equal",
                      lambda a, b: jnp.equal(a, jnp.asarray(b, a.dtype)),
                      (branch_index, idx), {})
        return cond(eq, fn, lambda: build(i + 1))

    return build(0)


def static_pylayer(forward_fn, inputs, backward_fn=None, name=None):
    """Custom-gradient region (reference control_flow.py:1303).

    forward_fn(*inputs) is traced as a sub-program; backward_fn receives
    one grad per forward output and must return one grad per input.
    Lowering wraps the region in ``jax.custom_vjp``.
    """
    inputs = list(inputs)
    in_metas = [_leaf_meta(x) for x in inputs]

    phprog = Program()
    in_phs = []
    for shape, dtype in in_metas:
        ph = Variable(phprog, shape, dtype)
        ph.stop_gradient = False
        phprog.vars[ph.name] = ph
        in_phs.append(ph)

    sub_f, flat_f, tree_f = _trace_subgraph(lambda: forward_fn(*in_phs))
    out_metas = [_leaf_meta(x) for x in flat_f]

    bwd_outs, g_phs, sub_b = None, [], None
    if backward_fn is not None:
        g_phs = []
        for shape, dtype in out_metas:
            ph = Variable(phprog, shape, dtype)
            ph.stop_gradient = False
            phprog.vars[ph.name] = ph
            g_phs.append(ph)
        sub_b, bwd_outs, _ = _trace_subgraph(lambda: backward_fn(*g_phs))
        if len(bwd_outs) != len(inputs):
            raise ValueError(
                f"static_pylayer: backward_fn returned {len(bwd_outs)} "
                f"grads for {len(inputs)} inputs")

    prog = default_main_program()
    _merge_params(sub_f, prog)
    subs = [sub_f]
    if sub_b is not None:
        _merge_params(sub_b, prog)
        subs.append(sub_b)
    ext = _collect_externals(subs, exclude=in_phs + g_phs,
                             extra_leaves=list(flat_f)
                             + list(bwd_outs or []))
    refs = [x for x in inputs if _is_leaf(x)] + ext
    payload = (flat_f, in_phs, inputs, bwd_outs, g_phs, ext)
    return _record_ctrl("__pylayer__", payload, refs, out_metas, tree_f,
                        prog)


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=True, print_phase="both"):
    """reference: python/paddle/static/nn/control_flow.py Print — debug
    passthrough via jax.debug.print at executor time."""
    import jax

    def body(x):
        jax.debug.print((message or "") + " {}", x)
        return x

    from ..ops.registry import apply_op
    return apply_op("print", body, (input,), {})
