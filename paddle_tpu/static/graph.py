"""Static-graph core: Program / Variable DAG + evaluation.

Reference analog: the PIR program + StandaloneExecutor pipeline
(paddle/pir/include/core/program.h, paddle/fluid/framework/new_executor/
standalone_executor.cc:171, python/paddle/base/framework.py Program) and
the `paddle.static` user API (python/paddle/static/).

TPU formulation: a Program is a recorded DAG of framework ops over
symbolic `Variable`s (captured by the op registry when a Variable flows
into an op — the analog of op capture into a pir::Block).  The executor
evaluates fetches by compiling the DAG slice into ONE `jax.jit` program
(cached per feed-shape signature), which is exactly the
PirInterpreter-over-kernels role XLA plays here.  Parameters stay
concrete `Parameter` tensors (the startup program is a no-op: eager
init), read at call time so optimizer updates are visible.
"""
from __future__ import annotations

import threading

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["Program", "Variable", "program_guard", "default_main_program",
           "default_startup_program", "data", "build_node", "in_build"]

_state = threading.local()

# flipped once the static API is touched; lets the hot eager op path skip
# the Variable scan entirely in pure-dygraph processes
_ever_static = False


def _mark_static():
    global _ever_static
    _ever_static = True


def _progs():
    if not hasattr(_state, "stack"):
        _state.stack = []
    return _state.stack


class Variable:
    """Symbolic SSA value (reference: pir::Value / base/framework.py
    Variable)."""

    _counter = 0

    def __init__(self, program, shape, dtype, name=None, source=None,
                 out_index=0):
        Variable._counter += 1
        self.program = program
        self.shape = list(shape)
        self.dtype = jnp.dtype(dtype)
        self.name = name or f"_var_{Variable._counter}"
        # source: None => feed slot; (body, args, kwargs, n_outs) => op node
        self.source = source
        self.out_index = out_index
        self.stop_gradient = source is None
        self.persistable = False

    # --- tensor-like surface so layers/ops can treat it like a Tensor ---
    @property
    def ndim(self):
        return len(self.shape)

    def dim(self):
        return len(self.shape)

    def astype(self, dt):
        from ..ops.manipulation import cast
        return cast(self, dt)

    def detach(self):
        # symbolic values carry no tape; gradient stopping is decided by
        # which leaves the executor differentiates
        return self

    def clone(self):
        return self

    def numpy(self):
        raise RuntimeError(
            "Variable has no data in static mode; fetch it via "
            "Executor.run(fetch_list=[...])")

    def __repr__(self):
        return (f"Variable(name={self.name}, shape={self.shape}, "
                f"dtype={self.dtype.name})")

    # python operators route back into framework ops (which re-enter
    # build_node via the registry's Variable check)
    def _binop(self, opname, other, reverse=False):
        from ..ops import math as O
        fn = getattr(O, opname)
        return fn(other, self) if reverse else fn(self, other)

    def __add__(self, o):
        return self._binop("add", o)

    __radd__ = __add__

    def __sub__(self, o):
        return self._binop("subtract", o)

    def __rsub__(self, o):
        return self._binop("subtract", o, reverse=True)

    def __mul__(self, o):
        return self._binop("multiply", o)

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binop("divide", o)

    def __rtruediv__(self, o):
        return self._binop("divide", o, reverse=True)

    def __matmul__(self, o):
        return self._binop("matmul", o)

    def flatten(self, start_axis=0, stop_axis=-1):
        from ..ops import manipulation as O
        return O.flatten(self, start_axis, stop_axis)

    def reshape(self, shape):
        from ..ops import manipulation as O
        return O.reshape(self, shape)

    def transpose(self, perm):
        from ..ops import manipulation as O
        return O.transpose(self, perm)

    def _cmp(self, opname, other):
        from ..ops import comparison as C
        return getattr(C, opname)(self, other)

    def __lt__(self, o):
        return self._cmp("less_than", o)

    def __le__(self, o):
        return self._cmp("less_equal", o)

    def __gt__(self, o):
        return self._cmp("greater_than", o)

    def __ge__(self, o):
        return self._cmp("greater_equal", o)

    def __neg__(self):
        from ..ops.math import scale
        return scale(self, -1.0)

    def __pow__(self, o):
        from ..ops.math import pow
        return pow(self, o)

    def __getitem__(self, idx):
        from ..ops.indexing import getitem
        return getitem(self, idx)


class Program:
    """Recorded op DAG (reference: base/framework.py Program:5706 /
    pir::Program)."""

    def __init__(self):
        self.vars: dict[str, Variable] = {}
        self.feed_vars: dict[str, Variable] = {}
        self.train_ops: list = []          # [(optimizer, loss_var)]
        self.stat_updates: list = []       # [(buffer Tensor, Variable)]
        self.version = 0
        self.random_seed = None
        self._param_refs: list = []        # Parameter tensors seen in ops

    def _note_param(self, p):
        if all(p is not q for q in self._param_refs):
            self._param_refs.append(p)

    def clone(self, for_test=False):
        p = Program()
        p.feed_vars = dict(self.feed_vars)
        p.train_ops = [] if for_test else list(self.train_ops)
        p.stat_updates = [] if for_test else list(self.stat_updates)
        p._param_refs = list(self._param_refs)
        if not for_test:
            p.vars = dict(self.vars)
            return p
        # test clone: rebuild the DAG with training=False baked into node
        # kwargs (reference: Program.clone(for_test=True) flips batch_norm
        # to global stats / disables dropout via the is_test attribute)
        from jax.tree_util import tree_flatten, tree_unflatten
        from ..framework.tensor import Tensor

        new_vars: dict[str, Variable] = {}
        new_sources: dict[int, tuple] = {}

        def remap_var(v):
            if v.name in new_vars:
                return new_vars[v.name]
            if v.source is None:
                nv = Variable(p, v.shape, v.dtype, name=v.name)
            else:
                if id(v.source) not in new_sources:
                    body, args, kwargs, n_outs = v.source
                    flat, td = tree_flatten(
                        (args, kwargs),
                        is_leaf=lambda x: isinstance(x, (Variable, Tensor)))
                    flat = [remap_var(x) if isinstance(x, Variable) else x
                            for x in flat]
                    a2, k2 = tree_unflatten(td, flat)
                    if isinstance(k2, dict) and "training" in k2:
                        k2 = dict(k2, training=False)
                    new_sources[id(v.source)] = (body, a2, k2, n_outs)
                nv = Variable(p, v.shape, v.dtype, name=v.name,
                              source=new_sources[id(v.source)],
                              out_index=v.out_index)
            new_vars[v.name] = nv
            return nv

        for v in self.vars.values():
            remap_var(v)
        p.vars = new_vars
        p.feed_vars = {k: new_vars[k] for k in self.feed_vars}
        return p

    def global_block(self):
        return self

    # Block-ish surface
    @property
    def ops(self):
        return [v for v in self.vars.values() if v.source is not None]

    def all_parameters(self):
        return list(self._param_refs)

    def list_vars(self):
        return list(self.vars.values())


class program_guard:
    """Reference: paddle.static.program_guard."""

    def __init__(self, main_program, startup_program=None):
        self.main = main_program
        self.startup = startup_program

    def __enter__(self):
        _mark_static()
        _progs().append(self.main)
        return self.main

    def __exit__(self, *exc):
        _progs().pop()
        return False


_default_main = Program()
_default_startup = Program()


def default_main_program():
    return _progs()[-1] if _progs() else _default_main


def default_startup_program():
    return _default_startup


def in_build():
    """True when a program_guard is active (static build mode)."""
    import paddle_tpu
    return bool(_progs()) or not paddle_tpu.in_dynamic_mode()


def data(name, shape, dtype="float32", lod_level=0):
    """Feed placeholder (reference: python/paddle/static/input.py data)."""
    _mark_static()
    prog = default_main_program()
    v = Variable(prog, [(-1 if s is None else s) for s in shape], dtype,
                 name=name)
    prog.vars[v.name] = v
    prog.feed_vars[name] = v
    prog.version += 1
    return v


def _placeholder_shape(shape):
    # -1/None dims become 1 for build-time shape inference only
    return tuple(1 if (s is None or s < 0) else int(s) for s in shape)


def build_node(opname, body, args, kwargs):
    """Record an op whose inputs include Variables; returns Variable(s).
    The registry calls this instead of executing (the analog of appending
    a pd_op to the current pir::Block)."""
    from jax.tree_util import tree_flatten, tree_unflatten
    from ..framework.tensor import Tensor

    prog = default_main_program()

    flat, treedef = tree_flatten((args, kwargs),
                                 is_leaf=lambda x: isinstance(
                                     x, (Variable, Tensor)))
    # abstract stand-ins for shape/dtype inference
    def stand_in(x):
        if isinstance(x, Variable):
            return jax.ShapeDtypeStruct(_placeholder_shape(x.shape), x.dtype)
        if isinstance(x, Tensor):
            from ..nn.layer import Parameter
            if isinstance(x, Parameter):
                prog._note_param(x)
            return jax.ShapeDtypeStruct(x._data.shape, x._data.dtype)
        return x

    abstract = [stand_in(x) for x in flat]

    def run_abstract(*leaves):
        a, k = tree_unflatten(treedef, list(leaves))
        return body(*a, **k)

    dyn_idx = [i for i, x in enumerate(abstract)
               if isinstance(x, jax.ShapeDtypeStruct)]
    dyn = [abstract[i] for i in dyn_idx]

    def fn(*dyn_vals):
        leaves = list(abstract)
        for i, v in zip(dyn_idx, dyn_vals):
            leaves[i] = v
        return run_abstract(*leaves)

    # shape inference must not advance (or trace-poison) the global RNG:
    # an RNG-consuming body under eval_shape would store a traced key
    # back into the generator — give split_key a scoped throwaway key
    from ..framework import random as _random
    with _random.trace_key_guard(jax.random.PRNGKey(0)):
        out_shape = jax.eval_shape(fn, *dyn)
    out_flat, out_treedef = tree_flatten(out_shape)

    outs = []
    node = (body, args, kwargs, len(out_flat))
    for i, aval in enumerate(out_flat):
        v = Variable(prog, aval.shape, aval.dtype,
                     name=f"{opname}_{Variable._counter}",
                     source=node, out_index=i)
        prog.vars[v.name] = v
        outs.append(v)
    prog.version += 1
    return tree_unflatten(out_treedef, outs)


def evaluate(fetch_vars, feed, params=None, env0=None):
    """Evaluate fetch Variables given feed dict (name -> np/jax array).
    Returns list of jax arrays.  Used by Executor (jitted there).

    env0: preset name->array bindings — control-flow branch bodies use it
    to bind their captured outer Variables / loop-carry placeholders to
    already-evaluated (possibly traced) values.
    """
    from jax.tree_util import tree_flatten, tree_unflatten
    from ..framework.tensor import Tensor

    env = dict(env0) if env0 else {}

    # batch all __grad__ fetches sharing a loss into ONE jax.grad sweep
    # (fetching N parameter grads must not cost N forward+backward passes)
    grad_fetches = [v for v in fetch_vars
                    if v.source is not None and v.source[0] == "__grad__"]
    by_loss = {}
    for v in grad_fetches:
        loss_v, wrt = v.source[1]
        by_loss.setdefault(id(loss_v), (loss_v, []))[1].append((v, wrt))
    for loss_v, pairs in by_loss.values():
        t_pairs = [(v, w) for v, w in pairs if isinstance(w, Tensor)]
        f_pairs = [(v, w) for v, w in pairs if not isinstance(w, Tensor)]
        if t_pairs:
            cur = [params[id(w)] if params and id(w) in params else w._data
                   for _, w in t_pairs]

            def f_t(arrs, _loss=loss_v, _pairs=t_pairs):
                p2 = dict(params or {})
                p2.update({id(w): a for (_, w), a in zip(_pairs, arrs)})
                return evaluate([_loss], feed, p2)[0] \
                    .astype(jnp.float32).sum()

            grads = jax.grad(f_t)(cur)
            for (v, _), g in zip(t_pairs, grads):
                env[v.name] = g
        if f_pairs:
            cur = [feed[w.name] for _, w in f_pairs]

            def f_f(arrs, _loss=loss_v, _pairs=f_pairs):
                f2 = dict(feed)
                f2.update({w.name: a for (_, w), a in zip(_pairs, arrs)})
                return evaluate([_loss], f2, params)[0] \
                    .astype(jnp.float32).sum()

            grads = jax.grad(f_f)(cur)
            for (v, _), g in zip(f_pairs, grads):
                env[v.name] = g

    def _leafvals(leaves, env0b):
        """Resolve mixed Variable/Tensor/const leaves inside a control-flow
        region: Variables share ONE evaluate call (memoized sub-env)."""
        vs = [x for x in leaves if isinstance(x, Variable)]
        vals = evaluate(vs, feed, params, env0b) if vs else []
        it = iter(vals)
        out = []
        for x in leaves:
            if isinstance(x, Variable):
                out.append(next(it))
            elif isinstance(x, Tensor):
                out.append(params[id(x)] if params and id(x) in params
                           else x._data)
            else:
                out.append(jnp.asarray(x))
        return out

    def _outer_leaf(x):
        if isinstance(x, Variable):
            return eval_var(x)
        if isinstance(x, Tensor):
            return params[id(x)] if params and id(x) in params else x._data
        return jnp.asarray(x)

    def eval_var(v):
        if v.name in env:
            return env[v.name]
        if v.source is None:
            if v.name not in feed:
                raise KeyError(f"feed missing input {v.name!r}")
            val = feed[v.name]
        elif v.source[0] == "__cond__":
            # region lowering: jax.lax.cond over the traced branch
            # subgraphs (control_flow.py); captured outer Variables are
            # evaluated HERE (memoized in this env) and bound by name
            pred, flat_t, flat_f, ext = v.source[1]
            pred_val = jnp.reshape(_outer_leaf(pred), ()).astype(bool)
            env0b = {e.name: eval_var(e) for e in ext}

            def mk(outs):
                return lambda _: tuple(_leafvals(outs, env0b))

            res = jax.lax.cond(pred_val, mk(flat_t), mk(flat_f), 0)
            for sib in v.program.vars.values():
                if sib.source is v.source:
                    env[sib.name] = res[sib.out_index]
            val = res[v.out_index]
        elif v.source[0] == "__while__":
            cond_out, body_outs, phs, init_leaves, ext = v.source[1]
            env0b = {e.name: eval_var(e) for e in ext}
            init = tuple(jnp.asarray(_outer_leaf(x)) for x in init_leaves)

            def cond_f(carry):
                e = dict(env0b)
                e.update({p.name: c for p, c in zip(phs, carry)})
                return jnp.reshape(
                    _leafvals([cond_out], e)[0], ()).astype(bool)

            def body_f(carry):
                e = dict(env0b)
                e.update({p.name: c for p, c in zip(phs, carry)})
                return tuple(jnp.asarray(r).astype(c.dtype)
                             for r, c in zip(_leafvals(body_outs, e),
                                             carry))

            res = jax.lax.while_loop(cond_f, body_f, init)
            for sib in v.program.vars.values():
                if sib.source is v.source:
                    env[sib.name] = res[sib.out_index]
            val = res[v.out_index]
        elif v.source[0] == "__pylayer__":
            flat_f, in_phs, input_leaves, bwd_outs, g_phs, ext = v.source[1]
            env0b = {e.name: eval_var(e) for e in ext}
            ins = tuple(_outer_leaf(x) for x in input_leaves)
            exts = tuple(env0b[e.name] for e in ext)

            def run_fwd(xs, es):
                e = {n.name: a for n, a in zip(ext, es)}
                e.update({p.name: x for p, x in zip(in_phs, xs)})
                return tuple(_leafvals(flat_f, e))

            if bwd_outs is None:
                res = run_fwd(ins, exts)
            else:
                def f(xs, es):
                    return run_fwd(xs, es)

                f = jax.custom_vjp(f)

                def fwd_rule(xs, es):
                    return run_fwd(xs, es), (xs, es)

                def bwd_rule(resid, gs):
                    xs, es = resid
                    e = {n.name: a for n, a in zip(ext, es)}
                    e.update({p.name: g for p, g in zip(g_phs, gs)})
                    dins = tuple(_leafvals(bwd_outs, e))
                    dexts = tuple(jnp.zeros_like(a) for a in es)
                    return (dins, dexts)

                f.defvjp(fwd_rule, bwd_rule)
                res = f(ins, exts)
            for sib in v.program.vars.values():
                if sib.source is v.source:
                    env[sib.name] = res[sib.out_index]
            val = res[v.out_index]
        elif v.source[0] == "__grad__":
            # static autodiff node (append_backward/gradients): grad of a
            # scalar-summed target w.r.t. a parameter Tensor or feed var
            _, (loss_v, wrt), _, _ = v.source
            if isinstance(wrt, Tensor):
                cur = params[id(wrt)] if params and id(wrt) in params \
                    else wrt._data
                val = jax.grad(lambda a: evaluate(
                    [loss_v], feed,
                    {**(params or {}), id(wrt): a})[0]
                    .astype(jnp.float32).sum())(cur)
            else:
                cur = feed[wrt.name]
                val = jax.grad(lambda a: evaluate(
                    [loss_v], {**feed, wrt.name: a}, params)[0]
                    .astype(jnp.float32).sum())(cur)
        else:
            body, args, kwargs, _ = v.source
            flat, treedef = tree_flatten(
                (args, kwargs),
                is_leaf=lambda x: isinstance(x, (Variable, Tensor)))
            vals = []
            for x in flat:
                if isinstance(x, Variable):
                    vals.append(eval_var(x))
                elif isinstance(x, Tensor):
                    key = id(x)
                    vals.append(params[key] if params and key in params
                                else x._data)
                else:
                    vals.append(x)
            a, k = tree_unflatten(treedef, vals)
            out = body(*a, **k)
            out_flat, _ = tree_flatten(out)
            val = out_flat[v.out_index]
            # memoize siblings
            for sib in v.program.vars.values():
                if sib.source is v.source:
                    env[sib.name] = out_flat[sib.out_index]
        env[v.name] = val
        return val

    return [eval_var(v) for v in fetch_vars]
