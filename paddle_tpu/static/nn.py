"""paddle.static.nn — graph-building layer helpers + static control flow.

Reference: python/paddle/static/nn/__init__.py (30 symbols: common.py
layer helpers + control_flow.py cond/while_loop/case/switch_case/
static_pylayer + sequence_lod.py sequence_* ops).

Each layer helper instantiates the dygraph layer (parameters init
eagerly — the "startup program" role) and applies it to the symbolic
Variable; the op registry records the resulting DAG nodes.  The
sequence_* family operates on padded dense batches ``[N, T, ...]`` —
the TPU formulation of the reference's LoD ragged tensors (static
shapes; ragged boundaries travel as explicit length/mask arguments
where they matter).
"""
from __future__ import annotations

import numpy as np

from .. import nn as dynn
from .control_flow import (Print, case, cond, static_pylayer, switch_case,
                           while_loop)
from .compat import py_func

__all__ = [
    "fc", "batch_norm", "bilinear_tensor_product", "embedding", "case",
    "cond", "static_pylayer", "conv2d", "conv2d_transpose", "conv3d",
    "conv3d_transpose", "data_norm", "deform_conv2d", "group_norm",
    "instance_norm", "layer_norm", "nce", "prelu", "py_func", "row_conv",
    "spectral_norm", "switch_case", "while_loop", "sparse_embedding",
    "sequence_conv", "sequence_softmax", "sequence_pool",
    "sequence_first_step", "sequence_last_step", "sequence_expand",
]


def _act(out, activation):
    if activation:
        import paddle_tpu.nn.functional as F
        out = getattr(F, activation)(out)
    return out


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    in_features = 1
    for s in x.shape[num_flatten_dims:]:
        in_features *= int(s)
    layer = dynn.Linear(in_features, size, weight_attr=weight_attr,
                        bias_attr=bias_attr)
    h = x
    if len(x.shape) > num_flatten_dims + 1:
        from ..ops.manipulation import flatten
        h = flatten(h, start_axis=num_flatten_dims)
    return _act(layer(h), activation)


def conv2d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           act=None, name=None, data_format="NCHW"):
    in_ch = int(input.shape[1 if data_format == "NCHW" else -1])
    layer = dynn.Conv2D(in_ch, num_filters, filter_size, stride=stride,
                        padding=padding, dilation=dilation, groups=groups,
                        weight_attr=param_attr, bias_attr=bias_attr,
                        data_format=data_format)
    return _act(layer(input), act)


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format="NCHW"):
    """reference: python/paddle/static/nn/common.py conv2d_transpose."""
    if filter_size is None:
        raise ValueError("conv2d_transpose: filter_size is required "
                         "(output_size-only inference not supported)")
    in_ch = int(input.shape[1 if data_format == "NCHW" else -1])
    layer = dynn.Conv2DTranspose(
        in_ch, num_filters, filter_size, stride=stride, padding=padding,
        dilation=dilation, groups=groups, weight_attr=param_attr,
        bias_attr=bias_attr, data_format=data_format)
    out = layer(input, output_size=output_size) \
        if output_size is not None else layer(input)
    return _act(out, act)


def conv3d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           use_cudnn=True, act=None, name=None, data_format="NCDHW"):
    in_ch = int(input.shape[1 if data_format == "NCDHW" else -1])
    layer = dynn.Conv3D(in_ch, num_filters, filter_size, stride=stride,
                        padding=padding, dilation=dilation, groups=groups,
                        weight_attr=param_attr, bias_attr=bias_attr,
                        data_format=data_format)
    return _act(layer(input), act)


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format="NCDHW"):
    if filter_size is None:
        raise ValueError("conv3d_transpose: filter_size is required")
    in_ch = int(input.shape[1 if data_format == "NCDHW" else -1])
    layer = dynn.Conv3DTranspose(
        in_ch, num_filters, filter_size, stride=stride, padding=padding,
        dilation=dilation, groups=groups, weight_attr=param_attr,
        bias_attr=bias_attr, data_format=data_format)
    return _act(layer(input), act)


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               name=None, **kwargs):
    ch = int(input.shape[1 if data_layout == "NCHW" else -1])
    layer = dynn.BatchNorm2D(ch, momentum=momentum, epsilon=epsilon,
                             weight_attr=param_attr, bias_attr=bias_attr,
                             data_format=data_layout)
    return _act(layer(input), act)


def group_norm(input, groups, epsilon=1e-5, param_attr=None,
               bias_attr=None, act=None, data_layout="NCHW", name=None):
    """reference: common.py group_norm."""
    ch = int(input.shape[1 if data_layout == "NCHW" else -1])
    layer = dynn.GroupNorm(groups, ch, epsilon=epsilon,
                           weight_attr=param_attr, bias_attr=bias_attr,
                           data_format=data_layout)
    return _act(layer(input), act)


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    """reference: common.py instance_norm (2-D spatial input)."""
    ch = int(input.shape[1])
    cls = {3: dynn.InstanceNorm1D, 4: dynn.InstanceNorm2D,
           5: dynn.InstanceNorm3D}[len(input.shape)]
    layer = cls(ch, epsilon=epsilon, weight_attr=param_attr,
                bias_attr=bias_attr)
    return layer(input)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    """reference: common.py layer_norm — normalizes over
    input.shape[begin_norm_axis:]."""
    normalized_shape = [int(s) for s in input.shape[begin_norm_axis:]]
    layer = dynn.LayerNorm(normalized_shape, epsilon=epsilon,
                           weight_attr=param_attr if scale else False,
                           bias_attr=bias_attr if shift else False)
    return _act(layer(input), act)


def data_norm(input, act=None, epsilon=1e-5, param_attr=None,
              data_layout="NCHW", in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=True, slot_dim=-1,
              sync_stats=False, summary_decay_rate=0.9999999,
              enable_scale_and_shift=False):
    """reference: common.py data_norm — normalization by accumulated
    batch statistics without learned affine (CTR models).  Dense
    formulation: standardize each feature by batch mean/std."""
    from ..ops import reduction as R
    from ..ops.math import sqrt

    mean = R.mean(input, axis=0, keepdim=True)
    var = R.var(input, axis=0, unbiased=False, keepdim=True)
    out = (input - mean) / sqrt(var + epsilon)
    return _act(out, act)


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    """reference: common.py bilinear_tensor_product: out_k = x W_k y^T."""
    layer = dynn.Bilinear(int(x.shape[-1]), int(y.shape[-1]), size,
                          weight_attr=param_attr, bias_attr=bias_attr)
    return _act(layer(x, y), act)


def deform_conv2d(input, offset, mask, num_filters, filter_size, stride=1,
                  padding=0, dilation=1, groups=1, deformable_groups=1,
                  im2col_step=1, param_attr=None, bias_attr=None,
                  name=None):
    """reference: common.py deform_conv2d → vision.ops.deform_conv2d
    engine with an eagerly initialized weight."""
    from .compat import create_parameter
    from ..vision.ops import deform_conv2d as _dc

    ks = filter_size if isinstance(filter_size, (list, tuple)) \
        else (filter_size, filter_size)
    in_ch = int(input.shape[1])
    weight = create_parameter(
        [num_filters, in_ch // groups, ks[0], ks[1]], "float32",
        attr=param_attr)
    bias = create_parameter([num_filters], "float32", attr=bias_attr,
                            is_bias=True) if bias_attr is not False else None
    return _dc(input, offset, weight, bias=bias, stride=stride,
               padding=padding, dilation=dilation,
               deformable_groups=deformable_groups, groups=groups,
               mask=mask)


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32"):
    layer = dynn.Embedding(size[0], size[1], padding_idx=padding_idx,
                           sparse=is_sparse, weight_attr=param_attr)
    return layer(input)


def sparse_embedding(input, size, padding_idx=None, is_test=False,
                     entry=None, table_class="MemorySparseTable",
                     param_attr=None, dtype="float32", slot=None):
    """reference: common.py sparse_embedding (parameter-server lookup
    table).  TPU formulation: a dense embedding whose gradient flows as
    rows (SelectedRows analog); the PS path shards it via
    distributed.ps tables."""
    return embedding(input, size, is_sparse=True, padding_idx=padding_idx,
                     param_attr=param_attr, dtype=dtype)


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=None, name=None,
        sampler="uniform", custom_dist=None, seed=0, is_sparse=False):
    """reference: common.py nce — noise-contrastive estimation loss.
    TPU formulation: uniform negative sampling with a fixed sample count
    (static shapes), logistic loss over [pos | negs] logits."""
    from .compat import create_parameter
    from ..ops.registry import apply_op
    import jax
    import jax.numpy as jnp

    dim = int(input.shape[-1])
    k = int(num_neg_samples or 10)
    weight = create_parameter([num_total_classes, dim], "float32",
                              attr=param_attr)
    bias = create_parameter([num_total_classes], "float32", attr=bias_attr,
                            is_bias=True)

    def body(x, lab, w, b):
        from ..framework import random as _random
        lab = lab.reshape((-1,))
        n = x.shape[0]
        negs = jax.random.randint(_random.split_key(), (n, k), 0,
                                  num_total_classes)
        pos_logit = jnp.einsum("nd,nd->n", x, w[lab]) + b[lab]
        neg_logit = jnp.einsum("nd,nkd->nk", x, w[negs]) + b[negs]
        # log-sigmoid losses: positive attracted, negatives repelled
        pos_loss = jax.nn.softplus(-pos_logit)
        neg_loss = jax.nn.softplus(neg_logit).sum(axis=1)
        return (pos_loss + neg_loss).reshape((-1, 1))

    return apply_op("nce", body, (input, label, weight, bias), {})


def prelu(x, mode, param_attr=None, data_format="NCHW", name=None):
    """reference: common.py prelu — modes all/channel/element."""
    if mode == "all":
        layer = dynn.PReLU(num_parameters=1, weight_attr=param_attr,
                           data_format=data_format)
        return layer(x)
    if mode == "channel":
        num = int(x.shape[1 if data_format == "NCHW" else -1])
        layer = dynn.PReLU(num_parameters=num, weight_attr=param_attr,
                           data_format=data_format)
        return layer(x)
    if mode == "element":
        # per-element slope, weight shaped like one sample; reference
        # initializes every slope to 0.25 (common.py prelu)
        from .compat import create_parameter
        from ..ops.registry import apply_op
        from ..nn import initializer as I
        import jax.numpy as jnp

        alpha = create_parameter(
            [int(s) for s in x.shape[1:]], "float32", attr=param_attr,
            default_initializer=I.Constant(0.25))

        def body(v, a):
            return jnp.where(v >= 0, v, a * v)

        return apply_op("prelu_element", body, (x, alpha), {})
    raise ValueError(f"prelu: unknown mode {mode!r}")


def row_conv(input, future_context_size, param_attr=None, act=None):
    """reference: common.py row_conv (lookahead convolution over the
    time axis of [N, T, D] batches — the LoD form collapses to padded
    dense here)."""
    from .compat import create_parameter
    from ..ops.registry import apply_op
    import jax.numpy as jnp

    d = int(input.shape[-1])
    w = create_parameter([future_context_size + 1, d], "float32",
                         attr=param_attr)

    def body(x, wt):
        outs = jnp.zeros_like(x)
        T = x.shape[1]
        for i in range(future_context_size + 1):
            shifted = jnp.pad(x[:, i:, :], ((0, 0), (0, i), (0, 0)))
            outs = outs + shifted * wt[i]
        return outs

    return _act(apply_op("row_conv", body, (input, w), {}), act)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """reference: common.py spectral_norm — returns W / sigma_max(W)
    estimated by power iteration (stateless static form: fresh u/v)."""
    from ..ops.registry import apply_op
    import jax.numpy as jnp

    def body(w):
        perm = [dim] + [i for i in range(w.ndim) if i != dim]
        m = jnp.transpose(w, perm).reshape((w.shape[dim], -1))
        u = jnp.ones((m.shape[0],), m.dtype) / np.sqrt(m.shape[0])
        v = None
        for _ in range(max(1, power_iters)):
            v = m.T @ u
            v = v / (jnp.linalg.norm(v) + eps)
            u = m @ v
            u = u / (jnp.linalg.norm(u) + eps)
        sigma = u @ (m @ v)
        return w / (sigma + eps)

    return apply_op("spectral_norm_static", body, (weight,), {})


# ------------------------------------------------------- sequence family
# reference: python/paddle/static/nn/sequence_lod.py.  LoD ragged rows
# become padded dense [N, T, ...] batches on TPU (static shapes).


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=True, padding_start=None, param_attr=None,
                  bias_attr=None, act=None, name=None):
    """reference: sequence_lod.py sequence_conv over [N, T, D]."""
    from .compat import create_parameter
    from ..ops.registry import apply_op
    import jax
    import jax.numpy as jnp

    d = int(input.shape[-1])
    w = create_parameter([filter_size * d, num_filters], "float32",
                         attr=param_attr)
    b = create_parameter([num_filters], "float32", attr=bias_attr,
                         is_bias=True) if bias_attr is not False else None

    start = -(filter_size // 2) if padding_start is None else padding_start

    def body(x, wt, bt=None):
        n, t, _ = x.shape
        cols = []
        for i in range(filter_size):
            off = start + i
            if off < 0:
                s = jnp.pad(x[:, :t + off, :],
                            ((0, 0), (-off, 0), (0, 0)))
            elif off > 0:
                s = jnp.pad(x[:, off:, :], ((0, 0), (0, off), (0, 0)))
            else:
                s = x
            cols.append(s)
        col = jnp.concatenate(cols, axis=-1)        # [N, T, k*D]
        out = col @ wt
        if bt is not None:
            out = out + bt
        return out

    args = (input, w) if b is None else (input, w, b)
    return _act(apply_op("sequence_conv", body, args, {}), act)


def sequence_softmax(input, use_cudnn=False, name=None):
    """softmax over the time axis of [N, T] / [N, T, 1]."""
    from ..ops.registry import apply_op
    import jax

    def body(x):
        axis = 1 if x.ndim > 1 else 0
        return jax.nn.softmax(x, axis=axis)

    return apply_op("sequence_softmax", body, (input,), {})


def sequence_pool(input, pool_type, is_test=False, pad_value=0.0):
    """reference: sequence_lod.py sequence_pool over the time axis:
    average/sum/sqrt/max/last/first."""
    from ..ops.registry import apply_op
    import jax.numpy as jnp

    pt = pool_type.lower()

    def body(x):
        if pt == "average":
            return x.mean(axis=1)
        if pt == "sum":
            return x.sum(axis=1)
        if pt == "sqrt":
            return x.sum(axis=1) / np.sqrt(x.shape[1])
        if pt == "max":
            return x.max(axis=1)
        if pt == "last":
            return x[:, -1]
        if pt == "first":
            return x[:, 0]
        raise ValueError(f"sequence_pool: unknown pool_type {pool_type!r}")

    return apply_op("sequence_pool", body, (input,), {})


def sequence_first_step(input):
    return sequence_pool(input, "first")


def sequence_last_step(input):
    return sequence_pool(input, "last")


def sequence_expand(x, y, ref_level=-1, name=None):
    """reference: sequence_lod.py sequence_expand — broadcast x rows to
    y's time length (dense padded form: tile along axis 1)."""
    from ..ops.registry import apply_op
    import jax.numpy as jnp

    def body(a, bref):
        t = bref.shape[1]
        if a.ndim == 2:
            a = a[:, None, :]
        return jnp.broadcast_to(a, (a.shape[0], t, a.shape[-1]))

    return apply_op("sequence_expand", body, (x, y), {})
