"""paddle.static.nn — graph-building layer helpers.

Reference: python/paddle/static/nn/common.py (fc, batch_norm, conv2d...).
Each helper instantiates the dygraph layer (parameters init eagerly — the
"startup program" role) and applies it to the symbolic Variable; the op
registry records the resulting DAG nodes.
"""
from __future__ import annotations

from .. import nn as dynn

__all__ = ["fc", "conv2d", "batch_norm", "embedding"]


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    in_features = 1
    for s in x.shape[num_flatten_dims:]:
        in_features *= int(s)
    layer = dynn.Linear(in_features, size, weight_attr=weight_attr,
                        bias_attr=bias_attr)
    h = x
    if len(x.shape) > num_flatten_dims + 1:
        from ..ops.manipulation import flatten
        h = flatten(h, start_axis=num_flatten_dims)
    out = layer(h)
    if activation:
        import paddle_tpu.nn.functional as F
        out = getattr(F, activation)(out)
    return out


def conv2d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           act=None, name=None, data_format="NCHW"):
    in_ch = int(input.shape[1 if data_format == "NCHW" else -1])
    layer = dynn.Conv2D(in_ch, num_filters, filter_size, stride=stride,
                        padding=padding, dilation=dilation, groups=groups,
                        weight_attr=param_attr, bias_attr=bias_attr,
                        data_format=data_format)
    out = layer(input)
    if act:
        import paddle_tpu.nn.functional as F
        out = getattr(F, act)(out)
    return out


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               name=None, **kwargs):
    ch = int(input.shape[1 if data_layout == "NCHW" else -1])
    layer = dynn.BatchNorm2D(ch, momentum=momentum, epsilon=epsilon,
                             weight_attr=param_attr, bias_attr=bias_attr,
                             data_format=data_layout)
    out = layer(input)
    if act:
        import paddle_tpu.nn.functional as F
        out = getattr(F, act)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None,
              param_attr=None, dtype="float32"):
    layer = dynn.Embedding(size[0], size[1], padding_idx=padding_idx,
                           sparse=is_sparse, weight_attr=param_attr)
    return layer(input)
