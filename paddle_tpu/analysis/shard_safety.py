"""Shard-map collective discipline: axis names must be bound.

The TP runner relies on convention today: collectives (``psum``,
``ppermute``, ``all_gather``, ...) are only legal inside a function that
``jax.shard_map`` maps over the mesh, and only on axis names the mapping
actually binds (``axis_names=`` / the mesh's axis tuple).  An unbound
axis name is a runtime ``NameError``-equivalent deep inside jit; a
misspelled PartitionSpec axis shards nothing and silently replicates.

The analyzer resolves every ``shard_map`` call's target the same way
``jit_safety`` resolves jit targets (named fns, nested defs, factory
closures, ``functools.partial`` wrappers), collects the axis universe
each call binds (literal ``axis_names={...}`` or the literal axis tuple
of the ``Mesh`` the ``mesh=`` argument refers to), and marks those
bodies — plus same-module helpers they call — as mapped.  Only *string
literal* axis arguments are judged: the repo's helper convention passes
the axis as a parameter (``def _ffn_tp(w, h, axis): ... psum(part,
axis)``), which is deliberate indirection the caller owns, so
parameter/closure axes are never flagged.

Rules:

``collective-outside-shardmap``
    A collective with a literal axis name in a function no ``shard_map``
    in the module maps — under jit this raises "unbound axis name".

``collective-unknown-axis``
    A literal axis that the mapping ``shard_map`` provably does not
    bind, or a literal ``PartitionSpec`` axis that is not an axis of
    any literal ``Mesh`` in the module.
"""
from __future__ import annotations

import ast

from .core import Finding, SourceFile, call_name
from .jit_safety import _JitCall, _ModuleIndex

__all__ = ["analyze"]

RULES = {
    "collective-outside-shardmap": "collective on a literal axis name "
                                   "outside any shard_map-mapped "
                                   "function",
    "collective-unknown-axis": "literal axis name not bound by the "
                               "mapping shard_map / mesh",
}

_COLLECTIVES = {"psum", "pmean", "pmax", "pmin", "ppermute",
                "all_gather", "all_to_all", "axis_index", "psum_scatter"}
# positional index of the axis-name argument
_AXIS_POS = {"axis_index": 0}
_DEFAULT_AXIS_POS = 1

_SHARD_MAP_NAMES = {"jax.shard_map", "shard_map",
                    "jax.experimental.shard_map.shard_map"}
_MESH_NAMES = {"Mesh", "jax.sharding.Mesh", "sharding.Mesh",
               "jax.make_mesh"}

_TOKENS = ("psum", "ppermute", "all_gather", "all_to_all", "pmean",
           "pmax", "pmin", "axis_index", "shard_map", "PartitionSpec")


def analyze(src: SourceFile) -> list[Finding]:
    text = src.text
    if not any(t in text for t in _TOKENS):
        return []
    findings: list[Finding] = []
    mod = _ModuleIndex(src)
    index = _ShardIndex(src, mod)
    for call, fn in index.collectives:
        axes = _literal_axes(call)
        if not axes:
            continue                # parameter/closure axis: caller owns
        cname = call_name(call)
        fn_name = fn.name if fn is not None else "<module>"
        universe = index.universe_of(fn)
        if fn is None or id(fn) not in index.mapped:
            findings.append(Finding(
                "collective-outside-shardmap", src.path, call.lineno,
                f"collective `{cname}` on axis "
                f"{_fmt_axes(axes)} in `{fn_name}` is not mapped by any "
                "shard_map in this module — under jit the axis name is "
                "unbound",
                hint="wrap the caller in jax.shard_map(..., axis_names="
                     "...) or take the axis as a parameter"))
            continue
        if universe:
            for ax in axes:
                if ax not in universe:
                    findings.append(Finding(
                        "collective-unknown-axis", src.path, call.lineno,
                        f"collective `{cname}` in `{fn_name}` uses axis "
                        f"'{ax}' but the mapping shard_map binds only "
                        f"{sorted(universe)}",
                        hint="use one of the bound axis names, or bind "
                             "the axis in axis_names=/the mesh"))
    findings.extend(_check_partition_specs(src, index))
    seen, unique = set(), []
    for f in findings:
        key = (f.rule, f.line, f.message)
        if key not in seen:
            seen.add(key)
            unique.append(f)
    return src.filter(unique)


def _fmt_axes(axes) -> str:
    if len(axes) == 1:
        return f"'{axes[0]}'"
    return "(" + ", ".join(f"'{a}'" for a in axes) + ")"


def _is_collective(call) -> str | None:
    name = call_name(call)
    if name is None:
        return None
    base = name.split(".")[-1]
    if base not in _COLLECTIVES:
        return None
    prefix = name[: -len(base)].rstrip(".")
    if prefix in ("", "lax", "jax.lax"):
        return base
    return None


def _literal_axes(call) -> list:
    base = _is_collective(call)
    if base is None:
        return []
    axis = None
    for kw in call.keywords:
        if kw.arg == "axis_name":
            axis = kw.value
    if axis is None:
        pos = _AXIS_POS.get(base, _DEFAULT_AXIS_POS)
        if len(call.args) > pos:
            axis = call.args[pos]
    if axis is None:
        return []
    out = []
    elts = axis.elts if isinstance(axis, (ast.Tuple, ast.List)) \
        else [axis]
    for e in elts:
        if isinstance(e, ast.Constant) and isinstance(e.value, str):
            out.append(e.value)
        else:
            return []               # any non-literal part: caller owns it
    return out


class _ShardIndex:
    """shard_map-mapped functions, their axis universes, and all
    collective call sites with their enclosing function."""

    def __init__(self, src, mod: _ModuleIndex):
        self.src = src
        self.mod = mod
        self.mapped: dict[int, ast.AST] = {}    # id(fn) -> fn
        self.universes: dict[int, set | None] = {}
        self.collectives: list = []             # (call, enclosing fn)
        self.mesh_axes: set = set()             # all literal mesh axes
        self.spec_aliases = {"PartitionSpec"}
        self._collect_imports(src.tree)
        self._walk(src.tree, None, None)
        self._expand_transitive()

    # ------------------------------------------------------------ walking
    def _collect_imports(self, tree):
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom):
                for alias in node.names:
                    if alias.name == "PartitionSpec":
                        self.spec_aliases.add(alias.asname or alias.name)

    def _walk(self, node, fn, cls):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                self._walk(child, fn, child)
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                self._walk(child, child, cls)
            else:
                self._visit_exprs(child, fn, cls)
                self._walk(child, fn, cls)

    def _visit_exprs(self, node, fn, cls):
        if not isinstance(node, ast.Call):
            return
        name = call_name(node)
        if name in _SHARD_MAP_NAMES and node.args:
            jit = _JitCall(node, fn, cls)
            body = self.mod._resolve_expr(node.args[0], jit)
            universe = self._universe(node, fn)
            if universe:
                self.mesh_axes |= universe
            if body is not None:
                key = id(body.node)
                self.mapped[key] = body.node
                if key in self.universes and \
                        self.universes[key] != universe:
                    self.universes[key] = None      # conflicting: unknown
                else:
                    self.universes[key] = universe
        elif _is_collective(node):
            self.collectives.append((node, fn))
        else:
            self._note_mesh(node)

    def _note_mesh(self, call):
        if call_name(call) not in _MESH_NAMES:
            return
        axes = self._mesh_axes_from_call(call)
        if axes:
            self.mesh_axes |= axes

    # ------------------------------------------------------ axis universes
    def universe_of(self, fn):
        return self.universes.get(id(fn)) if fn is not None else None

    def _universe(self, call, enclosing_fn) -> set | None:
        for kw in call.keywords:
            if kw.arg == "axis_names":
                axes = _str_literals(kw.value)
                if axes is not None:
                    return axes
        for kw in call.keywords:
            if kw.arg == "mesh":
                return self._mesh_universe(kw.value, enclosing_fn)
        return None

    def _mesh_universe(self, expr, enclosing_fn) -> set | None:
        if isinstance(expr, ast.Call):
            return self._mesh_axes_from_call(expr)
        if isinstance(expr, ast.Name):
            scopes = [self.src.tree]
            if enclosing_fn is not None:
                scopes.insert(0, enclosing_fn)
            for scope in scopes:
                for node in ast.walk(scope):
                    if isinstance(node, ast.Assign) and \
                            len(node.targets) == 1 and \
                            isinstance(node.targets[0], ast.Name) and \
                            node.targets[0].id == expr.id and \
                            isinstance(node.value, ast.Call):
                        return self._mesh_axes_from_call(node.value)
        return None

    @staticmethod
    def _mesh_axes_from_call(call) -> set | None:
        if call_name(call) not in _MESH_NAMES:
            return None
        cand = None
        for kw in call.keywords:
            if kw.arg in ("axis_names", "axis_name"):
                cand = kw.value
        if cand is None and len(call.args) > 1:
            cand = call.args[1]
        if cand is None:
            return None
        return _str_literals(cand)

    # ----------------------------------------- transitive mapped expansion
    def _expand_transitive(self):
        for _ in range(2):          # depth-bounded closure
            for fn in list(self.mapped.values()):
                universe = self.universes.get(id(fn))
                for node in ast.walk(fn):
                    for callee in self._referenced_defs(node, fn):
                        if id(callee) in self.mapped:
                            continue
                        self.mapped[id(callee)] = callee
                        self.universes[id(callee)] = universe

    def _referenced_defs(self, node, fn):
        """Defs a mapped body hands control to: direct calls, plus bare
        function references (scan/fori_loop bodies run in the mapped
        context without ever being *called* by name)."""
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            callee = self.mod.nested.get(id(fn), {}).get(node.id) or \
                self.mod.defs.get((None, node.id))
            if callee is not None:
                yield callee
        elif isinstance(node, ast.Call):
            name = call_name(node) or ""
            base = name.split(".")[-1]
            if name.startswith("self."):
                for (cls, fname), d in self.mod.defs.items():
                    if cls is not None and fname == base:
                        yield d
                        return
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a def nested in a mapped body runs in the mapped context
            if id(node) not in self.mapped and node is not fn:
                yield node


def _str_literals(node) -> set | None:
    """The set of string constants a literal collection denotes."""
    if isinstance(node, ast.Call) and \
            call_name(node) in ("frozenset", "set") and node.args:
        return _str_literals(node.args[0])
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        out = set()
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.add(e.value)
            else:
                return None
        return out
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return {node.value}
    return None


def _check_partition_specs(src, index: _ShardIndex) -> list[Finding]:
    if not index.mesh_axes:
        return []                   # no provable universe: stay silent
    findings = []
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node) or ""
        if name.split(".")[-1] not in index.spec_aliases:
            continue
        for arg in node.args:
            elts = arg.elts if isinstance(arg, (ast.Tuple, ast.List)) \
                else [arg]
            for e in elts:
                if isinstance(e, ast.Constant) and \
                        isinstance(e.value, str) and \
                        e.value not in index.mesh_axes:
                    findings.append(Finding(
                        "collective-unknown-axis", src.path, node.lineno,
                        f"PartitionSpec axis '{e.value}' is not an axis "
                        "of any mesh in this module "
                        f"({sorted(index.mesh_axes)}) — the dimension "
                        "silently replicates",
                        hint="use a mesh axis name, or None for "
                             "replicated dimensions"))
    return findings
