"""Lock-discipline analyzer: ordering cycles, unlocked shared writes,
blocking calls under a lock.

The serving stack is threaded (engine worker, router prober, watchdog,
SSE handlers) and every subsystem guards its state with attribute locks
(``self._lock = threading.Lock()``).  This analyzer reconstructs, per
module, which locks exist, where they are held (``with self._lock:``
scopes and paired ``.acquire()``/``.release()`` calls), and checks:

``lock-order-cycle``
    A lock-acquisition graph edge A->B is recorded whenever B is
    acquired while A is held.  Any strongly-connected component (A->B
    and B->A, or longer rings) is a potential ABBA deadlock.
    ``threading.Condition(existing_lock)`` is treated as an alias of
    its underlying lock, and reentrant re-acquisition of the same
    RLock/Condition is not an edge.

``lock-unlocked-write``
    Within a class that owns at least one lock, an attribute written
    both inside a lock scope and outside any lock scope (excluding
    ``__init__``, where the object is not yet shared) is a data race:
    the unlocked sites are flagged.

``lock-blocking-call``
    Calls that can block indefinitely while a lock is held starve every
    other thread contending for it: ``time.sleep``, socket/HTTP
    connects, ``Event.wait``, ``Condition.wait`` on a *different* lock
    than the one held (waiting on the condition you hold through the
    condition itself is the normal pattern and is fine),
    ``.block_until_ready()`` / ``jax.device_get`` / ``np.asarray`` on
    device values, and ``Thread.join``.

Only ``.acquire()``/``.release()`` on *resolved lock objects* count —
unrelated methods that happen to be called ``_acquire`` (e.g. the block
manager's page allocator) are ignored.
"""
from __future__ import annotations

import ast

from .core import Finding, SourceFile, call_name, expr_text

__all__ = ["analyze"]

RULES = {
    "lock-order-cycle": "locks acquired in inconsistent order "
                        "(potential ABBA deadlock)",
    "lock-unlocked-write": "attribute written both inside and outside "
                           "the class's lock scopes",
    "lock-blocking-call": "call that can block indefinitely made while "
                          "holding a lock",
}

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}
_REENTRANT_CTORS = {"RLock", "Condition"}
_EVENT_CTORS = {"Event"}

# paddle_tpu.sanitizer factories return (possibly instrumented) locks;
# a `self._lock = make_lock(...)` must stay visible to this pass
_FACTORY_CTORS = {"make_lock": "Lock", "make_rlock": "RLock",
                  "make_condition": "Condition"}

# dotted call names that block regardless of their arguments
_BLOCKING_CALLS = {
    "time.sleep": "sleeps with the lock held",
    "socket.create_connection": "network connect with the lock held",
    "urllib.request.urlopen": "HTTP round-trip with the lock held",
    "requests.get": "HTTP round-trip with the lock held",
    "requests.post": "HTTP round-trip with the lock held",
    "requests.request": "HTTP round-trip with the lock held",
    "jax.device_get": "device->host transfer with the lock held",
}

_BLOCKING_METHODS = {
    "block_until_ready": "device sync with the lock held",
    "getresponse": "HTTP read with the lock held",
    "recv": "socket read with the lock held",
}

_DEVICE_HINTS = ("_dev", "device")


class _LockInfo:
    __slots__ = ("key", "ctor", "alias_of")

    def __init__(self, key, ctor, alias_of=None):
        self.key = key              # canonical id, e.g. "Router._lock"
        self.ctor = ctor            # "Lock" | "RLock" | ...
        self.alias_of = alias_of    # canonical key of underlying lock


class _ModuleLocks:
    """Lock/event inventory for one module."""

    def __init__(self, tree):
        # "Class.attr" or bare module-global name -> _LockInfo
        self.locks: dict[str, _LockInfo] = {}
        self.events: set[str] = set()           # "Class.attr" keys
        # lock attr name -> class names defining it (cross-object lookup)
        self.attr_owners: dict[str, list] = {}
        self._collect(tree)

    def _collect(self, tree):
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Assign) and \
                            isinstance(sub.value, ast.Call):
                        self._maybe_lock(node.name, sub)
            elif isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        ctor = _ctor_of(node.value)
                        ctor = _FACTORY_CTORS.get(ctor, ctor)
                        if ctor in _LOCK_CTORS:
                            self.locks[tgt.id] = _LockInfo(tgt.id, ctor)

    def _maybe_lock(self, cls, assign):
        ctor = _ctor_of(assign.value)
        ctor = _FACTORY_CTORS.get(ctor, ctor)
        for tgt in assign.targets:
            text = expr_text(tgt)
            if not text.startswith("self."):
                continue
            attr = text[5:]
            key = f"{cls}.{attr}"
            if ctor in _LOCK_CTORS:
                alias = None
                if ctor == "Condition" and assign.value.args:
                    inner = expr_text(assign.value.args[0])
                    if inner.startswith("self."):
                        alias = f"{cls}.{inner[5:]}"
                self.locks[key] = _LockInfo(key, ctor, alias)
                self.attr_owners.setdefault(attr, []).append(cls)
            elif ctor in _EVENT_CTORS:
                self.events.add(key)

    # ------------------------------------------------------- resolution
    def resolve(self, expr, cls) -> _LockInfo | None:
        """The lock an expression refers to, or None."""
        text = expr_text(expr)
        if text.startswith("self.") and cls:
            info = self.locks.get(f"{cls}.{text[5:]}")
            if info is not None:
                return info
        if isinstance(expr, ast.Name):
            return self.locks.get(text)
        if isinstance(expr, ast.Attribute):
            owners = self.attr_owners.get(expr.attr, [])
            if len(owners) == 1 and not text.startswith("self."):
                return self.locks.get(f"{owners[0]}.{expr.attr}")
        return None

    def canonical(self, info: _LockInfo) -> str:
        seen = set()
        while info.alias_of and info.alias_of not in seen:
            seen.add(info.key)
            nxt = self.locks.get(info.alias_of)
            if nxt is None:
                break
            info = nxt
        return info.key

    def is_event(self, expr, cls) -> bool:
        text = expr_text(expr)
        return bool(text.startswith("self.") and cls and
                    f"{cls}.{text[5:]}" in self.events)


def _ctor_of(call) -> str | None:
    name = call_name(call)
    return name.rsplit(".", 1)[-1] if name else None


def analyze(src: SourceFile) -> list[Finding]:
    # cheap pre-gate: no lock constructor text, no resolvable locks
    if not any(ctor + "(" in src.text
               for ctor in _LOCK_CTORS | _EVENT_CTORS
               | set(_FACTORY_CTORS)):
        return []
    locks = _ModuleLocks(src.tree)
    findings: list[Finding] = []
    edges: dict[tuple, tuple] = {}       # (outer, inner) -> first site
    writes: dict[tuple, dict] = {}       # (cls, attr) -> {...}

    for cls, fn in _methods(src.tree):
        clsname = cls.name if cls else None
        v = _ScopeVisitor(src, locks, clsname, fn, edges, writes,
                          findings)
        v.visit_block(fn.body, [])

    findings.extend(_cycle_findings(src, edges))
    findings.extend(_write_findings(src, writes))
    return src.filter(findings)


def _methods(tree):
    """(class | None, function) pairs, outermost functions only —
    nested closures are visited as part of their parent's body."""
    def walk(node, cls):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                yield from walk(child, child)
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                yield cls, child
            else:
                yield from walk(child, cls)
    yield from walk(tree, None)


class _ScopeVisitor:
    """Statement-ordered traversal of one function tracking held locks."""

    def __init__(self, src, locks, cls, fn, edges, writes, findings):
        self.src = src
        self.locks = locks
        self.cls = cls
        self.fn = fn
        self.edges = edges
        self.writes = writes
        self.findings = findings

    # `held` is an ordered list of (canonical_key, ctor) for the current
    # path; acquire/release pairs mutate a copy scoped to the block.
    def visit_block(self, stmts, held):
        held = list(held)
        for stmt in stmts:
            held = self.visit_stmt(stmt, held)
        return held

    def visit_stmt(self, stmt, held):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested closure: runs later, not under the current locks —
            # but its own with-scopes still count, with an empty stack
            self.visit_block(stmt.body, [])
            return held
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            inner = list(held)
            acquired = []
            for item in stmt.items:
                ctx = item.context_expr
                info = self.locks.resolve(ctx, self.cls)
                if info is not None:
                    key = self.locks.canonical(info)
                    self._record_acquire(key, info, inner, ctx)
                    inner.append((key, info.ctor))
                    acquired.append(key)
            self.visit_block(stmt.body, inner)
            return held
        if isinstance(stmt, (ast.If,)):
            self._scan_expr(stmt.test, held)
            self.visit_block(stmt.body, held)
            self.visit_block(stmt.orelse, held)
            return held
        if isinstance(stmt, (ast.While, ast.For)):
            if isinstance(stmt, ast.While):
                self._scan_expr(stmt.test, held)
            else:
                self._scan_expr(stmt.iter, held)
            self.visit_block(stmt.body, held)
            self.visit_block(stmt.orelse, held)
            return held
        if isinstance(stmt, ast.Try):
            held = self.visit_block(stmt.body, held)
            for h in stmt.handlers:
                self.visit_block(h.body, held)
            self.visit_block(stmt.orelse, held)
            held = self.visit_block(stmt.finalbody, held)
            return held

        # leaf statement: explicit acquire()/release(), writes, calls
        held = self._handle_acquire_release(stmt, held)
        self._record_writes(stmt, held)
        self._scan_stmt_exprs(stmt, held)
        return held

    # ----------------------------------------------------- acquire pairs
    def _handle_acquire_release(self, stmt, held):
        for call in ast.walk(stmt):
            if not isinstance(call, ast.Call) or \
                    not isinstance(call.func, ast.Attribute):
                continue
            if call.func.attr not in ("acquire", "release"):
                continue
            info = self.locks.resolve(call.func.value, self.cls)
            if info is None:
                continue
            key = self.locks.canonical(info)
            if call.func.attr == "acquire":
                self._record_acquire(key, info, held, call.func.value)
                held = held + [(key, info.ctor)]
            else:
                held = [h for h in held if h[0] != key] if \
                    any(h[0] == key for h in held) else held
        return held

    def _record_acquire(self, key, info, held, site):
        for outer_key, _ in held:
            if outer_key == key:
                continue            # reentrant; RLock/Condition fine
            edge = (outer_key, key)
            if edge not in self.edges:
                self.edges[edge] = (self.src.path, site.lineno)

    # --------------------------------------------------- attribute writes
    def _record_writes(self, stmt, held):
        if self.cls is None or self.fn.name == "__init__":
            return
        targets = []
        if isinstance(stmt, ast.Assign):
            targets = stmt.targets
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            targets = [stmt.target]
        elif isinstance(stmt, ast.Expr):
            return
        my_locks_held = any(k.startswith(self.cls + ".")
                            for k, _ in held)
        for tgt in targets:
            for node in ast.walk(tgt):
                if isinstance(node, ast.Attribute) and \
                        isinstance(node.value, ast.Name) and \
                        node.value.id == "self":
                    attr = node.attr
                    ck = (self.cls, attr)
                    if f"{self.cls}.{attr}" in self.locks.locks or \
                            f"{self.cls}.{attr}" in self.locks.events:
                        continue
                    rec = self.writes.setdefault(
                        ck, {"locked": [], "unlocked": []})
                    rec["locked" if my_locks_held else
                        "unlocked"].append((self.src.path, node.lineno))

    # ----------------------------------------------------- blocking calls
    def _scan_stmt_exprs(self, stmt, held):
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                self._check_call(node, held)

    def _scan_expr(self, expr, held):
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                self._check_call(node, held)

    def _check_call(self, call, held):
        if not held:
            return
        name = call_name(call)
        held_keys = [k for k, _ in held]
        if name in _BLOCKING_CALLS:
            self._blocking(call, name, _BLOCKING_CALLS[name], held_keys)
            return
        if not isinstance(call.func, ast.Attribute):
            return
        attr = call.func.attr
        recv = call.func.value
        if attr in _BLOCKING_METHODS:
            self._blocking(call, f".{attr}()",
                           _BLOCKING_METHODS[attr], held_keys)
            return
        if attr == "wait":
            self._check_wait(call, recv, held, held_keys)
            return
        if attr == "join":
            rt = expr_text(recv).lower()
            if "thread" in rt or "proc" in rt or "worker" in rt:
                self._blocking(call, f"{expr_text(recv)}.join()",
                               "joins a thread with the lock held",
                               held_keys)
            return
        if name in ("np.asarray", "np.array", "numpy.asarray",
                    "numpy.array") and call.args:
            at = expr_text(call.args[0]).lower()
            if any(h in at for h in _DEVICE_HINTS):
                self._blocking(
                    call, f"{name}({expr_text(call.args[0])})",
                    "device->host transfer with the lock held",
                    held_keys)

    def _check_wait(self, call, recv, held, held_keys):
        info = self.locks.resolve(recv, self.cls)
        if info is not None and info.ctor == "Condition":
            own = self.locks.canonical(info)
            others = [k for k in held_keys if k != own]
            if others:
                self._blocking(
                    call, f"{expr_text(recv)}.wait()",
                    f"waits on {own} while still holding "
                    f"{', '.join(sorted(set(others)))}", held_keys)
            return
        if self.locks.is_event(recv, self.cls):
            self._blocking(call, f"{expr_text(recv)}.wait()",
                           "waits on an event with the lock held",
                           held_keys)

    def _blocking(self, call, what, why, held_keys):
        self.findings.append(Finding(
            "lock-blocking-call", self.src.path, call.lineno,
            f"{what} while holding {', '.join(sorted(set(held_keys)))}: "
            f"{why}",
            hint="move the blocking call outside the lock scope, or "
                 "snapshot state under the lock and release first"))


# ------------------------------------------------------------- reporting
def _cycle_findings(src, edges) -> list[Finding]:
    graph: dict[str, set] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
    out = []
    reported = set()
    for a in sorted(graph):
        for b in sorted(graph[a]):
            if a in graph.get(b, ()):       # 2-cycle (ABBA)
                key = frozenset((a, b))
                if key in reported:
                    continue
                reported.add(key)
                path, line = edges[(a, b)]
                other = edges[(b, a)]
                out.append(Finding(
                    "lock-order-cycle", path, line,
                    f"lock order cycle: {a} -> {b} here but "
                    f"{b} -> {a} at {other[0]}:{other[1]} "
                    "(potential ABBA deadlock)",
                    hint="pick one global order for these locks and "
                         "acquire them in that order everywhere"))
    # longer rings: DFS back-edge detection over the remaining graph
    out.extend(_long_cycles(graph, edges, reported))
    return out


def _long_cycles(graph, edges, reported) -> list[Finding]:
    out = []
    seen_cycles = set(reported)
    for start in sorted(graph):
        stack, on_path = [(start, iter(sorted(graph.get(start, ()))))], \
            [start]
        while stack:
            node, it = stack[-1]
            adv = next(it, None)
            if adv is None:
                stack.pop()
                on_path.pop()
                continue
            if adv in on_path:
                cyc = on_path[on_path.index(adv):] + [adv]
                key = frozenset(cyc)
                if len(key) > 2 and key not in seen_cycles:
                    seen_cycles.add(key)
                    path, line = edges[(node, adv)]
                    out.append(Finding(
                        "lock-order-cycle", path, line,
                        "lock order cycle: " + " -> ".join(cyc) +
                        " (potential deadlock ring)",
                        hint="pick one global order for these locks"))
                continue
            if len(stack) > 8:      # bound pathological graphs
                stack.pop()
                on_path.pop()
                continue
            stack.append((adv, iter(sorted(graph.get(adv, ())))))
            on_path.append(adv)
    return out


def _write_findings(src, writes) -> list[Finding]:
    out = []
    for (cls, attr), rec in sorted(writes.items()):
        if not rec["locked"] or not rec["unlocked"]:
            continue
        l_path, l_line = rec["locked"][0]
        for path, line in rec["unlocked"]:
            out.append(Finding(
                "lock-unlocked-write", path, line,
                f"`self.{attr}` of {cls} is written here without the "
                f"lock, but under the lock at {l_path}:{l_line} — "
                "racy if both paths run concurrently",
                hint=f"take the {cls} lock around this write, or "
                     "document single-threaded ownership with a "
                     "suppression"))
    return out
