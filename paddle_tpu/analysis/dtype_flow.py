"""Dtype-flow analyzer for jitted bodies: promotion & weak-scalar lint.

PR 10 shipped a real retrace bug: inside the speculative-decode verify
step, ``jnp.cumprod(m).sum()`` on an int32 mask silently promotes to
int64 when ``jax_enable_x64`` is set, changing the traced avals between
hosts and forcing a recompile that only the perf-gate trace counter
caught.  This analyzer rejects that class at lint time.

It reuses ``jit_safety``'s jit-target resolution — named functions,
lambdas, decorated defs, factory closures (``jax.jit(self._build_step())``)
and shard_map/partial wrappers (``jax.jit(jax.shard_map(step, ...))``)
— then runs a forward abstract dtype pass over each resolved body.
Dtypes are tracked as lattice strings (``bool``/``int32``/...); any
expression whose dtype cannot be proven stays unknown and is never
flagged, so the pass under-approximates rather than guesses.

Rules:

``jit-dtype-promotion``
    A reducing op (``sum``/``prod``/``cumsum``/``cumprod``) over a
    provably narrow operand (bool/int8/int16/int32) with no ``.astype``
    cast on the result expression — the result widens to the default
    int under x64, shifting avals and retracing.

``jit-weak-scalar``
    A Python float scalar combined (``+ - * **``) with a provably
    narrow-int traced operand — weak-type promotion turns the result
    float (float64 under x64); also an int literal too large for int32
    combined with an int32 operand.

``jit-np-constant``
    ``np.array``/``np.arange``/... creating a *constant* (untainted
    args — tainted ones are already ``jit-host-sync``) inside a traced
    body without a narrow dtype: numpy defaults to float64/int64 on
    host, baking wide constants into the program.
"""
from __future__ import annotations

import ast

from .core import Finding, SourceFile, call_name, expr_text
from .jit_safety import (_ModuleIndex, _is_tainted, _propagate,
                         _resolved_from_def)

__all__ = ["analyze"]

RULES = {
    "jit-dtype-promotion": "narrow-int reduction inside a jitted body "
                           "with no cast-back (int64 under x64)",
    "jit-weak-scalar": "python scalar weak-promoting a narrow traced "
                       "operand inside a jitted body",
    "jit-np-constant": "np.* constant without a narrow dtype inside a "
                       "jitted body (float64/int64 on host)",
}

_NARROW = {"bool", "int8", "int16", "int32"}
_NARROW_INT = {"int8", "int16", "int32"}

_REDUCTIONS = {"sum", "prod", "cumsum", "cumprod"}
_REDUCTION_CALLS = {f"jnp.{r}" for r in _REDUCTIONS} | \
    {f"jax.numpy.{r}" for r in _REDUCTIONS}

_NP_CTORS = {"array", "asarray", "ones", "zeros", "full", "arange",
             "linspace", "eye", "empty"}
# positional index of the dtype argument, where it is plausibly used
_NP_DTYPE_POS = {"array": 1, "asarray": 1, "zeros": 1, "ones": 1,
                 "empty": 1, "full": 2}

_WEAK_OPS = (ast.Add, ast.Sub, ast.Mult, ast.Pow)

_INT32_MAX = 2 ** 31 - 1

_DTYPE_NAMES = {"bool", "bool_", "int8", "int16", "int32", "int64",
                "uint8", "uint16", "uint32", "uint64", "float16",
                "bfloat16", "float32", "float64"}


def analyze(src: SourceFile) -> list[Finding]:
    if "jit" not in src.text:       # cheap pre-gate: nothing to resolve
        return []
    mod = _ModuleIndex(src)
    findings: list[Finding] = []
    done: set[int] = set()
    for jit in mod.jit_calls:
        body = mod.resolve_target(jit)
        if body is None or id(body.node) in done:
            continue
        done.add(id(body.node))
        _BodyCheck(src, body, findings).run()
    seen, unique = set(), []
    for f in findings:
        key = (f.rule, f.line, f.message)
        if key not in seen:
            seen.add(key)
            unique.append(f)
    return src.filter(unique)


def _dtype_name(node) -> str | None:
    """The dtype a dtype-position expression denotes, if literal."""
    if isinstance(node, ast.Attribute) and node.attr in _DTYPE_NAMES:
        return node.attr.rstrip("_")
    if isinstance(node, ast.Constant) and isinstance(node.value, str) \
            and node.value in _DTYPE_NAMES:
        return node.value
    if isinstance(node, ast.Name):
        if node.id == "bool":
            return "bool"
        if node.id == "float":
            return "float64"        # python float == double
        if node.id == "int":
            return "int64"
    return None


class _BodyCheck:
    def __init__(self, src, body, findings):
        self.src = src
        self.body = body
        self.findings = findings
        node = body.node
        self.fn_name = node.name if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef)) else "<lambda>"
        self.stmts = node.body if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
            else [ast.Expr(value=node.body)]
        self.tainted = {p for i, p in enumerate(body.params)
                        if i not in body.static_idx}
        for _ in range(2):
            for stmt in self.stmts:
                _propagate(stmt, self.tainted)

    def run(self):
        self._scan(self.stmts, {})

    # ------------------------------------------------------ statement walk
    def _scan(self, stmts, env):
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, ast.If):
                self._check(stmt.test, env)
                e1, e2 = dict(env), dict(env)
                self._scan(stmt.body, e1)
                self._scan(stmt.orelse, e2)
                for k in set(e1) | set(e2):
                    v1, v2 = e1.get(k), e2.get(k)
                    env[k] = v1 if v1 == v2 else None
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                head = stmt.test if isinstance(stmt, ast.While) \
                    else stmt.iter
                self._check(head, env)
                self._scan(stmt.body, env)
                self._scan(stmt.orelse, env)
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    self._check(item.context_expr, env)
                self._scan(stmt.body, env)
                continue
            if isinstance(stmt, ast.Try) or (hasattr(ast, "TryStar") and
                                             isinstance(stmt,
                                                        ast.TryStar)):
                self._scan(stmt.body, env)
                for h in stmt.handlers:
                    self._scan(h.body, env)
                self._scan(stmt.orelse, env)
                self._scan(stmt.finalbody, env)
                continue
            self._check(stmt, env)
            self._bind(stmt, env)

    def _bind(self, stmt, env):
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
            tgt = stmt.targets[0]
            if isinstance(tgt, ast.Name):
                env[tgt.id] = self._dtype_of(stmt.value, env)
            elif isinstance(tgt, ast.Tuple):
                for e in tgt.elts:
                    if isinstance(e, ast.Name):
                        env[e.id] = None
        elif isinstance(stmt, ast.AnnAssign) and \
                isinstance(stmt.target, ast.Name) and \
                stmt.value is not None:
            env[stmt.target.id] = self._dtype_of(stmt.value, env)
        elif isinstance(stmt, ast.AugAssign) and \
                isinstance(stmt.target, ast.Name):
            env[stmt.target.id] = None

    # ------------------------------------------------------ expression pass
    def _check(self, root, env):
        if root is None:
            return
        parents: dict[int, ast.AST] = {}
        nodes = []
        stack = [root]
        while stack:
            n = stack.pop()
            nodes.append(n)
            for c in ast.iter_child_nodes(n):
                if isinstance(c, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                    continue
                parents[id(c)] = n
                stack.append(c)
        for n in nodes:
            if isinstance(n, ast.Call):
                self._check_reduction(n, env, parents)
                self._check_np_constant(n)
            elif isinstance(n, ast.BinOp):
                self._check_weak_scalar(n, env)

    def _check_reduction(self, call, env, parents):
        operand = None
        if isinstance(call.func, ast.Attribute) and \
                call.func.attr in _REDUCTIONS:
            name = call_name(call)
            if name in _REDUCTION_CALLS:
                operand = call.args[0] if call.args else None
            elif name is None or not name.startswith(("np.", "numpy.")):
                operand = call.func.value      # method form m.cumprod()
        if operand is None:
            return
        dt = self._dtype_of(operand, env)
        if dt not in _NARROW:
            return
        if self._cast_ancestor(call, parents):
            return
        red = call.func.attr
        self.findings.append(Finding(
            "jit-dtype-promotion", self.src.path, call.lineno,
            f"`{red}` over {dt} operand `{expr_text(operand)}` in "
            f"`{self.fn_name}` promotes to the default int width under "
            "jax_enable_x64 — avals shift between hosts and the step "
            "retraces",
            hint="cast the result back explicitly, e.g. "
                 "`.astype(jnp.int32)` on the reduction chain"))

    @staticmethod
    def _cast_ancestor(call, parents) -> bool:
        n = parents.get(id(call))
        while n is not None:
            if isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Attribute) and \
                    n.func.attr == "astype":
                return True
            n = parents.get(id(n))
        return False

    def _check_weak_scalar(self, binop, env):
        if not isinstance(binop.op, _WEAK_OPS):
            return
        for const, other in ((binop.left, binop.right),
                             (binop.right, binop.left)):
            if not isinstance(const, ast.Constant):
                continue
            v = const.value
            dt = self._dtype_of(other, env)
            if isinstance(v, float) and dt in _NARROW_INT:
                self.findings.append(Finding(
                    "jit-weak-scalar", self.src.path, binop.lineno,
                    f"python float `{v}` combined with {dt} operand "
                    f"`{expr_text(other)}` in `{self.fn_name}` "
                    "weak-promotes the result to float "
                    "(float64 under x64)",
                    hint="cast the operand first "
                         "(`x.astype(jnp.float32)`) or use "
                         "`jnp.float32(scalar)`"))
                return
            if isinstance(v, int) and not isinstance(v, bool) and \
                    abs(v) > _INT32_MAX and dt == "int32":
                self.findings.append(Finding(
                    "jit-weak-scalar", self.src.path, binop.lineno,
                    f"int literal `{v}` does not fit int32; combining "
                    f"it with `{expr_text(other)}` in `{self.fn_name}` "
                    "forces int64",
                    hint="use an in-range constant or widen the "
                         "operand deliberately"))
                return

    def _check_np_constant(self, call):
        name = call_name(call) or ""
        if not name.startswith(("np.", "numpy.")):
            return
        ctor = name.split(".")[-1]
        if ctor not in _NP_CTORS:
            return
        if any(_is_tainted(a, self.tainted) for a in call.args):
            return                  # that is jit-host-sync, not this rule
        dt_node = None
        for kw in call.keywords:
            if kw.arg == "dtype":
                dt_node = kw.value
        pos = _NP_DTYPE_POS.get(ctor)
        if dt_node is None and pos is not None and len(call.args) > pos:
            dt_node = call.args[pos]
        if dt_node is None:
            detail = "with no dtype (numpy defaults to float64/int64 " \
                     "on host)"
        else:
            dt = _dtype_name(dt_node)
            if dt is None or ("64" not in dt):
                return              # explicitly narrow (or unknowable)
            detail = f"with dtype {dt}"
        self.findings.append(Finding(
            "jit-np-constant", self.src.path, call.lineno,
            f"`{name}(...)` constant {detail} inside jitted "
            f"`{self.fn_name}` bakes a wide host constant into the "
            "traced program",
            hint="pass dtype=jnp.float32/jnp.int32, or build the "
                 "constant with jnp.*"))

    # ---------------------------------------------------- dtype evaluation
    def _dtype_of(self, node, env) -> str | None:
        if isinstance(node, ast.Name):
            return env.get(node.id)
        if isinstance(node, ast.Constant):
            if isinstance(node.value, bool):
                return "bool"
            return None             # weak python scalars stay unknown
        if isinstance(node, ast.Compare):
            return "bool"
        if isinstance(node, ast.BoolOp):
            return "bool"
        if isinstance(node, ast.UnaryOp):
            if isinstance(node.op, ast.Not):
                return "bool"
            return self._dtype_of(node.operand, env)
        if isinstance(node, ast.Subscript):
            return self._dtype_of(node.value, env)
        if isinstance(node, ast.BinOp):
            lt = self._dtype_of(node.left, env)
            rt = self._dtype_of(node.right, env)
            if lt == rt:
                return lt
            return None
        if isinstance(node, ast.IfExp):
            a = self._dtype_of(node.body, env)
            b = self._dtype_of(node.orelse, env)
            return a if a == b else None
        if isinstance(node, ast.Call):
            return self._call_dtype(node, env)
        return None

    def _call_dtype(self, call, env) -> str | None:
        if isinstance(call.func, ast.Attribute):
            attr = call.func.attr
            if attr == "astype" and call.args:
                return _dtype_name(call.args[0])
            if attr in _REDUCTIONS:
                name = call_name(call)
                if name in _REDUCTION_CALLS and call.args:
                    inner = self._dtype_of(call.args[0], env)
                else:
                    inner = self._dtype_of(call.func.value, env)
                if inner in _NARROW:
                    return "int64"  # the promotion this pass flags
                return inner
        name = call_name(call) or ""
        for kw in call.keywords:
            if kw.arg == "dtype":
                return _dtype_name(kw.value)
        base = name.split(".")[-1]
        if name.startswith(("jnp.", "jax.numpy.")) and \
                base in ("zeros", "ones", "empty") and len(call.args) > 1:
            return _dtype_name(call.args[1])
        if name.startswith(("jnp.", "jax.numpy.")) and \
                base == "full" and len(call.args) > 2:
            return _dtype_name(call.args[2])
        return None
